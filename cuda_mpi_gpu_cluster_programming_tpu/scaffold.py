"""Experiment scaffolding, test sweep, and submission packaging CLI.

Python replacement for the reference's homework toolchain (SURVEY H8-H10,
H12):

- ``scaffold new N``     ↔ ``scripts/scaffold_hw.sh`` — generates
  ``experiments/hwN/`` (src/template.py + summary.md) from the package's
  ``templates/`` directory, refusing to overwrite existing work
  (scaffold_hw.sh checks per-file existence). There is no Makefile/CMake on
  TPU — the "build" is jit compilation, so the generated artifact is a
  runnable Python entry named ``template`` like the course's required
  executable name (homeworks/hw1/Makefile:18-19).
- ``scaffold test N``    ↔ ``scripts/test_hw.sh`` — sweeps
  np in 1..8 x n in {128..2048} with a 30 s per-run timeout (:8-10,124),
  skipping non-divisible (n, np) combos (:113-147), tri-state
  PASSED/FAILED/TIMEOUT summary with exit code 0/1/2 (:160-180). Runs each
  case on an np-device virtual CPU mesh (the ``mpirun --oversubscribe``
  analogue).
- ``scaffold package N last first`` ↔ ``scripts/package_hw.sh`` — stages
  ``hwN-<last>-<first>/`` (lowercased, :11-13) with the source + summary and
  tars it to ``hwN-<last>-<first>.tgz`` (:17-96).
"""

from __future__ import annotations

import argparse
import shutil
import subprocess
import sys
import tarfile
import tempfile
import time
from pathlib import Path
from typing import List, Optional, Tuple

from .utils.env_info import cpu_subprocess_env

TEMPLATES_DIR = Path(__file__).resolve().parent / "templates"
DEFAULT_EXPERIMENTS_ROOT = "experiments"

# test_hw.sh:8-10 sweep matrix and timeout.
PROBLEM_SIZES = (128, 256, 512, 1024, 2048)
PROCESS_COUNTS = (1, 2, 3, 4, 5, 6, 7, 8)
TIMEOUT_S = 30.0

PASSED, FAILED, TIMEOUT, SKIPPED = "PASSED", "FAILED", "TIMEOUT", "SKIPPED"


def hw_dir(root: Path, hw_num: int) -> Path:
    return root / f"hw{hw_num}"


def cmd_new(root: Path, hw_num: int, force: bool = False) -> Path:
    """Generate experiments/hwN from templates (scaffold_hw.sh analogue)."""
    target = hw_dir(root, hw_num)
    src_dir = target / "src"
    src_dir.mkdir(parents=True, exist_ok=True)
    plan = [
        (TEMPLATES_DIR / "template.py.template", src_dir / "template.py"),
        (TEMPLATES_DIR / "summary.md.template", target / "summary.md"),
    ]
    for tmpl, dest in plan:
        if dest.exists() and not force:
            print(f"skip (exists): {dest}")
            continue
        dest.write_text(tmpl.read_text().replace("{HW_NUM}", str(hw_num)))
        print(f"created: {dest}")
    return target


def run_case(
    entry: Path, n: int, np_: int, timeout_s: float = TIMEOUT_S
) -> Tuple[str, float, str]:
    """One sweep case on an np-device virtual CPU mesh. Returns
    (status, wall_s, detail)."""
    if n % np_ != 0:
        return SKIPPED, 0.0, f"n%np={n % np_}"
    env = cpu_subprocess_env(np_)
    cmd = [sys.executable, str(entry), str(n), "--shards", str(np_)]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=timeout_s, env=env
        )
    except subprocess.TimeoutExpired:
        return TIMEOUT, time.perf_counter() - t0, f"timeout {timeout_s:.0f}s"
    wall = time.perf_counter() - t0
    out = proc.stdout + proc.stderr
    if proc.returncode == 0 and "Test: PASSED" in out:
        return PASSED, wall, ""
    tail = [ln for ln in out.strip().splitlines() if ln.strip()]
    return FAILED, wall, (tail[-1][:100] if tail else f"exit {proc.returncode}")


def cmd_test(
    root: Path,
    hw_num: int,
    sizes: Tuple[int, ...] = PROBLEM_SIZES,
    np_counts: Tuple[int, ...] = PROCESS_COUNTS,
    timeout_s: float = TIMEOUT_S,
) -> int:
    """Sweep runner (test_hw.sh analogue). Exit 0/1/2 = pass/fail/timeout."""
    target = hw_dir(root, hw_num)
    entry = target / "src" / "template.py"
    if not entry.exists():
        print(f"Error: '{entry}' not found. Did you run 'scaffold new {hw_num}'?")
        return 1
    print(f"--- Testing experiment hw{hw_num} ---")
    results: List[Tuple[int, int, str, float, str]] = []
    worst = 0
    for np_ in np_counts:
        for n in sizes:
            status, wall, detail = run_case(entry, n, np_, timeout_s)
            results.append((np_, n, status, wall, detail))
            mark = {PASSED: "✓", FAILED: "✗", TIMEOUT: "⏱", SKIPPED: "-"}[status]
            line = f"[np={np_} n={n}] {mark} {status}"
            if status == PASSED:
                line += f" ({wall:.2f}s)"
            elif detail:
                line += f" ({detail})"
            print(line)
            worst = max(worst, {FAILED: 1, TIMEOUT: 2}.get(status, 0))
    n_pass = sum(1 for r in results if r[2] == PASSED)
    n_skip = sum(1 for r in results if r[2] == SKIPPED)
    print(
        f"--- hw{hw_num}: {n_pass} passed, "
        f"{sum(1 for r in results if r[2] == FAILED)} failed, "
        f"{sum(1 for r in results if r[2] == TIMEOUT)} timed out, "
        f"{n_skip} skipped ---"
    )
    return worst


def cmd_package(
    root: Path, hw_num: int, lastname: str, firstname: str, out_dir: Optional[Path] = None
) -> Path:
    """Build hwN-<last>-<first>.tgz (package_hw.sh analogue)."""
    target = hw_dir(root, hw_num)
    entry = target / "src" / "template.py"
    summary = target / "summary.md"
    if not target.is_dir():
        raise FileNotFoundError(f"experiment directory '{target}' not found")
    if not entry.exists():
        raise FileNotFoundError(f"required source file '{entry}' not found")
    sub_name = f"hw{hw_num}-{lastname.lower()}-{firstname.lower()}"
    out_dir = out_dir or target
    archive = out_dir / f"{sub_name}.tgz"
    with tempfile.TemporaryDirectory() as td:
        stage = Path(td) / sub_name
        (stage / "src").mkdir(parents=True)
        shutil.copy2(entry, stage / "src" / "template.py")
        if summary.exists():
            shutil.copy2(summary, stage / "summary.md")
        with tarfile.open(archive, "w:gz") as tf:
            tf.add(stage, arcname=sub_name)
    print(f"packaged: {archive}")
    return archive


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.scaffold")
    p.add_argument(
        "--root", default=DEFAULT_EXPERIMENTS_ROOT, help="experiments root directory"
    )
    sub = p.add_subparsers(dest="command", required=True)

    p_new = sub.add_parser("new", help="generate a new experiment from templates")
    p_new.add_argument("hw_num", type=int)
    p_new.add_argument("--force", action="store_true", help="overwrite existing files")

    p_test = sub.add_parser("test", help="np x size sweep with timeout")
    p_test.add_argument("hw_num", type=int)
    p_test.add_argument("--sizes", default=",".join(map(str, PROBLEM_SIZES)))
    p_test.add_argument("--np-counts", default=",".join(map(str, PROCESS_COUNTS)))
    p_test.add_argument("--timeout", type=float, default=TIMEOUT_S)

    p_pkg = sub.add_parser("package", help="create submission .tgz")
    p_pkg.add_argument("hw_num", type=int)
    p_pkg.add_argument("lastname")
    p_pkg.add_argument("firstname")
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    root = Path(args.root)
    if args.command == "new":
        cmd_new(root, args.hw_num, force=args.force)
        return 0
    if args.command == "test":
        return cmd_test(
            root,
            args.hw_num,
            sizes=tuple(int(s) for s in args.sizes.split(",")),
            np_counts=tuple(int(s) for s in args.np_counts.split(",")),
            timeout_s=args.timeout,
        )
    if args.command == "package":
        try:
            cmd_package(root, args.hw_num, args.lastname, args.firstname)
        except FileNotFoundError as e:
            print(f"Error: {e}")
            return 1
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

"""Native (C++) tier: shard-geometry planner + prefetching data pipeline.

The reference keeps its host-side runtime in C++ — the dim helpers and trim
math (v2_mpi_only/2.2_scatter_halo/include/alexnet.hpp:35-44,
v4_mpi_cuda/src/alexnet_mpi_cuda.cu:27-38) and the data-synthesis loops
(v1_serial/src/alexnet_serial.cpp:39-57). This package is the TPU framework's
equivalent native tier: ``csrc/`` is compiled on demand with ``g++`` into one
shared library, bound here via ctypes (no pybind11 in the image).

Public surface:

- :func:`conv_out_dim` / :func:`pool_out_dim` — native shape calculators.
- :func:`make_shard_plan_native` — ShardPlan structurally identical to
  ``parallel.plan.make_shard_plan`` (cross-validated in tests/test_native.py).
- :func:`owned_range_native` — per-shard global output-row ownership.
- :func:`fill_batch` — synchronous synthetic batch (ones / seeded uniform).
- :class:`NativeDataLoader` — multi-threaded prefetching batch iterator whose
  stream depends only on (seed, batch index), never thread timing.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
import threading
from pathlib import Path
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

from ..models.alexnet import Blocks12Config, ConvSpec, LrnSpec, PoolSpec
from ..parallel.plan import LayerPlan, ShardPlan

_SRC_DIR = Path(__file__).parent / "csrc"
_BUILD_DIR = Path(__file__).parent / "_build"
_LIB_PATH = _BUILD_DIR / "libtpunative.so"

_KIND_CODE = {"conv": 0, "pool": 1, "pointwise": 2}
_KIND_NAME = {v: k for k, v in _KIND_CODE.items()}

_ERRORS = {
    -1: "degenerate layer output (filter cannot fit)",
    -2: "uniform window escapes padded buffer",
    -3: "bad argument",
}

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


class _LayerPlanC(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("filter_size", ctypes.c_int32),
        ("stride", ctypes.c_int32),
        ("padding", ctypes.c_int32),
        ("l_in", ctypes.c_int32),
        ("l_out", ctypes.c_int32),
        ("b_in", ctypes.c_int32),
        ("b_out", ctypes.c_int32),
        ("h_top", ctypes.c_int32),
        ("h_bot", ctypes.c_int32),
        ("s0_coef", ctypes.c_int32),
        ("s0_const", ctypes.c_int32),
        ("win_rows", ctypes.c_int32),
        ("pad_bot", ctypes.c_int32),
    ]


def _build() -> Path:
    """Compile csrc/*.cpp into libtpunative.so if missing or stale."""
    sources = sorted(_SRC_DIR.glob("*.cpp"))
    if not sources:
        raise RuntimeError(f"no C++ sources under {_SRC_DIR}")
    if _LIB_PATH.exists():
        newest = max(s.stat().st_mtime for s in sources)
        if _LIB_PATH.stat().st_mtime >= newest:
            return _LIB_PATH
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(_BUILD_DIR))
    os.close(fd)
    cmd = [
        "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
        *map(str, sources), "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
    except FileNotFoundError as e:
        os.unlink(tmp)
        raise RuntimeError("g++ not found; the native tier needs a C++ toolchain") from e
    except subprocess.CalledProcessError as e:
        os.unlink(tmp)
        raise RuntimeError(f"native build failed:\n{e.stderr}") from e
    os.replace(tmp, _LIB_PATH)  # atomic: concurrent builders race harmlessly
    return _LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(str(_build()))
        lib.sp_conv_out_dim.restype = ctypes.c_int
        lib.sp_conv_out_dim.argtypes = [ctypes.c_int] * 4
        lib.sp_pool_out_dim.restype = ctypes.c_int
        lib.sp_pool_out_dim.argtypes = [ctypes.c_int] * 3
        lib.sp_plan_layer.restype = ctypes.c_int
        lib.sp_plan_layer.argtypes = [ctypes.c_int] * 6 + [ctypes.POINTER(_LayerPlanC)]
        lib.sp_plan_chain.restype = ctypes.c_int
        lib.sp_plan_chain.argtypes = [
            ctypes.c_int,
            *(ctypes.POINTER(ctypes.c_int32),) * 4,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(_LayerPlanC),
        ]
        lib.sp_owned_range.restype = None
        lib.sp_owned_range.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dl_fill.restype = None
        lib.dl_fill.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.dl_splitmix64.restype = ctypes.c_uint64
        lib.dl_splitmix64.argtypes = [ctypes.c_uint64]
        lib.dl_lcg_next.restype = ctypes.c_uint64
        lib.dl_lcg_next.argtypes = [ctypes.c_uint64]
        lib.dl_lcg_float.restype = ctypes.c_float
        lib.dl_lcg_float.argtypes = [ctypes.c_uint64]
        lib.dl_create.restype = ctypes.c_void_p
        lib.dl_create.argtypes = [
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
        lib.dl_next.restype = ctypes.c_int64
        lib.dl_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_float)]
        lib.dl_destroy.restype = None
        lib.dl_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


# ---------------------------------------------------------------- shard plan


def conv_out_dim(d: int, f: int, p: int, s: int) -> int:
    return _load().sp_conv_out_dim(d, f, p, s)


def pool_out_dim(d: int, f: int, s: int) -> int:
    return _load().sp_pool_out_dim(d, f, s)


def _chain_arrays(cfg: Blocks12Config):
    names, kinds, fs, ss, ps = [], [], [], [], []
    for name, spec in cfg.layer_chain():
        names.append(name)
        if isinstance(spec, ConvSpec):
            kinds.append(0); fs.append(spec.filter_size); ss.append(spec.stride); ps.append(spec.padding)
        elif isinstance(spec, PoolSpec):
            kinds.append(1); fs.append(spec.window); ss.append(spec.stride); ps.append(0)
        elif isinstance(spec, LrnSpec):
            kinds.append(2); fs.append(1); ss.append(1); ps.append(0)
        else:
            raise TypeError(f"unknown layer spec {spec!r}")
    return names, kinds, fs, ss, ps


def make_shard_plan_native(cfg: Blocks12Config, n_shards: int) -> ShardPlan:
    """Native twin of ``parallel.plan.make_shard_plan`` (same ShardPlan type)."""
    if n_shards < 1:
        raise ValueError("n_shards must be >= 1")
    lib = _load()
    names, kinds, fs, ss, ps = _chain_arrays(cfg)
    n = len(names)
    arr = lambda xs: (ctypes.c_int32 * n)(*xs)  # noqa: E731
    out = (_LayerPlanC * n)()
    rc = lib.sp_plan_chain(n, arr(kinds), arr(fs), arr(ss), arr(ps), cfg.in_height, n_shards, out)
    if rc != 0:
        raise ValueError(f"native plan failed: {_ERRORS.get(rc, rc)}")
    layers = tuple(
        LayerPlan(
            name=names[i],
            kind=_KIND_NAME[out[i].kind],
            filter_size=out[i].filter_size,
            stride=out[i].stride,
            padding=out[i].padding,
            l_in=out[i].l_in,
            l_out=out[i].l_out,
            b_in=out[i].b_in,
            b_out=out[i].b_out,
            h_top=out[i].h_top,
            h_bot=out[i].h_bot,
            s0_coef=out[i].s0_coef,
            s0_const=out[i].s0_const,
            win_rows=out[i].win_rows,
            pad_bot=out[i].pad_bot,
        )
        for i in range(n)
    )
    return ShardPlan(n_shards=n_shards, layers=layers)


def owned_range_native(b_out: int, l_out: int, i: int) -> Tuple[int, int]:
    start = ctypes.c_int32()
    end = ctypes.c_int32()
    _load().sp_owned_range(b_out, l_out, i, ctypes.byref(start), ctypes.byref(end))
    return start.value, end.value


# --------------------------------------------------------------- data loader

MODES = {"ones": 0, "uniform": 1}


def fill_batch(shape: Sequence[int], mode: str = "ones", seed: int = 0) -> np.ndarray:
    """Synchronously generate one synthetic batch (float32, C order)."""
    out = np.empty(shape, dtype=np.float32)
    _load().dl_fill(
        MODES[mode], ctypes.c_uint64(seed), out.size,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out


def lcg_uniform_numpy(seed: int, n: int) -> np.ndarray:
    """Pure-numpy mirror of the native uniform stream (parity oracle)."""
    with np.errstate(over="ignore"):
        x = np.uint64(seed) + np.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        s = x ^ (x >> np.uint64(31))
        out = np.empty(n, dtype=np.float32)
        mul = np.uint64(6364136223846793005)
        inc = np.uint64(1442695040888963407)
        for i in range(n):
            s = s * mul + inc
            out[i] = np.float32(s >> np.uint64(40)) * np.float32(1.0 / 16777216.0)
    return out


def batch_seed(seed: int, k: int) -> int:
    """Seed of batch ``k`` in a loader stream (mirrors dataloader.cpp)."""
    return (seed + 0x517CC1B727220A95 * (k + 1)) % (1 << 64)


class NativeDataLoader:
    """Prefetching iterator over synthetic NHWC batches.

    ``depth`` bounds how many finished batches buffer ahead of the consumer;
    ``workers`` fills batches concurrently. Batch ``k`` equals
    ``fill_batch(shape, mode, batch_seed(seed, k))`` regardless of timing.
    """

    def __init__(
        self,
        shape: Sequence[int],
        mode: str = "ones",
        seed: int = 0,
        depth: int = 2,
        workers: int = 2,
    ):
        self._handle = None  # so __del__->close is safe if init raises below
        self._shape = tuple(int(d) for d in shape)
        elems = int(np.prod(self._shape))
        self._handle = _load().dl_create(
            MODES[mode], ctypes.c_uint64(seed), elems, depth, workers
        )
        if not self._handle:
            raise ValueError("dl_create failed (bad shape/depth/workers/mode)")

    def __iter__(self) -> Iterator[np.ndarray]:
        return self

    def __next__(self) -> np.ndarray:
        if self._handle is None:
            raise StopIteration
        out = np.empty(self._shape, dtype=np.float32)
        k = _load().dl_next(
            self._handle, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
        )
        if k < 0:
            raise StopIteration
        return out

    def close(self) -> None:
        if self._handle is not None:
            _load().dl_destroy(self._handle)
            self._handle = None

    def __enter__(self) -> "NativeDataLoader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self) -> None:  # pragma: no cover - GC timing
        self.close()

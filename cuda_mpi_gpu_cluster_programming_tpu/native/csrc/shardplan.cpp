// Native shard-geometry planner.
//
// C++ counterpart of parallel/plan.py: the exact per-shard output-row
// ownership math (the corrected form of the reference's mapRangeStart/End,
// v4_mpi_cuda/src/alexnet_mpi_cuda.cu:27-38) plus the convOutDim/poolOutDim
// shape helpers (v2_mpi_only/2.2_scatter_halo/include/alexnet.hpp:35-44 with
// V4's degenerate-size guards, v4_mpi_cuda/include/alexnet.hpp:28-33). The
// reference keeps this host-side geometry logic in C++; so do we. The Python
// planner remains the tracing-time source of truth; this library is the
// native tier used by out-of-process tools and is cross-validated against
// the Python planner in tests/test_native.py.

#include <algorithm>
#include <cstdint>

namespace {

inline int ceil_div(int a, int b) { return (a + b - 1) / b; }

}  // namespace

extern "C" {

// Mirrors ops/shapes.py conv_out_dim.
int sp_conv_out_dim(int d, int f, int p, int s) {
  if (d <= 0 || f <= 0 || s <= 0) return 0;
  if (f > d + 2 * p) return 0;
  return (d - f + 2 * p) / s + 1;
}

// Mirrors ops/shapes.py pool_out_dim.
int sp_pool_out_dim(int d, int f, int s) {
  if (d <= 0 || f <= 0 || s <= 0) return 0;
  if (f > d) return 0;
  return (d - f) / s + 1;
}

// Field-for-field mirror of parallel/plan.py LayerPlan (geometry fields only;
// name/kind strings live on the Python side).
struct sp_layer_plan {
  int32_t kind;  // 0 conv, 1 pool, 2 pointwise
  int32_t filter_size;
  int32_t stride;
  int32_t padding;
  int32_t l_in;
  int32_t l_out;
  int32_t b_in;
  int32_t b_out;
  int32_t h_top;
  int32_t h_bot;
  int32_t s0_coef;
  int32_t s0_const;
  int32_t win_rows;
  int32_t pad_bot;
};

enum {
  SP_OK = 0,
  SP_ERR_DEGENERATE = -1,   // layer output length <= 0
  SP_ERR_WINDOW = -2,       // uniform window escapes the padded buffer
  SP_ERR_BAD_ARG = -3,      // n_shards < 1 or unknown kind
};

// Mirrors parallel/plan.py _plan_spatial_layer (kind 0/1) and the pointwise
// branch of make_shard_plan (kind 2).
int sp_plan_layer(int kind, int l_in, int n, int f, int s, int p,
                  sp_layer_plan* out) {
  if (n < 1 || kind < 0 || kind > 2 || out == nullptr) return SP_ERR_BAD_ARG;
  if (kind == 2) {  // pointwise (LRN): block-identical geometry, no halo
    int b = ceil_div(l_in, n);
    *out = {2, 1, 1, 0, l_in, l_in, b, b, 0, 0, 0, 0, b, 0};
    return SP_OK;
  }
  int l_out = kind == 0 ? sp_conv_out_dim(l_in, f, p, s) : sp_pool_out_dim(l_in, f, s);
  if (l_out <= 0) return SP_ERR_DEGENERATE;
  if (kind == 1) p = 0;
  int b_in = ceil_div(l_in, n);
  int b_out = ceil_div(l_out, n);

  int h_top = 0, h_bot = 0;
  for (int i = 0; i < n; ++i) {
    int own_start = i * b_out;
    int own_end = std::min((i + 1) * b_out, l_out);
    if (own_start >= own_end) continue;  // shard owns nothing; stays masked
    int need_start = own_start * s - p;
    int need_end = (own_end - 1) * s - p + f;  // exclusive
    h_top = std::max(h_top, i * b_in - need_start);
    h_bot = std::max(h_bot, need_end - (i + 1) * b_in);
  }
  h_top = std::max(h_top, 0);
  h_bot = std::max(h_bot, 0);

  int s0_coef = b_out * s - b_in;
  int s0_const = h_top - p;
  int win_rows = (b_out - 1) * s + f;
  int pad_bot = 0;
  for (int i = 0; i < n; ++i) {
    int s0 = std::max(0, i * s0_coef + s0_const);
    pad_bot = std::max(pad_bot, s0 + win_rows - (h_top + b_in + h_bot));
  }
  for (int i = 0; i < n; ++i) {
    int s0 = i * s0_coef + s0_const;
    if (std::min((i + 1) * b_out, l_out) <= i * b_out) continue;
    if (s0 < 0 || s0 + win_rows > h_top + b_in + h_bot + pad_bot) return SP_ERR_WINDOW;
  }
  *out = {static_cast<int32_t>(kind), f, s, p, l_in, l_out, b_in, b_out,
          h_top,  h_bot, s0_coef, s0_const, win_rows, pad_bot};
  return SP_OK;
}

// Plan a chain of layers: layer i consumes layer i-1's l_out. kinds/fs/ss/ps
// are parallel arrays of length n_layers. Returns SP_OK or the first error.
int sp_plan_chain(int n_layers, const int32_t* kinds, const int32_t* fs,
                  const int32_t* ss, const int32_t* ps, int l0, int n_shards,
                  sp_layer_plan* out) {
  if (n_layers < 1 || !kinds || !fs || !ss || !ps || !out) return SP_ERR_BAD_ARG;
  int l_cur = l0;
  for (int i = 0; i < n_layers; ++i) {
    int rc = sp_plan_layer(kinds[i], l_cur, n_shards, fs[i], ss[i], ps[i], &out[i]);
    if (rc != SP_OK) return rc;
    l_cur = out[i].l_out;
  }
  return SP_OK;
}

// Global output rows shard i owns: the mapRangeStart/End analogue
// (v4_mpi_cuda/src/alexnet_mpi_cuda.cu:27-38), exact-ownership form.
void sp_owned_range(int b_out, int l_out, int i, int32_t* start, int32_t* end) {
  *start = i * b_out;
  *end = std::min((i + 1) * b_out, l_out);  // end < start => shard owns nothing
}

}  // extern "C"

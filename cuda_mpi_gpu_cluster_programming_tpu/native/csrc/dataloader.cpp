// Native synthetic-data pipeline.
//
// C++ counterpart of the reference's host-side data initialization — the
// initializeData/initializeWeights loops (v1_serial/src/alexnet_serial.cpp:39-57)
// and the rank-0 synthesis in every MPI main (2.2_scatter_halo/src/main.cpp:35-47)
// — upgraded to a batched, multi-threaded prefetching loader. Modes:
//
//   0 "ones":    every element 1.0f (the deterministic cross-version oracle
//                input, 2.2_scatter_halo/src/main.cpp:37).
//   1 "uniform": uniform [0,1) from an explicit splitmix64-seeded LCG — the
//                V1 rand()/RAND_MAX semantics (alexnet_serial.cpp:41) made
//                reproducible: the reference's srand(time(0)) seeding
//                (v1_serial/src/main.cpp:12) is its known determinism flaw.
//
// Batch k's contents depend only on (seed, k), never on thread interleaving:
// workers claim batch indices from an atomic counter and results are
// delivered strictly in index order.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// splitmix64 (public-domain construction) for seed mixing; 64-bit LCG
// (Knuth MMIX multiplier) for the stream; top 24 bits -> float32 [0,1).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}

inline uint64_t lcg_next(uint64_t s) {
  return s * 6364136223846793005ULL + 1442695040888963407ULL;
}

inline float lcg_float(uint64_t s) {
  return static_cast<float>(s >> 40) * (1.0f / 16777216.0f);
}

void fill(int mode, uint64_t seed, int64_t n, float* out) {
  if (mode == 0) {
    for (int64_t i = 0; i < n; ++i) out[i] = 1.0f;
    return;
  }
  uint64_t s = splitmix64(seed);
  for (int64_t i = 0; i < n; ++i) {
    s = lcg_next(s);
    out[i] = lcg_float(s);
  }
}

struct Loader {
  int mode;
  uint64_t seed;
  int64_t batch_elems;
  int depth;     // max finished batches buffered ahead of the consumer
  std::vector<std::thread> workers;

  std::mutex mu;
  std::condition_variable cv_produced;  // consumer waits for ready[next_out]
  std::condition_variable cv_space;     // workers wait for buffer space
  std::condition_variable cv_drained;   // destroyer waits for consumers to leave
  std::map<int64_t, std::vector<float>> ready;
  std::atomic<int64_t> next_claim{0};
  int64_t next_out = 0;
  int consumers_inside = 0;
  bool stopping = false;

  void worker() {
    for (;;) {
      int64_t k = next_claim.fetch_add(1);
      {
        // Admission control BEFORE filling, so at most `depth` batches are
        // ever finished-or-in-flight ahead of the consumer.
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] { return stopping || k < next_out + depth; });
        if (stopping) return;
      }
      std::vector<float> buf(static_cast<size_t>(batch_elems));
      // Per-batch stream: seed mixed with batch index -> order-independent.
      fill(mode, seed + 0x517cc1b727220a95ULL * static_cast<uint64_t>(k + 1),
           batch_elems, buf.data());
      std::unique_lock<std::mutex> lk(mu);
      if (stopping) return;
      ready.emplace(k, std::move(buf));
      cv_produced.notify_all();
    }
  }
};

}  // namespace

extern "C" {

// Synchronous fill — the parity-test surface and the non-prefetching path.
void dl_fill(int mode, uint64_t seed, int64_t n, float* out) {
  fill(mode, seed, n, out);
}

// Expose the raw generator so Python can mirror the stream exactly.
uint64_t dl_splitmix64(uint64_t x) { return splitmix64(x); }
uint64_t dl_lcg_next(uint64_t s) { return lcg_next(s); }
float dl_lcg_float(uint64_t s) { return lcg_float(s); }

void* dl_create(int mode, uint64_t seed, int64_t batch_elems, int depth,
                int n_workers) {
  if (batch_elems <= 0 || depth < 1 || n_workers < 1 || mode < 0 || mode > 1)
    return nullptr;
  auto* L = new Loader();
  L->mode = mode;
  L->seed = seed;
  L->batch_elems = batch_elems;
  L->depth = depth;
  for (int i = 0; i < n_workers; ++i)
    L->workers.emplace_back([L] { L->worker(); });
  return L;
}

// Copy the next batch (in strict index order) into out. Returns the batch
// index (>= 0), or -1 if the loader is stopping.
int64_t dl_next(void* handle, float* out) {
  auto* L = static_cast<Loader*>(handle);
  std::vector<float> buf;
  int64_t k;
  {
    std::unique_lock<std::mutex> lk(L->mu);
    ++L->consumers_inside;  // dl_destroy waits for us to leave before delete
    L->cv_produced.wait(lk, [&] {
      return L->stopping || L->ready.count(L->next_out) > 0;
    });
    if (L->stopping) {
      if (--L->consumers_inside == 0) L->cv_drained.notify_all();
      return -1;
    }
    k = L->next_out;
    buf = std::move(L->ready[k]);
    L->ready.erase(k);
    L->next_out = k + 1;
    L->cv_space.notify_all();
    if (--L->consumers_inside == 0) L->cv_drained.notify_all();
  }
  std::memcpy(out, buf.data(), sizeof(float) * static_cast<size_t>(L->batch_elems));
  return k;
}

void dl_destroy(void* handle) {
  auto* L = static_cast<Loader*>(handle);
  {
    std::unique_lock<std::mutex> lk(L->mu);
    L->stopping = true;
    L->cv_space.notify_all();
    L->cv_produced.notify_all();
    // A consumer may still be blocked inside dl_next; deleting the mutex
    // under it is UB. Wait for every consumer to observe `stopping` and exit.
    L->cv_drained.wait(lk, [&] { return L->consumers_inside == 0; });
  }
  for (auto& t : L->workers) t.join();
  delete L;
}

}  // extern "C"

"""Journal-replay fleet simulator: re-drive a recorded serve run, live.

Every serve journal since PR 12 carries the full *input* of its run, not
just the outcomes: one ``serve_config`` record (config / shards / bucket
set / SLO policy / model geometry), one ``serve_submit`` record per
admission attempt (arrival offset, request size, class, resolved
deadline, admitted-or-rejected), and the ``sup_trip``/``mesh_shrink``
incident records naming exactly which devices were lost at which
supervised step. This module closes the loop: it reconstructs that
schedule and re-runs it through a **live** :class:`~..serving.server.
InferenceServer` on the CPU mesh — same arrivals, same request shapes
and classes, same chaos schedule (scripted via
:meth:`~..resilience.supervisor.Supervisor.script_fault`, no seeded
re-draw) — so capacity what-ifs are answered by deterministic replay
instead of a chip window:

- ``traffic_mult`` — replicate the arrival schedule k× (fractional parts
  selected by a stable per-rid hash, never a fresh RNG): "would 2×
  traffic hold p99?"
- ``devices`` — rebuild the server at a different shard width: "…at half
  the devices?"
- ``slo_scale`` — scale every class latency budget and per-request
  deadline: "…with SLOs twice as tight?"

**The determinism contract**: replaying a journal against its own
recorded conditions (all knobs neutral) must close per-class accounting
*identically* — same offered / ok / shed / failed / rejected per class —
and reproduce the journal-derived p50/p99 within the nearest-rank
estimator's resolution (:func:`percentile_resolution`: the bracket
between adjacent order statistics plus the dispatch poll quantum; wall
latencies on a shared CPU cannot be bit-identical, order statistics of
the same schedule must agree to within their own spacing). A neutral
replay that breaks accounting is a **divergence** — the CLI exits 3 on
it (docs/OBSERVABILITY.md "Replay & regression gating").

What does NOT replay, visibly: grow-back chaos (heal / probation /
promote records — replay re-drives *losses*, so a recorded run that also
healed is reported with ``unreplayed`` counts, never silently treated as
loss-only), and journals recorded before the PR 12 schema (no
``serve_submit`` records) raise an attributable ``ValueError``.

Layering: stdlib + numpy at import time; jax and the serving stack load
inside :func:`replay_recorded` (same lazy-import rule as ``stages``).
"""

from __future__ import annotations

import dataclasses
import math
import os
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..resilience.journal import Journal
from .export import load_records

# Journal kinds that mark grow-back activity replay cannot re-drive
# (losses are scripted; heals/promotions depend on live pool state).
_GROWBACK_KINDS = (
    "mesh_probation",
    "mesh_quarantine",
    "sup_promote",
    "sup_promote_refused",
)


# ------------------------------------------------------------- recording ---


@dataclasses.dataclass(frozen=True)
class RecordedSubmit:
    """One recorded admission attempt (a ``serve_submit`` record)."""

    t_ms: float  # arrival offset from the recorded server's epoch
    rid: str
    n: int
    cls: str
    deadline_s: Optional[float]
    admitted: bool
    reason: str  # "" | "queue_full" | "too_wide"


@dataclasses.dataclass(frozen=True)
class RecordedFault:
    """One recorded device-loss incident (``sup_trip`` + its paired
    ``mesh_shrink`` record when the loss shrank the pool)."""

    step: int
    kind: str  # "device_loss" | "mesh_shrink"
    lost: Tuple[int, ...]
    cause: str


def _empty_counts() -> Dict[str, int]:
    return {"offered": 0, "ok": 0, "shed": 0, "failed": 0, "rejected": 0}


@dataclasses.dataclass
class RecordedRun:
    """Everything a journal says about one serve run: the conditions
    (``config`` — the ``serve_config`` record), the offered schedule, the
    incident trail, and the recorded outcome accounting to diff a replay
    against."""

    config: dict
    submits: List[RecordedSubmit]
    faults: List[RecordedFault]
    accounting: Dict[str, Dict[str, int]]  # class -> closed counts
    latencies_ms: List[float]  # journal-derived (serve_batch req_lat_ms)
    class_latencies_ms: Dict[str, List[float]]
    unreplayed: Dict[str, int]  # journal kinds replay does not re-drive
    source: str = ""

    @property
    def duration_s(self) -> float:
        if not self.submits:
            return 0.0
        ts = [s.t_ms for s in self.submits]
        return (max(ts) - min(ts)) / 1e3


def load_recorded_run(journal_path) -> RecordedRun:
    """Reconstruct a :class:`RecordedRun` from a journal file/dir.

    Raises an attributable ``ValueError`` when the journal predates the
    replay schema (no ``serve_submit`` arrival records, or no
    ``serve_config`` conditions record) — an unreplayable journal is a
    loud refusal, never a silently-empty load."""
    records = load_records(journal_path)
    return recorded_run_from_records(records, source=str(journal_path))


def recorded_run_from_records(
    records: List[dict], source: str = ""
) -> RecordedRun:
    config: Optional[dict] = None
    submits: List[RecordedSubmit] = []
    faults: List[RecordedFault] = []
    accounting: Dict[str, Dict[str, int]] = {}
    latencies: List[float] = []
    class_lat: Dict[str, List[float]] = {}
    unreplayed: Dict[str, int] = {}
    pending_shrinks: List[dict] = []

    def counts(cls: str) -> Dict[str, int]:
        return accounting.setdefault(cls, _empty_counts())

    for rec in records:
        kind = rec.get("kind")
        if kind == "serve_config":
            new_cfg = {k: v for k, v in rec.items() if k not in ("kind", "key")}
            if config is not None and new_cfg != config:
                # Two DIFFERENT servers journaled into one file: there is
                # no single set of conditions to replay under. A reused
                # journal path is an operator mistake worth naming, not
                # silently replaying half the evidence.
                raise ValueError(
                    f"journal {source or '<records>'} carries two differing "
                    "serve_config records — it mixes runs from different "
                    "server configurations; record each serve run into its "
                    "own journal file"
                )
            config = new_cfg
        elif kind == "serve_submit":
            sub = RecordedSubmit(
                t_ms=float(rec.get("t_ms", 0.0)),
                rid=str(rec.get("rid", "")),
                n=int(rec.get("n", 1)),
                cls=str(rec.get("cls", "")),
                deadline_s=(
                    float(rec["deadline_s"])
                    if rec.get("deadline_s") is not None
                    else None
                ),
                admitted=bool(rec.get("admitted", True)),
                reason=str(rec.get("reason", "")),
            )
            submits.append(sub)
            c = counts(sub.cls)
            c["offered"] += 1
            if not sub.admitted:
                c["rejected"] += 1
        elif kind == "serve_batch":
            req_lat = rec.get("req_lat_ms") or {}
            req_cls = rec.get("req_cls") or {}
            for rid, ms in req_lat.items():
                cls = str(req_cls.get(rid, ""))
                counts(cls)["ok"] += 1
                if isinstance(ms, (int, float)):
                    latencies.append(float(ms))
                    class_lat.setdefault(cls, []).append(float(ms))
        elif kind == "serve_shed":
            counts(str(rec.get("cls", "")))["shed"] += 1
        elif kind == "serve_fail":
            req_cls = rec.get("req_cls")
            if isinstance(req_cls, dict) and req_cls:
                for cls in req_cls.values():
                    counts(str(cls))["failed"] += 1
            else:  # pre-PR12 serve_fail: no per-request attribution
                counts("")["failed"] += int(rec.get("n_requests", 0))
        elif kind == "mesh_shrink":
            pending_shrinks.append(rec)
        elif kind == "sup_trip":
            sdc_kind = str(rec.get("sdc_kind", "device_loss"))
            lost: Tuple[int, ...] = ()
            if sdc_kind == "mesh_shrink" and pending_shrinks:
                shrink = pending_shrinks.pop()
                lost = tuple(int(i) for i in shrink.get("lost") or ())
            faults.append(
                RecordedFault(
                    step=int(rec.get("step", 0)),
                    kind=sdc_kind,
                    lost=lost,
                    cause=str(rec.get("cause", ""))[:120],
                )
            )
        elif kind in _GROWBACK_KINDS:
            unreplayed[kind] = unreplayed.get(kind, 0) + 1

    if not submits:
        raise ValueError(
            f"journal {source or '<records>'} has no serve_submit records — "
            "it was recorded before the replay schema (docs/OBSERVABILITY.md "
            "'Replay & regression gating'); re-record with a journaled "
            "server (run --serve --serve-journal / BENCH_MODE=serve)"
        )
    if config is None:
        raise ValueError(
            f"journal {source or '<records>'} has no serve_config record — "
            "the recorded conditions (config/shards/buckets/SLO) are the "
            "other half of the replay contract; re-record with a journaled "
            "server"
        )
    return RecordedRun(
        config=config,
        submits=submits,
        faults=faults,
        accounting=accounting,
        latencies_ms=latencies,
        class_latencies_ms=class_lat,
        unreplayed=unreplayed,
        source=source,
    )


# ------------------------------------------------------------- estimator ---


def percentile_resolution(
    xs: List[float], q: float, floor: float = 50.0
) -> float:
    """The nearest-rank estimator's resolution at quantile ``q`` over
    sample ``xs``: half the bracket between the order statistics adjacent
    to the selected rank, floored at ``floor`` (default 50 — the serving
    dispatch poll quantum in ms, the granularity below which two wall
    measurements of one schedule are indistinguishable). Two runs of the
    same offered schedule "agree" on a percentile when they differ by
    less than the sum of their resolutions — the estimator cannot claim
    more precision than the spacing of its own observed samples."""
    if not xs:
        return floor
    s = sorted(xs)
    n = len(s)
    rank = int(math.ceil(q / 100.0 * n)) if q > 0 else 1
    i = min(max(rank, 1), n) - 1
    lo = s[max(0, i - 1)]
    hi = s[min(n - 1, i + 1)]
    return max(floor, (hi - lo) / 2.0)


def _nearest_rank(xs: List[float], q: float) -> Optional[float]:
    from ..serving.loadgen import percentile

    return percentile(xs, q)


# ----------------------------------------------------------------- replay ---


@dataclasses.dataclass(frozen=True)
class ReplayKnobs:
    """The what-if dials. All neutral = the determinism contract run."""

    traffic_mult: float = 1.0
    devices: Optional[int] = None  # shard width override (None = recorded)
    slo_scale: float = 1.0
    journal_path: str = ""  # replay's own journal (default: temp file)
    wait_timeout_s: float = 120.0
    percentile_floor_ms: float = 50.0
    # Autopilot A/B dial (ISSUE 18, docs/SERVING.md "Autopilot"): "" =
    # as recorded, "on" = force a controller onto the replay server (the
    # recorded one when the journal carried its config, defaults
    # otherwise), "off" = strip it. Re-driving one saturating trace both
    # ways is the controller's proof: interactive burn must drop with it
    # on, books must close both ways.
    controller: str = ""
    # Optional ControllerConfig.to_obj() dict for the forced-on side —
    # short CI drills need snappier dwell/cooldown than the production
    # defaults. Ignored unless ``controller == "on"``.
    controller_cfg: Optional[dict] = None

    @property
    def neutral(self) -> bool:
        # Forcing a controller ON is a what-if (the question being
        # asked); "" and "off" leave an uncontrolled recording untouched.
        return (
            self.traffic_mult == 1.0
            and self.devices is None
            and self.slo_scale == 1.0
            and self.controller != "on"
        )


def expand_schedule(
    submits: List[RecordedSubmit], mult: float
) -> List[RecordedSubmit]:
    """The offered schedule at ``mult``× traffic: each recorded arrival is
    replicated ``floor(mult)`` times (copies share the arrival instant —
    a doubled fleet of clients sends what it sends), and the fractional
    remainder selects arrivals by a stable hash of their rid — the
    deterministic-schedule rule (two replays at one mult offer identical
    work), with no RNG that a reseed could shear."""
    if mult <= 0:
        raise ValueError(f"traffic_mult must be > 0, got {mult}")
    whole, frac = int(mult), mult - int(mult)
    out: List[RecordedSubmit] = []
    for idx, sub in enumerate(submits):
        copies = whole
        if frac > 0.0:
            h = zlib.crc32(f"{sub.rid}:{idx}".encode()) % 10_000
            if h < frac * 10_000:
                copies += 1
        for c in range(copies):
            rid = sub.rid if c == 0 and sub.rid else ""
            out.append(dataclasses.replace(sub, rid=rid))
    out.sort(key=lambda s: s.t_ms)
    return out


@dataclasses.dataclass
class ReplayReport:
    """One replay's verdict: per-class accounting vs the record, both
    percentile pairs, and the divergence call."""

    knobs: ReplayKnobs
    recorded: RecordedRun
    per_class: Dict[str, Dict[str, int]]
    latencies_ms: List[float]
    class_latencies_ms: Dict[str, List[float]]
    scripted_faults: int
    duration_s: float
    sustained_img_s: float
    cache_misses: int
    journal_path: str
    trace_id: str = ""
    # Whether the replay server ran an autopilot, and what it did
    # (serving.controller state_obj) — the A/B's on-side summary.
    controller_active: bool = False
    controller_state: Optional[dict] = None

    # -- accounting ---------------------------------------------------------

    @property
    def accounting_matches(self) -> bool:
        """Per-class identity with the record — offered / ok / shed /
        failed / rejected all equal for every class. The determinism
        contract's accounting half (only meaningful at neutral knobs)."""
        classes = set(self.per_class) | set(self.recorded.accounting)
        for cls in classes:
            if self.per_class.get(cls, _empty_counts()) != (
                self.recorded.accounting.get(cls, _empty_counts())
            ):
                return False
        return True

    @property
    def accounting_closed(self) -> bool:
        """ok + shed + failed + rejected == offered, per class — the
        no-silent-loss contract, which must hold at ANY knob setting."""
        return all(
            c["ok"] + c["shed"] + c["failed"] + c["rejected"] == c["offered"]
            for c in self.per_class.values()
        )

    @property
    def n_offered(self) -> int:
        return sum(c["offered"] for c in self.per_class.values())

    @property
    def n_shed(self) -> int:
        return sum(c["shed"] for c in self.per_class.values())

    # -- percentiles --------------------------------------------------------

    def percentile_pair(self, q: float) -> Tuple[Optional[float], Optional[float]]:
        return (
            _nearest_rank(self.recorded.latencies_ms, q),
            _nearest_rank(self.latencies_ms, q),
        )

    def percentile_within_resolution(self, q: float) -> Optional[bool]:
        """None when either side measured nothing; else whether record and
        replay agree within the estimator's own resolution."""
        rec, rep = self.percentile_pair(q)
        if rec is None or rep is None:
            return None
        floor = self.knobs.percentile_floor_ms
        tol = percentile_resolution(
            self.recorded.latencies_ms, q, floor
        ) + percentile_resolution(self.latencies_ms, q, floor)
        return abs(rec - rep) <= tol

    @property
    def percentiles_within_resolution(self) -> bool:
        return all(
            self.percentile_within_resolution(q) is not False for q in (50, 99)
        )

    # -- verdict ------------------------------------------------------------

    @property
    def diverged(self) -> bool:
        """True when a NEUTRAL replay broke the determinism contract.
        Accounting must match identically in every neutral replay; the
        percentile half additionally gates incident-FREE replays only —
        a re-driven device loss pays the degraded rung's compile time,
        which is process compile-cache state, not part of the recorded
        schedule (both pairs are always reported either way). What-if
        runs (any knob turned) are never 'divergent'; they are the
        question being asked."""
        if not self.knobs.neutral:
            return False
        if self.controller_active or self.recorded.config.get("controller"):
            # A closed-loop controller actuates on wall-clock signals
            # (burn windows, queue waits) — its actions are not part of
            # the recorded schedule, so the determinism contract only
            # binds controller-free pairs. The A/B's assertable half is
            # accounting_closed + the burn comparison, not identity.
            return False
        if not self.accounting_matches:
            return True
        return self.scripted_faults == 0 and not self.percentiles_within_resolution

    def summary(self) -> str:
        """One machine-parseable 'Replay:' payload (run CLI contract)."""
        rec50, rep50 = self.percentile_pair(50)
        rec99, rep99 = self.percentile_pair(99)

        def fmt(v):
            return f"{v:.3f}" if v is not None else "nan"

        totals = _empty_counts()
        for c in self.per_class.values():
            for k in totals:
                totals[k] += c[k]
        return (
            f"offered={totals['offered']} ok={totals['ok']} "
            f"shed={totals['shed']} failed={totals['failed']} "
            f"rejected={totals['rejected']} "
            f"mult={self.knobs.traffic_mult:g} "
            f"devices={self.knobs.devices if self.knobs.devices is not None else 'recorded'} "
            f"slo_scale={self.knobs.slo_scale:g} "
            f"controller={'on' if self.controller_active else 'off'} "
            f"accounting_matches={self.accounting_matches} "
            f"closed={self.accounting_closed} "
            f"p50_ms={fmt(rep50)}/{fmt(rec50)} p99_ms={fmt(rep99)}/{fmt(rec99)} "
            f"within_resolution={self.percentiles_within_resolution} "
            f"faults={self.scripted_faults} diverged={self.diverged}"
        )

    def class_lines(self) -> List[str]:
        out = []
        for cls in sorted(set(self.per_class) | set(self.recorded.accounting)):
            got = self.per_class.get(cls, _empty_counts())
            want = self.recorded.accounting.get(cls, _empty_counts())
            out.append(
                f"Replay class: name={cls or 'default'} "
                + " ".join(
                    f"{k}={got[k]}/{want[k]}"
                    for k in ("offered", "ok", "shed", "failed", "rejected")
                )
            )
        return out

    def to_obj(self) -> dict:
        rec50, rep50 = self.percentile_pair(50)
        rec99, rep99 = self.percentile_pair(99)
        return {
            "source": self.recorded.source,
            "traffic_mult": self.knobs.traffic_mult,
            "devices": self.knobs.devices,
            "slo_scale": self.knobs.slo_scale,
            "neutral": self.knobs.neutral,
            "classes": {
                (cls or "default"): {
                    "replay": self.per_class.get(cls, _empty_counts()),
                    "recorded": self.recorded.accounting.get(
                        cls, _empty_counts()
                    ),
                }
                for cls in sorted(
                    set(self.per_class) | set(self.recorded.accounting)
                )
            },
            "accounting_matches": self.accounting_matches,
            "accounting_closed": self.accounting_closed,
            "p50_ms": rep50,
            "p99_ms": rep99,
            "recorded_p50_ms": rec50,
            "recorded_p99_ms": rec99,
            "percentiles_within_resolution": self.percentiles_within_resolution,
            "scripted_faults": self.scripted_faults,
            "unreplayed": dict(self.recorded.unreplayed),
            "duration_s": round(self.duration_s, 3),
            "value": round(self.sustained_img_s, 1),
            "cache_misses": self.cache_misses,
            "journal": self.journal_path,
            "trace_id": self.trace_id,
            "controller": self.controller_active,
            "controller_state": self.controller_state,
            "diverged": self.diverged,
        }


def _build_server(recorded: RecordedRun, knobs: ReplayKnobs):
    """A live server at the recorded conditions (modulo the knobs)."""
    import dataclasses as dc

    from ..models.alexnet import BLOCKS12
    from ..serving.server import InferenceServer, ServeConfig
    from ..serving.slo import SLOPolicy

    cfg = recorded.config
    channels = int(cfg.get("channels", 3))
    if channels != BLOCKS12.in_channels:
        raise ValueError(
            f"recorded run used {channels} input channels; the Blocks 1-2 "
            f"replay mesh serves {BLOCKS12.in_channels} — not replayable"
        )
    model_cfg = dc.replace(
        BLOCKS12,
        in_height=int(cfg.get("height", BLOCKS12.in_height)),
        in_width=int(cfg.get("width", BLOCKS12.in_width)),
    )
    slo = None
    if cfg.get("slo"):
        slo = SLOPolicy.from_obj(cfg["slo"])
        if knobs.slo_scale != 1.0:
            slo = slo.scaled(knobs.slo_scale)
    controller = None
    if knobs.controller != "off":
        # "" = as recorded; "on" forces one (rebuilding the recorded
        # knobs when the journal carried them, defaults otherwise).
        cobj = cfg.get("controller")
        if knobs.controller == "on" and knobs.controller_cfg:
            cobj = knobs.controller_cfg
        if knobs.controller == "on" or cobj:
            from ..serving.controller import ControllerConfig

            controller = (
                ControllerConfig.from_obj(cobj)
                if isinstance(cobj, dict)
                else ControllerConfig()
            )
    scfg = ServeConfig(
        config=str(cfg.get("config", "v1_jit")),
        n_shards=(
            int(knobs.devices)
            if knobs.devices is not None
            else int(cfg.get("n_shards", 1))
        ),
        compute=str(cfg.get("compute", "fp32")),
        policy="replay",
        max_batch=int(cfg.get("max_batch", 8)),
        buckets=tuple(cfg.get("buckets") or ()) or None,
        supervise=bool(cfg.get("supervise", False)),
        journal_path=knobs.journal_path,
        max_pending=int(cfg.get("max_pending", 1024)),
        poll_s=float(cfg.get("poll_s", 0.02)),
        default_deadline_s=(
            float(cfg["default_deadline_s"])
            if cfg.get("default_deadline_s") is not None
            else None
        ),
        model_cfg=model_cfg,
        slo=slo,
        controller=controller,
    )
    return InferenceServer(scfg)


def replay_recorded(
    recorded: RecordedRun, knobs: ReplayKnobs = ReplayKnobs()
) -> ReplayReport:
    """Re-drive a recorded run through a live server and report.

    The offered schedule is paced on the wall clock exactly as recorded
    (offsets normalized to the first arrival); every handle is awaited
    (bounded), so per-class accounting closes by construction. Scripted
    faults re-drive the recorded incident trail at the same supervised
    steps with the same victim device ids."""
    import tempfile

    import numpy as np

    from ..serving.queue import FAILED, OK, QueueFull, SHED
    from .metrics import registry as metrics_registry
    from .trace import Tracer, get_tracer, set_tracer, span

    if not knobs.journal_path:
        fd, tmp_journal = tempfile.mkstemp(
            prefix="replay_journal_", suffix=".jsonl"
        )
        os.close(fd)
        knobs = dataclasses.replace(knobs, journal_path=tmp_journal)
    server = _build_server(recorded, knobs)
    if recorded.faults and not server.cfg.supervise:
        # A recorded incident trail needs the supervisor to re-drive; a
        # bare forward would just... not trip. Refuse attributably.
        raise ValueError(
            f"recorded run has {len(recorded.faults)} device-loss "
            "incident(s) but was not supervised — cannot re-drive the "
            "chaos schedule without the ladder"
        )
    schedule = expand_schedule(recorded.submits, knobs.traffic_mult)
    metrics_registry().reset()
    owns_tracer = get_tracer() is None
    tracer = None
    if owns_tracer and server.journal is not None:
        tracer = Tracer(journal=server.journal)
        set_tracer(tracer)

    per_class: Dict[str, Dict[str, int]] = {}
    class_lat: Dict[str, List[float]] = {}
    handles: List[Tuple[str, object]] = []
    imgs: dict = {}  # n -> cached deterministic input (allocation, not payload)
    m = server._model_cfg()

    def _input(n: int) -> np.ndarray:
        if n not in imgs:
            imgs[n] = np.ones(
                (n, m.in_height, m.in_width, m.in_channels), np.float32
            )
        return imgs[n]

    def counts(cls: str) -> Dict[str, int]:
        return per_class.setdefault(cls, _empty_counts())

    t0 = t_done = time.monotonic()
    drained = False
    try:
        server.start()
        if server.sup is not None:
            for f in recorded.faults:
                server.sup.script_fault(
                    f.step, kind=f.kind, device_ids=f.lost,
                    cause=f"replay:{f.cause or f.kind}",
                )
        t_first = schedule[0].t_ms if schedule else 0.0
        with span(
            "replay.load",
            source=recorded.source,
            offered=len(schedule),
            traffic_mult=knobs.traffic_mult,
        ):
            t0 = time.monotonic()
            for sub in schedule:
                at = (sub.t_ms - t_first) / 1e3
                now = time.monotonic() - t0
                if at > now:
                    time.sleep(at - now)
                c = counts(sub.cls)
                c["offered"] += 1
                deadline_s = sub.deadline_s
                if deadline_s is not None and knobs.slo_scale != 1.0:
                    deadline_s *= knobs.slo_scale
                try:
                    handles.append(
                        (
                            sub.cls,
                            server.submit(
                                _input(sub.n),
                                deadline_s=deadline_s,
                                rid=sub.rid or None,
                                cls=sub.cls,
                            ),
                        )
                    )
                except (QueueFull, ValueError):
                    c["rejected"] += 1  # backpressure: counted, attributed
        wait_deadline = time.monotonic() + knobs.wait_timeout_s
        for _cls, h in handles:
            h.wait(max(0.0, wait_deadline - time.monotonic()))
        images_ok = 0
        completed_at: List[float] = []
        t_done = time.monotonic()
        for cls, h in handles:
            c = counts(cls)
            if h.completed_at is not None:
                completed_at.append(h.completed_at)
            if h.status == OK:
                c["ok"] += 1
                images_ok += h.n_images
                if h.latency_ms is not None:
                    class_lat.setdefault(cls, []).append(h.latency_ms)
            elif h.status == SHED:
                c["shed"] += 1
            elif h.status == FAILED:
                c["failed"] += 1
            else:  # still PENDING past the bounded wait: a hung handle is
                # a failure, never an accounting leak
                c["failed"] += 1
        drained = True
    finally:
        try:
            # Drain only on the clean path; a failed replay must not hang
            # another wait_timeout on its way out.
            server.stop(drain=drained, timeout_s=10.0)
        except Exception:
            pass
        if tracer is not None:
            set_tracer(None)
    wall = (max(completed_at) - t0) if completed_at else (t_done - t0)

    # Journal-derived latencies — the SAME crash-consistent source the
    # recorded side's numbers come from, so the comparison is symmetric.
    from ..serving.server import latencies_from_records

    replay_records = Journal.load(knobs.journal_path)
    jlat = latencies_from_records(replay_records)

    return ReplayReport(
        knobs=knobs,
        recorded=recorded,
        per_class=per_class,
        latencies_ms=jlat,
        class_latencies_ms=class_lat,
        scripted_faults=len(recorded.faults),
        duration_s=wall,
        sustained_img_s=images_ok / wall if wall > 0 else 0.0,
        cache_misses=server.stats.cache_misses,
        journal_path=knobs.journal_path,
        trace_id=tracer.trace_id if tracer is not None else "",
        controller_active=server.controller is not None,
        controller_state=(
            server.controller.state_obj()
            if server.controller is not None
            else None
        ),
    )


def replay_journal(journal_path, **knob_kwargs) -> ReplayReport:
    """Load + replay in one call (the CLI / bench surface)."""
    return replay_recorded(
        load_recorded_run(journal_path), ReplayKnobs(**knob_kwargs)
    )

"""Fleet health analytics: incident MTTR decomposition, availability &
SLO-attainment accounting, and journaled compile-cost attribution.

PRs 8-13 journal every elasticity transition (``sup_trip``/``sup_degrade``
/``sup_reshard``/``sup_replay``, ``mesh_probation``, ``sup_promote``) and
every serve outcome (``serve_batch``/``serve_shed``/``serve_fail``), but
no layer answers what a fleet operator actually asks: *how long did each
incident cost and where did the time go, what availability did the fleet
deliver, and did each SLO class stay inside its error budget?* This
module is that analytics layer, over the journal ALONE — any recorded
run (serve, train, bench, replay) folds into one :class:`HealthReport`.

Three parts:

- **Compile-event journaling** — the one instrumentation point every
  compiling call site shares (``Supervisor`` first-calls/``warm``,
  serving ``warmup``/``_rewarm``, observer-gated
  ``configs.build_forward`` first-calls). Each XLA compile journals a
  ``compile_event`` record: site, rung entry, bucket shape, dtype,
  batch, measured wall ms, cache hit/miss, and — best effort — the
  compiled executable's own ``cost_analysis()`` flops/bytes, so the
  PR 13 analytic roofline ledger gets an XLA-side oracle
  (:data:`FLOPS_RTOL` states the agreement tolerance; backends without
  cost analysis degrade visibly to ``unavailable``, never silently).
  Recompilation is the known dominant MTTR component (PR 12's
  determinism contract carves it out as process cache state); before
  this record it was observed exactly once per supervisor lifetime.
- **Incident reconstruction** — :func:`incidents_from_records` folds the
  raw trail into :class:`Incident` objects: trip → degrade → compile/
  rewarm → reshard → replay → recovered, and heal → probation → promote
  grow-back cycles. Each carries a per-phase MTTR decomposition whose
  phases **sum to the incident wall time by construction** (the span
  tree gives the wall; nested child spans give exclusive phase times;
  ``detect`` absorbs the un-attributed remainder, with a proportional
  clamp when rounding would push the sum past the wall). Journals
  recorded before this PR (no ``compile_event`` anywhere) report the
  compile phase as *unattributed* — None, rendered as such — never as a
  false zero.
- **Availability & SLO attainment** — a device-seconds capacity timeline
  from ``mesh_shrink``/``sup_promote`` (timestamped by the nearest
  preceding ``t_ms``-bearing record — the serve epoch), per-class
  served/shed/failed against each ``SLOClass`` budget with error-budget
  burn (p99 target ⇒ a :data:`ERROR_BUDGET` violation allowance), and
  flap/quarantine accounting. ``observability health --fail-on-
  budget-burn`` exits 3 on a blown budget, the gate family's style.

Import weight: stdlib + ``serving.slo`` (itself stdlib) at module level.
jax is touched only inside :func:`xla_cost_analysis` and only when a
compiling call site asks for it; the report path never imports a
backend, so ``health`` runs on any journal anywhere.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..serving.slo import SLOClass, SLOPolicy

# XLA's cost model and the analytic ledger count ops under different
# conventions (XLA does not bill max-pool compares or LRN's fused
# elementwise chain the way models.alexnet.flops_per_image does — on the
# CPU backend the measured ratio sits at ~0.75x, stable across batch).
# The cross-check therefore asserts agreement within this RELATIVE
# tolerance, not equality; outside it the check reports "diverges" and
# the row stays visible for triage.
FLOPS_RTOL = 0.5
# Each class is operated against its p99 target (slo.SLOClass.slo_ms):
# the error budget is the 1% of completed requests allowed to violate.
# burn = violation share / ERROR_BUDGET; burn > 1.0 is a blown budget.
ERROR_BUDGET = 0.01

# The trip-incident phase order (rendered and exported in this order;
# they tile the incident wall time exactly).
TRIP_PHASES = ("detect", "degrade", "compile", "rewarm", "reshard", "replay")
GROWBACK_PHASES = ("probation", "spot_check", "compile", "promote")
# Router backend-down incidents (ISSUE 16, serving.router): a host dies,
# the probes notice (detect), in-flight traffic drains into failures and
# redirects (drain is the remainder phase), subsequent traffic redirects
# away (redirect), and the restarted backend waits out probation
# (readmit). Summing to the outage wall by the same _clamped_phases rule.
BACKEND_DOWN_PHASES = ("detect", "drain", "redirect", "readmit")
# Fleet-drain incidents (ISSUE 20, serving.fleet_controller): sustained
# burn is observed for drain_after_s (detect), the backend sits drained
# with home traffic spilled (drain is the remainder phase), and the LIFO
# readmit flips it back (readmit). Same _clamped_phases sum-to-wall rule.
FLEET_DRAIN_PHASES = ("detect", "drain", "readmit")

_DTYPE_TO_LEDGER = {
    "float32": "fp32", "fp32": "fp32",
    "bfloat16": "bf16", "bf16": "bf16",
}


def off_timed_path(fn):
    """Same contract (and decorator NAME — what staticcheck matches) as
    ``resilience.sentinel.off_timed_path``: never called inside a timed
    region. Declared locally so this module stays backend-import-free."""
    fn.__off_timed_path__ = True
    return fn


# --------------------------------------------------------------------------
# compile-event journaling (the shared instrumentation point)


@off_timed_path
def xla_cost_analysis(fn, *args) -> Tuple[Optional[float], Optional[float]]:
    """``(flops, bytes_accessed)`` from the compiled executable's own cost
    model (``fn.lower(*args).compile().cost_analysis()``), or
    ``(None, None)`` on ANY failure — a backend without cost analysis, a
    non-jitted callable, a lowering error. The caller records None as
    ``unavailable``; degradation is visible, never a fake number. With
    the persistent compile cache enabled (configs.build_forward) the
    re-lowering compiles from cache, so the probe costs a deserialize,
    not a second compile."""
    try:
        compiled = fn.lower(*args).compile()
        ca = compiled.cost_analysis()
        d = ca[0] if isinstance(ca, (list, tuple)) and ca else ca
        if not isinstance(d, dict):
            return None, None
        flops = d.get("flops")
        nbytes = d.get("bytes accessed")
        return (
            float(flops) if isinstance(flops, (int, float)) and flops > 0 else None,
            float(nbytes) if isinstance(nbytes, (int, float)) and nbytes > 0 else None,
        )
    except Exception:
        return None, None


@off_timed_path
def compile_event(
    *,
    site: str,
    entry: str,
    shape: Sequence[int],
    dtype: str,
    ms: float,
    cache_hit: bool,
    n_shards: int = 1,
    fn=None,
    args: tuple = (),
) -> dict:
    """Build one ``compile_event`` payload. ``ms`` is the measured wall of
    the first call at this (entry, shape) — compile + one run; the run is
    noise next to the compile, and the measurement is honest about being
    end-to-end. ``n_shards`` records the executable's partition degree:
    XLA's ``cost_analysis`` bills the PER-SHARD module for partitioned
    programs, so the roofline cross-check needs it to pick the right
    convention. Cost analysis is probed only on misses (a cache hit
    compiled nothing) and only when ``fn`` is given; the
    ``HEALTH_COST_ANALYSIS=0`` kill switch skips the probe entirely."""
    flops = nbytes = None
    if (
        fn is not None
        and not cache_hit
        and os.environ.get("HEALTH_COST_ANALYSIS", "1") != "0"
    ):
        flops, nbytes = xla_cost_analysis(fn, *args)
    shape = [int(d) for d in shape]
    return {
        "site": site,
        "entry": entry,
        "shape": shape,
        "batch": shape[0] if shape else 0,
        "dtype": str(dtype),
        "n_shards": max(1, int(n_shards)),
        "ms": round(float(ms), 3),
        "cache_hit": bool(cache_hit),
        "xla_flops": flops,
        "xla_bytes": nbytes,
    }


@off_timed_path
def journal_compile_event(journal, rec: dict) -> None:
    """Append one built :func:`compile_event` payload to a journal
    (no-op without one), merging the open span's correlation ids so an
    exported timeline pins each compile slice inside the rewarm/warmup
    span that paid for it."""
    if journal is None:
        return
    from .trace import current_ids

    journal.append(
        "compile_event",
        key=f"compile:{rec['site']}:{rec['entry']}:b{rec['batch']}",
        **{**current_ids(), **rec},
    )


# Observer hook for configs.build_forward first-call instrumentation.
# Uninstrumented builds (no observer installed) return the jitted
# callable UNCHANGED — function identity, .lower(), everything — so the
# hook costs existing callers nothing; run/bench install an observer
# that routes the events into their journal.
_COMPILE_OBSERVER: Optional[Callable[[dict], None]] = None


def set_compile_observer(
    cb: Optional[Callable[[dict], None]]
) -> Optional[Callable[[dict], None]]:
    """Install the process-wide compile observer (None uninstalls);
    returns the previous one so tests can restore it."""
    global _COMPILE_OBSERVER
    prev, _COMPILE_OBSERVER = _COMPILE_OBSERVER, cb
    return prev


def get_compile_observer() -> Optional[Callable[[dict], None]]:
    return _COMPILE_OBSERVER


def journal_compile_observer(journal) -> Callable[[dict], None]:
    """An observer that journals every event — the run/bench wiring."""

    def _observe(rec: dict) -> None:
        journal_compile_event(journal, rec)

    return _observe


def observed_first_calls(
    fn, *, site: str, entry: str, dtype: str, n_shards: int = 1
):
    """Wrap a jitted ``(params, x) -> out`` so the FIRST call per input
    shape — the XLA compile — is timed and reported to the installed
    compile observer. Only applied when an observer IS installed at
    build time (configs.build_forward checks); every uninstrumented
    build keeps the bare jitted callable."""

    seen: set = set()

    def wrapped(params, x):
        shape = tuple(int(d) for d in getattr(x, "shape", ()))
        if shape in seen:
            return fn(params, x)
        import jax

        t0 = time.perf_counter()
        out = fn(params, x)
        jax.block_until_ready(out)
        ms = (time.perf_counter() - t0) * 1e3
        seen.add(shape)
        _report_first_call(
            site=site, entry=entry, shape=shape, dtype=dtype, ms=ms,
            n_shards=n_shards, fn=fn, args=(params, x),
        )
        return out

    wrapped.__wrapped__ = fn  # .lower() etc. stay reachable
    return wrapped


@off_timed_path
def _report_first_call(
    *, site, entry, shape, dtype, ms, n_shards, fn, args
) -> None:
    obs = get_compile_observer()
    if obs is None:
        return
    obs(
        compile_event(
            site=site, entry=entry, shape=shape, dtype=dtype, ms=ms,
            cache_hit=False, n_shards=n_shards, fn=fn, args=args,
        )
    )


# --------------------------------------------------------------------------
# incident reconstruction


@dataclasses.dataclass
class Incident:
    """One folded incident: a trip (trip → degrade → compile/rewarm →
    reshard → replay → recovered) or a grow-back (heal → probation →
    promote). ``phases`` maps phase name → exclusive ms; the values sum
    to ``wall_ms`` by construction, with ``compile: None`` meaning
    *unattributed* (a pre-compile_event journal) — the sum identity then
    holds over the attributed phases."""

    kind: str  # "trip" | "growback"
    index: int
    entry: str  # rung tripped on / promoted to
    cause: str
    wall_ms: float
    phases: Dict[str, Optional[float]]
    t0_ms: Optional[float] = None  # tracer-epoch start (None: span-less)
    trace_id: str = ""

    @property
    def phase_sum_ms(self) -> float:
        return sum(v for v in self.phases.values() if v is not None)

    def to_obj(self) -> dict:
        return {
            "kind": self.kind,
            "index": self.index,
            "entry": self.entry,
            "cause": self.cause,
            "wall_ms": round(self.wall_ms, 3),
            "phases": {
                k: (round(v, 3) if v is not None else None)
                for k, v in self.phases.items()
            },
            "t0_ms": self.t0_ms,
        }

    def render(self) -> str:
        parts = " ".join(
            f"{k}={'unattributed' if v is None else format(v, '.1f')}"
            for k, v in self.phases.items()
        )
        if self.kind == "trip":
            head = f"trip {self.cause} @{self.entry}"
        elif self.kind == "backend_down":
            head = f"backend_down {self.entry} ({self.cause})"
        elif self.kind == "fleet_drain":
            head = f"fleet_drain {self.entry} ({self.cause})"
        else:
            head = f"growback -> {self.entry}"
        return f"#{self.index} {head} wall={self.wall_ms:.1f}ms  {parts}"


def _span_tree(spans: List[dict]):
    kids: Dict[str, List[dict]] = {}
    for s in spans:
        pid = s.get("parent_id") or ""
        if pid:
            kids.setdefault(pid, []).append(s)
    return kids


def _subtree(root: dict, kids) -> List[dict]:
    out, frontier = [root], [root]
    while frontier:
        nxt = []
        for s in frontier:
            for c in kids.get(s.get("span_id") or "", ()):
                out.append(c)
                nxt.append(c)
        frontier = nxt
    return out


def _dur(spans: List[dict], name: str) -> float:
    return sum(
        float(s.get("dur_ms") or 0.0) for s in spans if s.get("name") == name
    )


def _clamped_phases(
    wall: float, order: Sequence[str], raw: Dict[str, Optional[float]],
    remainder: str,
) -> Dict[str, Optional[float]]:
    """Exclusive phases that sum EXACTLY to ``wall``: the ``remainder``
    phase absorbs the unattributed rest; when attributed time exceeds the
    wall (cross-clock rounding), every phase scales proportionally so the
    identity survives instead of going negative."""
    attributed = sum(v for v in raw.values() if v is not None)
    if attributed > wall and attributed > 0.0:
        scale = wall / attributed
        raw = {
            k: (v * scale if v is not None else None) for k, v in raw.items()
        }
        rest = 0.0
    else:
        rest = wall - attributed
    phases: Dict[str, Optional[float]] = {}
    for name in order:
        if name == remainder:
            phases[name] = rest
        else:
            phases[name] = raw.get(name)
    return phases


def incidents_from_records(records: List[dict]) -> List[Incident]:
    """Fold a journal's raw trail into :class:`Incident` objects.

    With span records (a traced run) the ``sup.trip``/``sup.recover``
    span trees give each incident's wall time and the nested children
    (``sup.degrade`` ⊃ ``serve.rewarm``, ``sup.replay`` ⊃
    ``sup.reshard``, ``sup.promote``) give exclusive phase times.
    Compile time comes from the ``compile_event`` records landing between
    the trip record and its recovery record (``sup_ok``/``sup_step``) in
    append order — journals with no ``compile_event`` anywhere report the
    phase as None (*unattributed*). Span-less journals fall back to the
    attributed-ms fields alone (``serve_rewarm.ms``, ``sup_promote.ms``,
    probation ``ms``): the wall is then the phase sum by definition and
    ``detect`` is zero — coarser, but the identity still holds."""
    spans = [r for r in records if r.get("kind") == "span"]
    kids = _span_tree(spans)
    has_ce = any(r.get("kind") == "compile_event" for r in records)

    trip_spans = sorted(
        (s for s in spans if s.get("name") == "sup.trip"),
        key=lambda s: float(s.get("t0_ms") or 0.0),
    )
    recover_spans = sorted(
        (s for s in spans if s.get("name") == "sup.recover"),
        key=lambda s: float(s.get("t0_ms") or 0.0),
    )
    # Committed recoveries only: a refused candidate leaves a sup.recover
    # span with no sup.promote child (and no sup_promote record).
    committed = [
        s
        for s in recover_spans
        if any(c.get("name") == "sup.promote" for c in _subtree(s, kids))
    ]

    trip_recs = [
        (i, r) for i, r in enumerate(records) if r.get("kind") == "sup_trip"
    ]
    promote_recs = [
        (i, r) for i, r in enumerate(records) if r.get("kind") == "sup_promote"
    ]
    ce_recs = [
        (i, r)
        for i, r in enumerate(records)
        if r.get("kind") == "compile_event"
    ]
    pass_recs = [
        (i, r)
        for i, r in enumerate(records)
        if r.get("kind") == "mesh_probation" and r.get("event") == "pass"
    ]

    def _ce_ms(lo: int, hi: int) -> float:
        return sum(
            float(r.get("ms") or 0.0) for i, r in ce_recs if lo < i < hi
        )

    incidents: List[Incident] = []

    # ---- trips ----
    end_kinds = ("sup_ok", "sup_step", "sup_trip")
    for n, (idx, rec) in enumerate(trip_recs):
        hi = next(
            (
                i
                for i, r in enumerate(records)
                if i > idx and r.get("kind") in end_kinds
            ),
            len(records),
        )
        raw_ce = _ce_ms(idx, hi)
        compile_p: Optional[float] = raw_ce if has_ce else None
        sp = trip_spans[n] if n < len(trip_spans) else None
        if sp is not None:
            sub = _subtree(sp, kids)
            wall = float(sp.get("dur_ms") or 0.0)
            degrade_s = _dur(sub, "sup.degrade")
            rewarm_s = _dur(sub, "serve.rewarm")
            reshard_s = _dur(sub, "sup.reshard")
            replay_s = _dur(sub, "sup.replay")
            c = compile_p or 0.0
            raw = {
                "degrade": max(0.0, degrade_s - rewarm_s),
                "compile": compile_p,
                "rewarm": max(0.0, rewarm_s - min(c, rewarm_s)),
                "reshard": reshard_s,
                "replay": max(0.0, replay_s - reshard_s),
            }
            phases = _clamped_phases(wall, TRIP_PHASES, raw, "detect")
            t0 = float(sp.get("t0_ms") or 0.0)
            trace_id = str(sp.get("trace_id") or "")
        else:
            rewarm_s = sum(
                float(r.get("ms") or 0.0)
                for i, r in enumerate(records)
                if idx < i < hi and r.get("kind") == "serve_rewarm"
            )
            c = compile_p or 0.0
            raw = {
                "degrade": 0.0,
                "compile": compile_p,
                "rewarm": max(0.0, rewarm_s - min(c, rewarm_s)),
                "reshard": 0.0,
                "replay": 0.0,
            }
            wall = sum(v for v in raw.values() if v is not None)
            phases = _clamped_phases(wall, TRIP_PHASES, raw, "detect")
            t0, trace_id = None, ""
        incidents.append(
            Incident(
                kind="trip",
                index=len(incidents) + 1,
                entry=str(rec.get("entry") or ""),
                cause=str(rec.get("sdc_kind") or rec.get("cause") or "trip"),
                wall_ms=wall,
                phases=phases,
                t0_ms=t0,
                trace_id=trace_id,
            )
        )

    # ---- grow-backs ----
    used_pass: set = set()
    for n, (idx, rec) in enumerate(promote_recs):
        # The probation the heal waited out: the latest un-consumed
        # "pass" record preceding this promotion in append order.
        prob_ms, prob_idx = 0.0, None
        for i, r in pass_recs:
            if i < idx and i not in used_pass:
                prob_ms, prob_idx = float(r.get("ms") or 0.0), i
        if prob_idx is not None:
            used_pass.add(prob_idx)
        raw_ce = _ce_ms(prob_idx if prob_idx is not None else -1, idx + 1)
        compile_p = raw_ce if has_ce else None
        c = compile_p or 0.0
        sp = committed[n] if n < len(committed) else None
        if sp is not None:
            sub = _subtree(sp, kids)
            recover_s = float(sp.get("dur_ms") or 0.0)
            promote_s = _dur(sub, "sup.promote")
            wall = prob_ms + recover_s
            raw = {
                "probation": prob_ms,
                "compile": compile_p,
                "promote": max(0.0, promote_s - min(c, promote_s)),
            }
            phases = _clamped_phases(wall, GROWBACK_PHASES, raw, "spot_check")
            # The incident starts when the probation the heal waited out
            # started, prob_ms before the recover span — so an exported
            # parent slice covers probation AND promotion to scale.
            t0 = max(0.0, float(sp.get("t0_ms") or 0.0) - prob_ms)
            trace_id = str(sp.get("trace_id") or "")
        else:
            promote_s = float(rec.get("ms") or 0.0)
            raw = {
                "probation": prob_ms,
                "compile": compile_p,
                "promote": max(0.0, promote_s - min(c, promote_s)),
            }
            wall = sum(v for v in raw.values() if v is not None)
            phases = _clamped_phases(wall, GROWBACK_PHASES, raw, "spot_check")
            t0, trace_id = None, ""
        incidents.append(
            Incident(
                kind="growback",
                index=len(incidents) + 1,
                entry=str(rec.get("to") or ""),
                cause=f"promote {rec.get('frm', '?')} -> {rec.get('to', '?')}",
                wall_ms=wall,
                phases=phases,
                t0_ms=t0,
                trace_id=trace_id,
            )
        )

    # ---- router backend-down windows (ISSUE 16, serving.router) ----
    # A window opens at a real state transition to "down" and closes at
    # the matching readmission to "up" (endpoint replacements journal
    # frm == to and are not transitions; a still-down backend at journal
    # end is an OPEN outage, not an incident row). The incident starts
    # detect_ms BEFORE the down verdict — the detection latency is part
    # of the outage, not prologue.
    state_recs = [
        (i, r)
        for i, r in enumerate(records)
        if r.get("kind") == "router_backend_state"
        and r.get("frm") != r.get("to")
    ]
    redirect_recs = [
        (i, r) for i, r in enumerate(records) if r.get("kind") == "router_redirect"
    ]
    open_down: Dict[str, Tuple[int, dict]] = {}
    for i, r in state_recs:
        b = str(r.get("backend") or "")
        if r.get("to") == "down":
            open_down.setdefault(b, (i, r))
        elif r.get("to") == "up" and b in open_down:
            oi, orec = open_down.pop(b)
            detect = float(orec.get("detect_ms") or 0.0)
            t_down = float(orec.get("t_ms") or 0.0)
            t_up = float(r.get("t_ms") or 0.0)
            t0 = max(0.0, t_down - detect)
            wall = max(0.0, t_up - t0)
            red_ts = [
                float(rr.get("t_ms") or 0.0)
                for j, rr in redirect_recs
                if oi < j < i and rr.get("frm") == b
            ]
            raw: Dict[str, Optional[float]] = {
                "detect": detect,
                "redirect": max(0.0, max(red_ts) - t_down) if red_ts else 0.0,
                "readmit": float(r.get("probation_ms") or 0.0),
            }
            incidents.append(
                Incident(
                    kind="backend_down",
                    index=len(incidents) + 1,
                    entry=b,
                    cause=str(orec.get("reason") or "probe_failed"),
                    wall_ms=wall,
                    phases=_clamped_phases(wall, BACKEND_DOWN_PHASES, raw, "drain"),
                    t0_ms=t0,
                )
            )
    return incidents


# --------------------------------------------------------------------------
# availability (device-seconds capacity timeline)


def capacity_timeline(
    records: List[dict],
) -> Tuple[Optional[int], float, List[Tuple[float, int]]]:
    """``(initial_devices, duration_ms, [(t_ms, devices), ...])``.

    Journal records carry no wall timestamps; the serve epoch's
    ``t_ms``-bearing records (``serve_submit``/``serve_gauges``/
    ``mem_snapshot``) are the clock, so each capacity change
    (``mesh_shrink``, ``sup_promote``) is timestamped at the nearest
    PRECEDING ``t_ms`` — the resolution the journal affords. Journals
    without a serve epoch report a zero duration (availability
    degrades to None, visibly)."""
    t = 0.0
    dev0: Optional[int] = None
    segs: List[Tuple[float, int]] = []
    for r in records:
        k = r.get("kind")
        if k in ("serve_submit", "serve_gauges", "mem_snapshot"):
            tm = r.get("t_ms")
            if isinstance(tm, (int, float)):
                t = max(t, float(tm))
        elif k == "serve_config":
            d = r.get("devices")
            if dev0 is None and isinstance(d, int) and d > 0:
                dev0 = d
                segs.append((0.0, d))
        elif k == "mesh_shrink":
            before = r.get("before")
            if dev0 is None and isinstance(before, int) and before > 0:
                dev0 = before
                segs.append((0.0, before))
            after = r.get("after")
            if isinstance(after, int):
                segs.append((t, after))
        elif k == "sup_promote":
            d = r.get("devices")
            if isinstance(d, int) and d > 0:
                if dev0 is None:
                    dev0 = d
                    segs.append((0.0, d))
                segs.append((t, d))
    return dev0, t, segs


def availability_from_records(
    records: List[dict],
) -> Tuple[Optional[float], Optional[float], Optional[int], float]:
    """``(availability, delivered_device_ms, initial_devices,
    duration_ms)`` — delivered device-time integrated over the capacity
    timeline against the full-fleet ideal. None when the journal carries
    no capacity signal or no serve epoch to time it against."""
    dev0, dur, segs = capacity_timeline(records)
    if dev0 is None or dur <= 0.0:
        return None, None, dev0, dur
    delivered = 0.0
    cur = dev0
    last = 0.0
    for tm, dev in segs:
        tm = min(max(tm, 0.0), dur)
        delivered += cur * (tm - last)
        cur, last = dev, tm
    delivered += cur * (dur - last)
    return delivered / (dev0 * dur), delivered, dev0, dur


# --------------------------------------------------------------------------
# SLO attainment & error-budget burn


def _percentile(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank (the loadgen/metrics estimator — one convention
    repo-wide, so percentiles cross-check exactly)."""
    if not xs:
        return None
    ys = sorted(xs)
    rank = max(1, int(round(q / 100.0 * len(ys) + 0.5)))
    return ys[min(rank, len(ys)) - 1]


@dataclasses.dataclass
class ClassHealth:
    """One request class's served/shed/failed accounting against its
    :class:`~..serving.slo.SLOClass` budget."""

    name: str
    slo_ms: float  # 0 = unbounded (never burns)
    offered: int
    ok: int
    shed: int
    failed: int
    rejected: int
    p99_ms: Optional[float]
    violations: int
    burn: Optional[float]  # violation share / ERROR_BUDGET; None: unbounded

    @property
    def blown(self) -> bool:
        return self.burn is not None and self.burn > 1.0

    def to_obj(self) -> dict:
        return {
            "class": self.name,
            "slo_ms": self.slo_ms,
            "offered": self.offered,
            "ok": self.ok,
            "shed": self.shed,
            "failed": self.failed,
            "rejected": self.rejected,
            "p99_ms": self.p99_ms,
            "violations": self.violations,
            "error_budget": ERROR_BUDGET,
            "burn": (round(self.burn, 3) if self.burn is not None else None),
            "blown": self.blown,
        }

    def render(self) -> str:
        name = self.name or "(default)"
        slo = f"slo={self.slo_ms:.0f}ms" if self.slo_ms else "slo=unbounded"
        p99 = f"{self.p99_ms:.1f}ms" if self.p99_ms is not None else "n/a"
        burn = (
            f"burn={self.burn:.2f}x{' BLOWN' if self.blown else ''}"
            if self.burn is not None
            else "burn=n/a"
        )
        return (
            f"{name:<14s} {slo:<14s} p99={p99:<9s} ok={self.ok} "
            f"shed={self.shed} failed={self.failed} "
            f"rejected={self.rejected} violations={self.violations} {burn}"
        )


def slo_attainment(records: List[dict]) -> List[ClassHealth]:
    """Per-class attainment from the journal alone: offered from
    ``serve_submit``, completions + latencies from ``serve_batch``
    (``req_cls``/``req_lat_ms``), sheds from ``serve_shed``, failures
    from ``serve_fail``, budgets from the ``serve_config`` SLO policy.
    Violations = sheds + failures + completions over the class p99
    target; burn ranks classes worst-first. Admission rejections
    (``admitted=false``) are counted separately — a refused request
    never entered the service and burns no serving budget."""
    pol: Optional[SLOPolicy] = None
    for r in records:
        if r.get("kind") == "serve_config" and isinstance(r.get("slo"), dict):
            pol = SLOPolicy.from_obj(r["slo"])
    offered: Dict[str, int] = {}
    rejected: Dict[str, int] = {}
    lat: Dict[str, List[float]] = {}
    shed: Dict[str, int] = {}
    failed: Dict[str, int] = {}
    saw_submit = False
    for r in records:
        k = r.get("kind")
        if k == "serve_submit":
            saw_submit = True
            cls = str(r.get("cls") or "")
            if r.get("admitted", True):
                offered[cls] = offered.get(cls, 0) + 1
            else:
                rejected[cls] = rejected.get(cls, 0) + 1
        elif k == "serve_batch":
            cls_map = r.get("req_cls") or {}
            for rid, ms in (r.get("req_lat_ms") or {}).items():
                cls = str(cls_map.get(rid, ""))
                lat.setdefault(cls, []).append(float(ms))
        elif k == "serve_shed":
            cls = str(r.get("cls") or "")
            shed[cls] = shed.get(cls, 0) + 1
        elif k == "serve_fail":
            for cls in (r.get("req_cls") or {}).values():
                failed[str(cls)] = failed.get(str(cls), 0) + 1
    names = (
        set(offered) | set(rejected) | set(lat) | set(shed) | set(failed)
    )
    if pol is not None:
        names |= set(pol.classes)
    out: List[ClassHealth] = []
    for name in sorted(names):
        cls_obj = (
            pol.class_for(name) if pol is not None else SLOClass(name, 0.0)
        )
        ls = lat.get(name, [])
        n_ok, n_shed, n_failed = len(ls), shed.get(name, 0), failed.get(name, 0)
        completed = n_ok + n_shed + n_failed
        slo_ms = float(cls_obj.slo_ms or 0.0)
        late = sum(1 for v in ls if slo_ms and v > slo_ms)
        violations = late + n_shed + n_failed
        burn = (
            (violations / completed) / ERROR_BUDGET
            if slo_ms and completed
            else (0.0 if slo_ms else None)
        )
        out.append(
            ClassHealth(
                name=name,
                slo_ms=slo_ms,
                offered=(
                    offered.get(name, 0) if saw_submit else completed
                ),
                ok=n_ok,
                shed=n_shed,
                failed=n_failed,
                rejected=rejected.get(name, 0),
                p99_ms=_percentile(ls, 99),
                violations=violations,
                burn=burn,
            )
        )
    out.sort(key=lambda c: (c.burn is not None, c.burn or 0.0), reverse=True)
    return out


# --------------------------------------------------------------------------
# autopilot action attribution (ISSUE 18, serving.controller)


def controller_summary(records: List[dict]) -> dict:
    """Fold ``controller_action`` records into a did-it-help view: action
    counts by kind (escalations, reversals, refusals), plus the per-class
    error-budget burn split at the FIRST actuated action — burn over the
    outcomes journaled before the controller touched anything vs. burn
    after. Serve records carry no timestamps; journal append order is
    the temporal axis (the same convention the incident folder uses), so
    "after" is everything from that action's append position on. The
    ``serve_config`` header (the SLO budgets both halves are priced
    against) is re-prepended to the after-slice. Empty dict when the
    journal has no controller records — old journals fold unchanged."""
    actions = [
        (i, r)
        for i, r in enumerate(records)
        if r.get("kind") == "controller_action"
    ]
    if not actions:
        return {}
    by_kind: Dict[str, int] = {}
    refused = reversals = 0
    for _, r in actions:
        kind = str(r.get("action") or "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
        if not r.get("actuated", True):
            refused += 1
        elif r.get("reversal"):
            reversals += 1
    out: dict = {
        "actions": by_kind,
        "total": len(actions),
        "refused": refused,
        "reversals": reversals,
    }
    first = next(
        (i for i, r in actions if r.get("actuated", True)), None
    )
    if first is not None:
        header = [
            r for r in records[:first] if r.get("kind") == "serve_config"
        ]

        def burns(rs: List[dict]) -> Dict[str, Optional[float]]:
            return {
                c.name: (
                    round(c.burn, 3) if c.burn is not None else None
                )
                for c in slo_attainment(rs)
            }

        out["burn_before"] = burns(records[:first])
        out["burn_after"] = burns(header + records[first:])
    return out


def fleet_summary(records: List[dict]) -> dict:
    """Fold the fleet control plane's trail (ISSUE 20): ``fleet_action``/
    ``fleet_refusal`` counts by action, the max number of simultaneously
    degraded backends (walked from the ``router_probe`` scrape trail —
    per-backend last-seen ladder level, max count of nonzero levels at
    any probe), and drain incidents folded into detect → drain → readmit
    phases summing to the drain wall (a ``drain`` action paired with its
    backend's next ``readmit``). Empty dict when the journal has no
    fleet-control records — old journals fold unchanged."""
    acts = [
        r
        for r in records
        if r.get("kind") in ("fleet_action", "fleet_refusal")
    ]
    probes = [r for r in records if r.get("kind") == "router_probe"]
    if not acts and not probes:
        return {}
    by_kind: Dict[str, int] = {}
    refusals = 0
    for r in acts:
        name = str(r.get("action") or "?")
        by_kind[name] = by_kind.get(name, 0) + 1
        if r.get("kind") == "fleet_refusal":
            refusals += 1
    levels: Dict[str, int] = {}
    max_deg = 0
    for r in probes:
        lvl = r.get("level")
        levels[str(r.get("backend") or "")] = (
            int(lvl) if isinstance(lvl, int) else 0
        )
        max_deg = max(max_deg, sum(1 for v in levels.values() if v > 0))
    drains: List[Incident] = []
    open_drain: Dict[str, dict] = {}
    for r in acts:
        if r.get("kind") != "fleet_action" or not r.get("actuated", True):
            continue
        tgt = str(r.get("target") or "")
        if r.get("action") == "drain":
            open_drain.setdefault(tgt, r)
        elif r.get("action") == "readmit" and tgt in open_drain:
            d = open_drain.pop(tgt)
            detect = float((d.get("evidence") or {}).get("detect_ms") or 0.0)
            t_drain = float(d.get("t_ms") or 0.0)
            t_up = float(r.get("t_ms") or 0.0)
            t0 = max(0.0, t_drain - detect)
            wall = max(0.0, t_up - t0)
            raw: Dict[str, Optional[float]] = {
                "detect": min(detect, wall),
                "readmit": float(r.get("ms") or 0.0),
            }
            drains.append(
                Incident(
                    kind="fleet_drain",
                    index=len(drains) + 1,
                    entry=tgt,
                    cause=str(d.get("cause") or "drain"),
                    wall_ms=wall,
                    phases=_clamped_phases(
                        wall, FLEET_DRAIN_PHASES, raw, "drain"
                    ),
                    t0_ms=t0,
                )
            )
    return {
        "actions": by_kind,
        "total": len(acts),
        "refusals": refusals,
        "max_simultaneous_degraded": max_deg,
        "drains": [d.to_obj() for d in drains],
    }


# --------------------------------------------------------------------------
# compile-cost attribution & the roofline cross-check


def compile_attribution(records: List[dict]) -> dict:
    """Fold the ``compile_event`` trail: per-(site, entry, shape, dtype)
    compile counts/ms, totals, and the XLA-vs-analytic-ledger flops
    cross-check (tolerance :data:`FLOPS_RTOL`; rows without XLA cost
    analysis — or geometries the ledger cannot model — degrade to
    ``unavailable``). ``unattributed`` is True for journals recorded
    before this schema existed: compile time is then unknown, not zero."""
    evs = [r for r in records if r.get("kind") == "compile_event"]
    groups: Dict[tuple, dict] = {}
    for r in evs:
        key = (
            str(r.get("site") or ""),
            str(r.get("entry") or ""),
            tuple(r.get("shape") or ()),
            str(r.get("dtype") or ""),
        )
        g = groups.setdefault(
            key,
            {
                "site": key[0], "entry": key[1], "shape": list(key[2]),
                "dtype": key[3], "n_shards": max(1, int(r.get("n_shards") or 1)),
                "compiles": 0, "cache_hits": 0,
                "ms": 0.0, "xla_flops": None, "xla_bytes": None,
            },
        )
        if r.get("cache_hit"):
            g["cache_hits"] += 1
        else:
            g["compiles"] += 1
            g["ms"] += float(r.get("ms") or 0.0)
        for f in ("xla_flops", "xla_bytes"):
            if g[f] is None and isinstance(r.get(f), (int, float)):
                g[f] = float(r[f])
    rows = [
        {**g, "ms": round(g["ms"], 3)} for g in groups.values()
    ]
    rows.sort(key=lambda g: g["ms"], reverse=True)
    checks = [_flops_check(g) for g in rows]
    return {
        "unattributed": not evs,
        "events": len(evs),
        "total_ms": round(
            sum(
                float(r.get("ms") or 0.0)
                for r in evs
                if not r.get("cache_hit")
            ),
            3,
        ),
        "tolerance": FLOPS_RTOL,
        "rows": rows,
        "flops_checks": [c for c in checks if c is not None],
    }


def _flops_check(g: dict) -> Optional[dict]:
    """One row's XLA-vs-ledger verdict, from the event's own shape (the
    geometry is in the record — no config lookup needed). Verdicts:
    ``agree`` (within FLOPS_RTOL), ``diverges``, or ``unavailable``
    (no XLA cost analysis on this backend / unmodelable geometry)."""
    shape = g.get("shape") or []
    base = {
        "entry": g["entry"], "shape": shape, "dtype": g["dtype"],
        "xla_flops": g["xla_flops"], "ledger_flops": None,
        "ratio": None, "tolerance": FLOPS_RTOL,
    }
    if g["xla_flops"] is None:
        return {**base, "verdict": "unavailable"}
    ledger_dtype = _DTYPE_TO_LEDGER.get(g["dtype"])
    if len(shape) != 4 or ledger_dtype is None:
        return {**base, "verdict": "unavailable"}
    try:
        import dataclasses as _dc

        from ..models.alexnet import BLOCKS12
        from .roofline import pass_ledger

        cfg = _dc.replace(
            BLOCKS12, in_height=int(shape[1]), in_width=int(shape[2])
        )
        ledger = sum(
            s.flops
            for s in pass_ledger(
                cfg=cfg, dtype=ledger_dtype, batch=int(shape[0])
            )
        )
    except Exception:
        return {**base, "verdict": "unavailable"}
    if not ledger:
        return {**base, "verdict": "unavailable"}
    # XLA's cost model bills the PER-SHARD module for partitioned
    # programs; the ledger bills the whole pass. Compare under both
    # conventions and keep the closer one (the scale used is reported, so
    # nothing is hidden) — a 2-shard executable at raw ratio ~0.5 is an
    # agreeing per-shard module, not a divergence.
    n_sh = max(1, int(g.get("n_shards") or 1))
    best_scale, best_err = 1, abs(g["xla_flops"] - ledger) / ledger
    if n_sh > 1:
        err = abs(g["xla_flops"] * n_sh - ledger) / ledger
        if err < best_err:
            best_scale, best_err = n_sh, err
    ratio = g["xla_flops"] * best_scale / ledger
    return {
        **base,
        "ledger_flops": float(ledger),
        "shard_scale": best_scale,
        "ratio": round(ratio, 4),
        "verdict": "agree" if best_err <= FLOPS_RTOL else "diverges",
    }


# --------------------------------------------------------------------------
# the report


@dataclasses.dataclass
class HealthReport:
    """The folded fleet-health view of one journal (or a directory of
    them): incidents with MTTR decomposition, availability, ranked SLO
    attainment, flap/quarantine accounting, and compile attribution."""

    incidents: List[Incident]
    classes: List[ClassHealth]
    availability: Optional[float]
    delivered_device_ms: Optional[float]
    devices: Optional[int]
    duration_ms: float
    flaps: int
    quarantines: int
    probation_enters: int
    probation_passes: int
    compile: dict
    n_records: int
    # Autopilot fold (controller_summary): action counts + the
    # before/after burn split. Empty for journals without controller
    # records — and then absent from to_obj(), so pre-ISSUE-18 journals
    # produce byte-identical report objects.
    controller: dict = dataclasses.field(default_factory=dict)
    # Fleet control fold (fleet_summary): action counts, max
    # simultaneously degraded backends, drain incidents. Empty for
    # journals without fleet records — and then absent from to_obj(),
    # so pre-ISSUE-20 journals produce byte-identical report objects.
    fleet: dict = dataclasses.field(default_factory=dict)

    @property
    def trips(self) -> List[Incident]:
        return [i for i in self.incidents if i.kind == "trip"]

    @property
    def mttr_ms(self) -> Optional[float]:
        ts = self.trips
        return sum(i.wall_ms for i in ts) / len(ts) if ts else None

    @property
    def worst_burn(self) -> Optional[float]:
        burns = [c.burn for c in self.classes if c.burn is not None]
        return max(burns) if burns else None

    @property
    def budget_blown(self) -> bool:
        return any(c.blown for c in self.classes)

    def to_obj(self) -> dict:
        return {
            "n_records": self.n_records,
            "incidents": [i.to_obj() for i in self.incidents],
            "mttr_ms": (
                round(self.mttr_ms, 3) if self.mttr_ms is not None else None
            ),
            "availability": (
                round(self.availability, 6)
                if self.availability is not None
                else None
            ),
            "devices": self.devices,
            "duration_ms": round(self.duration_ms, 3),
            "delivered_device_ms": (
                round(self.delivered_device_ms, 3)
                if self.delivered_device_ms is not None
                else None
            ),
            "flaps": self.flaps,
            "quarantines": self.quarantines,
            "probation_enters": self.probation_enters,
            "probation_passes": self.probation_passes,
            "classes": [c.to_obj() for c in self.classes],
            "worst_burn": (
                round(self.worst_burn, 3)
                if self.worst_burn is not None
                else None
            ),
            "budget_blown": self.budget_blown,
            "compile": self.compile,
            **({"controller": self.controller} if self.controller else {}),
            **({"fleet": self.fleet} if self.fleet else {}),
        }

    def summary_line(self) -> str:
        """One machine-parseable line for the run/train CLIs
        (``Health: ...``)."""
        avail = (
            f"{self.availability * 100:.2f}%"
            if self.availability is not None
            else "n/a"
        )
        mttr = f"{self.mttr_ms:.1f}" if self.mttr_ms is not None else "n/a"
        burn = (
            f"{self.worst_burn:.2f}x"
            if self.worst_burn is not None
            else "n/a"
        )
        comp = (
            "unattributed"
            if self.compile.get("unattributed")
            else f"{self.compile.get('total_ms', 0.0):.1f}"
        )
        return (
            f"incidents={len(self.incidents)} mttr_ms={mttr} "
            f"availability={avail} worst_burn={burn} "
            f"compile_ms={comp} budget_blown={self.budget_blown}"
        )

    def render(self) -> str:
        lines = [f"Fleet health: {self.summary_line()}"]
        if self.devices is not None and self.duration_ms > 0:
            lines.append(
                f"  capacity: {self.devices} devices over "
                f"{self.duration_ms / 1e3:.2f}s; flaps={self.flaps} "
                f"quarantines={self.quarantines} "
                f"probation={self.probation_passes}/{self.probation_enters} "
                f"passed"
            )
        if self.incidents:
            lines.append(
                "Incidents (phase decomposition sums to wall time):"
            )
            for inc in self.incidents:
                lines.append(f"  {inc.render()}")
        else:
            lines.append("Incidents: none")
        if self.classes:
            lines.append(
                f"SLO attainment (ranked by error-budget burn; budget = "
                f"{ERROR_BUDGET:.0%} of completed):"
            )
            for c in self.classes:
                lines.append(f"  {c.render()}")
        if self.controller:
            ctl = self.controller
            acts = ",".join(
                f"{k}={v}" for k, v in sorted(ctl["actions"].items())
            )
            lines.append(
                f"Autopilot: {ctl['total']} action(s) "
                f"({acts}); refused={ctl['refused']} "
                f"reversals={ctl['reversals']}"
            )
            if "burn_after" in ctl:
                for name in sorted(
                    set(ctl.get("burn_before") or {})
                    | set(ctl["burn_after"])
                ):
                    b0 = (ctl.get("burn_before") or {}).get(name)
                    b1 = ctl["burn_after"].get(name)
                    fmt = lambda v: f"{v:.2f}x" if v is not None else "n/a"
                    lines.append(
                        f"  burn {name or '(default)'}: "
                        f"{fmt(b0)} before first action -> {fmt(b1)} after"
                    )
        if self.fleet:
            fl = self.fleet
            acts = ",".join(
                f"{k}={v}" for k, v in sorted(fl["actions"].items())
            ) or "none"
            lines.append(
                f"Fleet control: {fl['total']} action(s) ({acts}); "
                f"refusals={fl['refusals']} "
                f"max_degraded={fl['max_simultaneous_degraded']}"
            )
            for d in fl["drains"]:
                parts = " ".join(
                    f"{k}={'unattributed' if v is None else format(v, '.1f')}"
                    for k, v in d["phases"].items()
                )
                lines.append(
                    f"  drain {d['entry']} ({d['cause']}) "
                    f"wall={d['wall_ms']:.1f}ms  {parts}"
                )
        comp = self.compile
        if comp.get("unattributed"):
            lines.append(
                "Compile attribution: unattributed (journal predates "
                "compile_event records — compile time is unknown, not "
                "zero)"
            )
        else:
            lines.append(
                f"Compile attribution: {comp['events']} event(s), "
                f"{comp['total_ms']:.1f} ms compiling "
                f"(XLA-vs-ledger tolerance ±{comp['tolerance']:.0%}):"
            )
            for g in comp["rows"]:
                lines.append(
                    f"  {g['site']:<10s} {g['entry']:<22s} "
                    f"shape={tuple(g['shape'])} {g['dtype']} "
                    f"compiles={g['compiles']} hits={g['cache_hits']} "
                    f"ms={g['ms']:.1f}"
                )
            for c in comp["flops_checks"]:
                if c["verdict"] == "unavailable":
                    lines.append(
                        f"  flops-check {c['entry']} "
                        f"shape={tuple(c['shape'])}: unavailable "
                        f"(no XLA cost analysis on this backend)"
                    )
                else:
                    lines.append(
                        f"  flops-check {c['entry']} "
                        f"shape={tuple(c['shape'])}: xla={c['xla_flops']:.3e} "
                        f"ledger={c['ledger_flops']:.3e} "
                        f"ratio={c['ratio']:.3f} -> {c['verdict']}"
                    )
        return "\n".join(lines)


def health_from_records(records: List[dict]) -> HealthReport:
    """The one folding entry point: any journal's records (serve, train,
    bench, replay) into a :class:`HealthReport`."""
    availability, delivered, devices, duration = availability_from_records(
        records
    )
    return HealthReport(
        incidents=incidents_from_records(records),
        classes=slo_attainment(records),
        availability=availability,
        delivered_device_ms=delivered,
        devices=devices,
        duration_ms=duration,
        # Router backend hysteresis (ISSUE 16) folds into the same
        # counters as the device-level ElasticPool records: a backend
        # process flapping into quarantine and a device flapping out of
        # the mesh are one fleet-health story at two granularities.
        flaps=sum(
            int(r.get("flaps") or 0)
            for r in records
            if r.get("kind") == "mesh_quarantine"
            or (
                r.get("kind") == "router_backend_state"
                and r.get("to") == "quarantined"
            )
        ),
        quarantines=sum(
            1
            for r in records
            if r.get("kind") == "mesh_quarantine"
            or (
                r.get("kind") == "router_backend_state"
                and r.get("to") == "quarantined"
            )
        ),
        probation_enters=sum(
            1
            for r in records
            if (r.get("kind") == "mesh_probation" and r.get("event") == "enter")
            or (
                r.get("kind") == "router_backend_state"
                and r.get("to") == "probation"
            )
        ),
        probation_passes=sum(
            1
            for r in records
            if (r.get("kind") == "mesh_probation" and r.get("event") == "pass")
            or (
                r.get("kind") == "router_backend_state"
                and r.get("to") == "up"
                and r.get("reason") == "readmit"
            )
        ),
        compile=compile_attribution(records),
        n_records=len(records),
        controller=controller_summary(records),
        fleet=fleet_summary(records),
    )


def health_from_journal(path) -> HealthReport:
    """Load a ``.jsonl`` journal (or a directory of them) and fold it."""
    from .export import load_records

    return health_from_records(load_records(path))

"""Roofline attribution: per-stage MFU, an HBM-traffic ledger, and the
predicted fused-block ceiling (docs/OBSERVABILITY.md "Roofline
attribution").

PR 9's ``stages`` module attributes *time* per stage; ``bench.py``
computes *whole-pass* MFU. Neither can answer the question ROADMAP
item 1 actually asks: which stage is compute-bound vs HBM-bound, and
what is a VMEM-resident fused block worth *before anyone writes it*?
This module is the efficiency half of the observability stack:

- **Analytic ledger** (:func:`stage_ledger` / :func:`pass_ledger`):
  per-stage FLOPs (from ``models.alexnet.stage_flops`` — the SAME
  generator ``flops_per_image`` sums, so ledger and headline accounting
  cannot drift) plus HBM bytes read/written under *staged* execution:
  each stage reads its input activation and params and writes its
  output activation, per the dtype policy's byte widths (fp32 4B, bf16
  2B, int8w 1B weights + fp32 per-channel scales over bf16
  activations). Conv stages include their ReLU (the sentinel tap
  boundary — the fused activation never round-trips).
- **Fused byte model** (:func:`fused_blocks`): one VMEM-resident pass
  per block (Conv→ReLU→Pool; +LRN for block 2) reads the block input +
  params and writes the block output only — ``staged − fused`` is
  exactly the intermediates' write+read round-trips. Dividing by the
  device spec's roofs yields a predicted fused time floor and an MFU
  ceiling per block: the judge every ROADMAP-1 megakernel candidate
  answers to before it exists.
- **Measured attribution** (:func:`attribute_roofline`): join the
  ledger with a measured per-stage breakdown (PR 9 ``attribute_stages``
  or a bench row's ``breakdown``) to emit per-stage achieved FLOP/s,
  MFU, achieved GB/s, arithmetic intensity, a compute/memory-bound
  verdict against the device spec's ridge point, and headroom — the ms
  between the measurement and its binding roof. Rows without a measured
  breakdown (the committed pre-PR-9 BENCH trail) fall back to a
  **model split**: ``per_pass_ms`` distributed across stages
  proportionally to each stage's roofline floor, labeled
  ``source="model"`` so nobody mistakes a prediction for a measurement.
- **Bench-row views** (:func:`roofline_from_bench_row`): committed
  ``BENCH_r*.json`` rows reproduce their own MFU from their own fields
  (fresh values, ``last_good`` carries and the ``bf16`` sub-object
  alike) — the BENCH_r05 bf16 0.5713 is recomputed, not trusted.

Device capability comes from :mod:`.specs` — one table for bench and
roofline both. Import-light except for the ledger's ``models`` import
(jax); the CLI lives in ``observability.__main__`` (``roofline``
subcommand).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .specs import hbm_gbps as _spec_hbm, peak_tflops as _spec_peak, spec_for

# Activation / weight byte widths per dtype policy (docs/PRECISION.md):
# int8w stores int8 weights with fp32 per-output-channel scales and runs
# bf16 activations through the dequant-free forward.
_ACT_BYTES = {"fp32": 4, "bf16": 2, "int8w": 2}
_WEIGHT_BYTES = {"fp32": 4, "bf16": 2, "int8w": 1}

# The block structure the megakernel work fuses (ROADMAP item 1):
# one VMEM-resident pass per block.
BLOCKS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("block1", ("conv1", "pool1")),
    ("block2", ("conv2", "pool2", "lrn2")),
)


@dataclasses.dataclass(frozen=True)
class StageCost:
    """One stage's analytic cost for ONE pass at a given batch: FLOPs and
    the staged-execution HBM traffic (activations scale with batch;
    params are read once per pass)."""

    name: str
    flops: int  # all work, batch-scaled
    matmul_flops: int  # MXU work only (MFU numerator), batch-scaled
    act_in_bytes: int
    act_out_bytes: int
    param_bytes: int

    @property
    def staged_bytes(self) -> int:
        """HBM bytes this stage moves when executed staged: read input
        activation + params, write output activation."""
        return self.act_in_bytes + self.param_bytes + self.act_out_bytes

    @property
    def intensity(self) -> float:
        """Arithmetic intensity (FLOP/byte) under staged execution."""
        return self.flops / self.staged_bytes if self.staged_bytes else 0.0


def _dtype_bytes(dtype: str) -> Tuple[int, int]:
    if dtype not in _ACT_BYTES:
        raise ValueError(
            f"roofline ledger supports {sorted(_ACT_BYTES)}, got {dtype!r}"
        )
    return _ACT_BYTES[dtype], _WEIGHT_BYTES[dtype]


def stage_ledger(cfg=None, dtype: str = "fp32") -> List[StageCost]:
    """Per-stage costs for ONE image (batch=1) — see :func:`pass_ledger`
    for the batch-scaled form the attribution joins against."""
    return pass_ledger(cfg, dtype=dtype, batch=1)


def pass_ledger(cfg=None, dtype: str = "fp32", batch: int = 1) -> List[StageCost]:
    """The analytic per-stage ledger for one pass of ``batch`` images.

    FLOPs come from ``models.alexnet.stage_flops`` (the generator the
    whole-pass counters sum — exact agreement by construction); bytes
    from the layer dims under the dtype policy's widths. Params are
    counted once per pass (they are resident reads amortized over the
    batch), activations per image.
    """
    from ..models.alexnet import BLOCKS12, ConvSpec, layer_dims, stage_flops

    cfg = cfg if cfg is not None else BLOCKS12
    act_b, w_b = _dtype_bytes(dtype)
    batch = max(1, int(batch))
    flops_by_stage = {n: (f, mm) for n, f, mm in stage_flops(cfg)}
    out: List[StageCost] = []
    for name, spec, (hi, wi, ci), (h, w, c) in layer_dims(cfg):
        flops, matmul = flops_by_stage[name]
        params = 0
        if isinstance(spec, ConvSpec):
            params = spec.filter_size**2 * ci * c * w_b + c * act_b  # w + bias
            if dtype == "int8w":
                params += c * 4  # fp32 per-output-channel scales
        out.append(
            StageCost(
                name=name,
                flops=flops * batch,
                matmul_flops=matmul * batch,
                act_in_bytes=hi * wi * ci * act_b * batch,
                act_out_bytes=h * w * c * act_b * batch,
                param_bytes=params,
            )
        )
    return out


# ------------------------------------------------------ fused byte model ---


@dataclasses.dataclass(frozen=True)
class BlockModel:
    """Staged-vs-fused prediction for one block at a device spec."""

    name: str
    stages: Tuple[str, ...]
    flops: int
    matmul_flops: int
    staged_bytes: int
    fused_bytes: int  # block input + params + block output only
    staged_floor_ms: float  # sum of per-stage max(compute, memory) floors
    fused_floor_ms: float  # max(compute, memory) over the fused pass
    fused_mfu_ceiling: Optional[float]  # matmul/(peak * fused_floor)

    @property
    def intermediate_bytes(self) -> int:
        """The HBM round-trips fusion deletes: every interior boundary's
        activation written once and read once."""
        return self.staged_bytes - self.fused_bytes

    def to_obj(self) -> dict:
        return {
            "stages": list(self.stages),
            "flops": self.flops,
            "matmul_flops": self.matmul_flops,
            "staged_bytes": self.staged_bytes,
            "fused_bytes": self.fused_bytes,
            "intermediate_bytes": self.intermediate_bytes,
            "staged_floor_ms": round(self.staged_floor_ms, 4),
            "fused_floor_ms": round(self.fused_floor_ms, 4),
            "fused_mfu_ceiling": (
                round(self.fused_mfu_ceiling, 4)
                if self.fused_mfu_ceiling is not None
                else None
            ),
        }


def _floor_ms(flops: int, num_bytes: int, peak_tflops: float, bw_gbps: float) -> float:
    """Roofline time floor: the binding of the compute and memory roofs."""
    compute_s = flops / (peak_tflops * 1e12) if peak_tflops else 0.0
    memory_s = num_bytes / (bw_gbps * 1e9) if bw_gbps else 0.0
    return max(compute_s, memory_s) * 1e3


def fused_blocks(
    entries: List[StageCost], peak_tflops: float, bw_gbps: float
) -> List[BlockModel]:
    """The fused-ceiling prediction per block: what a VMEM-resident
    megakernel is worth before it exists (ROADMAP item 1's judge)."""
    by_name = {e.name: e for e in entries}
    out: List[BlockModel] = []
    for block, names in BLOCKS:
        stages = [by_name[n] for n in names if n in by_name]
        if len(stages) != len(names):
            continue  # non-blocks12 ledger: no block story to tell
        flops = sum(e.flops for e in stages)
        matmul = sum(e.matmul_flops for e in stages)
        staged = sum(e.staged_bytes for e in stages)
        fused = (
            stages[0].act_in_bytes
            + sum(e.param_bytes for e in stages)
            + stages[-1].act_out_bytes
        )
        staged_floor = sum(
            _floor_ms(e.flops, e.staged_bytes, peak_tflops, bw_gbps)
            for e in stages
        )
        fused_floor = _floor_ms(flops, fused, peak_tflops, bw_gbps)
        ceiling = (
            matmul / (peak_tflops * 1e12 * fused_floor / 1e3)
            if peak_tflops and fused_floor > 0
            else None
        )
        out.append(
            BlockModel(
                name=block,
                stages=tuple(names),
                flops=flops,
                matmul_flops=matmul,
                staged_bytes=staged,
                fused_bytes=fused,
                staged_floor_ms=staged_floor,
                fused_floor_ms=fused_floor,
                fused_mfu_ceiling=ceiling,
            )
        )
    return out


# -------------------------------------------------- measured attribution ---


@dataclasses.dataclass(frozen=True)
class StageRoofline:
    """One stage's measured-vs-roof verdict."""

    name: str
    ms: float
    share: float  # of the pass total
    flops: int
    matmul_flops: int
    bytes: int
    intensity: float  # FLOP/byte, staged
    achieved_tflops: float
    achieved_gbps: float
    mfu: Optional[float]
    bound: str  # "compute" | "memory"
    floor_ms: float  # the binding roof's time floor
    headroom_ms: float  # ms - floor_ms: reclaimable time at this roof
    headroom_x: Optional[float]  # ms / floor_ms
    # Block-granularity rows only: the fused_mfu_ceiling this block's
    # measured MFU is judged against (the acceptance comparison ISSUE 17
    # names). None on per-stage rows — a staged stage has no fused ceiling
    # of its own.
    mfu_ceiling: Optional[float] = None

    def to_obj(self) -> dict:
        obj = {
            "name": self.name,
            "ms": round(self.ms, 4),
            "share": round(self.share, 4),
            "flops": self.flops,
            "matmul_flops": self.matmul_flops,
            "bytes": self.bytes,
            "intensity": round(self.intensity, 2),
            "achieved_tflops": round(self.achieved_tflops, 4),
            "achieved_gbps": round(self.achieved_gbps, 2),
            "mfu": round(self.mfu, 4) if self.mfu is not None else None,
            "bound": self.bound,
            "floor_ms": round(self.floor_ms, 4),
            "headroom_ms": round(self.headroom_ms, 4),
            "headroom_x": (
                round(self.headroom_x, 2) if self.headroom_x is not None else None
            ),
        }
        if self.mfu_ceiling is not None:
            obj["mfu_ceiling"] = round(self.mfu_ceiling, 4)
        return obj


@dataclasses.dataclass
class RooflineReport:
    """The full attribution: ranked stages, block predictions, pass MFU."""

    dtype: str
    batch: int
    device: str  # spec name the verdicts are judged against
    device_kind: str  # what jax reported (or the row carried)
    spec_assumed: bool  # True = no spec matched; v5e default stands in
    peak_tflops: float
    hbm_gbps: float
    ridge_intensity: float  # FLOP/byte where the roofs cross
    source: str  # "breakdown" (measured stage ms) | "model" (split)
    total_ms: float
    pass_mfu: Optional[float]
    stages: List[StageRoofline]  # ranked: biggest headroom_ms first
    blocks: List[BlockModel]
    fused_pass_mfu_ceiling: Optional[float] = None
    label: str = ""  # row context ("bf16@b128", "last_good ...")
    stale: bool = False  # a last_good carry, not a fresh measurement
    granularity: str = "stage"  # "stage" | "block" (megakernel rows)

    def to_obj(self) -> dict:
        return {
            "dtype": self.dtype,
            "granularity": self.granularity,
            "batch": self.batch,
            "device": self.device,
            "device_kind": self.device_kind,
            "spec_assumed": self.spec_assumed,
            "peak_tflops": self.peak_tflops,
            "hbm_gbps": self.hbm_gbps,
            "ridge_intensity": round(self.ridge_intensity, 2),
            "source": self.source,
            "total_ms": round(self.total_ms, 4),
            "pass_mfu": (
                round(self.pass_mfu, 4) if self.pass_mfu is not None else None
            ),
            "fused_pass_mfu_ceiling": (
                round(self.fused_pass_mfu_ceiling, 4)
                if self.fused_pass_mfu_ceiling is not None
                else None
            ),
            "stale": self.stale,
            "label": self.label or None,
            "stages": [s.to_obj() for s in self.stages],
            "blocks": {b.name: b.to_obj() for b in self.blocks},
        }

    def render(self) -> str:
        """The ranked stage table (the CLI's text face)."""
        hdr = (
            f"roofline [{self.dtype} b={self.batch} {self.device}"
            f"{' (assumed spec)' if self.spec_assumed else ''}"
            f" peak={self.peak_tflops:g}TF/s hbm={self.hbm_gbps:g}GB/s"
            f" ridge_ai={self.ridge_intensity:.0f}]"
        )
        if self.label:
            hdr += f" {self.label}"
        lines = [hdr]
        mfu = f"{self.pass_mfu:.4f}" if self.pass_mfu is not None else "n/a"
        lines.append(
            f"  pass: {self.total_ms:.4f} ms mfu={mfu} source={self.source}"
            f"{' granularity=block' if self.granularity == 'block' else ''}"
            f"{' STALE (last_good carry)' if self.stale else ''}"
        )
        lines.append(
            "  rank stage    ms      share  AI      TF/s    GB/s    mfu"
            "     bound    floor_ms headroom_ms"
        )
        for i, s in enumerate(self.stages, 1):
            smfu = f"{s.mfu:.3f}" if s.mfu is not None else "  n/a"
            line = (
                f"  {i:<4d} {s.name:<8s} {s.ms:<7.4f} {s.share:<6.2f} "
                f"{s.intensity:<7.1f} {s.achieved_tflops:<7.2f} "
                f"{s.achieved_gbps:<7.1f} {smfu:<7s} {s.bound:<8s} "
                f"{s.floor_ms:<8.4f} {s.headroom_ms:.4f}"
            )
            if s.mfu_ceiling is not None:
                line += f" mfu_ceiling<={s.mfu_ceiling:.3f}"
            lines.append(line)
        for b in self.blocks:
            ceil = (
                f"{b.fused_mfu_ceiling:.3f}"
                if b.fused_mfu_ceiling is not None
                else "n/a"
            )
            lines.append(
                f"  fused {b.name} ({'+'.join(b.stages)}): floor "
                f"{b.fused_floor_ms:.4f} ms (staged floor "
                f"{b.staged_floor_ms:.4f} ms, deletes "
                f"{b.intermediate_bytes} intermediate bytes) "
                f"mfu_ceiling<={ceil}"
            )
        if self.fused_pass_mfu_ceiling is not None:
            lines.append(
                f"  fused pass mfu ceiling <= {self.fused_pass_mfu_ceiling:.4f}"
            )
        return "\n".join(lines)


def model_stage_split(
    total_ms: float, entries: List[StageCost], peak_tflops: float, bw_gbps: float
) -> Dict[str, float]:
    """Distribute a measured whole-pass time across stages proportionally
    to each stage's roofline floor — the model-backed attribution for
    rows that predate the PR 9 breakdown. Sums exactly to ``total_ms``."""
    floors = {
        e.name: _floor_ms(e.flops, e.staged_bytes, peak_tflops, bw_gbps)
        for e in entries
    }
    floor_sum = sum(floors.values())
    if floor_sum <= 0:
        even = total_ms / max(1, len(entries))
        return {e.name: even for e in entries}
    return {n: total_ms * f / floor_sum for n, f in floors.items()}


def attribute_roofline(
    stages_ms: Dict[str, float],
    *,
    dtype: str,
    batch: int,
    device_kind: str = "",
    cfg=None,
    source: str = "breakdown",
    total_ms: Optional[float] = None,
    peak_override: Optional[float] = None,
    hbm_override: Optional[float] = None,
    pass_img_s: Optional[float] = None,
    label: str = "",
    stale: bool = False,
) -> RooflineReport:
    """Join measured (or model-split) per-stage ms with the analytic
    ledger and the device spec into the ranked verdict table.

    ``peak_override`` lets a bench row's own ``assumed_peak_tflops``
    govern (the row must reproduce its committed MFU from its own
    fields); otherwise the spec table (+ env overrides) decides.
    ``pass_img_s`` computes the whole-pass MFU the conventional way
    (img/s x matmul FLOPs per image / peak) — exactly bench's formula.
    """
    spec, assumed = spec_for(device_kind)
    peak = (
        float(peak_override)
        if peak_override
        else _spec_peak(device_kind, dtype=dtype)
    )
    bw = float(hbm_override) if hbm_override else _spec_hbm(device_kind)
    entries = pass_ledger(cfg, dtype=dtype, batch=batch)
    by_name = {e.name: e for e in entries}
    ridge = (peak * 1e12) / (bw * 1e9) if bw else 0.0
    blocks = fused_blocks(entries, peak, bw)
    by_block = {b.name: b for b in blocks}
    known = {n: float(ms) for n, ms in stages_ms.items() if n in by_name}
    granularity = "stage"
    if not known:
        # A fuse="block" breakdown speaks block vocabulary (block1/block2)
        # — join it against the fused-ceiling BlockModels instead of faking
        # per-stage rows the megakernel never measured. Bytes/floor come
        # from the FUSED cost model, so the verdict judges the megakernel
        # against the ceiling it was built to approach.
        known = {n: float(ms) for n, ms in stages_ms.items() if n in by_block}
        if not known:
            raise ValueError(
                f"no ledger stage or fused block matches the breakdown "
                f"stages {sorted(stages_ms)!r} (ledger: {sorted(by_name)!r},"
                f" blocks: {sorted(by_block)!r})"
            )
        granularity = "block"
    total = float(total_ms) if total_ms else sum(known.values())
    rows: List[StageRoofline] = []
    for name, ms in known.items():
        if granularity == "block":
            b = by_block[name]
            flops, matmul = b.flops, b.matmul_flops
            nbytes, floor = b.fused_bytes, b.fused_floor_ms
            intensity = flops / nbytes if nbytes else 0.0
            ceiling = b.fused_mfu_ceiling
        else:
            e = by_name[name]
            flops, matmul = e.flops, e.matmul_flops
            nbytes, intensity = e.staged_bytes, e.intensity
            floor = _floor_ms(e.flops, e.staged_bytes, peak, bw)
            ceiling = None
        secs = ms / 1e3
        achieved_f = flops / secs / 1e12 if ms > 0 else 0.0
        achieved_b = nbytes / secs / 1e9 if ms > 0 else 0.0
        # A clamped-to-zero stage (noise-negative prefix diff) still gets
        # a 0.0 MFU when the peak is known: "measured nothing" and
        # "utilized nothing" render the same, and None stays reserved for
        # "no peak to judge against".
        if peak:
            mfu: Optional[float] = (
                matmul / (secs * peak * 1e12) if ms > 0 else 0.0
            )
        else:
            mfu = None
        rows.append(
            StageRoofline(
                name=name,
                ms=ms,
                share=ms / total if total > 0 else 0.0,
                flops=flops,
                matmul_flops=matmul,
                bytes=nbytes,
                intensity=intensity,
                achieved_tflops=achieved_f,
                achieved_gbps=achieved_b,
                mfu=mfu,
                bound="compute" if intensity >= ridge else "memory",
                floor_ms=floor,
                headroom_ms=ms - floor,
                headroom_x=ms / floor if floor > 0 else None,
                mfu_ceiling=ceiling,
            )
        )
    # Ranked by headroom: the ms the binding roof says are reclaimable —
    # the optimization target list, biggest opportunity first.
    rows.sort(key=lambda s: s.headroom_ms, reverse=True)
    matmul_total = sum(e.matmul_flops for e in entries)
    if pass_img_s and peak:
        per_image_matmul = matmul_total / max(1, batch)
        pass_mfu: Optional[float] = pass_img_s * per_image_matmul / (peak * 1e12)
    elif total > 0 and peak:
        pass_mfu = matmul_total / (total / 1e3 * peak * 1e12)
    else:
        pass_mfu = None
    fused_total_floor = sum(b.fused_floor_ms for b in blocks)
    fused_pass_ceiling = (
        matmul_total / (fused_total_floor / 1e3 * peak * 1e12)
        if blocks and fused_total_floor > 0 and peak
        else None
    )
    return RooflineReport(
        dtype=dtype,
        batch=batch,
        device=spec.name,
        device_kind=device_kind or "",
        spec_assumed=assumed,
        peak_tflops=peak,
        hbm_gbps=bw,
        ridge_intensity=ridge,
        source=source,
        total_ms=total,
        pass_mfu=pass_mfu,
        stages=rows,
        blocks=blocks,
        fused_pass_mfu_ceiling=fused_pass_ceiling,
        label=label,
        stale=stale,
        granularity=granularity,
    )


# ---------------------------------------------------------- bench rows ---


def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) and v > 0 else None


def _view(src: dict, carrier: dict, obj: dict, stale: bool) -> Optional[dict]:
    """One dtype view of a bench row: the fields roofline needs, pulled
    from the sub-object first and its carrier row second (the ``bf16``
    sub-object inherits batch/peak/device from its parent)."""
    img_s = _num(src.get("value")) or _num(src.get("stale_value"))
    if img_s is None:
        return None
    def pick(key):
        for d in (src, carrier, obj):
            v = d.get(key)
            if v is not None:
                return v
        return None

    dtype = src.get("dtype") or src.get("compute") or pick("compute") or "fp32"
    batch = pick("batch") or 1
    per_pass = _num(src.get("per_pass_ms")) or (batch / img_s * 1e3)
    bd = src.get("breakdown") if isinstance(src.get("breakdown"), dict) else None
    if bd is None and src is carrier and isinstance(obj.get("breakdown"), dict):
        bd = obj["breakdown"]
    return {
        "label": f"{dtype}@b{int(batch)}" + (" last_good" if stale else ""),
        "dtype": str(dtype),
        "img_s": img_s,
        "batch": int(batch),
        "per_pass_ms": per_pass,
        "peak": _num(pick("assumed_peak_tflops")),
        "device_kind": str(pick("device_kind") or ""),
        "breakdown": bd,
        "stale": stale,
    }


def row_views(obj: dict) -> List[dict]:
    """The measurable dtype views a bench row carries: the fresh primary
    (plus its ``bf16`` sub-object), or the ``last_good`` carry (plus ITS
    ``bf16``) when the round measured nothing — stale views say so."""
    views: List[dict] = []

    def add(src, carrier, stale):
        v = _view(src, carrier, obj, stale)
        if v is not None:
            views.append(v)

    if _num(obj.get("value")):
        add(obj, obj, False)
        if isinstance(obj.get("bf16"), dict):
            add(obj["bf16"], obj, False)
    else:
        lg = obj.get("last_good")
        if isinstance(lg, dict):
            add(lg, lg, True)
            if isinstance(lg.get("bf16"), dict):
                add(lg["bf16"], lg, True)
    return views


def roofline_from_bench_row(obj: dict, cfg=None) -> List[RooflineReport]:
    """Every dtype view of one bench row, attributed. Views with a
    measured ``breakdown`` join it (``source="breakdown"``); views
    without one model-split their ``per_pass_ms`` (``source="model"``).
    The view's own ``assumed_peak_tflops`` governs, so a committed row
    reproduces its committed MFU from its own fields."""
    reports: List[RooflineReport] = []
    for v in row_views(obj):
        bd = v["breakdown"]
        stages = bd.get("stages") if isinstance(bd, dict) else None
        if isinstance(stages, dict) and stages:
            stages_ms = {n: float(ms) for n, ms in stages.items()}
            source = "breakdown"
            total = _num(bd.get("total_ms")) or sum(stages_ms.values())
        else:
            entries = pass_ledger(cfg, dtype=v["dtype"], batch=v["batch"])
            peak = v["peak"] or _spec_peak(v["device_kind"], dtype=v["dtype"])
            stages_ms = model_stage_split(
                v["per_pass_ms"], entries, peak, _spec_hbm(v["device_kind"])
            )
            source = "model"
            total = v["per_pass_ms"]
        reports.append(
            attribute_roofline(
                stages_ms,
                dtype=v["dtype"],
                batch=v["batch"],
                device_kind=v["device_kind"],
                cfg=cfg,
                source=source,
                total_ms=total,
                peak_override=v["peak"],
                pass_img_s=v["img_s"],
                label=v["label"],
                stale=v["stale"],
            )
        )
    return reports

"""Span tracing: trace/span/parent ids over the crash-consistent journal.

The repo's six journal schemas (``sup_*``, ``serve_*``, ``gate_*``,
``mesh_shrink``, watchdog, bench rows) each record *that* something
happened; none of them records *where the time went* or how one record
relates to another. This module adds the correlation layer:

- :class:`Tracer` owns one ``trace_id`` per run, mints span ids, and
  persists every span as a ``kind="span"`` record in a PR 3
  :class:`~..resilience.journal.Journal` — the same fsync'd append-only
  trail every other artifact uses, so a killed run's trace covers exactly
  the spans that completed.
- :func:`Tracer.span` is a context manager (``with tracer.span(name,
  **attrs):``) stacking parent ids per thread; :meth:`Tracer.emit`
  records an explicitly-timed span after the fact — the serving dispatch
  loop measures its timed region first and emits the span from its
  ``@off_timed_path`` completion helper, so tracing adds zero host work
  to the hot loop (staticcheck's ``span-write-in-timed-region`` rule
  enforces exactly this discipline).
- :func:`set_tracer` installs a process-wide tracer; :func:`span` /
  :func:`current_ids` are the no-op-when-untraced module-level surface
  the wired subsystems (server, supervisor, autotuner, train loop) call —
  an untraced run pays one ``None`` check per site.
- Every journal-writing call site that merges :func:`current_ids` into
  its payload gains *optional* ``trace_id``/``span_id`` fields; old
  tooling keys on ``kind``/``key`` and never sees them.

Timestamps are ``time.monotonic`` readings relative to the tracer's
epoch (``t0_ms``/``dur_ms``), so spans from one process stitch into one
timeline regardless of wall-clock steps; the exporter
(``observability.export``) converts them to Chrome trace-event
microseconds.

Stdlib + ``resilience.journal`` only (no jax/numpy import) — the same
import-weight rule as the journal itself, so the harness/bench layers
pay nothing to trace.
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from typing import Dict, Iterator, List, Optional

from ..resilience.journal import Journal


def off_timed_path(fn):
    """Same contract (and decorator NAME — what staticcheck matches) as
    ``resilience.sentinel.off_timed_path``: this function is never called
    inside a timed region. Declared locally so this module stays free of
    the sentinel's jax import."""
    fn.__off_timed_path__ = True
    return fn


def _new_hex(rng: random.Random, n: int) -> str:
    return "".join(rng.choice("0123456789abcdef") for _ in range(n))


class Span:
    """Handle yielded by :meth:`Tracer.span`; ``set(**attrs)`` attaches
    result attributes before the span closes (a timed tuning candidate
    records its measured ms on the span that timed it)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "attrs")

    def __init__(self, trace_id: str, span_id: str, parent_id: str, name: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.attrs: Dict = {}

    def set(self, **attrs) -> "Span":
        self.attrs.update(attrs)
        return self


class Tracer:
    """One run's trace: a ``trace_id``, a per-thread span stack, and a
    journal the spans persist to. Thread-safe — the serving dispatch
    thread and the submitting thread share one tracer, each with its own
    parent stack and a stable small ``tid`` for the exporter."""

    def __init__(
        self,
        journal: Optional[Journal] = None,
        trace_id: Optional[str] = None,
        seed: Optional[int] = None,
    ):
        self._rng = random.Random(
            seed if seed is not None else int.from_bytes(os.urandom(8), "big")
        )
        self.journal = journal
        self.trace_id = trace_id or _new_hex(self._rng, 16)
        self.clock = time.monotonic
        self._epoch = self.clock()
        self._lock = threading.Lock()
        self._local = threading.local()
        self._tids: Dict[int, int] = {}
        self.spans: List[dict] = []  # in-memory mirror (tests, no-journal use)

    # ------------------------------------------------------------- plumbing

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            if ident not in self._tids:
                self._tids[ident] = len(self._tids)
            return self._tids[ident]

    def new_id(self) -> str:
        with self._lock:
            return _new_hex(self._rng, 8)

    def rel_ms(self, t_s: float) -> float:
        """A ``time.monotonic`` reading as ms since the tracer epoch."""
        return (t_s - self._epoch) * 1e3

    @off_timed_path
    def _persist(self, rec: dict) -> None:
        """Journal one completed span — fsync'd, strictly between timed
        regions (the span body already ended when this runs)."""
        self.spans.append(rec)
        if self.journal is not None:
            self.journal.append("span", key=f"span:{rec['span_id']}", **rec)

    # -------------------------------------------------------------- surface

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Span]:
        """Record the enclosed block as one span. Exceptions are recorded
        as an ``error`` attribute and re-raised — a trace of a failed run
        shows WHERE it failed."""
        stack = self._stack()
        sp = Span(self.trace_id, self.new_id(), stack[-1] if stack else "", name)
        sp.attrs.update(attrs)
        stack.append(sp.span_id)
        t0 = self.clock()
        try:
            yield sp
        except BaseException as e:
            sp.attrs["error"] = f"{type(e).__name__}: {e}"[:200]
            raise
        finally:
            t1 = self.clock()
            stack.pop()
            self._persist(
                {
                    "name": name,
                    "trace_id": self.trace_id,
                    "span_id": sp.span_id,
                    "parent_id": sp.parent_id,
                    "tid": self._tid(),
                    "t0_ms": round(self.rel_ms(t0), 3),
                    "dur_ms": round((t1 - t0) * 1e3, 3),
                    **({"attrs": sp.attrs} if sp.attrs else {}),
                }
            )

    @off_timed_path
    def emit(
        self,
        name: str,
        t0_s: float,
        t1_s: float,
        parent_id: Optional[str] = None,
        track: str = "",
        **attrs,
    ) -> str:
        """Record an explicitly-timed span after the fact (both bounds are
        ``time.monotonic`` readings). This is how the serving layer traces
        its timed dispatch region: measure first, emit from the
        ``@off_timed_path`` completion helper. ``track`` labels an export
        lane (e.g. queue-wait vs dispatch). Returns the span id so journal
        records can carry it."""
        stack = self._stack()
        sid = self.new_id()
        rec = {
            "name": name,
            "trace_id": self.trace_id,
            "span_id": sid,
            "parent_id": (
                parent_id if parent_id is not None else (stack[-1] if stack else "")
            ),
            "tid": self._tid(),
            "t0_ms": round(self.rel_ms(t0_s), 3),
            "dur_ms": round(max(0.0, t1_s - t0_s) * 1e3, 3),
        }
        if track:
            rec["track"] = track
        if attrs:
            rec["attrs"] = attrs
        self._persist(rec)
        return sid

    def current_span_id(self) -> str:
        stack = self._stack()
        return stack[-1] if stack else ""


# ---------------------------------------------------------------- module API

_TRACER: Optional[Tracer] = None


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install the process-wide tracer (None uninstalls); returns the
    previous one so tests can restore it."""
    global _TRACER
    prev, _TRACER = _TRACER, tracer
    return prev


def get_tracer() -> Optional[Tracer]:
    return _TRACER


@contextlib.contextmanager
def span(name: str, **attrs) -> Iterator[Optional[Span]]:
    """``with span("sup.trip", kind=...):`` — records on the installed
    tracer, or does nothing (yields None) when tracing is off. The wired
    subsystems call THIS, so an untraced run pays one None check."""
    t = _TRACER
    if t is None:
        yield None
        return
    with t.span(name, **attrs) as sp:
        yield sp


def current_ids() -> Dict[str, str]:
    """``{"trace_id": ..., "span_id": ...}`` of the innermost open span on
    this thread ({} when untraced; no ``span_id`` key outside any span).
    Journal call sites merge this into payloads so existing record schemas
    gain correlation without changing shape for old tooling."""
    t = _TRACER
    if t is None:
        return {}
    ids = {"trace_id": t.trace_id}
    sid = t.current_span_id()
    if sid:
        ids["span_id"] = sid
    return ids

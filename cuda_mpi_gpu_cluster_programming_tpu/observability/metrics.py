"""Process-wide metrics registry: counters, gauges, histograms.

Every subsystem so far has grown its own ad-hoc counters
(``ServeStats``, supervisor ``attempts``/``replays``, bench retry
``FaultLog``); this registry is the one place a process accumulates
named metrics so the bench row and the CLI summary lines read from a
single source:

- :class:`Counter` (monotonic ``inc``), :class:`Gauge` (last ``set``
  wins), :class:`Histogram` (``observe`` + nearest-rank p50/p99 via the
  serving helper — the SAME estimator the serve bench reports, so a
  metrics percentile and a bench percentile of the same stream agree).
- :meth:`MetricsRegistry.summary` is the compact dict the bench serve
  row embeds; :meth:`MetricsRegistry.export` writes one JSON line per
  metric through the PR 3 atomic-write helper (readers see the old
  complete export or the new one, never a torn file).

Registration and observation are thread-safe (the serving dispatch
thread observes while the load thread submits). Import-light: stdlib +
``resilience.journal``; the nearest-rank helper is imported lazily at
percentile time (``serving.loadgen`` pulls numpy).

Observing inside a timed region is the same contract violation as a
journal write there — staticcheck's ``span-write-in-timed-region`` rule
flags ``.observe(``/``.inc(`` in timed loops unless the enclosing
function is ``@off_timed_path``.
"""

from __future__ import annotations

import json
import re
import threading
from typing import Dict, List, Optional

from ..resilience.journal import atomic_write_text

# Prometheus metric-name grammar: [a-zA-Z_:][a-zA-Z0-9_:]* — the dotted
# registry names ("serve.ok") sanitize to underscores ("serve_ok").
_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    pname = _PROM_BAD.sub("_", name)
    return pname if not pname[:1].isdigit() else f"_{pname}"


def _nearest_rank(xs: List[float], q: float) -> Optional[float]:
    # The serving estimator (serving.loadgen.percentile): nearest-rank, so
    # small samples report an observed value, never an interpolated one.
    # Lazy import — loadgen pulls numpy + the server module.
    from ..serving.loadgen import percentile

    return percentile(xs, q)


class Counter:
    """Monotonic event count."""

    def __init__(self, name: str):
        self.name = name
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self.value += n

    def to_obj(self) -> dict:
        return {"name": self.name, "type": "counter", "value": self.value}


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def to_obj(self) -> dict:
        return {"name": self.name, "type": "gauge", "value": self.value}


class Histogram:
    """Value stream with nearest-rank percentiles. Keeps up to ``cap``
    observations (newest win — a bounded reservoir so a week-long serve
    process cannot grow without bound); count/sum stay exact."""

    def __init__(self, name: str, cap: int = 65536):
        self.name = name
        self.cap = cap
        self.count = 0
        self.sum = 0.0
        self._values: List[float] = []
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self._values.append(v)
            if len(self._values) > self.cap:
                del self._values[: len(self._values) - self.cap]

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            vals = list(self._values)
        return _nearest_rank(vals, q)

    def to_obj(self) -> dict:
        p50, p99 = self.percentile(50), self.percentile(99)
        return {
            "name": self.name,
            "type": "histogram",
            "count": self.count,
            "sum": round(self.sum, 4),
            "mean": round(self.sum / self.count, 4) if self.count else None,
            "p50": round(p50, 4) if p50 is not None else None,
            "p99": round(p99, 4) if p99 is not None else None,
        }


class MetricsRegistry:
    """Named metrics, one instance per process (module-level
    :func:`registry`); ``counter``/``gauge``/``histogram`` create on first
    use and return the existing instrument after — a name can hold exactly
    one instrument type (mixing is a bug worth failing loudly on)."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kw)
            elif not isinstance(m, cls):
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}"
                )
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, cap: int = 65536) -> Histogram:
        return self._get(name, Histogram, cap=cap)

    def snapshot(self) -> Dict[str, dict]:
        """{name: instrument.to_obj()} for every registered metric."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: m.to_obj() for name, m in sorted(items)}

    def summary(self) -> Dict[str, object]:
        """The compact form the bench row embeds: counters/gauges as bare
        values, histograms as {count, mean, p50, p99}."""
        out: Dict[str, object] = {}
        for name, obj in self.snapshot().items():
            if obj["type"] == "histogram":
                out[name] = {
                    k: obj[k] for k in ("count", "mean", "p50", "p99")
                }
            else:
                out[name] = obj["value"]
        return out

    def export(self, path) -> None:
        """Atomic JSONL export: one JSON object per metric (tmp-write,
        fsync, rename — the journal module's artifact contract)."""
        lines = [json.dumps(obj) for _name, obj in sorted(self.snapshot().items())]
        atomic_write_text(path, "\n".join(lines) + ("\n" if lines else ""))

    def prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of every registered
        metric — what the serving front end's ``GET /metrics`` serves so
        the stack is scrapeable (docs/SERVING.md). Counters/gauges map
        directly; histograms expose as summaries (p50/p99 quantile
        samples plus ``_sum``/``_count`` — the same nearest-rank
        percentiles every other surface reports). Metric names sanitize
        ``.`` to ``_`` per the exposition grammar."""
        lines: List[str] = []
        for name, obj in self.snapshot().items():
            pname = _prom_name(name)
            if obj["type"] == "counter":
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname} {obj['value']}")
            elif obj["type"] == "gauge":
                lines.append(f"# TYPE {pname} gauge")
                v = obj["value"]
                lines.append(f"{pname} {v if v is not None else 'NaN'}")
            else:  # histogram -> summary
                lines.append(f"# TYPE {pname} summary")
                for q, key in (("0.5", "p50"), ("0.99", "p99")):
                    if obj.get(key) is not None:
                        lines.append(f'{pname}{{quantile="{q}"}} {obj[key]}')
                lines.append(f"{pname}_sum {obj['sum']}")
                lines.append(f"{pname}_count {obj['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every wired subsystem records into."""
    return _REGISTRY

"""Stitch spans + journal records into one Chrome trace-event timeline.

``python -m cuda_mpi_gpu_cluster_programming_tpu.observability export
--journal <dir|file.jsonl>`` reads every journal in sight and writes a
Perfetto-loadable JSON object (the Chrome trace-event format:
``{"traceEvents": [...]}``, ``ts``/``dur`` in microseconds) so a
``run --serve`` session, a supervised training run, or a tuning sweep
opens in https://ui.perfetto.dev as one correlated timeline:

- ``kind="span"`` records (``observability.trace``) become complete
  ("X") events; nesting comes from their shared monotonic clock, and a
  greedy lane assigner splits genuinely-overlapping spans (concurrent
  queue waits) onto separate tids so Perfetto never renders a
  mis-nested slice.
- journal records carrying a ``span_id`` (the correlation fields the
  wired call sites merge in) become instant events pinned to their
  span's lane at the span's end — the ``serve_batch`` row sits ON its
  dispatch span.
- uncorrelated records (old journals, other processes) land on a
  synthetic per-kind timeline ordered by append index; records with a
  duration field (``batch_ms``/``ms``) still render as slices, so even
  a pre-observability journal produces a readable trace.

Process rows group by subsystem (span-name prefix / record kind):
serving, supervisor, tuning, train, journal. ``M`` metadata events name
every pid/tid.

Also here: :func:`bench_report`, the text face of the cross-run
``BENCH_r*.json`` regression gate (the structured verdict, echo
exclusion, and the nonzero-exit CI wiring live in
:mod:`..observability.gate`).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Tuple

from ..resilience.journal import Journal, atomic_write_text

# Subsystem -> pid. Span names are namespaced "<subsystem>.<what>"; journal
# record kinds map via _KIND_PID below.
_PIDS = {
    "run": 1,
    "serve": 2,
    "sup": 3,
    "tune": 4,
    "train": 5,
    "stages": 6,
    "journal": 7,
    # Folded incidents (ISSUE 15, observability.health): each trip /
    # grow-back renders as a parent slice whose per-phase children tile
    # it end to end — the MTTR decomposition drawn to scale.
    "incident": 8,
    # Fleet router tier (ISSUE 16, serving.router): route verdicts,
    # journaled redirects, and backend state transitions on their own
    # lane — stitched beside each backend's serve lane when the shared
    # journal DIRECTORY is exported.
    "router": 9,
    # Autopilot lane (ISSUE 18, serving.controller): every closed-loop
    # action/reversal/refusal renders as its own slice with the full
    # triggering evidence as args — the timeline shows WHY the serve
    # lane's behavior changed mid-run.
    "controller": 10,
    # Fleet control plane (ISSUE 20, serving.fleet_controller): the
    # router's per-probe scrapes (rung/burn/depth per backend) plus
    # every cross-backend arbitration — token grants/refusals, drains,
    # readmits, forecast pre-shedding — on one lane, so the timeline
    # shows WHY a backend stopped receiving traffic before it ever
    # went unhealthy.
    "fleet": 11,
}
_KIND_PID = {
    "serve_batch": "serve", "serve_shed": "serve", "serve_fail": "serve",
    "serve_miss": "serve", "serve_warm": "serve", "serve_rewarm": "serve",
    # Live resource telemetry (ISSUE 13, docs/OBSERVABILITY.md "Roofline
    # attribution"): periodic queue-saturation gauges and device-memory
    # snapshots render as Perfetto COUNTER tracks (ph "C") on the serve
    # lane — see _COUNTER_KINDS. Old journals without them export
    # unchanged.
    "serve_gauges": "serve", "mem_snapshot": "serve",
    # Network front end records (ISSUE 11, docs/SERVING.md "Network front
    # end & SLOs") land on the serve lane: one serve_transport per HTTP
    # exchange (span-correlated when traced — it pins ONTO its
    # serve.transport span), one serve_reject per 429/413 refusal. Old
    # journals without them export unchanged.
    "serve_transport": "serve", "serve_reject": "serve",
    # Replay-schema records (ISSUE 12, docs/OBSERVABILITY.md "Replay &
    # regression gating"): the run-conditions header and the per-request
    # arrival records land on the serve lane as instants, so an exported
    # timeline shows the offered schedule beside its dispatches. Old
    # journals without them export unchanged.
    "serve_config": "serve", "serve_submit": "serve",
    "sup_build": "sup", "sup_trip": "sup", "sup_degrade": "sup",
    "sup_ok": "sup", "sup_warm": "sup", "sup_reshard": "sup",
    "sup_replay": "sup", "sup_step": "sup", "mesh_shrink": "sup",
    # Grow-back records (ISSUE 10, docs/RESILIENCE.md "Grow-back &
    # hysteresis") land on the same incident lane as the trip/degrade
    # family, so one timeline reads trip -> degrade -> heal -> probation ->
    # promote end to end. Old journals without them export unchanged.
    "mesh_probation": "sup", "mesh_quarantine": "sup",
    "sup_promote": "sup", "sup_promote_refused": "sup",
    # Compile-cost records (ISSUE 15, observability.health): every XLA
    # compile renders as a slice on the supervisor lane (correlated ones
    # pin inside the warmup/rewarm span that paid for them, on a
    # "compile" sub-lane). Old journals without them export unchanged.
    "compile_event": "sup",
    # Fleet router records (ISSUE 16, docs/SERVING.md "Fleet router"):
    # one router_route per northbound request (its ms renders as a
    # slice), instants for redirects/backend state transitions, and the
    # config header. Old journals without them export unchanged.
    "router_config": "router", "router_route": "router",
    "router_redirect": "router", "router_backend_state": "router",
    # Autopilot records (ISSUE 18, docs/SERVING.md "Autopilot"): one
    # controller_action per ladder transition (escalation, reversal, or
    # journaled refusal), its actuation wall ms as the slice duration
    # and its evidence (signals + thresholds + hysteresis state) riding
    # as args. Old journals without them export unchanged.
    "controller_action": "controller",
    # Fleet control records (ISSUE 20, docs/SERVING.md "Fleet control
    # plane"): one fleet_action per arbitration (its actuation ms as
    # the slice), fleet_refusal/router_probe as instants with the full
    # fleet evidence as args. Old journals without them export
    # unchanged (the lane's process meta only emits when it has
    # events).
    "fleet_action": "fleet", "fleet_refusal": "fleet",
    "router_probe": "fleet",
    "gate_pass": "tune", "gate_fail": "tune",
    "step": "train", "ckpt": "train", "rollback": "train", "resume": "train",
    "wedge_detected": "journal", "recycle": "journal", "reprobe": "journal",
}
# Duration field per record kind for uncorrelated records that still carry
# a measured wall time — they render as slices, not instants.
_KIND_DUR_FIELD = {
    "serve_batch": "batch_ms",
    "serve_warm": "ms",
    "serve_rewarm": "ms",
    # An uncorrelated serve_transport (untraced run) still renders as a
    # slice — its ms is the whole HTTP exchange.
    "serve_transport": "ms",
    "sup_warm": "ms",
    # A committed promotion carries its wall ms (spot-check + reshard +
    # re-warm); a probation "pass" record carries the ms the device waited
    # — both render as slices on the incident lane.
    "sup_promote": "ms",
    "mesh_probation": "ms",
    "compile_event": "ms",
    # A routed request's full router-side wall (receive -> response).
    "router_route": "ms",
    # A controller action's actuation wall (screen + rebuild + re-warm
    # for the dtype rung; near-zero for a policy swap).
    "controller_action": "ms",
    # A fleet action's actuation wall (a drain/preshed flag flip —
    # near-zero, but the slice keeps the action/refusal vocabulary
    # uniform with the controller lane).
    "fleet_action": "ms",
}
# Gauge-bearing record kinds -> the numeric fields that become counter
# series. Each record emits one "C" (counter) event per listed field, so
# Perfetto draws queue depth / oldest wait / memory-in-use as stepped
# counter tracks beside the slices (the Chrome trace-event counter
# phase). Records missing a field simply skip that series.
_COUNTER_KINDS = {
    # ctl_level (ISSUE 20) rides the gauge record on controlled servers
    # — pre-20 records lack the field and skip the series.
    "serve_gauges": (
        "depth", "pending_images", "oldest_wait_ms", "ctl_level",
    ),
    "mem_snapshot": ("bytes_in_use", "peak_bytes_in_use"),
}


def load_records(path) -> List[dict]:
    """All journal records under ``path``: one ``.jsonl`` file, or every
    ``*.jsonl`` in a directory (sorted by name so replays are stable)."""
    p = Path(path)
    if p.is_dir():
        records: List[dict] = []
        for f in sorted(p.glob("*.jsonl")):
            records.extend(Journal.load(f))
        return records
    return Journal.load(p)


def _span_pid(name: str) -> int:
    return _PIDS.get(name.split(".", 1)[0], _PIDS["run"])


def _kind_pid(kind: str) -> int:
    return _PIDS[_KIND_PID.get(kind, "journal")]


class _Lanes:
    """Greedy interval-partitioning of slices into lanes (exported tids):
    a slice joins a lane if it nests inside the lane's innermost open
    slice or starts after everything on the lane ended. Keeps Chrome's
    same-tid containment invariant true by construction."""

    def __init__(self):
        self._lanes: List[List[float]] = []  # per lane: stack of open end-times

    def place(self, t0: float, t1: float) -> int:
        for i, stack in enumerate(self._lanes):
            while stack and stack[-1] <= t0:
                stack.pop()
            if not stack or t1 <= stack[-1]:
                stack.append(t1)
                return i
        self._lanes.append([t1])
        return len(self._lanes) - 1


def to_trace_events(records: List[dict]) -> dict:
    """Stitch journal records into ``{"traceEvents": [...]}`` (µs)."""
    spans = [r for r in records if r.get("kind") == "span"]
    others = [r for r in records if r.get("kind") != "span"]

    events: List[dict] = []
    # (pid, track-group) -> lane allocator; exported tid = stable index of
    # (pid, group, lane) so every lane gets its own named thread row.
    lanes: Dict[Tuple[int, str], _Lanes] = {}
    tid_map: Dict[Tuple[int, str, int], int] = {}
    tid_names: Dict[Tuple[int, int], str] = {}

    def _tid_for(pid: int, group: str, t0: float, t1: float) -> int:
        lane = lanes.setdefault((pid, group), _Lanes()).place(t0, t1)
        key = (pid, group, lane)
        if key not in tid_map:
            tid_map[key] = len(tid_map) + 1
            tid_names[(pid, tid_map[key])] = (
                f"{group}" + (f" [{lane}]" if lane else "")
            )
        return tid_map[key]

    # Spans: sorted by start so lane assignment sees intervals in order.
    span_loc: Dict[str, Tuple[int, int, float, float]] = {}  # sid -> pid,tid,t0,t1
    for rec in sorted(spans, key=lambda r: (r.get("t0_ms", 0.0), -r.get("dur_ms", 0.0))):
        t0 = float(rec.get("t0_ms", 0.0)) * 1e3  # ms -> µs
        dur = max(1.0, float(rec.get("dur_ms", 0.0)) * 1e3)
        pid = _span_pid(str(rec.get("name", "")))
        group = str(rec.get("track") or f"t{rec.get('tid', 0)}")
        tid = _tid_for(pid, group, t0, t0 + dur)
        args = {
            k: rec[k]
            for k in ("trace_id", "span_id", "parent_id")
            if rec.get(k)
        }
        args.update(rec.get("attrs") or {})
        events.append(
            {
                "ph": "X", "name": rec.get("name", "span"), "cat": "span",
                "ts": round(t0, 1), "dur": round(dur, 1),
                "pid": pid, "tid": tid, "args": args,
            }
        )
        if rec.get("span_id"):
            span_loc[rec["span_id"]] = (pid, tid, t0, t0 + dur)

    # Incident lane (observability.health): fold the trail into incidents
    # and draw every span-timed one as a parent slice whose per-phase
    # children tile it end to end — the MTTR decomposition to scale.
    # Span-less incidents (old/untraced journals) have no wall-clock
    # placement and are skipped; the journal otherwise exports unchanged.
    from .health import incidents_from_records

    for inc in incidents_from_records(records):
        if inc.t0_ms is None or inc.wall_ms <= 0:
            continue
        pid = _PIDS["incident"]
        p_ts = round(inc.t0_ms * 1e3, 1)
        p_end = round((inc.t0_ms + inc.wall_ms) * 1e3, 1)
        if p_end <= p_ts:
            continue
        tid = _tid_for(pid, inc.kind, p_ts, p_end)
        events.append(
            {
                "ph": "X", "name": f"incident.{inc.kind}",
                "cat": "incident", "ts": p_ts,
                "dur": round(p_end - p_ts, 1), "pid": pid, "tid": tid,
                "args": {
                    "entry": inc.entry, "cause": inc.cause,
                    "wall_ms": round(inc.wall_ms, 3),
                },
            }
        )
        cursor = p_ts
        for pname, v in inc.phases.items():
            if not v or v <= 0:
                continue
            end = min(p_end, round(cursor + v * 1e3, 1))
            dur = round(end - cursor, 1)
            if dur <= 0:
                continue
            events.append(
                {
                    "ph": "X", "name": f"phase.{pname}",
                    "cat": "incident", "ts": round(cursor, 1),
                    "dur": dur, "pid": pid, "tid": tid,
                    "args": {"ms": round(v, 3)},
                }
            )
            cursor = end

    # Journal records: correlated ones pin to their span; the rest get a
    # synthetic per-kind timeline that preserves append order.
    synth_clock: Dict[str, float] = {}
    for idx, rec in enumerate(others):
        kind = str(rec.get("kind", "record"))
        args = {k: v for k, v in rec.items() if k != "kind"}
        sid = rec.get("span_id")
        if sid and sid in span_loc:
            pid, tid, s_t0, t1 = span_loc[sid]
            ms = rec.get("ms")
            if (
                kind == "compile_event"
                and isinstance(ms, (int, float))
                and ms > 0
            ):
                # A correlated compile renders as a SLICE ending where its
                # enclosing warmup/rewarm span ends (the compile is the
                # tail of the timed first call), on a "compile" sub-lane
                # of the span's process row — several buckets compiling
                # under one rewarm span would mis-nest on the span's own
                # lane.
                dur = float(ms) * 1e3
                ts = max(s_t0, t1 - dur)
                ctid = _tid_for(pid, "compile", ts, t1)
                events.append(
                    {
                        "ph": "X", "name": kind, "cat": "journal",
                        "ts": round(ts, 1), "dur": round(t1 - ts, 1),
                        "pid": pid, "tid": ctid, "args": args,
                    }
                )
                continue
            events.append(
                {
                    "ph": "i", "name": kind, "cat": "journal",
                    "ts": round(t1, 1), "pid": pid, "tid": tid,
                    "s": "t", "args": args,
                }
            )
            continue
        pid = _kind_pid(kind)
        if kind in _COUNTER_KINDS:
            # Counter tracks: one "C" event per gauge field. The synthetic
            # append-order clock keeps the series monotonic alongside the
            # other uncorrelated records.
            t0 = max(synth_clock.get(kind, 0.0), float(idx) * 1e3)
            for field in _COUNTER_KINDS[kind]:
                v = rec.get(field)
                if isinstance(v, (int, float)):
                    events.append(
                        {
                            "ph": "C", "name": f"{kind}.{field}",
                            "cat": "journal", "ts": round(t0, 1),
                            "pid": pid, "tid": 0,
                            "args": {field: v},
                        }
                    )
            synth_clock[kind] = t0 + 1.0
            continue
        dur_field = _KIND_DUR_FIELD.get(kind)
        dur_ms = rec.get(dur_field) if dur_field else None
        t0 = max(synth_clock.get(kind, 0.0), float(idx) * 1e3)  # µs, ordered
        if isinstance(dur_ms, (int, float)) and dur_ms > 0:
            dur = float(dur_ms) * 1e3
            tid = _tid_for(pid, kind, t0, t0 + dur)
            events.append(
                {
                    "ph": "X", "name": kind, "cat": "journal",
                    "ts": round(t0, 1), "dur": round(dur, 1),
                    "pid": pid, "tid": tid, "args": args,
                }
            )
            synth_clock[kind] = t0 + dur
        else:
            tid = _tid_for(pid, kind, t0, t0 + 1.0)
            events.append(
                {
                    "ph": "i", "name": kind, "cat": "journal",
                    "ts": round(t0, 1), "pid": pid, "tid": tid,
                    "s": "t", "args": args,
                }
            )
            synth_clock[kind] = t0 + 1.0

    meta: List[dict] = []
    for name, pid in sorted(_PIDS.items(), key=lambda kv: kv[1]):
        if any(ev["pid"] == pid for ev in events):
            meta.append(
                {
                    "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                    "args": {"name": name},
                }
            )
    for (pid, tid), tname in sorted(tid_names.items()):
        meta.append(
            {
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": tname},
            }
        )
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def export_trace(journal_path, out_path) -> dict:
    """Load, stitch, atomically write. Returns a summary dict (the CLI
    prints it machine-readably)."""
    records = load_records(journal_path)
    trace = to_trace_events(records)
    atomic_write_text(out_path, json.dumps(trace))
    n_spans = sum(1 for r in records if r.get("kind") == "span")
    return {
        "out": str(out_path),
        "records": len(records),
        "spans": n_spans,
        "events": len(trace["traceEvents"]),
    }


# ------------------------------------------------------------ bench report


def bench_report(paths) -> str:
    """Cross-run text report: the BENCH_r*.json trajectory with >10%
    regressions between consecutive measured rounds flagged (plus
    per-stage breakdown deltas, with ``last_good``-echo rounds labeled
    and excluded). The text face of :mod:`..observability.gate` — the
    structured verdict (and the nonzero-exit CI gate) lives there."""
    from .gate import evaluate

    return evaluate(paths).render()

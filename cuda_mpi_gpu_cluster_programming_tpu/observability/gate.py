"""Perf-regression gate over the ``BENCH_r*.json`` trajectory.

PR 9's ``bench_report`` *flagged* >10% regressions as text that scrolled
by; this module promotes it into a **gate**: :func:`evaluate` returns a
structured :class:`GateVerdict` (machine-readable ``to_obj``, the same
human ``render`` text), and the CLI / tier-1 / ``on_heal.sh`` wiring
exits nonzero on any regression — perf claims fail CI instead of being
eyeballed (docs/OBSERVABILITY.md "Replay & regression gating").

Two disciplines the plain diff lacked:

- **Echo exclusion.** The committed BENCH_r02–r05 trail is wedged-tunnel
  ``last_good`` echoes: each failed round re-reports the previous
  round's number with a staleness marker. Diffing an echo as a fresh
  measurement can both manufacture regressions (echo vs a later real
  value) and mask them (a flat echoed line looks healthy). A round whose
  only value is a ``last_good`` carry **identical to a value an earlier
  round already reported** (plus the provenance marker —
  ``value_last_good`` / ``last_good.stale``) is classified
  ``stale (echo of rNN)`` and excluded from every comparison,
  attributably. A ``last_good`` number appearing for the FIRST time is
  kept as a measured-once value (it *was* measured, in an uncommitted
  window) — the echo rule removes copies, not information.
- **Per-stage verdicts.** Rounds carrying the PR 9 ``breakdown``
  sub-object are diffed stage by stage (conv1/pool1/conv2/pool2/lrn2),
  so "conv2 got 30% slower" fails the gate even when the headline hides
  it inside noise.

``export.bench_report`` keeps its exact text contract by delegating to
:meth:`GateVerdict.render`. Stdlib only.
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# The regression bar: a headline drop or per-stage rise past this
# fraction between compared rounds fails the gate.
THRESHOLD = 0.10


# ------------------------------------------------------------ row parsing ---


def _bench_obj(path: Path) -> Optional[dict]:
    """One BENCH_r*.json's measured row. The committed files are
    driver-wrapped ({"parsed": {...}, "tail": ...}); bare row objects and
    raw JSONL (first parseable line) are accepted too."""
    try:
        text = path.read_text()
    except OSError:
        return None
    try:
        obj = json.loads(text)
    except ValueError:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    obj = json.loads(line)
                    break
                except ValueError:
                    continue
        else:
            return None
    if isinstance(obj, dict) and isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    return obj if isinstance(obj, dict) else None


def _stale_value(row: dict) -> Tuple[Optional[float], bool]:
    """(the row's last_good carry value, whether it wears the staleness
    provenance marker). The marker is what separates 'a wedged round
    echoing old evidence' from 'two rounds that legitimately measured
    the same number' — only marked rows can ever be echoes."""
    lg = row.get("last_good")
    lg = lg if isinstance(lg, dict) else {}
    marker = bool(lg.get("stale")) or "value_last_good" in row
    for v in (row.get("value_last_good"), lg.get("value"), lg.get("stale_value")):
        if isinstance(v, (int, float)) and v > 0:
            return float(v), marker
    return None, marker


@dataclasses.dataclass
class RoundRow:
    """One round's classified evidence."""

    name: str
    row: dict
    value: Optional[float]  # measurable throughput (img/s) or None
    provenance: str  # fresh | last_good(stale) | stale (echo of rNN) | error | none
    echo_of: str = ""  # origin round name when provenance is an echo
    per_pass_ms: Optional[float] = None
    stages: Optional[Dict[str, float]] = None
    # "stage" (sentinel-boundary rows) vs "block" (fuse="block" megakernel
    # rows, block1/block2 vocabulary). Stage diffs only ever compare rows
    # of the SAME granularity: a fused block1 time against a staged conv1
    # time is not a regression signal, it's a vocabulary collision.
    granularity: str = "stage"
    error: str = ""

    @property
    def is_echo(self) -> bool:
        return bool(self.echo_of)

    @property
    def measured(self) -> bool:
        """Participates in comparisons: carries a value that was measured
        (fresh, or a first-appearance last_good carry) — echoes and
        error-only rounds do not."""
        return self.value is not None and not self.is_echo

    def to_obj(self) -> dict:
        return {
            "name": self.name,
            "value": self.value,
            "provenance": self.provenance,
            "echo_of": self.echo_of or None,
            "per_pass_ms": self.per_pass_ms,
            "stages": self.stages,
            "granularity": self.granularity,
            "error": self.error or None,
        }


def load_rounds(paths) -> List[RoundRow]:
    """Parse + classify a trajectory (sorted by path name, the round
    order). Echo detection is cross-round by construction: a marked
    ``last_good`` value equal to ANY value an earlier round reported
    (measured or itself a first-appearance carry) is the echo of that
    round."""
    rows: List[RoundRow] = []
    seen_values: Dict[float, str] = {}  # value -> first round reporting it
    for p in sorted(Path(str(p)) for p in paths):
        obj = _bench_obj(p)
        if obj is None:
            continue
        v = obj.get("value")
        per_pass = obj.get("per_pass_ms")
        bd = obj.get("breakdown")
        stages = bd.get("stages") if isinstance(bd, dict) else None
        stages = (
            {
                s: float(ms)
                for s, ms in stages.items()
                if isinstance(ms, (int, float))
            }
            if isinstance(stages, dict) and stages
            else None
        )
        rr = RoundRow(
            name=p.name,
            row=obj,
            value=None,
            provenance="none",
            per_pass_ms=float(per_pass) if isinstance(per_pass, (int, float)) else None,
            stages=stages,
            granularity=(
                str(bd.get("granularity") or "stage")
                if isinstance(bd, dict)
                else "stage"
            ),
            error=str(obj.get("error") or ""),
        )
        if isinstance(v, (int, float)) and v > 0:
            rr.value, rr.provenance = float(v), "fresh"
            seen_values.setdefault(rr.value, rr.name)
        else:
            carry, marked = _stale_value(obj)
            if carry is not None:
                rr.value = carry
                if marked and carry in seen_values:
                    rr.echo_of = seen_values[carry]
                    rr.provenance = f"stale (echo of {rr.echo_of})"
                else:
                    rr.provenance = "last_good(stale)"
                    seen_values.setdefault(carry, rr.name)
            else:
                rr.provenance = "error" if rr.error else "none"
        rows.append(rr)
    return rows


# ---------------------------------------------------------------- verdict ---


@dataclasses.dataclass
class Regression:
    """One >threshold finding between two compared rounds."""

    kind: str  # "headline" | "stage"
    frm: str  # earlier round name
    to: str  # later round name
    prev: float
    cur: float
    pct: float  # signed change percent (negative = slower/worse headline)
    stage: str = ""
    provenance: str = ""  # the later round's value provenance

    def to_obj(self) -> dict:
        return dataclasses.asdict(self)

    def line(self) -> str:
        if self.kind == "stage":
            return (
                f"  REGRESSION {self.to} stage {self.stage}: "
                f"{self.prev:.3f} -> {self.cur:.3f} ms "
                f"(+{self.pct:.0f}% vs {self.frm})"
            )
        return (
            f"  REGRESSION {self.to}: {self.prev:.1f} -> {self.cur:.1f} img/s "
            f"(-{self.pct:.0f}% vs {self.frm})"
        )


@dataclasses.dataclass
class GateVerdict:
    """The gate's full structured output (``ok`` is the exit-code bit)."""

    rows: List[RoundRow]
    regressions: List[Regression]
    threshold: float = THRESHOLD
    compared: int = 0  # headline round-pairs actually diffed

    @property
    def ok(self) -> bool:
        return not self.regressions

    @property
    def echoes(self) -> List[RoundRow]:
        return [r for r in self.rows if r.is_echo]

    def to_obj(self) -> dict:
        return {
            "ok": self.ok,
            "threshold": self.threshold,
            "compared": self.compared,
            "rounds": [r.to_obj() for r in self.rows],
            "regressions": [r.to_obj() for r in self.regressions],
            "echoes": [r.name for r in self.echoes],
        }

    def render(self) -> str:
        """The human report — the exact ``bench_report`` text contract
        (header, per-round lines, ``flags:`` section), with echo rounds
        now labeled instead of diffed."""
        if not self.rows:
            return "bench report: no parseable BENCH rows"
        lines = ["bench trajectory:"]
        for r in self.rows:
            bits = [
                f"  {r.name}:",
                f"value={r.value:.1f} img/s" if r.value is not None else "value=unmeasured",
                f"({r.provenance})",
            ]
            if r.per_pass_ms is not None:
                bits.append(f"per_pass={r.per_pass_ms:.3f} ms")
            if r.error:
                bits.append(f"error={r.error[:60]!r}")
            if r.stages:
                worst = max(r.stages, key=lambda s: r.stages[s])
                gran = "" if r.granularity == "stage" else f" {r.granularity}-granularity"
                bits.append(
                    f"breakdown[{len(r.stages)} stages, top {worst}="
                    f"{r.stages[worst]:.3f} ms{gran}]"
                )
            lines.append(" ".join(bits))
        if self.regressions:
            lines.append("flags:")
            lines.extend(r.line() for r in self.regressions)
        else:
            lines.append(
                "flags: none (no >10% regression between measured rounds)"
            )
        return "\n".join(lines)


def evaluate(paths, threshold: float = THRESHOLD) -> GateVerdict:
    """Classify a trajectory and diff consecutive MEASURED rounds.

    Headline: a later measured value below ``(1 - threshold)`` × the
    previous measured value is a regression. Stages: between consecutive
    breakdown-carrying measured rounds OF THE SAME GRANULARITY, any stage
    above ``(1 + threshold)`` × its predecessor is a regression — a
    staged round and a ``fuse="block"`` megakernel round are distinct
    variants whose per-stage chains diff independently (ISSUE 17: a
    fused block1 row must never diff against a staged conv1 row). Echo
    rounds are excluded from every chain (and reported via the
    verdict)."""
    rows = load_rounds(paths)
    regressions: List[Regression] = []
    compared = 0
    prev: Optional[RoundRow] = None
    prev_stages_by_gran: Dict[str, Tuple[str, Dict[str, float]]] = {}
    for r in rows:
        if r.is_echo:
            continue
        if r.stages and not r.is_echo:
            prev_stages = prev_stages_by_gran.get(r.granularity)
            if prev_stages is not None:
                frm_name, p_stages = prev_stages
                for s, ms in r.stages.items():
                    p_ms = p_stages.get(s)
                    if (
                        isinstance(p_ms, (int, float))
                        and p_ms > 0
                        and ms > p_ms * (1.0 + threshold)
                    ):
                        regressions.append(
                            Regression(
                                kind="stage", frm=frm_name, to=r.name,
                                prev=p_ms, cur=ms,
                                pct=(ms / p_ms - 1) * 100, stage=s,
                                provenance=r.provenance,
                            )
                        )
            prev_stages_by_gran[r.granularity] = (r.name, r.stages)
        if not r.measured:
            continue
        if prev is not None:
            compared += 1
            if r.value < prev.value * (1.0 - threshold):
                regressions.append(
                    Regression(
                        kind="headline", frm=prev.name, to=r.name,
                        prev=prev.value, cur=r.value,
                        pct=(1 - r.value / prev.value) * 100,
                        provenance=r.provenance,
                    )
                )
        prev = r
    return GateVerdict(
        rows=rows, regressions=regressions, threshold=threshold,
        compared=compared,
    )

"""Device spec table: peak TFLOP/s per dtype + HBM bandwidth per TPU
generation, plus the live device-memory snapshot helper.

Until ISSUE 13 the chip-capability knowledge lived as ``bench.py``'s
private ``_PEAK_TABLE`` — a bf16-peak-only list no other subsystem could
consult, which is why the repo could compute whole-pass MFU but never a
per-stage bandwidth verdict. This module is the ONE source of truth:
``bench.peak_tflops`` delegates here, and the roofline attribution layer
(``observability.roofline``) reads the same table for its
compute-vs-HBM-bound classification, so a bench row's ``assumed_peak``
and a roofline verdict can never disagree about what the chip can do.

Numbers come from the public TPU spec sheets, matched against jax's
``device_kind`` string exactly the way ``bench.py`` always has ("v5"
matches the "TPU v5 lite" spelling v5e reports). Per-dtype peaks:

- ``bf16`` — the MXU peak from the table (``BENCH_PEAK_TFLOPS``
  overrides, same contract as the bench headline).
- ``fp32`` — ``bf16 / 6``: ``lax.Precision.HIGHEST`` synthesizes true
  fp32 MACs out of 6 bf16 MXU passes (the ``fp32_ceiling_fraction``
  convention bench rows already carry).
- ``int8w`` — equals the bf16 peak HERE, deliberately: this repo's
  int8w forward is dequant-free bf16-accumulate (docs/PRECISION.md) —
  the MXU executes bf16 operand passes, so the int8 TOPS column of the
  spec sheet is not the ceiling this codebase can reach. ``int8_tops``
  is still recorded on the spec for reference.

Stdlib-only at module scope (bench imports this before jax exists);
:func:`device_memory_stats` imports jax lazily and degrades to a
process-RSS reading so the ``mem_snapshot`` telemetry record always has
something truthful to say (``source`` names which reading it is).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional, Tuple

# lax.Precision.HIGHEST fp32 synthesis: 6 bf16 MXU passes per fp32 MAC.
FP32_SYNTH_FACTOR = 6.0


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """One TPU generation's roofline-relevant capabilities."""

    marker: str  # substring matched against device_kind.lower()
    name: str
    bf16_tflops: float  # MXU peak, dense bf16
    hbm_gbps: float  # HBM bandwidth, GB/s per chip
    int8_tops: Optional[float] = None  # spec-sheet int8 (reference only)

    def peak_tflops(self, dtype: str = "bf16") -> float:
        """The MXU ceiling a ``dtype`` policy of THIS repo can chase."""
        if dtype == "fp32":
            return self.bf16_tflops / FP32_SYNTH_FACTOR
        # bf16 and int8w both execute bf16 MXU passes here (module doc).
        return self.bf16_tflops

    def to_obj(self) -> dict:
        return {
            "name": self.name,
            "bf16_tflops": self.bf16_tflops,
            "hbm_gbps": self.hbm_gbps,
            "int8_tops": self.int8_tops,
        }


# Ordered: longer/newer markers first so "v5p" wins over "v5" (the same
# first-match discipline bench's private table used).
SPEC_TABLE: Tuple[DeviceSpec, ...] = (
    DeviceSpec("v6", "TPU v6e (Trillium)", 918.0, 1640.0, 1836.0),
    DeviceSpec("v5p", "TPU v5p", 459.0, 2765.0, 918.0),
    DeviceSpec("v5", "TPU v5e", 197.0, 819.0, 394.0),  # kind: "TPU v5 lite"
    DeviceSpec("v4", "TPU v4", 275.0, 1228.0, 275.0),
    DeviceSpec("v3", "TPU v3", 123.0, 900.0, None),
    DeviceSpec("v2", "TPU v2", 45.0, 700.0, None),
)

# Unknown kind (CPU containers, exotic relays): assume the chip we
# actually develop on — callers surface the ``assumed`` bit visibly.
DEFAULT_SPEC = SPEC_TABLE[2]


def spec_for(device_kind: str) -> Tuple[DeviceSpec, bool]:
    """``(spec, assumed)`` for a jax ``device_kind`` string. ``assumed``
    is True when the kind matched nothing and the v5e default stands in
    (a CPU mesh judged against an assumed chip must SAY so)."""
    kind = (device_kind or "").lower()
    for spec in SPEC_TABLE:
        if spec.marker in kind:
            return spec, False
    return DEFAULT_SPEC, True


def peak_tflops(device_kind: str, dtype: str = "bf16") -> float:
    """Peak TFLOP/s for ``device_kind`` under this repo's ``dtype``
    policies. ``BENCH_PEAK_TFLOPS`` overrides the bf16 MXU peak (the
    historical bench contract); the fp32 ceiling scales with it."""
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        bf16 = float(env)
    else:
        spec, _assumed = spec_for(device_kind)
        bf16 = spec.bf16_tflops
    return bf16 / FP32_SYNTH_FACTOR if dtype == "fp32" else bf16


def hbm_gbps(device_kind: str) -> float:
    """HBM bandwidth (GB/s) for ``device_kind``; ``BENCH_PEAK_HBM_GBPS``
    overrides (the bandwidth twin of ``BENCH_PEAK_TFLOPS``)."""
    env = os.environ.get("BENCH_PEAK_HBM_GBPS")
    if env:
        return float(env)
    spec, _assumed = spec_for(device_kind)
    return spec.hbm_gbps


def bf16_peak_table() -> List[Tuple[str, float]]:
    """The historical ``bench._PEAK_TABLE`` shape — ``(marker, bf16
    TFLOP/s)`` pairs — derived from the one spec table."""
    return [(s.marker, s.bf16_tflops) for s in SPEC_TABLE]


# ------------------------------------------------------- live telemetry ---


def device_memory_stats() -> dict:
    """One resource snapshot for the ``mem_snapshot`` journal record.

    Prefers jax's per-device ``memory_stats()`` (``source="device"``:
    bytes_in_use / peak_bytes_in_use / bytes_limit summed over local
    devices, with the per-device list alongside); on backends that
    expose none (the CPU container) it degrades to the process max-RSS
    (``source="rss"``) so the telemetry lane never goes silent — the
    record always says which reading it carries.
    """
    try:
        import jax

        devices = []
        for d in jax.local_devices():
            getter = getattr(d, "memory_stats", None)
            stats = getter() if callable(getter) else None
            if isinstance(stats, dict) and stats.get("bytes_in_use") is not None:
                devices.append(
                    {
                        "device": getattr(d, "id", len(devices)),
                        "bytes_in_use": int(stats["bytes_in_use"]),
                        "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
                        "bytes_limit": stats.get("bytes_limit"),
                    }
                )
        if devices:
            def _total(field: str) -> Optional[int]:
                vals = [d.get(field) for d in devices]
                nums = [v for v in vals if isinstance(v, (int, float))]
                return int(sum(nums)) if nums else None

            return {
                "source": "device",
                "bytes_in_use": _total("bytes_in_use"),
                "peak_bytes_in_use": _total("peak_bytes_in_use"),
                "bytes_limit": _total("bytes_limit"),
                "devices": devices,
            }
    except Exception:  # backend quirks must never break the dispatch loop
        pass
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return {
            "source": "rss",
            "bytes_in_use": int(rss_kb) * 1024,  # linux reports KB
            "peak_bytes_in_use": None,
            "bytes_limit": None,
        }
    except Exception:
        return {
            "source": "none",
            "bytes_in_use": None,
            "peak_bytes_in_use": None,
            "bytes_limit": None,
        }

"""Per-stage latency attribution for the Blocks 1-2 forward.

The reference repo's headline artifact is a staged per-phase breakdown —
scatter/halo/compute/gather ms per block — while our bench rows report
one ``per_pass_ms``. This module attributes that total across the EXACT
stage boundaries the in-graph sentinel taps (``with_digests=True``
compiles digests at conv1/pool1/conv2/pool2/lrn2 inside the shard_map
bodies — docs/RESILIENCE.md), so the attribution and the SDC screen
speak the same stage vocabulary.

Method: **timed staged re-execution, off the timed path**. The hot loop
stays sync-free — attribution never instruments the production forward.
Instead, :func:`attribute_stages` re-executes the staged chain as five
jitted *prefixes* (conv1; conv1+pool1; ...; the full chain) under the
repo's amortized work-floor estimator and attributes
``stage_k = t(prefix_k) - t(prefix_{k-1})``. The differences telescope,
so the per-stage breakdown sums EXACTLY to the measured full-chain time
(noise-negative diffs clamp to zero, then the stages renormalize onto
the measured total) — the sums-to-total contract the bench ``breakdown``
sub-object carries.
Per-stage timing of each stage in isolation (``utils.profiling.
layer_breakdown``) cannot make that promise: XLA fuses across stage
boundaries, so isolated stages systematically over-count.

``@off_timed_path`` by contract (staticcheck's ``host-sync-in-hot-loop``
scope covers this file): every call here is a measurement pass between
timed regions, never inside one.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, List, Tuple

from .trace import off_timed_path, span

# The sentinel tap boundaries (parallel.sharded / tensor_parallel
# with_digests=True) — conv stages include their ReLU, exactly as the
# in-graph digest taps bound them.
SENTINEL_STAGES = ("conv1", "pool1", "conv2", "pool2", "lrn2")


def sentinel_stage_fns(cfg=None, tier: str = "reference") -> List[Tuple[str, Callable]]:
    """(name, fn) per sentinel stage; each fn maps the previous stage's
    output to this stage's output. Conv stages fuse ReLU (the tap is
    after activation on both op tiers)."""
    from ..models.alexnet import BLOCKS12
    from ..utils.profiling import _tier_ops

    cfg = cfg if cfg is not None else BLOCKS12
    conv, pool, lrn, _fused = _tier_ops(tier)
    return [
        ("conv1", functools.partial(conv, name="conv1", spec=cfg.conv1, relu=True)),
        ("pool1", functools.partial(pool, spec=cfg.pool1)),
        ("conv2", functools.partial(conv, name="conv2", spec=cfg.conv2, relu=True)),
        ("pool2", functools.partial(pool, spec=cfg.pool2)),
        ("lrn2", functools.partial(lrn, spec=cfg.lrn2)),
    ]


@dataclasses.dataclass(frozen=True)
class StageAttribution:
    """One attribution pass: per-stage ms (telescoped prefix differences),
    the raw prefix times, and the full-chain total the stages sum to.

    ``granularity``: "stage" (the five sentinel boundaries) or "block"
    (block1/block2 — the honest vocabulary for ``fuse="block"`` rows,
    where a fused pass has no interior boundaries to tap; the sub-object
    names its source so a block row can never be mistaken for a faked
    per-stage split)."""

    stages: Tuple[Tuple[str, float], ...]  # (name, attributed ms), in order
    prefix_ms: Tuple[float, ...]  # t(prefix_1) .. t(prefix_5) == total
    total_ms: float  # full staged chain, the reported per-pass analogue
    batch: int
    tier: str
    compute: str
    granularity: str = "stage"

    @property
    def stage_sum_ms(self) -> float:
        return sum(ms for _n, ms in self.stages)

    def to_obj(self) -> dict:
        """The bench ``breakdown`` sub-object — per-stage ms machine-
        comparable across BENCH_r*.json captures."""
        return {
            "stages": {name: round(ms, 4) for name, ms in self.stages},
            "stage_sum_ms": round(self.stage_sum_ms, 4),
            "total_ms": round(self.total_ms, 4),
            "method": (
                "prefix-diff" if self.granularity == "stage"
                else "prefix-diff/megakernel-blocks"
            ),
            "tier": self.tier,
            "compute": self.compute,
            "batch": self.batch,
            "granularity": self.granularity,
        }


@off_timed_path
def attribute_stages(
    params,
    x,
    cfg=None,
    *,
    tier: str = "reference",
    compute: str = "fp32",
    repeats: int = 3,
    warmup: int = 1,
) -> StageAttribution:
    """Measure the staged Blocks 1-2 chain and attribute per-stage ms.

    ``compute`` follows ``configs.build_forward``'s fp32/bf16 casting
    (bf16 casts params and activations, matching the headline numerics);
    ``int8w`` has no staged-chain analogue here and raises — callers on
    the quantized path degrade visibly instead of mislabeling fp32
    numbers as int8w attribution.
    """
    import jax

    from ..utils.timing import amortized_stats

    if compute == "bf16":
        import jax.numpy as jnp

        params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
        x = x.astype(jnp.bfloat16)
    elif compute != "fp32":
        raise ValueError(
            f"stage attribution supports fp32|bf16, got {compute!r} "
            "(the int8w lowering has no staged-chain analogue)"
        )
    stage_list = sentinel_stage_fns(cfg, tier=tier)

    def _prefix(k: int) -> Callable:
        fns = [fn for _n, fn in stage_list[:k]]

        def run(p, xin):
            cur = xin
            for fn in fns:
                cur = fn(p, cur)
            return cur

        return run

    n_small = max(1, warmup)
    prefix_ms: List[float] = []
    with span("stages.attribute", tier=tier, compute=compute, batch=int(x.shape[0])):
        for k in range(1, len(stage_list) + 1):
            # One jit per distinct prefix — per-stage attribution is the
            # point, not a retrace of one function.
            jfn = jax.jit(_prefix(k))  # noqa: jit-in-loop
            st = amortized_stats(
                jfn, params, x,
                n_small=n_small, n_large=n_small + max(1, repeats),
            )
            prefix_ms.append(st.per_call_ms)
    stages: List[Tuple[str, float]] = []
    prev = 0.0
    for (name, _fn), t in zip(stage_list, prefix_ms):
        stages.append((name, max(0.0, t - prev)))
        prev = t
    # A noise-negative diff (a longer prefix timing faster — sub-ms stages
    # under fusion jitter) clamps to 0 but leaves the clamped sum above the
    # measured total; renormalize onto the total so the sums-to-total
    # contract holds exactly. The raw prefix times stay on the result for
    # audit.
    clamped_sum = sum(ms for _n, ms in stages)
    if clamped_sum > 0 and abs(clamped_sum - prefix_ms[-1]) > 1e-12:
        scale = prefix_ms[-1] / clamped_sum
        stages = [(name, ms * scale) for name, ms in stages]
    return StageAttribution(
        stages=tuple(stages),
        prefix_ms=tuple(prefix_ms),
        total_ms=prefix_ms[-1],
        batch=int(x.shape[0]),
        tier=tier,
        compute=compute,
    )


@off_timed_path
def attribute_blocks(
    params,
    x,
    cfg=None,
    *,
    compute: str = "fp32",
    variants=None,
    repeats: int = 3,
    warmup: int = 1,
) -> StageAttribution:
    """Block-granularity attribution for ``fuse="block"`` (megakernel)
    rows: the same telescoped prefix-diff method as
    :func:`attribute_stages`, but the prefixes are the two FUSED passes
    (block1; block1+block2) — the only boundaries a megakernel row
    honestly has. The result carries ``granularity="block"`` and a
    method string naming the source, so downstream consumers (bench
    rows, the regression gate) can never mistake it for a per-stage
    split the fused pass did not measure.

    ``variants``: the per-layer plan the row ran under (conv variant and
    row_block govern the megakernel lowering; ``fuse`` itself is implied).
    fp32/bf16 only, like :func:`attribute_stages`."""
    import jax

    from ..models.alexnet import BLOCKS12
    from ..ops import megakernel as mk
    from ..ops import pallas_kernels as pk
    from ..ops.pallas_model import _layer_variants
    from ..utils.timing import amortized_stats

    cfg = cfg if cfg is not None else BLOCKS12
    if compute == "bf16":
        import jax.numpy as jnp

        params = jax.tree.map(lambda a: a.astype(jnp.bfloat16), params)
        x = x.astype(jnp.bfloat16)
    elif compute != "fp32":
        raise ValueError(
            f"block attribution supports fp32|bf16, got {compute!r} "
            "(the int8w megakernel rides the quantized bench path)"
        )
    v = variants if variants is not None else pk.KernelVariants()
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2

    def _block(cur, p, name, cspec, pspec, lrn):
        lv = _layer_variants(v, name)
        conv_v = lv.conv if lv.conv in ("taps", "vcol") else "vcol"
        ho = (
            cur.shape[1] + 2 * cspec.padding - cspec.filter_size
        ) // cspec.stride + 1
        return mk.conv_block_pallas(
            cur, p[name]["w"], p[name]["b"],
            stride=cspec.stride, padding=cspec.padding,
            pool_window=pspec.window, pool_stride=pspec.stride,
            lrn=lrn, variant=conv_v, row_block=max(lv.row_block, ho),
        )

    def _prefix(k: int):
        def run(p, xin):
            cur = _block(xin, p, "conv1", c1, p1, None)
            if k >= 2:
                cur = _block(cur, p, "conv2", c2, p2, n2)
            return cur

        return run

    n_small = max(1, warmup)
    prefix_ms: List[float] = []
    with span(
        "stages.attribute_blocks", compute=compute, batch=int(x.shape[0])
    ):
        for k in (1, 2):
            jfn = jax.jit(_prefix(k))  # noqa: jit-in-loop
            st = amortized_stats(
                jfn, params, x,
                n_small=n_small, n_large=n_small + max(1, repeats),
            )
            prefix_ms.append(st.per_call_ms)
    stages: List[Tuple[str, float]] = []
    prev = 0.0
    for name, t in zip(("block1", "block2"), prefix_ms):
        stages.append((name, max(0.0, t - prev)))
        prev = t
    clamped_sum = sum(ms for _n, ms in stages)
    if clamped_sum > 0 and abs(clamped_sum - prefix_ms[-1]) > 1e-12:
        scale = prefix_ms[-1] / clamped_sum
        stages = [(name, ms * scale) for name, ms in stages]
    return StageAttribution(
        stages=tuple(stages),
        prefix_ms=tuple(prefix_ms),
        total_ms=prefix_ms[-1],
        batch=int(x.shape[0]),
        tier="pallas",
        compute=compute,
        granularity="block",
    )

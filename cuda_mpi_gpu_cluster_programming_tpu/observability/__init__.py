"""Unified observability: journal-correlated tracing, a metrics registry,
per-stage latency attribution, and Perfetto export (docs/OBSERVABILITY.md).

- ``trace``   — span API (``span(name, **attrs)`` with trace/span/parent
  ids, monotonic clocks) persisting through the PR 3 crash-consistent
  ``Journal``; journal records at wired call sites gain optional
  ``trace_id``/``span_id`` correlation fields.
- ``metrics`` — process-wide counters/gauges/histograms (nearest-rank
  p50/p99 — the serve bench's estimator) with atomic JSONL export and the
  ``summary()`` the bench rows embed.
- ``stages``  — per-stage attribution of the Blocks 1-2 forward at the
  sentinel tap boundaries (conv1/pool1/conv2/pool2/lrn2), via timed
  staged re-execution strictly off the timed path; the bench
  ``breakdown`` sub-object's source.
- ``export``  — stitch spans AND the existing journal schemas
  (``serve_*``, ``sup_*``, ``gate_*``, ``mesh_shrink``, watchdog) into
  one Chrome trace-event / Perfetto JSON timeline, plus the cross-run
  BENCH_r*.json text report.
- ``replay``  — the journal-replay fleet simulator: reconstruct a
  recorded serve run's arrival schedule, request classes/deadlines, and
  chaos schedule from its journal alone and re-drive it through a live
  server on the CPU mesh, with ``traffic_mult``/``devices``/
  ``slo_scale`` what-if knobs; a neutral replay must close per-class
  accounting identically (the determinism contract).
- ``gate``    — the structured BENCH_r*.json regression gate: >10%
  headline/per-stage regressions fail (exit 3) with ``last_good``-echo
  rounds excluded attributably.
- ``specs``   — the ONE device spec table (peak TFLOP/s per dtype + HBM
  GB/s per TPU generation; ``bench.peak_tflops`` delegates here) plus
  the live ``device_memory_stats`` snapshot helper.
- ``roofline`` — per-stage MFU / HBM-traffic attribution: the analytic
  FLOP+byte ledger from ``models.alexnet``, the staged-vs-fused byte
  model predicting each block's fused time floor and MFU ceiling (the
  ROADMAP-1 megakernel judge), and the measured join emitting
  compute/memory-bound verdicts with headroom.

CLI: ``python -m cuda_mpi_gpu_cluster_programming_tpu.observability
export --journal <dir|file> [--out trace.json]``,
``... replay --journal <dir|file> [--traffic-mult K] [--devices N]
[--slo-scale F]``,
``... report [--fail-on-regression] [--json] BENCH_r*.json``, and
``... roofline [BENCH_r*.json] [--live]``
(exit codes: 0 clean / 2 usage or unreplayable / 3 regression or
replay divergence — docs/OBSERVABILITY.md).

This package init re-exports only the import-light tracing/metrics
surface (stdlib + journal — the wired subsystems pay no jax import);
``stages`` imports jax and is imported as a submodule by its callers.
"""

from .metrics import Counter, Gauge, Histogram, MetricsRegistry, registry
from .trace import Span, Tracer, current_ids, get_tracer, set_tracer, span

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "registry",
    "Span",
    "Tracer",
    "current_ids",
    "get_tracer",
    "set_tracer",
    "span",
]

"""CLI: Perfetto export, journal replay, and the BENCH regression gate.

    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        export --journal logs/serve_journal.jsonl --out logs/trace.json
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        replay --journal logs/serve_journal.jsonl [--traffic-mult 2] \\
        [--devices 1] [--slo-scale 0.5] [--journal-out replay.jsonl]
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        report [--fail-on-regression] [--json] BENCH_r*.json

Exit codes (docs/OBSERVABILITY.md "Replay & regression gating"):

- ``0`` — clean: trace exported / replay matched (or a what-if ran) /
  no regression.
- ``2`` — usage: missing journal, unreplayable journal (recorded before
  the replay schema), bad arguments.
- ``3`` — the gate tripped: a >10% regression with
  ``--fail-on-regression``, or a NEUTRAL replay that broke the
  determinism contract (per-class accounting or percentile divergence).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuda_mpi_gpu_cluster_programming_tpu.observability"
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ex = sub.add_parser(
        "export",
        help="stitch span + journal records into a Perfetto-loadable "
        "Chrome trace-event JSON",
    )
    ex.add_argument(
        "--journal",
        required=True,
        help="a journal .jsonl file, or a directory whose *.jsonl files "
        "are stitched together",
    )
    ex.add_argument(
        "--out",
        default="",
        help="output trace path (default: <journal>.trace.json next to "
        "the input)",
    )
    rp = sub.add_parser(
        "report",
        help="cross-run report diffing BENCH_r*.json trajectories "
        "(>10% headline/stage regressions; last_good echoes excluded "
        "attributably)",
    )
    rp.add_argument("bench", nargs="+", help="BENCH_r*.json paths")
    rp.add_argument(
        "--fail-on-regression",
        action="store_true",
        help="exit 3 when any >threshold regression survives echo "
        "exclusion — the CI gate mode (tier-1 + on_heal.sh wiring)",
    )
    rp.add_argument(
        "--json",
        action="store_true",
        help="print the machine-readable GateVerdict object instead of "
        "the text report",
    )
    rl = sub.add_parser(
        "replay",
        help="re-drive a recorded serve journal through a live server on "
        "the CPU mesh (same arrivals/classes/deadlines, same chaos "
        "schedule) — scaling knobs turn it into a capacity what-if",
    )
    rl.add_argument(
        "--journal",
        required=True,
        help="the recorded serve journal (.jsonl file or directory)",
    )
    rl.add_argument(
        "--traffic-mult",
        type=float,
        default=1.0,
        help="offer the recorded schedule at this multiple (2 = every "
        "arrival twice; fractions select by a stable per-rid hash)",
    )
    rl.add_argument(
        "--devices",
        type=int,
        default=None,
        help="rebuild the server at this shard width instead of the "
        "recorded one ('would it hold at half the devices?')",
    )
    rl.add_argument(
        "--slo-scale",
        type=float,
        default=1.0,
        help="scale every class SLO budget and per-request deadline "
        "(0.5 = twice as tight)",
    )
    rl.add_argument(
        "--journal-out",
        default="",
        help="journal the replay run here (default: a temp file; the "
        "replay journal is itself replayable)",
    )
    rl.add_argument(
        "--json",
        action="store_true",
        help="print the machine-readable replay report object",
    )
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.cmd == "export":
        from .export import export_trace

        src = Path(args.journal)
        if not src.exists():
            print(f"no journal at {src}", file=sys.stderr)
            return 2
        out = args.out or str(
            (src if src.is_dir() else src.with_suffix("")).with_suffix("")
        ) + ".trace.json"
        info = export_trace(src, out)
        print(
            f"Trace exported: {info['out']} events={info['events']} "
            f"spans={info['spans']} records={info['records']}"
        )
        if info["spans"] == 0:
            print(
                "note: no span records found — the timeline is the "
                "synthetic journal-order view (run with tracing wired, "
                "e.g. run --serve --serve-journal / --trace, for real "
                "timestamps)"
            )
        return 0
    if args.cmd == "report":
        from .gate import evaluate

        verdict = evaluate(args.bench)
        if args.json:
            print(json.dumps(verdict.to_obj()))
        else:
            print(verdict.render())
        if args.fail_on_regression and not verdict.ok:
            print(
                f"regression gate: FAIL ({len(verdict.regressions)} "
                f"regression(s) > {verdict.threshold:.0%})",
                file=sys.stderr,
            )
            return 3
        return 0
    if args.cmd == "replay":
        from .replay import ReplayKnobs, load_recorded_run, replay_recorded

        src = Path(args.journal)
        if not src.exists():
            print(f"no journal at {src}", file=sys.stderr)
            return 2
        try:
            recorded = load_recorded_run(src)
        except ValueError as e:
            print(f"unreplayable journal: {e}", file=sys.stderr)
            return 2
        if args.traffic_mult <= 0 or args.slo_scale <= 0:
            print("--traffic-mult/--slo-scale must be > 0", file=sys.stderr)
            return 2
        report = replay_recorded(
            recorded,
            ReplayKnobs(
                traffic_mult=args.traffic_mult,
                devices=args.devices,
                slo_scale=args.slo_scale,
                journal_path=args.journal_out,
            ),
        )
        if args.json:
            print(json.dumps(report.to_obj()))
        else:
            print(f"Replay: {report.summary()}")
            for line in report.class_lines():
                print(line)
        if report.diverged:
            print(
                "replay divergence: a neutral replay must reproduce the "
                "recorded per-class accounting identically and land its "
                "percentiles within estimator resolution "
                "(docs/OBSERVABILITY.md)",
                file=sys.stderr,
            )
            return 3
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

"""CLI: export a Perfetto trace from journals; diff BENCH trajectories.

    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        export --journal logs/serve_journal.jsonl --out logs/trace.json
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        report BENCH_r*.json
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuda_mpi_gpu_cluster_programming_tpu.observability"
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ex = sub.add_parser(
        "export",
        help="stitch span + journal records into a Perfetto-loadable "
        "Chrome trace-event JSON",
    )
    ex.add_argument(
        "--journal",
        required=True,
        help="a journal .jsonl file, or a directory whose *.jsonl files "
        "are stitched together",
    )
    ex.add_argument(
        "--out",
        default="",
        help="output trace path (default: <journal>.trace.json next to "
        "the input)",
    )
    rp = sub.add_parser(
        "report",
        help="cross-run text report diffing BENCH_r*.json trajectories "
        "(flags >10% regressions)",
    )
    rp.add_argument("bench", nargs="+", help="BENCH_r*.json paths")
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.cmd == "export":
        from .export import export_trace

        src = Path(args.journal)
        if not src.exists():
            print(f"no journal at {src}", file=sys.stderr)
            return 2
        out = args.out or str(
            (src if src.is_dir() else src.with_suffix("")).with_suffix("")
        ) + ".trace.json"
        info = export_trace(src, out)
        print(
            f"Trace exported: {info['out']} events={info['events']} "
            f"spans={info['spans']} records={info['records']}"
        )
        if info["spans"] == 0:
            print(
                "note: no span records found — the timeline is the "
                "synthetic journal-order view (run with tracing wired, "
                "e.g. run --serve --serve-journal / --trace, for real "
                "timestamps)"
            )
        return 0
    if args.cmd == "report":
        from .export import bench_report

        print(bench_report(args.bench))
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())

"""CLI: Perfetto export, journal replay, the BENCH regression gate,
roofline attribution, and the fleet-health report.

    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        export --journal logs/serve_journal.jsonl --out logs/trace.json
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        replay --journal logs/serve_journal.jsonl [--traffic-mult 2] \\
        [--devices 1] [--slo-scale 0.5] [--journal-out replay.jsonl]
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        report [--fail-on-regression] [--json] BENCH_r*.json
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        roofline BENCH_r*.json            # committed rows, echo-aware
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        roofline --live [--batch N] [--height H --width W]  # measure now
    python -m cuda_mpi_gpu_cluster_programming_tpu.observability \\
        health --journal logs/serve_journal.jsonl \\
        [--json] [--fail-on-budget-burn]

Exit codes (docs/OBSERVABILITY.md "Replay & regression gating" /
"Roofline attribution" / "Fleet health & compile attribution"):

- ``0`` — clean: trace exported / replay matched (or a what-if ran) /
  no regression / roofline rendered / health report rendered (budgets
  intact, or no gate requested).
- ``2`` — usage: missing journal, unreplayable journal (recorded before
  the replay schema), empty journal, bad arguments, no measurable
  roofline view.
- ``3`` — the gate tripped: a >10% regression with
  ``--fail-on-regression``, a NEUTRAL replay that broke the
  determinism contract (per-class accounting or percentile divergence),
  or a blown SLO error budget with ``--fail-on-budget-burn``.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuda_mpi_gpu_cluster_programming_tpu.observability"
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    ex = sub.add_parser(
        "export",
        help="stitch span + journal records into a Perfetto-loadable "
        "Chrome trace-event JSON",
    )
    ex.add_argument(
        "--journal",
        required=True,
        help="a journal .jsonl file, or a directory whose *.jsonl files "
        "are stitched together",
    )
    ex.add_argument(
        "--out",
        default="",
        help="output trace path (default: <journal>.trace.json next to "
        "the input)",
    )
    rp = sub.add_parser(
        "report",
        help="cross-run report diffing BENCH_r*.json trajectories "
        "(>10% headline/stage regressions; last_good echoes excluded "
        "attributably)",
    )
    rp.add_argument("bench", nargs="+", help="BENCH_r*.json paths")
    rp.add_argument(
        "--fail-on-regression",
        action="store_true",
        help="exit 3 when any >threshold regression survives echo "
        "exclusion — the CI gate mode (tier-1 + on_heal.sh wiring)",
    )
    rp.add_argument(
        "--json",
        action="store_true",
        help="print the machine-readable GateVerdict object instead of "
        "the text report",
    )
    rl = sub.add_parser(
        "replay",
        help="re-drive a recorded serve journal through a live server on "
        "the CPU mesh (same arrivals/classes/deadlines, same chaos "
        "schedule) — scaling knobs turn it into a capacity what-if",
    )
    rl.add_argument(
        "--journal",
        required=True,
        help="the recorded serve journal (.jsonl file or directory)",
    )
    rl.add_argument(
        "--traffic-mult",
        type=float,
        default=1.0,
        help="offer the recorded schedule at this multiple (2 = every "
        "arrival twice; fractions select by a stable per-rid hash)",
    )
    rl.add_argument(
        "--devices",
        type=int,
        default=None,
        help="rebuild the server at this shard width instead of the "
        "recorded one ('would it hold at half the devices?')",
    )
    rl.add_argument(
        "--slo-scale",
        type=float,
        default=1.0,
        help="scale every class SLO budget and per-request deadline "
        "(0.5 = twice as tight)",
    )
    rl.add_argument(
        "--controller",
        choices=("on", "off"),
        default="",
        help="autopilot A/B dial (docs/SERVING.md \"Autopilot\"): 'on' "
        "forces the closed-loop controller onto the replay server, "
        "'off' strips a recorded one; default re-drives as recorded. "
        "Replaying one saturating trace both ways is the controller's "
        "win-quantification: interactive burn lower with it on, books "
        "closed both ways",
    )
    rl.add_argument(
        "--journal-out",
        default="",
        help="journal the replay run here (default: a temp file; the "
        "replay journal is itself replayable)",
    )
    rl.add_argument(
        "--json",
        action="store_true",
        help="print the machine-readable replay report object",
    )
    rf = sub.add_parser(
        "roofline",
        help="per-stage MFU / HBM-bandwidth attribution with "
        "compute-vs-memory-bound verdicts and the predicted fused-block "
        "ceiling, over committed BENCH_r*.json rows (echo-aware) or a "
        "live measurement",
    )
    rf.add_argument(
        "bench",
        nargs="*",
        help="BENCH_r*.json rows (driver-wrapped, bare objects, or "
        "JSONL); last_good echoes are marked via the gate's detection "
        "and never ranked as fresh",
    )
    rf.add_argument(
        "--live",
        action="store_true",
        help="measure a per-stage breakdown NOW (observability.stages on "
        "the current backend) and attribute it — CPU runs are judged "
        "against an assumed spec, and say so",
    )
    rf.add_argument("--batch", type=int, default=4, help="live batch size")
    rf.add_argument(
        "--height", type=int, default=227, help="live input height"
    )
    rf.add_argument("--width", type=int, default=227, help="live input width")
    rf.add_argument(
        "--dtype", default="fp32", help="live dtype policy (fp32|bf16)"
    )
    rf.add_argument(
        "--repeats", type=int, default=3, help="live per-prefix chain size"
    )
    rf.add_argument(
        "--json",
        action="store_true",
        help="print machine-readable RooflineReport objects (one JSON "
        "line per view)",
    )
    hl = sub.add_parser(
        "health",
        help="fleet-health report over any journal: incident MTTR "
        "decomposition (phases sum to wall time), availability from the "
        "device-seconds capacity timeline, per-class SLO attainment with "
        "error-budget burn, and compile-cost attribution",
    )
    hl.add_argument(
        "--journal",
        required=True,
        help="a journal .jsonl file, or a directory whose *.jsonl files "
        "are folded together",
    )
    hl.add_argument(
        "--json",
        action="store_true",
        help="print the machine-readable HealthReport object",
    )
    hl.add_argument(
        "--fail-on-budget-burn",
        action="store_true",
        help="exit 3 when any SLO class has burned through its error "
        "budget (burn > 1.0) — the on_heal.sh chip-time gate mode",
    )
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.cmd == "export":
        from .export import export_trace

        src = Path(args.journal)
        if not src.exists():
            print(f"no journal at {src}", file=sys.stderr)
            return 2
        out = args.out or str(
            (src if src.is_dir() else src.with_suffix("")).with_suffix("")
        ) + ".trace.json"
        info = export_trace(src, out)
        print(
            f"Trace exported: {info['out']} events={info['events']} "
            f"spans={info['spans']} records={info['records']}"
        )
        if info["spans"] == 0:
            print(
                "note: no span records found — the timeline is the "
                "synthetic journal-order view (run with tracing wired, "
                "e.g. run --serve --serve-journal / --trace, for real "
                "timestamps)"
            )
        return 0
    if args.cmd == "report":
        from .gate import evaluate

        verdict = evaluate(args.bench)
        if args.json:
            print(json.dumps(verdict.to_obj()))
        else:
            print(verdict.render())
        if args.fail_on_regression and not verdict.ok:
            print(
                f"regression gate: FAIL ({len(verdict.regressions)} "
                f"regression(s) > {verdict.threshold:.0%})",
                file=sys.stderr,
            )
            return 3
        return 0
    if args.cmd == "replay":
        from .replay import ReplayKnobs, load_recorded_run, replay_recorded

        src = Path(args.journal)
        if not src.exists():
            print(f"no journal at {src}", file=sys.stderr)
            return 2
        try:
            recorded = load_recorded_run(src)
        except ValueError as e:
            print(f"unreplayable journal: {e}", file=sys.stderr)
            return 2
        if args.traffic_mult <= 0 or args.slo_scale <= 0:
            print("--traffic-mult/--slo-scale must be > 0", file=sys.stderr)
            return 2
        report = replay_recorded(
            recorded,
            ReplayKnobs(
                traffic_mult=args.traffic_mult,
                devices=args.devices,
                slo_scale=args.slo_scale,
                journal_path=args.journal_out,
                controller=args.controller,
            ),
        )
        if args.json:
            print(json.dumps(report.to_obj()))
        else:
            print(f"Replay: {report.summary()}")
            for line in report.class_lines():
                print(line)
            if report.controller_state is not None:
                st = report.controller_state
                print(
                    f"Replay controller: mode={st['mode']} "
                    f"level={st['level']} actions={st['actions'] or 'none'}"
                )
        if report.diverged:
            print(
                "replay divergence: a neutral replay must reproduce the "
                "recorded per-class accounting identically and land its "
                "percentiles within estimator resolution "
                "(docs/OBSERVABILITY.md)",
                file=sys.stderr,
            )
            return 3
        return 0
    if args.cmd == "roofline":
        return _roofline_main(args)
    if args.cmd == "health":
        from .export import load_records
        from .health import health_from_records

        src = Path(args.journal)
        if not src.exists():
            print(f"no journal at {src}", file=sys.stderr)
            return 2
        records = load_records(src)
        if not records:
            print(f"empty journal at {src}", file=sys.stderr)
            return 2
        report = health_from_records(records)
        if args.json:
            print(json.dumps(report.to_obj()))
        else:
            print(report.render())
        if args.fail_on_budget_burn and report.budget_blown:
            from .health import ERROR_BUDGET

            blown = [c.name or "(default)" for c in report.classes if c.blown]
            print(
                f"health gate: FAIL — error budget blown for class(es) "
                f"{', '.join(blown)} (burn > 1.0x of the "
                f"{ERROR_BUDGET:.0%} violation budget)",
                file=sys.stderr,
            )
            return 3
        return 0


def _roofline_main(args) -> int:
    """``roofline`` subcommand: ranked per-stage tables over committed
    BENCH rows (gate-classified, echoes marked and never ranked as
    fresh) or a live breakdown measurement."""
    rendered = 0
    # Row-per-line artifacts (perf/bench_tuned_*.jsonl — one row PER
    # config) render every row; round files go through the gate's
    # classifier so echoes are marked.
    jsonl = [p for p in args.bench if str(p).endswith(".jsonl")]
    rounds_paths = [p for p in args.bench if p not in jsonl]
    for path in jsonl:
        try:
            lines = Path(path).read_text().splitlines()
        except OSError as e:
            print(f"cannot read {path}: {e}", file=sys.stderr)
            return 2
        from .roofline import roofline_from_bench_row

        for i, line in enumerate(lines):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            for rep in roofline_from_bench_row(obj):
                rendered += 1
                rep.label = f"{obj.get('config', '')} {rep.label}".strip()
                if args.json:
                    print(json.dumps({"row": f"{path}:{i + 1}", **rep.to_obj()}))
                else:
                    print(f"== {path}:{i + 1}")
                    print(rep.render())
    if rounds_paths:
        from .gate import load_rounds
        from .roofline import roofline_from_bench_row

        rounds = load_rounds(rounds_paths)
        if not rounds:
            print("no parseable BENCH rows", file=sys.stderr)
            return 2
        for rr in rounds:
            print(f"== {rr.name}: {rr.provenance}")
            if rr.is_echo:
                # The gate's echo detection, reused: a wedged round
                # re-reporting an earlier round's number is marked and
                # skipped — ranking it would double-count stale evidence.
                print(
                    f"   echo of {rr.echo_of} — stale carry, not ranked"
                )
                continue
            reports = roofline_from_bench_row(rr.row)
            if not reports:
                print("   no measurable roofline view (error-only round)")
                continue
            for rep in reports:
                rendered += 1
                if args.json:
                    print(json.dumps({"round": rr.name, **rep.to_obj()}))
                else:
                    print(rep.render())
    if args.live:
        import jax

        from ..models.alexnet import BLOCKS12
        from ..models.init import deterministic_input, init_params_deterministic
        from .roofline import attribute_roofline
        from .stages import attribute_stages

        import dataclasses as _dc

        cfg = _dc.replace(
            BLOCKS12, in_height=args.height, in_width=args.width
        )
        att = attribute_stages(
            init_params_deterministic(cfg),
            deterministic_input(args.batch, cfg),
            cfg,
            compute=args.dtype,
            repeats=args.repeats,
            warmup=1,
        )
        device = jax.devices()[0]
        rep = attribute_roofline(
            dict(att.stages),
            dtype=args.dtype,
            batch=args.batch,
            device_kind=device.device_kind,
            cfg=cfg,
            source="breakdown",
            total_ms=att.total_ms,
            label=f"live {device.platform}",
        )
        rendered += 1
        print(json.dumps(rep.to_obj()) if args.json else rep.render())
    if not args.bench and not args.live:
        print("roofline: name BENCH rows and/or pass --live", file=sys.stderr)
        return 2
    return 0 if rendered else 2


if __name__ == "__main__":
    raise SystemExit(main())

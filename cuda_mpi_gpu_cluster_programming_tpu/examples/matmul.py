"""Distributed matrix multiply with self-verification (the hw1 analogue).

The reference's ``homeworks/hw1/src/template.c`` is a row-scatter MPI matmul:
root scatters rows of A point-to-point (template.c:120-129), broadcasts B
(:132), each rank computes its row block (:195-208), root gathers (:138-146)
and verifies against a serial recompute with tolerance 1e-6 (:220-238). The
course's three homework variants map communication styles: HW1 point-to-point,
HW2 collective, HW3 one-sided.

Here the same study is expressed TPU-natively as three strategies over a 1-D
device mesh — all computing C = A @ B with A row-sharded:

- ``scatter``    (HW1 analogue): explicit ``shard_map`` — A sharded over the
  mesh axis, B fully replicated (the Bcast), local MXU ``dot``, output left
  row-sharded (the gather is the sharded→replicated ``jax.device_get``).
- ``collective`` (HW2 analogue): no manual comms at all — ``jit`` with
  ``NamedSharding`` annotations; XLA chooses and inserts the collectives.
- ``ring``       (HW3 analogue): B stays sharded along its contraction axis;
  each step multiplies the resident block and rotates B one neighbor over ICI
  via ``ppermute`` — the device-initiated-transfer analogue of one-sided RMA,
  and the standard TPU ring-matmul building block.

Initialization matches the reference (integers 0-9, template.c:211-216), which
makes fp32 arithmetic *exact* for n <= 4096 (products <= 81, row sums
<= 4096*81 < 2^24), so the reference's 1e-6 tolerance (:222) is meaningful on
TPU without fp64.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel.compat import shard_map
from ..parallel.mesh import make_mesh

MAXDIM = 1 << 12  # 4096 (template.c:20)
TOLERANCE = 1e-6  # template.c:222
STRATEGIES = ("scatter", "collective", "ring")


def validate_n(n: int, num_shards: int) -> int:
    """Reference argument contract: positive power of two (template.c:48-55),
    clamped to MAXDIM (:56-63), divisible by the process count (:65-72)."""
    if n <= 0 or (n & (n - 1)) != 0:
        raise ValueError(f"matrix dimension n ({n}) must be a positive power of two")
    if n > MAXDIM:
        n = MAXDIM
    if n % num_shards != 0:
        raise ValueError(
            f"matrix dimension n ({n}) must be divisible by the shard count ({num_shards})"
        )
    return n


def init_data(key: jax.Array, n: int) -> jax.Array:
    """Random integers 0-9 as floats (template.c:211-216) — exact in fp32."""
    return jax.random.randint(key, (n, n), 0, 10).astype(jnp.float32)


def mat_mult_serial(a: jax.Array, b: jax.Array) -> jax.Array:
    """Single-device verification oracle (template.c:195-208 with my_work=n)."""
    return jax.lax.dot(a, b, precision=jax.lax.Precision.HIGHEST)


def _local_dot(a_blk: jax.Array, b: jax.Array) -> jax.Array:
    return jax.lax.dot(a_blk, b, precision=jax.lax.Precision.HIGHEST)


@functools.cache
def _build_scatter(mesh: Mesh, axis: str):
    return jax.jit(
        shard_map(
            _local_dot,
            mesh=mesh,
            in_specs=(P(axis, None), P(None, None)),
            out_specs=P(axis, None),
        )
    )


def mat_mult_scatter(a: jax.Array, b: jax.Array, mesh: Mesh, axis: str = "sp") -> jax.Array:
    """HW1 analogue: explicit row scatter + replicated B + local MXU dot."""
    return _build_scatter(mesh, axis)(a, b)


@functools.cache
def _build_collective(mesh: Mesh, axis: str):
    return jax.jit(_local_dot, out_shardings=NamedSharding(mesh, P(axis, None)))


def mat_mult_collective(a: jax.Array, b: jax.Array, mesh: Mesh, axis: str = "sp") -> jax.Array:
    """HW2 analogue: sharding annotations only; XLA inserts the collectives."""
    a = jax.device_put(a, NamedSharding(mesh, P(axis, None)))
    b = jax.device_put(b, NamedSharding(mesh, P(None, None)))
    return _build_collective(mesh, axis)(a, b)


@functools.cache
def _build_ring(mesh: Mesh, axis: str):
    n_shards = mesh.shape[axis]

    def local(a_blk: jax.Array, b_blk: jax.Array) -> jax.Array:
        blk = a_blk.shape[1] // n_shards
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]

        def step(s, carry):
            acc, b_cur = carry
            owner = (idx + s) % n_shards  # whose k-block is resident now
            a_cols = jax.lax.dynamic_slice_in_dim(a_blk, owner * blk, blk, axis=1)
            acc = acc + jax.lax.dot(a_cols, b_cur, precision=jax.lax.Precision.HIGHEST)
            b_nxt = jax.lax.ppermute(b_cur, axis, perm)
            return acc, b_nxt

        # The carry must be marked device-varying over the mesh axis up front
        # (the ppermute output is), or the fori_loop carry types mismatch.
        # parallel.compat.to_varying: lax.pcast where available, identity on
        # releases whose rep system has no varying annotation.
        from ..parallel.compat import to_varying

        acc = to_varying((axis,))(
            jnp.zeros((a_blk.shape[0], b_blk.shape[1]), a_blk.dtype)
        )
        acc, _ = jax.lax.fori_loop(0, n_shards, step, (acc, b_blk))
        return acc

    return jax.jit(
        shard_map(
            local,
            mesh=mesh,
            in_specs=(P(axis, None), P(axis, None)),
            out_specs=P(axis, None),
        )
    )


def mat_mult_ring(a: jax.Array, b: jax.Array, mesh: Mesh, axis: str = "sp") -> jax.Array:
    """HW3 analogue: B stays k-sharded; blocks rotate over ICI via ppermute.

    Device d holds A rows block d and B k-block d. At step s it multiplies
    its A columns [owner*blk : (owner+1)*blk] against the resident B block,
    then passes the block to its ring predecessor — n_shards steps, each a
    dense MXU matmul overlapped with a neighbor transfer.
    """
    return _build_ring(mesh, axis)(a, b)


_IMPLS = {
    "scatter": mat_mult_scatter,
    "collective": mat_mult_collective,
    "ring": mat_mult_ring,
}


def mat_mult_distributed(
    a: jax.Array,
    b: jax.Array,
    n_shards: int,
    strategy: str = "scatter",
    mesh: Optional[Mesh] = None,
) -> jax.Array:
    if strategy not in _IMPLS:
        raise ValueError(f"unknown strategy {strategy!r}; choose from {STRATEGIES}")
    mesh = mesh or make_mesh(n_shards)
    # The shard axis is the innermost mesh axis, whatever the caller named it.
    return _IMPLS[strategy](a, b, mesh, axis=mesh.axis_names[-1])


def check_result(c: jax.Array, d: jax.Array, tolerance: float = TOLERANCE) -> bool:
    """Epsilon compare (template.c:220-238). True = mismatch (their flag)."""
    return bool(jnp.max(jnp.abs(c - d)) > tolerance)


def main(argv=None) -> int:
    import argparse
    import time

    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.examples.matmul")
    p.add_argument("n", type=int, nargs="?", default=64, help="matrix dimension (power of two)")
    p.add_argument("--shards", type=int, default=1, help="row-shard count (mpirun -np analogue)")
    p.add_argument("--strategy", choices=STRATEGIES, default="scatter")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    try:
        n = validate_n(args.n, args.shards)
    except ValueError as e:
        print(f"Error: {e}")
        return 1
    if n != args.n:
        print(f"Warning: n ({args.n}) exceeds MAXDIM ({MAXDIM}). Clamping to MAXDIM.")

    my_work = n // args.shards
    print(f"pid=0: num_procs={args.shards} n={n} my_work={my_work} (rows per proc)")

    ka, kb = jax.random.split(jax.random.PRNGKey(args.seed))
    a, b = init_data(ka, n), init_data(kb, n)

    # Warm-up compile outside the timed region (the reference times only the
    # distribute+compute+gather phase, after MPI_Barrier — template.c:114-116).
    c = jax.block_until_ready(mat_mult_distributed(a, b, args.shards, args.strategy))
    t0 = time.perf_counter()
    c = jax.block_until_ready(mat_mult_distributed(a, b, args.shards, args.strategy))
    elapsed = time.perf_counter() - t0
    print(f"pid=0: Parallel computation finished in {elapsed:f} seconds.")

    print("pid=0: Performing serial computation for verification...")
    d = jax.block_until_ready(mat_mult_serial(a, b))
    t0 = time.perf_counter()
    d = jax.block_until_ready(mat_mult_serial(a, b))
    print(f"pid=0: Serial computation finished in {time.perf_counter() - t0:f} seconds.")

    if check_result(c, d):
        print("--------------------------------------")
        print("pid=0: Test: FAILED")
        print("--------------------------------------")
        return 1
    print("--------------------------------------")
    print("pid=0: Test: PASSED")
    print(f"pid=0: Total PARALLEL time: {elapsed:f} seconds.")
    print("--------------------------------------")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Byte-LM training demo: the transformer family end-to-end on any mesh.

Trains the tiny decoder-only LM on a synthetic repeating-byte corpus until
the pattern is memorized — the long-context analogue of the matmul example's
self-verification: loss must fall below a threshold or the run FAILs.

    python -m cuda_mpi_gpu_cluster_programming_tpu.examples.lm \
        --steps 40 --seq-len 128 --attn flash
    python -m cuda_mpi_gpu_cluster_programming_tpu.examples.lm \
        --attn ring --shards 8 --fake-devices 8
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.examples.lm")
    p.add_argument("--steps", type=int, default=40)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=128, help="training context length")
    p.add_argument("--attn", choices=["reference", "flash", "ring", "ulysses"], default="reference")
    p.add_argument("--shards", type=int, default=1, help="sp shards for ring/ulysses")
    p.add_argument(
        "--sp-engine",
        choices=["einsum", "flash"],
        default="einsum",
        help="within-shard engine for ring/ulysses (both train: ulysses via "
        "the whole-sequence VJP, ring via the joint (out, lse) VJP)",
    )
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--period", type=int, default=8, help="repeating-pattern period")
    p.add_argument(
        "--experts", type=int, default=0,
        help="MoE experts per FFN (0 = dense); expert axis ep-shards over the "
        "devices when the device count divides it, else runs replicated",
    )
    p.add_argument(
        "--pp-stages", type=int, default=0,
        help="pipeline stages for the decoder stack (0 = no pipeline)",
    )
    p.add_argument("--microbatches", type=int, default=2, help="pp microbatches")
    p.add_argument(
        "--fsdp", action="store_true",
        help="ZeRO/FSDP: shard params+optimizer over 'dp' and split the "
        "batch over the same axis. Composes with --attn ring/ulysses "
        "--shards N on a (dp, sp) mesh (dp = devices/N). Not combinable "
        "with --pp-stages or ep-sharded --experts (those compositions "
        "live in the library/tests)",
    )
    p.add_argument(
        "--remat", action="store_true",
        help="rematerialize each decoder block (jax.checkpoint): activation "
        "memory O(1) in depth at ~1 extra forward of FLOPs",
    )
    p.add_argument(
        "--compute", choices=["fp32", "bf16"], default="fp32",
        help="bf16 = mixed precision: forward/backward in bfloat16 (native "
        "MXU), fp32 master weights + optimizer",
    )
    p.add_argument("--accum-steps", type=int, default=1,
                   help="gradient-accumulation microbatches per optimizer step")
    p.add_argument(
        "--generate", type=int, default=0, metavar="N",
        help="after training, greedy-decode N tokens from the first 16 of "
        "the pattern via the KV-cache path and verify the continuation",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--target-loss", type=float, default=1.0, help="PASS threshold")
    p.add_argument("--save-params", help="save trained params to this .npz")
    p.add_argument("--resume", help="load initial params from this .npz checkpoint")
    p.add_argument("--fake-devices", type=int, default=0)
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.steps < 1:
        print(f"--steps must be >= 1, got {args.steps}", file=sys.stderr)
        return 2
    if args.fake_devices:
        from ..utils.env_info import force_virtual_cpu

        force_virtual_cpu(args.fake_devices)
    import jax
    import jax.numpy as jnp

    from ..models.transformer import TINY_LM, init_transformer, make_lm_train_step

    # Argument-compatibility checks: fail with a clean rc=2 here instead of a
    # raw traceback from inside jit tracing (advisor finding, round 1).
    from ..ops.flash_attention import flash_block

    def flash_len_err(flag: str):
        bq = flash_block(args.seq_len)
        if args.seq_len % bq:
            return f"{flag} needs --seq-len divisible by {bq} (got {args.seq_len})"
        return None

    err = None
    if args.attn == "flash":
        err = flash_len_err("--attn flash")
    elif args.attn in ("ring", "ulysses"):
        if args.shards < 1:
            err = f"--shards must be >= 1, got {args.shards}"
        elif args.seq_len % args.shards:
            err = f"--attn {args.attn} needs --seq-len divisible by --shards ({args.seq_len} % {args.shards} != 0)"
        elif args.attn == "ulysses" and TINY_LM.n_heads % args.shards:
            err = f"--attn ulysses needs --shards dividing n_heads={TINY_LM.n_heads} (got {args.shards})"
        elif args.shards > jax.device_count():
            err = (
                f"--shards {args.shards} exceeds {jax.device_count()} available "
                f"device(s) (use --fake-devices N on CPU)"
            )
        elif args.sp_engine == "flash":
            if args.attn == "ring":
                # ring+flash trains (joint (out, lse) VJP); its divisibility
                # rule is per-shard: each hop's block is L/shards rows.
                lb = args.seq_len // args.shards
                if lb % flash_block(lb):
                    err = (
                        f"--sp-engine flash with --attn ring needs the "
                        f"per-shard block (seq-len/shards = {lb}) to divide "
                        f"by the flash block ({flash_block(lb)})"
                    )
            else:  # ulysses: local flash attends the FULL sequence
                err = flash_len_err("--sp-engine flash")
    if err is not None:
        print(err, file=sys.stderr)
        return 2

    # PP argument guards — clean rc=2, same policy as the --shards checks.
    if args.pp_stages:
        if TINY_LM.n_layers % args.pp_stages:
            err = f"--pp-stages must divide n_layers={TINY_LM.n_layers}, got {args.pp_stages}"
        elif args.pp_stages > jax.device_count():
            err = (
                f"--pp-stages {args.pp_stages} exceeds {jax.device_count()} available "
                f"device(s) (use --fake-devices N on CPU)"
            )
        elif args.microbatches < 1 or args.batch % args.microbatches:
            err = (
                f"--microbatches must divide --batch "
                f"({args.batch} % {args.microbatches} != 0)"
            )
        if err is not None:
            print(err, file=sys.stderr)
            return 2
    # FSDP argument guards (clean rc=2 policy). With ring/ulysses the run
    # uses a (dp, sp) mesh — dp = devices/shards — so the guards check the
    # composed geometry, not a blanket ban (round-4 verdict weak item 3).
    if args.fsdp:
        n_dev = jax.device_count()
        sp = args.shards if args.attn in ("ring", "ulysses") else 1
        if args.pp_stages:
            err = "--fsdp is not combinable with --pp-stages"
        elif args.experts and n_dev > 1 and args.experts % n_dev == 0:
            err = "--fsdp is not combinable with ep-sharded --experts"
        elif n_dev % sp:
            err = (
                f"--fsdp with --attn {args.attn} --shards {sp} needs the "
                f"device count ({n_dev}) divisible by the sp shards"
            )
        elif args.batch % (n_dev // sp):
            err = (
                f"--fsdp needs --batch divisible by the dp axis "
                f"({n_dev}//{sp} = {n_dev // sp} device(s))"
            )
        if err is not None:
            print(err, file=sys.stderr)
            return 2
    # Accum / generate guards — BEFORE any param init, checkpoint load, or
    # device placement (pre-work clean-rc=2 policy, like every guard above).
    if args.accum_steps < 1:
        print(f"--accum-steps must be >= 1, got {args.accum_steps}", file=sys.stderr)
        return 2
    if args.batch % args.accum_steps:
        print(
            f"--accum-steps must divide --batch "
            f"({args.batch} % {args.accum_steps} != 0)",
            file=sys.stderr,
        )
        return 2
    if args.pp_stages and (args.batch // args.accum_steps) % args.microbatches:
        # The scan hands batch/accum rows to the pipeline loss, which then
        # splits by --microbatches — guard the composition here or it
        # surfaces as a raw trace-time ValueError.
        print(
            f"--accum-steps {args.accum_steps} with --pp-stages leaves "
            f"microbatches of {args.batch // args.accum_steps} rows, not "
            f"divisible by --microbatches {args.microbatches}",
            file=sys.stderr,
        )
        return 2
    eff_max_len = max(TINY_LM.max_len, args.seq_len)
    if args.generate > 0:
        plen = min(16, args.seq_len)
        if plen + args.generate > eff_max_len:
            print(
                f"--generate {args.generate} exceeds max_len "
                f"{eff_max_len} - prompt {plen}",
                file=sys.stderr,
            )
            return 2
    cfg = dataclasses.replace(
        TINY_LM,
        attn_impl=args.attn,
        attn_engine=args.sp_engine,
        sp_shards=args.shards,
        max_len=eff_max_len,
        n_experts=args.experts,
        remat=args.remat,
    )
    if args.resume:
        from ..utils.checkpoint import load_params_npz

        like = init_transformer(jax.random.PRNGKey(args.seed), cfg)
        try:
            params = load_params_npz(args.resume, like=like)
        except KeyError as e:
            # Structurally different config (dense checkpoint + --experts,
            # etc.): the archive is missing leaves the like-tree expects.
            print(
                f"--resume {args.resume} does not match this run's config: {e}",
                file=sys.stderr,
            )
            return 2
        # Pre-flight shape check (clean rc=2 policy): a checkpoint saved
        # under a different config (seq-len > saved max_len, different
        # --experts, ...) must not surface as a jit broadcast traceback.
        mismatches = [
            f"{jax.tree_util.keystr(path)}: checkpoint {tuple(got.shape)} "
            f"vs config {tuple(want.shape)}"
            for (path, got), (_, want) in zip(
                jax.tree_util.tree_leaves_with_path(params),
                jax.tree_util.tree_leaves_with_path(like),
            )
            if tuple(got.shape) != tuple(want.shape)
        ]
        if mismatches:
            print(
                f"--resume {args.resume} does not match this run's config:\n  "
                + "\n  ".join(mismatches[:8]),
                file=sys.stderr,
            )
            return 2
        print(f"Resumed params from {args.resume}")
    else:
        params = init_transformer(jax.random.PRNGKey(args.seed), cfg)
    # Expert parallelism: when the device count divides the expert count,
    # shard the expert axis over an "ep" mesh (GSPMD inserts the
    # all-to-alls). Otherwise the MoE runs replicated (single device).
    ep_note = ""
    if args.experts and not args.pp_stages:
        n_dev = jax.device_count()
        if n_dev > 1 and args.experts % n_dev == 0:
            from ..parallel.expert import shard_moe_params
            from ..parallel.mesh import make_mesh

            params = shard_moe_params(params, make_mesh(n_dev, axis_name="ep"))
            ep_note = f", ep-sharded over {n_dev} devices"
    # +1 token so the next-token shift keeps L divisible by the sp shards.
    base = jnp.arange(args.seq_len + 1, dtype=jnp.int32) % args.period
    tokens = jnp.tile(base[None], (args.batch, 1))

    fsdp_note = ""
    fsdp_mesh = None
    if args.fsdp:
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..parallel.fsdp import shard_params_fsdp, sharded_fraction
        from ..parallel.mesh import make_mesh

        n_dev = jax.device_count()
        if args.attn in ("ring", "ulysses") and args.shards > 1:
            # Composed (dp, sp) mesh: params/batch FSDP-shard over dp, the
            # sequence axis rides sp inside the forward (the composition
            # tests/test_fsdp.py proves; geometry pre-validated above).
            dp = n_dev // args.shards
            fsdp_mesh = Mesh(
                np.array(jax.devices()).reshape(dp, args.shards), ("dp", "sp")
            )
            mesh_note = f"(dp={dp}) x sp={args.shards}"
        else:
            dp = n_dev
            fsdp_mesh = make_mesh(n_dev, axis_name="dp")
            mesh_note = f"over {n_dev} devices"
        params = shard_params_fsdp(params, fsdp_mesh)
        tokens = jax.device_put(tokens, NamedSharding(fsdp_mesh, P("dp")))
        fsdp_note = (
            f", fsdp {mesh_note} "
            f"({sharded_fraction(params):.0%} of param bytes sharded)"
        )

    extras = (
        (f", experts={cfg.n_experts}{ep_note}" if cfg.n_experts else "")
        + (f", pp={args.pp_stages}x{args.microbatches}mb" if args.pp_stages else "")
        + fsdp_note
        + (", remat" if args.remat else "")
        + (", bf16-mixed" if args.compute == "bf16" else "")
        + (f", accum={args.accum_steps}" if args.accum_steps > 1 else "")
    )
    print(
        f"--- Byte-LM training [{args.attn}] (shards={args.shards}, "
        f"L={args.seq_len}, batch={args.batch}, layers={cfg.n_layers}, "
        f"d={cfg.d_model}{extras}) ---"
    )
    print(f"Devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    step_kw = dict(  # accum/generate guards ran pre-work, with the others
        lr=args.lr,
        accum_steps=args.accum_steps,
        compute_dtype=jnp.bfloat16 if args.compute == "bf16" else None,
    )
    if args.pp_stages:
        # Pipeline the decoder stack: same loss through the shared step
        # factory, staged GPipe schedule inside the loss.
        from ..parallel.mesh import make_mesh
        from ..parallel.pipeline import pipeline_lm_loss

        pp_mesh = make_mesh(args.pp_stages, axis_name="pp")
        opt_init, step = make_lm_train_step(
            cfg,
            loss_fn=lambda p, t: pipeline_lm_loss(
                p, t, cfg, n_stages=args.pp_stages,
                n_microbatches=args.microbatches, mesh=pp_mesh,
            ),
            **step_kw,
        )
    else:
        # The composed-mesh fsdp run must hand ITS mesh to the step factory
        # so ring/ulysses shard_map binds the same "sp" axis GSPMD uses for
        # the dp gradient all-reduce.
        opt_init, step = make_lm_train_step(cfg, mesh=fsdp_mesh, **step_kw)
    opt_state = opt_init(params)
    first = last = None
    t0 = time.perf_counter()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens)
        last = float(loss)
        if first is None:
            first = last
        if (i + 1) % 10 == 0 or i == 0:
            print(f"Step {i + 1}/{args.steps}: loss = {last:.4f}")
    wall = time.perf_counter() - t0
    tok_s = args.steps * args.batch * args.seq_len / wall
    print(f"Training completed in {wall * 1e3:.1f} ms ({tok_s:.0f} tok/s)")
    if args.save_params:
        from ..utils.checkpoint import save_params_npz

        save_params_npz(args.save_params, params)
        print(f"Saved params to {args.save_params}")
    ok = last <= args.target_loss
    print(
        f"Verification: loss {first:.4f} -> {last:.4f} "
        f"(target {args.target_loss}) -> {'PASSED' if ok else 'FAILED'}"
    )
    if args.generate > 0:
        # MoE configs serve too: capacity-∞ routing (models.transformer
        # ._moe_ffn_decode) — identical to training whenever nothing was
        # dropped, which a memorized repeating pattern satisfies.
        from ..models.transformer import generate as lm_generate

        plen = min(16, args.seq_len)  # length pre-validated above
        seq = lm_generate(params, tokens[:1, :plen], cfg, steps=args.generate)
        got = [int(v) for v in seq[0, plen:]]
        want = [int((plen + i) % args.period) for i in range(args.generate)]
        gen_ok = got == want
        print(f"Generated {args.generate} tokens: {got[:24]}")
        print(f"Generation continuation: {'PASSED' if gen_ok else 'FAILED'}")
        ok = ok and gen_ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())

"""Long-context attention demo/bench: ring vs Ulysses vs single-device.

The long-context analogue of the reference's staged comparison story: one
workload, multiple parallelization strategies, machine-parseable output
lines for the harness/analysis pipeline (the stdout contract of
scripts/common_test_utils.sh:296-317 applied to sequence parallelism).

    python -m cuda_mpi_gpu_cluster_programming_tpu.examples.long_context \
        --seq-len 4096 --shards 8 --strategy ring

With ring attention each device keeps only ``L/n`` of the sequence; the
printed per-device KV-residency line makes the memory-scaling story visible
the same way the reference's speedup tables make its comm story visible.
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cuda_mpi_gpu_cluster_programming_tpu.examples.long_context"
    )
    p.add_argument("--batch", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=4096)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--head-dim", type=int, default=64)
    p.add_argument("--shards", type=int, default=1)
    p.add_argument(
        "--strategy",
        choices=["single", "flash", "ring", "ulysses"],
        default="ring",
        help="single = O(L^2) reference op; flash = fused Pallas kernel; "
        "ring/ulysses = sequence-parallel over the mesh",
    )
    p.add_argument("--causal", action="store_true", default=True)
    p.add_argument("--no-causal", dest="causal", action="store_false")
    p.add_argument("--dtype", choices=["fp32", "bf16"], default="fp32")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--repeats", type=int, default=10)
    p.add_argument("--warmup", type=int, default=3)
    p.add_argument(
        "--engine",
        choices=["einsum", "flash"],
        default="einsum",
        help="within-shard engine for ring/ulysses: einsum = XLA score "
        "blocks; flash = Pallas flash kernel per hop/shard (O(block) "
        "memory) — both differentiable (ring+flash via the joint "
        "(out, lse) VJP)",
    )
    p.add_argument(
        "--verify",
        action="store_true",
        help="also run the single-device oracle and report max |delta| "
        "(hw1-style self-verification, homeworks/hw1/src/template.c:149-176)",
    )
    p.add_argument(
        "--fake-devices",
        type=int,
        default=0,
        help="use N virtual CPU devices (mpirun --oversubscribe analogue)",
    )
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.fake_devices:
        from ..utils.env_info import force_virtual_cpu

        force_virtual_cpu(args.fake_devices)

    from ..ops.attention import attention
    from ..parallel.sequence_parallel import ring_attention, ulysses_attention
    from ..utils.timing import amortized_ms

    dtype = jnp.float32 if args.dtype == "fp32" else jnp.bfloat16
    shape = (args.batch, args.seq_len, args.heads, args.head_dim)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(args.seed), 3)
    q = jax.random.normal(kq, shape, dtype)
    k = jax.random.normal(kk, shape, dtype)
    v = jax.random.normal(kv, shape, dtype)

    if args.strategy == "single":
        fn = jax.jit(lambda q, k, v: attention(q, k, v, causal=args.causal))
    elif args.strategy == "flash":
        from ..ops.flash_attention import flash_attention

        fn = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=args.causal))
    elif args.strategy == "ring":
        fn = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, n_shards=args.shards, causal=args.causal,
                engine=args.engine,
            )
        )
    else:
        fn = jax.jit(
            lambda q, k, v: ulysses_attention(
                q, k, v, n_shards=args.shards, causal=args.causal,
                engine=args.engine,
            )
        )

    print(
        f"--- Long-context attention [{args.strategy}] "
        f"(shards={args.shards}, L={args.seq_len}, B={args.batch}, "
        f"H={args.heads}, D={args.head_dim}, {args.dtype}, "
        f"causal={args.causal}) ---"
    )
    print(f"Devices: {jax.device_count()} x {jax.devices()[0].device_kind}")
    # Per-device KV residency: ring keeps L/n tokens x all heads; ulysses
    # keeps all L tokens x H/n heads (post all_to_all); single keeps it all.
    kv_tokens = args.seq_len // (args.shards if args.strategy == "ring" else 1)
    kv_heads = args.heads // (args.shards if args.strategy == "ulysses" else 1)
    bytes_per = 2 * args.batch * kv_tokens * kv_heads * args.head_dim * q.dtype.itemsize
    print(
        f"KV resident per device: {kv_tokens} tokens x {kv_heads} heads "
        f"({bytes_per / 2**20:.2f} MiB)"
    )

    out = jax.block_until_ready(fn(q, k, v))
    n_small = max(1, args.warmup)
    ms = amortized_ms(fn, q, k, v, n_small=n_small, n_large=n_small + max(1, args.repeats))
    toks = args.batch * args.seq_len / (ms / 1e3)
    print(f"Final Output Shape: {'x'.join(str(d) for d in out.shape)}")
    flat = np.asarray(out[0, :, 0, :], np.float32).reshape(-1)
    print("Final Output (first 10 values): " + " ".join(f"{x:.4f}" for x in flat[:10]))
    print(f"Attention completed in {ms:.3f} ms ({toks:.0f} tok/s)")

    if args.verify:
        want = np.asarray(attention(q, k, v, causal=args.causal), np.float32)
        delta = float(np.max(np.abs(want - np.asarray(out, np.float32))))
        tol = 1e-4 if args.dtype == "fp32" else 3e-2
        ok = delta <= tol
        print(f"Verification: max|delta| = {delta:.2e} (tol {tol:.0e}) -> "
              f"{'PASSED' if ok else 'FAILED'}")
        return 0 if ok else 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

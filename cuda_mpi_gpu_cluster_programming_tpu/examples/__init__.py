"""Worked distributed-programming examples (the homeworks/ analogue)."""

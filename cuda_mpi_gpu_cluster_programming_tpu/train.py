"""Training-loop CLI: synthetic teacher-student training on any mesh.

The reference is inference-only (SURVEY §5.4: "nothing to save"); this loop
is the framework's training tier wired end-to-end: the native C++ data
pipeline feeds batches, the distributed train step (dp and/or sp axes) fits
a randomly-initialized student to a fixed deterministic teacher's outputs,
loss is printed per step in a machine-parseable line, and weights checkpoint
to npz so runs can resume.

    python -m cuda_mpi_gpu_cluster_programming_tpu.train --steps 20 --batch 8
    python -m cuda_mpi_gpu_cluster_programming_tpu.train --sp 8 --fake-devices 8
"""

from __future__ import annotations

import argparse
import sys
import time


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.train")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--optimizer", choices=["sgd", "adam"], default="sgd")
    p.add_argument("--dp", type=int, default=1, help="data-parallel mesh axis size")
    p.add_argument("--sp", type=int, default=0, help="spatial/context-parallel shards (0 = off)")
    p.add_argument("--tp", type=int, default=0, help="tensor-parallel (K-axis) shards (0 = off)")
    p.add_argument("--remat", action="store_true", help="rematerialize activations in backward")
    p.add_argument("--height", type=int, default=63, help="input H (default small for fast demo)")
    p.add_argument("--width", type=int, default=63)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--loader-workers", type=int, default=2)
    p.add_argument("--checkpoint", help="save trained params to this .npz")
    p.add_argument("--resume", help="initialize student from this .npz")
    p.add_argument(
        "--fake-devices",
        type=int,
        default=0,
        help="run on N virtual CPU devices (mpirun --oversubscribe analogue)",
    )
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.steps < 1:
        print(f"--steps must be >= 1, got {args.steps}", file=sys.stderr)
        return 2
    if args.fake_devices:
        from .utils.env_info import force_virtual_cpu

        force_virtual_cpu(args.fake_devices)
    import dataclasses

    import jax
    import optax

    from . import native
    from .models.alexnet import BLOCKS12, output_shape
    from .models.init import init_params_deterministic, init_params_random
    from .parallel.mesh import make_mesh
    from .training import make_train_step

    cfg = dataclasses.replace(BLOCKS12, in_height=args.height, in_width=args.width)
    oh, ow, oc = output_shape(cfg)
    if min(oh, ow) <= 0:
        print(f"degenerate model for H={args.height} W={args.width}", file=sys.stderr)
        return 2

    if args.sp and args.tp:
        print("--sp and --tp are mutually exclusive strategies", file=sys.stderr)
        return 2
    if args.tp and args.dp > 1:
        print("--tp does not compose with --dp yet (TP builds its own 1-D mesh)", file=sys.stderr)
        return 2
    model_shards = args.sp or args.tp or 1
    n_devices_needed = max(1, args.dp) * model_shards
    if jax.device_count() < n_devices_needed:
        print(
            f"need {n_devices_needed} devices (dp={args.dp} x shards={model_shards}), "
            f"have {jax.device_count()}; use --fake-devices on CPU",
            file=sys.stderr,
        )
        return 2

    mesh = None
    if args.sp or args.dp > 1:
        mesh = make_mesh(args.sp or 1, dp=args.dp)
    opt = optax.adam(args.lr) if args.optimizer == "adam" else optax.sgd(args.lr)
    opt_init, step_fn = make_train_step(
        cfg, mesh=mesh, optimizer=opt, sp_shards=args.sp, tp_shards=args.tp,
        remat=args.remat,
    )

    teacher = init_params_deterministic(cfg)
    if args.resume:
        from .utils.checkpoint import load_params_npz

        student = load_params_npz(args.resume)
        print(f"Resumed student from {args.resume}")
    else:
        student = init_params_random(jax.random.PRNGKey(args.seed), cfg)
    opt_state = opt_init(student)

    from .configs import REGISTRY, build_forward

    teacher_fwd = build_forward(REGISTRY["v1_jit"], cfg)

    print(
        f"--- Training (teacher-student, {args.optimizer}, lr={args.lr}, "
        f"batch={args.batch}, dp={args.dp}, sp={args.sp or 'off'}, "
        f"remat={args.remat}, H={args.height}) ---"
    )
    print(f"Devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    shape = (args.batch, cfg.in_height, cfg.in_width, cfg.in_channels)
    first = last = None
    t0 = time.perf_counter()
    try:
        loader_cm = native.NativeDataLoader(
            shape, mode="uniform", seed=args.seed, workers=args.loader_workers
        )
    except RuntimeError as e:  # toolchain missing / native build broke
        print(f"cannot build native input tier: {e}", file=sys.stderr)
        return 2
    with loader_cm as loader:
        for i in range(args.steps):
            x = jax.device_put(next(loader))
            y = teacher_fwd(teacher, x)
            student, opt_state, loss = step_fn(student, opt_state, x, y)
            loss = float(loss)
            if first is None:
                first = loss
            last = loss
            print(f"Step {i + 1}/{args.steps}: loss = {loss:.6f}")
    wall = time.perf_counter() - t0
    print(
        f"Training completed in {wall * 1e3:.1f} ms "
        f"({args.steps} steps, loss {first:.6f} -> {last:.6f})"
    )

    if args.checkpoint:
        from .utils.checkpoint import save_params_npz

        save_params_npz(args.checkpoint, student)
        print(f"Saved params to {args.checkpoint}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

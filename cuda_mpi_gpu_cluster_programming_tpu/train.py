"""Training-loop CLI: synthetic teacher-student training on any mesh.

The reference is inference-only (SURVEY §5.4: "nothing to save"); this loop
is the framework's training tier wired end-to-end: the native C++ data
pipeline feeds batches, the distributed train step (dp and/or sp axes) fits
a randomly-initialized student to a fixed deterministic teacher's outputs,
loss is printed per step in a machine-parseable line, and weights checkpoint
to npz so runs can resume.

    python -m cuda_mpi_gpu_cluster_programming_tpu.train --steps 20 --batch 8
    python -m cuda_mpi_gpu_cluster_programming_tpu.train --sp 8 --fake-devices 8

Resilience (docs/RESILIENCE.md): the SDC sentinel screens every step's
loss/grad-norm/params for NaN/Inf and norm spikes (``--no-sentinel`` opts
out). ``--checkpoint-every N`` additionally makes the run preemption- and
corruption-tolerant: the training state (params + optimizer state + step)
is checkpointed atomically every N steps into ``--work-dir`` alongside a
crash-consistent journal, a sentinel trip rolls back to the last-good
checkpoint and re-enters (bounded by ``--max-rollbacks``), and relaunching
the same command resumes at the last checkpointed step. Batches in this
mode are derived per step index (identical stream to the prefetching
loader), so a resumed or rolled-back run replays exactly the batches the
uninterrupted run would have seen:

    python -m cuda_mpi_gpu_cluster_programming_tpu.train --steps 200 \\
        --checkpoint-every 20 --work-dir logs/train_work

``--supervise-steps`` additionally puts every step under the elastic
supervisor (docs/RESILIENCE.md "True elastic meshes"): a mid-step device/
mesh loss or sentinel trip degrades down the shard ladder, rebuilds the
step over the SURVIVING devices, live-reshards params+opt-state, and
replays the same batch — rollback only once the ladder is spent.
"""

from __future__ import annotations

import argparse
import sys
import time


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.train")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--optimizer", choices=["sgd", "adam"], default="sgd")
    p.add_argument("--dp", type=int, default=1, help="data-parallel mesh axis size")
    p.add_argument("--sp", type=int, default=0, help="spatial/context-parallel shards (0 = off)")
    p.add_argument("--tp", type=int, default=0, help="tensor-parallel (K-axis) shards (0 = off)")
    p.add_argument("--remat", action="store_true", help="rematerialize activations in backward")
    p.add_argument("--height", type=int, default=63, help="input H (default small for fast demo)")
    p.add_argument("--width", type=int, default=63)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--loader-workers", type=int, default=2)
    p.add_argument("--checkpoint", help="save trained params to this .npz")
    p.add_argument("--resume", help="initialize student from this .npz")
    p.add_argument(
        "--fake-devices",
        type=int,
        default=0,
        help="run on N virtual CPU devices (mpirun --oversubscribe analogue)",
    )
    p.add_argument(
        "--checkpoint-every",
        type=int,
        default=0,
        help="atomically checkpoint the full training state every N steps "
        "into --work-dir and journal progress; enables idempotent resume "
        "(relaunch the same command) and sentinel rollback (0 = off, the "
        "historical run-once behavior)",
    )
    p.add_argument(
        "--work-dir",
        default="logs/train_work",
        help="state directory for --checkpoint-every: last-good checkpoint "
        "+ crash-consistent journal.jsonl",
    )
    p.add_argument(
        "--checkpoint-shards",
        type=int,
        default=0,
        help="with --checkpoint-every: save the training state as a "
        "crash-consistent SHARDED tree (N atomic shard files + a "
        "manifest-last commit, utils.checkpoint.save_train_state_sharded) "
        "instead of one npz; a kill mid-save always leaves the last-good "
        "generation loadable (0 = single-file npz, the historical format)",
    )
    p.add_argument(
        "--supervise-steps",
        action="store_true",
        help="supervisor-managed training steps (requires --checkpoint-every; "
        "docs/RESILIENCE.md 'True elastic meshes'): a sentinel trip or a "
        "device/mesh loss DURING a step degrades down the elastic ladder "
        "(halo@n -> halo@n/2 -> ... -> single@1), rebuilds the step over "
        "the surviving-device mesh, live-reshards params+opt-state onto it "
        "(jax.device_put, no checkpoint round-trip) and replays the SAME "
        "batch — step-level replay instead of whole-checkpoint rollback; "
        "rollback remains the floor once the ladder or --max-rollbacks is "
        "exhausted. Prints one machine-parseable 'Elastic: ...' line",
    )
    p.add_argument(
        "--max-rollbacks",
        type=int,
        default=2,
        help="consecutive sentinel-trip rollbacks tolerated before aborting "
        "(the counter resets at every successful checkpoint)",
    )
    p.add_argument(
        "--no-sentinel",
        action="store_true",
        help="disable the SDC sentinel (NaN/Inf + norm-spike screening of "
        "loss/grads/params each step)",
    )
    p.add_argument(
        "--sentinel-window",
        type=int,
        default=8,
        help="rolling history length per watched scalar for spike detection",
    )
    p.add_argument(
        "--sentinel-spike",
        type=float,
        default=1e3,
        help="trip when a watched scalar exceeds this factor times its "
        "window median",
    )
    p.add_argument(
        "--oracle-every",
        type=int,
        default=0,
        help="run the golden-oracle conv spot check (tests/oracle.py) every "
        "N-th param check; a mismatch trips the sentinel (0 = off)",
    )
    return p


def _run_resilient_loop(
    args, jr, save_state, load_state, start_step, get_batch, teacher_fwd, teacher,
    step_fn, student, opt_state, sentinel, mesh, flog, sup=None,
):
    """The quarantine-capable training loop (``--checkpoint-every`` > 0).

    Every committed step is journaled; every N-th commit atomically
    checkpoints (params, opt_state, step) as the last-good state via
    ``save_state`` (single-npz or sharded-tree, per --checkpoint-shards). A
    sentinel :class:`~..resilience.sentinel.SDC` trip rolls the loop back
    to that state (``load_state``) and re-enters (the chaos
    ``sdc``/``nan_loss`` drills exercise exactly this path on CPU);
    ``--max-rollbacks`` consecutive trips without a successful checkpoint
    abort with rc 3. Returns either an exit code (int) or
    ``(first_loss, last_loss, steps_run, final_params, final_opt_state)``
    — callers must take the trained state from the return value (a
    rebound local never propagates back through an argument).

    With ``sup`` (``--supervise-steps``, an elastic
    :class:`~.resilience.supervisor.Supervisor` in step mode) the CHEAP
    recovery comes first: any trip — in-step device/mesh loss caught by
    ``supervise_step``, or a host-side sentinel trip routed through
    ``trip_external`` — degrades the ladder, live-reshards the state onto
    the surviving-device mesh, and REPLAYS the same step-indexed batch.
    Checkpoint rollback runs only once the ladder is exhausted.
    """
    import jax

    from .observability.trace import current_ids, span as obs_span
    from .resilience import chaos
    from .resilience.policy import DegradationExhausted
    from .resilience.sentinel import SDC

    first = last = None
    last_good_step = start_step
    rollbacks = 0
    steps_run = 0
    i = start_step

    def _rollback(cause: str):
        """The floor: consume one rollback, restore last-good, rewind.
        Returns rc 3 when the budget is spent, else None."""
        nonlocal rollbacks, student, opt_state, i
        rollbacks += 1
        flog.record("retry", cause=cause[:160])
        jr.append(
            "rollback", key=f"rollback:{i + 1}", step=i + 1, cause=cause[:200],
            **current_ids(),
        )
        print(
            f"{cause} -> rollback to last-good step {last_good_step} "
            f"(rollback {rollbacks}/{args.max_rollbacks})",
            flush=True,
        )
        if rollbacks > args.max_rollbacks:
            flog.record("fail", cause="rollback budget exhausted")
            print(
                f"sentinel: {args.max_rollbacks} consecutive rollbacks "
                "exhausted without progress; aborting",
                file=sys.stderr,
            )
            return 3
        student, opt_state, _ = load_state(student, opt_state)
        i = last_good_step
        return None

    while i < args.steps:
        x = get_batch(i)
        if sup is None:
            x = jax.device_put(x)
        # Supervised mode leaves the batch UNCOMMITTED: an explicit
        # device_put would pin it to the default device, which the elastic
        # floor must not assume survives (ROADMAP item 3 leftover (d)) —
        # placement follows the supervisor-resharded params instead.
        y = teacher_fwd(teacher, x)
        try:
            # One span per training step (no-op untraced): the supervisor's
            # trip->degrade->reshard->replay spans nest under it, so an
            # incident reads as one tree in the exported timeline.
            with obs_span("train.step", step=i + 1):
                if sup is not None:
                    out = sup.supervise_step(student, opt_state, x, y, step=i)
                else:
                    out = step_fn(student, opt_state, x, y)
        except DegradationExhausted as e:
            # Ladder spent mid-step: the checkpoint rollback is the floor.
            rc = _rollback(f"elastic ladder exhausted: {str(e)[:120]}")
            if rc is not None:
                return rc
            continue
        new_student, new_opt, loss = out[0], out[1], float(out[2])
        gnorm = float(out[3]) if len(out) > 3 else None
        ch = chaos.active()
        if ch is not None:
            if ch.draw("nan_loss"):
                print(f"chaos: injected nan_loss at step {i + 1}", flush=True)
                loss = float("nan")
            if ch.draw("sdc"):
                from .resilience.sentinel import inject_bit_flip

                new_student, loc = inject_bit_flip(new_student, seed=ch.spec.seed)
                print(
                    f"chaos: injected sdc bit-flip at step {i + 1} "
                    f"(leaf/elem {loc})",
                    flush=True,
                )
        try:
            if sentinel is not None:
                sentinel.check_scalar(i, loss, "loss")
                if gnorm is not None:
                    sentinel.check_scalar(i, gnorm, "grad_norm")
                sentinel.check_tree(i, new_student, "params")
                if mesh is not None:
                    sentinel.check_divergence(i, new_student, "params")
        except SDC as e:
            if sup is not None:
                # Step-level replay first: degrade, reshard the PRE-step
                # state live, re-run the same batch — no rollback consumed,
                # no checkpoint touched. The discarded new_student carries
                # whatever tripped the screen.
                try:
                    student, opt_state = sup.trip_external(e, student, opt_state)
                    print(
                        f"{e} -> elastic replay of step {i + 1} on "
                        f"{sup.entry.key} (no rollback consumed)",
                        flush=True,
                    )
                    continue
                except DegradationExhausted:
                    pass  # ladder spent: fall through to the floor
            rc = _rollback(str(e))
            if rc is not None:
                return rc
            continue
        student, opt_state = new_student, new_opt
        if first is None:
            first = loss
        last = loss
        steps_run += 1
        print(f"Step {i + 1}/{args.steps}: loss = {loss:.6f}")
        jr.append("step", key=f"step:{i + 1}", step=i + 1, loss=loss, **current_ids())
        i += 1
        if i % args.checkpoint_every == 0 or i == args.steps:
            save_state(student, opt_state, i)
            jr.append("ckpt", key=f"ckpt:{i}", step=i, **current_ids())
            last_good_step = i
            rollbacks = 0  # progress made: reset the consecutive-trip budget
        if sup is not None:
            # Grow-back check between steps: pending heals are retried
            # against a fresh device re-query, and once a rejoined device
            # graduates probation the supervisor climbs the ladder back up
            # — mid-run, with the live state resharded onto the promoted
            # rung (no restart, no checkpoint round-trip).
            promoted = sup.maybe_promote(student, opt_state)
            if promoted is not None:
                student, opt_state = promoted
                print(
                    f"Elastic promote: climbed back to {sup.entry.key} "
                    f"(pool={sup.pool.summary()})",
                    flush=True,
                )
    flog.record("ok")
    if flog.retried:
        print(f"Sentinel fault log: {flog.summary()}")
    return first, last, steps_run, student, opt_state


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    if args.steps < 1:
        print(f"--steps must be >= 1, got {args.steps}", file=sys.stderr)
        return 2
    if args.fake_devices:
        from .utils.env_info import force_virtual_cpu

        force_virtual_cpu(args.fake_devices)
    import dataclasses

    import jax
    import optax

    from . import native
    from .models.alexnet import BLOCKS12, output_shape
    from .models.init import init_params_deterministic, init_params_random
    from .parallel.mesh import make_mesh
    from .training import make_train_step

    cfg = dataclasses.replace(BLOCKS12, in_height=args.height, in_width=args.width)
    oh, ow, oc = output_shape(cfg)
    if min(oh, ow) <= 0:
        print(f"degenerate model for H={args.height} W={args.width}", file=sys.stderr)
        return 2

    if args.sp and args.tp:
        print("--sp and --tp are mutually exclusive strategies", file=sys.stderr)
        return 2
    if args.tp and args.dp > 1:
        print("--tp does not compose with --dp yet (TP builds its own 1-D mesh)", file=sys.stderr)
        return 2
    model_shards = args.sp or args.tp or 1
    n_devices_needed = max(1, args.dp) * model_shards
    if jax.device_count() < n_devices_needed:
        print(
            f"need {n_devices_needed} devices (dp={args.dp} x shards={model_shards}), "
            f"have {jax.device_count()}; use --fake-devices on CPU",
            file=sys.stderr,
        )
        return 2

    mesh = None
    if args.sp or args.dp > 1:
        mesh = make_mesh(args.sp or 1, dp=args.dp)
    sentinel = None
    if not args.no_sentinel:
        from .resilience.sentinel import SDC, Sentinel, SentinelConfig

        sentinel = Sentinel(
            SentinelConfig(
                window=args.sentinel_window,
                spike_factor=args.sentinel_spike,
                oracle_every=args.oracle_every,
            )
        )

    opt = optax.adam(args.lr) if args.optimizer == "adam" else optax.sgd(args.lr)
    opt_init, step_fn = make_train_step(
        cfg, mesh=mesh, optimizer=opt, sp_shards=args.sp, tp_shards=args.tp,
        remat=args.remat, with_grad_norm=sentinel is not None,
    )

    teacher = init_params_deterministic(cfg)
    if args.resume:
        from .utils.checkpoint import load_params_npz

        student = load_params_npz(args.resume)
        print(f"Resumed student from {args.resume}")
    else:
        student = init_params_random(jax.random.PRNGKey(args.seed), cfg)
    opt_state = opt_init(student)

    from .configs import REGISTRY, build_forward

    teacher_fwd = build_forward(REGISTRY["v1_jit"], cfg)

    print(
        f"--- Training (teacher-student, {args.optimizer}, lr={args.lr}, "
        f"batch={args.batch}, dp={args.dp}, sp={args.sp or 'off'}, "
        f"remat={args.remat}, H={args.height}) ---"
    )
    print(f"Devices: {jax.device_count()} x {jax.devices()[0].device_kind}")

    shape = (args.batch, cfg.in_height, cfg.in_width, cfg.in_channels)
    resilient = args.checkpoint_every > 0
    if args.supervise_steps and not resilient:
        print(
            "--supervise-steps requires --checkpoint-every (checkpoint "
            "rollback is the floor below the elastic ladder)",
            file=sys.stderr,
        )
        return 2

    from .resilience import chaos
    from .resilience.policy import FaultLog

    jr = None
    save_state = load_state = None
    start_step = 0
    if resilient:
        from pathlib import Path

        from .resilience.journal import Journal

        work = Path(args.work_dir)
        work.mkdir(parents=True, exist_ok=True)
        jr = Journal(work / "journal.jsonl")
        if args.checkpoint_shards > 0:
            # Crash-consistent sharded tree: N atomic shard files, manifest
            # committed last — a kill mid-save leaves the previous
            # generation loadable (docs/RESILIENCE.md).
            from .utils.checkpoint import (
                MANIFEST_NAME,
                load_train_state_sharded,
                save_train_state_sharded,
            )

            ckpt_path = work / "ckpt_last_good"
            ckpt_exists = (ckpt_path / MANIFEST_NAME).exists()

            def save_state(p, o, s):
                return save_train_state_sharded(
                    ckpt_path, p, o, s, n_shards=args.checkpoint_shards
                )

            def load_state(lp, lo):
                return load_train_state_sharded(ckpt_path, lp, lo)
        else:
            from .utils.checkpoint import load_train_state, save_train_state

            ckpt_path = work / "ckpt_last_good.npz"
            ckpt_exists = ckpt_path.exists()

            def save_state(p, o, s):
                return save_train_state(ckpt_path, p, o, s)

            def load_state(lp, lo):
                return load_train_state(ckpt_path, lp, lo)

        if ckpt_exists:
            try:
                student, opt_state, start_step = load_state(student, opt_state)
                print(f"Resumed training state from {ckpt_path} at step {start_step}")
                jr.append("resume", key=f"resume:{start_step}", step=start_step)
            except (ValueError, KeyError) as e:
                # A corrupt/mismatched checkpoint must not brick the run —
                # report it and start fresh (the atomic saver will replace it
                # at the next boundary).
                print(f"ignoring unusable checkpoint {ckpt_path}: {e}", file=sys.stderr)
        if start_step == 0:
            # The rollback target must exist BEFORE the first step so a trip
            # at step 1 has a last-good state to quarantine back to.
            save_state(student, opt_state, 0)
            jr.append("ckpt", key="ckpt:0", step=0)

    first = last = None
    t0 = time.perf_counter()
    if resilient:
        # Per-step-indexed batches (bit-identical to the loader stream:
        # batch k = fill_batch(shape, mode, batch_seed(seed, k))) so resume
        # and rollback replay exactly the batches an uninterrupted run sees.
        try:
            native.fill_batch((1, 1, 1, 1))
        except RuntimeError as e:
            print(f"cannot build native input tier: {e}", file=sys.stderr)
            return 2

        def get_batch(k: int):
            return native.fill_batch(shape, "uniform", native.batch_seed(args.seed, k))

        sup = None
        train_tracer = None
        if args.supervise_steps:
            # Spans ride the SAME work-dir journal the step/ckpt records
            # use, so one export covers the whole supervised run
            # (docs/OBSERVABILITY.md); step/rollback/ckpt records gain the
            # trace id, supervisor trips their span ids.
            from .observability.trace import Tracer, set_tracer

            train_tracer = Tracer(journal=jr)
            set_tracer(train_tracer)
            print(f"Trace: id={train_tracer.trace_id} journal={jr.path}")
            from .resilience.supervisor import Supervisor, train_ladder
            from .training import make_elastic_step_builder

            # The elastic step ladder replaces the bare step_fn: same
            # optimizer (opt-state trees stay portable across rungs), every
            # sharded rung rebuilt over the supervisor pool's SURVIVING
            # devices, trips replayed step-level before any rollback.
            sup = Supervisor(
                cfg,
                train_ladder(sp_shards=args.sp, tp_shards=args.tp),
                step_builder=make_elastic_step_builder(
                    cfg, optimizer=opt, remat=args.remat,
                    with_grad_norm=sentinel is not None,
                ),
                journal=jr,
                site="train",
            )

        try:
            rc = _run_resilient_loop(
                args, jr, save_state, load_state, start_step, get_batch, teacher_fwd,
                teacher, step_fn, student, opt_state, sentinel, mesh,
                FaultLog(site="train-sentinel"), sup=sup,
            )
        finally:
            if train_tracer is not None:
                from .observability.trace import set_tracer

                set_tracer(None)  # in-process callers must not leak a tracer
        if isinstance(rc, int):
            return rc
        # Take the TRAINED state back from the loop: --checkpoint below
        # must save what the run actually learned (the loop's locals never
        # flow back through its arguments; saving the pre-loop `student`
        # here silently exported the INITIAL params).
        first, last, steps_run, student, opt_state = rc
        if sup is not None:
            # Machine-parseable elastic summary (scripts/on_heal.sh gates
            # on 'Elastic: .*replays='): rung, trip kinds, replay count,
            # surviving pool.
            print(f"Elastic: {sup.summary()}")
            if jr is not None:
                # One-line fleet-health fold of the work-dir journal
                # (observability.health): incident MTTR, compile-cost
                # attribution for the supervised run.
                from .observability.health import health_from_journal

                try:
                    print(
                        f"Health: "
                        f"{health_from_journal(jr.path).summary_line()}"
                    )
                except Exception as e:  # noqa — evidence, not the result
                    print(f"Health: unavailable ({type(e).__name__}: {e})")
    else:
        try:
            loader_cm = native.NativeDataLoader(
                shape, mode="uniform", seed=args.seed, workers=args.loader_workers
            )
        except RuntimeError as e:  # toolchain missing / native build broke
            print(f"cannot build native input tier: {e}", file=sys.stderr)
            return 2
        with loader_cm as loader:
            for i in range(args.steps):
                x = jax.device_put(next(loader))
                y = teacher_fwd(teacher, x)
                out = step_fn(student, opt_state, x, y)
                student, opt_state, loss = out[0], out[1], float(out[2])
                gnorm = float(out[3]) if len(out) > 3 else None
                ch = chaos.active()
                if ch is not None and ch.draw("nan_loss"):
                    print(f"chaos: injected nan_loss at step {i + 1}", flush=True)
                    loss = float("nan")
                if sentinel is not None:
                    try:
                        sentinel.check_scalar(i, loss, "loss")
                        if gnorm is not None:
                            sentinel.check_scalar(i, gnorm, "grad_norm")
                    except SDC as e:  # no checkpoint: abort loudly
                        print(f"{e} (no checkpoint to roll back to; "
                              "run with --checkpoint-every)", file=sys.stderr)
                        return 3
                if first is None:
                    first = loss
                last = loss
                print(f"Step {i + 1}/{args.steps}: loss = {loss:.6f}")
        steps_run = args.steps
    wall = time.perf_counter() - t0
    if last is None:
        print(f"Training already complete at step {start_step}/{args.steps} (resumed)")
    else:
        print(
            f"Training completed in {wall * 1e3:.1f} ms "
            f"({steps_run} steps, loss {first:.6f} -> {last:.6f})"
        )

    if args.checkpoint:
        from .utils.checkpoint import save_params_npz

        save_params_npz(args.checkpoint, student)
        print(f"Saved params to {args.checkpoint}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

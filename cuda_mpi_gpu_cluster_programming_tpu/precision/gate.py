"""ToleranceGate: no lowered-precision candidate wins without beating the
fp32 oracle within budget.

The paper's staged-parallelism study treats the fp32 serial pass as ground
truth; this module is the machine-checkable form of that contract for the
precision subsystem. A gate screening runs the candidate policy and the
fp32 reference THROUGH THE SAME staged forward (per-layer taps at every
conv/pool/LRN boundary), compares each stage against its budget, and
journals a ``gate_pass``/``gate_fail`` record — the autotuner refuses to
let a non-fp32 dtype win (or even be swept) without a pass, and
``scripts/on_heal.sh`` refuses to publish a tuned non-fp32 headline row
whose gate fails on-chip.

Trust chain: before trusting the on-device fp32 forward as the oracle, the
gate preflights ``resilience.sentinel.oracle_spot_check`` — the numpy
loop-nest oracle from ``tests/oracle.py`` (the reference's serial layer
semantics, hand-checkable) must agree with the device fp32 conv first. A
device whose fp32 path is itself off (the SDC class the sentinel hunts)
fails the gate for every candidate rather than blessing a matching error.

Budgets are per-stage max-abs / max-rel pairs; ``rel`` is normalized by
the oracle stage's max-|value| (elementwise relative error explodes near
zeros — LRN outputs cross zero). ``margin`` is the fraction of budget left
(1.0 = exact, 0.0 = at budget, negative = fail): the number bench rows
carry as ``gate_margin``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List

import numpy as np

from ..models.alexnet import BLOCKS12
from .policy import DtypePolicy, jdt, resolve_policy

# Per-policy, per-stage budgets; "*" is the any-stage default. bf16 carries
# ~2^-8 operand rounding through two convs; int8w adds <= scale/2 per
# weight (~0.4% of the channel max) on top — budgets leave ~4x headroom
# over the observed CPU/TPU error so a genuine SDC or broken lowering
# (not rounding) is what trips them.
@dataclasses.dataclass(frozen=True)
class StageBudget:
    max_abs: float = math.inf
    max_rel: float = math.inf


DEFAULT_BUDGETS: Dict[str, Dict[str, StageBudget]] = {
    "fp32": {
        "*": StageBudget(max_abs=1e-4, max_rel=1e-5),
        # Block-granularity rows (the megakernel screen): the "*" budget is
        # calibrated for the oracle comparing against ITSELF through the
        # same staged ops; the fused fp32 kernel is a different lowering
        # whose fp32 MACs accumulate in a different order (~1 ulp per step,
        # observed 1.3e-4 abs through conv1's 363-term dots at the full 227
        # geometry) — still ~7x headroom below these, so a broken fusion
        # (not reassociation) is what trips them.
        "block1": StageBudget(max_abs=1e-3, max_rel=1e-4),
        "block2": StageBudget(max_abs=1e-3, max_rel=1e-4),
    },
    "bf16": {"*": StageBudget(max_rel=2e-2)},
    "int8w": {"*": StageBudget(max_rel=6e-2)},
}


@dataclasses.dataclass
class StageCheck:
    stage: str
    max_abs: float
    max_rel: float  # |cand-oracle|max / |oracle|max
    abs_budget: float
    rel_budget: float

    @property
    def passed(self) -> bool:
        return self.max_abs <= self.abs_budget and self.max_rel <= self.rel_budget

    @property
    def margin(self) -> float:
        """Fraction of budget unspent; the binding (smaller) of abs/rel."""
        m = 1.0
        if math.isfinite(self.abs_budget) and self.abs_budget > 0:
            m = min(m, 1.0 - self.max_abs / self.abs_budget)
        if math.isfinite(self.rel_budget) and self.rel_budget > 0:
            m = min(m, 1.0 - self.max_rel / self.rel_budget)
        return m

    def to_obj(self) -> dict:
        return {
            "stage": self.stage,
            "max_abs": float(self.max_abs),
            "max_rel": float(self.max_rel),
            "abs_budget": self.abs_budget if math.isfinite(self.abs_budget) else None,
            "rel_budget": self.rel_budget if math.isfinite(self.rel_budget) else None,
            "passed": self.passed,
            "margin": round(self.margin, 6),
        }


@dataclasses.dataclass
class GateResult:
    policy: str
    stages: List[StageCheck] = dataclasses.field(default_factory=list)
    oracle_fault: str = ""  # non-empty: the fp32 oracle itself failed preflight

    @property
    def passed(self) -> bool:
        return not self.oracle_fault and all(s.passed for s in self.stages)

    @property
    def margin(self) -> float:
        if self.oracle_fault:
            return -math.inf
        return min((s.margin for s in self.stages), default=1.0)

    @property
    def worst_stage(self) -> str:
        if not self.stages:
            return ""
        return min(self.stages, key=lambda s: s.margin).stage

    def reason(self) -> str:
        """Attributable verdict line — what a pruned dtype's record says."""
        if self.oracle_fault:
            return f"{self.policy}: {self.oracle_fault}"
        if self.passed:
            return ""
        s = min(self.stages, key=lambda s: s.margin)
        parts = []
        if s.max_rel > s.rel_budget:
            parts.append(f"max_rel {s.max_rel:.3e} > budget {s.rel_budget:.1e}")
        if s.max_abs > s.abs_budget:
            parts.append(f"max_abs {s.max_abs:.3e} > budget {s.abs_budget:.1e}")
        return f"{self.policy}: stage {s.stage} " + ", ".join(parts)

    def to_obj(self) -> dict:
        return {
            "policy": self.policy,
            "passed": self.passed,
            "margin": None if self.margin == -math.inf else round(self.margin, 6),
            "worst_stage": self.worst_stage,
            "oracle_fault": self.oracle_fault,
            "reason": self.reason(),
            "stages": [s.to_obj() for s in self.stages],
        }


def staged_policy_outputs(params, x, cfg=BLOCKS12, policy="fp32") -> Dict[str, np.ndarray]:
    """fp32 copies of every layer-boundary activation under ``policy`` —
    the comparison surface both gate sides run through.

    The fp32 policy IS the oracle side (reference ops, ``Precision.
    HIGHEST`` true-fp32 MACs, the tier every golden number was minted on).
    bf16 casts operands per layer and pins fp32 accumulation via
    ``preferred_element_type``; int8w delegates to the quantized forward's
    taps (one implementation — the gate screens the path that ships)."""
    import jax.numpy as jnp

    from ..ops import reference as ops

    pol = resolve_policy(policy)
    if pol.quantized:
        from .quantize import forward_blocks12_int8w

        _out, stages = forward_blocks12_int8w(
            params, x, cfg, tier="reference", taps=True
        )
        return {k: np.asarray(v) for k, v in stages.items()}

    from jax import lax

    stages: Dict[str, np.ndarray] = {}
    cur = x
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2
    for cname, cspec, pname, pspec in (
        ("conv1", c1, "pool1", p1),
        ("conv2", c2, "pool2", p2),
    ):
        lp = pol.layer(cname)
        cdt, adt = jdt(lp.compute), jdt(lp.accumulate)
        w = params[cname]["w"].astype(jdt(lp.params))
        b = params[cname]["b"]
        cur = ops.conv2d(
            cur.astype(cdt),
            w,
            b.astype(adt),
            stride=cspec.stride,
            padding=cspec.padding,
            precision=(
                lax.Precision.HIGHEST if lp.compute == "float32"
                else lax.Precision.DEFAULT
            ),
            preferred_element_type=adt,
        )
        cur = ops.relu(cur).astype(cdt)
        stages[cname] = np.asarray(cur, np.float32)
        cur = ops.maxpool(cur, window=pspec.window, stride=pspec.stride)
        stages[pname] = np.asarray(cur, np.float32)
    out = ops.lrn(
        cur.astype(jnp.float32),
        size=n2.size, alpha=n2.alpha, beta=n2.beta, k=n2.k,
        alpha_over_size=n2.alpha_over_size,
    )
    stages["lrn2"] = np.asarray(out, np.float32)
    return stages


# The fused megakernel's comparison surface: each block's single output,
# joined to the staged oracle at the block BOUNDARY stages (a fused block
# has no interior taps to compare — block granularity is the honest one).
BLOCK_BOUNDARIES = (("block1", "pool1"), ("block2", "lrn2"))


def megakernel_block_outputs(
    params, x, cfg=BLOCKS12, policy="fp32", variants=None
) -> Dict[str, np.ndarray]:
    """fp32 copies of the fused megakernel's block outputs under ``policy``
    — the candidate side of :meth:`ToleranceGate.screen_blocks`. Runs both
    blocks through ``ops.megakernel`` (whole image per program, the only
    regime block fusion has), int8w via the dequant-free epilogue-rescale
    variant."""
    import jax.numpy as jnp

    from ..ops import megakernel as mk
    from ..ops import pallas_kernels as pk

    pol = resolve_policy(policy)
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2
    v = variants if variants is not None else pk.KernelVariants()
    conv_v = v.conv if v.conv in ("taps", "vcol") else "vcol"
    out: Dict[str, np.ndarray] = {}
    blocks = (("block1", "conv1", c1, p1, None), ("block2", "conv2", c2, p2, n2))
    if pol.quantized:
        from .quantize import quantize_conv_params

        qp = quantize_conv_params(params)
        cur = x.astype(jnp.bfloat16)
        for bname, cname, cspec, pspec, lrn in blocks:
            ho = (
                cur.shape[1] + 2 * cspec.padding - cspec.filter_size
            ) // cspec.stride + 1
            e = qp[cname]
            cur = mk.int8w_conv_block_pallas(
                cur, e["q"], e["scale"], e["b"],
                stride=cspec.stride, padding=cspec.padding,
                pool_window=pspec.window, pool_stride=pspec.stride,
                lrn=lrn, variant=conv_v, row_block=max(v.row_block, ho),
            )
            out[bname] = np.asarray(cur, np.float32)
        return out
    cur = x
    for bname, cname, cspec, pspec, lrn in blocks:
        lp = pol.layer(cname)
        cdt = jdt(lp.compute)
        ho = (
            cur.shape[1] + 2 * cspec.padding - cspec.filter_size
        ) // cspec.stride + 1
        cur = mk.conv_block_pallas(
            cur.astype(cdt),
            params[cname]["w"].astype(jdt(lp.params)),
            params[cname]["b"].astype(cdt),
            stride=cspec.stride, padding=cspec.padding,
            pool_window=pspec.window, pool_stride=pspec.stride,
            lrn=lrn, variant=conv_v, row_block=max(v.row_block, ho),
        )
        out[bname] = np.asarray(cur, np.float32)
    return out


class ToleranceGate:
    """Screen a candidate policy against the fp32 oracle, stage by stage.

    ``budgets``: ``{policy_name: {stage_or_"*": StageBudget}}`` overrides
    (missing entries fall back to :data:`DEFAULT_BUDGETS`). ``journal``: a
    ``resilience.journal.Journal`` receiving one fsync'd ``gate_pass`` /
    ``gate_fail`` record per screening — the durable evidence the
    autotuner's persistence and ``on_heal.sh``'s publish step key on."""

    def __init__(self, budgets=None, journal=None, preflight: bool = True):
        self.budgets = dict(DEFAULT_BUDGETS)
        if budgets:
            self.budgets.update(budgets)
        self.journal = journal
        self.preflight = preflight

    def budget_for(self, policy: str, stage: str) -> StageBudget:
        table = self.budgets.get(policy, {})
        return table.get(stage) or table.get("*") or StageBudget()

    def screen(
        self,
        policy,
        params,
        x,
        model_cfg=BLOCKS12,
        *,
        key: str = "",
        candidate_params=None,
    ) -> GateResult:
        """One screening: oracle and candidate staged forwards, per-stage
        compare, journaled verdict.

        ``candidate_params``: optional distinct param tree for the
        candidate side — the SDC-drill surface (a corrupted replica gated
        against the clean oracle must fail)."""
        pol: DtypePolicy = resolve_policy(policy)
        res = GateResult(policy=pol.name)
        if self.preflight:
            from ..resilience.sentinel import oracle_spot_check

            err = oracle_spot_check()
            if err is not None and err > 1e-3:
                res.oracle_fault = (
                    f"fp32 oracle failed preflight: device fp32 conv deviates "
                    f"from the tests/oracle.py loop oracle by {err:.3e}"
                )
                self._journal(res, key)
                return res
        oracle = staged_policy_outputs(params, x, model_cfg, "fp32")
        if pol.name == "fp32" and candidate_params is None:
            # The oracle trivially matches itself; record exact stages so
            # the margin/journal schema stays uniform.
            for stage in oracle:
                b = self.budget_for("fp32", stage)
                res.stages.append(
                    StageCheck(stage, 0.0, 0.0, b.max_abs, b.max_rel)
                )
            self._journal(res, key)
            return res
        cand = staged_policy_outputs(
            candidate_params if candidate_params is not None else params,
            x, model_cfg, pol,
        )
        for stage, want in oracle.items():
            got = cand[stage]
            diff = float(np.max(np.abs(got - want))) if want.size else 0.0
            denom = float(np.max(np.abs(want))) if want.size else 0.0
            rel = diff / denom if denom > 0 else (0.0 if diff == 0.0 else math.inf)
            b = self.budget_for(pol.name, stage)
            res.stages.append(StageCheck(stage, diff, rel, b.max_abs, b.max_rel))
        self._journal(res, key)
        return res

    def screen_blocks(
        self,
        policy,
        params,
        x,
        model_cfg=BLOCKS12,
        *,
        variants=None,
        key: str = "",
    ) -> GateResult:
        """Screen the fused megakernel at BLOCK granularity: each block's
        single output vs the fp32 staged oracle at the block-boundary
        stages (``BLOCK_BOUNDARIES``). This is the screen that guards the
        ``fuse="block"`` candidates — a fused block has no interior taps,
        so per-stage comparison would be fake; the honest surface is the
        block output, and the budgets are the boundary stage's (falling
        back to the policy's "*" row). Journals ``gate_pass``/``gate_fail``
        like :meth:`screen`."""
        pol: DtypePolicy = resolve_policy(policy)
        res = GateResult(policy=pol.name)
        if self.preflight:
            from ..resilience.sentinel import oracle_spot_check

            err = oracle_spot_check()
            if err is not None and err > 1e-3:
                res.oracle_fault = (
                    f"fp32 oracle failed preflight: device fp32 conv deviates "
                    f"from the tests/oracle.py loop oracle by {err:.3e}"
                )
                self._journal(res, key)
                return res
        oracle = staged_policy_outputs(params, x, model_cfg, "fp32")
        try:
            cand = megakernel_block_outputs(
                params, x, model_cfg, pol, variants=variants
            )
        except Exception as e:  # noqa — an unlowerable megakernel must fail, not wedge
            b = self.budget_for(pol.name, "block1")
            res.stages.append(
                StageCheck(
                    f"megakernel-error:{type(e).__name__}",
                    math.inf, math.inf, b.max_abs, b.max_rel,
                )
            )
            self._journal(res, key)
            return res
        for bname, boundary in BLOCK_BOUNDARIES:
            want, got = oracle[boundary], cand[bname]
            diff = float(np.max(np.abs(got - want))) if want.size else 0.0
            denom = float(np.max(np.abs(want))) if want.size else 0.0
            rel = diff / denom if denom > 0 else (0.0 if diff == 0.0 else math.inf)
            b = self.budget_for(pol.name, bname)
            res.stages.append(StageCheck(bname, diff, rel, b.max_abs, b.max_rel))
        self._journal(res, key)
        return res

    def screen_sharded(
        self,
        policy,
        params,
        x,
        model_cfg=BLOCKS12,
        *,
        n_shards: int,
        tier: str = "reference",
        staged: bool = False,
        key: str = "",
    ) -> GateResult:
        """Per-rung screen for the sharded tier: the full sharded forward's
        FINAL output under ``policy`` (int8w runs the quantized sharded
        path) vs the fp32 staged oracle's lrn2 boundary. Shard count is
        part of the journaled key — the halo/mask machinery must hold the
        budget at EVERY rung, not just n=1."""
        import jax.numpy as jnp

        from ..parallel.sharded import build_sharded_forward

        pol: DtypePolicy = resolve_policy(policy)
        res = GateResult(policy=pol.name)
        if self.preflight:
            from ..resilience.sentinel import oracle_spot_check

            err = oracle_spot_check()
            if err is not None and err > 1e-3:
                res.oracle_fault = (
                    f"fp32 oracle failed preflight: device fp32 conv deviates "
                    f"from the tests/oracle.py loop oracle by {err:.3e}"
                )
                self._journal(res, key or f"gate-sharded:{pol.name}|n{n_shards}")
                return res
        want = staged_policy_outputs(params, x, model_cfg, "fp32")["lrn2"]
        fwd = build_sharded_forward(
            model_cfg, n_shards, tier=tier, staged=staged,
            quantized=pol.quantized,
        )
        if pol.quantized or pol.name == "fp32":
            got = np.asarray(fwd(params, x), np.float32)
        else:
            # bf16 rung: the same cast wrapper configs.build_forward ships.
            pb = {
                name: {k2: a.astype(jnp.bfloat16) for k2, a in p.items()}
                for name, p in params.items()
            }
            got = np.asarray(fwd(pb, x.astype(jnp.bfloat16)), np.float32)
        stage = f"lrn2@n{n_shards}"
        diff = float(np.max(np.abs(got - want))) if want.size else 0.0
        denom = float(np.max(np.abs(want))) if want.size else 0.0
        rel = diff / denom if denom > 0 else (0.0 if diff == 0.0 else math.inf)
        b = self.budget_for(pol.name, "lrn2")
        res.stages.append(StageCheck(stage, diff, rel, b.max_abs, b.max_rel))
        self._journal(res, key or f"gate-sharded:{pol.name}|n{n_shards}")
        return res

    def _journal(self, res: GateResult, key: str) -> None:
        if self.journal is not None:
            # Optional trace correlation (observability.trace): a verdict
            # screened inside a traced tuning sweep carries the sweep
            # span's ids; untraced runs journal the PR 7 schema unchanged.
            from ..observability.trace import current_ids

            self.journal.append(
                "gate_pass" if res.passed else "gate_fail",
                key=key or f"gate:{res.policy}",
                **current_ids(),
                **res.to_obj(),
            )

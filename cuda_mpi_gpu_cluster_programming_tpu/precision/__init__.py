"""Precision subsystem: dtype policies, int8 weight quantization, and the
fp32-oracle tolerance gate (docs/PRECISION.md).

Makes compute precision a first-class, tuned, oracle-gated axis instead of
a hand-pinned flag: ``policy`` names the per-layer dtype assignment
(``fp32``/``bf16``/``int8w``), ``quantize`` implements symmetric
per-output-channel int8 weights with a dequant-free bf16-accumulate
forward, and ``gate`` screens every non-fp32 candidate against the fp32
oracle before the autotuner may persist it as a winner."""

from .gate import DEFAULT_BUDGETS, GateResult, StageBudget, ToleranceGate
from .policy import (
    POLICY_NAMES,
    PRESETS,
    DtypePolicy,
    LayerPrecision,
    resolve_policy,
)
from .quantize import (
    dequantize,
    forward_blocks12_int8w,
    quantize_channelwise,
    quantize_conv_params,
)

__all__ = [
    "DEFAULT_BUDGETS",
    "GateResult",
    "StageBudget",
    "ToleranceGate",
    "POLICY_NAMES",
    "PRESETS",
    "DtypePolicy",
    "LayerPrecision",
    "resolve_policy",
    "dequantize",
    "forward_blocks12_int8w",
    "quantize_channelwise",
    "quantize_conv_params",
]

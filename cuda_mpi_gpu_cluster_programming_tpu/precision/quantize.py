"""Symmetric per-output-channel int8 weight quantization + the dequant-free
forward path.

Scheme (the ``int8w`` policy, docs/PRECISION.md):

- **Calibration** comes from the seeded init stream: scales are derived
  from the actual weights the keyed initializers drew, so two processes
  with the same seed quantize identically — no calibration dataset, no
  activation statistics (weights only).
- **Per output channel, symmetric**: for conv weights ``(F, F, C, K)`` each
  output channel k gets ``scale[k] = max|w[..., k]| / 127`` and
  ``q = clip(round(w / scale), -127, 127)`` as int8. Roundtrip error is
  bounded by ``scale/2`` elementwise (tests hold this).
- **Dequant-free compute**: the contraction runs on the RAW quantized
  values cast to bf16 (integers up to 127 are exact in bf16's 8-bit
  mantissa) with fp32 accumulation (explicit ``preferred_element_type`` —
  the accumulation dtype is stated, never inferred), and the per-channel
  ``scale`` multiplies the conv OUTPUT once, before bias and ReLU:
  ``relu(conv(x_bf16, q_bf16) * scale + b)``. Weights are never
  materialized in fp32/bf16 dequantized form — HBM traffic for the filter
  banks drops 4x vs fp32, 2x vs bf16.

Both op tiers are covered: the reference tier lowers through
``lax.conv_general_dilated`` and the Pallas tier through
``ops.pallas_kernels.conv2d_pallas`` with the fused bias/ReLU epilogue
DISABLED (``relu=False``, zero bias) because the channel rescale must
land between the accumulation and the bias add — which is also why the
``hpool`` epilogue fusion is pruned from the int8w candidate space
(``tuning.space.prune_reason``).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..models.alexnet import BLOCKS12, Blocks12Config

QMAX = 127  # symmetric int8: [-127, 127]; -128 is unused (no zero-point)


def quantize_channelwise(w: jax.Array, qmax: int = QMAX) -> Tuple[jax.Array, jax.Array]:
    """(q_int8, scale_f32) for a weight tensor whose LAST axis is the
    output-channel axis (HWIO convs and (in, out) matmuls alike).

    ``scale[k] = max|w[..., k]| / qmax`` (1.0 for an all-zero channel so
    the divide is safe and q stays zero); ``q = clip(round(w/scale))``."""
    reduce_axes = tuple(range(w.ndim - 1))
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axes)
    scale = jnp.where(amax > 0, amax / qmax, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -qmax, qmax)
    return q.astype(jnp.int8), scale


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    """fp32 reconstruction (tests/error-bound checks; the forward path never
    calls this — that is the point of the dequant-free layout)."""
    return q.astype(jnp.float32) * scale


def quantize_conv_params(params) -> dict:
    """Per-layer ``{"q", "scale", "b"}`` for every conv entry of a Blocks
    1-2 style param dict. Biases stay fp32 (they are added after the
    rescale, in the accumulation dtype)."""
    out = {}
    for name, p in params.items():
        if isinstance(p, dict) and "w" in p:
            q, scale = quantize_channelwise(p["w"])
            out[name] = {"q": q, "scale": scale, "b": p["b"]}
    return out


def int8w_conv(
    x: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    b: jax.Array,
    *,
    stride: int,
    padding: int,
    relu: bool = True,
    tier: str = "reference",
    variants=None,
) -> jax.Array:
    """One dequant-free int8-weight conv: ``relu(conv(x, q)*scale + b)``.

    ``x`` enters in (or is cast to) bf16; the int8 ``q`` is cast to bf16
    (exact for |q| <= 127) so the MXU's native bf16 MACs apply; the
    accumulate dtype is pinned fp32; rescale/bias/ReLU run in fp32 and the
    result returns to bf16 for the next stage."""
    xq = x.astype(jnp.bfloat16)
    wq = q.astype(jnp.bfloat16)
    if tier == "pallas":
        from ..ops import pallas_kernels as pk

        v = variants if variants is not None else pk.KernelVariants()
        # Fused epilogue off: the channel rescale must land between the
        # kernel's fp32 accumulation and the bias add.
        y = pk.conv2d_pallas(
            xq, wq, jnp.zeros((q.shape[-1],), jnp.bfloat16),
            stride=stride, padding=padding, relu=False,
            variant=v.conv, row_block=v.row_block, k_block=v.k_block,
        ).astype(jnp.float32)
    else:
        y = lax.conv_general_dilated(
            xq,
            wq,
            window_strides=(stride, stride),
            padding=[(padding, padding), (padding, padding)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        )
    y = y * scale + b.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(jnp.bfloat16)


def int8w_conv_then_pool(x, q, scale, b, cspec, pspec, v=None, *, tier="pallas", lrn=None):
    """The int8w lowering unit the dtype sweep times — the quantized
    counterpart of ``ops.pallas_model._conv_then_pool`` (conv + rescale +
    bias + ReLU, then the trailing max pool under the same per-layer
    variant plan). ``v.fuse == "block"`` routes the whole block through the
    dequant-free megakernel (``ops.megakernel.int8w_conv_block_pallas``)
    where the geometry gate allows: per-channel rescale in the epilogue on
    the UNCAST fp32 accumulator — which the staged chain cannot do (its
    conv kernel writes bf16 before the host rescale), so megakernel int8w
    parity is tolerance-gated, not bitwise. ``lrn`` (a LrnSpec) folds the
    block's trailing LRN in either way — fused in-kernel, staged via the
    fp32 reference LRN (the same op ``forward_blocks12_int8w`` uses)."""
    ho = (x.shape[1] + 2 * cspec.padding - cspec.filter_size) // cspec.stride + 1
    if tier == "pallas" and v is not None and v.fuse == "block":
        from ..ops import megakernel as mk

        if not mk.block_fusible_reason(
            variant=v.conv, row_block=v.row_block, k_block=v.k_block,
            pool=v.pool, out_h=ho, pool_window=pspec.window,
        ):
            return mk.int8w_conv_block_pallas(
                x, q, scale, b, stride=cspec.stride, padding=cspec.padding,
                pool_window=pspec.window, pool_stride=pspec.stride,
                lrn=lrn, variant=v.conv, row_block=v.row_block,
            )
    y = int8w_conv(
        x, q, scale, b, stride=cspec.stride, padding=cspec.padding,
        relu=True, tier=tier, variants=v,
    )
    if tier == "pallas":
        from ..ops import pallas_kernels as pk

        pool_variant = v.pool if v is not None else None
        out = pk.maxpool_pallas(
            y, window=pspec.window, stride=pspec.stride, variant=pool_variant
        )
    else:
        from ..ops import reference as ops

        out = ops.maxpool(y, window=pspec.window, stride=pspec.stride)
    if lrn is not None:
        from ..ops import reference as ops

        out = ops.lrn(
            out.astype(jnp.float32),
            size=lrn.size, alpha=lrn.alpha, beta=lrn.beta, k=lrn.k,
            alpha_over_size=lrn.alpha_over_size,
        )
    return out


def forward_blocks12_int8w(
    params,
    x: jax.Array,
    cfg: Blocks12Config = BLOCKS12,
    variants=None,
    tier: str = "reference",
    taps: bool = False,
):
    """Blocks 1-2 forward under the ``int8w`` policy (both op tiers).

    Quantization happens in-graph from the fp32 params (calibration == the
    seeded init stream that drew them), so the function keeps the standard
    ``(params, x) -> out`` shape every builder/caller expects. Activations
    ride bf16 between stages; LRN computes in fp32 (squares + pow need the
    headroom) and the final output is fp32, matching the bf16 path's
    output contract.

    ``taps=True`` additionally returns ``{stage: fp32 array}`` at every
    layer boundary — the per-stage surface the ``ToleranceGate`` screens
    against the fp32 oracle."""
    from ..ops.pallas_model import _layer_variants
    from ..ops import pallas_kernels as pk

    qp = quantize_conv_params(params)
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2
    v = variants if variants is not None else pk.KernelVariants()
    stages = {}

    def tap(name, arr):
        if taps:
            stages[name] = arr.astype(jnp.float32)

    if tier == "pallas" and not taps and any(
        _layer_variants(v, n).fuse == "block" for n in ("conv1", "conv2")
    ):
        # Megakernel route: each block is one VMEM-resident pass (the
        # trailing LRN folds into block 2). Taps callers (the gate's
        # staged-oracle surface) stay on the staged chain below — a fused
        # block has no interior boundaries to tap; the gate screens fused
        # outputs at BLOCK granularity instead (precision.gate
        # ``screen_blocks``).
        cur = x.astype(jnp.bfloat16)
        e1, e2 = qp["conv1"], qp["conv2"]
        cur = int8w_conv_then_pool(
            cur, e1["q"], e1["scale"], e1["b"], c1, p1,
            _layer_variants(v, "conv1"), tier=tier,
        )
        return int8w_conv_then_pool(
            cur, e2["q"], e2["scale"], e2["b"], c2, p2,
            _layer_variants(v, "conv2"), tier=tier, lrn=n2,
        )

    cur = x.astype(jnp.bfloat16)
    for cname, cspec, pname, pspec in (
        ("conv1", c1, "pool1", p1),
        ("conv2", c2, "pool2", p2),
    ):
        lv = _layer_variants(v, cname)
        e = qp[cname]
        cur = int8w_conv(
            cur, e["q"], e["scale"], e["b"],
            stride=cspec.stride, padding=cspec.padding, relu=True,
            tier=tier, variants=lv,
        )
        tap(cname, cur)
        if tier == "pallas":
            cur = pk.maxpool_pallas(
                cur, window=pspec.window, stride=pspec.stride, variant=lv.pool
            )
        else:
            from ..ops import reference as ops

            cur = ops.maxpool(cur, window=pspec.window, stride=pspec.stride)
        tap(pname, cur)
    from ..ops import reference as ops

    out = ops.lrn(
        cur.astype(jnp.float32),
        size=n2.size, alpha=n2.alpha, beta=n2.beta, k=n2.k,
        alpha_over_size=n2.alpha_over_size,
    )
    tap("lrn2", out)
    return (out, stages) if taps else out


def roundtrip_error_bound(w: jax.Array) -> jax.Array:
    """Elementwise quantization error bound, ``scale/2`` broadcast to the
    weight shape — what tests assert the actual roundtrip error against."""
    _q, scale = quantize_channelwise(w)
    return jnp.broadcast_to(scale / 2.0, w.shape)

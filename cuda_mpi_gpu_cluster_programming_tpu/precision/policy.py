"""Dtype policies: precision as a first-class, named, per-layer axis.

BENCH_r05 measured bf16 at MFU 0.571 against fp32's 0.123 on the same
kernels — a ~4.6x ceiling the fp32 default leaves on the table — but until
now ``dtype`` was only a passive key of the tuning plan that every caller
had to pin by hand. A :class:`DtypePolicy` makes the choice explicit and
auditable: per layer it names the dtype operands enter the contraction in
(``compute``), the dtype the contraction accumulates in (``accumulate`` —
threaded as ``preferred_element_type`` so the MXU/XLA accumulation width
is stated, never inferred), and the dtype parameters are stored in
(``params``; ``int8`` means symmetric per-channel quantized weights, see
``precision.quantize``).

Three named presets cover the production points:

- ``fp32``  — the reference floor: fp32 operands, fp32 accumulation, exact
  parity with the paper's serial oracle (``lax.Precision.HIGHEST`` MACs).
- ``bf16``  — the TPU-native fast path: bf16 operands and params, fp32
  accumulation on the MXU, fp32 output.
- ``int8w`` — weight-only int8 quantization: int8 params (per-output-
  channel symmetric scales), bf16 activations, fp32 accumulation, the
  per-channel rescale applied once to the conv OUTPUT (dequant-free — the
  contraction runs on the raw quantized values).

Every non-fp32 policy must clear the fp32-oracle :class:`~.gate.
ToleranceGate` before the autotuner will let it win a sweep
(docs/PRECISION.md).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple, Union

# Names the CLI/bench/tuning surfaces accept, in reference-floor-first
# order (also the deterministic tie-break order of the dtype sweep).
POLICY_NAMES = ("fp32", "bf16", "int8w")


def jdt(name: str):
    """jnp dtype for a policy dtype name (lazy import: policy objects are
    metadata and must stay importable without a backend)."""
    import jax.numpy as jnp

    return {
        "float32": jnp.float32,
        "bfloat16": jnp.bfloat16,
        "int8": jnp.int8,
    }[name]


@dataclasses.dataclass(frozen=True)
class LayerPrecision:
    """One layer's dtype triple.

    ``compute``: dtype operands enter the contraction in. ``accumulate``:
    the contraction's accumulation dtype — threaded into dot/conv as
    ``preferred_element_type`` wherever the policy path mixes precisions
    (the staticcheck ``implicit-upcast`` rule holds hot-path code to this).
    ``params``: parameter storage dtype; ``int8`` selects the quantized
    weight path."""

    compute: str = "float32"
    accumulate: str = "float32"
    params: str = "float32"


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    """A named per-layer precision assignment.

    ``layers`` overrides the ``default`` triple for specific layer names
    (conv1/conv2/...); un-named layers take the default — the same
    layer-addressing shape as ``ops.pallas_kernels.LayerVariants``."""

    name: str
    default: LayerPrecision = LayerPrecision()
    layers: Tuple[Tuple[str, LayerPrecision], ...] = ()

    def layer(self, layer_name: str) -> LayerPrecision:
        for n, lp in self.layers:
            if n == layer_name:
                return lp
        return self.default

    @property
    def quantized(self) -> bool:
        """True when any layer stores int8 params (the quantize path)."""
        return any(
            lp.params == "int8" for lp in (self.default, *(lp for _n, lp in self.layers))
        )


PRESETS: Dict[str, DtypePolicy] = {
    "fp32": DtypePolicy("fp32", LayerPrecision("float32", "float32", "float32")),
    "bf16": DtypePolicy("bf16", LayerPrecision("bfloat16", "float32", "bfloat16")),
    "int8w": DtypePolicy("int8w", LayerPrecision("bfloat16", "float32", "int8")),
}


def resolve_policy(spec: Union[str, DtypePolicy, None]) -> DtypePolicy:
    """A DtypePolicy from a preset name, a policy object, or None (fp32).

    The one place policy names are validated — ``configs.build_forward``,
    the run CLI and bench all route through here, so an unknown name fails
    identically everywhere."""
    if spec is None:
        return PRESETS["fp32"]
    if isinstance(spec, DtypePolicy):
        return spec
    name = str(spec).strip().lower()
    if name not in PRESETS:
        raise ValueError(
            f"unknown precision policy {spec!r} (known: {'|'.join(POLICY_NAMES)})"
        )
    return PRESETS[name]

"""CLI entry point: run one execution config and print the stdout contract.

The reference has one hard-coded ``main()`` per version (L3 layer,
SURVEY §1); this runner replaces all of them with a real flag system (a
capability upgrade the reference lacked — SURVEY §5.6) while keeping the
exact machine-parseable stdout contract its harness greps
(scripts/common_test_utils.sh:296-317):

    Final Output Shape: 13x13x256
    Final Output (first 10 values): 29.2932 25.9153 ...
    AlexNet TPU Forward Pass completed in X ms

Usage (run from the repo root so cwd is importable; leave the ambient
PYTHONPATH alone — it loads the TPU plugin's sitecustomize):

    python -m cuda_mpi_gpu_cluster_programming_tpu.run --config v1_jit --batch 1
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time

import jax
import numpy as np


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.run")
    p.add_argument("--config", default="v1_jit", help="execution config key (see configs.REGISTRY)")
    p.add_argument("--batch", type=int, default=1, help="batch size (reference is strictly batch-1)")
    p.add_argument("--shards", type=int, default=1, help="row-shard count (mpirun -np analogue)")
    p.add_argument("--init", choices=["deterministic", "random"], default="deterministic")
    p.add_argument(
        "--input",
        choices=["jax", "native"],
        default="jax",
        help="input source: jax = on-device init, native = C++ data pipeline",
    )
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--repeats", type=int, default=10, help="fenced passes for amortized timing")
    p.add_argument(
        "--warmup", type=int, default=5, help="short-queue passes subtracted by the fence protocol"
    )
    p.add_argument(
        "--compute",
        choices=["fp32", "bf16"],
        default="fp32",
        help="fp32 = exact reference-parity numerics; bf16 = MXU fast path "
        "(legacy spelling; --dtype/--policy supersede it when given)",
    )
    p.add_argument(
        "--dtype",
        choices=["", "fp32", "bf16", "int8w"],
        default="",
        help="force a precision policy for this run (docs/PRECISION.md): "
        "fp32 = reference floor, bf16 = MXU fast path, int8w = per-channel "
        "int8 weights with dequant-free bf16-accumulate compute. With "
        "--tune, pins the dtype sweep to this single dtype",
    )
    p.add_argument(
        "--policy",
        choices=["", "tuned", "fp32", "bf16", "int8w"],
        default="",
        help="named precision-policy selection: 'tuned' runs the winning "
        "dtype of the persisted dtype sweep (the plan file's policy "
        "record; falls back to --compute with a visible note when none "
        "matches); a preset name behaves like --dtype. Mutually exclusive "
        "with --dtype",
    )
    p.add_argument(
        "--gate-journal",
        default="",
        help="with --tune: journal every tolerance-gate verdict "
        "(gate_pass/gate_fail records) to this jsonl path; default: "
        "<plan>_gate.jsonl next to the plan file (docs/PRECISION.md)",
    )
    p.add_argument(
        "--lrn-form",
        choices=["cuda", "cpu"],
        default="cuda",
        help="LRN scale: cuda = k+a*sum (golden 29.2932...), cpu = k+a*sum/N (44.4152...)",
    )
    p.add_argument("--height", type=int, default=227)
    p.add_argument("--width", type=int, default=227)
    p.add_argument("--params", help="load weights from this .npz checkpoint instead of --init")
    p.add_argument("--save-params", help="save the weights used to this .npz checkpoint")
    p.add_argument("--list-configs", action="store_true")
    p.add_argument(
        "--breakdown",
        action="store_true",
        help="also print a fenced per-layer timing breakdown (XLA-op tier)",
    )
    p.add_argument(
        "--profile",
        metavar="DIR",
        help="capture a jax.profiler trace of the timed passes into DIR",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=0,
        help="retry the build+compile step on transient faults with "
        "exponential backoff (0 = fail immediately, the historical behavior)",
    )
    p.add_argument(
        "--fallback-chain",
        default="",
        help="comma-separated config keys to degrade to when the requested "
        "config cannot build/compile (e.g. 'v4_hybrid,v2.2_sharded,v1_jit'), "
        "or 'auto' for the canonical tier ladder; each step prints a "
        "structured DEGRADED(from -> to) event",
    )
    p.add_argument(
        "--deadline-s",
        type=float,
        default=0.0,
        help="wall-clock budget for build+compile retries (0 = unbounded); "
        "with --tune it also bounds the sweep, which degrades to the "
        "default plan instead of wedging",
    )
    p.add_argument(
        "--tune",
        action="store_true",
        help="autotune the Pallas kernel-variant plan for this geometry/"
        "dtype/batch and run with it; the plan is cached in --plan (a "
        "fresh matching entry skips the sweep entirely — docs/TUNING.md)",
    )
    p.add_argument(
        "--tune-force",
        action="store_true",
        help="with --tune: re-sweep even when the plan cache has a fresh entry",
    )
    p.add_argument(
        "--tune-repeats", type=int, default=5,
        help="timed chain length per tuning candidate (amortized_stats n_large - n_small)",
    )
    p.add_argument(
        "--tune-warmup", type=int, default=2,
        help="warmup chain length per tuning candidate",
    )
    p.add_argument(
        "--plan",
        default="",
        help="TunePlan JSON path: with --tune the cache target (default "
        "perf/tune_plan.json), otherwise load per-layer kernel variants "
        "from it; explicit TPU_FRAMEWORK_* env knobs still win "
        "(docs/TUNING.md)",
    )
    p.add_argument(
        "--supervise",
        action="store_true",
        help="run under the elastic supervisor: the forward compiles with "
        "in-graph per-stage digest taps, every batch is screened off the "
        "timed path, and a trip (stage_digest / shard_divergence / "
        "device_loss) degrades down the shard ladder and replays the batch "
        "(docs/RESILIENCE.md). Blocks 1-2 configs only; prints one "
        "machine-parsed 'Supervisor: ...' line",
    )
    p.add_argument(
        "--supervisor-journal",
        default="",
        help="with --supervise: journal every build/trip/degrade/ok "
        "transition to this jsonl path (resilience.journal format)",
    )
    p.add_argument(
        "--serve",
        action="store_true",
        help="run the continuous-batching inference service under a seeded "
        "Poisson load instead of the one-shot forward: admission queue with "
        "per-request deadlines, bucketed batch assembly (compile-cache-"
        "safe padded shapes), journaled dispatch; with --supervise the "
        "PR 5 elastic ladder degrades in-service instead of failing "
        "requests (docs/SERVING.md). Blocks 1-2 configs only; prints "
        "machine-parsed 'Serve load:' and 'Serve:' lines",
    )
    p.add_argument("--serve-rate", type=float, default=20.0,
                   help="with --serve: Poisson arrival rate (requests/s)")
    p.add_argument("--serve-duration", type=float, default=2.0,
                   help="with --serve: load-generation window (s)")
    p.add_argument("--serve-max-batch", type=int, default=8,
                   help="with --serve: largest dispatch bucket (powers of "
                   "two below it form the default bucket set)")
    p.add_argument("--serve-deadline-s", type=float, default=0.0,
                   help="with --serve: per-request deadline (0 = none); "
                   "expired requests are shed explicitly, never dropped")
    p.add_argument("--serve-journal", default="",
                   help="with --serve: journal every warm/batch/shed/"
                   "degrade record to this jsonl path (the serve bench's "
                   "p50/p99 source)")
    p.add_argument("--serve-buckets", default="",
                   help="with --serve: comma-separated explicit bucket "
                   "sizes (overrides the powers-of-two/TunePlan-derived "
                   "set)")
    p.add_argument(
        "--serve-controller",
        action="store_true",
        help="with --serve: run the Autopilot closed-loop controller on "
        "the dispatch loop (docs/SERVING.md 'Autopilot') — journaled, "
        "hysteresis-bounded degrade/restore off live error-budget burn "
        "and queue-knee signals (shed bulk -> narrow buckets -> int8w "
        "downshift -> supervisor degrade; reversed in LIFO order on "
        "recovery). Pairs with --traffic-shape: the class mix's SLO "
        "policy is the controller's signal source; without one it is "
        "inert by design. Prints a machine-parsed 'Serve controller:' "
        "line",
    )
    p.add_argument(
        "--serve-frontend",
        type=int,
        default=None,
        metavar="PORT",
        help="with --serve: expose the service over HTTP on 127.0.0.1:PORT "
        "(0 = ephemeral) and drive the load through a threaded HTTP client "
        "fleet over real sockets instead of in-process submits "
        "(docs/SERVING.md 'Network front end & SLOs'). Backpressure is "
        "429, sheds are 504 with their reason, every exchange journals a "
        "serve.transport span. Prints a machine-parsed 'Serve frontend:' "
        "line",
    )
    p.add_argument(
        "--route",
        type=int,
        default=0,
        metavar="N",
        help="with --serve: fleet mode — spawn N backend serving PROCESSES "
        "(serving.fleet) behind a FleetRouter and drive the HTTP client "
        "fleet through the router instead of one in-process server "
        "(docs/SERVING.md 'Fleet router'). Deterministic crc32-of-rid "
        "routing, probe-driven up/probation/quarantine hysteresis, "
        "journaled retry-with-redirect; prints machine-parsed 'Route "
        "fleet:'/'Route load:'/'Route class:'/'Route:' lines. Ignores "
        "--config et al. (backends build their own v1_jit servers)",
    )
    p.add_argument(
        "--route-dir",
        default="logs/route",
        help="with --route: journal directory — one backend_<i>.jsonl per "
        "backend plus router.jsonl, exportable as ONE stitched timeline "
        "via 'observability export --journal DIR'",
    )
    p.add_argument(
        "--traffic-shape",
        default="",
        help="with --serve: traffic-shaped load instead of plain Poisson — "
        "steady | diurnal | burst | flash, composable with '+' (e.g. "
        "'diurnal+burst'), params as key=value ('diurnal:amp=0.8,period=2"
        "+burst:every=1,mult=5'). Requests draw a seeded heavy-tailed "
        "class mix (interactive/batch/bulk) with per-class deadlines and "
        "SLO-aware shed-by-class; prints per-class 'Serve class:' lines",
    )
    p.add_argument(
        "--serve-replay",
        default="",
        metavar="JOURNAL",
        help="re-drive a recorded serve journal through a live server on "
        "this mesh (docs/OBSERVABILITY.md 'Replay & regression gating'): "
        "same arrivals, request shapes/classes/deadlines, and chaos "
        "schedule, reconstructed from the journal alone (--config et al. "
        "are ignored — the journal's serve_config record is the truth). "
        "Prints machine-parsed 'Replay:' and 'Replay class:' lines; rc 3 "
        "when a neutral replay diverges from the recorded accounting, "
        "rc 2 on an unreplayable (pre-replay-schema) journal",
    )
    p.add_argument(
        "--replay-mult",
        type=float,
        default=1.0,
        help="with --serve-replay: offer the recorded schedule at this "
        "traffic multiple (what-if knob; non-neutral replays never rc 3)",
    )
    p.add_argument(
        "--replay-devices",
        type=int,
        default=None,
        help="with --serve-replay: rebuild the server at this shard "
        "width instead of the recorded one",
    )
    p.add_argument(
        "--replay-slo-scale",
        type=float,
        default=1.0,
        help="with --serve-replay: scale every class SLO budget and "
        "per-request deadline (0.5 = twice as tight)",
    )
    p.add_argument(
        "--replay-journal",
        default="",
        help="with --serve-replay: journal the replay run here (itself "
        "replayable; default: a temp file)",
    )
    p.add_argument(
        "--trace",
        default="",
        help="journal spans (observability.trace) to this jsonl path: "
        "build/tune/measure phases, supervisor trip->degrade->reshard->"
        "replay parents, per-request serve queue-wait vs dispatch; export "
        "with 'python -m cuda_mpi_gpu_cluster_programming_tpu."
        "observability export --journal PATH' (docs/OBSERVABILITY.md). "
        "With --serve and --serve-journal, spans default into the serve "
        "journal so one file carries the whole correlated timeline",
    )
    return p


def _chaos_build_faults(exec_cfg) -> None:
    """Fault-injection hook for the build+compile step (CHAOS_SPEC; no-op
    when chaos is off). Sites map onto the real failure modes each config
    class is exposed to: collectives for the sharded strategies, Mosaic
    lowering for the Pallas tier, device loss for anything needing a mesh."""
    from .resilience import chaos

    ch = chaos.active()
    if ch is None:
        return
    if exec_cfg.strategy != "single":
        ch.maybe_raise("collective", f"{exec_cfg.key} halo/collective transport")
        if ch.draw("device_loss"):
            # Mesh shrink: mimic the exact message the mesh-size guard
            # raises, so triage (MESH_WARN patterns) sees the real signature.
            raise RuntimeError(
                f"chaos: injected device_loss fault: config {exec_cfg.key!r} "
                f"needs 2 devices, have 1"
            )
    if exec_cfg.tier == "pallas":
        ch.maybe_raise("kernel_compile", f"{exec_cfg.key} Mosaic lowering")


def _run_route(args, blocks_cfg) -> int:
    """Fleet mode (--serve --route N): N backend serving processes behind
    a FleetRouter, the HTTP client fleet driven through the router, and
    the journals (one per backend + the router's) stitched from one
    directory. With host_loss chaos armed, the seeded backend is
    SIGKILLed mid-load, restarted after the load window, and must
    re-admit through probation — the CLI face of the acceptance drill
    (docs/SERVING.md 'Fleet router')."""
    import threading
    import time as _time
    from pathlib import Path

    from .resilience.policy import RetryPolicy
    from .serving.batcher import power_of_two_buckets
    from .serving.fleet import BackendFleet, maybe_host_loss
    from .serving.frontend import http_fleet_load
    from .serving.router import UP, FleetRouter, RouterConfig
    from .serving.traffic import default_class_mix

    route_dir = Path(args.route_dir)
    route_dir.mkdir(parents=True, exist_ok=True)
    fleet = BackendFleet(
        args.route,
        route_dir,
        height=blocks_cfg.in_height,
        width=blocks_cfg.in_width,
        max_batch=args.serve_max_batch,
    )
    router = None
    killed = [None]
    try:
        fleet.start()
        router = FleetRouter(
            fleet.urls(),
            RouterConfig(
                probe_interval_s=0.1,
                probe_timeout_s=2.0,
                fail_k=2,
                readmit_m=2,
                retry=RetryPolicy(
                    max_retries=3, base_delay_s=0.02, max_delay_s=0.25,
                    jitter=0.1,
                ),
                default_deadline_s=args.serve_deadline_s or None,
                journal_path=str(route_dir / "router.jsonl"),
            ),
        ).start()
        print(
            f"Route fleet: n={args.route} url={router.url} dir={route_dir}"
        )
        mix = list(
            default_class_mix(power_of_two_buckets(args.serve_max_batch))
        )
        # host_loss chaos fires mid-window from a timer — the load keeps
        # offering while the victim dies, which is the point.
        timer = threading.Timer(
            max(0.05, args.serve_duration / 2),
            lambda: killed.__setitem__(0, maybe_host_loss(fleet)),
        )
        timer.start()
        t_kill = _time.monotonic()
        report = http_fleet_load(
            router.url,
            (
                blocks_cfg.in_height,
                blocks_cfg.in_width,
                blocks_cfg.in_channels,
            ),
            shape=args.traffic_shape or "steady",
            rate_rps=args.serve_rate,
            duration_s=args.serve_duration,
            classes=mix,
            seed=args.seed,
        )
        timer.cancel()
        recovery_ms = None
        if killed[0] is not None:
            idx = killed[0]
            print(f"Route host loss: killed=b{idx} (chaos host_loss)")
            router.replace_backend(idx, fleet.restart(idx))
            deadline = _time.monotonic() + 60.0
            while (
                _time.monotonic() < deadline
                and router.backend_states()[f"b{idx}"] != UP
            ):
                _time.sleep(0.05)
            if router.backend_states()[f"b{idx}"] == UP:
                recovery_ms = (_time.monotonic() - t_kill) * 1e3
        print(f"Route load: {report.summary()}")
        rrep = router.report()
        for line in rrep.class_lines():
            print(line)
        if recovery_ms is not None:
            print(f"Route recovery: killed=b{killed[0]} ms={recovery_ms:.0f}")
        print(f"Route: {rrep.summary()}")
    finally:
        if router is not None:
            router.stop()
        fleet.stop()
    from .observability.health import health_from_journal

    try:
        print(f"Health: {health_from_journal(route_dir).summary_line()}")
    except Exception as e:  # noqa — the fold is evidence, not the verdict
        print(f"Health: unavailable ({type(e).__name__}: {e})")
    return 0


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)

    # Join a multi-host cluster when launched by parallel.distributed's
    # launch_plan/launch_local (no-op otherwise) — must happen before any
    # backend use.
    from .parallel.distributed import maybe_initialize_from_env

    maybe_initialize_from_env()

    # Persistent compile cache (the prebuilt-binaries analogue,
    # build_local_binaries.sh:8-10) — before the first jit.
    from .utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    from .configs import REGISTRY, build_forward
    from .models.alexnet import BLOCKS12
    from .models.init import (
        deterministic_input,
        init_params_deterministic,
        init_params_random,
        random_input,
    )
    from .observability.trace import Tracer, set_tracer, span as obs_span
    from .utils.timing import amortized_stats

    if args.trace:
        # Journal-backed span tracing (docs/OBSERVABILITY.md): every
        # wired subsystem below (tuner, supervisor, serving) records into
        # this trail; the "Trace:" line is the machine-parseable pointer.
        from .resilience.journal import Journal as _Journal

        tracer = Tracer(journal=_Journal(args.trace))
        set_tracer(tracer)
        print(f"Trace: id={tracer.trace_id} journal={args.trace}")

    if args.list_configs:
        for c in REGISTRY.values():
            print(f"{c.key:18s} {c.version_name:22s} {c.description}")
        return 0

    if args.serve_replay:
        # Journal-replay mode: the recorded serve_config record carries
        # the run's conditions, so every CLI build knob below is moot —
        # rebuild from the journal, re-drive, judge.
        from .observability.replay import (
            ReplayKnobs,
            load_recorded_run,
            replay_recorded,
        )

        if args.replay_mult <= 0 or args.replay_slo_scale <= 0:
            print(
                "--replay-mult/--replay-slo-scale must be > 0",
                file=sys.stderr,
            )
            return 2
        try:
            recorded = load_recorded_run(args.serve_replay)
        except ValueError as e:
            print(f"--serve-replay: {e}", file=sys.stderr)
            return 2
        report = replay_recorded(
            recorded,
            ReplayKnobs(
                traffic_mult=args.replay_mult,
                devices=args.replay_devices,
                slo_scale=args.replay_slo_scale,
                journal_path=args.replay_journal,
            ),
        )
        print(f"Replay source: {args.serve_replay}")
        print(f"Replay journal: {report.journal_path}")
        print(f"Replay: {report.summary()}")
        for line in report.class_lines():
            print(line)
        if report.diverged:
            print(
                "replay divergence: neutral replay broke the recorded "
                "accounting/percentile contract (docs/OBSERVABILITY.md)",
                file=sys.stderr,
            )
            return 3
        return 0

    if args.config not in REGISTRY:
        print(f"unknown config {args.config!r}; try --list-configs", file=sys.stderr)
        return 2
    exec_cfg = REGISTRY[args.config]

    blocks_cfg = dataclasses.replace(
        BLOCKS12,
        in_height=args.height,
        in_width=args.width,
        lrn2=dataclasses.replace(BLOCKS12.lrn2, alpha_over_size=(args.lrn_form == "cpu")),
    )
    if exec_cfg.model == "alexnet_full":
        from .models.alexnet_full import AlexNetConfig

        model_cfg = AlexNetConfig(blocks12=blocks_cfg)
    else:
        model_cfg = blocks_cfg

    print(f"--- AlexNet TPU {exec_cfg.version_name} [{exec_cfg.key}] "
          f"(shards={args.shards}, batch={args.batch}) ---")
    print(f"Devices: {jax.device_count()} x {jax.devices()[0].device_kind} "
          f"({jax.default_backend()})")

    # Precision-policy resolution (docs/PRECISION.md): an explicit --dtype
    # (or preset --policy) pins the run; --policy tuned reads the persisted
    # dtype-sweep winner; plain --tune adopts the sweep winner; otherwise
    # the legacy --compute flag stands. The "Precision:" line below is
    # machine-parsed (harness._RE_PRECISION) into the CSV's Dtype column.
    if args.dtype and args.policy:
        print("--dtype and --policy are mutually exclusive", file=sys.stderr)
        return 2
    pinned = args.dtype or (args.policy if args.policy not in ("", "tuned") else "")
    run_dtype = pinned or args.compute
    dtype_source = "dtype" if args.dtype else ("policy" if pinned else "compute")
    gate_info = None

    # Kernel-variant tuning plan: --tune sweeps (or loads the cached sweep),
    # --plan alone loads; either way the resolved plan rides into
    # build_forward and its hash is printed for the harness CSV. The
    # "Tune plan:" line is part of the machine-parsed stdout contract
    # (harness._RE_PLAN).
    plan = None
    if args.tune or args.plan or args.policy == "tuned":
        from pathlib import Path

        from .resilience.policy import Deadline as _Deadline
        from .tuning.autotune import DTYPES, autotune, autotune_precision
        from .tuning.plan import load_plan, load_policy

        plan_path = args.plan or str(
            Path(__file__).resolve().parent.parent / "perf" / "tune_plan.json"
        )
        device_kind = jax.devices()[0].device_kind
        if args.policy == "tuned" and not args.tune:
            rec = load_policy(
                plan_path, device_kind=device_kind, model_cfg=model_cfg,
                batch=args.batch,
            )
            if rec is None:
                print(
                    f"Policy: no tuned dtype record in {plan_path} "
                    f"(falling back to --compute {args.compute}; "
                    "run --tune to sweep)"
                )
            else:
                run_dtype = rec["dtype"]
                dtype_source = "tuned"
                gate_info = rec.get("gates", {}).get(run_dtype)
        if args.tune:
            if exec_cfg.model == "blocks12":
                # ONE sweep covers {fp32, bf16, int8w} x kernel variants per
                # conv layer; gate-failed dtypes are pruned attributably and
                # the winner's policy record is persisted (docs/PRECISION.md).
                res = None
                try:
                    with obs_span("run.tune", config=args.config, batch=args.batch):
                        res = autotune_precision(
                            plan_path,
                            model_cfg,
                            batch=args.batch,
                            dtypes=(run_dtype,) if pinned else DTYPES,
                            force=args.tune_force,
                            deadline=_Deadline.after(args.deadline_s or None),
                            repeats=args.tune_repeats,
                            warmup=args.tune_warmup,
                            device_kind=device_kind,
                            gate_journal=args.gate_journal,
                            seed=args.seed,
                        )
                except RuntimeError as e:
                    # Every requested dtype gate-pruned (possible only for a
                    # pinned sweep, or a broken fp32 oracle): say so and run
                    # the forced dtype untuned — the gate blocks PERSISTED
                    # winners, not explicitly forced runs.
                    print(f"Gate pruned: {e}")
                if res is not None:
                    for dt, why in sorted(res.pruned.items()):
                        print(f"Gate pruned: {dt} ({why})")
                    if not pinned:
                        run_dtype = res.winner
                        dtype_source = "tuned"
                    gate_info = res.gates.get(run_dtype)
                    plan = res.plans.get(run_dtype)
                if plan is not None:
                    print(
                        f"Tune plan: {'cache' if res.cached else 'swept'} "
                        f"hash={plan.plan_hash()} key={plan.key} path={plan_path}"
                        + (f" DEGRADED({plan.degraded})" if plan.degraded else "")
                    )
                else:
                    print(
                        f"Tune plan: none for dtype {run_dtype} "
                        "(gate-pruned; untuned defaults)"
                    )
            else:
                plan, cached = autotune(
                    plan_path,
                    model_cfg,
                    dtype=run_dtype,
                    batch=args.batch,
                    force=args.tune_force,
                    deadline=_Deadline.after(args.deadline_s or None),
                    repeats=args.tune_repeats,
                    warmup=args.tune_warmup,
                    device_kind=device_kind,
                )
                print(
                    f"Tune plan: {'cache' if cached else 'swept'} "
                    f"hash={plan.plan_hash()} key={plan.key} path={plan_path}"
                    + (f" DEGRADED({plan.degraded})" if plan.degraded else "")
                )
        else:  # --plan and/or --policy tuned: load, never sweep
            plan = load_plan(
                plan_path, device_kind=device_kind, model_cfg=model_cfg,
                dtype=run_dtype, batch=args.batch,
            )
            if plan is None:
                print(
                    f"Tune plan: none matching in {plan_path} "
                    "(untuned defaults; run --tune to sweep)"
                )
            else:
                print(f"Tune plan: loaded hash={plan.plan_hash()} key={plan.key}")
            if gate_info is None:
                rec = load_policy(
                    plan_path, device_kind=device_kind, model_cfg=model_cfg,
                    batch=args.batch,
                )
                if rec is not None:
                    gate_info = rec.get("gates", {}).get(run_dtype)

    if run_dtype == "fp32":
        gate_str = "ref"  # fp32 IS the oracle: nothing to gate against
    elif isinstance(gate_info, dict):
        margin = gate_info.get("margin")
        gate_str = ("pass" if gate_info.get("passed") else "fail") + (
            f" margin={margin:.4f}" if isinstance(margin, (int, float)) else ""
        )
    else:
        gate_str = "none"
    print(f"Precision: dtype={run_dtype} source={dtype_source} gate={gate_str}")

    if exec_cfg.model == "alexnet_full":
        from .models.alexnet_full import init_full_deterministic, init_full_random

        init_det, init_rnd = init_full_deterministic, init_full_random
    else:
        init_det, init_rnd = init_params_deterministic, init_params_random
    input_cfg = blocks_cfg  # inputs depend only on the Blocks 1-2 input dims
    # kp/kx derivation is shared by every branch, so --params w.npz --seed S
    # reproduces the exact inputs of the run that saved w.npz.
    kp, kx = jax.random.split(jax.random.PRNGKey(args.seed))
    if args.params:
        from .utils.checkpoint import load_params_npz

        params = load_params_npz(args.params)
        print(f"Loaded params from {args.params}")
    elif args.init == "deterministic":
        params = init_det(model_cfg)
    else:
        params = init_rnd(kp, model_cfg)

    if args.serve:
        # Continuous-batching service mode: the serving subsystem owns the
        # build (per-bucket warmup through the compile cache; with
        # --supervise the elastic ladder), so every later build/measure
        # path below is bypassed.
        if exec_cfg.model != "blocks12":
            print("--serve supports the Blocks 1-2 configs only", file=sys.stderr)
            return 2
        if args.fallback_chain:
            print(
                "--serve degrades through the supervisor ladder "
                "(--supervise); drop --fallback-chain",
                file=sys.stderr,
            )
            return 2
        if args.route:
            # Fleet mode: N backend PROCESSES behind the router — the
            # single-process build below is bypassed entirely (each
            # backend owns its server; the router owns the accounting).
            return _run_route(args, blocks_cfg)
        from .serving.loadgen import run_load, run_shaped_load
        from .serving.server import InferenceServer, ServeConfig
        from .serving.traffic import default_class_mix, parse_shape, slo_policy

        buckets = tuple(
            int(b) for b in args.serve_buckets.split(",") if b.strip()
        )
        # Shaped traffic carries a class mix whose SLO policy rides into
        # admission (shed-by-class); plain Poisson keeps PR 6 behavior.
        mix = None
        slo = None
        if args.traffic_shape:
            try:
                parse_shape(args.traffic_shape)  # fail loudly before building
            except ValueError as e:
                print(f"--traffic-shape: {e}", file=sys.stderr)
                return 2
        scfg = ServeConfig(
            config=args.config,
            n_shards=args.shards,
            # The resolved precision policy rides into serving whole: the
            # bucket set derives from the plan at THIS dtype and every
            # warmup compile runs it (docs/SERVING.md).
            compute=run_dtype,
            policy=dtype_source,
            max_batch=args.serve_max_batch,
            buckets=buckets or None,
            plan_path=args.plan,
            supervise=args.supervise,
            journal_path=args.serve_journal,
            default_deadline_s=args.serve_deadline_s or None,
            model_cfg=blocks_cfg,
        )
        if args.traffic_shape:
            mix = list(default_class_mix(
                InferenceServer(scfg, params=params, plan=plan).buckets
            ))
            slo = slo_policy(mix)
            scfg = dataclasses.replace(scfg, slo=slo)
        if args.serve_controller:
            from .serving.controller import ControllerConfig

            scfg = dataclasses.replace(scfg, controller=ControllerConfig())
            if scfg.slo is None:
                print(
                    "Serve controller: inert (no SLO policy — pair with "
                    "--traffic-shape for the class-mix signal source)"
                )
        server = InferenceServer(scfg, params=params, plan=plan)
        # With --trace the tracer is already installed; otherwise the
        # serve journal doubles as the span trail, so ONE file exports
        # into the full correlated timeline (queue-wait vs dispatch spans
        # beside their serve_batch records — docs/OBSERVABILITY.md).
        serve_tracer = None
        if not args.trace and server.journal is not None:
            serve_tracer = Tracer(journal=server.journal)
            set_tracer(serve_tracer)
            print(f"Trace: id={serve_tracer.trace_id} journal={scfg.journal_path}")
        frontend = None
        try:
            server.start()
            try:
                if args.serve_frontend is not None:
                    # The network path: requests travel a real socket into
                    # the admission queue; the load is a threaded HTTP
                    # client fleet (docs/SERVING.md).
                    from .serving.frontend import ServingFrontend, http_fleet_load

                    frontend = ServingFrontend(
                        server, port=args.serve_frontend
                    ).start()
                    print(f"Serve frontend: url={frontend.url}")
                    with obs_span(
                        "serve.load",
                        rate_rps=args.serve_rate,
                        duration_s=args.serve_duration,
                        transport="http",
                    ):
                        report = http_fleet_load(
                            frontend.url,
                            (
                                blocks_cfg.in_height,
                                blocks_cfg.in_width,
                                blocks_cfg.in_channels,
                            ),
                            shape=args.traffic_shape or "steady",
                            rate_rps=args.serve_rate,
                            duration_s=args.serve_duration,
                            classes=mix or list(default_class_mix(server.buckets)),
                            seed=args.seed,
                        )
                elif args.traffic_shape:
                    with obs_span(
                        "serve.load",
                        rate_rps=args.serve_rate,
                        duration_s=args.serve_duration,
                        shape=args.traffic_shape,
                    ):
                        report = run_shaped_load(
                            server,
                            shape=args.traffic_shape,
                            rate_rps=args.serve_rate,
                            duration_s=args.serve_duration,
                            classes=mix,
                            seed=args.seed,
                        )
                else:
                    with obs_span(
                        "serve.load",
                        rate_rps=args.serve_rate,
                        duration_s=args.serve_duration,
                    ):
                        report = run_load(
                            server,
                            rate_rps=args.serve_rate,
                            duration_s=args.serve_duration,
                            seed=args.seed,
                        )
            finally:
                if frontend is not None:
                    frontend.stop()
                server.stop()
        finally:
            if serve_tracer is not None:
                set_tracer(None)  # in-process callers must not leak a tracer
        print(f"Serve buckets: {','.join(str(b) for b in server.buckets)}")
        print(f"Serve load: {report.summary()}")
        if hasattr(report, "class_lines"):
            for line in report.class_lines():
                print(line)
        print(f"Serve: {server.summary()}")
        if frontend is not None:
            codes = " ".join(
                f"http_{c}={n}"
                for c, n in sorted(frontend.http_codes.items())
            )
            print(f"Serve transport: {codes}")
        if server.controller is not None:
            # Machine-parsed Autopilot line: mode/level/action counts
            # (docs/SERVING.md "Autopilot").
            print(f"Serve controller: {server.controller.summary()}")
        if server.sup is not None:
            # Same machine-parsed supervisor line as the one-shot
            # --supervise path (harness._RE_SUPERVISOR).
            print(f"Supervisor: {server.sup.summary()}")
        if scfg.journal_path:
            # One-line fleet-health fold of the run's own journal
            # (observability.health; the full report via
            # `observability health --journal <path>`).
            from .observability.health import health_from_journal

            try:
                print(
                    f"Health: "
                    f"{health_from_journal(scfg.journal_path).summary_line()}"
                )
            except Exception as e:  # noqa — the fold is evidence, not
                # the serve result; degrade visibly, never fatally.
                print(f"Health: unavailable ({type(e).__name__}: {e})")
        return 0

    if args.input == "native":
        # C++ pipeline generates the batch host-side (the reference's C++
        # initializeData analogue); deterministic mode is bit-identical to the
        # jax path, random mode uses the native LCG stream instead of
        # jax.random (documented, seeded, reproducible).
        try:
            from . import native

            mode = "ones" if args.init == "deterministic" else "uniform"
            x = jax.device_put(
                native.fill_batch(
                    (args.batch, input_cfg.in_height, input_cfg.in_width, input_cfg.in_channels),
                    mode=mode,
                    seed=args.seed,
                )
            )
        except RuntimeError as e:  # toolchain missing / native build broke
            print(f"cannot build native input tier: {e}", file=sys.stderr)
            return 2
    elif args.init == "deterministic":
        x = deterministic_input(args.batch, input_cfg)
    else:
        x = random_input(kx, args.batch, input_cfg)
    if args.save_params:
        from .utils.checkpoint import save_params_npz

        save_params_npz(args.save_params, params)
        print(f"Saved params to {args.save_params}")

    from .resilience import chaos

    chain = [args.config]
    if args.fallback_chain:
        from .resilience.policy import tier_fallback_chain

        if args.fallback_chain.strip() == "auto":
            chain = tier_fallback_chain(args.config)
        else:
            chain += [k.strip() for k in args.fallback_chain.split(",") if k.strip()]
        chain = list(dict.fromkeys(chain))
        unknown = [k for k in chain if k not in REGISTRY]
        if unknown:
            print(f"unknown configs in --fallback-chain: {unknown}", file=sys.stderr)
            return 2
        mixed = [k for k in chain if REGISTRY[k].model != exec_cfg.model]
        if mixed:
            # Degrading across model families would run the wrong network
            # against this process's params/input — a silent lie, not a
            # graceful fallback.
            print(
                f"--fallback-chain crosses model families: {mixed} "
                f"(primary is {exec_cfg.model})",
                file=sys.stderr,
            )
            return 2

    def _build_and_compile(key: str):
        cfg = REGISTRY[key]
        _chaos_build_faults(cfg)
        f = build_forward(
            cfg, model_cfg, n_shards=args.shards, policy=run_dtype, plan=plan
        )
        t0 = time.perf_counter()
        jax.block_until_ready(f(params, x))
        return f, (time.perf_counter() - t0) * 1e3

    resilient = (
        len(chain) > 1
        or args.max_retries > 0
        or args.deadline_s > 0
        or chaos.active() is not None
    )
    sup = None
    if args.supervise:
        # Elastic supervisor: digest-tapped forward + screening + ladder
        # re-planning. It owns building (and its own chaos draws), so the
        # retry/degrader build path below is bypassed.
        if exec_cfg.model != "blocks12":
            print("--supervise supports the Blocks 1-2 configs only", file=sys.stderr)
            return 2
        if args.fallback_chain:
            print(
                "--supervise has its own degradation ladder; drop --fallback-chain",
                file=sys.stderr,
            )
            return 2
        from .resilience.journal import Journal
        from .resilience.policy import DegradationExhausted
        from .resilience.supervisor import Supervisor, default_ladder

        try:
            ladder = default_ladder(exec_cfg.strategy, exec_cfg.tier, args.shards)
        except ValueError as e:
            print(f"cannot supervise config {exec_cfg.key!r}: {e}", file=sys.stderr)
            return 2
        sup = Supervisor(
            model_cfg,
            ladder,
            plan=plan,
            journal=(
                Journal(args.supervisor_journal) if args.supervisor_journal else None
            ),
            # DEGRADED events print to stdout where the harness greps them,
            # exactly like the build-time Degrader's.
            on_event=lambda ev: print(ev, flush=True),
        )
        try:
            sup.execute(params, x)
        except DegradationExhausted as e:
            print(f"supervisor: every ladder rung failed: {e.last}", file=sys.stderr)
            return 2
        fwd = sup.fwd()  # (params, x) -> (out, digests): taps ride the timed path
        compile_ms = sup.compile_ms or 0.0
    elif not resilient:
        # Historical fast path, byte-identical stdout/stderr.
        try:
            fwd = build_forward(
                exec_cfg, model_cfg, n_shards=args.shards, policy=run_dtype,
                plan=plan,
            )
        except (ValueError, NotImplementedError, ModuleNotFoundError) as e:
            print(f"cannot build config {exec_cfg.key!r}: {e}", file=sys.stderr)
            return 2
        t0 = time.perf_counter()
        jax.block_until_ready(fwd(params, x))
        compile_ms = (time.perf_counter() - t0) * 1e3
    else:
        from .resilience.policy import (
            Deadline,
            DegradationExhausted,
            Degrader,
            RetryPolicy,
            retry_call,
        )

        policy = RetryPolicy(max_retries=max(0, args.max_retries), base_delay_s=1.0)
        deadline = Deadline.after(args.deadline_s or None)
        # DEGRADED events go to stdout: the harness greps them out of the
        # captured log and triages the row as DEGRADED rather than FAIL.
        degrader = Degrader(chain, on_event=lambda ev: print(ev, flush=True))
        try:
            ran_key, (fwd, compile_ms) = degrader.run(
                lambda key: retry_call(
                    lambda: _build_and_compile(key), policy=policy, deadline=deadline
                )
            )
        except DegradationExhausted as e:
            print(f"cannot build config {chain[-1]!r}: {e.last}", file=sys.stderr)
            return 2
        except (ValueError, NotImplementedError, ModuleNotFoundError) as e:
            print(f"cannot build config {exec_cfg.key!r}: {e}", file=sys.stderr)
            return 2
        if ran_key != args.config:
            # Downstream consumers (--breakdown tier attribution) must see
            # the tier that actually ran, not the one that was asked for.
            exec_cfg = REGISTRY[ran_key]
    n_small = max(1, args.warmup)
    if args.profile:
        from .utils.profiling import trace as profile_ctx
    else:
        import contextlib

        profile_ctx = lambda _dir: contextlib.nullcontext()  # noqa: E731
    with profile_ctx(args.profile):
        # Work-floor stats, not a single sample: the conv-variant A/B and
        # every harness row route through this line, so it must resolve
        # deltas smaller than the relay's ~40% single-sample noise.
        with obs_span(
            "run.measure", config=exec_cfg.key, batch=args.batch,
            dtype=run_dtype,
        ) as _msp:
            st = amortized_stats(
                fwd, params, x, n_small=n_small, n_large=n_small + max(1, args.repeats)
            )
            if _msp is not None:
                _msp.set(per_pass_ms=round(st.per_call_ms, 4))
        per_pass_ms = st.per_call_ms
    if args.profile:
        print(f"Profiler trace written to {args.profile}")
    if sup is not None:
        # Screened verification pass (digest screening off the timed path),
        # then the machine-parsed supervisor line for the harness CSV.
        out = np.asarray(sup.execute(params, x))
        print(f"Supervisor: {sup.summary()}")
    else:
        out = np.asarray(fwd(params, x))

    shape_str = "x".join(str(d) for d in out.shape[1:])
    flat = out[0].reshape(-1)
    first10 = " ".join(f"{v:.4f}" for v in flat[:10])
    print(f"Compile time: {compile_ms:.1f} ms")
    print(f"Final Output Shape: {shape_str}")
    print(f"Final Output (first 10 values): {first10}")
    print(
        f"AlexNet TPU Forward Pass completed in {per_pass_ms:.3f} ms "
        f"(amortized over {args.repeats} fenced passes; "
        f"{args.batch / (per_pass_ms / 1e3):.1f} img/s)"
    )
    # Separate line: the 'completed in' format above is the harness-regexed
    # stdout contract (common_test_utils.sh analogue) and must not change.
    print(
        f"Timing stats: n={st.n_samples} ci95={st.ci95_ms:.4f} ms "
        f"chain={st.n_chain}"
        + (" SHADOWED" if st.shadowed else "")
        + (" UNDERCONVERGED" if st.underconverged else "")
    )
    if args.breakdown and run_dtype == "int8w":
        print(
            "--breakdown does not support the int8w policy "
            "(the quantized lowering has no per-layer XLA-tier analogue); "
            "skipped"
        )
    elif args.breakdown:
        from .utils.profiling import layer_breakdown

        # Per-layer costs (the per-phase breakdown the reference lists as
        # future work, reference README.md:233) — timed on the SELECTED
        # config's op tier, so a v3_pallas breakdown attributes cost to
        # the hand-written kernels, not the XLA ops.
        for name, ms, shape in layer_breakdown(
            params,
            x,
            model_cfg,
            repeats=max(1, args.repeats),
            warmup=n_small,
            compute=run_dtype,
            tier=exec_cfg.tier,
        ):
            shape_s = "x".join(str(d) for d in shape[1:])
            print(f"Layer {name} completed in {ms:.3f} ms -> {shape_s}")
        if exec_cfg.strategy in ("halo", "staged_halo"):
            # Static comm/compute plan for the sharded strategies — the
            # per-phase breakdown the reference listed as future work
            # (reference README.md:233); exact because the halo geometry
            # is Python ints at trace time (parallel/plan.py). The same
            # numbers are asserted against the compiled jaxpr's collective
            # count in tests/test_breakdown.py.
            from .parallel.breakdown import comm_compute_breakdown, format_table

            staged = exec_cfg.strategy == "staged_halo"
            dtype_bytes = 2 if run_dtype in ("bf16", "int8w") else 4
            rows = comm_compute_breakdown(
                blocks_cfg, args.shards, batch=args.batch,
                dtype_bytes=dtype_bytes, staged=staged,
            )
            print(format_table(rows, staged=staged))
        elif exec_cfg.strategy == "tp":
            # Same static-plan guarantee for the filter-decomposition dual:
            # channel-halo ppermutes + the conv2 boundary all_gather
            # (parallel/tensor_parallel.py), asserted against the compiled
            # jaxpr per primitive in tests/test_breakdown.py.
            from .parallel.breakdown import format_table, tp_comm_compute_breakdown

            dtype_bytes = 2 if run_dtype in ("bf16", "int8w") else 4
            rows = tp_comm_compute_breakdown(
                blocks_cfg, args.shards, batch=args.batch, dtype_bytes=dtype_bytes,
            )
            print(format_table(rows, transport="all_gather + channel-halo ppermute"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""Execution-config registry: the reference's V1-V5 stages as configs.

The reference implements its parallelization stages as five divergent source
trees (final_project/v1_serial ... v5_cuda_aware_mpi). Here each stage is an
``ExecConfig`` selecting (a) the op tier — XLA reference ops or Pallas
kernels — and (b) the distribution strategy — none, replicate-all, or
row-sharded with halo exchange — over the *same* model definition.

Stage mapping (version_name strings stay compatible with the reference's
canonical analysis mapping, analysis.md:69-92, extended with a V6 family):

- ``v1_jit``        ↔ V1 Serial (v1_serial/): single device, XLA ops.
- ``v2.1_replicated``↔ V2.1 BroadcastAll (2.1_broadcast_all/src/main.cpp:49-87):
  fully-replicated input+params, every device computes the full pass — kept
  as the pedagogical anti-baseline.
- ``v2.2_sharded``  ↔ V2.2 ScatterHalo (2.2_scatter_halo/src/main.cpp:100-249):
  1-D row decomposition, neighbor halo exchange, XLA ops.
- ``v3_pallas``     ↔ V3 CUDA (v3_cuda_only/): single device, hand-written
  Pallas kernels (the TPU counterpart of the .cu kernels).
- ``v4_hybrid``     ↔ V4 MPI+CUDA (v4_mpi_cuda/): row-sharded with
  *host-staged-style* halo (all_gather + reslice — the analogue of V4's
  D2H→MPI→H2D staging) + Pallas kernels per shard.
- ``v5_collective`` ↔ V5 CUDA-aware MPI (planned-only in the reference,
  README.md:158-166): row-sharded with direct device-to-device ``ppermute``
  halos over ICI — the natural state of the TPU backend, exposed as an
  explicit measured config to reproduce the V4-vs-V5 comparison story.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional

import jax

from .models.alexnet import BLOCKS12, forward_blocks12


@dataclasses.dataclass(frozen=True)
class ExecConfig:
    key: str
    version_name: str  # canonical name for CSV/analysis compatibility
    tier: str  # "reference" (XLA ops) | "pallas"
    strategy: str  # "single" | "replicated" | "halo" | "staged_halo"
    description: str
    model: str = "blocks12"  # "blocks12" | "alexnet_full"


REGISTRY: Dict[str, ExecConfig] = {
    c.key: c
    for c in [
        ExecConfig(
            "v1_jit",
            "V1 Serial",
            "reference",
            "single",
            "single-device jit-compiled XLA ops (serial-CPU analogue)",
        ),
        ExecConfig(
            "v2.1_replicated",
            "V2.1 BroadcastAll",
            "reference",
            "replicated",
            "fully-replicated compute on every device (anti-baseline)",
        ),
        ExecConfig(
            "v2.2_sharded",
            "V2.2 ScatterHalo",
            "reference",
            "halo",
            "1-D row decomposition + ppermute halo exchange, XLA ops",
        ),
        ExecConfig(
            "v3_pallas",
            "V3 CUDA",
            "pallas",
            "single",
            "single-device hand-written Pallas kernels (CUDA-kernel analogue)",
        ),
        ExecConfig(
            "v4_hybrid",
            "V4 MPI+CUDA",
            "pallas",
            "staged_halo",
            "row-sharded, Pallas per shard, all_gather-staged halos (V4 host-staging analogue)",
        ),
        ExecConfig(
            "v5_collective",
            "V5 MPI+CUDA-Aware",
            "pallas",
            "halo",
            "row-sharded, Pallas per shard, device-to-device ppermute halos over ICI",
        ),
        ExecConfig(
            "v7_tp",
            "V7 TensorParallel",
            "reference",
            "tp",
            "conv filter-bank (K-axis) decomposition — the reference's named-"
            "but-unbuilt alternative to row decomposition (README.md:638); "
            "weights sharded, channel-halo LRN, boundary all_gather",
        ),
        # V6 family: the reference's explicit extension task (README.md:19) —
        # full AlexNet through conv5 + FC6-8 (dims summary.md:29-45).
        ExecConfig(
            "v6_full_jit",
            "V6 AlexNet Full",
            "reference",
            "single",
            "full AlexNet (conv1-5 + FC6-8) single device, XLA ops",
            model="alexnet_full",
        ),
        ExecConfig(
            "v6_full_pallas",
            "V6 AlexNet Full Pallas",
            "pallas",
            "single",
            "full AlexNet, Pallas kernels for the spatial part, MXU matmul FC",
            model="alexnet_full",
        ),
        ExecConfig(
            "v6_full_sharded",
            "V6 AlexNet Full Sharded",
            "reference",
            "halo",
            "full AlexNet, row-sharded spatial part + replicated FC head",
            model="alexnet_full",
        ),
    ]
}


def _resolve_variants(plan):
    """Env-resolved variants, overlaid with a TunePlan's per-layer winners
    when one is supplied. Precedence per knob: explicit env var beats the
    tuned plan beats the code default (tuning.plan.effective_layer_variants
    is the one implementation)."""
    from .ops.pallas_kernels import KernelVariants

    kv = KernelVariants.resolve()
    if plan is None:
        return kv
    from .tuning.plan import effective_layer_variants

    return effective_layer_variants(plan, base=kv)


def build_forward(
    exec_cfg: ExecConfig,
    model_cfg=None,
    n_shards: int = 1,
    mesh: Optional[jax.sharding.Mesh] = None,
    compute: str = "fp32",
    plan=None,
    donate: bool = False,
    policy=None,
) -> Callable:
    """Return a jitted ``(params, x) -> out`` for the given execution config.

    ``model_cfg`` defaults per model family (BLOCKS12 / ALEXNET).
    ``n_shards`` is the TPU analogue of ``mpirun -np N``
    (scripts/common_test_utils.sh:274-276).
    ``policy`` (or the legacy ``compute`` string, which accepts the same
    names) selects numerics via the precision subsystem
    (docs/PRECISION.md): ``fp32`` (exact reference parity — fp32 MACs even
    on the MXU), ``bf16`` (params+input cast to bfloat16, fp32 accumulation
    on the MXU, fp32 output — the TPU-native perf mode; halves HBM traffic
    and engages the MXU's fast path), or ``int8w`` (symmetric per-channel
    int8 weights, dequant-free bf16-accumulate compute — single-device
    Blocks 1-2 tiers only). A ``precision.policy.DtypePolicy`` object is
    accepted wherever a name is. Non-fp32 policies are expected to have
    cleared the fp32-oracle ``ToleranceGate`` (the autotuner enforces this
    before persisting a winner).
    ``plan``: a ``tuning.plan.TunePlan`` whose per-layer kernel variants the
    Pallas tiers run with (single-device AND sharded builders; reference
    tiers ignore it); explicit env knobs still win — see docs/TUNING.md.
    ``donate``: donate the input-activation buffer to the computation
    (single-device tiers; halves peak HBM for the activation at the cost of
    consuming ``x`` — callers that re-invoke with the same array, e.g. the
    amortized timing chains, must leave this off).
    """
    from .precision.policy import POLICY_NAMES, resolve_policy

    try:
        pol = resolve_policy(policy if policy is not None else compute)
    except ValueError:
        raise ValueError(
            f"unknown compute mode / precision policy "
            f"{(policy if policy is not None else compute)!r} "
            f"({'|'.join(POLICY_NAMES)})"
        ) from None
    # Persistent XLA compile cache (the prebuilt-binaries analogue), wired
    # at build time so EVERY builder caller — tuner candidates included —
    # gets it, not just the run/bench entry mains. Never fatal: a read-only
    # FS degrades to uncached compiles.
    try:
        from .utils.compile_cache import enable_persistent_cache

        enable_persistent_cache()
    except Exception:
        pass
    if pol.quantized:
        if exec_cfg.model != "blocks12" or exec_cfg.strategy not in (
            "single", "halo", "staged_halo", "replicated"
        ):
            raise ValueError(
                f"policy {pol.name!r} supports the Blocks 1-2 single-device, "
                f"halo-sharded, and replicated tiers only (config "
                f"{exec_cfg.key!r} is {exec_cfg.model}/{exec_cfg.strategy}); "
                "quantized tensor-parallel and full-AlexNet forwards are "
                "open ROADMAP items"
            )
        from .models.alexnet import BLOCKS12 as _B12

        mcfg = model_cfg or _B12
        if exec_cfg.strategy in ("halo", "staged_halo", "replicated"):
            # Sharded int8w rungs: int8 values + per-channel scales ride the
            # replicated param tree; each rung is expected to re-screen via
            # precision.gate.ToleranceGate.screen_sharded before its rows
            # publish (scripts/on_heal.sh wires this on-chip).
            need = n_shards
            if mesh is None and jax.device_count() < need:
                raise ValueError(
                    f"config {exec_cfg.key!r} with {n_shards} shards needs "
                    f"{need} devices, have {jax.device_count()} (use "
                    f"XLA_FLAGS=--xla_force_host_platform_device_count=N on "
                    f"CPU to fake a mesh)"
                )
            if exec_cfg.strategy == "replicated":
                from .parallel.replicated import build_replicated_forward

                fwd = build_replicated_forward(
                    mcfg, n_shards, mesh=mesh, quantized=True
                )
            else:
                from .parallel.sharded import build_sharded_forward

                fwd = build_sharded_forward(
                    mcfg,
                    n_shards,
                    mesh=mesh,
                    tier=exec_cfg.tier,
                    staged=(exec_cfg.strategy == "staged_halo"),
                    plan=plan,
                    quantized=True,
                )
            return _observed(fwd, exec_cfg, pol.name, n_shards)
        from .precision.quantize import forward_blocks12_int8w

        kv = _resolve_variants(plan) if exec_cfg.tier == "pallas" else None
        tier = exec_cfg.tier
        return _observed(
            _jit(
                lambda p, x: forward_blocks12_int8w(
                    p, x, mcfg, variants=kv, tier=tier
                ),
                donate,
            ),
            exec_cfg,
            pol.name,
            1,
        )
    fwd = _build_forward_fp32(exec_cfg, model_cfg, n_shards, mesh, plan, donate)
    if pol.name == "fp32":
        return _observed(fwd, exec_cfg, pol.name, n_shards)
    import jax.numpy as jnp

    def fwd_bf16(p, x):
        pb = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        return fwd(pb, x.astype(jnp.bfloat16)).astype(jnp.float32)

    return _observed(_jit(fwd_bf16, donate), exec_cfg, pol.name, n_shards)


def _observed(
    fn: Callable, exec_cfg: ExecConfig, dtype: str, n_shards: int
) -> Callable:
    """Compile-observer gate (observability.health): when an observer is
    installed (run/bench journal wiring), first calls per input shape are
    timed and reported as ``compile_event`` records. With no observer —
    every existing caller — the jitted callable is returned UNCHANGED:
    same identity, same ``.lower()``, zero overhead."""
    from .observability.health import get_compile_observer, observed_first_calls

    if get_compile_observer() is None:
        return fn
    return observed_first_calls(
        fn,
        site="build",
        entry=exec_cfg.key,
        dtype=dtype,
        n_shards=n_shards if exec_cfg.strategy != "single" else 1,
    )


def _jit(fn: Callable, donate: bool) -> Callable:
    # Donation argnums: 1 is the activation input x of (params, x). Params
    # are never donated — every caller reuses them across passes.
    return jax.jit(fn, donate_argnums=(1,) if donate else ())


def _build_forward_fp32(
    exec_cfg: ExecConfig,
    model_cfg=None,
    n_shards: int = 1,
    mesh: Optional[jax.sharding.Mesh] = None,
    plan=None,
    donate: bool = False,
) -> Callable:
    need = n_shards if exec_cfg.strategy != "single" else 1
    if mesh is None and jax.device_count() < need:
        raise ValueError(
            f"config {exec_cfg.key!r} with {n_shards} shards needs {need} devices, "
            f"have {jax.device_count()} (use XLA_FLAGS=--xla_force_host_platform_"
            f"device_count=N on CPU to fake a mesh)"
        )

    if exec_cfg.model == "alexnet_full":
        from .models.alexnet_full import ALEXNET, forward_alexnet

        model_cfg = model_cfg or ALEXNET
        if exec_cfg.strategy == "single":
            if exec_cfg.tier == "pallas":
                from .ops.pallas_model import forward_alexnet_pallas

                # Resolve lowering variants NOW: each build_forward call
                # re-reads the env, so the A/B workflow is build-per-variant
                # instead of the round-3 process-per-variant footgun. A
                # TunePlan overlays per-layer winners (env still wins).
                kv = _resolve_variants(plan)
                return _jit(
                    lambda p, x: forward_alexnet_pallas(p, x, model_cfg, variants=kv),
                    donate,
                )
            return _jit(lambda p, x: forward_alexnet(p, x, model_cfg), donate)
        if exec_cfg.strategy in ("halo", "staged_halo"):
            from .models.alexnet_full import fc_head
            from .parallel.sharded import build_sharded_forward

            spatial = build_sharded_forward(
                model_cfg,
                n_shards,
                mesh=mesh,
                tier=exec_cfg.tier,
                staged=(exec_cfg.strategy == "staged_halo"),
                plan=plan,
            )
            # Row-sharded feature extractor; FC head on the gathered features
            # (replicated — the 6x6x256 activations are tiny next to conv1's).
            return jax.jit(lambda p, x: fc_head(p, spatial(p, x), model_cfg))
        raise ValueError(f"strategy {exec_cfg.strategy!r} not supported for alexnet_full")

    model_cfg = model_cfg or BLOCKS12
    if exec_cfg.strategy == "single":
        if exec_cfg.tier == "pallas":
            from .ops.pallas_model import _chain_variant, forward_blocks12_pallas

            kv = _resolve_variants(plan)  # eager: see alexnet_full branch
            ch = _chain_variant()
            return _jit(
                lambda p, x: forward_blocks12_pallas(
                    p, x, model_cfg, variants=kv, chain=ch
                ),
                donate,
            )
        return _jit(lambda p, x: forward_blocks12(p, x, model_cfg), donate)

    if exec_cfg.strategy == "replicated":
        from .parallel.replicated import build_replicated_forward

        return build_replicated_forward(model_cfg, n_shards, mesh=mesh)

    if exec_cfg.strategy in ("halo", "staged_halo"):
        from .parallel.sharded import build_sharded_forward

        return build_sharded_forward(
            model_cfg,
            n_shards,
            mesh=mesh,
            tier=exec_cfg.tier,
            staged=(exec_cfg.strategy == "staged_halo"),
            plan=plan,
        )

    if exec_cfg.strategy == "tp":
        from .parallel.tensor_parallel import build_tp_forward

        return build_tp_forward(model_cfg, n_shards, mesh=mesh)

    raise ValueError(f"unknown strategy {exec_cfg.strategy!r}")

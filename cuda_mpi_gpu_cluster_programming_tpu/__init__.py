"""TPU-native staged-parallelism framework.

A brand-new JAX/XLA/Pallas/shard_map framework with the capabilities of the
reference CUDA+MPI repo (`mykolas-perevicius/CUDA-MPI-GPU-Cluster-Programming`):
a staged parallelization study of AlexNet Blocks 1-2 inference, where the
reference's five divergent code copies (V1 serial, V2.1 broadcast-all,
V2.2 scatter+halo, V3 CUDA, V4 MPI+CUDA) become *execution configs of one
codebase*:

- ``ops.reference``  — pure jax.numpy/XLA op tier (the "V1" semantics,
  jit-compiled; reference: v1_serial/src/layers_serial.cpp:37-175).
- ``ops.pallas_kernels`` — hand-written Pallas TPU kernels (the "V3" tier;
  reference: v3_cuda_only/src/layers_cuda.cu:20-152).
- ``parallel`` — 1-D mesh row decomposition with ppermute halo exchange over
  ICI (the "V2.2/V4/V5" tier; reference: v2_mpi_only/2.2_scatter_halo/src/
  main.cpp:100-249 and v4_mpi_cuda/src/alexnet_mpi_cuda.cu:27-154), with
  exact per-shard output-row ownership replacing the reference's buggy
  compute-then-trim heuristic (v4_mpi_cuda/src/main_mpi_cuda.cpp:102-119).
- ``models.alexnet`` — the single model definition all tiers share.
- ``utils`` / ``analysis`` — bench harness (CSV schema, ASCII table, env
  triage) and the DuckDB/sqlite speedup-efficiency ETL (reference:
  scripts/common_test_utils.sh, log_analysis.py).
"""

__version__ = "0.1.0"

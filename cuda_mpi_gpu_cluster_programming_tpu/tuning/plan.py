"""Persistent kernel-tuning plans: on-disk winners of the variant sweep.

A ``TunePlan`` records, for one (device kind, model geometry, batch, dtype,
code revision) point, the per-layer ``KernelVariants`` the autotuner
measured fastest — the durable analogue of the hand-set TPU_FRAMEWORK_*
env knobs (the compilation-cache + sweep pattern of SNIPPETS.md [1] and
the AutoTVM/Triton-style searchers in PAPERS.md). Plan files hold many
plans keyed by the full point, so one ``perf/tune_plan.json`` serves CPU
CI and the v5e alike.

File format (docs/TUNING.md):

    {
      "version": 1,
      "plans": {
        "<device_kind>|<shape_key>|b<batch>|<dtype>|rev=<code_rev>": {
          "device_kind": ..., "shape_key": ..., "batch": ..., "dtype": ...,
          "code_rev": ..., "degraded": "", "created": "...",
          "layers":  {"conv1": {"conv": "vcol", "pool": "sep2", ...}},
          "stats":   {"conv1": {"best_ms": ..., "default_ms": ..., ...}}
        }
      }
    }

Staleness: ``code_rev`` hashes the kernel/lowering sources; a plan tuned
against different kernel code is a MISS (re-sweep), never silently reused.

Precedence (one implementation, here): an EXPLICIT env knob beats the
tuned plan beats the code default — so a hand A/B (TPU_FRAMEWORK_CONV=taps)
still pins every layer even when a plan is loaded, and an untuned knob
falls back exactly as before. scripts/lint.py's ``variant-env`` rule keeps
stray ``os.environ`` reads of these knobs from forking this chain.
"""

from __future__ import annotations

import dataclasses
import datetime
import hashlib
import json
import os
from pathlib import Path
from typing import Dict, Optional, Tuple

from ..ops.pallas_kernels import KernelVariants, LayerVariants

PLAN_VERSION = 1

# Variant-knob field -> env var. The single source the precedence merge and
# the lint rule's knob census both read.
VARIANT_ENV = {
    "conv": "TPU_FRAMEWORK_CONV",
    "pool": "TPU_FRAMEWORK_POOL",
    "row_block": "TPU_FRAMEWORK_ROWBLOCK",
    "k_block": "TPU_FRAMEWORK_KBLOCK",
    "fuse": "TPU_FRAMEWORK_FUSE",
}

# Sources whose drift invalidates tuned winners: the kernels themselves,
# the model chain that decides fusion adjacency, the candidate space, and
# the quantized lowering (an int8w plan tuned against a different rescale
# path is as stale as one tuned against different kernels).
_REV_FILES = (
    "../ops/pallas_kernels.py",
    "../ops/pallas_model.py",
    "../ops/megakernel.py",
    "space.py",
    "../precision/quantize.py",
)


def code_rev() -> str:
    """12-hex digest of the kernel/lowering sources — the plan-staleness key."""
    h = hashlib.sha256()
    here = Path(__file__).resolve().parent
    for rel in _REV_FILES:
        h.update((here / rel).read_bytes())
    return h.hexdigest()[:12]


def explicit_env_knobs() -> frozenset:
    """Variant-knob FIELDS the environment explicitly sets right now
    (non-empty value) — these outrank any tuned plan."""
    return frozenset(
        f for f, env in VARIANT_ENV.items() if os.environ.get(env, "").strip()
    )


def _input_dims(model_cfg) -> Tuple[int, int, int]:
    return model_cfg.in_height, model_cfg.in_width, model_cfg.in_channels


def shape_key(model_cfg) -> str:
    """Geometry identity of a model config: family + input dims (the layer
    chain is derived from these by the shared traversal)."""
    family = "alexnet_full" if hasattr(model_cfg, "blocks12") else "blocks12"
    h, w, c = _input_dims(model_cfg)
    return f"{family}_{h}x{w}x{c}"


def plan_key(device_kind: str, shape_k: str, batch: int, dtype: str, rev: str) -> str:
    return f"{device_kind}|{shape_k}|b{batch}|{dtype}|rev={rev}"


@dataclasses.dataclass(frozen=True)
class TunePlan:
    """Winners of one autotune sweep (or the default-plan degradation)."""

    device_kind: str
    shape_key: str
    batch: int
    dtype: str  # a precision policy name: "fp32" | "bf16" | "int8w"
    code_rev: str
    layers: Tuple[Tuple[str, KernelVariants], ...]
    stats: Dict[str, dict] = dataclasses.field(default_factory=dict)
    # Non-empty = the sweep could not finish (deadline/chaos/faults) and fell
    # back to defaults for the listed layers — visible, never silent.
    degraded: str = ""

    @property
    def key(self) -> str:
        return plan_key(
            self.device_kind, self.shape_key, self.batch, self.dtype, self.code_rev
        )

    def variants_for(self, name: str, default: Optional[KernelVariants] = None):
        for n, v in self.layers:
            if n == name:
                return v
        return default if default is not None else KernelVariants()

    def plan_hash(self) -> str:
        """10-hex identity of (key, winners) — the CSV/bench row label that
        makes tuned measurements attributable to one exact plan."""
        payload = json.dumps(
            {"key": self.key, "layers": {n: v._asdict() for n, v in self.layers}},
            sort_keys=True,
        )
        return hashlib.sha256(payload.encode()).hexdigest()[:10]

    def to_obj(self) -> dict:
        return {
            "device_kind": self.device_kind,
            "shape_key": self.shape_key,
            "batch": self.batch,
            "dtype": self.dtype,
            "code_rev": self.code_rev,
            "degraded": self.degraded,
            "layers": {n: v._asdict() for n, v in self.layers},
            "stats": self.stats,
        }

    @classmethod
    def from_obj(cls, obj: dict) -> "TunePlan":
        layers = tuple(
            (n, KernelVariants(**fields)) for n, fields in obj["layers"].items()
        )
        return cls(
            device_kind=obj["device_kind"],
            shape_key=obj["shape_key"],
            batch=int(obj["batch"]),
            dtype=obj["dtype"],
            code_rev=obj["code_rev"],
            layers=layers,
            stats=obj.get("stats", {}),
            degraded=obj.get("degraded", ""),
        )


def _read_file(path) -> dict:
    """The whole plan file as a dict (``plans`` + the sibling ``policies``
    section); a missing/torn file degrades to empty sections."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        obj = {}
    if not isinstance(obj, dict):
        obj = {}
    if not isinstance(obj.get("plans"), dict):
        obj["plans"] = {}
    if not isinstance(obj.get("policies"), dict):
        obj["policies"] = {}
    obj["version"] = PLAN_VERSION
    return obj


def _read_plans(path) -> dict:
    return _read_file(path)["plans"]


def _write_file(path, obj: dict) -> None:
    # Atomic replace: the plan cache is a committed run artifact; a crash
    # mid-save must leave the previous (complete) file, never a torn one.
    from ..resilience.journal import atomic_write_text

    atomic_write_text(path, json.dumps(obj, indent=2) + "\n")


def save_plan(plan: TunePlan, path) -> str:
    """Merge one plan into the file under its key (read-modify-write; other
    device/dtype/batch points AND the policy records are preserved).
    Returns the key written."""
    path = Path(path)
    obj = _read_file(path)
    entry = plan.to_obj()
    entry["created"] = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%MZ"
    )
    obj["plans"][plan.key] = entry
    _write_file(path, obj)
    return plan.key


def load_plan(
    path,
    *,
    device_kind: str,
    model_cfg,
    dtype: str,
    batch: int,
    rev: Optional[str] = None,
    match_any_batch: bool = True,
) -> Optional[TunePlan]:
    """The plan for this exact point, or None (= tune or run untuned).

    A different ``code_rev`` is a MISS even when everything else matches —
    stale winners must never be applied to changed kernels. With
    ``match_any_batch`` a same-device/geometry/dtype plan tuned at another
    batch is accepted as the nearest usable point (the variants space is
    geometry-dominated; the returned plan keeps its own batch so consumers
    can see the approximation).
    """
    plans = _read_plans(path)
    if not plans:
        return None
    rev = rev or code_rev()
    sk = shape_key(model_cfg)
    exact = plans.get(plan_key(device_kind, sk, batch, dtype, rev))
    if exact is not None:
        return TunePlan.from_obj(exact)
    if not match_any_batch:
        return None
    prefix = f"{device_kind}|{sk}|b"
    suffix = f"|{dtype}|rev={rev}"
    for key in sorted(plans):
        if key.startswith(prefix) and key.endswith(suffix):
            return TunePlan.from_obj(plans[key])
    return None


def plan_batches(
    path,
    *,
    device_kind: str,
    model_cfg,
    dtype: str,
    rev: Optional[str] = None,
) -> list:
    """Batch sizes the plan file holds CURRENT tuned winners for at this
    (device kind, geometry, dtype, code-rev) point, sorted ascending.

    This is the serving bucket-set derivation (docs/SERVING.md): the
    continuous-batching dispatcher pads every batch to one of these sizes,
    so every shape it hands the persistent compile cache is a shape the
    autotuner already swept — tuned winners apply and the cache hits.
    Stale-rev entries are excluded for the same reason ``load_plan``
    misses on them: their winners no longer describe the current kernels.
    Empty when the file is missing/unmatched — callers fall back to the
    powers-of-two default set."""
    plans = _read_plans(path)
    if not plans:
        return []
    rev = rev or code_rev()
    prefix = f"{device_kind}|{shape_key(model_cfg)}|b"
    suffix = f"|{dtype}|rev={rev}"
    batches = set()
    for key, obj in plans.items():
        if not (key.startswith(prefix) and key.endswith(suffix)):
            continue
        try:
            batches.add(int(obj["batch"]))
        except (KeyError, TypeError, ValueError):
            continue  # malformed entry: not a usable bucket, skip it
    return sorted(batches)


def policy_key(device_kind: str, shape_k: str, batch: int, rev: str) -> str:
    """Key of a dtype-policy record — the plan key WITHOUT the dtype field
    (the record's whole job is to say which dtype won at this point)."""
    return f"{device_kind}|{shape_k}|b{batch}|rev={rev}"


def save_policy(
    path,
    *,
    device_kind: str,
    model_cfg,
    batch: int,
    dtype: str,
    rev: Optional[str] = None,
    swept=(),
    pruned: Optional[Dict[str, str]] = None,
    gates: Optional[Dict[str, dict]] = None,
) -> str:
    """Persist the dtype-sweep winner for one (device, geometry, batch,
    code-rev) point into the plan file's ``policies`` section (sibling of
    ``plans``; the per-dtype kernel winners stay under their own keys).

    ``pruned`` records every gate-failed dtype with its attributable
    reason; ``gates`` the full per-dtype gate verdicts (margin and all) —
    bench rows read ``gate_margin`` from here. The gate's journaled
    ``gate_pass`` record is written by the gate itself at screening time;
    this record points at the same verdict."""
    path = Path(path)
    obj = _read_file(path)
    rev = rev or code_rev()
    key = policy_key(device_kind, shape_key(model_cfg), batch, rev)
    obj["policies"][key] = {
        "device_kind": device_kind,
        "shape_key": shape_key(model_cfg),
        "batch": batch,
        "code_rev": rev,
        "dtype": dtype,
        "swept": list(swept),
        "pruned": dict(pruned or {}),
        "gates": dict(gates or {}),
        "created": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%MZ"
        ),
    }
    _write_file(path, obj)
    return key


def load_policy(
    path,
    *,
    device_kind: str,
    model_cfg,
    batch: int,
    rev: Optional[str] = None,
    match_any_batch: bool = True,
) -> Optional[dict]:
    """The dtype-policy record for this point, or None. Same staleness and
    nearest-batch semantics as ``load_plan``: a different code_rev is a
    MISS, and with ``match_any_batch`` a same-device/geometry record tuned
    at another batch is the nearest usable point."""
    policies = _read_file(path)["policies"]
    if not policies:
        return None
    rev = rev or code_rev()
    sk = shape_key(model_cfg)
    exact = policies.get(policy_key(device_kind, sk, batch, rev))
    if exact is not None:
        return exact
    if not match_any_batch:
        return None
    prefix = f"{device_kind}|{sk}|b"
    suffix = f"|rev={rev}"
    for key in sorted(policies):
        if key.startswith(prefix) and key.endswith(suffix):
            return policies[key]
    return None


def effective_layer_variants(
    plan: TunePlan, base: Optional[KernelVariants] = None
) -> LayerVariants:
    """Merge a tuned plan with the environment into the per-layer variants a
    forward builder closes over. Precedence per knob: explicit env var >
    tuned plan > code default. ``base`` is the env-resolved variants
    (``KernelVariants.resolve()``), whose values are authoritative exactly
    for the knobs the env explicitly sets; unset knobs take the plan's
    winners. Layers the plan does not cover fall back to ``base`` whole."""
    base = base if base is not None else KernelVariants.resolve()
    explicit = explicit_env_knobs()
    layers = []
    for name, pv in plan.layers:
        merged = {
            f: getattr(base if f in explicit else pv, f) for f in VARIANT_ENV
        }
        layers.append((name, KernelVariants(**merged, k_channels=pv.k_channels)))
    return LayerVariants(layers=tuple(layers), default=base)

"""Legal candidate space of the kernel autotuner.

One conv layer (plus the pool it feeds, when adjacent) is the tuning unit —
exactly the granularity ``ops.pallas_model._conv_then_pool`` lowers at. The
space is the cartesian product of every ``KernelVariants`` knob, PRUNED to
combinations that can actually lower and DEDUPED to distinct effective
lowerings, so the sweep never spends timing budget on a candidate that
``conv2d_pallas`` would reject (hardware k_block lane rule), silently
degrade (geometry-dropped k_block — the mislabeled-A/B-row hazard
``_warn_k_block_dropped`` guards), or alias (row blocks beyond the output
height all clamp to whole-image programs).

Every prune is attributable: ``prune_reason`` returns WHY a combo is out,
and ``candidate_space`` can report each drop to a logger — no silent caps.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, List, Optional, Tuple

from ..ops.megakernel import block_fusible_reason
from ..ops.pallas_kernels import KernelVariants

# Knob domains — mirror the env_variant allowed-sets in ops.pallas_kernels
# (the tuner must not invent values the env interface refuses).
CONV_VARIANTS = ("taps", "pairs", "fused", "vcol", "g8")
POOL_VARIANTS = ("sep2", "phases")
ROW_BLOCKS = (8, 16, 32, 64)
K_BLOCKS = (0, 64, 128)
FUSES = ("none", "hpool", "block")


@dataclasses.dataclass(frozen=True)
class ConvGeometry:
    """One conv layer's tuning-relevant geometry (+ its trailing pool)."""

    name: str
    filter_size: int
    stride: int
    padding: int
    in_channels: int
    out_channels: int
    in_h: int
    in_w: int
    pool_window: int = 0  # 0 = no adjacent pool
    pool_stride: int = 0
    # The LRN trailing the pool, when the model has one: (size, alpha,
    # beta, k, alpha_over_size) as a hashable tuple, () = none. Part of
    # the tuning unit because fuse="block" folds it into the megakernel
    # pass — and the timer must include it for STAGED candidates too, or
    # fused-vs-staged timings would compare unequal work.
    lrn: tuple = ()

    @property
    def out_h(self) -> int:
        return (self.in_h - self.filter_size + 2 * self.padding) // self.stride + 1

    @property
    def fq(self) -> int:
        return -(-self.filter_size // self.stride)  # ceil(F/S): taps per axis

    @property
    def has_pool(self) -> bool:
        return self.pool_window > 0

    def describe(self) -> str:
        pool = f" pool={self.pool_window}/{self.pool_stride}" if self.has_pool else ""
        return (
            f"{self.name}: {self.filter_size}x{self.filter_size}s{self.stride}"
            f"p{self.padding} K={self.out_channels} "
            f"in={self.in_h}x{self.in_w}x{self.in_channels}{pool}"
        )


def conv_geometries(model_cfg) -> List[ConvGeometry]:
    """The model's conv layers with their input dims and trailing pools —
    driven by the shared ``models.alexnet.layer_dims`` traversal, so tuned
    geometry cannot drift from the FLOP/shape accounting."""
    from ..models.alexnet import ConvSpec, LrnSpec, PoolSpec, layer_dims

    chain = list(layer_dims(model_cfg))
    out: List[ConvGeometry] = []
    for i, (name, spec, (hi, wi, ci), _o) in enumerate(chain):
        if not isinstance(spec, ConvSpec):
            continue
        pw = ps = 0
        lrn: tuple = ()
        if i + 1 < len(chain) and isinstance(chain[i + 1][1], PoolSpec):
            nxt = chain[i + 1][1]
            pw, ps = nxt.window, nxt.stride
            if i + 2 < len(chain) and isinstance(chain[i + 2][1], LrnSpec):
                n = chain[i + 2][1]
                lrn = (
                    n.size, n.alpha, n.beta, n.k, n.alpha_over_size,
                )
        out.append(
            ConvGeometry(
                name=name,
                filter_size=spec.filter_size,
                stride=spec.stride,
                padding=spec.padding,
                in_channels=ci,
                out_channels=spec.out_channels,
                in_h=hi,
                in_w=wi,
                pool_window=pw,
                pool_stride=ps,
                lrn=lrn,
            )
        )
    return out


def prune_reason(
    v: KernelVariants, g: ConvGeometry, *, interpret: bool, dtype: str = "fp32"
) -> str:
    """Why this combo is out of the sweep ('' = legal). Mirrors the gates in
    _conv2d_pallas / _conv_then_pool — a candidate this accepts must lower
    and run the variant it claims. ``dtype`` is the sweep's precision
    policy: int8w runs the conv with the fused bias/ReLU epilogue disabled
    (the per-channel rescale lands between accumulation and bias —
    precision.quantize), so epilogue fusion is not a legal candidate
    there. fuse="block" IS legal under int8w: the megakernel applies the
    per-channel rescale in its own epilogue, between the fp32
    accumulation and the bias (ops.megakernel) — the staged-chain
    limitation that rules hpool out does not apply."""
    if v.fuse == "hpool" and dtype == "int8w":
        return (
            "hpool fusion needs the in-kernel bias/ReLU epilogue; int8w "
            "applies bias after the per-channel rescale (precision.quantize)"
        )
    if v.conv == "pairs" and g.fq < 2:
        return f"pairs degenerates to taps at fq={g.fq} (nothing to pair)"
    if v.conv == "g8" and g.stride < 2:
        return "g8 falls back to vcol at stride 1 (phase packing needs s>=2)"
    if v.k_block:
        if v.conv not in ("taps", "vcol"):
            return f"k_block applies to the taps/vcol path only (conv={v.conv})"
        if v.k_block % 128 != 0 and not interpret:
            return f"k_block={v.k_block} cannot lower on hardware (lane tiling 128)"
        if not (g.out_channels % v.k_block == 0 and g.out_channels > v.k_block):
            return (
                f"k_block={v.k_block} dropped at K={g.out_channels} "
                "(runs unblocked — duplicate of kb=0)"
            )
    if v.fuse == "hpool":
        if not g.has_pool:
            return "hpool fusion needs an adjacent pool"
        if v.conv not in ("taps", "vcol"):
            return f"hpool fusion supports taps/vcol only (conv={v.conv})"
        if v.pool != "sep2":
            return "hpool fusion is the sep2 pool's H stage (pool=phases excluded)"
        if v.row_block < g.out_h:
            return (
                f"hpool fusion needs the whole image per program "
                f"(row_block {v.row_block} < ho {g.out_h})"
            )
        if v.k_block:
            return "hpool fusion does not compose with k_block"
    if v.fuse == "block":
        # One gate for builder, wrapper, and sweep: ops.megakernel owns
        # the block-fusion geometry rules, so a candidate this accepts is
        # exactly one ops.pallas_model._conv_then_pool would fuse.
        why = block_fusible_reason(
            variant=v.conv, row_block=v.row_block, k_block=v.k_block,
            pool=v.pool, out_h=g.out_h, pool_window=g.pool_window,
        )
        if why:
            return why
    return ""


def _effective_signature(v: KernelVariants, g: ConvGeometry) -> tuple:
    """What actually lowers: row blocks clamp to the output height, and the
    pool knob is moot without an adjacent pool."""
    return (
        v.conv,
        v.pool if g.has_pool else "-",
        min(v.row_block, g.out_h),
        v.k_block,
        v.fuse,
    )


def candidate_space(
    g: ConvGeometry,
    *,
    interpret: bool,
    dtype: str = "fp32",
    on_prune: Optional[Callable[[KernelVariants, str], None]] = None,
) -> List[KernelVariants]:
    """Every legal, effectively-distinct candidate for this layer, each
    bound to the layer's K so logs/plans are self-labeling. ``dtype``: the
    sweep's precision policy (int8w excludes epilogue fusion — see
    prune_reason)."""
    seen: set = set()
    out: List[KernelVariants] = []
    for conv, pool, rb, kb, fuse in itertools.product(
        CONV_VARIANTS, POOL_VARIANTS, ROW_BLOCKS, K_BLOCKS, FUSES
    ):
        v = KernelVariants(
            conv=conv, pool=pool, row_block=rb, k_block=kb, fuse=fuse,
            k_channels=g.out_channels,
        )
        why = prune_reason(v, g, interpret=interpret, dtype=dtype)
        if not why:
            sig = _effective_signature(v, g)
            if sig in seen:
                why = f"duplicate effective lowering {sig}"
            else:
                seen.add(sig)
                out.append(v)
                continue
        if on_prune is not None:
            on_prune(v, why)
    return out


def layer_tuning_units(model_cfg) -> List[Tuple[str, ConvGeometry]]:
    """(layer_name, geometry) pairs in chain order — the sweep's work list."""
    return [(g.name, g) for g in conv_geometries(model_cfg)]

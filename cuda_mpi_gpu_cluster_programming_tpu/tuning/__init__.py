"""Kernel autotuning: candidate space, sweep, and persistent TunePlans.

The per-(geometry, dtype, batch, device, code-rev) replacement for hand-set
TPU_FRAMEWORK_* variant knobs — see docs/TUNING.md. Env precedence: an
explicit env var beats a tuned plan beats the code default.
"""

from .autotune import autotune, autotune_model, tune_layer
from .plan import (
    TunePlan,
    VARIANT_ENV,
    code_rev,
    effective_layer_variants,
    explicit_env_knobs,
    load_plan,
    plan_key,
    save_plan,
    shape_key,
)
from .space import ConvGeometry, candidate_space, conv_geometries, prune_reason

__all__ = [
    "ConvGeometry",
    "TunePlan",
    "VARIANT_ENV",
    "autotune",
    "autotune_model",
    "candidate_space",
    "code_rev",
    "conv_geometries",
    "effective_layer_variants",
    "explicit_env_knobs",
    "load_plan",
    "plan_key",
    "prune_reason",
    "save_plan",
    "shape_key",
    "tune_layer",
]

"""The kernel autotuner: sweep, time, pick, persist.

Per conv layer (the ``_conv_then_pool`` lowering unit) the sweep times every
legal ``KernelVariants`` candidate with the repo's chained-timing discipline
(``utils.timing.amortized_stats``: warmup chain, repeat chain, CI95 on the
median — the same estimator every committed headline uses, so tuned-vs-
default deltas are apples-to-apples) and persists the winners as a
``TunePlan`` keyed by (device kind, geometry, batch, dtype, code revision).

Resilience contract (PR-1 layer): the whole sweep runs under a ``Deadline``
and every candidate is a chaos-injectable ``kernel_compile`` site. A
candidate that fails to compile/lower is recorded and skipped; a layer whose
candidates ALL fail, or that the deadline cuts off, degrades to the DEFAULT
variants — the plan says so in ``degraded`` and per-layer stats, and the
caller gets a usable plan instead of a wedge.
"""

from __future__ import annotations

import dataclasses
import functools
from pathlib import Path
from typing import Callable, Dict, Optional, Tuple

from ..observability.trace import span as obs_span
from ..ops.pallas_kernels import KernelVariants
from ..resilience import chaos
from ..resilience.policy import Deadline
from .plan import (
    TunePlan,
    code_rev,
    load_plan,
    load_policy,
    save_plan,
    save_policy,
    shape_key,
)
from .space import ConvGeometry, candidate_space, layer_tuning_units

# The dtype dimension of the sweep — precision policy names, reference
# floor first (also the deterministic tie-break order).
DTYPES = ("fp32", "bf16", "int8w")

# timer(geometry, variants, dtype, batch, repeats, warmup) -> (ms, ci95, n).
# Injectable so tier-1 tests sweep deterministically without timing jax.
Timer = Callable[[ConvGeometry, KernelVariants, str, int, int, int],
                 Tuple[float, float, int]]


def _default_timer(
    g: ConvGeometry, v: KernelVariants, dtype: str, batch: int,
    repeats: int, warmup: int,
) -> Tuple[float, float, int]:
    """Time one candidate on the real backend via the production lowering
    path (``_conv_then_pool``, or its quantized counterpart for int8w —
    the same gates the model forward runs)."""
    import jax
    import jax.numpy as jnp

    from ..models.alexnet import ConvSpec, LrnSpec, PoolSpec
    from ..ops import pallas_kernels as pk
    from ..ops.pallas_model import _conv_then_pool
    from ..utils.timing import amortized_stats

    cspec = ConvSpec(g.out_channels, g.filter_size, g.stride, g.padding)
    pspec = PoolSpec(g.pool_window, g.pool_stride) if g.has_pool else None
    # The block's trailing LRN (when the model has one) is timed for EVERY
    # candidate — fused candidates fold it in-kernel, staged candidates run
    # it as the trailing launch — so fused-vs-staged compare equal work.
    lrn = LrnSpec(*g.lrn) if g.lrn else None
    n_small = max(1, warmup)
    if dtype == "int8w":
        # The quantized lowering unit: bf16 activations, int8-valued bf16
        # weights, fp32 accumulate, per-channel rescale + bias + ReLU
        # between conv and pool (precision.quantize.int8w_conv_then_pool).
        from ..precision.quantize import int8w_conv, int8w_conv_then_pool

        x = jnp.full((batch, g.in_h, g.in_w, g.in_channels), 1.0, jnp.bfloat16)
        q = jnp.ones(
            (g.filter_size, g.filter_size, g.in_channels, g.out_channels),
            jnp.int8,
        )
        s = jnp.full((g.out_channels,), 0.01, jnp.float32)
        b = jnp.zeros((g.out_channels,), jnp.float32)
        if pspec is not None:
            fn = jax.jit(
                lambda x, q, s, b: int8w_conv_then_pool(
                    x, q, s, b, cspec, pspec, v, tier="pallas", lrn=lrn
                )
            )
        else:
            fn = jax.jit(
                lambda x, q, s, b: int8w_conv(
                    x, q, s, b, stride=g.stride, padding=g.padding,
                    tier="pallas", variants=v,
                )
            )
        st = amortized_stats(
            fn, x, q, s, b,
            n_small=n_small, n_large=n_small + max(1, repeats),
            min_samples=2, max_samples=4,
        )
        return st.per_call_ms, st.ci95_ms, st.n_samples
    jdt = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    x = jnp.full((batch, g.in_h, g.in_w, g.in_channels), 1.0, jdt)
    w = jnp.full(
        (g.filter_size, g.filter_size, g.in_channels, g.out_channels), 0.01, jdt
    )
    b = jnp.zeros((g.out_channels,), jdt)
    if g.has_pool:
        fn = jax.jit(
            lambda x, w, b: _conv_then_pool(x, w, b, cspec, pspec, v, lrn=lrn)
        )
    else:
        fn = jax.jit(
            functools.partial(
                pk.conv2d_pallas, stride=g.stride, padding=g.padding, relu=True,
                variant=v.conv, row_block=v.row_block, k_block=v.k_block,
            )
        )
    st = amortized_stats(
        fn, x, w, b,
        n_small=n_small, n_large=n_small + max(1, repeats),
        min_samples=2, max_samples=4,
    )
    return st.per_call_ms, st.ci95_ms, st.n_samples


def _interpret_mode() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def tune_layer(
    g: ConvGeometry,
    *,
    dtype: str,
    batch: int,
    deadline: Deadline,
    repeats: int,
    warmup: int,
    timer: Timer,
    log: Callable[[str], None],
    interpret: Optional[bool] = None,
    block_screen: str = "",
) -> Tuple[KernelVariants, dict, str]:
    """Sweep one layer; returns (winner, stats, degraded_reason).

    ``block_screen``: a non-empty string prunes every ``fuse="block"``
    candidate with that reason BEFORE timing — the dtype sweep passes the
    ToleranceGate's block-screen failure here, so a megakernel that fails
    its fp32-oracle screen never spends timing budget and its fate is
    attributable in the plan record (``pruned_reasons``), exactly like a
    geometry prune."""
    interpret = _interpret_mode() if interpret is None else interpret
    default = KernelVariants().bind(g.out_channels)
    pruned: list = []
    cands = candidate_space(
        g, interpret=interpret, dtype=dtype,
        on_prune=lambda v, why: pruned.append(why),
    )
    if block_screen:
        kept = []
        for v in cands:
            if v.fuse == "block":
                pruned.append(block_screen)
            else:
                kept.append(v)
        cands = kept
    ch = chaos.active()
    timed: list = []   # (ms, ci95, n, variants)
    failed: list = []  # (variants, cause)
    cut = ""
    for v in cands:
        if deadline.expired:
            cut = (
                f"deadline expired after {len(timed) + len(failed)}/"
                f"{len(cands)} candidates"
            )
            break
        try:
            if ch is not None:
                ch.maybe_raise("kernel_compile", f"tune {g.name} [{v.label()}]")
            # One span per timed candidate (observability.trace; no-op
            # untraced): the sweep's wall time becomes attributable
            # per-candidate in the exported timeline.
            with obs_span(
                "tune.candidate", layer=g.name, variant=v.label(), dtype=dtype
            ) as sp:
                ms, ci, n = timer(g, v, dtype, batch, repeats, warmup)
                if sp is not None:
                    sp.set(ms=round(ms, 4), ci95_ms=round(ci, 4), n=n)
            timed.append((ms, ci, n, v))
            log(f"tune {g.name}: {v.label()} -> {ms:.3f} ms (ci95 {ci:.3f}, n={n})")
        except Exception as e:  # noqa — a broken candidate must not kill the sweep
            cause = f"{type(e).__name__}: {e}"[:120]
            failed.append((v, cause))
            log(f"tune {g.name}: {v.label()} FAILED ({cause})")
    # Attributable prunes in the persisted record: reason -> count, so a
    # plan says WHY every dropped candidate (geometry, dtype policy, or a
    # gate-failed megakernel) is absent — not just how many.
    reasons: dict = {}
    for why in pruned:
        reasons[why] = reasons.get(why, 0) + 1
    stats = {
        "geometry": g.describe(),
        "candidates": len(cands),
        "pruned": len(pruned),
        "pruned_reasons": reasons,
        "timed": len(timed),
        "failed": len(failed),
    }
    if not timed:
        reason = cut or (
            f"all {len(cands)} candidates failed" if failed else "no legal candidates"
        )
        stats["degraded"] = reason
        log(f"tune {g.name}: DEGRADED to defaults ({reason})")
        return default, stats, reason
    best_ms, best_ci, best_n, winner = min(timed, key=lambda t: t[0])
    stats.update(
        best_ms=round(best_ms, 4), best_ci95_ms=round(best_ci, 4), best_n=best_n
    )
    # The default lowering's time, for the tuned-vs-default story — matched
    # by effective signature (rb=64 and rb=32 can be the same lowering on a
    # 27-row image; either row is THE default's measurement).
    from .space import _effective_signature

    dsig = _effective_signature(default, g)
    for ms, _ci, _n, v in timed:
        if _effective_signature(v, g) == dsig:
            stats["default_ms"] = round(ms, 4)
            break
    if cut:
        stats["degraded"] = cut  # partial sweep: winner stands, but say so
    log(
        f"tune {g.name}: winner {winner.label()} at {best_ms:.3f} ms"
        + (f" (default {stats['default_ms']:.3f} ms)" if "default_ms" in stats else "")
    )
    return winner, stats, cut


def autotune_model(
    model_cfg,
    *,
    dtype: str,
    batch: int,
    deadline: Optional[Deadline] = None,
    repeats: int = 5,
    warmup: int = 2,
    timer: Optional[Timer] = None,
    log: Callable[[str], None] = print,
    device_kind: Optional[str] = None,
    block_screen: str = "",
) -> TunePlan:
    """Sweep every conv layer of ``model_cfg`` and return the TunePlan."""
    deadline = deadline or Deadline.after(None)
    timer = timer or _default_timer
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    layers: list = []
    stats: dict = {}
    notes: list = []
    for name, g in layer_tuning_units(model_cfg):
        if deadline.expired:
            # Degrade, don't wedge: remaining layers get the defaults and the
            # plan says which and why.
            layers.append((name, KernelVariants().bind(g.out_channels)))
            stats[name] = {
                "geometry": g.describe(),
                "degraded": "deadline expired before sweep",
            }
            notes.append(f"{name}: deadline expired before sweep")
            continue
        with obs_span("tune.layer", layer=name, dtype=dtype, batch=batch):
            winner, lstats, degraded = tune_layer(
                g, dtype=dtype, batch=batch, deadline=deadline,
                repeats=repeats, warmup=warmup, timer=timer, log=log,
                block_screen=block_screen,
            )
        layers.append((name, winner))
        stats[name] = lstats
        if degraded:
            notes.append(f"{name}: {degraded}")
    return TunePlan(
        device_kind=device_kind,
        shape_key=shape_key(model_cfg),
        batch=batch,
        dtype=dtype,
        code_rev=code_rev(),
        layers=tuple(layers),
        stats=stats,
        degraded="; ".join(notes),
    )


def autotune(
    path,
    model_cfg,
    *,
    dtype: str,
    batch: int,
    force: bool = False,
    deadline: Optional[Deadline] = None,
    repeats: int = 5,
    warmup: int = 2,
    timer: Optional[Timer] = None,
    log: Callable[[str], None] = print,
    device_kind: Optional[str] = None,
    block_screen: str = "",
) -> Tuple[TunePlan, bool]:
    """Cached sweep: a fresh on-disk plan for this exact point (same device,
    geometry, batch, dtype, code revision) short-circuits the whole sweep —
    ``(plan, True)``. Otherwise sweep, persist, ``(plan, False)``."""
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    if not force:
        cached = load_plan(
            path, device_kind=device_kind, model_cfg=model_cfg,
            dtype=dtype, batch=batch, match_any_batch=False,
        )
        if cached is not None:
            return cached, True
    plan = autotune_model(
        model_cfg, dtype=dtype, batch=batch, deadline=deadline,
        repeats=repeats, warmup=warmup, timer=timer, log=log,
        device_kind=device_kind, block_screen=block_screen,
    )
    save_plan(plan, path)
    return plan, False


# --------------------------------------------------------- dtype sweep ----


@dataclasses.dataclass
class PrecisionResult:
    """Outcome of one dtype-swept autotune: per-dtype kernel plans, the
    winning policy, and the attributable fate of every pruned dtype."""

    winner: str
    plans: Dict[str, TunePlan]
    pruned: Dict[str, str]  # dtype -> gate reason (attributable, journaled)
    gates: Dict[str, dict]  # dtype -> GateResult.to_obj()
    cached: bool = False

    @property
    def plan(self) -> Optional[TunePlan]:
        return self.plans.get(self.winner)

    def summary(self) -> str:
        parts = []
        for dt in DTYPES:
            if dt in self.pruned:
                parts.append(f"{dt}=gate-pruned")
            elif dt in self.plans:
                s = _plan_score(self.plans[dt])
                label = f"{s:.3f}ms" if s != float("inf") else "degraded"
                parts.append(f"{dt}={label}" + (" *" if dt == self.winner else ""))
        return " ".join(parts)


def _plan_score(plan: TunePlan) -> float:
    """Total best-candidate time across the plan's layers — the number the
    dtype race is decided on. A layer that degraded without a timed winner
    makes the whole dtype unscoreable (inf): an untimed dtype must not win."""
    total = 0.0
    for name, _v in plan.layers:
        ms = plan.stats.get(name, {}).get("best_ms")
        if not isinstance(ms, (int, float)):
            return float("inf")
        total += ms
    return total


def autotune_precision(
    path,
    model_cfg,
    *,
    batch: int,
    dtypes: Tuple[str, ...] = DTYPES,
    force: bool = False,
    deadline: Optional[Deadline] = None,
    repeats: int = 5,
    warmup: int = 2,
    timer: Optional[Timer] = None,
    log: Callable[[str], None] = print,
    device_kind: Optional[str] = None,
    gate=None,
    gate_journal: str = "",
    gate_batch: int = 2,
    seed: int = 0,
) -> PrecisionResult:
    """ONE sweep covering {fp32, bf16, int8w} x kernel variants per conv
    layer (ROADMAP item 2's first half).

    Per dtype: every non-fp32 candidate is first screened by the
    :class:`~..precision.gate.ToleranceGate` against the fp32 oracle on
    params/input drawn from the seeded init stream — a gate failure prunes
    the WHOLE dtype with an attributable reason (journaled ``gate_fail``)
    before a second of timing budget is spent on it. Surviving dtypes get
    the full per-layer kernel-variant sweep (``autotune`` — each dtype's
    plan lands under its own key, so fp32 is always kept as the reference
    floor). The winner is the dtype with the lowest summed best-candidate
    time; its policy record is persisted next to the plans
    (``plan.save_policy``) — by construction a non-fp32 winner exists only
    with a journaled ``gate_pass`` record.

    Blocks 1-2 geometries only (the gate's staged oracle is the Blocks 1-2
    chain); full-AlexNet callers keep using single-dtype ``autotune``."""
    if hasattr(model_cfg, "blocks12"):
        raise ValueError(
            "dtype-swept autotune supports Blocks 1-2 configs only "
            "(the tolerance gate screens the Blocks 1-2 staged oracle); "
            "use autotune(dtype=...) for alexnet_full"
        )
    unknown = [dt for dt in dtypes if dt not in DTYPES]
    if unknown:
        raise ValueError(f"unknown sweep dtypes {unknown} (valid: {DTYPES})")
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind

    # Cache: a fresh policy record covering the SAME dtype set, plus a
    # fresh plan per surviving dtype, short-circuits gate + sweep alike.
    if not force:
        rec = load_policy(
            path, device_kind=device_kind, model_cfg=model_cfg, batch=batch,
            match_any_batch=False,
        )
        if rec is not None and set(rec.get("swept", [])) == set(dtypes):
            plans = {}
            complete = True
            for dt in dtypes:
                if dt in rec.get("pruned", {}):
                    continue
                cached_plan = load_plan(
                    path, device_kind=device_kind, model_cfg=model_cfg,
                    dtype=dt, batch=batch, match_any_batch=False,
                )
                if cached_plan is None:
                    complete = False
                    break
                plans[dt] = cached_plan
            if complete and rec.get("dtype") in plans:
                return PrecisionResult(
                    winner=rec["dtype"],
                    plans=plans,
                    pruned=dict(rec.get("pruned", {})),
                    gates=dict(rec.get("gates", {})),
                    cached=True,
                )

    if gate is None:
        from ..precision.gate import ToleranceGate
        from ..resilience.journal import Journal

        jpath = gate_journal or str(Path(path).with_name(
            Path(path).stem + "_gate.jsonl"
        ))
        gate = ToleranceGate(journal=Journal(jpath))

    # Gate inputs come from the seeded init stream — the keyed random init
    # (constant init is degenerate for per-channel scales: every channel
    # identical), reproducible across processes from the seed alone.
    import jax

    from ..models.init import init_params_random, random_input

    kp, kx = jax.random.split(jax.random.PRNGKey(seed))
    params = init_params_random(kp, model_cfg)
    x = random_input(kx, gate_batch, model_cfg)

    sk = shape_key(model_cfg)
    plans: Dict[str, TunePlan] = {}
    pruned: Dict[str, str] = {}
    gates: Dict[str, dict] = {}
    inner_cached: list = []
    for dt in dtypes:
        with obs_span("tune.gate", dtype=dt):
            res = gate.screen(
                dt, params, x, model_cfg,
                key=f"gate:{dt}|{device_kind}|{sk}|b{batch}",
            )
        gates[dt] = res.to_obj()
        if not res.passed:
            # fp32 failing means the ORACLE CHAIN is broken (preflight or
            # budgets) — prune it like any other dtype; the caller sees an
            # attributable reason instead of a silently-blessed floor.
            pruned[dt] = res.reason()
            log(f"tune dtype {dt}: GATE-PRUNED ({res.reason()})")
            continue
        log(
            f"tune dtype {dt}: gate pass (margin {res.margin:.3f}, "
            f"worst stage {res.worst_stage or '-'})"
        )
        # Second screen, block granularity: the megakernel's fused block
        # outputs vs the fp32 oracle's block boundaries. A failure prunes
        # ONLY the fuse="block" candidates for this dtype (journaled,
        # reason lands in the plan's pruned_reasons) — the staged chain
        # already passed its per-stage screen above. Injectable gates
        # without the method (test stubs) skip the screen.
        block_screen = ""
        if hasattr(gate, "screen_blocks"):
            with obs_span("tune.gate_blocks", dtype=dt):
                bres = gate.screen_blocks(
                    dt, params, x, model_cfg,
                    key=f"gate-blocks:{dt}|{device_kind}|{sk}|b{batch}",
                )
            if not bres.passed:
                block_screen = (
                    f"fuse=block gate-pruned for {dt}: {bres.reason()}"
                )
                log(f"tune dtype {dt}: megakernel {block_screen}")
            else:
                log(
                    f"tune dtype {dt}: megakernel block gate pass "
                    f"(margin {bres.margin:.3f})"
                )
        plan, was_cached = autotune(
            path, model_cfg, dtype=dt, batch=batch, force=force,
            deadline=deadline, repeats=repeats, warmup=warmup, timer=timer,
            log=log, device_kind=device_kind, block_screen=block_screen,
        )
        plans[dt] = plan
        inner_cached.append(was_cached)
        log(
            f"tune dtype {dt}: plan {'cache' if was_cached else 'swept'} "
            f"hash={plan.plan_hash()}"
        )

    if not plans:
        raise RuntimeError(
            "every sweep dtype was gate-pruned: "
            + "; ".join(f"{d}: {r}" for d, r in pruned.items())
        )
    scores = {dt: _plan_score(p) for dt, p in plans.items()}
    finite = {dt: s for dt, s in scores.items() if s != float("inf")}
    if finite:
        winner = min(finite, key=lambda dt: (finite[dt], DTYPES.index(dt)))
    else:
        # Nothing timed anywhere (deadline/chaos): the reference floor
        # stands if present; otherwise the first surviving dtype.
        winner = "fp32" if "fp32" in plans else next(iter(plans))
    if len(dtypes) > 1:
        # Single-dtype (pinned) sweeps must not clobber the full-sweep
        # policy record with a race they never ran.
        save_policy(
            path, device_kind=device_kind, model_cfg=model_cfg, batch=batch,
            dtype=winner, swept=dtypes, pruned=pruned, gates=gates,
        )
    result = PrecisionResult(
        winner=winner, plans=plans, pruned=pruned, gates=gates,
        # A pinned (single-dtype) re-run whose every inner sweep hit the
        # plan cache is a cache outcome too, even though no policy record
        # short-circuited it (only multi-dtype sweeps write the record).
        cached=bool(inner_cached) and all(inner_cached) and not force,
    )
    log(f"tune dtype winner: {winner} ({result.summary()})")
    return result

"""The kernel autotuner: sweep, time, pick, persist.

Per conv layer (the ``_conv_then_pool`` lowering unit) the sweep times every
legal ``KernelVariants`` candidate with the repo's chained-timing discipline
(``utils.timing.amortized_stats``: warmup chain, repeat chain, CI95 on the
median — the same estimator every committed headline uses, so tuned-vs-
default deltas are apples-to-apples) and persists the winners as a
``TunePlan`` keyed by (device kind, geometry, batch, dtype, code revision).

Resilience contract (PR-1 layer): the whole sweep runs under a ``Deadline``
and every candidate is a chaos-injectable ``kernel_compile`` site. A
candidate that fails to compile/lower is recorded and skipped; a layer whose
candidates ALL fail, or that the deadline cuts off, degrades to the DEFAULT
variants — the plan says so in ``degraded`` and per-layer stats, and the
caller gets a usable plan instead of a wedge.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

from ..ops.pallas_kernels import KernelVariants
from ..resilience import chaos
from ..resilience.policy import Deadline
from .plan import TunePlan, code_rev, load_plan, save_plan, shape_key
from .space import ConvGeometry, candidate_space, layer_tuning_units

# timer(geometry, variants, dtype, batch, repeats, warmup) -> (ms, ci95, n).
# Injectable so tier-1 tests sweep deterministically without timing jax.
Timer = Callable[[ConvGeometry, KernelVariants, str, int, int, int],
                 Tuple[float, float, int]]


def _default_timer(
    g: ConvGeometry, v: KernelVariants, dtype: str, batch: int,
    repeats: int, warmup: int,
) -> Tuple[float, float, int]:
    """Time one candidate on the real backend via the production lowering
    path (``_conv_then_pool`` — the same gates the model forward runs)."""
    import jax
    import jax.numpy as jnp

    from ..models.alexnet import ConvSpec, PoolSpec
    from ..ops import pallas_kernels as pk
    from ..ops.pallas_model import _conv_then_pool
    from ..utils.timing import amortized_stats

    jdt = jnp.bfloat16 if dtype == "bf16" else jnp.float32
    x = jnp.full((batch, g.in_h, g.in_w, g.in_channels), 1.0, jdt)
    w = jnp.full(
        (g.filter_size, g.filter_size, g.in_channels, g.out_channels), 0.01, jdt
    )
    b = jnp.zeros((g.out_channels,), jdt)
    cspec = ConvSpec(g.out_channels, g.filter_size, g.stride, g.padding)
    if g.has_pool:
        pspec = PoolSpec(g.pool_window, g.pool_stride)
        fn = jax.jit(lambda x, w, b: _conv_then_pool(x, w, b, cspec, pspec, v))
    else:
        fn = jax.jit(
            functools.partial(
                pk.conv2d_pallas, stride=g.stride, padding=g.padding, relu=True,
                variant=v.conv, row_block=v.row_block, k_block=v.k_block,
            )
        )
    n_small = max(1, warmup)
    st = amortized_stats(
        fn, x, w, b,
        n_small=n_small, n_large=n_small + max(1, repeats),
        min_samples=2, max_samples=4,
    )
    return st.per_call_ms, st.ci95_ms, st.n_samples


def _interpret_mode() -> bool:
    import jax

    return jax.default_backend() != "tpu"


def tune_layer(
    g: ConvGeometry,
    *,
    dtype: str,
    batch: int,
    deadline: Deadline,
    repeats: int,
    warmup: int,
    timer: Timer,
    log: Callable[[str], None],
    interpret: Optional[bool] = None,
) -> Tuple[KernelVariants, dict, str]:
    """Sweep one layer; returns (winner, stats, degraded_reason)."""
    interpret = _interpret_mode() if interpret is None else interpret
    default = KernelVariants().bind(g.out_channels)
    pruned: list = []
    cands = candidate_space(
        g, interpret=interpret, on_prune=lambda v, why: pruned.append(why)
    )
    ch = chaos.active()
    timed: list = []   # (ms, ci95, n, variants)
    failed: list = []  # (variants, cause)
    cut = ""
    for v in cands:
        if deadline.expired:
            cut = (
                f"deadline expired after {len(timed) + len(failed)}/"
                f"{len(cands)} candidates"
            )
            break
        try:
            if ch is not None:
                ch.maybe_raise("kernel_compile", f"tune {g.name} [{v.label()}]")
            ms, ci, n = timer(g, v, dtype, batch, repeats, warmup)
            timed.append((ms, ci, n, v))
            log(f"tune {g.name}: {v.label()} -> {ms:.3f} ms (ci95 {ci:.3f}, n={n})")
        except Exception as e:  # noqa — a broken candidate must not kill the sweep
            cause = f"{type(e).__name__}: {e}"[:120]
            failed.append((v, cause))
            log(f"tune {g.name}: {v.label()} FAILED ({cause})")
    stats = {
        "geometry": g.describe(),
        "candidates": len(cands),
        "pruned": len(pruned),
        "timed": len(timed),
        "failed": len(failed),
    }
    if not timed:
        reason = cut or (
            f"all {len(cands)} candidates failed" if failed else "no legal candidates"
        )
        stats["degraded"] = reason
        log(f"tune {g.name}: DEGRADED to defaults ({reason})")
        return default, stats, reason
    best_ms, best_ci, best_n, winner = min(timed, key=lambda t: t[0])
    stats.update(
        best_ms=round(best_ms, 4), best_ci95_ms=round(best_ci, 4), best_n=best_n
    )
    # The default lowering's time, for the tuned-vs-default story — matched
    # by effective signature (rb=64 and rb=32 can be the same lowering on a
    # 27-row image; either row is THE default's measurement).
    from .space import _effective_signature

    dsig = _effective_signature(default, g)
    for ms, _ci, _n, v in timed:
        if _effective_signature(v, g) == dsig:
            stats["default_ms"] = round(ms, 4)
            break
    if cut:
        stats["degraded"] = cut  # partial sweep: winner stands, but say so
    log(
        f"tune {g.name}: winner {winner.label()} at {best_ms:.3f} ms"
        + (f" (default {stats['default_ms']:.3f} ms)" if "default_ms" in stats else "")
    )
    return winner, stats, cut


def autotune_model(
    model_cfg,
    *,
    dtype: str,
    batch: int,
    deadline: Optional[Deadline] = None,
    repeats: int = 5,
    warmup: int = 2,
    timer: Optional[Timer] = None,
    log: Callable[[str], None] = print,
    device_kind: Optional[str] = None,
) -> TunePlan:
    """Sweep every conv layer of ``model_cfg`` and return the TunePlan."""
    deadline = deadline or Deadline.after(None)
    timer = timer or _default_timer
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    layers: list = []
    stats: dict = {}
    notes: list = []
    for name, g in layer_tuning_units(model_cfg):
        if deadline.expired:
            # Degrade, don't wedge: remaining layers get the defaults and the
            # plan says which and why.
            layers.append((name, KernelVariants().bind(g.out_channels)))
            stats[name] = {
                "geometry": g.describe(),
                "degraded": "deadline expired before sweep",
            }
            notes.append(f"{name}: deadline expired before sweep")
            continue
        winner, lstats, degraded = tune_layer(
            g, dtype=dtype, batch=batch, deadline=deadline,
            repeats=repeats, warmup=warmup, timer=timer, log=log,
        )
        layers.append((name, winner))
        stats[name] = lstats
        if degraded:
            notes.append(f"{name}: {degraded}")
    return TunePlan(
        device_kind=device_kind,
        shape_key=shape_key(model_cfg),
        batch=batch,
        dtype=dtype,
        code_rev=code_rev(),
        layers=tuple(layers),
        stats=stats,
        degraded="; ".join(notes),
    )


def autotune(
    path,
    model_cfg,
    *,
    dtype: str,
    batch: int,
    force: bool = False,
    deadline: Optional[Deadline] = None,
    repeats: int = 5,
    warmup: int = 2,
    timer: Optional[Timer] = None,
    log: Callable[[str], None] = print,
    device_kind: Optional[str] = None,
) -> Tuple[TunePlan, bool]:
    """Cached sweep: a fresh on-disk plan for this exact point (same device,
    geometry, batch, dtype, code revision) short-circuits the whole sweep —
    ``(plan, True)``. Otherwise sweep, persist, ``(plan, False)``."""
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    if not force:
        cached = load_plan(
            path, device_kind=device_kind, model_cfg=model_cfg,
            dtype=dtype, batch=batch, match_any_batch=False,
        )
        if cached is not None:
            return cached, True
    plan = autotune_model(
        model_cfg, dtype=dtype, batch=batch, deadline=deadline,
        repeats=repeats, warmup=warmup, timer=timer, log=log,
        device_kind=device_kind,
    )
    save_plan(plan, path)
    return plan, False

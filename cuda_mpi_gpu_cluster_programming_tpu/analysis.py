"""Performance-analysis ETL: warehouse, stats, speedup, plots, exports.

Python analogue of the reference's L6 layer (``log_analysis.py``, 296 LoC,
Typer + DuckDB). DuckDB is not in this image, so the warehouse is stdlib
``sqlite3`` with registered aggregate functions giving the same SQL-view
surface; the command set is identical:

- ``ingest``  — walk a logs root, SHA1-dedup files (log_analysis.py:104,113-115),
  load harness summary CSVs, scrape run logs by regex, compute source stats
  (log_analysis.py:75-160 analogue).
- ``stats``   — run_stats view: n, mean, stddev, 95% CI per variant/np/batch
  (log_analysis.py:176-198).
- ``speedup`` — S(N)=T1/TN and E=S/N against the V1 serial baseline, in SQL
  (log_analysis.py:213-222).
- ``plot``    — matplotlib speedup/efficiency PNGs (log_analysis.py:226-266).
- ``export``  — dump any view to csv/parquet (log_analysis.py:269-292).

Variant names ingested from the harness CSVs use the reference's canonical
version-name mapping (analysis.md:60-80) extended with the V6 TPU family, so
historical reference data and new TPU data plot on the same axes.
"""

from __future__ import annotations

import argparse
import csv
import hashlib
import json
import math
import re
import sqlite3
import statistics
import sys
from pathlib import Path
from typing import List, Optional

DEFAULT_DB = ".warehouse/cluster_logs.sqlite"

# Canonical version-name normalisation (analysis.md:60-80 analogue): maps raw
# variant strings from either the reference's CSVs or ours onto one family.
CANONICAL_VARIANTS = {
    "v1": "V1 Serial",
    "v1 serial": "V1 Serial",
    "v1_serial": "V1 Serial",
    "v2.1": "V2.1 BroadcastAll",
    "v2 2.1-broadcast-all": "V2.1 BroadcastAll",
    "v2.1 broadcastall": "V2.1 BroadcastAll",
    "v2.2": "V2.2 ScatterHalo",
    "v2 2.2-scatter-halo": "V2.2 ScatterHalo",
    "v2.2 scatterhalo": "V2.2 ScatterHalo",
    "v3": "V3 CUDA",
    "v3 cuda": "V3 CUDA",
    "v3 cuda only": "V3 CUDA",
    "v4": "V4 MPI+CUDA",
    "v4 mpi+cuda": "V4 MPI+CUDA",
    "v5": "V5 MPI+CUDA-Aware",
    "v5 mpi+cuda-aware": "V5 MPI+CUDA-Aware",
}


def canonical_variant(name: str) -> str:
    return CANONICAL_VARIANTS.get(name.strip().lower(), name.strip())


class _Stdev:
    """Sample stddev aggregate (DuckDB's stddev_samp analogue for sqlite)."""

    def __init__(self) -> None:
        self.vals: List[float] = []

    def step(self, v) -> None:
        if v is not None:
            self.vals.append(float(v))

    def finalize(self) -> Optional[float]:
        # NULL for n<2, matching DuckDB's stddev_samp — a single-sample group
        # must not masquerade as a zero-variance measurement.
        return statistics.stdev(self.vals) if len(self.vals) > 1 else None


def _backfill_platform(conn: sqlite3.Connection) -> None:
    """Derive platform for rows that predate the column. The
    sha1-incremental ingest never revisits unchanged CSVs, so without this
    an upgraded warehouse would keep pooling its old CPU and TPU rows in
    one NULL-platform group — the exact conflation the column exists to
    fix. Runs on EVERY connect, not just the migration: it is idempotent
    (only NULL rows are touched, so the steady-state query is cheap) and a
    one-shot attempt could fail silently-forever when the log paths don't
    resolve from the current cwd (src_csv is stored as ingested, often
    relative) — retrying each connect picks those rows up the next time
    the warehouse is opened from the right directory."""
    # Reference-corpus rows intentionally stay NULL (platform is encoded in
    # the variant name), so exclude them in SQL — otherwise every connect
    # re-fetches and re-skips them forever (round-3 advisor finding); the
    # steady-state scan only sees genuinely unresolved rows.
    rows = conn.execute(
        "SELECT rowid, src_csv, log_file, corpus FROM summary_runs "
        "WHERE platform IS NULL "
        "  AND COALESCE(corpus, '') != 'reference' "
        "  AND src_csv IS NOT NULL AND src_csv != '' "
        "  AND NOT (corpus IS NULL AND (src_csv LIKE '%/reference/%' "
        "           OR src_csv LIKE '%reference_import%'))"
    ).fetchall()
    defaults: dict = {}
    n = 0
    for rowid, src_csv, log_file, corpus in rows:
        csv_path = Path(src_csv)
        if csv_path not in defaults:
            defaults[csv_path] = _session_platform(csv_path)
        p = _row_platform({"LogFile": log_file}, csv_path, defaults[csv_path])
        if p:
            conn.execute(
                "UPDATE summary_runs SET platform=? WHERE rowid=?", (p, rowid)
            )
            n += 1
    if n:
        # Persist explicitly: read-only subcommands (stats/speedup/plot/
        # export/report) never call conn.commit(), so without this the
        # UPDATEs roll back on close and the backfill re-runs forever.
        conn.commit()
        print(f"backfilled platform for {n} pre-migration rows", file=sys.stderr)


def connect(db_path: str | Path) -> sqlite3.Connection:
    path = Path(db_path)
    path.parent.mkdir(parents=True, exist_ok=True)
    conn = sqlite3.connect(path)
    conn.create_aggregate("stddev_samp", 1, _Stdev)
    # SQLite's built-in math functions (SQRT among them) are a compile-time
    # option (-DSQLITE_ENABLE_MATH_FUNCTIONS) this image's build lacks —
    # register a Python sqrt so the run_stats ci95 view works on any build.
    # NULL-in/NULL-out and negative-input NULL match the SQL convention.
    conn.create_function(
        "SQRT", 1,
        lambda v: math.sqrt(v) if v is not None and v >= 0 else None,
        deterministic=True,
    )
    conn.executescript(
        """
        CREATE TABLE IF NOT EXISTS file_index (
            path TEXT PRIMARY KEY, sha1 TEXT, kind TEXT, ingested_at TEXT
        );
        CREATE TABLE IF NOT EXISTS summary_runs (
            session_id TEXT, machine_id TEXT, git_commit TEXT, ts TEXT,
            variant TEXT, config_key TEXT, np INTEGER, batch INTEGER,
            build_status TEXT, run_status TEXT, parse_status TEXT, status TEXT,
            time_ms REAL, compile_ms REAL, shape TEXT, first5 TEXT,
            log_file TEXT, src_csv TEXT, corpus TEXT, platform TEXT
        );
        CREATE TABLE IF NOT EXISTS run_logs (
            path TEXT, session_id TEXT, time_ms REAL, shape TEXT
        );
        CREATE TABLE IF NOT EXISTS source_stats (
            path TEXT PRIMARY KEY, loc INTEGER, lang TEXT
        );
        """
    )
    # Migration for warehouses created before the corpus column existed:
    # add it (NULL rows fall through to the view's src_csv heuristic). Must
    # run before the views below, which reference the column.
    cols = {r[1] for r in conn.execute("PRAGMA table_info(summary_runs)")}
    if "corpus" not in cols:  # pragma: no cover — legacy DB only
        conn.execute("ALTER TABLE summary_runs ADD COLUMN corpus TEXT")
    if "platform" not in cols:
        conn.execute("ALTER TABLE summary_runs ADD COLUMN platform TEXT")
    conn.executescript(
        """
        DROP VIEW IF EXISTS perf_runs;
        DROP VIEW IF EXISTS best_runs;
        DROP VIEW IF EXISTS run_stats;
        CREATE VIEW perf_runs AS
            SELECT session_id, machine_id, git_commit, variant, config_key,
                   np, batch, time_ms, compile_ms, shape,
                   COALESCE(corpus,
                       CASE WHEN src_csv LIKE '%/reference/%'
                              OR src_csv LIKE '%reference_import%'
                            THEN 'reference' ELSE 'local' END) AS corpus,
                   platform
            FROM summary_runs
            WHERE status = 'OK' AND time_ms IS NOT NULL;
        -- Grouping includes platform: one machine's sessions span the CPU
        -- fallback and the tunneled TPU; pooling 11 ms CPU passes with
        -- 0.3 ms TPU passes would fabricate wild stddevs and meaningless
        -- baselines (NULL platform = pre-column or reference rows, which
        -- group among themselves per corpus).
        CREATE VIEW best_runs AS
            SELECT variant, np, batch, MIN(time_ms) AS best_ms, COUNT(*) AS n,
                   corpus, platform
            FROM perf_runs GROUP BY corpus, platform, variant, np, batch;
        CREATE VIEW run_stats AS
            SELECT variant, np, batch, COUNT(*) AS n,
                   AVG(time_ms) AS mean_ms,
                   stddev_samp(time_ms) AS stdev_ms,
                   1.96 * stddev_samp(time_ms) / SQRT(COUNT(*)) AS ci95_ms,
                   corpus, platform
            FROM perf_runs GROUP BY corpus, platform, variant, np, batch;
        """
    )
    _backfill_platform(conn)
    return conn


def _sha1(path: Path) -> str:
    h = hashlib.sha1()
    h.update(path.read_bytes())
    return h.hexdigest()


def _already_ingested(conn: sqlite3.Connection, path: Path, sha1: str) -> bool:
    row = conn.execute("SELECT sha1 FROM file_index WHERE path=?", (str(path),)).fetchone()
    return row is not None and row[0] == sha1


def _mark(conn: sqlite3.Connection, path: Path, sha1: str, kind: str) -> None:
    conn.execute(
        "INSERT OR REPLACE INTO file_index VALUES (?,?,?,datetime('now'))",
        (str(path), sha1, kind),
    )


# Reference-schema column mapping (log_analysis.py:45-72 normalises two
# schema generations; we accept both of them plus our own):
# gen-2 = the reference's session CSVs (summary_report_*.csv), gen-1 = its
# early ts/version/np/total_time_s exports (all_runs.csv style).
_REF_GEN2_MAP = {
    "ProjectVariant": "Variant",
    "NumProcesses": "NP",
    "EntryTimestamp": "Timestamp",
    "OutputFirst5Values": "First5Values",
    "RunLogFile": "LogFile",
    "OverallStatusSymbol": "Status",
}


def _normalize_row(r: dict) -> dict:
    """Normalise one CSV row to our column names and tag its corpus.

    The corpus ('reference' vs 'local') is decided by SCHEMA, not by file
    path: both reference schema generations are unmistakable from their
    headers, so reference CSVs copied anywhere (a tmp logs tree, a
    reference_import staging dir) still classify correctly. The per-corpus
    speedup baseline depends on this tag.
    """
    if "ProjectVariant" in r:  # reference gen-2 session schema
        out = dict(r)
        out["_corpus"] = "reference"
        for src, dst in _REF_GEN2_MAP.items():
            if src in out:
                out[dst] = out.pop(src)
        for src, dst in (
            ("BuildSucceeded", "BuildStatus"),
            ("RunCommandSucceeded", "RunStatus"),
            ("ParseSucceeded", "ParseStatus"),
        ):
            if src in out:
                out[dst] = "OK" if str(out.pop(src)).lower() == "true" else "FAIL"
        # Status symbols (✔/⚠/✘, common_test_utils.sh:119-178) -> our words,
        # so the perf_runs view's status='OK' filter sees both corpora.
        out["Status"] = {"✔": "OK", "⚠": "WARN", "✘": "FAIL", "✗": "FAIL"}.get(
            str(out.get("Status", "")).strip(), out.get("Status")
        )
        return out
    if "version" in r and "total_time_s" in r:  # reference gen-1 export schema
        out = {
            "_corpus": "reference",
            "Timestamp": r.get("ts"),
            "Variant": r.get("version"),
            "NP": r.get("np"),
            # gen-1 exports (all_runs.csv) contain only completed perf runs —
            # no status column exists, so mark OK or the perf_runs view's
            # status='OK' filter would silently drop the whole corpus.
            "Status": "OK",
        }
        if r.get("total_time_s"):
            out["ExecutionTime_ms"] = str(float(r["total_time_s"]) * 1e3)
        return out
    return r


_RE_DEVICES = re.compile(r"Devices: \d+ x .+ \((\w+)\)")


def _session_platform(csv_path: Path) -> Optional[str]:
    """Session-level platform fallback from the harness's env.json dump
    ('axon' is the tunneled TPU registration — see the verify skill)."""
    try:
        env = json.loads((csv_path.parent / "env.json").read_text()).get("env", {})
    except (OSError, ValueError):
        return None
    # JAX_PLATFORMS is a comma-separated priority list; the first entry is
    # the effective backend ('axon,cpu' must not mint a separate group).
    jp = str(env.get("JAX_PLATFORMS", "")).lower().split(",")[0].strip()
    if jp in ("axon", "tpu"):
        return "tpu"
    return jp or None


def _row_platform(r: dict, csv_path: Path, session_default: Optional[str]) -> Optional[str]:
    """Per-row platform: the run log's 'Devices: N x <kind> (<platform>)'
    line is authoritative (a session could mix backends); fall back to the
    session env. Reference-corpus rows get NULL — their platform axis
    (CPU vs CUDA) is already encoded in the variant name."""
    if r.get("_corpus") == "reference":
        return None
    log = r.get("LogFile")
    if log:
        try:
            m = _RE_DEVICES.search((csv_path.parent / log).read_text(errors="replace"))
            if m:
                return m.group(1).lower()
        except OSError:
            pass
    return session_default


def ingest_summary_csv(conn: sqlite3.Connection, path: Path) -> int:
    """Load one summary CSV — ours (harness.CSV_COLUMNS) or either of the
    reference's two schema generations, so historical reference data and new
    TPU data land in one warehouse and plot on the same axes (SURVEY §7.3)."""
    with open(path, newline="") as f:
        rows = [_normalize_row(r) for r in csv.DictReader(f)]
    conn.execute("DELETE FROM summary_runs WHERE src_csv=?", (str(path),))
    session_default = _session_platform(path)
    n = 0
    for r in rows:
        conn.execute(
            "INSERT INTO summary_runs VALUES (?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?,?)",
            (
                r.get("SessionID"),
                r.get("MachineID"),
                r.get("GitCommit"),
                r.get("Timestamp"),
                canonical_variant(r.get("Variant", "")),
                r.get("ConfigKey"),
                int(r["NP"]) if r.get("NP") else None,
                int(r["Batch"]) if r.get("Batch") else None,
                r.get("BuildStatus"),
                r.get("RunStatus"),
                r.get("ParseStatus"),
                r.get("Status"),
                float(r["ExecutionTime_ms"]) if r.get("ExecutionTime_ms") else None,
                float(r["Compile_ms"]) if r.get("Compile_ms") else None,
                r.get("OutputShape"),
                r.get("First5Values"),
                r.get("LogFile"),
                str(path),
                r.get("_corpus", "local"),
                _row_platform(r, path, session_default),
            ),
        )
        n += 1
    return n


def ingest_run_log(conn: sqlite3.Connection, path: Path) -> int:
    """Regex-scrape one run log (log_analysis.py run-log scrape analogue)."""
    from .harness import _RE_SHAPE, _RE_TIME

    text = path.read_text(errors="replace")
    t = _RE_TIME.search(text)
    s = _RE_SHAPE.search(text)
    conn.execute("DELETE FROM run_logs WHERE path=?", (str(path),))
    conn.execute(
        "INSERT INTO run_logs VALUES (?,?,?,?)",
        (
            str(path),
            path.parent.name,
            float(t.group(1)) if t else None,
            s.group(1) if s else None,
        ),
    )
    return 1


_LANG = {".py": "python", ".sh": "bash", ".cpp": "c++", ".cc": "c++", ".h": "c++", ".hpp": "c++"}
_SKIP_DIRS = {"node_modules", "__pycache__", "venv", "build", "dist"}


def ingest_source_stats(conn: sqlite3.Connection, repo_root: Path) -> int:
    n = 0
    for p in sorted(repo_root.rglob("*")):
        if p.suffix not in _LANG or not p.is_file():
            continue
        rel_parts = p.relative_to(repo_root).parts
        if any(part.startswith(".") or part in _SKIP_DIRS for part in rel_parts):
            continue
        loc = sum(1 for _ in open(p, errors="replace"))
        conn.execute(
            "INSERT OR REPLACE INTO source_stats VALUES (?,?,?)",
            (str(p.relative_to(repo_root)), loc, _LANG[p.suffix]),
        )
        n += 1
    return n


def _csv_kind(path: Path) -> Optional[str]:
    """Schema-sniff a CSV header: ours/gen-2 session schema or the gen-1
    export schema (e.g. the reference's root-level ``all_runs.csv``, whose
    name a bare "summary" filter would miss)."""
    try:
        with open(path, newline="", errors="replace") as f:
            header = f.readline()
    except OSError:
        return None
    if "ProjectVariant" in header or ("Variant" in header and "Status" in header):
        return "summary_csv"
    if "version" in header and "total_time_s" in header:
        return "summary_csv"
    return None


def cmd_ingest(conn: sqlite3.Connection, logs_root: Path, repo_root: Optional[Path]) -> None:
    n_csv = n_log = skipped = 0
    for path in sorted(logs_root.rglob("*")):
        if not path.is_file():
            continue
        if path.suffix == ".csv":
            kind = _csv_kind(path)
            if kind is None:
                continue
        elif path.suffix == ".log":
            kind = "run_log"
        else:
            continue
        sha1 = _sha1(path)
        if _already_ingested(conn, path, sha1):  # incremental re-ingest
            skipped += 1
            continue
        if kind == "summary_csv":
            n_csv += ingest_summary_csv(conn, path)
        else:
            n_log += ingest_run_log(conn, path)
        _mark(conn, path, sha1, kind)
    n_src = ingest_source_stats(conn, repo_root) if repo_root else 0
    conn.commit()
    print(f"ingested: {n_csv} csv rows, {n_log} run logs, {n_src} source files, {skipped} unchanged")


SPEEDUP_SQL = """
WITH base AS (
    SELECT corpus, COALESCE(platform, '') AS platform,
           COALESCE(batch, 1) AS batch, MIN(best_ms) AS t1_ms
    FROM best_runs
    WHERE variant = ? AND np = 1
    GROUP BY corpus, COALESCE(platform, ''), COALESCE(batch, 1)
)
SELECT b.variant, b.np, b.batch, b.best_ms,
       base.t1_ms / b.best_ms AS speedup,
       base.t1_ms / b.best_ms / b.np AS efficiency,
       b.corpus, b.platform
FROM best_runs b
JOIN base ON base.corpus = b.corpus
         AND base.platform = COALESCE(b.platform, '')
         AND base.batch = COALESCE(b.batch, 1)
ORDER BY b.corpus, b.platform, b.variant, b.batch, b.np
"""
# batch NULL (the reference corpus has no batch column; it is batch-1 by
# construction) is COALESCEd to 1 so historical reference rows and new
# batch-1 TPU rows share one per-image baseline. Rows at other batch sizes
# still require a same-batch np=1 baseline — no silent cross-batch ratios.
# The baseline T1 is additionally grouped PER CORPUS (reference-ingested
# CSVs vs this repo's own sessions, derived from src_csv origin) AND PER
# PLATFORM (one machine's local sessions span the CPU fallback and the
# tunneled TPU — a 0.3 ms TPU run must not be "sped up" against an 11 ms
# CPU baseline): each (corpus, platform) group is judged against its own
# serial baseline — mirroring log_analysis.py:213-222, which only ever
# saw one corpus on one backend. Cross-corpus/platform comparison stays
# available via the raw best_runs view (all share the variant axis).


def cmd_speedup(conn: sqlite3.Connection, baseline: str) -> List[tuple]:
    rows = conn.execute(SPEEDUP_SQL, (baseline,)).fetchall()
    if not rows:
        print(f"no data (is there a '{baseline}' np=1 run ingested?)", file=sys.stderr)
        return []
    print(
        f"{'variant':22s} {'np':>3s} {'batch':>5s} {'best_ms':>10s} {'S(N)':>7s} "
        f"{'E(N)':>6s} {'corpus':>9s} {'platform':>8s}"
    )
    for v, np_, b, ms, s, e, corpus, platform in rows:
        # batch is NULL for reference-corpus rows (the reference is batch-1
        # with no batch column).
        print(
            f"{v:22s} {np_:3d} {str(b) if b is not None else '-':>5s} "
            f"{ms:10.3f} {s:7.2f} {e:6.2f} {corpus:>9s} {platform or '-':>8s}"
        )
    return rows


def cmd_stats(conn: sqlite3.Connection) -> None:
    rows = conn.execute(
        "SELECT variant, np, batch, n, mean_ms, stdev_ms, ci95_ms, corpus, platform "
        "FROM run_stats ORDER BY corpus, platform, variant, batch, np"
    ).fetchall()
    print(
        f"{'variant':22s} {'np':>3s} {'batch':>5s} {'n':>4s} {'mean_ms':>10s} "
        f"{'stdev':>8s} {'ci95':>8s} {'corpus':>9s} {'platform':>8s}"
    )
    for v, np_, b, n, mean, sd, ci, corpus, platform in rows:
        # batch NULL = the (batch-1) reference corpus; '-' like the other
        # commands, never a fabricated 0.
        print(
            f"{v:22s} {np_:3d} {str(b) if b is not None else '-':>5s} {n:4d} "
            f"{mean:10.3f} {sd or 0:8.3f} {ci or 0:8.3f} {corpus:>9s} "
            f"{platform or '-':>8s}"
        )


def cmd_plot(conn: sqlite3.Connection, out_dir: Path, baseline: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    rows = conn.execute(SPEEDUP_SQL, (baseline,)).fetchall()
    if not rows:
        print("no data to plot", file=sys.stderr)
        return
    out_dir.mkdir(parents=True, exist_ok=True)
    groups = {(r[6], r[7]) for r in rows}
    by_variant: dict = {}
    for v, np_, b, ms, s, e, corpus, platform in rows:
        # batch NULL = the (batch-1) reference corpus; normalize so mixed
        # corpora sort and label consistently. The corpus/platform tag only
        # appears when the warehouse actually holds more than one group.
        label = f"{v} (b={b if b is not None else 1})"
        if len(groups) > 1:
            label += f" [{corpus}{'/' + platform if platform else ''}]"
        by_variant.setdefault(label, []).append((np_, s, e))
    for idx, (title, ylab, fname) in enumerate(
        [("Speedup vs serial baseline", "S(N) = T1/TN", "speedup.png"),
         ("Parallel efficiency", "E(N) = S(N)/N", "efficiency.png")]
    ):
        fig, ax = plt.subplots(figsize=(7, 4.5))
        for label, pts in sorted(by_variant.items()):
            pts.sort()
            xs = [p[0] for p in pts]
            ys = [p[1 + idx] for p in pts]
            ax.plot(xs, ys, marker="o", label=label)
        if idx == 0:
            lim = max(p[0] for pts in by_variant.values() for p in pts)
            ax.plot([1, lim], [1, lim], "k--", alpha=0.4, label="ideal")
        else:
            ax.axhline(1.0, color="k", ls="--", alpha=0.4)
        ax.set_xlabel("shard count (np)")
        ax.set_ylabel(ylab)
        ax.set_title(title)
        ax.legend(fontsize=7)
        fig.tight_layout()
        fig.savefig(out_dir / fname, dpi=120)
        plt.close(fig)
        print(f"wrote {out_dir / fname}")


def cmd_report(conn: sqlite3.Connection, out: Path, baseline: str) -> None:
    """Markdown analysis report — the reference's ``best_runs.md`` /
    ``analysis_exports/*_report.md`` analogue, generated from the warehouse.
    """
    import datetime

    lines: List[str] = []
    lines.append("# Performance analysis report")
    lines.append("")
    n_runs = conn.execute("SELECT COUNT(*) FROM summary_runs").fetchone()[0]
    n_perf = conn.execute("SELECT COUNT(*) FROM perf_runs").fetchone()[0]
    sessions = conn.execute(
        "SELECT COUNT(DISTINCT session_id) FROM summary_runs"
    ).fetchone()[0]
    machines = [
        r[0]
        for r in conn.execute(
            "SELECT DISTINCT machine_id FROM summary_runs WHERE machine_id IS NOT NULL"
        )
    ]
    lines.append(
        f"Generated {datetime.datetime.now(datetime.timezone.utc).strftime('%Y-%m-%d %H:%M UTC')} "
        f"from {n_runs} ingested rows ({n_perf} OK perf runs) across "
        f"{sessions} sessions; machines: {', '.join(machines) or 'n/a'}."
    )

    lines.append("")
    lines.append("## Best runs (min time per variant / np / batch)")
    lines.append("")
    lines.append("| variant | np | batch | best_ms | img/s | n | corpus | platform |")
    lines.append("|---|---:|---:|---:|---:|---:|---|---|")
    for v, np_, b, ms, n, corpus, platform in conn.execute(
        "SELECT variant, np, batch, best_ms, n, corpus, platform FROM best_runs "
        "ORDER BY corpus, platform, variant, batch, np"
    ):
        imgs = (b or 1) / (ms / 1e3) if ms else 0.0
        lines.append(
            f"| {v} | {np_} | {b if b is not None else '-'} | {ms:.3f} | {imgs:.1f} "
            f"| {n} | {corpus} | {platform or '-'} |"
        )

    lines.append("")
    lines.append(
        f"## Speedup & efficiency vs `{baseline}` (np=1, same batch, same corpus+platform)"
    )
    lines.append("")
    lines.append("| variant | np | batch | best_ms | S(N) | E(N) | corpus | platform |")
    lines.append("|---|---:|---:|---:|---:|---:|---|---|")
    for v, np_, b, ms, s, e, corpus, platform in conn.execute(SPEEDUP_SQL, (baseline,)):
        lines.append(
            f"| {v} | {np_} | {b if b is not None else '-'} | {ms:.3f} | {s:.2f} "
            f"| {e:.2f} | {corpus} | {platform or '-'} |"
        )

    lines.append("")
    lines.append("## Run statistics (mean / stddev / 95% CI)")
    lines.append("")
    lines.append(
        "| variant | np | batch | n | mean_ms | stdev_ms | ci95_ms | corpus | platform |"
    )
    lines.append("|---|---:|---:|---:|---:|---:|---:|---|---|")
    for v, np_, b, n, mean, sd, ci, corpus, platform in conn.execute(
        "SELECT variant, np, batch, n, mean_ms, stdev_ms, ci95_ms, corpus, platform "
        "FROM run_stats ORDER BY corpus, platform, variant, batch, np"
    ):
        lines.append(
            f"| {v} | {np_} | {b if b is not None else '-'} | {n} | {mean:.3f} "
            f"| {f'{sd:.3f}' if sd is not None else '-'} "
            f"| {f'{ci:.3f}' if ci is not None else '-'} | {corpus} | {platform or '-'} |"
        )

    lines.append("")
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text("\n".join(lines))
    print(f"wrote {out} ({n_perf} perf runs, {sessions} sessions)")


# Stage map for the narrative: canonical variant name (shared with the
# reference's corpus), this framework's config key, and what the stage IS.
# The names are the join key between the two corpora, so the narrative can
# put the reference's GPU/MPI measurements and the TPU re-design's
# measurements in one story (reference analysis.md's canonical-name
# discipline, canonical_version_name).
_STAGES = (
    ("V1 Serial", "v1_jit", "single-device XLA baseline (reference: serial C++)"),
    ("V2.1 BroadcastAll", "v2.1_replicated", "replicate-compute-everywhere (the negative-scaling pedagogy stage)"),
    ("V2.2 ScatterHalo", "v2.2_sharded", "row-sharded + multi-hop ppermute halos (reference: MPI scatter+halo)"),
    ("V3 CUDA", "v3_pallas", "hand-written kernels (Pallas MXU vs reference CUDA)"),
    ("V4 MPI+CUDA", "v4_hybrid", "sharded + all_gather-staged halos (reference: host-staged MPI+CUDA)"),
    ("V5 MPI+CUDA-Aware", "v5_collective", "device-device halos over ICI (reference: planned, never built)"),
    ("V6 AlexNet Full", "v6_full_jit", "full 8-layer AlexNet + FC head (beyond the reference's blocks 1-2)"),
    ("V7 TensorParallel", "v7_tp", "conv-K tensor parallelism (beyond the reference)"),
)


def cmd_narrative(conn: sqlite3.Connection, out: Path, baseline: str) -> None:
    """The H7 narrative artifact: a regenerable reference-vs-TPU story woven
    from the warehouse — per-stage comparison, scaling pedagogy, MFU, and
    the static comm plan — not a table dump (that's ``report``). The
    reference's equivalent is its ``analysis.md``/notebook walk-through."""
    import datetime
    import json as _json

    L: List[str] = []
    say = L.append
    say("# Analysis narrative: the staged study, reference GPU/MPI vs TPU re-design")
    say("")
    say(
        f"Generated {datetime.datetime.now(datetime.timezone.utc).strftime('%Y-%m-%d %H:%M UTC')} "
        "by `python -m cuda_mpi_gpu_cluster_programming_tpu.analysis narrative` "
        "from the measurement warehouse (re-run after any capture to refresh)."
    )
    say("")

    # --- 1. The study -----------------------------------------------------
    say("## 1. What is being compared")
    say("")
    say(
        "The reference project tells a staged story — serial C++, naive "
        "replication, scatter+halo MPI, CUDA kernels, hybrid MPI+CUDA — each "
        "stage measured on the same AlexNet blocks-1-2 workload. This "
        "framework re-designs every stage TPU-first (XLA/Pallas/shard_map "
        "over a device mesh) and ingests the reference's own measurement "
        "corpus next to its own, so both sit in one warehouse under "
        "canonical stage names:"
    )
    say("")
    say("| stage | TPU config | what it is |")
    say("|---|---|---|")
    for name, key, desc in _STAGES:
        say(f"| {name} | `{key}` | {desc} |")
    say("")
    # COALESCE like the views: legacy NULL-corpus rows count as local
    # (SQL NULL != 'reference' is NULL, which would drop them from BOTH).
    n_ref = conn.execute(
        "SELECT COUNT(*) FROM summary_runs WHERE COALESCE(corpus,'')='reference'"
    ).fetchone()[0]
    n_loc = conn.execute(
        "SELECT COUNT(*) FROM summary_runs WHERE COALESCE(corpus,'')!='reference'"
    ).fetchone()[0]
    say(
        f"Warehouse contents: {n_ref} reference-corpus rows (the reference's "
        f"committed CSVs/logs) and {n_loc} rows from this framework's own "
        "sessions, keyed by (corpus, platform) so nothing is ever judged "
        "against another machine's baseline."
    )
    say("")

    # --- 2. Headline ------------------------------------------------------
    say("## 2. Headline")
    say("")
    bench_path = Path("perf/bench_latest.json")
    if bench_path.exists():
        try:
            bl = _json.loads(bench_path.read_text())
        except ValueError:
            bl = {}
        if bl.get("value"):
            say(
                f"The committed headline (`perf/bench_latest.json`): "
                f"**{bl['value']:,.0f} img/s** {bl.get('compute', 'fp32')} at "
                f"batch {bl.get('batch', '?')} on the {bl.get('device_kind', 'TPU')} "
                f"— {bl.get('vs_baseline', 0):,.0f}x the reference's best GPU "
                "stage (V4 MPI+CUDA, RTX-3090-class, 0.183 s/image — "
                "reference best_runs.md)."
            )
            if bl.get("mfu") is not None:
                say("")
                say(
                    f"MFU {bl['mfu']:.3f} against the chip's bf16 MXU peak"
                    + (
                        f"; fp32 runs synthesize true-fp32 from ~6 bf16 MXU "
                        f"passes, so the same measurement is "
                        f"**{bl['fp32_ceiling_fraction']:.0%} of the "
                        f"achievable fp32 ceiling**."
                        if bl.get("fp32_ceiling_fraction")
                        else "."
                    )
                )
            if isinstance(bl.get("bf16"), dict) and bl["bf16"].get("value"):
                b16 = bl["bf16"]
                say("")
                say(
                    f"bf16 headline alongside: **{b16['value']:,.0f} img/s** "
                    f"(MFU {b16.get('mfu', 0):.3f}, n={b16.get('timing_n', '?')}, "
                    f"ci95 {b16.get('timing_ci95_ms', 0):.3f} ms)."
                )
    else:
        say("No committed headline yet (perf/bench_latest.json absent).")
    say("")

    # --- 3. Stage by stage ------------------------------------------------
    say("## 3. The staged comparison, on chip")
    say("")
    say(
        "Per-image best times (min over ingested runs; ours are ms/batch at "
        "the best batch, the reference's corpus is batch-1 by construction):"
    )
    say("")
    say("| stage | reference best (ms/img, np) | TPU best (ms/img, batch) | TPU vs ref |")
    say("|---|---|---|---|")
    pending = []
    for name, key, _ in _STAGES:
        ref = conn.execute(
            "SELECT MIN(best_ms), np FROM best_runs "
            "WHERE corpus='reference' AND variant=?",
            (name,),
        ).fetchone()
        # best_ms > 0.001 excludes rows at the timing clamp floor (1e-3 ms
        # = the documented RTT-shadow fabrication from pre-work-floor
        # sessions, utils/timing.py) — a 0.001 ms "measurement" is a bound
        # that was explicitly not trusted, not a best run.
        tpu = conn.execute(
            "SELECT MIN(best_ms / COALESCE(batch, 1)) FROM best_runs "
            "WHERE corpus!='reference' AND platform='tpu' AND variant=? "
            "AND best_ms > 0.001",
            (name,),
        ).fetchone()
        ref_s = f"{ref[0]:.1f} (np={ref[1]})" if ref and ref[0] else "—"
        if tpu and tpu[0]:
            tpu_s = f"{tpu[0]:.3f}"
            ratio = f"**{ref[0] / tpu[0]:,.0f}x**" if ref and ref[0] else "—"
        else:
            tpu_s, ratio = "*pending capture*", "—"
            pending.append(name)
        say(f"| {name} | {ref_s} | {tpu_s} | {ratio} |")
    say("")
    if pending:
        say(
            f"Stages still without an on-chip row: {', '.join(pending)} — "
            "queued in `scripts/on_heal.sh` (the tunneled chip wedges for "
            "hours at a time; `logs/probe_attempts_r*.log` is the timeline). "
            "Regenerate this narrative after the capture lands."
        )
    else:
        say("Every stage the reference measured has an on-chip row.")
    say("")

    # --- 4. Scaling pedagogy ----------------------------------------------
    say("## 4. The scaling pedagogy (reference corpus)")
    say("")
    rows = [
        r
        for r in conn.execute(SPEEDUP_SQL, (baseline,))
        if r[6] == "reference"
    ]
    v21 = sorted((r for r in rows if r[0] == "V2.1 BroadcastAll"), key=lambda r: r[1])
    v22 = sorted((r for r in rows if r[0] == "V2.2 ScatterHalo"), key=lambda r: r[1])
    if v21:
        curve = ", ".join(f"S({r[1]})={r[4]:.2f}" for r in v21)
        say(
            f"V2.1 BroadcastAll is the study's negative result and its best "
            f"lesson: every rank recomputes everything, so adding ranks only "
            f"adds broadcast cost — the reference's own corpus shows "
            f"{curve}. The TPU analogue (`v2.1_replicated`) keeps the stage "
            "as a measured config precisely to reproduce this curve."
        )
        say("")
    if v22:
        curve = ", ".join(f"S({r[1]})={r[4]:.2f}" for r in v22)
        say(
            f"V2.2 ScatterHalo actually divides work ({curve}); its TPU "
            "analogue moves the same halos device-to-device over ICI via "
            "multi-hop `ppermute` instead of MPI_Irecv/Isend, and the exact "
            "row-ownership planner (parallel/plan.py) fixes the trim bug "
            "that corrupted the reference's np=4 gathers."
        )
        say("")
    if not (v21 or v22):
        say("(reference corpus not ingested — run the capture/ingest first)")
        say("")

    # --- 4b. This framework's own measured scaling curves -----------------
    say("## 4b. This framework's own S(N)/E(N) study (virtual CPU mesh)")
    say("")
    cpu_rows = [
        r
        for r in conn.execute(SPEEDUP_SQL, (baseline,))
        if r[6] != "reference" and (r[7] or "") == "cpu"
    ]
    if cpu_rows:
        say(
            "The same shards sweep the reference ran with `mpirun -np N "
            "--oversubscribe`, measured with THIS framework's own configs on "
            "the 8-virtual-device CPU mesh — the first self-measured scaling "
            "rows for the sharded/distributed family. **Honest caveat, same "
            "as the reference's oversubscribe runs** (its "
            "common_test_utils.sh warned the ranks share cores): this host "
            "has ONE physical core, so the mesh time-slices and wall time "
            "tracks *total work plus partition/collective overhead*, not "
            "parallel speedup. Read the curves as a work-conservation and "
            "overhead study: a work-conserving sharded config should hold "
            "S(N) ≈ 1 (flat time as shards grow), replicate-everything "
            "should fall as S(N) ≈ 1/N (N× work), and any extra droop is "
            "the cost of halos/gathers/regrouping. ICI-speedup claims stay "
            "with the on-chip rows."
        )
        say("")
        say("| variant | np | best ms | S(N) vs V1 | E(N) |")
        say("|---|---:|---:|---:|---:|")
        for v, np_, b, ms, s, e, _corpus, _plat in sorted(
            cpu_rows, key=lambda r: (r[0], r[1])
        ):
            say(f"| {v} (b={b}) | {np_} | {ms:.1f} | {s:.2f} | {e:.2f} |")
        say("")
        # Per-(variant, batch) np->ms cells — same-batch rows only (a
        # variant measured at several batches but one np would otherwise
        # fake a huge "scaling" ratio out of the batch difference), and
        # only where the np axis actually spans a range.
        by_cell: dict = {}
        for v, np_, b, ms, _s, _e, _c, _p in cpu_rows:
            by_cell.setdefault((v, b), {})[np_] = ms
        v21_cells = sorted(
            (pts for (name, _b), pts in by_cell.items()
             if name == "V2.1 BroadcastAll" and len(pts) >= 2),
            key=len, reverse=True,
        )
        flat = {
            (f"{name} (b={b})", min(pts), max(pts)): pts[max(pts)] / pts[min(pts)]
            for (name, b), pts in by_cell.items()
            if len(pts) >= 2 and name != "V2.1 BroadcastAll"
        }
        if v21_cells:
            pts = v21_cells[0]
            lo, hi = min(pts), max(pts)
            say(
                f"Measured: V2.1 BroadcastAll grows "
                f"{pts[lo]:.0f} → {pts[hi]:.0f} ms from np={lo} "
                f"to np={hi} (every shard recomputes everything — the "
                "reference's negative-scaling lesson, reproduced with this "
                "framework's own data)."
            )
        if flat:
            (bname, blo, bhi), bratio = min(flat.items(), key=lambda kv: kv[1])
            (wname, wlo, whi), wratio = max(flat.items(), key=lambda kv: kv[1])
            say(
                f"The work-dividing configs hold time ~flat on the shared "
                f"core: {bratio:.2f}× T(np={bhi})/T(np={blo}) ({bname}) to "
                f"{wratio:.2f}× T(np={whi})/T(np={wlo}) ({wname}) — the "
                "spread IS the measured partition/collective overhead."
            )
        say("")
    else:
        say(
            "*(no CPU-mesh scaling rows ingested yet — run the shards sweep "
            "via the harness with --fake-devices and re-ingest)*"
        )
        say("")

    # --- 5. Where the bytes go --------------------------------------------
    say("## 5. Where the bytes go (static comm/compute plan, 4 shards)")
    say("")
    try:
        from .models.alexnet import BLOCKS12
        from .parallel.breakdown import comm_compute_breakdown

        halo = comm_compute_breakdown(BLOCKS12, 4)
        staged = comm_compute_breakdown(BLOCKS12, 4, staged=True)
        say("| layer | halo rows (t/b) | collectives | KiB/pass | MFLOP | flop/byte |")
        say("|---|---|---:|---:|---:|---:|")
        for r in halo:
            inten = f"{r.intensity:.1f}" if r.halo_bytes else "∞"
            say(
                f"| {r.name} | {r.h_top}/{r.h_bot} | {r.collectives} "
                f"| {r.halo_bytes / 1024:.1f} | {r.flops / 1e6:.1f} | {inten} |"
            )
        hb = sum(r.halo_bytes for r in halo)
        sb = sum(r.halo_bytes for r in staged)
        say("")
        say(
            f"The staged (V4-style all_gather) transport would move "
            f"{sb / 1024:.0f} KiB/pass against the halo-only ppermute "
            f"transport's {hb / 1024:.0f} KiB — **{sb / hb:.1f}x more bytes "
            "for identical math**, which is the V4-vs-V5 story stated "
            "statically; tests assert the compiled jaxpr contains exactly "
            "these collective counts (tests/test_breakdown.py)."
        )
    except Exception as e:  # narrative must never fail the pipeline
        say(f"(static plan unavailable: {e})")
    say("")

    # --- 6. Measurement discipline ----------------------------------------
    say("## 6. Measurement discipline")
    say("")
    cells = conn.execute(
        "SELECT COUNT(*), SUM(CASE WHEN n >= 3 THEN 1 ELSE 0 END), "
        "MAX(CASE WHEN n >= 2 THEN ci95_ms END) FROM run_stats "
        "WHERE corpus!='reference' AND platform='tpu'"
    ).fetchone()
    if cells and cells[0]:
        say(
            f"{cells[0]} on-chip (variant, np, batch) cells; "
            f"{cells[1] or 0} with n>=3 samples; worst 95% CI "
            f"{cells[2]:.3f} ms." if cells[2] is not None else
            f"{cells[0]} on-chip cells (single samples so far)."
        )
    else:
        say("No on-chip cells yet.")
    say("")
    say(
        "Timing protocol: the tunneled chip's `block_until_ready` is "
        "optimistic, so every number uses the amortized two-queue-length "
        "fence with a 100 ms work floor and a MAD-based CI on the median "
        "(utils/timing.py) — sub-3 ms rows previously carried ~40% "
        "session-to-session spread; the work floor is the fix. Device "
        "wedges are first-class: probes, triage, and the stale-labeled "
        "bench fallback are all tested code paths, and every probe attempt "
        "is logged."
    )
    say("")
    spread_path = Path("perf/session_spread_latest.json")
    if spread_path.exists():
        # Quote the ACHIEVED two-session spread (scripts/session_spread.py
        # persists the newest comparison) — measured, pass or fail, never
        # just the protocol's claim.
        try:
            sp = json.loads(spread_path.read_text())
            bar = sp.get("bar", 0.10)
            fast = [c for c in sp.get("cells", []) if c.get("sub3ms")]
            b1 = [c for c in fast if c.get("batch") == 1]
            rest = [c for c in fast if c.get("batch") != 1]
            parts = [
                "Achieved two-session spread "
                f"({' vs '.join(sp.get('sessions', []))}):"
            ]
            if rest:
                worst = max(c["spread"] for c in rest)
                batches = sorted({c["batch"] for c in rest})
                verdict = "met" if worst <= bar else "MISSED"
                parts.append(
                    f"sub-3 ms cells at batch in {batches} within "
                    f"{worst:.1%} (bar {bar:.0%} {verdict});"
                )
            if b1:
                lo_ms = min(min(c["t_a_ms"], c["t_b_ms"]) for c in b1)
                hi_ms = max(max(c["t_a_ms"], c["t_b_ms"]) for c in b1)
                lo = min(c["spread"] for c in b1)
                hi = max(c["spread"] for c in b1)
                parts.append(
                    f"batch=1 cells ({lo_ms:.1f}-{hi_ms:.1f} ms/pass) spread "
                    f"{lo:.0%}-{hi:.0%}"
                    + (
                        " — a shift the timing chain cannot average out, so "
                        "b=1 latency is reported as a bound, not a claim."
                        if hi > bar
                        else f" (bar {bar:.0%} met)."
                    )
                )
            # The fresh-process diagnostic (on_heal.sh, three back-to-back
            # runs of the worst cell in ONE session) attributes the b=1
            # shift: spread within minutes ~ spread across sessions =>
            # per-process dispatch/lowering variance, not device drift.
            diag_path = Path("perf/b1_diag_latest.json")
            if b1 and diag_path.exists():
                try:
                    dg = json.loads(diag_path.read_text())
                    runs = dg.get("runs_ms", [])
                    if runs:
                        dspread = dg.get("spread", 0)
                        # Decision rule (scripts/on_heal.sh): the
                        # back-to-back spread must be compared against
                        # the OBSERVED cross-session b=1 spread (hi), not
                        # a fixed bar — comparable = per-process
                        # variance explains the shift; much tighter =
                        # the shift happens BETWEEN sessions (device/
                        # relay state drift).
                        verdict = (
                            f"comparable to the {hi:.0%} cross-session "
                            "shift, so the b=1 instability is per-process "
                            "dispatch/lowering variance, not device or "
                            "relay drift; the bound stands."
                            if dspread >= hi / 2
                            else f"far tighter than the {hi:.0%} "
                            "cross-session shift, which therefore points "
                            "at device/relay state drift between "
                            "sessions; the bound stands."
                        )
                        parts.append(
                            f"Fresh-process diagnostic ({len(runs)} "
                            f"back-to-back runs, {dg.get('source', '?')}): "
                            f"{min(runs):.2f}-{max(runs):.2f} ms, "
                            f"{dspread:.0%} spread — {verdict}"
                        )
                except (OSError, ValueError):
                    pass
            say(" ".join(parts))
            say("")
        except (OSError, ValueError):
            pass
    say("---")
    say(
        "Regenerate: `python -m cuda_mpi_gpu_cluster_programming_tpu.analysis "
        "narrative --out docs/ANALYSIS.md` (after `... analysis ingest`)."
    )
    say("")
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text("\n".join(L))
    print(f"wrote {out}")


VIEWS = ("perf_runs", "best_runs", "run_stats", "summary_runs", "run_logs", "source_stats")


def cmd_export(conn: sqlite3.Connection, view: str, out: Path, fmt: str) -> None:
    if view not in VIEWS:
        raise SystemExit(f"unknown view {view!r}; choose from {VIEWS}")
    cur = conn.execute(f"SELECT * FROM {view}")  # noqa: S608 — view name validated above
    cols = [d[0] for d in cur.description]
    rows = cur.fetchall()
    out.parent.mkdir(parents=True, exist_ok=True)
    if fmt == "csv":
        with open(out, "w", newline="") as f:
            wtr = csv.writer(f)
            wtr.writerow(cols)
            wtr.writerows(rows)
    elif fmt == "parquet":
        import pandas as pd

        pd.DataFrame(rows, columns=cols).to_parquet(out)
    else:
        raise SystemExit(f"unknown format {fmt!r}")
    print(f"exported {len(rows)} rows from {view} to {out}")


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.analysis")
    p.add_argument("--db", default=DEFAULT_DB)
    sub = p.add_subparsers(dest="cmd", required=True)
    pi = sub.add_parser("ingest", help="walk logs root, dedup, load warehouse")
    pi.add_argument("--logs", default="logs")
    pi.add_argument("--repo-root", default=".", help="root for source stats ('' to skip)")
    sub.add_parser("stats", help="run_stats view (n/mean/stddev/95%% CI)")
    ps = sub.add_parser("speedup", help="S(N)=T1/TN and E=S/N vs baseline")
    ps.add_argument("--baseline", default="V1 Serial")
    pp = sub.add_parser("plot", help="speedup/efficiency PNGs")
    pp.add_argument("--out", default="plots")
    pp.add_argument("--baseline", default="V1 Serial")
    pe = sub.add_parser("export", help="dump a view to csv/parquet")
    pe.add_argument("--view", required=True)
    pe.add_argument("--out", required=True)
    pe.add_argument("--fmt", choices=["csv", "parquet"], default="csv")
    pr = sub.add_parser("report", help="markdown best-runs/stats report")
    pr.add_argument("--out", default="analysis_exports/best_runs_report.md")
    pr.add_argument("--baseline", default="V1 Serial")
    pn = sub.add_parser(
        "narrative", help="reference-vs-TPU analysis narrative (H7 artifact)"
    )
    pn.add_argument("--out", default="docs/ANALYSIS.md")
    pn.add_argument("--baseline", default="V1 Serial")
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    conn = connect(args.db)
    try:
        if args.cmd == "ingest":
            cmd_ingest(
                conn,
                Path(args.logs),
                Path(args.repo_root) if args.repo_root else None,
            )
        elif args.cmd == "stats":
            cmd_stats(conn)
        elif args.cmd == "speedup":
            cmd_speedup(conn, args.baseline)
        elif args.cmd == "plot":
            cmd_plot(conn, Path(args.out), args.baseline)
        elif args.cmd == "export":
            cmd_export(conn, args.view, Path(args.out), args.fmt)
        elif args.cmd == "report":
            cmd_report(conn, Path(args.out), args.baseline)
        elif args.cmd == "narrative":
            cmd_narrative(conn, Path(args.out), args.baseline)
    finally:
        conn.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

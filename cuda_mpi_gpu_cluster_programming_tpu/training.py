"""Minimal distributed training step over the Blocks 1-2 model.

The reference is inference-only, but the framework exposes a training
capability as the natural extension point (SURVEY §7.2 step 8 "future work"):
MSE regression loss, optax SGD, data-parallel gradient psum implied by
sharding constraints — XLA inserts the collectives (GSPMD) from the
annotations, the idiomatic TPU replacement for hand-written MPI reductions.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.alexnet import BLOCKS12, Blocks12Config, forward_blocks12


def make_train_step(
    cfg: Blocks12Config = BLOCKS12,
    mesh: Mesh | None = None,
    optimizer: optax.GradientTransformation | None = None,
    lr: float = 1e-3,
    sp_shards: int = 0,
    tp_shards: int = 0,
    remat: bool = False,
    with_grad_norm: bool = False,
) -> Tuple[Callable, Callable]:
    """Build ``(init_fn, step_fn)`` for any optax optimizer (default SGD).

    ``init_fn(params) -> opt_state``;
    ``step_fn(params, opt_state, x, y) -> (new_params, new_opt_state, loss)``.

    ``with_grad_norm=True`` appends the global gradient L2 norm to the step
    output (``(new_params, new_opt_state, loss, grad_norm)``) — computed
    inside the jitted step so the SDC sentinel screens gradients without a
    second device round-trip.

    When ``mesh`` is given, activations are constrained to shard batch over
    "dp" (if present); params stay replicated, so XLA emits the all-reduce
    for the gradient sum automatically.

    ``sp_shards >= 1`` instead routes the forward through the explicit
    shard_map + ppermute halo pipeline (parallel.sharded) over a 1-D "sp"
    mesh — spatial/context-parallel training. This path is used *instead of*
    GSPMD H-axis annotation because the latter produces wrong conv weight
    gradients in this JAX build (see x_spec note below); shard_map's
    collectives have exact transposes (ppermute^T = reverse permute,
    replicated-in^T = psum), so gradients here are correct by construction.

    ``tp_shards >= 1`` routes the forward through the K-axis filter
    decomposition (parallel.tensor_parallel): conv weights sharded over the
    mesh's last axis, gradients flow through the same explicit collectives
    (all_gather^T = dynamic-slice+psum, channel-ppermute^T = reverse shift).
    """
    if sp_shards and tp_shards:
        raise ValueError("sp_shards and tp_shards are mutually exclusive strategies")
    opt = optimizer if optimizer is not None else optax.sgd(lr)

    def _build_step(loss_fn, pre=None, post=None):
        return _jit_step(opt, loss_fn, pre, post, with_grad_norm=with_grad_norm)

    if sp_shards and sp_shards >= 1:
        from .parallel.sharded import build_sharded_forward

        if mesh is not None:
            sp_size = dict(zip(mesh.axis_names, mesh.devices.shape)).get("sp")
            if sp_size != sp_shards:
                raise ValueError(
                    f"mesh 'sp' axis has {sp_size} devices but sp_shards={sp_shards}; "
                    "the halo/ownership plan would be built for the wrong shard count"
                )
        sharded_fwd = build_sharded_forward(cfg, n_shards=sp_shards, mesh=mesh)
        if remat:
            sharded_fwd = jax.checkpoint(sharded_fwd)

        def sp_loss_fn(params, x, y):
            return jnp.mean((sharded_fwd(params, x) - y) ** 2)

        return opt.init, _build_step(sp_loss_fn)

    if tp_shards and tp_shards >= 1:
        from .parallel.tensor_parallel import build_tp_forward

        tp_fwd = build_tp_forward(cfg, n_shards=tp_shards, mesh=mesh)
        if remat:
            tp_fwd = jax.checkpoint(tp_fwd)

        def tp_loss_fn(params, x, y):
            return jnp.mean((tp_fwd(params, x) - y) ** 2)

        return opt.init, _build_step(tp_loss_fn)

    def base_fwd(params, x):
        return forward_blocks12(params, x, cfg)

    if remat:
        # Trade FLOPs for memory: recompute activations in the backward pass.
        base_fwd = jax.checkpoint(base_fwd)

    def loss_fn(params, x, y):
        return jnp.mean((base_fwd(params, x) - y) ** 2)

    pre, post = _dp_pre_post(mesh)
    return opt.init, _build_step(loss_fn, pre=pre, post=post)


def make_elastic_step_builder(
    cfg: Blocks12Config = BLOCKS12,
    optimizer: optax.GradientTransformation | None = None,
    lr: float = 1e-3,
    remat: bool = False,
    with_grad_norm: bool = False,
) -> Callable:
    """``(entry, mesh) -> step_fn`` for the supervisor's step-replay path
    (``resilience.supervisor.Supervisor(step_builder=...)``).

    Maps a ladder rung onto :func:`make_train_step`'s strategies, building
    against the SURVIVING-device mesh the supervisor passes after a shrink
    — never a mesh of its own (the stale-device-set discipline). ONE
    optimizer instance is shared across every rung, so the opt-state tree
    stays structurally identical through a degrade and the live reshard is
    a pure ``jax.device_put`` — no state translation, no checkpoint
    round-trip.
    """
    opt = optimizer if optimizer is not None else optax.sgd(lr)

    def build(entry, mesh) -> Callable:
        if entry.strategy in ("halo", "staged_halo") and entry.n_shards >= 2:
            return make_train_step(
                cfg, mesh=mesh, optimizer=opt, sp_shards=entry.n_shards,
                remat=remat, with_grad_norm=with_grad_norm,
            )[1]
        if entry.strategy == "tp" and entry.n_shards >= 2:
            return make_train_step(
                cfg, mesh=mesh, optimizer=opt, tp_shards=entry.n_shards,
                remat=remat, with_grad_norm=with_grad_norm,
            )[1]
        if entry.strategy in ("single", "replicated") or entry.n_shards == 1:
            return make_train_step(
                cfg, optimizer=opt, remat=remat, with_grad_norm=with_grad_norm
            )[1]
        raise ValueError(f"no elastic training step for ladder entry {entry.key}")

    return build


def _jit_step(opt, loss_fn, pre=None, post=None, with_grad_norm=False) -> Callable:
    """The shared update scaffold: (optional pre-constraints) ->
    value_and_grad -> opt.update -> apply_updates -> (optional post) —
    ONE home for the step discipline every trainable uses."""

    @jax.jit
    def step(params, opt_state, x, y):
        if pre is not None:
            params, x = pre(params, x)
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if post is not None:
            new_params = post(new_params)
        if with_grad_norm:
            return new_params, new_opt_state, loss, optax.global_norm(grads)
        return new_params, new_opt_state, loss

    return step


def _dp_pre_post(mesh: Mesh | None):
    """(pre, post) sharding-constraint pair for the replicated-params /
    dp-sharded-batch discipline; (None, None) without a mesh.

    Batch (dp) sharding only. Spatial-parallel training goes through the
    explicitly-differentiable shard_map + ppermute halo path in
    parallel.sharded (the framework's explicit-collectives design, the
    reference's MPI-halo analogue) rather than a GSPMD "sp" annotation on
    the H axis. Round 1 additionally observed wrong conv *weight*
    gradients from the GSPMD partitioner with an H-axis annotation;
    round 2 could NOT reproduce that on cpu/jax==0.9.0 (minimal conv,
    full model, remat, dp x sp all give correct grads — see
    scripts/gspmd_conv_grad_repro.py and tests/test_gspmd_repro.py, which
    will fail loudly if the bug (re)appears). Behavior on the axon TPU
    backend is still unverified.
    """
    if mesh is None:
        return None, None
    spec = P("dp" if "dp" in mesh.axis_names else None)

    def pre(params, x):
        return (
            jax.lax.with_sharding_constraint(params, NamedSharding(mesh, P())),
            jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec)),
        )

    def post(new_params):
        return jax.lax.with_sharding_constraint(new_params, NamedSharding(mesh, P()))

    return pre, post


def make_classifier_train_step(
    cfg,
    mesh: Mesh | None = None,
    optimizer: optax.GradientTransformation | None = None,
    lr: float = 1e-3,
    remat: bool = False,
) -> Tuple[Callable, Callable]:
    """(init_fn, step_fn) for FULL-AlexNet classification training.

    The reference's extension task (conv3-5 + FC6-8, summary.md:29-45) made
    trainable: cross-entropy over the FC8 logits,
    ``step_fn(params, opt_state, x, labels)``. With a mesh containing "dp",
    the batch is sharded over it and params stay replicated (GSPMD emits
    the gradient all-reduce), same discipline as make_train_step.
    """
    from .models.alexnet_full import forward_alexnet

    opt = optimizer if optimizer is not None else optax.adam(lr)

    fwd = forward_alexnet
    if remat:
        fwd = jax.checkpoint(fwd, static_argnums=(2,))

    def loss_fn(params, x, labels):
        logits = fwd(params, x, cfg).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))

    pre, post = _dp_pre_post(mesh)
    return opt.init, _jit_step(opt, loss_fn, pre, post)

"""Minimal distributed training step over the Blocks 1-2 model.

The reference is inference-only, but the framework exposes a training
capability as the natural extension point (SURVEY §7.2 step 8 "future work"):
MSE regression loss, optax SGD, data-parallel gradient psum implied by
sharding constraints — XLA inserts the collectives (GSPMD) from the
annotations, the idiomatic TPU replacement for hand-written MPI reductions.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .models.alexnet import BLOCKS12, Blocks12Config, forward_blocks12


def make_train_step(
    cfg: Blocks12Config = BLOCKS12,
    mesh: Mesh | None = None,
    optimizer: optax.GradientTransformation | None = None,
    lr: float = 1e-3,
) -> Tuple[Callable, Callable]:
    """Build ``(init_fn, step_fn)`` for any optax optimizer (default SGD).

    ``init_fn(params) -> opt_state``;
    ``step_fn(params, opt_state, x, y) -> (new_params, new_opt_state, loss)``.

    When ``mesh`` is given, activations are constrained to shard batch over
    "dp" (if present); params stay replicated, so XLA emits the all-reduce
    for the gradient sum automatically.
    """
    opt = optimizer if optimizer is not None else optax.sgd(lr)

    def x_spec() -> P:
        if mesh is None:
            return P()
        names = mesh.axis_names
        # Batch (dp) sharding only. KNOWN ISSUE: annotating the H axis ("sp")
        # here produces numerically wrong conv *weight* gradients from XLA's
        # GSPMD partitioner in this JAX build (verified vs a float64 oracle:
        # bias grads match, weight grads are garbage while the forward loss
        # is correct). Spatial-parallel training instead goes through the
        # explicitly-differentiable shard_map + ppermute halo path in
        # parallel.sharded, where the collectives are ours.
        return P("dp" if "dp" in names else None)

    def loss_fn(params, x, y):
        out = forward_blocks12(params, x, cfg)
        return jnp.mean((out - y) ** 2)

    @jax.jit
    def step(params, opt_state, x, y):
        if mesh is not None:
            x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, x_spec()))
            params = jax.lax.with_sharding_constraint(params, NamedSharding(mesh, P()))
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        if mesh is not None:
            new_params = jax.lax.with_sharding_constraint(new_params, NamedSharding(mesh, P()))
        return new_params, new_opt_state, loss

    return opt.init, step

"""Blocks 1-2 forward pass on the Pallas kernel tier.

The counterpart of the reference's V3 device pass
(v3_cuda_only/src/alexnet_cuda.cu:22-95: malloc-all → H2D → 7 launches →
D2H), reduced to 5 fused launches (conv+bias+ReLU fused) with no manual
memory management — buffers are XLA-managed, eliminating V3/V4's measured
per-call cudaMalloc/weight-reupload bottleneck (PROBLEMS.txt:114-135).
"""

from __future__ import annotations

import jax

from ..models.alexnet import BLOCKS12, Blocks12Config
from . import pallas_kernels as pk


def forward_blocks12_pallas(params, x: jax.Array, cfg: Blocks12Config = BLOCKS12) -> jax.Array:
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2
    x = pk.conv2d_pallas(
        x, params["conv1"]["w"], params["conv1"]["b"], stride=c1.stride, padding=c1.padding, relu=True
    )
    x = pk.maxpool_pallas(x, window=p1.window, stride=p1.stride)
    x = pk.conv2d_pallas(
        x, params["conv2"]["w"], params["conv2"]["b"], stride=c2.stride, padding=c2.padding, relu=True
    )
    x = pk.maxpool_pallas(x, window=p2.window, stride=p2.stride)
    x = pk.lrn_pallas(
        x, size=n2.size, alpha=n2.alpha, beta=n2.beta, k=n2.k, alpha_over_size=n2.alpha_over_size
    )
    return x


def forward_alexnet_pallas(params, x: jax.Array, cfg=None) -> jax.Array:
    """Full AlexNet on the Pallas tier: chain-driven spatial part (fused
    conv+bias+ReLU launches), then the shared MXU-matmul FC head."""
    from ..models.alexnet import ConvSpec, LrnSpec, PoolSpec
    from ..models.alexnet_full import ALEXNET, fc_head

    cfg = cfg or ALEXNET
    for name, spec in cfg.layer_chain():
        if isinstance(spec, ConvSpec):
            x = pk.conv2d_pallas(
                x,
                params[name]["w"],
                params[name]["b"],
                stride=spec.stride,
                padding=spec.padding,
                relu=True,
            )
        elif isinstance(spec, PoolSpec):
            x = pk.maxpool_pallas(x, window=spec.window, stride=spec.stride)
        elif isinstance(spec, LrnSpec):
            x = pk.lrn_pallas(
                x,
                size=spec.size,
                alpha=spec.alpha,
                beta=spec.beta,
                k=spec.k,
                alpha_over_size=spec.alpha_over_size,
            )
    return fc_head(params, x, cfg)

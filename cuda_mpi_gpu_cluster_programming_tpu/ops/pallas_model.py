"""Blocks 1-2 forward pass on the Pallas kernel tier.

The counterpart of the reference's V3 device pass
(v3_cuda_only/src/alexnet_cuda.cu:22-95: malloc-all → H2D → 7 launches →
D2H), reduced to 5 fused launches (conv+bias+ReLU fused) with no manual
memory management — buffers are XLA-managed, eliminating V3/V4's measured
per-call cudaMalloc/weight-reupload bottleneck (PROBLEMS.txt:114-135).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from ..models.alexnet import BLOCKS12, Blocks12Config
from . import pallas_kernels as pk


def _chain_variant() -> str:
    """TPU_FRAMEWORK_CHAIN=pad128 runs block 1 with the channel axis
    zero-padded 96 -> 128 end to end: conv1 gains full MXU column fill
    (its N dim is the lane axis), pool1 runs on lane-aligned tiles (the
    measured 3.7x regime of the sep2 pool, scripts/pool_ab.py), and conv2
    contracts over 128 channels whose extra 32 are zeros. Padded lanes
    carry exact zeros through conv1 (zero weights, zero bias, relu(0)=0)
    and contribute exact +0.0 terms to conv2's accumulation — bitwise
    identical to the plain chain on TPU (fixed Mosaic accumulation
    order; verified on v5e), within 1 ulp on the CPU backend whose
    matmul retiles the larger contraction (tests/test_pallas.py).
    Measured on v5e b=128: no wall-clock delta vs plain (fp32 15.0 vs
    15.1 ms, bf16 3.886 vs 3.884) — conv fp32 sits at the
    precision-ceiling, not the fill limit, so the extra columns don't
    pay. Kept as a layout experiment. Same scope caveat as
    pallas_kernels.env_variant: resolved at trace time."""
    return pk.env_variant("TPU_FRAMEWORK_CHAIN", "plain", ("plain", "pad128"))


def _pad_axis(a: jax.Array, axis: int, to: int) -> jax.Array:
    if a.shape[axis] >= to:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, to - a.shape[axis])
    return jnp.pad(a, widths)


def _layer_variants(v, name: str) -> "pk.KernelVariants":
    """Dispatch a variants argument that is either one process-global
    ``KernelVariants`` (the historical shape) or a per-layer
    ``LayerVariants`` plan (the tuner's product) down to ONE layer's
    resolved knobs — the single point where the per-layer refactor meets
    the kernel wrappers."""
    return v.for_layer(name) if isinstance(v, pk.LayerVariants) else v


def _conv_then_pool(x, w, b, cspec, pspec, v: "pk.KernelVariants", lrn=None):
    """conv(+relu) then max-pool, the ONE place that decides whether the
    pool rides the conv pass — both forward builders route conv->pool
    adjacencies through here, so the geometry gates cannot drift between
    paths. ``fuse="hpool"`` fuses the pool's H stage into the conv
    epilogue; ``fuse="block"`` goes further and runs the whole block
    (conv+ReLU+pool, plus ``lrn`` when the caller passes the trailing
    LrnSpec) as one VMEM-resident megakernel pass (ops/megakernel.py).
    Both share the geometry regime: taps/vcol lowering, sep2 pool, whole
    image per program, no K-blocking. hpool is bitwise identical either
    way (_conv_epilogue); block is bitwise for fp32/bf16 (same
    accumulation order, same cast points — tests/test_megakernel.py).
    When ``lrn`` is given but the fused path is not taken, the trailing
    LRN still runs here (staged), so callers hand off the whole block
    either way."""
    from . import megakernel as mk

    ho = (x.shape[1] + 2 * cspec.padding - cspec.filter_size) // cspec.stride + 1
    if v.fuse == "block" and not mk.block_fusible_reason(
        variant=v.conv, row_block=v.row_block, k_block=v.k_block,
        pool=v.pool, out_h=ho, pool_window=pspec.window,
    ):
        return mk.conv_block_pallas(
            x, w, b, stride=cspec.stride, padding=cspec.padding,
            pool_window=pspec.window, pool_stride=pspec.stride,
            lrn=lrn, variant=v.conv, row_block=v.row_block,
        )
    if (
        v.fuse == "hpool"
        and v.conv in ("taps", "vcol")
        and v.pool == "sep2"
        and v.row_block >= ho
        and v.k_block == 0
    ):
        y = pk.conv2d_pallas(
            x, w, b, stride=cspec.stride, padding=cspec.padding, relu=True,
            variant=v.conv, row_block=v.row_block, k_block=0,
            hpool=(pspec.window, pspec.stride),
        )
        out = pk.maxpool_pallas_w(y, window=pspec.window, stride=pspec.stride)
    else:
        y = pk.conv2d_pallas(
            x, w, b, stride=cspec.stride, padding=cspec.padding, relu=True,
            variant=v.conv, row_block=v.row_block, k_block=v.k_block,
        )
        out = pk.maxpool_pallas(
            y, window=pspec.window, stride=pspec.stride, variant=v.pool
        )
    if lrn is not None:
        out = pk.lrn_pallas(
            out, size=lrn.size, alpha=lrn.alpha, beta=lrn.beta, k=lrn.k,
            alpha_over_size=lrn.alpha_over_size,
        )
    return out


def forward_blocks12_pallas(
    params,
    x: jax.Array,
    cfg: Blocks12Config = BLOCKS12,
    variants: pk.KernelVariants | pk.LayerVariants | None = None,
    chain: str | None = None,
) -> jax.Array:
    """``variants``/``chain``: explicit lowering choices. Build-time callers
    (configs.build_forward) resolve them eagerly and pass them in, so the
    selection is part of the function they jit — re-building after an env
    flip picks up the new variant (the round-3 footgun fix). ``variants``
    may be one KernelVariants for every layer or a per-layer LayerVariants
    plan (tuning/). Direct callers may omit them (env/defaults resolve at
    trace time, as before)."""
    v = variants if variants is not None else pk.KernelVariants.resolve()
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2
    pad128 = (chain if chain is not None else _chain_variant()) == "pad128"
    w1, b1 = params["conv1"]["w"], params["conv1"]["b"]
    w2, b2 = params["conv2"]["w"], params["conv2"]["b"]
    if pad128:
        kp = -(-w1.shape[-1] // 128) * 128  # conv1 output channels -> 128
        w1, b1 = _pad_axis(w1, 3, kp), _pad_axis(b1, 0, kp)
        w2 = _pad_axis(w2, 2, kp)  # conv2 contraction axis: zero rows
    x = _conv_then_pool(x, w1, b1, c1, p1, _layer_variants(v, "conv1"))
    # Block 2's trailing LRN rides the conv->pool handoff so fuse="block"
    # can fold it into the same pass; staged paths run it after the pool.
    x = _conv_then_pool(x, w2, b2, c2, p2, _layer_variants(v, "conv2"), lrn=n2)
    return x


def forward_alexnet_pallas(
    params,
    x: jax.Array,
    cfg=None,
    variants: pk.KernelVariants | pk.LayerVariants | None = None,
) -> jax.Array:
    """Full AlexNet on the Pallas tier: chain-driven spatial part (fused
    conv+bias+ReLU launches), then the shared MXU-matmul FC head.
    ``variants``: see :func:`forward_blocks12_pallas`."""
    from ..models.alexnet import ConvSpec, LrnSpec, PoolSpec
    from ..models.alexnet_full import ALEXNET, fc_head

    cfg = cfg or ALEXNET
    v = variants if variants is not None else pk.KernelVariants.resolve()
    chain = list(cfg.layer_chain())
    skip_idx: set = set()
    for idx, (name, spec) in enumerate(chain):
        if idx in skip_idx:
            continue  # this pool/LRN was consumed by _conv_then_pool
        lv = _layer_variants(v, name)
        if isinstance(spec, ConvSpec):
            nxt = chain[idx + 1][1] if idx + 1 < len(chain) else None
            if isinstance(nxt, PoolSpec):
                # conv->pool adjacency: the shared helper owns the
                # fuse="hpool"/"block" decision (one gate for both
                # builders); the conv's per-layer plan also governs the
                # pool it feeds. A trailing LRN is part of the block.
                nxt2 = chain[idx + 2][1] if idx + 2 < len(chain) else None
                lrn = nxt2 if isinstance(nxt2, LrnSpec) else None
                x = _conv_then_pool(
                    x, params[name]["w"], params[name]["b"], spec, nxt, lv,
                    lrn=lrn,
                )
                skip_idx.add(idx + 1)
                if lrn is not None:
                    skip_idx.add(idx + 2)
                continue
            x = pk.conv2d_pallas(
                x,
                params[name]["w"],
                params[name]["b"],
                stride=spec.stride,
                padding=spec.padding,
                relu=True,
                variant=lv.conv,
                row_block=lv.row_block,
                k_block=lv.k_block,
            )
        elif isinstance(spec, PoolSpec):
            x = pk.maxpool_pallas(
                x, window=spec.window, stride=spec.stride, variant=lv.pool
            )
        elif isinstance(spec, LrnSpec):
            x = pk.lrn_pallas(
                x,
                size=spec.size,
                alpha=spec.alpha,
                beta=spec.beta,
                k=spec.k,
                alpha_over_size=spec.alpha_over_size,
            )
    return fc_head(params, x, cfg)

"""Pallas flash attention: fused online-softmax attention for TPU.

The hot-op counterpart of ``ops.attention.attention`` (which materializes
the full (L, L) score matrix in HBM): one kernel per (batch, head, q-block)
streams K/V through VMEM in blocks, carrying the numerically-stable running
(max, numerator, denominator) — O(L) memory instead of O(L^2), with the
QK^T and PV matmuls on the MXU and fp32 accumulation throughout.

Composes with the sequence-parallel tier: ``ring_attention`` shards the
sequence *across* chips; this kernel is the *within-chip* block engine
(same online-softmax recurrence, one level down the memory hierarchy).

Runs in Pallas interpreter mode on non-TPU backends so the CPU test mesh
exercises the identical code path (tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .attention import NEG_INF

try:  # pltpu importable everywhere; only used for memory-space hints
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    _VMEM = None


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _spec(block_shape, index_map):
    kw = {"memory_space": _VMEM} if _VMEM is not None else {}
    return pl.BlockSpec(block_shape, index_map, **kw)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, bq: int, bk: int, causal: bool, scale: float):
    """One (batch, head, q-block) program.

    q_ref: (1, 1, bq, D); k_ref/v_ref: (1, 1, L, D); o_ref: (1, 1, bq, D).
    """
    qi = pl.program_id(2)
    d = q_ref.shape[-1]
    l = k_ref.shape[-2]
    q = q_ref[0, 0].astype(jnp.float32) * scale  # (bq, D)

    m0 = jnp.full((bq,), NEG_INF, jnp.float32)
    num0 = jnp.zeros((bq, d), jnp.float32)
    den0 = jnp.zeros((bq,), jnp.float32)

    def body(j, carry):
        m, num, den = carry
        k_blk = k_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)  # (bk, D)
        v_blk = v_ref[0, 0, pl.ds(j * bk, bk), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        if causal:
            q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        blk_max = jnp.max(s, axis=-1)  # (bq,)
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])  # (bq, bk)
        num = num * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        den = den * corr + jnp.sum(p, axis=-1)
        return m_new, num, den

    if causal:
        # Blocks strictly above the diagonal contribute nothing: iterate only
        # far enough to cover this q-block's last row (dynamic trip count).
        n_blocks = pl.cdiv((qi + 1) * bq, bk)
    else:
        n_blocks = l // bk
    _, num, den = lax.fori_loop(0, n_blocks, body, (m0, num0, den0))
    o_ref[0, 0] = (num / jnp.maximum(den, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Fused attention. q,k,v: (B, L, H, D) -> (B, L, H, D).

    ``L`` must be divisible by the (clamped) block sizes. K/V for one head
    reside in VMEM, bounding L at roughly 16 MB / (8 B * D) per head —
    beyond that, shard the sequence with ``parallel.sequence_parallel``.

    Differentiable: the backward pass recomputes gradients with the O(L^2)
    reference math (``ops.attention``) under a custom VJP — the fused kernel
    accelerates the forward/inference path; training at lengths where the
    quadratic backward is prohibitive should shard the sequence instead.
    """
    return _flash_diff(causal, block_q, block_k, q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _flash_diff(causal, block_q, block_k, q, k, v):
    return _flash_forward(q, k, v, causal=causal, block_q=block_q, block_k=block_k)


def _flash_diff_fwd(causal, block_q, block_k, q, k, v):
    out = _flash_forward(q, k, v, causal=causal, block_q=block_q, block_k=block_k)
    return out, (q, k, v)


def _flash_diff_bwd(causal, block_q, block_k, res, g):
    from .attention import attention

    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: attention(q, k, v, causal=causal), q, k, v)
    return vjp(g)


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def _flash_forward(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    block_q: int,
    block_k: int,
) -> jax.Array:
    b, l, h, d = q.shape
    bq = min(block_q, l)
    bk = min(block_k, l)
    if l % bq or l % bk:
        raise ValueError(f"sequence length {l} not divisible by blocks ({bq}, {bk})")
    scale = 1.0 / (d**0.5)  # Python math: stays static under jit tracing

    # (B, L, H, D) -> (B, H, L, D): heads become a grid axis, L contiguous.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    kernel = functools.partial(_flash_kernel, bq=bq, bk=bk, causal=causal, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid=(b, h, l // bq),
        in_specs=[
            _spec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            _spec((1, 1, l, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            _spec((1, 1, l, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=_spec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, l, d), q.dtype),
        interpret=_interpret(),
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3))

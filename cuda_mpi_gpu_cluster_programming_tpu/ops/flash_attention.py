"""Pallas flash attention: fused online-softmax attention for TPU.

The hot-op counterpart of ``ops.attention.attention`` (which materializes
the full (L, L) score matrix in HBM). Forward and backward are both O(L)
memory:

- **Forward**: grid (batch, head, q-block, k-block) — K/V are *streamed
  through VMEM one block at a time by the grid* (only (block, D) tiles are
  ever resident, not whole-L), carrying the numerically-stable running
  (max, numerator, denominator) in VMEM scratch across the k-block axis.
  QK^T and PV ride the MXU with fp32 accumulation. The forward also emits
  the per-row log-sum-exp (LSE) for the backward.
- **Backward**: FlashAttention-2-style recompute — no residual score
  matrix. Two kernels: dQ (stream K/V per q-block) and dK/dV (stream Q/dO
  per k-block), each recomputing the normalized probabilities from Q, K and
  the saved LSE, so peak memory stays O(L·D) end to end. The O(L^2) VJP
  fallback from round 1 is gone.

Composes with the sequence-parallel tier: ``ring_attention`` shards the
sequence *across* chips; this kernel is the *within-chip* block engine
(same online-softmax recurrence, one level down the memory hierarchy).

Runs in Pallas interpreter mode on non-TPU backends so the CPU test mesh
exercises the identical code path (tests/test_flash_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF
from .vma import vma_struct as _vma_struct


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _spec(block_shape, index_map):
    return pl.BlockSpec(block_shape, index_map, memory_space=pltpu.VMEM)


def _causal_mask(s, qi, ki, bq, bk):
    q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ki * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_sc, den_sc, acc_sc, *, bq, bk, causal, scale
):
    """One (batch, head, q-block, k-block) program.

    q_ref: (1, 1, bq, D); k_ref/v_ref: (1, 1, bk, D) — ONE k/v block, indexed
    by the grid (streaming). Running stats live in VMEM scratch across the
    k-block grid axis (sequential on TPU and in interpret mode).
    """
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_sc[...] = jnp.full_like(m_sc, NEG_INF)
        den_sc[...] = jnp.zeros_like(den_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    # Causal: blocks strictly above the diagonal contribute nothing — skip
    # the math (the grid still visits them; pl.when skips the compute).
    contributes = (not causal) or ((qi + 1) * bq - 1 >= ki * bk)

    @pl.when(contributes)
    def _update():
        q = q_ref[0, 0].astype(jnp.float32) * scale  # (bq, D)
        k_blk = k_ref[0, 0].astype(jnp.float32)  # (bk, D)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        m_prev = m_sc[:, 0]  # (bq,)
        den_prev = den_sc[:, 0]
        blk_max = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, blk_max)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new[:, None])  # (bq, bk)
        acc_sc[...] = acc_sc[...] * corr[:, None] + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        den_new = den_prev * corr + jnp.sum(p, axis=-1)
        m_sc[...] = jnp.broadcast_to(m_new[:, None], m_sc.shape)
        den_sc[...] = jnp.broadcast_to(den_new[:, None], den_sc.shape)

    @pl.when(ki == nk - 1)
    def _finalize():
        m = m_sc[:, 0]
        den = jnp.maximum(den_sc[:, 0], 1e-30)
        o_ref[0, 0] = (acc_sc[...] / den[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0, 0] = m + jnp.log(den)


# Lane width of the (bq,)-shaped running stats held in VMEM scratch: Mosaic
# wants >= 2D tiles, so the vectors are broadcast across a 128-lane axis.
_STAT_LANES = 128


def _flash_forward(q, k, v, *, causal, block_q, block_k, return_lse, vma=None):
    b, l, h, d = q.shape
    bq = min(block_q, l)
    bk = min(block_k, l)
    if l % bq or l % bk:
        raise ValueError(f"sequence length {l} not divisible by blocks ({bq}, {bk})")
    scale = 1.0 / (d**0.5)  # Python math: stays static under jit tracing

    # (B, L, H, D) -> (B, H, L, D): heads become a grid axis, L contiguous.
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    kernel = functools.partial(_fwd_kernel, bq=bq, bk=bk, causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, h, l // bq, l // bk),
        in_specs=[
            _spec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            _spec((1, 1, bk, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            _spec((1, 1, bk, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
        ],
        out_specs=[
            _spec((1, 1, bq, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            # LSE rides as (B, H, 1, L): Mosaic requires the block's last two
            # dims to be (sublane-divisible | equal-to-array), which a
            # (1, 1, bq) block over (B, H, L) violates (H is second-minor).
            # The explicit singleton makes the block (1, bq) vs array (1, L)
            # — legal, and caught only on real TPU (interpret mode doesn't
            # enforce tiling).
            _spec((1, 1, 1, bq), lambda bi, hi, qi, ki: (bi, hi, 0, qi)),
        ],
        out_shape=[
            _vma_struct((b, h, l, d), q.dtype, vma),
            _vma_struct((b, h, 1, l), jnp.float32, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _STAT_LANES), jnp.float32),  # running max
            pltpu.VMEM((bq, _STAT_LANES), jnp.float32),  # running denominator
            pltpu.VMEM((bq, d), jnp.float32),  # output accumulator
        ],
        interpret=_interpret(),
    )(qt, kt, vt)
    out = jnp.transpose(out, (0, 2, 1, 3))
    return (out, lse) if return_lse else out


# ---------------------------------------------------------------------------
# Backward (FlashAttention-2 recompute: no (L, L) residency anywhere)
# ---------------------------------------------------------------------------


def _recompute_p(q_ref, k_ref, lse_ref, qi, ki, bq, bk, causal, scale):
    """Normalized probabilities for one (q-block, k-block) tile, from the
    saved LSE: p = exp(s - lse) = softmax(s) exactly, no running max needed."""
    q = q_ref[0, 0].astype(jnp.float32) * scale
    k_blk = k_ref[0, 0].astype(jnp.float32)
    s = lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    if causal:
        s = _causal_mask(s, qi, ki, bq, bk)
    return jnp.exp(s - lse_ref[0, 0, 0][:, None])  # (bq, bk)


def _dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_sc, *, bq, bk, causal, scale
):
    """dQ for one q-block, streaming K/V blocks over the last grid axis."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        dq_sc[...] = jnp.zeros_like(dq_sc)

    contributes = (not causal) or ((qi + 1) * bq - 1 >= ki * bk)

    @pl.when(contributes)
    def _update():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, bq, bk, causal, scale)
        do = do_ref[0, 0].astype(jnp.float32)  # (bq, D)
        v_blk = v_ref[0, 0].astype(jnp.float32)  # (bk, D)
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        ds = p * (dp - delta_ref[0, 0, 0][:, None])  # (bq, bk)
        dq_sc[...] += scale * lax.dot_general(
            ds, k_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[...].astype(dq_ref.dtype)


def _dkv_kernel(
    k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_sc, dv_sc,
    *, bq, bk, causal, scale,
):
    """dK and dV for one k-block, streaming Q/dO blocks over the last grid axis."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_sc[...] = jnp.zeros_like(dk_sc)
        dv_sc[...] = jnp.zeros_like(dv_sc)

    contributes = (not causal) or ((qi + 1) * bq - 1 >= ki * bk)

    @pl.when(contributes)
    def _update():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, bq, bk, causal, scale)
        do = do_ref[0, 0].astype(jnp.float32)  # (bq, D)
        v_blk = v_ref[0, 0].astype(jnp.float32)
        dv_sc[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bk, D)
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # (bq, bk)
        ds = p * (dp - delta_ref[0, 0, 0][:, None])
        dk_sc[...] += scale * lax.dot_general(
            ds, q_ref[0, 0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bk, D)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, *, causal, block_q, block_k, vma=None, lse_grad=None):
    b, l, h, d = q.shape
    bq = min(block_q, l)
    bk = min(block_k, l)
    scale = 1.0 / (d**0.5)

    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    dot = jnp.transpose(out, (0, 2, 1, 3))
    gt = jnp.transpose(g, (0, 2, 1, 3))

    # delta_i = sum_d dO_i * O_i — O(L) rowwise term of dS (FA-2 eq. 4).
    # (b, h, 1, l) — same explicit-singleton layout as the LSE (see the
    # forward out_specs note on Mosaic's block tiling rule).
    delta = jnp.sum(
        gt.astype(jnp.float32) * dot.astype(jnp.float32), axis=-1, keepdims=True
    ).swapaxes(-1, -2)
    if lse_grad is not None:
        # Joint (out, lse) VJP: lse_i = logsumexp(s_i) has d(lse_i)/d(s_ij)
        # = p_ij, so an lse cotangent g_lse adds p_ij * g_lse_i to dS —
        # algebraically dS_ij = p_ij (dP_ij - (delta_i - g_lse_i)), i.e.
        # the SAME kernels with delta shifted by -g_lse. dV is untouched
        # (lse does not depend on V). This one-line shift is what makes
        # the ring engine's per-hop LSE merge differentiable end to end.
        delta = delta - lse_grad.astype(jnp.float32)[:, :, None, :]

    qb = lambda bi, hi, qi, ki: (bi, hi, qi, 0)
    kb = lambda bi, hi, qi, ki: (bi, hi, ki, 0)
    rowq = lambda bi, hi, qi, ki: (bi, hi, 0, qi)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, bq=bq, bk=bk, causal=causal, scale=scale),
        grid=(b, h, l // bq, l // bk),
        in_specs=[
            _spec((1, 1, bq, d), qb),
            _spec((1, 1, bk, d), kb),
            _spec((1, 1, bk, d), kb),
            _spec((1, 1, bq, d), qb),
            _spec((1, 1, 1, bq), rowq),
            _spec((1, 1, 1, bq), rowq),
        ],
        out_specs=_spec((1, 1, bq, d), qb),
        out_shape=_vma_struct((b, h, l, d), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=_interpret(),
    )(qt, kt, vt, gt, lse, delta)

    # k-block outer, q-block streamed innermost.
    kb2 = lambda bi, hi, ki, qi: (bi, hi, ki, 0)
    qb2 = lambda bi, hi, ki, qi: (bi, hi, qi, 0)
    rowq2 = lambda bi, hi, ki, qi: (bi, hi, 0, qi)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, bq=bq, bk=bk, causal=causal, scale=scale),
        grid=(b, h, l // bk, l // bq),
        in_specs=[
            _spec((1, 1, bk, d), kb2),
            _spec((1, 1, bk, d), kb2),
            _spec((1, 1, bq, d), qb2),
            _spec((1, 1, bq, d), qb2),
            _spec((1, 1, 1, bq), rowq2),
            _spec((1, 1, 1, bq), rowq2),
        ],
        out_specs=[
            _spec((1, 1, bk, d), kb2),
            _spec((1, 1, bk, d), kb2),
        ],
        out_shape=[
            _vma_struct((b, h, l, d), k.dtype, vma),
            _vma_struct((b, h, l, d), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(kt, vt, qt, gt, lse, delta)

    tr = lambda a: jnp.transpose(a, (0, 2, 1, 3))
    return tr(dq), tr(dk), tr(dv)


# ---------------------------------------------------------------------------
# Public API + custom VJP
# ---------------------------------------------------------------------------


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    vma=None,
) -> jax.Array:
    """Fused attention. q,k,v: (B, L, H, D) -> (B, L, H, D).

    ``L`` must be divisible by the (clamped) block sizes. Only (block, D)
    K/V tiles are VMEM-resident at a time (the grid streams them), so L is
    bounded by HBM, not VMEM.

    Differentiable with O(L)-memory: the custom VJP recomputes probabilities
    blockwise from the saved log-sum-exp (FlashAttention-2 backward) in two
    Pallas kernels — training at long L never materializes (L, L).

    ``vma``: mesh axes this call varies over when used inside a
    ``shard_map`` body with ``check_vma=True`` (e.g. the ulysses engine);
    tags the kernels' out_shapes so the caller keeps the vma checker on.
    """
    vma = tuple(vma) if vma is not None else None  # hashable static arg
    return _flash_diff(causal, block_q, block_k, vma, q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flash_diff(causal, block_q, block_k, vma, q, k, v):
    return _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        return_lse=False, vma=vma,
    )


def _flash_diff_fwd(causal, block_q, block_k, vma, q, k, v):
    out, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        return_lse=True, vma=vma,
    )
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(causal, block_q, block_k, vma, res, g):
    q, k, v, out, lse = res
    return _flash_backward(
        q, k, v, out, lse, g, causal=causal, block_q=block_q, block_k=block_k,
        vma=vma,
    )


_flash_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


def flash_block(l: int, block_q: int = 128) -> int:
    """The clamped flash block size for sequence length ``l`` — THE shared
    source of the ``l % flash_block(l) == 0`` divisibility rule, so CLI
    pre-checks (examples/lm.py) and the library validations
    (sequence_parallel) cannot drift from the kernel's actual tiling."""
    return min(block_q, l)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    vma=None,
) -> tuple:
    """Fused attention returning ``(out, lse)``, differentiable in both.

    ``out``: (B, L, H, D) normalized attention; ``lse``: (B, H, L) per-row
    log-sum-exp of the scaled scores. Two normalized partials over disjoint
    key sets merge exactly via their LSEs::

        lse  = logaddexp(lse1, lse2)
        out  = exp(lse1 - lse) * out1 + exp(lse2 - lse) * out2

    which is what the ring-attention flash engine does per hop
    (parallel.sequence_parallel). DIFFERENTIABLE, jointly in both outputs:
    the custom VJP accepts cotangents for ``out`` AND ``lse`` (the lse
    cotangent shifts the FA-2 backward's delta term by -g_lse — see
    ``_flash_backward``), which is exactly what flowing gradients through
    the ring engine's per-hop LSE merge requires. Memory stays O(L)
    (blockwise recompute, no (L, L) residency). ``vma``: see
    :func:`flash_attention`.
    """
    vma = tuple(vma) if vma is not None else None
    return _flash_lse(causal, block_q, block_k, vma, q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _flash_lse(causal, block_q, block_k, vma, q, k, v):
    out, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        return_lse=True, vma=vma,
    )
    return out, lse[:, :, 0, :]  # (B, H, 1, L) internal layout -> (B, H, L)


def _flash_lse_fwd(causal, block_q, block_k, vma, q, k, v):
    out, lse = _flash_forward(
        q, k, v, causal=causal, block_q=block_q, block_k=block_k,
        return_lse=True, vma=vma,
    )
    # Residual lse keeps the kernels' (B, H, 1, L) layout; the primal
    # output exposes (B, H, L).
    return (out, lse[:, :, 0, :]), (q, k, v, out, lse)


def _flash_lse_bwd(causal, block_q, block_k, vma, res, g):
    q, k, v, out, lse = res
    g_o, g_lse = g
    return _flash_backward(
        q, k, v, out, lse, g_o.astype(q.dtype), causal=causal,
        block_q=block_q, block_k=block_k, vma=vma, lse_grad=g_lse,
    )


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)

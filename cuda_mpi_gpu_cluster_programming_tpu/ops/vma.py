"""Varying-mesh-axes tagging for pallas_call out_shapes.

``pallas_call`` outputs carry no vma metadata, so a ``shard_map`` caller
with ``check_vma=True`` rejects any body containing a kernel — which
historically forced ``check_vma=False`` on whole bodies, silently losing
the checker on their ppermutes / all_to_alls too (round-3 advisor
finding). Kernels that can run inside shard_map accept a ``vma`` tuple of
mesh axis names and tag their out_shapes here, so callers keep the
checker on end to end.
"""

from __future__ import annotations

import os

import jax


def interpret_mode() -> bool:
    """Pallas interpret mode — same rule as every kernel's ``_interpret``."""
    return jax.default_backend() != "tpu"


def vma_struct(shape, dtype, vma=None) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct, tagged varying over ``vma`` axes when given.

    ``vma=None`` is the plain single-device call (no metadata — identical
    to the bare constructor). In interpret mode the tag is dropped: the
    HLO interpreter discharges the kernel into jax ops whose internal
    dynamic_slices mix tagged blocks with untagged grid scalars and fail
    the checker ("Primitive dynamic_slice requires varying manual axes to
    match", jax 0.9.0 hlo_interpreter.py) — its own message prescribes
    check_vma=False there, which :func:`kernel_check_vma` implements.
    """
    if vma is None or interpret_mode():
        return jax.ShapeDtypeStruct(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))


def kernel_check_vma() -> bool:
    """``check_vma`` value for shard_map bodies containing Pallas kernels:
    True on real TPU (kernels tag their out_shapes via :func:`vma_struct`,
    so the checker guards the body's collectives end to end — the scoped
    fix for the round-3 advisor finding), False in interpret mode (see
    :func:`vma_struct`; revisit when jax's interpreter propagates vma).

    ``TPU_FRAMEWORK_CHECK_VMA=0|1`` overrides — the operational
    kill-switch: the on-TPU tagged path cannot run in CI (interpret mode
    always drops the tags), so its first execution happens inside a
    scarce heal window; scripts/on_heal.sh probes it with a tiny tagged
    shard_map first and exports =0 for the rest of the queue if the
    chip-side checker rejects anything, instead of burning the capture.
    """
    env = os.environ.get("TPU_FRAMEWORK_CHECK_VMA", "").strip()
    if env in ("0", "1"):
        return env == "1"
    return not interpret_mode()

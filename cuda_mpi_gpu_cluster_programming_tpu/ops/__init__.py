from .shapes import conv_out_dim, pool_out_dim  # noqa: F401
from .reference import conv2d, relu, maxpool, lrn  # noqa: F401

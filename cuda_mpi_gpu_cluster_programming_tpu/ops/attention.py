"""Scaled dot-product attention — the single-device reference op.

The reference repo has no attention anywhere (SURVEY §2.2: its closest
structural cousin is the halo-ring over the image H axis). This op exists as
the oracle for the framework's long-context sequence-parallel strategies
(``parallel.sequence_parallel``): ring attention and Ulysses all-to-all are
validated shard-vs-single against it, exactly how the sharded conv pipeline
is validated against the single-device pass.

Layout: ``(B, L, H, D)`` — batch, sequence, heads, head_dim. bf16-friendly:
softmax statistics are computed in fp32 regardless of input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30  # finite mask value: keeps running-max math NaN-free


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
) -> jax.Array:
    """Full O(L^2) attention. q,k,v: (B, L, H, D) -> (B, L, H, D)."""
    b, lq, h, d = q.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    # (B, H, Lq, Lk) scores in fp32.
    s = jnp.einsum("blhd,bmhd->bhlm", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        lk = k.shape[1]
        mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhlm,bmhd->blhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)

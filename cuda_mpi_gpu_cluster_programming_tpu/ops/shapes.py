"""Output-shape calculators for conv and pool layers.

Reference parity: ``convOutDim``/``poolOutDim`` inline helpers
(v2_mpi_only/2.2_scatter_halo/include/alexnet.hpp:35-44), including V4's
degenerate-size guards that return 0 when the filter cannot fit
(v4_mpi_cuda/include/alexnet.hpp:28-33).
"""

from __future__ import annotations


def conv_out_dim(d: int, f: int, p: int, s: int) -> int:
    """Output length of a conv along one spatial dim: (d - f + 2p)/s + 1."""
    if d <= 0 or f <= 0 or s <= 0:
        return 0
    if f > d + 2 * p:
        return 0
    return (d - f + 2 * p) // s + 1


def pool_out_dim(d: int, f: int, s: int) -> int:
    """Output length of a VALID pool along one spatial dim: (d - f)/s + 1."""
    if d <= 0 or f <= 0 or s <= 0:
        return 0
    if f > d:
        return 0
    return (d - f) // s + 1

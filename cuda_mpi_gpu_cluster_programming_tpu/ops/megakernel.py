"""Fused-block Pallas megakernels: one VMEM-resident pass per Blocks 1-2
block (ROADMAP item 1 — the kernel the roofline layer built the judge for).

``observability.roofline.fused_blocks`` prices what a block-fused pass is
worth before it exists: staged execution round-trips every interior
activation through HBM (conv writes, pool reads, pool writes, LRN reads),
and ``staged − fused`` bytes is exactly those 2x-interior-activation
round-trips. This module deletes them: block 1 = Conv1→ReLU→Pool1 and
block 2 = Conv2→ReLU→Pool2→LRN2 each run as ONE ``pallas_call`` whose
program reads the block input + params from HBM once and writes the block
output once — everything between lives in VMEM registers.

In-kernel structure per program (one image; grid over batch only):

- **conv**: the vcol/taps accumulation from ``pallas_kernels`` verbatim —
  per-qh lane-axis concat (vcol) or tap loop (taps) over the space-to-depth
  input, fp32 accumulator, fixed order — so the conv numerics are bitwise
  the staged kernel's (whole image per program == row_block >= ho, the
  same regime hpool fusion requires).
- **epilogue**: bias + ReLU + cast for fp32/bf16; for int8w the per-channel
  rescale lands BETWEEN the fp32 accumulation and the bias
  (``precision.quantize``'s contract) — on the UNCAST accumulator, which
  the staged chain cannot do (its conv kernel writes bf16 before the host
  rescale), so the int8w megakernel is gated by tolerance, not bitwise.
- **pool**: the separable sep2 pool, both stages in-register: the H stage
  is the untiled-leading-axis phase-split reshape (``_axis_pool_kernel``'s
  math), then an in-register axis swap puts W leading for the same split.
  Valid windows never read the W alignment padding (max tap column is
  ``wo - 1``), so the relu(bias) garbage in padded columns stays inert.
- **LRN** (block 2): the banded 0/1-matrix matmul of ``_lrn_kernel``, all
  math fp32, on the pooled value.

Off-TPU the kernel runs in Pallas interpreter mode like every kernel in
``pallas_kernels`` — CPU tests hold fp32/bf16 outputs bitwise equal to the
staged Pallas chain (tests/test_megakernel.py). On-chip lowering of the
in-register W-axis swap is the open Mosaic risk; per repo precedent
(g8, hpool) the first on-chip proof + A/B rides ``scripts/on_heal.sh``'s
gated megakernel step, and the autotuner only selects the fused candidate
where it measures faster under a ToleranceGate pass.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from . import pallas_kernels as pk
from .vma import vma_struct


def block_fusible_reason(
    *,
    variant: str,
    row_block: int,
    k_block: int,
    pool: str,
    out_h: int,
    pool_window: int,
) -> str:
    """Why ``fuse="block"`` cannot lower for this knob/geometry set
    ('' = it can). The ONE gate the model builder
    (``pallas_model._conv_then_pool``), the tuner's candidate space
    (``tuning.space.prune_reason``), and the kernel wrapper all consult,
    so the three cannot drift."""
    if pool_window <= 0:
        return "block fusion needs an adjacent pool"
    if variant not in ("taps", "vcol"):
        return f"block fusion supports taps/vcol only (conv={variant})"
    if pool != "sep2":
        return (
            "block fusion pools in-kernel via the sep2 phase split "
            "(pool=phases excluded)"
        )
    if row_block < out_h:
        return (
            f"block fusion needs the whole image per program "
            f"(row_block {row_block} < ho {out_h})"
        )
    if k_block:
        return "block fusion does not compose with k_block (no K grid dim)"
    return ""


def _pool_leading_axis(out: jax.Array, *, window: int, stride: int, po: int) -> jax.Array:
    """Max-pool the LEADING axis of a (rows, width, K) in-register value via
    the untiled-leading-axis phase split — the same math as
    ``pallas_kernels._axis_pool_kernel``, on a value instead of a ref.
    Zero-padded rows never enter a valid window (taps stop at
    ``(po-1)*stride + window - 1 < rows``), mirroring ``_pool_rows``."""
    qmax = (window - 1) // stride
    q_rows = po + qmax
    rows, width, k = out.shape
    if rows < q_rows * stride:
        out = jnp.concatenate(
            [out, jnp.zeros((q_rows * stride - rows, width, k), out.dtype)],
            axis=0,
        )
    u = out[: q_rows * stride].reshape(q_rows, stride, width, k)
    res = None
    for fy in range(window):
        q, p = fy // stride, fy % stride
        win = u[q : q + po, p]
        res = win if res is None else jnp.maximum(res, win)
    return res


def _block_kernel(
    *refs,
    fq: int,
    ho: int,
    wo_p: int,
    conv_variant: str,
    pool: tuple,
    lrn: tuple | None,
    has_scale: bool,
    mid_dtype,
):
    """One fused block for one image: conv accumulation → epilogue →
    two-stage in-register pool → optional LRN → single write.

    ``pool`` = (window, stride, hp_o, wp_o); ``lrn`` = (size, alpha, beta,
    k, alpha_over_size) or None; ``mid_dtype`` is the interior compute
    dtype the staged chain would round-trip (x.dtype, or bf16 for int8w).
    """
    if has_scale:
        x_ref, w_ref, b_ref, s_ref, o_ref = refs
    else:
        x_ref, w_ref, b_ref, o_ref = refs
        s_ref = None
    cs = x_ref.shape[-1]
    k = w_ref.shape[-1]
    prec = pk._mxu_precision(x_ref.dtype)
    acc = jnp.zeros((ho * wo_p, k), jnp.float32)
    if conv_variant == "vcol":
        # _conv_vcol_kernel's accumulation verbatim (row0 = 0: whole image).
        for qh in range(fq):
            wide = jnp.concatenate(
                [
                    x_ref[0, pl.ds(qh, ho), qw : qw + wo_p, :].reshape(
                        ho * wo_p, cs
                    )
                    for qw in range(fq)
                ],
                axis=-1,
            )
            acc = acc + jnp.dot(
                wide,
                w_ref[qh].reshape(fq * cs, k),
                preferred_element_type=jnp.float32,
                precision=prec,
            )
    else:  # taps — _conv_kernel's fixed (qh, qw) order
        for qh in range(fq):
            for qw in range(fq):
                win = x_ref[0, pl.ds(qh, ho), qw : qw + wo_p, :]
                acc = acc + jnp.dot(
                    win.reshape(ho * wo_p, cs),
                    w_ref[qh, qw, :, :],
                    preferred_element_type=jnp.float32,
                    precision=prec,
                )
    out = acc.reshape(ho, wo_p, k)
    if s_ref is not None:
        # int8w epilogue rescale: per-channel scale between the fp32
        # accumulation and the bias, on the uncast accumulator.
        out = out * s_ref[:]
    out = out + b_ref[:].astype(jnp.float32)
    out = jnp.maximum(out, 0.0)  # the block contract is Conv→ReLU→Pool
    out = out.astype(mid_dtype)
    pwin, pstr, hp_o, wp_o = pool
    out = _pool_leading_axis(out, window=pwin, stride=pstr, po=hp_o)
    out = jnp.swapaxes(out, 0, 1)  # (wo_p, hp_o, K): W leads for stage 2
    out = _pool_leading_axis(out, window=pwin, stride=pstr, po=wp_o)
    out = jnp.swapaxes(out, 0, 1)  # (hp_o, wp_o, K)
    if lrn is not None:
        size, alpha, beta, lk, aos = lrn
        xf = out.astype(jnp.float32)  # _lrn_kernel: all math fp32
        h2, w2, c2 = xf.shape
        half = size // 2
        ci = lax.broadcasted_iota(jnp.int32, (c2, c2), 0)
        cj = lax.broadcasted_iota(jnp.int32, (c2, c2), 1)
        band = (jnp.abs(ci - cj) <= half).astype(jnp.float32)
        sq = (xf * xf).reshape(h2 * w2, c2)
        ssum = jnp.dot(
            sq, band,
            preferred_element_type=jnp.float32,
            precision=lax.Precision.HIGHEST,
        ).reshape(h2, w2, c2)
        a = alpha / size if aos else alpha
        out = xf / (lk + a * ssum) ** beta
    o_ref[0] = out.astype(o_ref.dtype)


def conv_block_pallas(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array,
    *,
    stride: int,
    padding: int,
    pool_window: int,
    pool_stride: int,
    lrn=None,
    variant: str | None = None,
    row_block: int | None = None,
    scale: jax.Array | None = None,
    vma=None,
) -> jax.Array:
    """One fused block: conv(+bias+ReLU) → max-pool (→ LRN) in a single
    Pallas pass. x: (N,H,W,C); w: (F,F,C,K) — for int8w pass the int8
    values cast to bf16 plus their per-channel fp32 ``scale``.

    ``lrn``: a ``models.alexnet.LrnSpec`` (or None) to fold the block's
    trailing LRN into the same pass (block 2). ``scale``: the int8w
    epilogue rescale, applied between accumulation and bias. Output dtype:
    x.dtype for fp32/bf16; for int8w, bf16 (block 1) or fp32 after the
    in-kernel LRN (block 2) — matching the staged quantized chain's
    stage-boundary dtypes. Geometry the gate refuses is a raise, never a
    silent fallback (same policy as hpool/k_block)."""
    lrn_t = None
    if lrn is not None:
        lrn_t = (
            int(lrn.size), float(lrn.alpha), float(lrn.beta), float(lrn.k),
            bool(lrn.alpha_over_size),
        )
    return _conv_block(
        x, w, b, scale,
        stride=stride,
        padding=padding,
        pool_window=pool_window,
        pool_stride=pool_stride,
        lrn=lrn_t,
        variant=variant if variant is not None else "vcol",
        row_block=row_block if row_block is not None else pk._ROW_BLOCK,
        vma=tuple(vma) if vma is not None else None,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "stride", "padding", "pool_window", "pool_stride", "lrn", "variant",
        "row_block", "vma",
    ),
)
def _conv_block(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array,
    scale: jax.Array | None,
    *,
    stride: int,
    padding: int,
    pool_window: int,
    pool_stride: int,
    lrn: tuple | None,
    variant: str,
    row_block: int,
    vma=None,
) -> jax.Array:
    n, h, wdt, c = x.shape
    f = w.shape[0]
    s = stride
    ho = (h - f + 2 * padding) // s + 1
    wo = (wdt - f + 2 * padding) // s + 1
    why = block_fusible_reason(
        variant=variant, row_block=row_block, k_block=0, pool="sep2",
        out_h=ho, pool_window=pool_window,
    )
    if why:
        raise ValueError(why)
    fq = -(-f // s)
    if padding:
        x = jnp.pad(x, ((0, 0), (padding, padding), (padding, padding), (0, 0)))
    # Whole image per program: bh == ho (the hpool regime), W sublane-aligned.
    wo_p = -(-wo // pk._W_ALIGN) * pk._W_ALIGN
    hs, ws = ho + fq - 1, wo_p + fq - 1
    xs = pk._space_to_depth(x, s, hs, ws)
    ws2d = pk._weights_to_depth(w, s, fq)
    cs = s * s * c
    kk = w.shape[-1]
    hp_o = (ho - pool_window) // pool_stride + 1
    wp_o = (wo - pool_window) // pool_stride + 1
    if scale is not None:
        mid_dtype = jnp.bfloat16
        out_dtype = jnp.float32 if lrn is not None else jnp.bfloat16
    else:
        mid_dtype = out_dtype = x.dtype
    kernel = functools.partial(
        _block_kernel,
        fq=fq, ho=ho, wo_p=wo_p, conv_variant=variant,
        pool=(pool_window, pool_stride, hp_o, wp_o),
        lrn=lrn, has_scale=scale is not None, mid_dtype=mid_dtype,
    )
    in_specs = [
        pk._vmem_spec((1, hs, ws, cs), lambda i: (i, 0, 0, 0)),
        pk._vmem_spec(),
        pk._vmem_spec(),
    ]
    operands = [xs, ws2d, b]
    if scale is not None:
        in_specs.append(pk._vmem_spec())
        operands.append(scale)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=in_specs,
        out_specs=pk._vmem_spec((1, hp_o, wp_o, kk), lambda i: (i, 0, 0, 0)),
        out_shape=vma_struct((n, hp_o, wp_o, kk), out_dtype, vma),
        compiler_params=pk._tc_params("parallel"),
        interpret=pk._interpret(),
    )(*operands)


def int8w_conv_block_pallas(
    x: jax.Array,
    q: jax.Array,
    scale: jax.Array,
    b: jax.Array,
    *,
    stride: int,
    padding: int,
    pool_window: int,
    pool_stride: int,
    lrn=None,
    variant: str | None = None,
    row_block: int | None = None,
    vma=None,
) -> jax.Array:
    """The dequant-free int8w megakernel variant: int8 weights cast to bf16
    (exact for |q| <= 127), bf16 MACs, fp32 accumulate, per-channel rescale
    in the epilogue — ``precision.quantize.int8w_conv``'s numerics fused
    through the whole block, minus the staged path's bf16 round-trip of the
    accumulator before the rescale."""
    return conv_block_pallas(
        x.astype(jnp.bfloat16),
        q.astype(jnp.bfloat16),
        b.astype(jnp.float32),
        stride=stride,
        padding=padding,
        pool_window=pool_window,
        pool_stride=pool_stride,
        lrn=lrn,
        variant=variant,
        row_block=row_block,
        scale=scale.astype(jnp.float32),
        vma=vma,
    )

"""Hand-written Pallas TPU kernels for the four layer ops.

The TPU-native counterpart of the reference's CUDA kernels
(v3_cuda_only/src/layers_cuda.cu:20-152: convKernel, reluKernel, poolKernel,
lrnKernel; hardened V4 copies v4_mpi_cuda/src/layers_mpi_cuda.cu:25-136).
NOT a translation: the CUDA kernels map one thread per output element —
scalar code that would waste the MXU entirely. Here:

- conv: for each (fy, fx) filter tap, a strided window of the image becomes
  a (BH*Wo, C) x (C, K) matmul on the MXU, accumulated in fp32 VMEM. The
  channel axes live on the 128-wide lanes. Bias add + optional ReLU are
  fused into the same kernel (the reference launches ReLU separately).
- maxpool: separable two-stage max (rows then cols). The stride-s phase
  split is a PURE VIEW reshape (H -> (H/s, s) preserves contiguity), so
  no strided gather is ever materialized; the W stage reuses the same
  kernel after an XLA transpose. Measured on v5e (scripts/pool_ab.py,
  b=128 fp32): 3.7x faster than the phase-stack kernel on lane-aligned
  channels (pool2: 0.39 vs 1.44 ms), within noise on pool1's C=96.
  TPU_FRAMEWORK_POOL=phases restores the old single-kernel lowering.
- LRN: channel-window sum of squares via shifted adds, one pow + divide —
  both LRN alpha conventions supported (see ops.reference.lrn).

Conv grid: one program per (batch image, BH-row output block). Row tiling
keeps the per-program accumulator and window slices small — the earlier
whole-image-per-program layout blew the 16 MB scoped-VMEM limit at batch
>= 128 on a real v5e (18.5 MB stack allocation). W is padded to a multiple
of 16 so collapsing (BH, Wo, C) windows to 2-D matmul operands is a
layout-legal reshape for fp32 (8-sublane) AND bf16 (16-sublane) — Mosaic
rejects the unaligned collapse outright in bf16 ("unsupported shape cast").
Accumulation order over filter taps is fixed (row-major fy, fx), giving
deterministic numerics across runs; fp32 inputs use HIGHEST (true-fp32)
MXU precision, bf16 inputs the native bf16 MACs with fp32 accumulation.

On non-TPU backends the kernels run in Pallas interpreter mode so the same
code path is unit-testable on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from .vma import vma_struct

try:  # pltpu is importable on CPU; only used for memory-space hints
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _tc_params(*semantics: str):
    """Grid dimension semantics for the Mosaic scheduler ('parallel' grid
    dims let it pipeline DMA against compute across programs). None in
    interpreter mode, where CompilerParams is ignored anyway."""
    if pltpu is None or _interpret():
        return None
    return pltpu.CompilerParams(dimension_semantics=tuple(semantics))


def _vmem_spec(block_shape=None, index_map=None):
    kw = {}
    if _VMEM is not None:
        kw["memory_space"] = _VMEM
    if block_shape is None:
        return pl.BlockSpec(**kw)
    return pl.BlockSpec(block_shape, index_map, **kw)


def env_variant(env_name: str, default: str, allowed: tuple) -> str:
    """Resolve a lowering-variant switch from the environment (shared by
    TPU_FRAMEWORK_CONV / _POOL here and _CHAIN in pallas_model).

    Resolved at TRACE time — outside the per-op jit, so the variant
    participates in the jit cache key. Build-time callers
    (configs.build_forward, the sharded tier) resolve variants EAGERLY via
    KernelVariants.resolve() and close over the result, so re-calling
    build_forward after an env flip returns a function with the new
    variant — the supported A/B workflow is build-per-variant (the round-3
    process-per-variant footgun is gone; tests/test_configs.py holds
    this)."""
    import os

    v = os.environ.get(env_name, "").strip().lower()
    if not v:
        return default  # unset or set-but-empty: the default
    if v not in allowed:
        raise ValueError(f"{env_name} must be {'|'.join(allowed)}, got {v!r}")
    return v


# Conv lowering variants:
#   "taps"  (default) — fq^2 tap matmuls per row block, static unroll.
#   "pairs" — adjacent-qw taps fused two-at-a-time: a host-side shifted
#             concat doubles the contraction dim (conv1: 48 -> 96 of the
#             MXU's 128 rows, the round-3 verdict's named underfill lever)
#             at 2x input HBM — the midpoint between "taps" (1x HBM, 48
#             contraction) and "fused" (fq^2 x HBM, measured 2x slower).
#   "fused" — host-side im2col + ONE big matmul per row block. Measured
#             ~2x SLOWER on v5e (docs/PALLAS_PERF.md round-3 results);
#             kept as the recorded negative result.
#   "vcol"  — in-kernel (VMEM) im2col over the qw taps: taps' 1x HBM
#             traffic with one fq*cs-contraction matmul per qh row
#             (round-5 lever; see _conv_vcol_kernel). ADOPTED as the
#             default from the 2026-07-31 on-chip A/B: with rowblock 64
#             it is the grid winner at b=128 — bf16 2.997 ms (42.7k
#             img/s, 0.42x v1_jit, from taps' 0.38x) and fp32 11.003 ms
#             (11.6k img/s, 0.53x v1_jit — the first tier/compute cell
#             to clear the 0.5x adoption bar).
#   "g8"    — phase-packed conv for strided convs (s>=2; s=1 falls back
#             to vcol): space-to-depth at g=2s puts g*g*C channels on the
#             lanes (conv1: 192 vs 48) and computes the 2x2 output phases
#             on separate grid programs (see _conv_g8_kernel). Round-5
#             named lever targeting conv1's measured data-movement bound;
#             coded + CPU-verified against a wedged chip, on-chip
#             lowering proof and A/B queued in scripts/on_heal.sh.
def _conv_variant() -> str:
    return env_variant("TPU_FRAMEWORK_CONV", "vcol", ("taps", "pairs", "fused", "vcol", "g8"))


# Default output rows per conv program (TPU_FRAMEWORK_ROWBLOCK overrides).
# BH * Wo_pad is the matmul M dim. 64 (i.e. the whole 55/27-row image for
# the AlexNet convs, grid over batch only) won the 2026-07-31 on-chip
# rowblock sweep at every measured cell — 8/16/32/64 bf16 full pass:
# 3.588/3.642/3.219/2.997 ms with vcol — the per-program VMEM footprint
# (conv1 rb=64: ~360 KB window + ~1.4 MB acc) stays well under budget.
_ROW_BLOCK = 64
# W padded up to this multiple so the (BH, Wo, C) -> (BH*Wo, C) collapse is
# sublane-aligned for fp32 (8) and bf16 (16) alike.
_W_ALIGN = 16


# Output-row block height (the matmul M dim is rowblock * Wo_pad): a wider
# block amortizes per-program overhead and weight re-reads across more MXU
# work at more VMEM per program — the round-3 verdict's lever (b), made
# measurable now that the sep2 pool freed VMEM headroom.
def _row_block() -> int:
    return int(env_variant("TPU_FRAMEWORK_ROWBLOCK", str(_ROW_BLOCK), ("8", "16", "32", "64")))


# Output-channel (K) grid blocking for the taps variant — the third lever,
# named in the round-4 verdict as the follow-up if pairs/rowblock miss the
# bar. 0 = off (whole K per program, the historical layout). A K block
# splits the filter bank across grid programs: each program's weight slice
# and accumulator shrink K/nk-fold (conv2: 256 -> 128 halves the VMEM-
# resident weights and the fp32 acc), buying Mosaic pipelining headroom at
# the cost of re-reading the input window once per K block. Output blocks
# are disjoint, accumulation order per output element is unchanged —
# bitwise identical to unblocked, like the rowblock lever.
def _k_block() -> int:
    return int(env_variant("TPU_FRAMEWORK_KBLOCK", "0", ("0", "64", "128")))


# One warning per (k_block, K) per process: trace-time, so an unbounded
# per-call stream would drown the A/B log it is trying to protect.
_K_BLOCK_WARNED: set = set()


def _warn_k_block_dropped(k_block: int, kk: int) -> None:
    key = (k_block, kk)
    if key in _K_BLOCK_WARNED:
        return
    _K_BLOCK_WARNED.add(key)
    import warnings

    warnings.warn(
        f"requested k_block={k_block} does not apply to K={kk} (needs "
        f"K % k_block == 0 and K > k_block) — this conv runs UNBLOCKED; "
        "label its A/B rows kb=0 (KernelVariants.bind(K) makes the repr "
        f"state this: kb={k_block}->0(K={kk}))",
        RuntimeWarning,
        stacklevel=3,
    )


# Epilogue fusion (round-5 lever): "hpool" fuses the separable pool's H
# stage into the conv epilogue where the model's conv feeds a pool (the
# full-height conv output never round-trips HBM; the pool's first kernel
# launch disappears). Bitwise-neutral (exact max on the casted value).
# Applies where the geometry allows (taps/vcol, row_block >= ho); the
# model builder falls back to the separate pool otherwise.
# "block" goes all the way: the whole block (conv+ReLU+pool, +LRN when one
# trails the pool) runs as ONE VMEM-resident pass (ops/megakernel.py) —
# interior activations never touch HBM. Same geometry regime as hpool
# (taps/vcol, sep2, whole image per program, no k_block).
def _fuse_variant() -> str:
    return env_variant("TPU_FRAMEWORK_FUSE", "none", ("none", "hpool", "block"))


class KernelVariants(NamedTuple):
    """Resolved lowering-variant set — hashable, so it can ride jit static
    args. ``resolve()`` reads the environment ONCE; build-time callers
    (configs.build_forward, the sharded tier) resolve eagerly and close
    over the result, which kills the round-3 footgun where flipping an env
    var after the first forward silently kept the old variant inside the
    outer jit's trace: every ``build_forward`` call now re-reads the env
    and returns a fresh function carrying its variants explicitly."""

    conv: str = "vcol"
    pool: str = "sep2"
    row_block: int = _ROW_BLOCK
    k_block: int = 0
    fuse: str = "none"
    # Layer-binding metadata, NOT a lowering knob: the conv's output-channel
    # count when the variants are bound to one layer (``bind``; the tuner's
    # per-layer plans always bind). 0 = unbound/process-global. Lets the repr
    # state the EFFECTIVE k_block next to the requested one, so tuner logs
    # and A/B rows are self-labeling even though _warn_k_block_dropped fires
    # only once per process.
    k_channels: int = 0

    @classmethod
    def resolve(cls) -> "KernelVariants":
        return cls(
            conv=_conv_variant(), pool=_pool_variant(), row_block=_row_block(),
            k_block=_k_block(), fuse=_fuse_variant(),
        )

    def bind(self, k_channels: int) -> "KernelVariants":
        """The same knobs bound to a conv with K output channels."""
        return self._replace(k_channels=k_channels)

    def knobs(self) -> "KernelVariants":
        """The lowering knobs alone (binding stripped) — the equality the
        tuner's candidate dedup and tests should compare on."""
        return self._replace(k_channels=0)

    @property
    def effective_k_block(self) -> int:
        """The k_block that actually applies at K=k_channels (the geometry
        gate in _conv2d_pallas: K % k_block == 0 and K > k_block, else the
        conv runs unblocked). Unbound variants report the requested value —
        only a bound layer has a geometry to judge against. The hardware
        lane rule (k_block % 128) is NOT folded in: on chip that case
        raises rather than silently degrading."""
        if not self.k_block or not self.k_channels:
            return self.k_block
        if self.k_channels % self.k_block == 0 and self.k_channels > self.k_block:
            return self.k_block
        return 0

    def label(self) -> str:
        """Compact A/B-row/tuner-log label; requested->effective k_block is
        spelled out when a bound geometry drops the request."""
        kb = str(self.k_block)
        if self.k_channels and self.effective_k_block != self.k_block:
            kb = f"{self.k_block}->{self.effective_k_block}(K={self.k_channels})"
        return (
            f"conv={self.conv} pool={self.pool} rb={self.row_block} "
            f"kb={kb} fuse={self.fuse}"
        )

    def __repr__(self) -> str:
        return f"KernelVariants({self.label()})"


class LayerVariants(NamedTuple):
    """Per-layer lowering plan — the tuner's product (tuning/). Variants are
    no longer process-global: each conv layer (and the pool it feeds) can
    carry its own ``KernelVariants``. Hashable like KernelVariants, so a
    plan can ride closures/static args the same way. Forward builders accept
    either type; ``ops.pallas_model._layer_variants`` dispatches."""

    layers: tuple = ()  # ((layer_name, KernelVariants), ...)
    default: KernelVariants = KernelVariants()

    def for_layer(self, name: str) -> KernelVariants:
        for n, v in self.layers:
            if n == name:
                return v
        return self.default


def _mxu_precision(dtype):
    """fp32 inputs: HIGHEST = true fp32 MACs on the MXU (the default would
    round the operands to bf16 and miss the reference numerics by ~1e-3
    rel). bf16 inputs: native bf16 MACs, fp32 accumulation."""
    return lax.Precision.HIGHEST if dtype == jnp.float32 else lax.Precision.DEFAULT


def _conv_epilogue(acc, b_ref, o_ref, *, bh: int, wo_p: int, k: int, relu: bool,
                   hpool=None):
    """Shared bias + optional-ReLU + cast tail of both conv variants —
    one place, so the variants cannot diverge numerically in the epilogue.

    ``hpool=(window, stride, hp_o)`` (round-5 fusion lever): additionally
    max-pool the H axis in-kernel before the write, so the full-height
    conv output never round-trips HBM and the separable pool's first
    stage disappears. Requires the whole image in one program (bh == ho).
    The pool runs on the CASTED value — exactly the tensor the unfused
    sep2 H-stage would read back — and the row phase-split is a reshape
    of the leading UNTILED axis (the tiled (W, C) dims are untouched), so
    the result is bitwise identical to conv-then-pool
    (tests/test_pallas.py::test_conv_hpool_fusion_bitwise)."""
    out = acc.reshape(bh, wo_p, k) + b_ref[:].astype(jnp.float32)
    if relu:
        out = jnp.maximum(out, 0.0)
    out = out.astype(o_ref.dtype)
    if hpool is not None:
        window, stride, hp_o = hpool
        qmax = (window - 1) // stride
        hq = hp_o + qmax           # H view-rows the pool reads
        if bh < hq * stride:       # pad rows never entering a window
            out = jnp.concatenate(
                [out, jnp.zeros((hq * stride - bh, wo_p, k), out.dtype)], axis=0
            )
        u = out[: hq * stride].reshape(hq, stride, wo_p, k)
        res = None
        for fy in range(window):
            q, p = fy // stride, fy % stride
            win = u[q : q + hp_o, p]
            res = win if res is None else jnp.maximum(res, win)
        out = res
    o_ref[0] = out


def _conv_fused_kernel(x_ref, w_ref, b_ref, o_ref, *, bh: int, wo_p: int, relu: bool):
    """im2col variant: x_ref (1, bh, wo_p, fq^2*cs), w_ref (fq^2*cs, K)."""
    kdim = x_ref.shape[-1]
    k = w_ref.shape[-1]
    acc = jnp.dot(
        x_ref[0].reshape(bh * wo_p, kdim),
        w_ref[:],
        preferred_element_type=jnp.float32,
        precision=_mxu_precision(x_ref.dtype),
    )
    _conv_epilogue(acc, b_ref, o_ref, bh=bh, wo_p=wo_p, k=k, relu=relu)


def _pairs_acc(xp_ref, wp_ref, leftover, *, fq: int, bh: int, wo_p: int):
    """Shared pair-matmul accumulation of both pairs kernels: xp_ref
    (1, Hs, Ws-1, 2*S*S*C) holds column j's and j+1's channels concatenated
    (host-side shifted concat), so tap pair (qw=2p, 2p+1) is ONE matmul with
    a doubled contraction dim. ``leftover`` is ``(x_ref, wl_ref)`` for the
    odd trailing tap (fq odd; reads the plain s2d buffer), or None when fq
    is even. Accumulation order is fixed (qh outer; pairs left-to-right,
    then the leftover), so results stay deterministic — but differ from
    "taps" in the last ulps (one 2cs-wide reduction vs two cs-wide adds);
    tests hold bitwise equality within a variant, allclose across variants.
    """
    cs2 = xp_ref.shape[-1]
    k = wp_ref.shape[-1]
    row0 = pl.program_id(1) * bh
    prec = _mxu_precision(xp_ref.dtype)
    n_pairs = fq // 2
    acc = jnp.zeros((bh * wo_p, k), jnp.float32)
    for qh in range(fq):
        for p in range(n_pairs):
            win = xp_ref[0, pl.ds(row0 + qh, bh), 2 * p : 2 * p + wo_p, :]
            acc = acc + jnp.dot(
                win.reshape(bh * wo_p, cs2),
                wp_ref[qh, p, :, :],
                preferred_element_type=jnp.float32,
                precision=prec,
            )
        if leftover is not None:
            x_ref, wl_ref = leftover
            cs = x_ref.shape[-1]
            win = x_ref[0, pl.ds(row0 + qh, bh), fq - 1 : fq - 1 + wo_p, :]
            acc = acc + jnp.dot(
                win.reshape(bh * wo_p, cs),
                wl_ref[qh, :, :],
                preferred_element_type=jnp.float32,
                precision=prec,
            )
    return acc


def _conv_pairs_kernel(
    xp_ref, x_ref, wp_ref, wl_ref, b_ref, o_ref, *, fq: int, bh: int, wo_p: int, relu: bool
):
    """Odd-fq pairs variant: pair matmuls plus the leftover tap from x_ref."""
    acc = _pairs_acc(xp_ref, wp_ref, (x_ref, wl_ref), fq=fq, bh=bh, wo_p=wo_p)
    _conv_epilogue(acc, b_ref, o_ref, bh=bh, wo_p=wo_p, k=wp_ref.shape[-1], relu=relu)


def _conv_pairs_even_kernel(
    xp_ref, wp_ref, b_ref, o_ref, *, fq: int, bh: int, wo_p: int, relu: bool
):
    """Even-fq pairs variant: pairs cover every tap, so the plain s2d buffer
    and the leftover weight tap are not operands at all — the round-4
    advisor flagged their dead VMEM residency/HBM traffic in the variant
    whose whole point is better HBM/MXU balance."""
    acc = _pairs_acc(xp_ref, wp_ref, None, fq=fq, bh=bh, wo_p=wo_p)
    _conv_epilogue(acc, b_ref, o_ref, bh=bh, wo_p=wo_p, k=wp_ref.shape[-1], relu=relu)


def _conv_kernel(x_ref, w_ref, b_ref, o_ref, *, fq: int, bh: int, wo_p: int, relu: bool, hpool=None):
    """Space-to-depth conv: x_ref (1, Hs, Ws, S*S*C), w_ref (fq, fq, S*S*C, K).

    Program (i, j) computes output rows [j*bh, (j+1)*bh) of image i. Every
    tap group is a unit-stride window slice feeding one MXU matmul (Mosaic
    forbids strided vector slices, and skinny K-dim matmuls would waste the
    systolic array — the S*S*C contraction axis fixes both).
    """
    cs = x_ref.shape[-1]
    k = w_ref.shape[-1]
    row0 = pl.program_id(1) * bh
    prec = _mxu_precision(x_ref.dtype)

    # Fully static fq x fq tap unroll: with 8-row windows (~100 KB each)
    # the whole tap set fits VMEM comfortably (the pre-row-tiling kernel
    # could only afford a fori_loop over qh — full unrolling of whole-image
    # windows OOMed), and straight-line code lets Mosaic software-pipeline
    # the matmul chain. Fixed (qh outer, qw inner) order => deterministic
    # fp32 accumulation (SURVEY §7.3). The dynamic H start (row0 + qh) is
    # legal because dim 1 is untiled; W taps must be static slices — W is
    # the sublane-tiled dim, where Mosaic requires provable 8-alignment.
    acc = jnp.zeros((bh * wo_p, k), jnp.float32)
    for qh in range(fq):
        for qw in range(fq):
            win = x_ref[0, pl.ds(row0 + qh, bh), qw : qw + wo_p, :]
            wtap = w_ref[qh, qw, :, :]
            acc = acc + jnp.dot(
                win.reshape(bh * wo_p, cs),
                wtap,
                preferred_element_type=jnp.float32,
                precision=prec,
            )
    _conv_epilogue(acc, b_ref, o_ref, bh=bh, wo_p=wo_p, k=k, relu=relu, hpool=hpool)


def _conv_vcol_kernel(x_ref, w_ref, b_ref, o_ref, *, fq: int, bh: int, wo_p: int, relu: bool, hpool=None):
    """VMEM-level im2col over the qw taps (round-5 lever, named from the
    per-layer A/B in scripts/v3_layer_ab.py): same operands and HBM
    traffic as "taps" (1x input), but the fq qw-windows are concatenated
    on the lane axis INSIDE the kernel, so each qh row is ONE matmul with
    an fq*cs contraction (conv1: 144 vs 48 of the MXU's 128 rows; conv2:
    480 vs 96) instead of fq skinny ones. This is "pairs"/"fused"'s fill
    win without their host-side HBM blowup — the concat is a VMEM lane
    relayout, whose cost is what the A/B measures. Accumulation: one
    reduction per qh over fq*cs (deterministic; differs from taps in the
    last ulps like the other variants — allclose across variants, bitwise
    within)."""
    cs = x_ref.shape[-1]
    k = w_ref.shape[-1]
    row0 = pl.program_id(1) * bh
    prec = _mxu_precision(x_ref.dtype)
    acc = jnp.zeros((bh * wo_p, k), jnp.float32)
    for qh in range(fq):
        # The qw windows are sublane-shifted views of the same rows;
        # Mosaic's concat requires matching offsets on non-concat dims
        # ("result/input offset mismatch", measured on v5e), so each
        # window is reshaped to 2-D FIRST — merging the (bh, wo_p) tiles
        # forces an offset-0 materialization — and the concat runs on the
        # lane axis of the already-flat operands.
        wide = jnp.concatenate(
            [
                x_ref[0, pl.ds(row0 + qh, bh), qw : qw + wo_p, :].reshape(
                    bh * wo_p, cs
                )
                for qw in range(fq)
            ],
            axis=-1,
        )
        acc = acc + jnp.dot(
            wide,
            w_ref[qh].reshape(fq * cs, k),
            preferred_element_type=jnp.float32,
            precision=prec,
        )
    _conv_epilogue(acc, b_ref, o_ref, bh=bh, wo_p=wo_p, k=k, relu=relu, hpool=hpool)


def _conv_g8_kernel(x_ref, w_ref, b_ref, o_ref, *, fq8: int, bh: int, wo_p: int, relu: bool):
    """Phase-packed conv (the round-5 verdict-protocol 'next lever' for
    conv1): x_ref (1, hs8, ws8, G) is the input space-to-depth-packed at
    g = 2*stride (G = g*g*C — conv1: 192 lanes vs the stride-s packing's
    48), w_ref (1, 1, fq8, fq8, G, K) is THIS program's phase weight
    frame, o_ref (1, 1, 1, bh, wo_p, K) is one of the 2x2 output phases
    (out row j = 2a + phase_h; the de-interleave is a host-side XLA
    transpose). Grid (N, 2, 2). Every phase-output row a reads g-rows
    [a, a+fq8) regardless of phase — the phase's intra-block offset lives
    entirely in the zero-padded weight frame — so the kernel body is the
    vcol lowering at 4x the lane occupancy and fq8 (=2 for conv1) taps
    per axis instead of fq (=3)."""
    gch = x_ref.shape[-1]
    k = w_ref.shape[-1]
    prec = _mxu_precision(x_ref.dtype)
    acc = jnp.zeros((bh * wo_p, k), jnp.float32)
    for qh in range(fq8):
        wide = jnp.concatenate(
            [
                x_ref[0, qh : qh + bh, qw : qw + wo_p, :].reshape(bh * wo_p, gch)
                for qw in range(fq8)
            ],
            axis=-1,
        )
        acc = acc + jnp.dot(
            wide,
            w_ref[0, 0, qh].reshape(fq8 * gch, k),
            preferred_element_type=jnp.float32,
            precision=prec,
        )
    # Shared epilogue via a phase sub-ref (o_ref.at[0, 0] drops the two
    # leading unit dims so _conv_epilogue's o_ref[0] write lands on
    # [0, 0, 0]) — the one-place invariant holds across all variants.
    _conv_epilogue(acc, b_ref, o_ref.at[0, 0], bh=bh, wo_p=wo_p, k=k, relu=relu)


def _weights_to_phase_depth(w: jax.Array, s: int, g: int, fq8: int) -> jax.Array:
    """(F, F, C, K) -> (2, 2, fq8, fq8, g*g*C, K) phase weight frames.

    Phase (ph, pw) of the g = 2s packing sees the filter at spatial offset
    (ph*s, pw*s) inside its fq8*g-wide zero frame; the frame is then
    depth-packed with the same (g_h, g_w, c) channel order as
    :func:`_space_to_depth`, so frame row v = ph*s + u lands at tap v//g,
    channel block v%g — exactly where input row j*s + u sits in xs8."""
    f, _, c, k = w.shape
    frames = []
    for ph in range(2):
        row = []
        for pw in range(2):
            wp = jnp.pad(
                w,
                (
                    (ph * s, fq8 * g - f - ph * s),
                    (pw * s, fq8 * g - f - pw * s),
                    (0, 0),
                    (0, 0),
                ),
            )
            wp = wp.reshape(fq8, g, fq8, g, c, k)
            row.append(wp.transpose(0, 2, 1, 3, 4, 5).reshape(fq8, fq8, g * g * c, k))
        frames.append(jnp.stack(row))
    return jnp.stack(frames)


def _space_to_depth(x: jax.Array, s: int, hs: int, ws: int) -> jax.Array:
    """(N, H, W, C) -> (N, hs, ws, s*s*C); H, W zero-padded to hs*s, ws*s.

    Geometries where (H - F) % S != 0 leave trailing rows/cols the conv
    never reads — cropped here, matching the reference's floor-division
    output dims (convOutDim, v2_mpi_only/2.2_scatter_halo/include/alexnet.hpp:35-39).
    """
    n, h, w, c = x.shape
    if h < hs * s or w < ws * s:
        x = jnp.pad(
            x, ((0, 0), (0, max(0, hs * s - h)), (0, max(0, ws * s - w)), (0, 0))
        )
    x = x[:, : hs * s, : ws * s, :]
    x = x.reshape(n, hs, s, ws, s, c)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(n, hs, ws, s * s * c)


def _weights_to_depth(w: jax.Array, s: int, fq: int) -> jax.Array:
    """(F, F, C, K) -> (fq, fq, s*s*C, K), zero taps past F."""
    f, _, c, k = w.shape
    if f < fq * s:
        w = jnp.pad(w, ((0, fq * s - f), (0, fq * s - f), (0, 0), (0, 0)))
    w = w.reshape(fq, s, fq, s, c, k)
    return w.transpose(0, 2, 1, 3, 4, 5).reshape(fq, fq, s * s * c, k)


def conv2d_pallas(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array,
    *,
    stride: int,
    padding: int = 0,
    padding_w: int | None = None,
    relu: bool = False,
    vma=None,
    variant: str | None = None,
    row_block: int | None = None,
    k_block: int | None = None,
    hpool: tuple | None = None,
) -> jax.Array:
    """Direct conv (+bias, optional fused ReLU) — thin wrapper resolving the
    lowering variant (explicit arg wins; env var otherwise) before entering
    jit. ``vma``: mesh axes the call varies over inside a check_vma=True
    shard_map (ops.vma). ``hpool=(window, stride)``: fuse the separable
    pool's H stage into the conv epilogue (requires variant taps/vcol and
    row_block >= the conv's output height; see _conv_epilogue) — the
    result has pooled H, full W; run :func:`maxpool_pallas_w` after."""
    return _conv2d_pallas(
        x, w, b, stride=stride, padding=padding, padding_w=padding_w,
        relu=relu,
        variant=variant if variant is not None else _conv_variant(),
        row_block=row_block if row_block is not None else _row_block(),
        k_block=k_block if k_block is not None else _k_block(),
        vma=tuple(vma) if vma is not None else None,
        hpool=hpool,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "stride", "padding", "padding_w", "relu", "variant", "row_block",
        "k_block", "vma", "hpool",
    ),
)
def _conv2d_pallas(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array,
    *,
    stride: int,
    padding: int = 0,
    padding_w: int | None = None,
    relu: bool = False,
    variant: str = "taps",
    row_block: int = _ROW_BLOCK,
    k_block: int = 0,
    vma=None,
    hpool: tuple | None = None,
) -> jax.Array:
    """Direct conv (+bias, optional fused ReLU). x: (N,H,W,C), w: (F,F,C,K).

    ``padding`` pads H; ``padding_w`` (default = padding) pads W — the split
    exists for the row-sharded tier, whose halo machinery supplies the H
    context (VALID on H, padded on W).

    Strided convolution is lowered by phase decomposition (space-to-depth):
    the input is repacked host-side to (N, H/S, W/S, S*S*C) and the weights
    to (ceil(F/S)^2, S*S*C, K); output row i tap fy reads s2d row
    ``i + fy//S`` channel block ``fy%S`` — so the kernel's window slices are
    all unit-stride and each matmul contracts over S*S*C. For S=1 this
    degenerates to the identity packing.
    """
    if hpool is not None and variant not in ("taps", "vcol"):
        raise ValueError(
            f"hpool fusion supports the taps/vcol lowering only, got {variant!r}"
        )
    n, h, wdt, c = x.shape
    f = w.shape[0]
    s = stride
    pw = padding if padding_w is None else padding_w
    ph = padding
    ho = (h - f + 2 * ph) // s + 1
    wo = (wdt - f + 2 * pw) // s + 1
    fq = -(-f // s)  # ceil(F/S): tap groups per axis

    if ph or pw:
        x = jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)))

    if variant == "g8" and s >= 2:
        # Phase-packed lowering (round-5 'next lever', per-layer A/B
        # attribution in docs/PALLAS_PERF.md): repack at g = 2s so the
        # lane dim carries g*g*C channels (conv1: 192 vs 48 — the vcol
        # kernel's window relayouts ran at 37% lane occupancy, which the
        # A/B measured as conv1's dominating cost), compute the 2x2
        # output phases on separate grid programs, and de-interleave with
        # one host-side XLA transpose of the (smaller) output.
        g = 2 * s
        fq8 = -(-(f + s) // g)       # g-taps per axis, max over phases
        ho2, wo2 = -(-ho // 2), -(-wo // 2)
        wo2_p = -(-wo2 // _W_ALIGN) * _W_ALIGN
        bh8 = ho2                    # whole phase image per program
        hs8, ws8 = bh8 + fq8 - 1, wo2_p + fq8 - 1
        xs8 = _space_to_depth(x, g, hs8, ws8)
        w8 = _weights_to_phase_depth(w, s, g, fq8)
        gch = g * g * c
        kk = w.shape[-1]
        out8 = pl.pallas_call(
            functools.partial(
                _conv_g8_kernel, fq8=fq8, bh=bh8, wo_p=wo2_p, relu=relu
            ),
            grid=(n, 2, 2),
            in_specs=[
                _vmem_spec((1, hs8, ws8, gch), lambda i, u, v: (i, 0, 0, 0)),
                _vmem_spec(
                    (1, 1, fq8, fq8, gch, kk),
                    lambda i, u, v: (u, v, 0, 0, 0, 0),
                ),
                _vmem_spec(),
            ],
            out_specs=_vmem_spec(
                (1, 1, 1, bh8, wo2_p, kk), lambda i, u, v: (i, u, v, 0, 0, 0)
            ),
            out_shape=vma_struct((n, 2, 2, bh8, wo2_p, kk), x.dtype, vma),
            compiler_params=_tc_params("parallel", "parallel", "parallel"),
            interpret=_interpret(),
        )(xs8, w8, b)
        # out[j, l] = out8[j%2, l%2, j//2, l//2]: interleave rows/cols by
        # phase, then crop the alignment padding (it lands past ho/wo).
        out = out8.transpose(0, 3, 1, 4, 2, 5).reshape(n, 2 * bh8, 2 * wo2_p, kk)
        return out[:, :ho, :wo, :]
    # Round the output tile up to (row-block, sublane-aligned W); the extra
    # rows/cols read zero padding and are cropped after the call. Cheap:
    # <= _W_ALIGN-1 wasted columns, <= row_block-1 wasted rows.
    bh = min(row_block, ho)
    nbh = -(-ho // bh)
    ho_p = nbh * bh
    wo_p = -(-wo // _W_ALIGN) * _W_ALIGN
    hs, ws = ho_p + fq - 1, wo_p + fq - 1  # s2d rows/cols the kernel reads
    xs = _space_to_depth(x, s, hs, ws)
    ws2d = _weights_to_depth(w, s, fq)
    cs = s * s * c

    if variant == "fused":
        # im2col variant: XLA materializes the tap-concatenated input
        # host-side (HBM cost ~fq^2 x input, still << compute at these
        # sizes) and the kernel is ONE (bh*wo_p, fq^2*cs) x (fq^2*cs, K)
        # MXU matmul per row block — 3-9x better array fill than the
        # tap-loop on conv1. Accumulation: one reduction over the whole
        # contraction (deterministic, but a DIFFERENT fixed order than the
        # tap-loop variant — pick one variant per process; tests hold
        # within-variant bitwise equality).
        xcol = jnp.concatenate(
            [
                xs[:, qh : qh + ho_p, qw : qw + wo_p, :]
                for qh in range(fq)
                for qw in range(fq)
            ],
            axis=-1,
        )  # (N, ho_p, wo_p, fq^2*cs)
        operands = (xcol, ws2d.reshape(fq * fq * cs, w.shape[-1]), b)
        kernel = functools.partial(_conv_fused_kernel, bh=bh, wo_p=wo_p, relu=relu)
        in_specs = [
            _vmem_spec((1, bh, wo_p, fq * fq * cs), lambda i, j: (i, j, 0, 0)),
            _vmem_spec(),
            _vmem_spec(),
        ]
    elif variant == "pairs" and fq >= 2:
        # Host-side shifted concat: xpair[..., j, :] carries column j's AND
        # j+1's channel blocks, so each kernel matmul contracts over 2*cs
        # (conv1: 96/128 MXU rows vs taps' 48/128) at 2x input HBM traffic.
        xpair = jnp.concatenate([xs[:, :, :-1, :], xs[:, :, 1:, :]], axis=-1)
        m = fq // 2
        wpair = jnp.concatenate(
            [ws2d[:, 0 : 2 * m : 2], ws2d[:, 1 : 2 * m : 2]], axis=2
        )  # (fq, m, 2*cs, K)
        if fq % 2:
            wlast = ws2d[:, fq - 1]  # (fq, cs, K): the odd leftover tap
            operands = (xpair, xs, wpair, wlast, b)
            kernel = functools.partial(
                _conv_pairs_kernel, fq=fq, bh=bh, wo_p=wo_p, relu=relu
            )
            in_specs = [
                _vmem_spec((1, hs, ws - 1, 2 * cs), lambda i, j: (i, 0, 0, 0)),
                _vmem_spec((1, hs, ws, cs), lambda i, j: (i, 0, 0, 0)),
                _vmem_spec(),
                _vmem_spec(),
                _vmem_spec(),
            ]
        else:
            # Even fq: pairs cover all taps — xs/wlast are not operands
            # (dead VMEM residency + HBM traffic otherwise; round-4 advisor).
            operands = (xpair, wpair, b)
            kernel = functools.partial(
                _conv_pairs_even_kernel, fq=fq, bh=bh, wo_p=wo_p, relu=relu
            )
            in_specs = [
                _vmem_spec((1, hs, ws - 1, 2 * cs), lambda i, j: (i, 0, 0, 0)),
                _vmem_spec(),
                _vmem_spec(),
            ]
    else:  # "taps"/"vcol" (and "pairs" at fq == 1, where there is nothing to pair)
        operands = (xs, ws2d, b)
        kern_fn = _conv_vcol_kernel if variant in ("vcol", "g8") else _conv_kernel
        kernel = functools.partial(kern_fn, fq=fq, bh=bh, wo_p=wo_p, relu=relu)
        kk = w.shape[-1]
        if hpool is not None:
            # Fused H-stage pool (see _conv_epilogue): caller contract, not
            # a silent fallback — the model builder gates on these.
            if bh != ho:
                raise ValueError(
                    f"hpool fusion needs the whole image per program "
                    f"(row_block {row_block} < ho {ho})"
                )
            if k_block:
                raise ValueError(
                    "hpool fusion does not compose with k_block (the fused "
                    "path has no K grid dim); unset one of them"
                )
            pwin, pstr = hpool
            hp_o = (ho - pwin) // pstr + 1
            kernel = functools.partial(
                kern_fn, fq=fq, bh=bh, wo_p=wo_p, relu=relu,
                hpool=(pwin, pstr, hp_o),
            )
            out = pl.pallas_call(
                kernel,
                grid=(n, 1),
                in_specs=[
                    _vmem_spec((1, hs, ws, cs), lambda i, j: (i, 0, 0, 0)),
                    _vmem_spec(),
                    _vmem_spec(),
                ],
                out_specs=_vmem_spec((1, hp_o, wo_p, kk), lambda i, j: (i, j, 0, 0)),
                out_shape=vma_struct((n, hp_o, wo_p, kk), x.dtype, vma),
                compiler_params=_tc_params("parallel", "parallel"),
                interpret=_interpret(),
            )(*operands)
            return out[:, :, :wo, :]
        # Mosaic constraint (measured on the real v5e, 2026-07-31): every
        # blocked operand's minor dim is k_block, and the lane tiling is 128
        # — a non-multiple (the env's 64 setting) cannot lower on chip
        # ("block shape is a multiple of the tiling size"). Interpret mode
        # has no tiling, so CI keeps exercising 64; on hardware the request
        # is REFUSED rather than silently dropped (same raise-not-fallback
        # policy as hpool): an A/B row labeled kb=64 measuring kb=0 is
        # mislabeled perf evidence (ADVICE round-5 item 1).
        k_block_ok = k_block % 128 == 0 or _interpret()
        if k_block and not k_block_ok:
            raise ValueError(
                f"k_block={k_block} cannot lower on {jax.default_backend()}: "
                "the lane tiling is 128, so k_block must be a multiple of 128 "
                "on hardware (interpret mode has no tiling); unset "
                "TPU_FRAMEWORK_KBLOCK or use 128"
            )
        if k_block and not (kk % k_block == 0 and kk > k_block):
            # Geometry fallback (e.g. conv1's K=96 under kb=128): legitimate
            # per-layer degradation, but it must be VISIBLE — a one-time
            # warning per (k_block, K) so A/B logs can label this layer kb=0.
            _warn_k_block_dropped(k_block, kk)
        if k_block and kk % k_block == 0 and kk > k_block:
            # Third grid dim over K blocks (the round-4 verdict's named
            # next lever): each program owns k_block output channels, so
            # the VMEM-resident weight slice and fp32 accumulator shrink
            # kk/k_block-fold at the cost of re-reading the input window
            # per K block (the x spec ignores the k index, so Mosaic can
            # keep the window resident across the inner dim). Outputs are
            # disjoint and per-element accumulation order is untouched —
            # bitwise identical to unblocked, like the rowblock lever.
            nk = kk // k_block
            # Bias rides as (1, K) with a (1, k_block) block: a rank-1
            # (k_block,) spec is illegal on chip — rank-1 tiling is
            # 256 for bf16 (128 lanes x 2 packing), so a 128 block was
            # rejected by the lowering. Rank-2 puts k_block on the lane
            # dim where 128 is exactly the tile. The epilogue's
            # broadcast add is rank-agnostic.
            operands = (xs, ws2d, b.reshape(1, kk))
            in_specs = [
                _vmem_spec((1, hs, ws, cs), lambda i, j, k: (i, 0, 0, 0)),
                _vmem_spec((fq, fq, cs, k_block), lambda i, j, k: (0, 0, 0, k)),
                _vmem_spec((1, k_block), lambda i, j, k: (0, k)),
            ]
            out = pl.pallas_call(
                kernel,
                grid=(n, nbh, nk),
                in_specs=in_specs,
                out_specs=_vmem_spec(
                    (1, bh, wo_p, k_block), lambda i, j, k: (i, j, 0, k)
                ),
                out_shape=vma_struct((n, ho_p, wo_p, kk), x.dtype, vma),
                compiler_params=_tc_params("parallel", "parallel", "parallel"),
                interpret=_interpret(),
            )(*operands)
            if ho_p != ho or wo_p != wo:
                out = out[:, :ho, :wo, :]
            return out
        in_specs = [
            _vmem_spec((1, hs, ws, cs), lambda i, j: (i, 0, 0, 0)),
            _vmem_spec(),
            _vmem_spec(),
        ]
    out = pl.pallas_call(
        kernel,
        grid=(n, nbh),
        in_specs=in_specs,
        out_specs=_vmem_spec((1, bh, wo_p, w.shape[-1]), lambda i, j: (i, j, 0, 0)),
        out_shape=vma_struct((n, ho_p, wo_p, w.shape[-1]), x.dtype, vma),
        compiler_params=_tc_params("parallel", "parallel"),
        interpret=_interpret(),
    )(*operands)
    if ho_p != ho or wo_p != wo:
        out = out[:, :ho, :wo, :]
    return out


def conv2d_pallas_hvalid(
    x, w, b, *, stride: int, padding_w: int, vma=None,
    variant: str | None = None, row_block: int | None = None,
    k_block: int | None = None,
):
    """Sharded-tier entry: VALID on H (halo-provided), padded on W, fused ReLU
    is NOT applied here (the sharded pipeline masks then relus)."""
    return conv2d_pallas(
        x, w, b, stride=stride, padding=0, padding_w=padding_w, vma=vma,
        variant=variant, row_block=row_block, k_block=k_block,
    )


def _pool_kernel(x_ref, o_ref, *, window: int, stride: int, ho: int, wo: int):
    """x_ref: (s*s, 1, hp, wp, C) stacked stride-phases; max over window taps.

    Tap (fy, fx) lives in phase (fy % s)*s + (fx % s) at spatial offset
    (fy//s, fx//s) — every in-kernel slice is unit-stride (Mosaic forbids
    strided vector slices; the phase split is done host-side by XLA).
    """
    s = stride
    c = x_ref.shape[-1]
    out = None
    for fy in range(window):
        for fx in range(window):
            ph = (fy % s) * s + (fx % s)
            qh, qw = fy // s, fx // s
            win = lax.slice(
                x_ref[ph, 0], (qh, qw, 0), (qh + ho, qw + wo, c)
            )
            out = win if out is None else jnp.maximum(out, win)
    o_ref[0] = out


def _pool_phases(x: jax.Array, s: int, hp: int, wp: int) -> jax.Array:
    """(N,H,W,C) -> (s*s, N, hp, wp, C): stride-phase views, zero-padded.

    Padded rows/cols are never read: kernel taps stop at fy,fx < window.
    """
    n, h, w, c = x.shape
    phases = []
    for r in range(s):
        for p in range(s):
            v = x[:, r::s, p::s, :][:, :hp, :wp, :]  # crop phases longer than hp/wp
            phases.append(
                jnp.pad(v, ((0, 0), (0, hp - v.shape[1]), (0, wp - v.shape[2]), (0, 0)))
            )
    return jnp.stack(phases)


def _pool_variant() -> str:
    # sep2 is the measured default (scripts/pool_ab.py).
    return env_variant("TPU_FRAMEWORK_POOL", "sep2", ("sep2", "phases"))


def maxpool_pallas(
    x: jax.Array, *, window: int, stride: int, vma=None, variant: str | None = None
) -> jax.Array:
    """Window max — thin wrapper resolving the lowering variant (explicit
    arg wins; env var otherwise) before entering jit. ``vma``: see ops.vma."""
    vma = tuple(vma) if vma is not None else None
    if (variant if variant is not None else _pool_variant()) == "phases":
        return _maxpool_phases(x, window=window, stride=stride, vma=vma)
    return _maxpool_sep2(x, window=window, stride=stride, vma=vma)


@functools.partial(jax.jit, static_argnames=("window", "stride", "vma"))
def _maxpool_phases(x: jax.Array, *, window: int, stride: int, vma=None) -> jax.Array:
    n, h, wdt, c = x.shape
    s = stride
    ho = (h - window) // s + 1
    wo = (wdt - window) // s + 1
    qmax = (window - 1) // s
    hp, wp = ho + qmax, wo + qmax
    xph = _pool_phases(x, s, hp, wp)
    kernel = functools.partial(_pool_kernel, window=window, stride=s, ho=ho, wo=wo)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[_vmem_spec((s * s, 1, hp, wp, c), lambda i: (0, i, 0, 0, 0))],
        out_specs=_vmem_spec((1, ho, wo, c), lambda i: (i, 0, 0, 0)),
        out_shape=vma_struct((n, ho, wo, c), x.dtype, vma),
        compiler_params=_tc_params("parallel"),
        interpret=_interpret(),
    )(xph)


def _axis_pool_kernel(x_ref, o_ref, *, window: int, stride: int, ho: int):
    """Pool along H only. x_ref: (1, hq, s, W, C) — dims 1-2 are the
    view-split H (half-row, phase), both untiled; W and C carry the 8x128
    tiling unchanged. Output row i = max over taps fy of input row
    i*s + fy = view element (i + fy//s, fy%s). Max is associative and
    exact in floating point, so the two-stage split cannot change results.
    """
    out = None
    for fy in range(window):
        q, p = fy // stride, fy % stride
        win = x_ref[0, q : q + ho, p]
        out = win if out is None else jnp.maximum(out, win)
    o_ref[0] = out


def _pool_rows(x: jax.Array, *, window: int, stride: int, vma=None) -> jax.Array:
    """Max-pool the H axis via the view-reshape phase split. x: (N,H,W,C).

    The reshape H -> (hq, s) is contiguity-preserving — XLA emits no data
    movement — which is the whole advantage over the phase-stack path
    (whose s*s strided gathers cost more than the pool itself)."""
    n, h, w, c = x.shape
    s = stride
    ho = (h - window) // s + 1
    qmax = (window - 1) // s
    hq = ho + qmax  # H view-rows the kernel reads
    if h < hq * s:
        x = jnp.pad(x, ((0, 0), (0, hq * s - h), (0, 0), (0, 0)))
    xv = x[:, : hq * s].reshape(n, hq, s, w, c)
    kernel = functools.partial(_axis_pool_kernel, window=window, stride=s, ho=ho)
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[_vmem_spec((1, hq, s, w, c), lambda i: (i, 0, 0, 0, 0))],
        out_specs=_vmem_spec((1, ho, w, c), lambda i: (i, 0, 0, 0)),
        out_shape=vma_struct((n, ho, w, c), x.dtype, vma),
        compiler_params=_tc_params("parallel"),
        interpret=_interpret(),
    )(xv)


@functools.partial(jax.jit, static_argnames=("window", "stride", "vma"))
def _maxpool_sep2(x: jax.Array, *, window: int, stride: int, vma=None) -> jax.Array:
    """Separable two-stage pool: rows, transpose, rows again, transpose."""
    y = _pool_rows(x, window=window, stride=stride, vma=vma)  # (N, ho, W, C)
    yt = jnp.swapaxes(y, 1, 2)                           # (N, W, ho, C)
    z = _pool_rows(yt, window=window, stride=stride, vma=vma)  # (N, wo, ho, C)
    return jnp.swapaxes(z, 1, 2)                         # (N, ho, wo, C)


@functools.partial(jax.jit, static_argnames=("window", "stride", "vma"))
def maxpool_pallas_w(x: jax.Array, *, window: int, stride: int, vma=None) -> jax.Array:
    """W-axis-only pool stage — the second half of the separable pool, for
    outputs whose H stage was already fused into the conv epilogue
    (``conv2d_pallas(..., hpool=...)``). Same kernel, same numerics as the
    sep2 W stage, so fused-conv + this == conv + maxpool_pallas bitwise."""
    yt = jnp.swapaxes(x, 1, 2)                           # (N, W, hpooled, C)
    z = _pool_rows(yt, window=window, stride=stride, vma=vma)
    return jnp.swapaxes(z, 1, 2)


def _lrn_kernel(x_ref, o_ref, *, size: int, alpha: float, beta: float, k: float, alpha_over_size: bool):
    """Cross-channel LRN; the channel-window sum of squares is a banded
    0/1-matrix matmul on the MXU — no lane-dimension slicing, and the band
    edges implement the reference's window truncation exactly."""
    # All math in fp32 regardless of the activation dtype: the band matmul
    # must be dtype-homogeneous (Mosaic rejects a bf16 lhs against the f32
    # band — "Bad lhs type"), and the scale/power path is precision-critical.
    x = x_ref[0].astype(jnp.float32)  # (H, W, C)
    h, w, c = x.shape
    half = size // 2
    ci = lax.broadcasted_iota(jnp.int32, (c, c), 0)
    cj = lax.broadcasted_iota(jnp.int32, (c, c), 1)
    band = (jnp.abs(ci - cj) <= half).astype(jnp.float32)
    sq = (x * x).reshape(h * w, c)
    ssum = jnp.dot(
        sq, band, preferred_element_type=jnp.float32, precision=lax.Precision.HIGHEST
    ).reshape(h, w, c)
    a = alpha / size if alpha_over_size else alpha
    scale = k + a * ssum
    o_ref[0] = (x / scale**beta).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("size", "alpha", "beta", "k", "alpha_over_size"))
def lrn_pallas(
    x: jax.Array,
    *,
    size: int,
    alpha: float,
    beta: float,
    k: float,
    alpha_over_size: bool = False,
) -> jax.Array:
    n, h, wdt, c = x.shape
    kernel = functools.partial(
        _lrn_kernel, size=size, alpha=alpha, beta=beta, k=k, alpha_over_size=alpha_over_size
    )
    return pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[_vmem_spec((1, h, wdt, c), lambda i: (i, 0, 0, 0))],
        out_specs=_vmem_spec((1, h, wdt, c), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        compiler_params=_tc_params("parallel"),
        interpret=_interpret(),
    )(x)


def relu_pallas(x: jax.Array) -> jax.Array:
    """Standalone elementwise ReLU kernel (reference: reluKernel,
    layers_cuda.cu:66-75). The conv kernel fuses ReLU, so this exists for
    parity/benchmarking of the unfused launch sequence.

    Gridded over the leading axis for ndim >= 3: a gridless whole-array
    VMEM mapping would exceed the ~16 MB scoped-VMEM limit for any real
    batch of activations (e.g. conv1 at b >= 32). ndim <= 2 stays
    gridless — a (1, M) block over a 2-D array would put 1 in the
    sublane dim, which Mosaic's last-two-dims tiling rule rejects (the
    same constraint as the flash LSE layout), and 2-D inputs here are
    small parity-test vectors."""

    def kernel(x_ref, o_ref):
        o_ref[:] = jnp.maximum(x_ref[:], 0.0).astype(o_ref.dtype)

    if x.ndim >= 3:
        n = x.shape[0]
        rest = x.shape[1:]
        block = (1, *rest)
        idx = lambda i: (i,) + (0,) * len(rest)  # noqa: E731
        return pl.pallas_call(
            kernel,
            grid=(n,),
            in_specs=[_vmem_spec(block, idx)],
            out_specs=_vmem_spec(block, idx),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            compiler_params=_tc_params("parallel"),
            interpret=_interpret(),
        )(x)
    return pl.pallas_call(
        kernel,
        in_specs=[_vmem_spec()],
        out_specs=_vmem_spec(),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=_interpret(),
    )(x)

"""Reference op tier: the four layer ops in pure JAX/XLA.

Semantics match the reference's serial CPU layer library
(v1_serial/src/layers_serial.cpp:37-175): direct convolution with symmetric
zero padding, in-place ReLU, VALID max-pool, and cross-channel LRN with
edge-truncated windows. The reference computes in fp32 with HWC-interleaved
activations (idx3D, layers_serial.cpp:15-17) and K,C,F,F weights
(layers_serial.cpp:70); here activations are batched NHWC (the TPU-friendly
layout — C maps to VPU lanes) and weights are HWIO ``(F, F, C, K)``.
Converters to/from the reference layout live in ``models.init``.

Everything here is jit-friendly: static shapes, no Python control flow on
traced values, so XLA can fuse bias+ReLU into the conv and tile the matmuls
onto the MXU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def conv2d(
    x: jax.Array,
    w: jax.Array,
    b: jax.Array,
    *,
    stride: int,
    padding: int,
    precision: lax.PrecisionLike = lax.Precision.HIGHEST,
    preferred_element_type=None,
) -> jax.Array:
    """Direct 2-D convolution (cross-correlation) with bias.

    Args:
      x: activations ``(N, H, W, C)``.
      w: filters ``(F, F, C, K)`` (HWIO).
      b: biases ``(K,)``.
      stride: spatial stride (same for H and W).
      padding: symmetric zero padding (same for H and W).

    Reference parity: ``serialConvLayer`` (v1_serial/src/layers_serial.cpp:37-81)
    — 7 nested loops, zero padding, bias added per output channel. The
    reference computes correlation (no filter flip), as does lax.conv.

    ``precision`` defaults to HIGHEST (true fp32 MACs) so this tier matches
    the reference's fp32 numerics on TPU, where the MXU's default precision
    would otherwise compute in bf16; perf-oriented configs pass
    ``lax.Precision.DEFAULT`` explicitly.

    ``preferred_element_type`` pins the accumulation dtype — the precision
    subsystem's mixed-dtype paths (bf16/int8w policies, precision.gate)
    thread fp32 here so the accumulation width is stated, never inferred
    (the staticcheck ``implicit-upcast`` contract).
    """
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(stride, stride),
        padding=[(padding, padding), (padding, padding)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        precision=precision,
        preferred_element_type=preferred_element_type,
    )
    return out + b.astype(out.dtype)


def relu(x: jax.Array) -> jax.Array:
    """Elementwise max(0, x).

    Reference parity: ``serialReluLayer`` (v1_serial/src/layers_serial.cpp:85-90).
    """
    return jnp.maximum(x, jnp.zeros((), dtype=x.dtype))


def maxpool(x: jax.Array, *, window: int, stride: int) -> jax.Array:
    """VALID max pooling over ``window``×``window`` with the given stride.

    Reference parity: ``serialMaxPoolLayer`` (v1_serial/src/layers_serial.cpp:94-129)
    — no padding, window max.
    """
    # Python-scalar init (not jnp.array): under jit the latter becomes a
    # tracer, defeating JAX's max-monoid recognition and losing autodiff.
    return lax.reduce_window(
        x,
        -float("inf"),
        lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def lrn(
    x: jax.Array,
    *,
    size: int,
    alpha: float,
    beta: float,
    k: float,
    alpha_over_size: bool = False,
) -> jax.Array:
    """Cross-channel local response normalization.

    ``out[c] = x[c] / (k + a * sum_{j in win(c)} x[j]^2) ** beta`` with
    ``a = alpha/size`` when ``alpha_over_size`` else ``a = alpha``, and
    ``win(c) = [max(0, c-size//2), min(C-1, c+size//2)]`` — the window is
    truncated at channel edges without renormalizing by the actual count.

    The reference disagrees with itself on ``a``: its CPU layers use
    ``alpha*sumSq/N`` (v1_serial/src/layers_serial.cpp:168,
    2.2_scatter_halo/src/layers_mpi.cpp:81 → printed ``44.4152 42.4612
    40.6967...``) while its CUDA kernels use ``alpha*sum`` with no ``/N``
    (v3_cuda_only/src/layers_cuda.cu:139, v4_mpi_cuda/src/layers_mpi_cuda.cu:86
    → the headline golden ``29.2932 25.9153 23.3255...``). Both forms are
    supported; the default is the CUDA form, which every deterministic
    V3/V4 log in the reference's regression corpus was produced with. The
    CPU-vs-CUDA divide-vs-``powf(scale,-beta)`` discrepancy is standardized
    here on the divide form across all tiers.
    """
    half = size // 2
    sq = x * x
    ssum = lax.reduce_window(
        sq,
        0.0,
        lax.add,
        window_dimensions=(1, 1, 1, size),
        window_strides=(1, 1, 1, 1),
        padding=[(0, 0), (0, 0), (0, 0), (half, half)],
    )
    a = alpha / size if alpha_over_size else alpha
    scale = k + a * ssum
    return x / scale**beta

"""Parameter and input initialization + reference-layout converters.

Two init modes, matching the reference:

- deterministic: input = 1.0, weights = 0.01, biases = 0.0 — the mode V2.1,
  V2.2, V3 and V4 all use so their outputs are cross-comparable
  (2.2_scatter_halo/src/main.cpp:37-47, v3_cuda_only/src/main_cuda.cpp:16-27,
  v4_mpi_cuda/src/main_mpi_cuda.cpp:29-33).
- random: uniform [0,1) data/weights, bias = 0.1 — V1's mode
  (v1_serial/src/alexnet_serial.cpp:39-57), except the reference seeds with
  ``srand(time(0))`` (v1_serial/src/main.cpp:12) making V1 non-comparable
  across runs; here randomness is always explicitly keyed.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from .alexnet import BLOCKS12, Blocks12Config

Params = Dict[str, Dict[str, Any]]


def _conv_shapes(cfg: Blocks12Config):
    c1, c2 = cfg.conv1, cfg.conv2
    w1 = (c1.filter_size, c1.filter_size, cfg.in_channels, c1.out_channels)
    w2 = (c2.filter_size, c2.filter_size, c1.out_channels, c2.out_channels)
    return w1, (c1.out_channels,), w2, (c2.out_channels,)


def init_params_deterministic(cfg: Blocks12Config = BLOCKS12, dtype=jnp.float32) -> Params:
    """weights = 0.01, biases = 0.0 (cross-version comparison oracle init)."""
    w1s, b1s, w2s, b2s = _conv_shapes(cfg)
    return {
        "conv1": {"w": jnp.full(w1s, 0.01, dtype), "b": jnp.zeros(b1s, dtype)},
        "conv2": {"w": jnp.full(w2s, 0.01, dtype), "b": jnp.zeros(b2s, dtype)},
    }


def init_params_random(key: jax.Array, cfg: Blocks12Config = BLOCKS12, dtype=jnp.float32) -> Params:
    """Uniform [0,1) weights, bias 0.1 — V1 semantics but reproducibly keyed."""
    k1, k2 = jax.random.split(key)
    w1s, b1s, w2s, b2s = _conv_shapes(cfg)
    return {
        "conv1": {
            "w": jax.random.uniform(k1, w1s, dtype),
            "b": jnp.full(b1s, 0.1, dtype),
        },
        "conv2": {
            "w": jax.random.uniform(k2, w2s, dtype),
            "b": jnp.full(b2s, 0.1, dtype),
        },
    }


def deterministic_input(batch: int = 1, cfg: Blocks12Config = BLOCKS12, dtype=jnp.float32) -> jax.Array:
    """All-ones NHWC input (2.2_scatter_halo/src/main.cpp:37)."""
    return jnp.ones((batch, cfg.in_height, cfg.in_width, cfg.in_channels), dtype)


def random_input(key: jax.Array, batch: int = 1, cfg: Blocks12Config = BLOCKS12, dtype=jnp.float32) -> jax.Array:
    """Uniform [0,1) NHWC input (v1_serial/src/alexnet_serial.cpp:39-43, keyed)."""
    return jax.random.uniform(key, (batch, cfg.in_height, cfg.in_width, cfg.in_channels), dtype)


def to_reference_layout(w: jax.Array) -> np.ndarray:
    """HWIO ``(F,F,C,K)`` → the reference's flat K,C,F,F weight layout.

    ``w_idx = ((k*C + c)*F + fy)*F + fx`` (v1_serial/src/layers_serial.cpp:70,
    v3_cuda_only/src/layers_cuda.cu:41).
    """
    return np.asarray(w).transpose(3, 2, 0, 1).reshape(-1)


def from_reference_layout(flat, f: int, c: int, k: int) -> jnp.ndarray:
    """Flat K,C,F,F reference weights → HWIO ``(F,F,C,K)``."""
    arr = np.asarray(flat, dtype=np.float32).reshape(k, c, f, f)
    return jnp.asarray(arr.transpose(2, 3, 1, 0))

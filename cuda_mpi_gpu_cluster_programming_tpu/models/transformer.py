"""Decoder-only transformer LM: the long-context model family.

The reference has no attention model (SURVEY §2.2); this family is the
framework's demonstration that its long-context machinery composes into a
trainable model: pre-norm decoder blocks whose attention op is pluggable —

- ``impl="reference"``: the O(L^2) oracle (``ops.attention``),
- ``impl="flash"``: the fused Pallas kernel (``ops.flash_attention``),
- ``impl="ring"``/``"ulysses"``: sequence-parallel over a mesh axis
  (``parallel.sequence_parallel``) — context length scales with ring size.

Design is TPU-first: pure-functional params pytree, static shapes, RMSNorm,
learned positional embeddings (static slice — no data-dependent control
flow), bf16-safe (norms and softmax statistics in fp32), weight-tied LM
head so the embedding matmul rides the MXU twice.

The FFN is pluggable too: dense (default) or a Switch-style top-1
mixture-of-experts (``n_experts > 0``) whose capacity-limited dense
dispatch/combine einsums are the EP tier — ``parallel.expert`` shards the
expert axis over an "ep" mesh axis. The decoder block is exposed as
``decoder_block`` so ``parallel.pipeline`` can stage the layer stack over
a "pp" axis without duplicating any model code.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import NEG_INF, attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256  # byte-level LM by default
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 1024
    attn_impl: str = "reference"  # reference | flash | ring | ulysses
    sp_shards: int = 1  # ring/ulysses mesh size
    # sp x tp composition: name of the mesh axis the attention heads are
    # tensor-sharded over (shard_lm_params_tp's axis); ring/ulysses then
    # name it in their shard_map specs so CP and TP compose in one step.
    sp_head_axis: Optional[str] = None
    # Within-shard engine for ring/ulysses: "einsum" (XLA score blocks) or
    # "flash" (Pallas kernel). BOTH compositions train: ulysses+flash via
    # the whole-sequence VJP, ring+flash via the joint (out, lse) VJP
    # (round 4 — the lse cotangent shifts the FA-2 backward's delta term,
    # ops/flash_attention._flash_backward), so every sp x engine cell is
    # differentiable with the flash cells at O(L) memory per shard.
    attn_engine: str = "einsum"

    def __post_init__(self):
        if self.attn_engine not in ("einsum", "flash"):
            raise ValueError(f"attn_engine must be einsum|flash, got {self.attn_engine!r}")
    # Mixture-of-experts FFN (0 = dense). Top-1 (Switch) routing with a
    # capacity limit; the expert axis is what EP shards (see moe_ffn).
    n_experts: int = 0
    capacity_factor: float = 1.25
    # Rematerialization: wrap each decoder block in jax.checkpoint so the
    # backward recomputes block activations instead of storing them —
    # activation memory drops from O(n_layers * B * L * D) to O(B * L * D)
    # at ~1 extra forward of FLOPs. The long-context memory lever that
    # composes with ring/ulysses (which shard L) and flash (which keeps
    # attention O(L)): remat removes the remaining per-layer residuals.
    remat: bool = False

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


TINY_LM = TransformerConfig()


def init_transformer(key: jax.Array, cfg: TransformerConfig = TINY_LM, dtype=jnp.float32) -> Params:
    """Scaled-normal init (1/sqrt(fan_in); output projections /sqrt(2*L))."""
    n_mats = 4 * cfg.n_layers + 1
    keys = iter(jax.random.split(key, n_mats + 1))

    def dense(k, fan_in, shape, scale=1.0):
        return (jax.random.normal(k, shape, dtype) * scale / math.sqrt(fan_in))

    params: Params = {
        "embed": dense(next(keys), 1, (cfg.vocab, cfg.d_model)),
        "pos": dense(next(keys), 1, (cfg.max_len, cfg.d_model)) * 0.02,
        "final_norm": {"g": jnp.ones((cfg.d_model,), dtype)},
        "layers": [],
    }
    resid_scale = 1.0 / math.sqrt(2 * cfg.n_layers)
    for _ in range(cfg.n_layers):
        layer = {
            "attn_norm": {"g": jnp.ones((cfg.d_model,), dtype)},
            # (D, 3, D) rather than packed (D, 3D): the explicit q/k/v axis
            # keeps tensor-parallel column shards aligned with the split —
            # a packed layout sharded in contiguous 3D/tp chunks crosses
            # the q/k/v boundaries whenever tp is not a multiple of 3,
            # forcing GSPMD to reshard inside attention.
            "wqkv": dense(next(keys), cfg.d_model, (cfg.d_model, 3, cfg.d_model)),
            "wo": dense(next(keys), cfg.d_model, (cfg.d_model, cfg.d_model), resid_scale),
            "mlp_norm": {"g": jnp.ones((cfg.d_model,), dtype)},
        }
        if cfg.n_experts:
            e = cfg.n_experts
            kr, ku, kd = jax.random.split(next(keys), 3)
            layer["router"] = dense(kr, cfg.d_model, (cfg.d_model, e))
            layer["w_up"] = dense(ku, cfg.d_model, (e, cfg.d_model, cfg.d_ff))
            layer["w_down"] = dense(kd, cfg.d_ff, (e, cfg.d_ff, cfg.d_model), resid_scale)
        else:
            layer["w_up"] = dense(next(keys), cfg.d_model, (cfg.d_model, cfg.d_ff))
            layer["w_down"] = dense(next(keys), cfg.d_ff, (cfg.d_ff, cfg.d_model), resid_scale)
        params["layers"].append(layer)
    return params


def rmsnorm(x: jax.Array, g: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMS layer norm, statistics in fp32 (bf16-safe)."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * g


def _attend(q, k, v, cfg: TransformerConfig, mesh=None):
    if cfg.attn_impl == "reference":
        return attention(q, k, v, causal=True)
    if cfg.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    if cfg.attn_impl == "ring":
        from ..parallel.sequence_parallel import ring_attention

        return ring_attention(
            q, k, v, n_shards=cfg.sp_shards, causal=True, mesh=mesh,
            head_axis=cfg.sp_head_axis, engine=cfg.attn_engine,
        )
    if cfg.attn_impl == "ulysses":
        from ..parallel.sequence_parallel import ulysses_attention

        return ulysses_attention(
            q, k, v, n_shards=cfg.sp_shards, causal=True, mesh=mesh,
            head_axis=cfg.sp_head_axis, engine=cfg.attn_engine,
        )
    raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")


def moe_ffn(
    layer: Params, h: jax.Array, cfg: TransformerConfig, return_aux: bool = False
):
    """Top-1 (Switch) mixture-of-experts FFN with a capacity limit.

    The EP tier: expert-stacked weights (E, D, F)/(E, F, D) carry the
    expert axis that an "ep" mesh axis shards (see parallel/expert.py for
    the sharding wrapper). Dispatch/combine are dense one-hot einsums —
    static shapes, no gather/scatter — the GShard/Switch formulation GSPMD
    partitions into all-to-alls on its own. Tokens routed past an expert's
    capacity are dropped (contribute nothing; the residual connection
    carries them unchanged) — standard Switch behavior, which also bounds
    the damage of load imbalance.

    ``return_aux`` additionally returns the Switch load-balancing loss
    ``E * sum_e f_e * P_e`` (f_e = fraction of tokens routed to expert e,
    P_e = mean router probability of e) — 1.0 at perfect balance, up to E
    at full collapse; ``lm_loss(aux_coef=...)`` adds it to the objective
    so the router cannot collapse onto one expert.
    """
    b, l, d = h.shape
    e = cfg.n_experts
    t = b * l
    cap = max(1, int(cfg.capacity_factor * t / e))
    hf = h.reshape(t, d)
    # Routing bookkeeping entirely in fp32/int32 — the module's bf16-safety
    # rule: a bf16 cumsum is inexact past 256 tokens, which would corrupt
    # queue positions (two tokens sharing a capacity slot get silently
    # blended). Only the final dispatch/combine einsums run in h.dtype.
    router_logits = (hf.astype(jnp.float32)) @ layer["router"].astype(jnp.float32)
    gates = jax.nn.softmax(router_logits, axis=-1)  # (T, E) fp32
    idx = jnp.argmax(gates, axis=-1)  # (T,) top-1 expert
    gate = jnp.take_along_axis(gates, idx[:, None], axis=-1)[:, 0]  # (T,) fp32
    onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (T, E)
    # Position of each token in its expert's queue; beyond capacity -> drop.
    pos = jnp.sum((jnp.cumsum(onehot, axis=0) - onehot) * onehot, axis=-1).astype(jnp.int32)
    keep = (pos < cap).astype(jnp.float32)
    # dispatch (T, E, C): one-hot over (expert, slot), zero for dropped.
    slot = jax.nn.one_hot(pos, cap, dtype=jnp.float32)  # (T, C)
    dispatch = (onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]).astype(h.dtype)
    xin = jnp.einsum("tec,td->ecd", dispatch, hf)  # (E, C, D)
    hidden = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xin, layer["w_up"]))
    out_e = jnp.einsum("ecf,efd->ecd", hidden, layer["w_down"])  # (E, C, D)
    combine = dispatch * gate[:, None, None].astype(h.dtype)
    out = jnp.einsum("tec,ecd->td", combine, out_e).reshape(b, l, d)
    if not return_aux:
        return out
    # Switch aux loss (fp32): differentiable through P_e (gates); f_e uses
    # the pre-capacity argmax assignment, per the Switch formulation.
    f_e = jnp.mean(onehot, axis=0)  # (E,) fraction routed to each expert
    p_e = jnp.mean(gates, axis=0)  # (E,) mean router probability
    aux = e * jnp.sum(f_e * p_e)
    return out, aux


def decoder_block(
    layer: Params,
    x: jax.Array,
    *,
    cfg: TransformerConfig,
    mesh=None,
    return_aux: bool = False,
):
    """One pre-norm decoder block: attention + (dense | MoE) FFN.

    The shared unit of every execution shape: the plain stacked forward
    (``forward_lm``), and the pipeline-parallel stage scan
    (``parallel.pipeline``, which uses the single-output form — the MoE
    aux term is a training refinement, not part of the staged schedule).
    """
    b, l, _ = x.shape
    h = rmsnorm(x, layer["attn_norm"]["g"])
    qkv = jnp.einsum("bld,dse->blse", h, layer["wqkv"])  # (B, L, 3, D)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    shape = (b, l, cfg.n_heads, cfg.head_dim)
    out = _attend(q.reshape(shape), k.reshape(shape), v.reshape(shape), cfg, mesh)
    x = x + out.reshape(b, l, cfg.d_model) @ layer["wo"]
    h = rmsnorm(x, layer["mlp_norm"]["g"])
    aux = jnp.float32(0.0)
    if cfg.n_experts:
        ffn, aux = moe_ffn(layer, h, cfg, return_aux=True)
        x = x + ffn
    else:
        x = x + jax.nn.gelu(h @ layer["w_up"]) @ layer["w_down"]
    return (x, aux) if return_aux else x


def forward_lm(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig = TINY_LM,
    mesh=None,
    return_aux: bool = False,
):
    """tokens (B, L) int32 -> logits (B, L, vocab). Causal, weight-tied head.

    ``return_aux`` also returns the mean MoE load-balance loss over layers
    (0.0 for dense configs)."""
    l = tokens.shape[1]
    if l > cfg.max_len:
        raise ValueError(f"sequence length {l} exceeds max_len {cfg.max_len}")
    x = params["embed"][tokens] + params["pos"][:l][None]
    aux_total = jnp.float32(0.0)
    block = lambda lyr, h: decoder_block(lyr, h, cfg=cfg, mesh=mesh, return_aux=True)  # noqa: E731
    if cfg.remat:
        block = jax.checkpoint(block)
    for layer in params["layers"]:
        x, aux = block(layer, x)
        aux_total = aux_total + aux
    x = rmsnorm(x, params["final_norm"]["g"])
    logits = x @ params["embed"].T  # weight-tied LM head
    if return_aux:
        return logits, aux_total / max(1, cfg.n_layers)
    return logits


def lm_loss(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig = TINY_LM,
    mesh=None,
    aux_coef: float = 0.01,
) -> jax.Array:
    """Next-token cross-entropy (fp32), mean over (B, L-1).

    For MoE configs, adds ``aux_coef`` x the Switch load-balance loss
    (0.01, the Switch-Transformer default) so the router cannot collapse
    onto one expert; dense configs are unaffected."""
    logits, aux = forward_lm(params, tokens[:, :-1], cfg, mesh, return_aux=True)
    logits = logits.astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    loss = -jnp.mean(ll)
    if cfg.n_experts:
        loss = loss + aux_coef * aux
    return loss


def make_lm_train_step(
    cfg: TransformerConfig = TINY_LM,
    mesh=None,
    optimizer=None,
    lr: float = 1e-3,
    loss_fn=None,
    accum_steps: int = 1,
    compute_dtype=None,
):
    """(init_fn, step_fn) for LM training; any optax optimizer (default adam).

    With a mesh whose axes include "dp", the batch is expected sharded over
    it (GSPMD inserts the gradient all-reduce); ring/ulysses attention adds
    the "sp" sequence axis inside the forward itself. ``loss_fn(params,
    tokens)`` overrides the default ``lm_loss`` — the single step factory
    serves the plain, expert-parallel, and pipeline-parallel paths.

    ``accum_steps > 1``: gradient accumulation — the batch is split into
    that many microbatches and their gradients averaged inside ONE
    ``lax.scan`` before a single optimizer update. Mathematically
    identical to the full-batch step (equal microbatch sizes make
    mean-of-means the global mean) while activation memory drops to one
    microbatch's worth — the optimizer-step-preserving way to grow the
    effective batch past memory, composing with remat/FSDP/sp.

    ``compute_dtype=jnp.bfloat16``: mixed precision with fp32 MASTER
    weights — the forward/backward run with params cast to bf16 (matmuls
    hit the MXU natively; the cast's VJP returns fp32 cotangents), while
    the stored params and the optimizer update stay fp32, so tiny adam
    updates are never rounded away step over step. The loss itself is
    already computed in fp32 (``lm_loss`` upcasts logits).
    """
    import optax

    opt = optimizer if optimizer is not None else optax.adam(lr)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if loss_fn is None:
        loss_fn = lambda p, t: lm_loss(p, t, cfg, mesh)  # noqa: E731
    @jax.jit
    def step(params, opt_state, tokens):
        # Mixed precision: cast the param tree ONCE per optimizer step (a
        # cast inside the accumulation scan would re-run per microbatch)
        # and differentiate at the low-precision point — the cast's VJP is
        # the final grads.astype back to the fp32 masters.
        if compute_dtype is not None:
            gp = jax.tree.map(
                lambda a: a.astype(compute_dtype)
                if jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                params,
            )
        else:
            gp = params
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(gp, tokens)
        else:
            b = tokens.shape[0]
            if b % accum_steps:
                raise ValueError(
                    f"batch {b} not divisible by accum_steps {accum_steps}"
                )
            micro = tokens.reshape(accum_steps, b // accum_steps, *tokens.shape[1:])

            def acc(carry, mb):
                loss_sum, grad_sum = carry
                l_mb, g_mb = jax.value_and_grad(loss_fn)(gp, mb)
                # Accumulate at MASTER precision: bf16 + bf16 + ... loses
                # low bits exactly where accumulation is supposed to help.
                return (
                    loss_sum + l_mb,
                    jax.tree.map(lambda s, g: s + g.astype(s.dtype), grad_sum, g_mb),
                ), None

            zeros = jax.tree.map(jnp.zeros_like, params)
            (loss_sum, grad_sum), _ = jax.lax.scan(
                acc, (jnp.float32(0.0), zeros), micro
            )
            loss = loss_sum / accum_steps
            grads = jax.tree.map(lambda g: g / accum_steps, grad_sum)
        if compute_dtype is not None:
            grads = jax.tree.map(lambda g, p: g.astype(p.dtype), grads, params)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state, loss

    return opt.init, step


# ---------------------------------------------------------------------------
# Inference: KV-cache incremental decode + autoregressive generation
# ---------------------------------------------------------------------------


def init_kv_cache(cfg: TransformerConfig, batch: int, dtype=jnp.float32):
    """Per-layer (B, max_len, H, Dh) K/V buffers for incremental decode —
    static shapes (XLA-friendly), filled in place by dynamic_update_slice
    as positions arrive. O(max_len * D) per layer instead of recomputing
    the full prefix every token (O(L^2) -> O(L) per generated token)."""
    shape = (batch, cfg.max_len, cfg.n_heads, cfg.head_dim)
    return [
        {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
        for _ in range(cfg.n_layers)
    ]


def _moe_ffn_decode(layer: Params, h: jax.Array, cfg: TransformerConfig) -> jax.Array:
    """Capacity-∞ single-token Switch FFN for the decode path.

    The training-time capacity queue (``moe_ffn``'s ``pos < cap``) ranks
    tokens in flattened batch-major order over the FULL (B, L) set — an
    incremental decoder cannot know batch 0's future tokens before batch
    1's early ones, so exact drop parity is impossible one token at a
    time. Serving therefore routes every token to its argmax expert with
    NO capacity limit (capacity=∞) — identical math to ``moe_ffn``
    whenever training would not have dropped the token, which the
    teacher-forced parity test pins down with an undroppable capacity
    factor (tests/test_decode.py). Cost note: all E experts are computed
    for the single token and one selected (static shapes beat an E-way
    gather at serving's B x 1 sizes; E times a tiny FFN).
    """
    b, l, d = h.shape
    hf = h.reshape(b * l, d)
    router_logits = hf.astype(jnp.float32) @ layer["router"].astype(jnp.float32)
    gates = jax.nn.softmax(router_logits, axis=-1)  # (T, E) fp32
    idx = jnp.argmax(gates, axis=-1)
    gate = jnp.take_along_axis(gates, idx[:, None], axis=-1)[:, 0]
    onehot = jax.nn.one_hot(idx, cfg.n_experts, dtype=h.dtype)
    hidden = jax.nn.gelu(jnp.einsum("td,edf->tef", hf, layer["w_up"]))
    out_e = jnp.einsum("tef,efd->ted", hidden, layer["w_down"])
    sel = onehot * gate.astype(h.dtype)[:, None]  # (T, E): gate on the argmax slot
    return jnp.einsum("te,ted->td", sel, out_e).reshape(b, l, d)


def _decode_block(layer: Params, x: jax.Array, cache, pos, cfg: TransformerConfig):
    """One pre-norm decoder block for ONE token (B, 1, D) at ``pos``.

    Mirrors ``decoder_block`` exactly (same rmsnorm/residual structure,
    fp32 softmax statistics like ops.attention) but attends q against the
    cached K/V prefix instead of the full sequence — the positions > pos
    are masked, so the zero-initialized tail of the cache never
    contributes. MoE configs route capacity-∞ (see ``_moe_ffn_decode``).
    """
    b = x.shape[0]
    h = rmsnorm(x, layer["attn_norm"]["g"])
    qkv = jnp.einsum("bld,dse->blse", h, layer["wqkv"])
    shape = (b, 1, cfg.n_heads, cfg.head_dim)
    q = qkv[:, :, 0].reshape(shape)
    k = qkv[:, :, 1].reshape(shape)
    v = qkv[:, :, 2].reshape(shape)
    ck = jax.lax.dynamic_update_slice_in_dim(
        cache["k"], k.astype(cache["k"].dtype), pos, axis=1
    )
    cv = jax.lax.dynamic_update_slice_in_dim(
        cache["v"], v.astype(cache["v"].dtype), pos, axis=1
    )
    scale = 1.0 / jnp.sqrt(jnp.asarray(cfg.head_dim, jnp.float32))
    s = (
        jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), ck.astype(jnp.float32))
        * scale
    )
    mask = (jnp.arange(cfg.max_len) <= pos)[None, None, None, :]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, cv.astype(jnp.float32)).astype(x.dtype)
    x = x + out.reshape(b, 1, cfg.d_model) @ layer["wo"]
    h2 = rmsnorm(x, layer["mlp_norm"]["g"])
    if cfg.n_experts:
        x = x + _moe_ffn_decode(layer, h2, cfg)
    else:
        x = x + jax.nn.gelu(h2 @ layer["w_up"]) @ layer["w_down"]
    return x, {"k": ck, "v": cv}


def _decode_scan(params, prompt, cfg, steps, temperature, key, collect_logits=False):
    b, plen = prompt.shape
    total = plen + steps
    if total > cfg.max_len:
        raise ValueError(f"prompt + steps = {total} exceeds max_len {cfg.max_len}")
    caches = init_kv_cache(cfg, b, params["embed"].dtype)
    padded = jnp.pad(prompt, ((0, 0), (0, steps)))

    def step(carry, t):
        tok, caches, key = carry
        cur = jnp.where(t < plen, padded[:, t], tok)  # teacher-force prompt
        x = params["embed"][cur][:, None, :] + params["pos"][t][None, None, :]
        new_caches = []
        for layer, cache in zip(params["layers"], caches):
            x, c2 = _decode_block(layer, x, cache, t, cfg)
            new_caches.append(c2)
        x = rmsnorm(x, params["final_norm"]["g"])
        logits = (x[:, 0] @ params["embed"].T).astype(jnp.float32)
        # temperature is a static Python float: the greedy graph carries no
        # sampling ops or per-step key splits at all.
        if temperature > 0:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(sub, logits / temperature, axis=-1)
        else:
            nxt = jnp.argmax(logits, axis=-1)
        out = (cur, logits) if collect_logits else cur
        return (nxt.astype(jnp.int32), new_caches, key), out

    init = (jnp.zeros((b,), jnp.int32), caches, key)
    # The consumed token at t is the prompt for t < plen, then the samples —
    # so the transposed collection IS the full output sequence. Logits are
    # only stacked when requested: generation would otherwise materialize a
    # (total, B, vocab) fp32 array just to discard it.
    if collect_logits:
        # Logits at the LAST position are part of the parity contract with
        # forward_lm, and only iteration total-1's forward pass computes
        # them — all total iterations are needed here.
        _, (toks, logits) = jax.lax.scan(step, init, jnp.arange(total))
        return jnp.swapaxes(toks, 0, 1), jnp.swapaxes(logits, 0, 1)
    # Generation: iteration total-1 would run a full forward pass only to
    # sample a token nothing consumes (round-4 advisor) — scan total-1
    # steps and append the final carry (the sample for position total-1;
    # steps >= 1 guarantees that position is generated, not prompt).
    (last_tok, _, _), toks = jax.lax.scan(step, init, jnp.arange(total - 1))
    return jnp.concatenate(
        [jnp.swapaxes(toks, 0, 1), last_tok[:, None]], axis=1
    ), None


def decode_logits(
    params: Params, tokens: jax.Array, cfg: TransformerConfig = TINY_LM
) -> jax.Array:
    """Teacher-forced logits through the KV-cache decode path — must match
    ``forward_lm`` (the parity contract tests/test_decode.py enforces)."""
    _, logits = _decode_scan(
        params, tokens, cfg, 0, 0.0, jax.random.PRNGKey(0), collect_logits=True
    )
    return logits


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: TransformerConfig = TINY_LM,
    *,
    steps: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive generation. prompt (B, P) int32 -> (B, P + steps).

    ``temperature == 0``: greedy argmax; otherwise categorical sampling at
    the given temperature (``key`` required). One jitted lax.scan over
    time with per-layer KV caches — O(L) per token, static shapes.
    """
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if temperature > 0 and key is None:
        raise ValueError("temperature > 0 sampling needs an explicit key")
    seq, _ = _decode_scan(
        params, prompt, cfg, steps, temperature,
        key if key is not None else jax.random.PRNGKey(0),
    )
    return seq

"""Decoder-only transformer LM: the long-context model family.

The reference has no attention model (SURVEY §2.2); this family is the
framework's demonstration that its long-context machinery composes into a
trainable model: pre-norm decoder blocks whose attention op is pluggable —

- ``impl="reference"``: the O(L^2) oracle (``ops.attention``),
- ``impl="flash"``: the fused Pallas kernel (``ops.flash_attention``),
- ``impl="ring"``/``"ulysses"``: sequence-parallel over a mesh axis
  (``parallel.sequence_parallel``) — context length scales with ring size.

Design is TPU-first: pure-functional params pytree, static shapes, RMSNorm,
learned positional embeddings (static slice — no data-dependent control
flow), bf16-safe (norms and softmax statistics in fp32), weight-tied LM
head so the embedding matmul rides the MXU twice.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict

import jax
import jax.numpy as jnp

from ..ops.attention import attention

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256  # byte-level LM by default
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_len: int = 1024
    attn_impl: str = "reference"  # reference | flash | ring | ulysses
    sp_shards: int = 1  # ring/ulysses mesh size

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


TINY_LM = TransformerConfig()


def init_transformer(key: jax.Array, cfg: TransformerConfig = TINY_LM, dtype=jnp.float32) -> Params:
    """Scaled-normal init (1/sqrt(fan_in); output projections /sqrt(2*L))."""
    n_mats = 4 * cfg.n_layers + 1
    keys = iter(jax.random.split(key, n_mats + 1))

    def dense(k, fan_in, shape, scale=1.0):
        return (jax.random.normal(k, shape, dtype) * scale / math.sqrt(fan_in))

    params: Params = {
        "embed": dense(next(keys), 1, (cfg.vocab, cfg.d_model)),
        "pos": dense(next(keys), 1, (cfg.max_len, cfg.d_model)) * 0.02,
        "final_norm": {"g": jnp.ones((cfg.d_model,), dtype)},
        "layers": [],
    }
    resid_scale = 1.0 / math.sqrt(2 * cfg.n_layers)
    for _ in range(cfg.n_layers):
        params["layers"].append(
            {
                "attn_norm": {"g": jnp.ones((cfg.d_model,), dtype)},
                "wqkv": dense(next(keys), cfg.d_model, (cfg.d_model, 3 * cfg.d_model)),
                "wo": dense(next(keys), cfg.d_model, (cfg.d_model, cfg.d_model), resid_scale),
                "mlp_norm": {"g": jnp.ones((cfg.d_model,), dtype)},
                "w_up": dense(next(keys), cfg.d_model, (cfg.d_model, cfg.d_ff)),
                "w_down": dense(next(keys), cfg.d_ff, (cfg.d_ff, cfg.d_model), resid_scale),
            }
        )
    return params


def rmsnorm(x: jax.Array, g: jax.Array, eps: float = 1e-6) -> jax.Array:
    """RMS layer norm, statistics in fp32 (bf16-safe)."""
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * g


def _attend(q, k, v, cfg: TransformerConfig, mesh=None):
    if cfg.attn_impl == "reference":
        return attention(q, k, v, causal=True)
    if cfg.attn_impl == "flash":
        from ..ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    if cfg.attn_impl == "ring":
        from ..parallel.sequence_parallel import ring_attention

        return ring_attention(q, k, v, n_shards=cfg.sp_shards, causal=True, mesh=mesh)
    if cfg.attn_impl == "ulysses":
        from ..parallel.sequence_parallel import ulysses_attention

        return ulysses_attention(q, k, v, n_shards=cfg.sp_shards, causal=True, mesh=mesh)
    raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")


def forward_lm(
    params: Params,
    tokens: jax.Array,
    cfg: TransformerConfig = TINY_LM,
    mesh=None,
) -> jax.Array:
    """tokens (B, L) int32 -> logits (B, L, vocab). Causal, weight-tied head."""
    b, l = tokens.shape
    if l > cfg.max_len:
        raise ValueError(f"sequence length {l} exceeds max_len {cfg.max_len}")
    x = params["embed"][tokens] + params["pos"][:l][None]
    for layer in params["layers"]:
        h = rmsnorm(x, layer["attn_norm"]["g"])
        qkv = h @ layer["wqkv"]  # (B, L, 3*D)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (b, l, cfg.n_heads, cfg.head_dim)
        out = _attend(q.reshape(shape), k.reshape(shape), v.reshape(shape), cfg, mesh)
        x = x + out.reshape(b, l, cfg.d_model) @ layer["wo"]
        h = rmsnorm(x, layer["mlp_norm"]["g"])
        x = x + jax.nn.gelu(h @ layer["w_up"]) @ layer["w_down"]
    x = rmsnorm(x, params["final_norm"]["g"])
    return x @ params["embed"].T  # weight-tied LM head


def lm_loss(params: Params, tokens: jax.Array, cfg: TransformerConfig = TINY_LM, mesh=None) -> jax.Array:
    """Next-token cross-entropy (fp32), mean over (B, L-1)."""
    logits = forward_lm(params, tokens[:, :-1], cfg, mesh).astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def make_lm_train_step(
    cfg: TransformerConfig = TINY_LM,
    mesh=None,
    optimizer=None,
    lr: float = 1e-3,
):
    """(init_fn, step_fn) for LM training; any optax optimizer (default adam).

    With a mesh whose axes include "dp", the batch is expected sharded over
    it (GSPMD inserts the gradient all-reduce); ring/ulysses attention adds
    the "sp" sequence axis inside the forward itself.
    """
    import optax

    opt = optimizer if optimizer is not None else optax.adam(lr)

    @jax.jit
    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(lm_loss)(params, tokens, cfg, mesh)
        updates, new_opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), new_opt_state, loss

    return opt.init, step

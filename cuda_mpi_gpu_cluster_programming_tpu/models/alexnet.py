"""AlexNet Blocks 1-2: the single model definition shared by every tier.

The reference maintains five divergent copies of this network (one per
parallelization stage); here there is exactly one functional definition and
the stages are execution configs. Hyperparameters default to the reference's
hard-coded values (v1_serial/src/main.cpp:21-43,
v2_mpi_only/2.2_scatter_halo/src/main.cpp:35-47):

    227x227x3 -Conv1(K=96,F=11,S=4,P=0)-> 55x55x96 -Pool1(3,2)-> 27x27x96
             -Conv2(K=256,F=5,S=1,P=2)-> 27x27x256 -Pool2(3,2)-> 13x13x256
             -LRN2(N=5, a=1e-4, b=0.75, k=2.0)-> 13x13x256
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax

from ..ops import reference as ops
from ..ops.shapes import conv_out_dim, pool_out_dim

Params = Dict[str, Dict[str, Any]]


@dataclasses.dataclass(frozen=True)
class ConvSpec:
    out_channels: int
    filter_size: int
    stride: int
    padding: int


@dataclasses.dataclass(frozen=True)
class PoolSpec:
    window: int
    stride: int


@dataclasses.dataclass(frozen=True)
class LrnSpec:
    size: int
    alpha: float
    beta: float
    k: float
    # False = the reference's CUDA form (k + alpha*sum — the headline golden
    # numbers); True = its CPU form (k + alpha*sum/size). See ops.reference.lrn.
    alpha_over_size: bool = False


@dataclasses.dataclass(frozen=True)
class Blocks12Config:
    """AlexNet Blocks 1-2 hyperparameters (reference defaults)."""

    in_height: int = 227
    in_width: int = 227
    in_channels: int = 3
    conv1: ConvSpec = ConvSpec(96, 11, 4, 0)
    pool1: PoolSpec = PoolSpec(3, 2)
    conv2: ConvSpec = ConvSpec(256, 5, 1, 2)
    pool2: PoolSpec = PoolSpec(3, 2)
    lrn2: LrnSpec = LrnSpec(5, 1e-4, 0.75, 2.0)

    def layer_chain(self) -> Tuple[Tuple[str, Any], ...]:
        """The spatial layer sequence (used by the shard planner)."""
        return (
            ("conv1", self.conv1),
            ("pool1", self.pool1),
            ("conv2", self.conv2),
            ("pool2", self.pool2),
            ("lrn2", self.lrn2),
        )


BLOCKS12 = Blocks12Config()


def layer_dims(cfg):
    """Walk the layer chain once, yielding ``(name, spec, in_dims, out_dims)``
    with dims as (H, W, C) — the ONE output-shape traversal shared by
    ``output_shape``, the FLOP counters and the kernel autotuner (each used
    to re-implement this loop; tuning geometry must not drift from the FLOP
    accounting it is judged by).

    Works for any config exposing ``layer_chain()`` plus input dims
    (Blocks12Config and the full AlexNetConfig's spatial chain alike).
    Mirrors the dim chain at v2_mpi_only/2.2_scatter_halo/src/main.cpp:49-58.
    """
    h, w, c = cfg.in_height, cfg.in_width, cfg.in_channels
    for name, spec in cfg.layer_chain():
        hin, win, cin = h, w, c
        if isinstance(spec, ConvSpec):
            h = conv_out_dim(h, spec.filter_size, spec.padding, spec.stride)
            w = conv_out_dim(w, spec.filter_size, spec.padding, spec.stride)
            c = spec.out_channels
        elif isinstance(spec, PoolSpec):
            h = pool_out_dim(h, spec.window, spec.stride)
            w = pool_out_dim(w, spec.window, spec.stride)
        yield name, spec, (hin, win, cin), (h, w, c)


def output_shape(cfg: Blocks12Config = BLOCKS12) -> Tuple[int, int, int]:
    """(H, W, C) of the final output — 13x13x256 for the defaults."""
    dims = cfg.in_height, cfg.in_width, cfg.in_channels
    for _name, _spec, _in, dims in layer_dims(cfg):
        pass
    return dims


def stage_flops(cfg: Blocks12Config = BLOCKS12):
    """Per-stage ``(name, flops, matmul_flops)`` for ONE image — the
    stage-level FLOP ledger shared by :func:`flops_per_image`,
    :func:`matmul_flops_per_image` and the roofline attribution layer
    (``observability.roofline``). Both totals sum over this generator, so
    a per-stage ledger and the whole-pass count can never drift apart.

    ``flops`` counts everything (conv MACs x2 + bias + ReLU, pool window
    compares, LRN window sums/scale); ``matmul_flops`` counts only the
    MXU work (conv MACs x2) — the conventional MFU numerator.
    """
    for name, spec, (_hi, _wi, c_in), (h, w, c_out) in layer_dims(cfg):
        if isinstance(spec, ConvSpec):
            macs = h * w * c_out * spec.filter_size**2 * c_in
            yield name, 2 * macs + h * w * c_out, 2 * macs  # +bias, +ReLU
        elif isinstance(spec, PoolSpec):
            yield name, h * w * c_out * spec.window**2, 0  # max compares
        elif isinstance(spec, LrnSpec):
            # per element: ~size multiplies + adds for the window sum, plus
            # the scale power and divide
            yield name, h * w * c_out * (2 * spec.size + 2), 0


def flops_per_image(cfg: Blocks12Config = BLOCKS12) -> int:
    """Exact FLOPs for one image through Blocks 1-2 (MAC = 2 FLOPs).

    Counts conv MACs plus the elementwise ReLU/pool/LRN work. For the default
    config this is ~1.12 GFLOP — note the reference's summary.md:29-45 claims
    "~0.33 GFLOPs" for the same workload; that figure undercounts (it is not
    reproducible from the layer dims), so we derive from the config instead.
    """
    return sum(f for _name, f, _mm in stage_flops(cfg))


def matmul_flops_per_image(cfg: Blocks12Config = BLOCKS12) -> int:
    """Matmul-only FLOPs (conv MACs x2) for one image through Blocks 1-2.

    The conventional MFU numerator: only work the MXU executes. Pool
    compares, LRN window sums, bias adds and ReLU are excluded —
    ``flops_per_image`` keeps the all-in count for throughput accounting.
    """
    return sum(mm for _name, _f, mm in stage_flops(cfg))


def forward_blocks12(params: Params, x: jax.Array, cfg: Blocks12Config = BLOCKS12) -> jax.Array:
    """Forward pass Conv1→ReLU→Pool1→Conv2→ReLU→Pool2→LRN2.

    Functional replacement for the reference's ping-pong double-buffer
    orchestrator (v1_serial/src/alexnet_serial.cpp:67-186). ``x`` is NHWC;
    params is ``{"conv1": {"w","b"}, "conv2": {"w","b"}}`` with HWIO weights.
    """
    c1, p1, c2, p2, n2 = cfg.conv1, cfg.pool1, cfg.conv2, cfg.pool2, cfg.lrn2
    x = ops.conv2d(x, params["conv1"]["w"], params["conv1"]["b"], stride=c1.stride, padding=c1.padding)
    x = ops.relu(x)
    x = ops.maxpool(x, window=p1.window, stride=p1.stride)
    x = ops.conv2d(x, params["conv2"]["w"], params["conv2"]["b"], stride=c2.stride, padding=c2.padding)
    x = ops.relu(x)
    x = ops.maxpool(x, window=p2.window, stride=p2.stride)
    x = ops.lrn(
        x, size=n2.size, alpha=n2.alpha, beta=n2.beta, k=n2.k, alpha_over_size=n2.alpha_over_size
    )
    return x

"""Full AlexNet: Blocks 1-2 (reference scope) extended through conv5 + FC.

The reference restricts itself to Blocks 1-2 and tabulates the remaining
dims as an explicit extension task (README.md:19 "Full AlexNet ... is an
extension task"; dim table summary.md:29-45). This module is that extension:

    227x227x3 -Conv1(96,11,s4)->55x55x96 -Pool1(3,2)->27x27x96
      -Conv2(256,5,p2)->27x27x256 -Pool2(3,2)->13x13x256 -LRN2->13x13x256
      -Conv3(384,3,p1)->13x13x384 -Conv4(384,3,p1)->13x13x384
      -Conv5(256,3,p1)->13x13x256 -Pool5(3,2)->6x6x256
      -flatten 9216- FC6(4096) -FC7(4096) -FC8(num_classes) -> logits

Layer ordering through Blocks 1-2 keeps the *reference's* semantics (ReLU
after each conv, LRN only after Pool2 — classic AlexNet also normalises
after conv1, the reference does not), so the Blocks 1-2 prefix of this
model is bit-identical to ``forward_blocks12`` and shares its golden oracle.

ReLU follows every conv and FC6/FC7; dropout (classic p=0.5) is optional and
keyed — inference is deterministic with ``dropout_key=None``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import reference as ops
from .alexnet import BLOCKS12, Blocks12Config, ConvSpec, LrnSpec, PoolSpec

Params = Dict[str, Dict[str, Any]]


@dataclasses.dataclass(frozen=True)
class AlexNetConfig:
    """Full-network hyperparameters; Blocks 1-2 defaults match the reference."""

    blocks12: Blocks12Config = BLOCKS12
    conv3: ConvSpec = ConvSpec(384, 3, 1, 1)
    conv4: ConvSpec = ConvSpec(384, 3, 1, 1)
    conv5: ConvSpec = ConvSpec(256, 3, 1, 1)
    pool5: PoolSpec = PoolSpec(3, 2)
    fc6: int = 4096
    fc7: int = 4096
    num_classes: int = 1000
    dropout_rate: float = 0.5

    def layer_chain(self) -> Tuple[Tuple[str, Any], ...]:
        """Spatial chain (shard-planner compatible: conv/pool/lrn specs)."""
        return self.blocks12.layer_chain() + (
            ("conv3", self.conv3),
            ("conv4", self.conv4),
            ("conv5", self.conv5),
            ("pool5", self.pool5),
        )

    # Duck-type the fields the shard planner / sharded pipeline read.
    @property
    def in_height(self) -> int:
        return self.blocks12.in_height

    @property
    def in_width(self) -> int:
        return self.blocks12.in_width

    @property
    def in_channels(self) -> int:
        return self.blocks12.in_channels


ALEXNET = AlexNetConfig()


def spatial_output_shape(cfg: AlexNetConfig = ALEXNET) -> Tuple[int, int, int]:
    """(H, W, C) after pool5 — 6x6x256 for the defaults (summary.md:29-45)."""
    from .alexnet import layer_dims

    dims = cfg.in_height, cfg.in_width, cfg.in_channels
    for _name, _spec, _in, dims in layer_dims(cfg):
        pass
    return dims


def forward_spatial(params: Params, x: jax.Array, cfg: AlexNetConfig = ALEXNET) -> jax.Array:
    """Conv1..Pool5 feature extractor; ReLU after every conv."""
    for name, spec in cfg.layer_chain():
        if isinstance(spec, ConvSpec):
            x = ops.conv2d(
                x,
                params[name]["w"],
                params[name]["b"],
                stride=spec.stride,
                padding=spec.padding,
            )
            x = ops.relu(x)
        elif isinstance(spec, PoolSpec):
            x = ops.maxpool(x, window=spec.window, stride=spec.stride)
        elif isinstance(spec, LrnSpec):
            x = ops.lrn(
                x,
                size=spec.size,
                alpha=spec.alpha,
                beta=spec.beta,
                k=spec.k,
                alpha_over_size=spec.alpha_over_size,
            )
    return x


def fc_head(
    params: Params,
    feats: jax.Array,
    cfg: AlexNetConfig = ALEXNET,
    dropout_key: Optional[jax.Array] = None,
) -> jax.Array:
    """flatten -> FC6(ReLU[,dropout]) -> FC7(ReLU[,dropout]) -> FC8 logits.

    The single FC-head definition shared by every tier (XLA, Pallas, and the
    sharded config's replicated head). FC layers are plain (N, in) x (in, out)
    matmuls — already the MXU's native shape; a hand kernel would add nothing
    over XLA here.
    """
    x = feats.reshape(feats.shape[0], -1)
    keys = (
        jax.random.split(dropout_key, 2) if dropout_key is not None else (None, None)
    )
    for name, key in (("fc6", keys[0]), ("fc7", keys[1])):
        x = ops.relu(x @ params[name]["w"] + params[name]["b"])
        if key is not None and cfg.dropout_rate > 0:
            keep = 1.0 - cfg.dropout_rate
            mask = jax.random.bernoulli(key, keep, x.shape)
            x = jnp.where(mask, x / keep, 0.0)
    return x @ params["fc8"]["w"] + params["fc8"]["b"]


def forward_alexnet(
    params: Params,
    x: jax.Array,
    cfg: AlexNetConfig = ALEXNET,
    dropout_key: Optional[jax.Array] = None,
) -> jax.Array:
    """Full forward pass -> (N, num_classes) logits."""
    return fc_head(params, forward_spatial(params, x, cfg), cfg, dropout_key)


def predict(params: Params, x: jax.Array, cfg: AlexNetConfig = ALEXNET) -> jax.Array:
    """Class probabilities (softmax over logits)."""
    return jax.nn.softmax(forward_alexnet(params, x, cfg), axis=-1)


def _param_shapes(cfg: AlexNetConfig) -> Dict[str, Tuple[Tuple[int, ...], Tuple[int, ...]]]:
    shapes: Dict[str, Tuple[Tuple[int, ...], Tuple[int, ...]]] = {}
    c_in = cfg.in_channels
    for name, spec in cfg.layer_chain():
        if isinstance(spec, ConvSpec):
            shapes[name] = (
                (spec.filter_size, spec.filter_size, c_in, spec.out_channels),
                (spec.out_channels,),
            )
            c_in = spec.out_channels
    h, w, c = spatial_output_shape(cfg)
    flat = h * w * c
    shapes["fc6"] = ((flat, cfg.fc6), (cfg.fc6,))
    shapes["fc7"] = ((cfg.fc6, cfg.fc7), (cfg.fc7,))
    shapes["fc8"] = ((cfg.fc7, cfg.num_classes), (cfg.num_classes,))
    return shapes


def init_full_deterministic(cfg: AlexNetConfig = ALEXNET, dtype=jnp.float32) -> Params:
    """weights=0.01, biases=0.0 — the cross-tier comparison init extended to
    the full net (2.2_scatter_halo/src/main.cpp:37-47 semantics)."""
    return {
        name: {"w": jnp.full(ws, 0.01, dtype), "b": jnp.zeros(bs, dtype)}
        for name, (ws, bs) in _param_shapes(cfg).items()
    }


def init_full_random(key: jax.Array, cfg: AlexNetConfig = ALEXNET, dtype=jnp.float32) -> Params:
    """He-scaled normal weights (proper for depth — uniform [0,1) explodes
    through 8 layers), bias 0.1 as in V1 (v1_serial/src/alexnet_serial.cpp:51-57)."""
    shapes = _param_shapes(cfg)
    keys = jax.random.split(key, len(shapes))
    params: Params = {}
    for k, (name, (ws, bs)) in zip(keys, shapes.items()):
        fan_in = math.prod(ws[:-1])
        params[name] = {
            "w": jax.random.normal(k, ws, dtype) * (2.0 / fan_in) ** 0.5,
            "b": jnp.full(bs, 0.1, dtype),
        }
    return params

from .alexnet import (  # noqa: F401
    ConvSpec,
    PoolSpec,
    LrnSpec,
    Blocks12Config,
    BLOCKS12,
    forward_blocks12,
    output_shape,
)
from .init import (  # noqa: F401
    init_params_deterministic,
    init_params_random,
    deterministic_input,
    random_input,
    to_reference_layout,
    from_reference_layout,
)

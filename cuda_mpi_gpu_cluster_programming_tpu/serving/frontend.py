"""HTTP front end: the admission queue's network transport.

PR 6 deliberately isolated the RPC layer behind :class:`AdmissionQueue`
so a real transport could land without touching dispatch — this module
is that transport: a stdlib ``http.server`` front end (no new deps) that
accepts inference requests over a socket and honors the queue contract
EXACTLY:

- **Backpressure is a status code, not a buffer.** ``QueueFull`` maps to
  HTTP 429 (+ ``Retry-After``), an over-wide request to 413 — the same
  admission-control refusals in-process submitters get, made wire-
  visible. Malformed bodies are 400 before anything touches the queue.
- **Sheds stay explicit.** A request shed in the queue (hard deadline:
  ``reason="deadline"``; class SLO blown: ``reason="slo"``) answers 504
  with the reason in the body — the client always learns what happened;
  nothing is silently dropped. Ladder-exhausted failures answer 500.
- **Every request is journaled and traced.** Each HTTP exchange emits a
  ``serve.transport`` span (receive -> response written) that temporally
  wraps the existing ``serve.queue_wait``/``serve.dispatch`` correlation,
  plus a ``serve_transport`` journal record carrying the span id, class,
  status, and HTTP code; refusals journal ``serve_reject``. One journal
  file still exports into one Perfetto timeline (docs/OBSERVABILITY.md).

Wire format (``POST /v1/infer``, JSON):

    {"shape": [n, H, W, C] | [H, W, C],    # required
     "data": [flat floats],               # payload, XOR "fill"
     "fill": 1.0,                         # constant image (load tests)
     "class": "interactive",              # traffic class (SLO policy)
     "deadline_s": 0.5,                   # hard deadline override
     "rid": "...",                        # optional request id
     "return_output": true}               # echo the output tensor

    -> 200 {"rid", "status": "OK", "class", "latency_ms",
            "output_shape", "output"?}
    -> 429/413/400/504/500 {"rid"?, "status", "reason"?, "error"}

``GET /healthz`` answers liveness + queue saturation gauges
(``oldest_wait_ms`` — observable before the first shed); ``GET /stats``
the full serve/queue counters; ``GET /metrics`` the process-wide
metrics registry in Prometheus text exposition (0.0.4 — counters,
gauges, histogram summaries with the repo's nearest-rank p50/p99), each
scrape journaled as a ``serve_transport`` record like the POST
exchanges.

Handler threads block on sockets and handle waits BY DESIGN — they are
transport, never the dispatch loop; staticcheck's
``blocking-socket-call-in-timed-region`` rule enforces that no socket
call creeps into a timed region. The journal/span writes happen in
``@off_timed_path`` helpers after the measured transport window closes.

Also here: :func:`http_fleet_load`, the threaded HTTP client fleet that
drives a traffic shape through the front end and returns the same
per-class closed accounting as the in-process shaped loader.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

import numpy as np

from ..observability.metrics import registry as metrics_registry
from ..observability.trace import get_tracer, off_timed_path
from .queue import FAILED, OK, SHED, QueueFull
from .server import InferenceServer
from .traffic import (
    ClassStats,
    RequestClass,
    ShapedReport,
    assign_classes,
    shaped_arrivals,
)


class _Handler(BaseHTTPRequestHandler):
    """One HTTP exchange. ``frontend`` is bound per-ServingFrontend via a
    subclass (http.server's intended extension point)."""

    frontend: "ServingFrontend"  # bound in ServingFrontend.__init__
    server_version = "tpu-serve-frontend/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:
        pass  # the journal is the access log; stderr chatter helps nobody

    # ----------------------------------------------------------- plumbing

    def _send_json(self, code: int, payload: dict, retry_after: bool = False) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after:
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    # ------------------------------------------------------------- routes

    def do_GET(self) -> None:
        fe = self.frontend
        if self.path == "/healthz":
            qs = fe.server.queue.stats()
            payload = {
                "status": "ok",
                "queue": qs.to_obj(),
                "buckets": list(fe.server.buckets),
            }
            # Autopilot state (docs/SERVING.md "Autopilot"): mode, level,
            # active overrides, last action + age — the router's probes
            # see degraded-but-healthy instead of inferring it from
            # latency. Since ISSUE 20 the payload also carries the
            # controller's "rung" (current ladder rung name) and
            # "intent" (overloaded/calm verdict + the burn/depth/wait it
            # was judged on, with age_s/idle_s freshness) — the fleet
            # control plane arbitrates on the controller's OWN verdict,
            # never a router-side re-derivation. Key absent on
            # uncontrolled servers, so the probe payload keeps its
            # pre-ISSUE-18 shape exactly.
            if fe.server.controller is not None:
                payload["controller"] = fe.server.controller.state_obj()
            self._send_json(200, payload)
        elif self.path == "/stats":
            srv = fe.server
            payload = {
                "serve": srv.stats.summary(),
                "queue": srv.queue.stats().to_obj(),
                "http": dict(fe.http_codes),
                "entry": srv.sup.entry.key if srv.sup else srv.cfg.config,
            }
            if srv.controller is not None:
                payload["controller"] = srv.controller.state_obj()
            self._send_json(200, payload)
        elif self.path == "/metrics":
            # Prometheus text exposition of the process-wide registry
            # (docs/SERVING.md): counters/gauges map directly, histograms
            # expose as summaries with the same nearest-rank p50/p99 every
            # other surface reports. Journaled like the POST exchanges —
            # one serve_transport record per scrape — so the access trail
            # the journal IS covers the scraper too.
            t0 = time.monotonic()
            self._send_text(
                200,
                metrics_registry().prometheus(),
                "text/plain; version=0.0.4; charset=utf-8",
            )
            fe._finish("", "", t0, "METRICS", 200)
        else:
            self._send_json(404, {"error": f"no route {self.path!r}"})

    def do_POST(self) -> None:
        if self.path != "/v1/infer":
            self._send_json(404, {"error": f"no route {self.path!r}"})
            return
        fe = self.frontend
        t0 = time.monotonic()
        rid = cls = ""
        try:
            length = int(self.headers.get("Content-Length") or 0)
            req = json.loads(self.rfile.read(length) or b"{}")
            x, cls, deadline_s, rid, want_out = _parse_infer(req)
        except (ValueError, KeyError, TypeError) as e:
            self._send_json(
                400, {"status": "REJECTED", "error": f"bad request: {e}"}
            )
            fe._finish(rid, cls, t0, "REJECTED", 400)
            return
        try:
            handle = fe.server.submit(x, deadline_s=deadline_s, rid=rid, cls=cls)
        except QueueFull as e:
            # Backpressure IS the contract: the queue refused, the wire
            # says 429, the client backs off. Never buffered to OOM.
            self._send_json(
                429, {"status": "REJECTED", "error": str(e)}, retry_after=True
            )
            fe._finish(rid, cls, t0, "REJECTED", 429)
            return
        except ValueError as e:  # wider than the largest bucket
            self._send_json(413, {"status": "REJECTED", "error": str(e)})
            fe._finish(rid, cls, t0, "REJECTED", 413)
            return
        handle.wait(fe.max_wait_s)
        if handle.status == OK:
            payload = {
                "rid": handle.rid,
                "status": OK,
                "class": cls,
                "latency_ms": round(handle.latency_ms, 3),
                "output_shape": list(handle.result.shape),
            }
            if want_out:
                payload["output"] = np.asarray(handle.result).reshape(-1).tolist()
            code = 200
        elif handle.status == SHED:
            # Explicit shed -> explicit 504: the deadline/SLO verdict the
            # queue journaled, surfaced to the caller with its reason.
            payload = {
                "rid": handle.rid, "status": SHED, "class": cls,
                "reason": "slo" if "SLO" in handle.error else "deadline",
                "error": handle.error,
            }
            code = 504
        elif handle.status == FAILED:
            payload = {
                "rid": handle.rid, "status": FAILED, "class": cls,
                "error": handle.error,
            }
            code = 500
        else:  # still PENDING past max_wait_s — transport gives up, the
            # request itself stays queued and will still complete/shed.
            payload = {
                "rid": handle.rid, "status": "TIMEOUT", "class": cls,
                "error": f"no verdict within {fe.max_wait_s}s",
            }
            code = 503
        self._send_json(code, payload)
        fe._finish(handle.rid, cls, t0, str(payload["status"]), code)


def _parse_infer(req: dict) -> Tuple[np.ndarray, str, Optional[float], str, bool]:
    """Decode one /v1/infer body into (x, cls, deadline_s, rid, want_out).
    Raises ValueError on anything malformed — mapped to 400 upstream."""
    shape = req.get("shape")
    if not isinstance(shape, list) or len(shape) not in (3, 4) or not all(
        isinstance(d, int) and d > 0 for d in shape
    ):
        raise ValueError(f"shape must be [n,H,W,C] or [H,W,C], got {shape!r}")
    n_elem = int(np.prod(shape))
    if "data" in req:
        data = req["data"]
        if not isinstance(data, list) or len(data) != n_elem:
            raise ValueError(
                f"data must be a flat list of {n_elem} numbers for shape {shape}"
            )
        x = np.asarray(data, np.float32).reshape(shape)
    else:
        x = np.full(shape, float(req.get("fill", 1.0)), np.float32)
    cls = str(req.get("class", ""))
    deadline_s = req.get("deadline_s")
    if deadline_s is not None:
        deadline_s = float(deadline_s)
        if deadline_s <= 0:
            raise ValueError("deadline_s must be > 0")
    rid = str(req.get("rid", "")) or None
    return x, cls, deadline_s, rid or "", bool(req.get("return_output", False))


class ServingFrontend:
    """The network face of one :class:`InferenceServer`.

    Owns a ``ThreadingHTTPServer`` (one handler thread per in-flight
    exchange — transport threads block on handle waits; the dispatch
    loop never does) on ``host:port`` (port 0 = ephemeral, the test
    default). The wrapped server must be ``start()``ed by the caller —
    the front end is a transport, not a lifecycle manager.
    """

    def __init__(
        self,
        server: InferenceServer,
        port: int = 0,
        host: str = "127.0.0.1",
        max_wait_s: float = 120.0,
    ):
        self.server = server
        self.max_wait_s = max_wait_s
        self.http_codes: Dict[int, int] = {}
        self._codes_lock = threading.Lock()
        handler = type("BoundHandler", (_Handler,), {"frontend": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "ServingFrontend":
        if self._thread is not None:
            raise RuntimeError("frontend already started")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="serve-frontend",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(10.0)
        self._thread = None

    @off_timed_path
    def _finish(
        self, rid: str, cls: str, t0: float, status: str, http_code: int
    ) -> None:
        """Transport accounting AFTER the response hit the socket: the
        ``serve.transport`` span (emitted from its measured bounds — it
        temporally wraps the request's queue-wait + dispatch spans), the
        ``serve_transport``/``serve_reject`` journal record, and the
        metrics. Off the handler's measured window by construction."""
        t1 = time.monotonic()
        ms = (t1 - t0) * 1e3
        with self._codes_lock:
            self.http_codes[http_code] = self.http_codes.get(http_code, 0) + 1
        reg = metrics_registry()
        reg.counter(f"serve.http_{http_code}").inc()
        reg.histogram("serve.transport_ms").observe(ms)
        sid = ""
        tr = get_tracer()
        if tr is not None:
            sid = tr.emit(
                "serve.transport", t0, t1, parent_id="", track="transport",
                rid=rid, cls=cls, status=status, http=http_code,
            )
        kind = "serve_reject" if status == "REJECTED" else "serve_transport"
        payload = {
            "rid": rid, "cls": cls, "status": status, "http": http_code,
            "ms": round(ms, 3),
        }
        if sid:
            payload["trace_id"] = tr.trace_id
            payload["span_id"] = sid
        self.server._journal(kind, key=f"http:{rid or http_code}", **payload)


# --------------------------------------------------------- client fleet ---


def http_fleet_load(
    url: str,
    image_shape: Tuple[int, int, int],
    *,
    shape: str = "steady",
    rate_rps: float,
    duration_s: float,
    classes: Optional[List[RequestClass]] = None,
    seed: int = 0,
    n_workers: int = 8,
    timeout_s: float = 120.0,
    fill: float = 1.0,
) -> ShapedReport:
    """Threaded HTTP client fleet: drive a traffic shape through the front
    end over real sockets and account every request by its HTTP verdict
    (200 ok / 504 shed / 429 or 413 rejected / anything else failed).

    The arrival schedule and class mix are the SAME seeded draws the
    in-process shaped loader uses, so an HTTP drill and an in-process
    drill at one seed offer identical work — what differs is the
    transport. Latencies are client-measured wall (POST sent -> response
    read): the number a user actually sees, transport included. Per-class
    accounting closes: ok + shed + failed + rejected == offered.
    """
    if classes is None:
        raise ValueError("http_fleet_load needs an explicit class mix")
    parsed = urlparse(url)
    host, port = parsed.hostname, parsed.port
    arrivals = shaped_arrivals(shape, rate_rps, duration_s, seed)
    plan = assign_classes(classes, len(arrivals), seed)
    work: List[Tuple[float, RequestClass, int]] = [
        (at, c, n) for at, (c, n) in zip(arrivals, plan)
    ]
    stats: Dict[str, ClassStats] = {c.name: ClassStats() for c in classes}
    lock = threading.Lock()
    next_idx = [0]
    t0 = time.monotonic()
    images_ok = [0]
    last_done = [t0]

    def _worker() -> None:
        conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
        try:
            while True:
                with lock:
                    i = next_idx[0]
                    if i >= len(work):
                        return
                    next_idx[0] = i + 1
                at, c, n = work[i]
                now = time.monotonic() - t0
                if at > now:
                    time.sleep(at - now)
                body = json.dumps(
                    {
                        "shape": [n, *image_shape],
                        "fill": fill,
                        "class": c.name,
                        "deadline_s": c.deadline_s,
                        "rid": f"h{i:06d}",
                    }
                )
                sent = time.monotonic()
                try:
                    conn.request(
                        "POST", "/v1/infer", body,
                        {"Content-Type": "application/json"},
                    )
                    # The fleet MEASURES user-visible latency around its
                    # own socket wait — blocking here is the experiment.
                    resp = conn.getresponse()  # noqa: blocking-socket-call-in-timed-region
                    resp.read()
                    code = resp.status
                except (OSError, http.client.HTTPException):
                    conn.close()
                    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
                    code = -1
                wall_ms = (time.monotonic() - sent) * 1e3
                with lock:
                    st = stats[c.name]
                    st.offered += 1
                    if code == 200:
                        st.ok += 1
                        st.images_ok += n
                        st.latencies_ms.append(wall_ms)
                        images_ok[0] += n
                    elif code == 504:
                        st.shed += 1
                    elif code in (429, 413):
                        st.rejected += 1
                    else:
                        st.failed += 1
                    last_done[0] = max(last_done[0], time.monotonic())
        finally:
            conn.close()

    threads = [
        threading.Thread(target=_worker, name=f"http-load-{i}", daemon=True)
        for i in range(max(1, n_workers))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout_s + duration_s)
    wall = max(1e-9, last_done[0] - t0)
    return ShapedReport(
        shape=shape,
        per_class=stats,
        duration_s=wall,
        sustained_img_s=images_ok[0] / wall,
    )

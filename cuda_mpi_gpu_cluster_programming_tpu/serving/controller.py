"""Autopilot: the journaled closed-loop controller over the serving stack.

Everything below the dispatch loop *measures* — per-class error-budget
burn (observability.health, PR 15), queue saturation gauges before the
first shed (PR 11), pool capacity (resilience.supervisor) — but until
this module every knob those signals could move was fixed at server
build time. :class:`AutopilotController` closes the loop: it is
evaluated from the dispatch loop's ``@off_timed_path`` observation
cadence (beside ``_observe_queue``/``_observe_resources``), folds the
PR 15 ``ERROR_BUDGET`` math incrementally over the live outcome stream
(a sliding window per class — never a post-hoc journal scan), and walks
a fixed **pressure ladder** of reversible actions when the protected
class's budget burns or the queue wait approaches the saturation knee:

1. ``tighten_admission`` — shed **bulk** first, then **batch**, by
   installing a tightened :class:`~.slo.SLOPolicy` on the queue's
   pop-time path (:meth:`SLOPolicy.tightened`). Interactive is never
   touched: the ladder exists to protect it.
2. ``narrow_buckets`` — drop the largest bucket, so wide work stops
   monopolizing dispatch slots and over-wide requests are rejected at
   the door (``submit``'s too-wide check tightens with the set). Wide
   requests already queued wait at the head until the (already
   tightened) admission policy sheds them — rung 1 always precedes
   rung 2, so narrowing cannot strand work forever.
3. ``downshift_dtype`` — bf16 → int8w, **only** after a journaled
   :class:`~..precision.gate.ToleranceGate` screen passes
   (``gate_pass``); a failed screen journals the refusal
   (``downshift_refused`` + the gate's own ``gate_fail``) and the rung
   is skipped — never silently adopted. Unsupervised servers only: the
   supervisor's ladder rungs carry no dtype axis.
4. ``degrade_capacity`` — one supervised rung DOWN, requested through
   :meth:`~..resilience.supervisor.Supervisor.request_degrade` as a
   *capacity decision* (cause ``"requested: ..."``), not a fault
   response; grow-back is the explicit reversal
   (:meth:`request_promote`), sentinel-verified like any promotion.

Every transition journals one ``controller_action`` record carrying its
triggering **evidence** (the signal values, the thresholds they crossed,
and the cooldown/dwell state that admitted the action) — the record the
replay A/B and the health report's did-it-help attribution read. Every
action has hysteresis: ``cooldown_s`` between consecutive actions and
``min_dwell_s`` at a level before de-escalating (the ElasticPool
anti-flap discipline), so a noisy signal cannot oscillate the server.
De-escalation reverses the ladder strictly LIFO, one rung per
evaluation, and every reversal is journaled too.

The controller is inert without an SLO policy (no classes ⇒ no burn, no
knee) and journals nothing on a calm trace — the calm-path acceptance
check ``BENCH_MODE=control`` pins.

Threading: every hook runs on the dispatch thread (``note_*`` from the
``@off_timed_path`` completion helpers, ``evaluate`` from the
observation cadence), so the state needs no lock; the HTTP front end
reads :meth:`state_obj` snapshots cross-thread (atomic attribute reads).

Layering: stdlib at import; jax is only reached through the server's
own actuators (rebuild/warm), lazily.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Deque, Dict, List, Optional, Tuple

from ..resilience.sentinel import off_timed_path
from .slo import SLOPolicy


@dataclasses.dataclass(frozen=True)
class ControllerConfig:
    """The autopilot's knobs (all hysteresis/threshold state in one
    journal-round-trippable place — ``serve_config`` carries
    :meth:`to_obj` so a replay rebuilds the exact controller)."""

    # -- cadence & signal fold
    eval_s: float = 0.25  # evaluation cadence off the dispatch loop
    window: int = 128  # per-class sliding window of recent outcomes
    min_completed: int = 20  # outcomes before a class's burn is trusted
    # -- thresholds
    burn_high: float = 1.0  # escalate when protected burn >= this
    burn_low: float = 0.25  # de-escalate only when burn <= this
    knee_frac: float = 0.7  # escalate when oldest wait >= frac * knee
    knee_release_frac: float = 0.35  # de-escalate only below this
    # -- hysteresis (the ElasticPool anti-flap discipline)
    min_dwell_s: float = 1.0  # min time at a level before de-escalating
    cooldown_s: float = 1.0  # min time between consecutive actions
    # -- ladder shape
    protected_cls: str = "interactive"  # the class the ladder defends
    shed_order: Tuple[str, ...] = ("bulk", "batch")  # tighten order
    tighten_factor: float = 0.5  # finite shed cuts scale by this
    downshift_to: str = "int8w"  # dtype rung target
    enable_admission: bool = True
    enable_buckets: bool = True
    enable_dtype: bool = True
    enable_degrade: bool = True

    def to_obj(self) -> dict:
        obj = dataclasses.asdict(self)
        obj["shed_order"] = list(self.shed_order)
        return obj

    @staticmethod
    def from_obj(obj: dict) -> "ControllerConfig":
        """Inverse of :meth:`to_obj` — the ``serve_config`` round-trip
        ``observability.replay`` rebuilds a recorded controller from.
        Unknown keys are ignored (newer journals replay on older code)."""
        fields = {f.name for f in dataclasses.fields(ControllerConfig)}
        kw = {k: v for k, v in (obj or {}).items() if k in fields}
        if "shed_order" in kw:
            kw["shed_order"] = tuple(str(c) for c in kw["shed_order"])
        return ControllerConfig(**kw)


@dataclasses.dataclass
class ControllerSignals:
    """One evaluation's inputs — journaled verbatim as action evidence."""

    burn: Dict[str, Optional[float]]  # per-class windowed burn (None: n/a)
    completed: Dict[str, int]  # window occupancy per class
    depth: int
    pending_images: int
    oldest_wait_ms: float
    knee_ms: Optional[float]  # tightest finite shed cut (None: no knee)
    pool_alive: Optional[int]  # supervised pool size (None: unsupervised)

    def to_obj(self) -> dict:
        return {
            "burn": {
                k: (round(v, 3) if v is not None else None)
                for k, v in self.burn.items()
            },
            "completed": dict(self.completed),
            "depth": self.depth,
            "pending_images": self.pending_images,
            "oldest_wait_ms": round(self.oldest_wait_ms, 3),
            "knee_ms": self.knee_ms,
            "pool_alive": self.pool_alive,
        }


class AutopilotController:
    """Closed-loop graceful degradation over one :class:`InferenceServer`.

    Owns no thread and no timer: the server's dispatch loop calls
    :meth:`evaluate` between batches and the completion helpers feed
    :meth:`note_ok`/:meth:`note_shed`/:meth:`note_fail` — the controller
    is a pure fold over signals the server already produces.
    """

    def __init__(self, server, cfg: Optional[ControllerConfig] = None):
        self.server = server
        self.cfg = cfg or ControllerConfig()
        # The BASE SLO policy burn is measured against — actuation swaps
        # the queue's live policy, never the product targets.
        self.base_slo: Optional[SLOPolicy] = server.cfg.slo
        # Per-class sliding windows of violation flags (1 = late/shed/
        # failed, 0 = met SLO) — the PR 15 burn math, folded live:
        # burn = (violations / completed) / ERROR_BUDGET over the window.
        self._win: Dict[str, Deque[int]] = {}
        # LIFO of applied rungs: (rung index, action name, target, undo).
        self._applied: List[Tuple[int, str, str, Any]] = []
        self._next_rung = 0
        self._blocked: set = set()  # refused rungs (e.g. gate-failed dtype)
        self._last_eval = 0.0
        self._last_action_t: Optional[float] = None
        self._level_enter_t: Optional[float] = None
        self._last_action: Optional[dict] = None
        self._seq = 0
        self.action_counts: Dict[str, int] = {}
        # Fleet intent (ISSUE 20): the last evaluated signal set is
        # cached so /healthz can publish the controller's own verdict
        # (overloaded/calm + the burn it was judged on) instead of the
        # router re-deriving it. Outcome counting is a plain counter —
        # the note_* hot path takes no clock reads.
        self._sig_cache: Optional[ControllerSignals] = None
        self._sig_t: Optional[float] = None
        self._n_outcomes = 0
        self._n_seen_outcomes = 0
        self._last_outcome_t: Optional[float] = None

    # ------------------------------------------------------------- signals

    def note_ok(self, cls: str, latency_ms: float) -> None:
        slo_ms = self._slo_ms(cls)
        self._window(cls).append(
            1 if (slo_ms and latency_ms > slo_ms) else 0
        )
        self._n_outcomes += 1

    def note_shed(self, cls: str) -> None:
        self._window(cls).append(1)
        self._n_outcomes += 1

    def note_fail(self, cls: str) -> None:
        self._window(cls).append(1)
        self._n_outcomes += 1

    def _window(self, cls: str) -> Deque[int]:
        w = self._win.get(cls)
        if w is None:
            w = self._win[cls] = collections.deque(maxlen=self.cfg.window)
        return w

    def _slo_ms(self, cls: str) -> float:
        if self.base_slo is None:
            return 0.0
        return float(self.base_slo.class_for(cls).slo_ms or 0.0)

    def burn(self, cls: str) -> Optional[float]:
        """The class's windowed error-budget burn — the same math as
        :func:`observability.health.slo_attainment` (violation share over
        completed, divided by ``ERROR_BUDGET``) over the last ``window``
        outcomes; None for unbounded classes or a window still shorter
        than ``min_completed`` (a burn estimated from three requests is
        noise, and noise must not actuate)."""
        from ..observability.health import ERROR_BUDGET

        if not self._slo_ms(cls):
            return None
        w = self._win.get(cls)
        if w is None or len(w) < self.cfg.min_completed:
            return None
        return (sum(w) / len(w)) / ERROR_BUDGET

    def signals(self) -> ControllerSignals:
        qs = self.server.queue.stats()
        knee = None
        if self.base_slo is not None:
            cuts = [
                c.shed_cut_ms
                for c in self.base_slo.classes.values()
                if c.shed_cut_ms
            ]
            if cuts:
                knee = min(cuts)
        return ControllerSignals(
            burn={cls: self.burn(cls) for cls in sorted(self._win)},
            completed={cls: len(w) for cls, w in sorted(self._win.items())},
            depth=qs.depth,
            pending_images=qs.pending_images,
            oldest_wait_ms=qs.oldest_wait_ms,
            knee_ms=knee,
            pool_alive=(
                self.server.sup.pool.n_alive
                if self.server.sup is not None
                else None
            ),
        )

    def _overloaded(self, sig: ControllerSignals) -> bool:
        b = sig.burn.get(self.cfg.protected_cls)
        if b is not None and b >= self.cfg.burn_high:
            return True
        return bool(
            sig.knee_ms
            and sig.oldest_wait_ms >= self.cfg.knee_frac * sig.knee_ms
        )

    def _calm(self, sig: ControllerSignals) -> bool:
        b = sig.burn.get(self.cfg.protected_cls)
        if b is not None and b > self.cfg.burn_low:
            return False
        return not (
            sig.knee_ms
            and sig.oldest_wait_ms > self.cfg.knee_release_frac * sig.knee_ms
        )

    # -------------------------------------------------------------- ladder

    def _rungs(self) -> List[Tuple[str, str]]:
        """The pressure ladder available to THIS server, in escalation
        order. Availability is structural (an unsupervised server has no
        capacity rung; a supervised one has no dtype axis) — refusals
        discovered at actuation time land in ``_blocked`` instead."""
        cfg, srv = self.cfg, self.server
        rungs: List[Tuple[str, str]] = []
        if cfg.enable_admission and self.base_slo is not None:
            for cls in cfg.shed_order:
                if cls in self.base_slo.classes and cls != cfg.protected_cls:
                    rungs.append(("tighten_admission", cls))
        if cfg.enable_buckets:
            rungs.append(("narrow_buckets", ""))
        if cfg.enable_dtype and srv.sup is None:
            if srv.cfg.compute != cfg.downshift_to:
                rungs.append(("downshift_dtype", cfg.downshift_to))
        if cfg.enable_degrade and srv.sup is not None:
            rungs.append(("degrade_capacity", ""))
        return rungs

    @property
    def level(self) -> int:
        return len(self._applied)

    @property
    def mode(self) -> str:
        return "degraded" if self._applied else "steady"

    # ---------------------------------------------------------- evaluation

    @off_timed_path
    def evaluate(self, now: Optional[float] = None) -> Optional[dict]:
        """One control decision, throttled to ``eval_s`` — called from
        the dispatch loop's observation cadence. Returns the journaled
        action record when a transition fired, else None. ``now`` is
        injectable so the hysteresis drills test dwell/cooldown without
        sleeping."""
        if now is None:
            now = time.monotonic()
        if self.base_slo is None:  # no classes ⇒ no burn, no knee: inert
            return None
        if now - self._last_eval < self.cfg.eval_s:
            return None
        self._last_eval = now
        sig = self.signals()
        self._sig_cache, self._sig_t = sig, now
        if self._n_outcomes != self._n_seen_outcomes:
            self._n_seen_outcomes = self._n_outcomes
            self._last_outcome_t = now
        if self._overloaded(sig):
            if not self._cooled(now):
                return None
            return self._escalate(sig, now)
        if self._applied and self._calm(sig):
            if not self._cooled(now) or not self._dwelled(now):
                return None
            return self._deescalate(sig, now)
        return None

    def _cooled(self, now: float) -> bool:
        return (
            self._last_action_t is None
            or now - self._last_action_t >= self.cfg.cooldown_s
        )

    def _dwelled(self, now: float) -> bool:
        return (
            self._level_enter_t is None
            or now - self._level_enter_t >= self.cfg.min_dwell_s
        )

    def _escalate(self, sig: ControllerSignals, now: float) -> Optional[dict]:
        rungs = self._rungs()
        i = self._next_rung
        while i < len(rungs):
            action, target = rungs[i]
            if (action, target) in self._blocked:
                i += 1
                continue
            t0 = time.perf_counter()
            try:
                undo, extra = self._apply(action, target)
            except Exception as e:  # noqa — a rung that cannot actuate is
                # refused attributably and skipped, never retried blind.
                self._blocked.add((action, target))
                self._journal_action(
                    f"{action.split('_')[0]}_refused", target, sig, now,
                    actuated=False, reversal=False,
                    ms=(time.perf_counter() - t0) * 1e3,
                    cause=f"{type(e).__name__}: {e}"[:200],
                )
                i += 1
                continue
            if undo is None:
                # Screened and refused (e.g. gate-failed dtype): journaled
                # by _apply via ``extra``; block the rung and move on.
                self._blocked.add((action, target))
                self._journal_action(
                    f"{action.split('_')[0]}_refused", target, sig, now,
                    actuated=False, reversal=False,
                    ms=(time.perf_counter() - t0) * 1e3, **extra,
                )
                i += 1
                continue
            self._applied.append((i, action, target, undo))
            self._next_rung = i + 1
            rec = self._journal_action(
                action, target, sig, now, actuated=True, reversal=False,
                ms=(time.perf_counter() - t0) * 1e3, **extra,
            )
            self._last_action_t = now
            self._level_enter_t = now
            return rec
        return None  # ladder exhausted (or every remaining rung refused)

    def _deescalate(self, sig: ControllerSignals, now: float) -> Optional[dict]:
        i, action, target, undo = self._applied[-1]
        t0 = time.perf_counter()
        reverse = _REVERSALS[action]
        try:
            ok, extra = self._unapply(action, target, undo)
        except Exception as e:  # noqa — a reversal that fails keeps the
            # rung applied (degraded-but-stable beats a half-reversal).
            ok, extra = False, {"cause": f"{type(e).__name__}: {e}"[:200]}
        ms = (time.perf_counter() - t0) * 1e3
        if not ok:
            rec = self._journal_action(
                f"{reverse.split('_')[0]}_refused", target, sig, now,
                actuated=False, reversal=True, ms=ms, **extra,
            )
            self._last_action_t = now  # cooldown a refused reversal too
            return rec
        self._applied.pop()
        self._next_rung = i
        rec = self._journal_action(
            reverse, target, sig, now, actuated=True, reversal=True,
            ms=ms, **extra,
        )
        self._last_action_t = now
        self._level_enter_t = now
        return rec

    # ------------------------------------------------------------ actuators

    @off_timed_path
    def _apply(self, action: str, target: str):
        """Actuate one rung through the server's hooks. Returns
        ``(undo, extra)`` — ``undo`` is what the reversal needs (None =
        screened and refused; ``extra`` then carries the cause)."""
        srv = self.server
        if action == "tighten_admission":
            prev = srv.queue.slo
            pol = prev or self.base_slo
            # The tightened cut must land BELOW the protected class's
            # budget, not merely at it: the admission queue's wait is
            # shared across classes, so with equal cuts everyone sheds
            # at the same wait and the protected class gains nothing.
            # At tighten_factor x the protected budget the queue
            # equilibrates where the tightened class starts shedding —
            # leaving the protected class's arrivals a wait comfortably
            # inside its own SLO.
            protected_cut = pol.class_for(
                self.cfg.protected_cls
            ).shed_cut_ms or (self.signals().knee_ms or 0.0)
            cut = protected_cut * self.cfg.tighten_factor
            own_cut = pol.class_for(target).shed_cut_ms
            if own_cut:
                cut = min(own_cut, cut)  # only ever tighten
            if not cut:
                return None, {"cause": "no finite cut derivable"}
            srv.apply_slo_policy(
                (prev or self.base_slo).tightened(target, cut)
            )
            return prev, {"shed_wait_ms": round(cut, 3)}
        if action == "narrow_buckets":
            prev = srv.buckets
            if len(prev) < 2:
                return None, {"cause": "bucket set already minimal"}
            srv.apply_buckets(prev[:-1])
            return prev, {"buckets": list(srv.buckets)}
        if action == "downshift_dtype":
            res = self._screen_dtype(target)
            if not res.passed:
                return None, {
                    "cause": f"gate refused: {res.reason()}"[:200],
                    "gate_margin": _finite(res.margin),
                }
            srv.apply_compute(target)
            return srv.cfg.compute, {
                "gate_margin": _finite(res.margin),
                "frm": srv.cfg.compute,
            }
        if action == "degrade_capacity":
            frm = srv.sup.entry.key
            if not srv.request_degrade("controller: protected-class burn"):
                return None, {"cause": "ladder floor reached"}
            return frm, {"frm": frm, "to": srv.sup.entry.key}
        raise ValueError(f"unknown rung {action!r}")

    @off_timed_path
    def _unapply(self, action: str, target: str, undo) -> Tuple[bool, dict]:
        srv = self.server
        if action == "tighten_admission":
            srv.apply_slo_policy(undo)
            return True, {}
        if action == "narrow_buckets":
            srv.apply_buckets(undo)
            return True, {"buckets": list(srv.buckets)}
        if action == "downshift_dtype":
            srv.apply_compute(undo)
            return True, {"to": undo}
        if action == "degrade_capacity":
            frm = srv.sup.entry.key
            if not srv.request_promote():
                # Sentinel-refused grow-back (sup_promote_refused is
                # already journaled): stay degraded, attributably.
                return False, {"cause": "promotion refused", "frm": frm}
            return True, {"frm": frm, "to": srv.sup.entry.key}
        raise ValueError(f"unknown rung {action!r}")

    @off_timed_path
    def _screen_dtype(self, compute: str):
        """ToleranceGate screen of the downshift candidate against the
        fp32 oracle on the sentinel input — the same no-silent-adoption
        contract the autotuner and the supervisor's promotion verify
        under. Pass/fail journals through the gate itself
        (``gate_pass``/``gate_fail`` with this key)."""
        from ..models.init import deterministic_input
        from ..precision.gate import ToleranceGate

        gate = ToleranceGate(journal=self.server.journal)
        return gate.screen(
            compute,
            self.server._params,
            deterministic_input(1, self.server._model_cfg()),
            model_cfg=self.server._model_cfg(),
            key=f"controller:{compute}",
        )

    # ------------------------------------------------------------ reporting

    @off_timed_path
    def _journal_action(
        self,
        action: str,
        target: str,
        sig: ControllerSignals,
        now: float,
        *,
        actuated: bool,
        reversal: bool,
        ms: float,
        **extra,
    ) -> dict:
        self._seq += 1
        cfg = self.cfg
        rec = {
            "action": action,
            "target": target,
            "actuated": actuated,
            "reversal": reversal,
            "level": self.level,
            "ms": round(ms, 3),
            "evidence": {
                **sig.to_obj(),
                "burn_high": cfg.burn_high,
                "burn_low": cfg.burn_low,
                "knee_frac": cfg.knee_frac,
                "cooldown_s": cfg.cooldown_s,
                "min_dwell_s": cfg.min_dwell_s,
                "since_last_action_s": (
                    round(now - self._last_action_t, 3)
                    if self._last_action_t is not None
                    else None
                ),
                "dwell_s": (
                    round(now - self._level_enter_t, 3)
                    if self._level_enter_t is not None
                    else None
                ),
            },
            **extra,
        }
        self.action_counts[action] = self.action_counts.get(action, 0) + 1
        self._last_action = {**rec, "t": now}
        from ..observability.metrics import registry as metrics_registry

        metrics_registry().counter("serve.controller_actions").inc()
        self.server._journal(
            "controller_action", key=f"ctl:{self._seq}", **rec
        )
        return rec

    def state_obj(self, now: Optional[float] = None) -> dict:
        """Cross-thread state snapshot for ``/healthz``/``/stats`` — the
        router probes read this to see degraded-but-healthy instead of
        inferring it from latency."""
        if now is None:
            now = time.monotonic()
        last = None
        if self._last_action is not None:
            last = {
                k: self._last_action[k]
                for k in ("action", "target", "actuated", "reversal", "level")
            }
            last["age_s"] = round(now - self._last_action["t"], 3)
        # Fleet intent (ISSUE 20): the controller's own verdict over its
        # last evaluated signals — what the FleetController arbitrates
        # on. None until the first evaluation (or with no SLO policy).
        sig, sig_t = self._sig_cache, self._sig_t
        intent = None
        if sig is not None and sig_t is not None:
            b = sig.burn.get(self.cfg.protected_cls)
            intent = {
                "overloaded": self._overloaded(sig),
                "calm": self._calm(sig),
                "burn": round(b, 3) if b is not None else None,
                "depth": sig.depth,
                "oldest_wait_ms": round(sig.oldest_wait_ms, 3),
                "age_s": round(now - sig_t, 3),
                "idle_s": (
                    round(now - self._last_outcome_t, 3)
                    if self._last_outcome_t is not None
                    else None
                ),
            }
        return {
            "mode": self.mode,
            "level": self.level,
            "rung": self._applied[-1][1] if self._applied else None,
            "overrides": [
                {"action": a, "target": t} for _, a, t, _ in self._applied
            ],
            "last_action": last,
            "actions": dict(self.action_counts),
            "intent": intent,
        }

    def summary(self) -> str:
        """One machine-parseable line (run CLI: ``Controller: ...``)."""
        acts = ",".join(
            f"{k}={v}" for k, v in sorted(self.action_counts.items())
        ) or "none"
        return f"mode={self.mode} level={self.level} actions={acts}"


# Escalation -> reversal action names (the journal's vocabulary).
_REVERSALS = {
    "tighten_admission": "relax_admission",
    "narrow_buckets": "widen_buckets",
    "downshift_dtype": "upshift_dtype",
    "degrade_capacity": "promote_capacity",
}


def _finite(v: float) -> Optional[float]:
    """JSON-safe margin (the gate reports -inf on an oracle fault)."""
    return round(v, 6) if v == v and abs(v) != float("inf") else None

"""Admission queue: per-request deadlines, FIFO pop, explicit shedding.

Requests enter through :meth:`AdmissionQueue.submit`, which returns a
:class:`RequestHandle` the caller waits on. The dispatch loop pops FIFO
prefixes with :meth:`AdmissionQueue.pop_ready`, which *returns* the
requests it sheds alongside the ones it takes — a shed request always
completes its handle with status ``SHED`` and is handed back for
journaling, never silently dropped (the same no-silent-loss contract as
PR 1's ``DegradedEvent``). Two shed causes, both attributable
(``Request.shed_reason``): the request's hard deadline expired
(``"deadline"`` — PR 6), or an installed :class:`~.slo.SLOPolicy` ruled
its class SLO blown (``"slo"`` — shed by class, not just by age).

Saturation is observable BEFORE the first shed: :meth:`AdmissionQueue.
stats` returns :class:`QueueStats` with the FIFO head's age
(``oldest_wait_ms``), depth, pending images, and per-class depths — the
gauges the server mirrors into the metrics registry each dispatch step.

Stdlib + numpy only (no jax import) so tests and the load generator pay
nothing to exercise queue semantics; ``Deadline`` is PR 1's monotonic
budget vocabulary, reused unchanged.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple

import numpy as np

from ..resilience.policy import Deadline

# Terminal request statuses. PENDING is the only non-terminal state; a
# handle's status moves exactly once, under the completing thread.
PENDING = "PENDING"
OK = "OK"
SHED = "SHED"  # deadline expired before dispatch — explicit, journaled
FAILED = "FAILED"  # dispatch raised even after the supervisor's ladder


class QueueFull(RuntimeError):
    """Admission refused: backpressure, not silent buffering to OOM."""


class RequestHandle:
    """Caller-facing completion handle for one submitted request."""

    def __init__(self, rid: str, n_images: int, cls: str = ""):
        self.rid = rid
        self.n_images = n_images
        self.cls = cls  # request class ("" = unclassed, never SLO-shed)
        self.status = PENDING
        self.result: Optional[np.ndarray] = None
        self.error = ""
        self.submitted_at = time.monotonic()
        self.completed_at: Optional[float] = None
        self._done = threading.Event()

    def _complete(self, status: str, result=None, error: str = "") -> None:
        self.status = status
        self.result = result
        self.error = error
        self.completed_at = time.monotonic()
        self._done.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self._done.wait(timeout_s)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def latency_ms(self) -> Optional[float]:
        """submit -> complete wall latency (the user-visible number the
        serve bench reports percentiles of); None while pending."""
        if self.completed_at is None:
            return None
        return (self.completed_at - self.submitted_at) * 1e3


@dataclasses.dataclass
class Request:
    """One queued unit of work: ``x`` is a host-side (n, H, W, C) array."""

    rid: str
    x: np.ndarray
    deadline: Deadline
    handle: RequestHandle
    cls: str = ""  # request class (SLO policy + journal attribution)
    shed_reason: str = ""  # "deadline" | "slo" once shed (journal field)

    @property
    def n_images(self) -> int:
        return int(self.x.shape[0])

    @property
    def waited_ms(self) -> float:
        return (time.monotonic() - self.handle.submitted_at) * 1e3


@dataclasses.dataclass(frozen=True)
class QueueStats:
    """One lock-held snapshot of queue saturation — readable BEFORE the
    first shed (the ``oldest_wait_ms`` gauge is the early-warning number:
    it climbs toward the tightest class SLO while every request is still
    servable)."""

    depth: int  # pending requests
    pending_images: int  # pending work in images (the dispatch unit)
    oldest_wait_ms: float  # age of the FIFO head; 0.0 when empty
    per_class: Dict[str, int]  # pending requests per class name

    def to_obj(self) -> dict:
        return {
            "depth": self.depth,
            "pending_images": self.pending_images,
            "oldest_wait_ms": round(self.oldest_wait_ms, 3),
            "per_class": dict(self.per_class),
        }


class AdmissionQueue:
    """Thread-safe FIFO with bounded depth and deadline/SLO-aware popping.

    ``slo`` is an optional :class:`~.slo.SLOPolicy`: when installed,
    :meth:`pop_ready` also sheds requests whose class SLO is already
    blown by their queue wait (``shed_reason="slo"``) — per-class
    admission control that activates only under saturation."""

    def __init__(self, max_pending: int = 1024, slo=None):
        self.max_pending = max_pending
        self.slo = slo
        self._pending: Deque[Request] = deque()
        self._pending_images = 0
        self._per_class: Dict[str, int] = {}
        self._cv = threading.Condition()
        self._seq = 0

    def __len__(self) -> int:
        with self._cv:
            return len(self._pending)

    def stats(self) -> QueueStats:
        """Saturation gauges under one lock hold (O(1) + per-class dict
        copy); the server mirrors these into the metrics registry."""
        with self._cv:
            oldest = (
                self._pending[0].waited_ms if self._pending else 0.0
            )
            return QueueStats(
                depth=len(self._pending),
                pending_images=self._pending_images,
                oldest_wait_ms=oldest,
                per_class={k: v for k, v in self._per_class.items() if v},
            )

    def submit(
        self,
        x,
        *,
        deadline_s: Optional[float] = None,
        rid: Optional[str] = None,
        cls: str = "",
    ) -> RequestHandle:
        """Admit one request. ``x`` is (H, W, C) or (n, H, W, C); a single
        image is promoted to a 1-batch. Raises :class:`QueueFull` past
        ``max_pending`` — admission control is the caller-visible
        backpressure signal, not an unbounded buffer. ``cls`` names the
        request's traffic class (SLO policy + journal attribution)."""
        x = np.asarray(x)
        if x.ndim == 3:
            x = x[None]
        if x.ndim != 4:
            raise ValueError(f"request input must be (H,W,C) or (n,H,W,C), got {x.shape}")
        with self._cv:
            if len(self._pending) >= self.max_pending:
                raise QueueFull(
                    f"admission queue at max_pending={self.max_pending}"
                )
            self._seq += 1
            rid = rid or f"r{self._seq:06d}"
            handle = RequestHandle(rid, int(x.shape[0]), cls=cls)
            self._pending.append(
                Request(rid, x, Deadline.after(deadline_s), handle, cls=cls)
            )
            self._pending_images += int(x.shape[0])
            self._per_class[cls] = self._per_class.get(cls, 0) + 1
            self._cv.notify_all()
            return handle

    def wait_nonempty(self, timeout_s: float) -> bool:
        """Block until a request is pending (or timeout) — the dispatch
        loop's idle parking spot, so an empty service burns no CPU."""
        with self._cv:
            return self._cv.wait_for(lambda: bool(self._pending), timeout_s)

    def _drop_head(self) -> Request:
        req = self._pending.popleft()
        self._pending_images -= req.n_images
        self._per_class[req.cls] = self._per_class.get(req.cls, 1) - 1
        return req

    def pop_ready(self, max_images: int) -> Tuple[List[Request], List[Request]]:
        """Pop a FIFO prefix of live requests totaling <= ``max_images``
        images, shedding every unservable request encountered on the way:
        hard-deadline expiry (``shed_reason="deadline"``) and, with an
        installed SLO policy, class-SLO blow-out (``shed_reason="slo"`` —
        the request's queue wait already exceeds its class latency
        budget, so dispatching it would only burn a batch slot that
        pushes the next request over too).

        Returns ``(taken, shed)``. Shed handles are completed with status
        ``SHED`` *here* (the caller stops waiting immediately) and the
        requests are returned so the server journals each one — counted,
        attributed, never silently dropped. FIFO order is preserved: the
        first live request that does not fit closes the batch (no
        out-of-order cherry-picking, so no starvation)."""
        taken: List[Request] = []
        shed: List[Request] = []
        images = 0
        with self._cv:
            while self._pending:
                req = self._pending[0]
                if req.deadline.expired:
                    self._drop_head()
                    req.shed_reason = "deadline"
                    req.handle._complete(
                        SHED, error="deadline expired before dispatch"
                    )
                    shed.append(req)
                    continue
                slo_reason = (
                    self.slo.should_shed(req.cls, req.waited_ms)
                    if self.slo is not None
                    else None
                )
                if slo_reason:
                    self._drop_head()
                    req.shed_reason = slo_reason
                    req.handle._complete(
                        SHED,
                        error=(
                            f"class {req.cls or 'default'!r} SLO blown "
                            "before dispatch"
                        ),
                    )
                    shed.append(req)
                    continue
                if images + req.n_images > max_images:
                    break
                self._drop_head()
                taken.append(req)
                images += req.n_images
        return taken, shed

"""Admission queue: per-request deadlines, FIFO pop, explicit shedding.

Requests enter through :meth:`AdmissionQueue.submit`, which returns a
:class:`RequestHandle` the caller waits on. The dispatch loop pops FIFO
prefixes with :meth:`AdmissionQueue.pop_ready`, which *returns* the
deadline-expired requests it sheds alongside the ones it takes — a shed
request always completes its handle with status ``SHED`` and is handed back
for journaling, never silently dropped (the same no-silent-loss contract as
PR 1's ``DegradedEvent``).

Stdlib + numpy only (no jax import) so tests and the load generator pay
nothing to exercise queue semantics; ``Deadline`` is PR 1's monotonic
budget vocabulary, reused unchanged.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Deque, List, Optional, Tuple

import numpy as np

from ..resilience.policy import Deadline

# Terminal request statuses. PENDING is the only non-terminal state; a
# handle's status moves exactly once, under the completing thread.
PENDING = "PENDING"
OK = "OK"
SHED = "SHED"  # deadline expired before dispatch — explicit, journaled
FAILED = "FAILED"  # dispatch raised even after the supervisor's ladder


class QueueFull(RuntimeError):
    """Admission refused: backpressure, not silent buffering to OOM."""


class RequestHandle:
    """Caller-facing completion handle for one submitted request."""

    def __init__(self, rid: str, n_images: int):
        self.rid = rid
        self.n_images = n_images
        self.status = PENDING
        self.result: Optional[np.ndarray] = None
        self.error = ""
        self.submitted_at = time.monotonic()
        self.completed_at: Optional[float] = None
        self._done = threading.Event()

    def _complete(self, status: str, result=None, error: str = "") -> None:
        self.status = status
        self.result = result
        self.error = error
        self.completed_at = time.monotonic()
        self._done.set()

    def wait(self, timeout_s: Optional[float] = None) -> bool:
        return self._done.wait(timeout_s)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def latency_ms(self) -> Optional[float]:
        """submit -> complete wall latency (the user-visible number the
        serve bench reports percentiles of); None while pending."""
        if self.completed_at is None:
            return None
        return (self.completed_at - self.submitted_at) * 1e3


@dataclasses.dataclass
class Request:
    """One queued unit of work: ``x`` is a host-side (n, H, W, C) array."""

    rid: str
    x: np.ndarray
    deadline: Deadline
    handle: RequestHandle

    @property
    def n_images(self) -> int:
        return int(self.x.shape[0])


class AdmissionQueue:
    """Thread-safe FIFO with bounded depth and deadline-aware popping."""

    def __init__(self, max_pending: int = 1024):
        self.max_pending = max_pending
        self._pending: Deque[Request] = deque()
        self._cv = threading.Condition()
        self._seq = 0

    def __len__(self) -> int:
        with self._cv:
            return len(self._pending)

    def submit(
        self,
        x,
        *,
        deadline_s: Optional[float] = None,
        rid: Optional[str] = None,
    ) -> RequestHandle:
        """Admit one request. ``x`` is (H, W, C) or (n, H, W, C); a single
        image is promoted to a 1-batch. Raises :class:`QueueFull` past
        ``max_pending`` — admission control is the caller-visible
        backpressure signal, not an unbounded buffer."""
        x = np.asarray(x)
        if x.ndim == 3:
            x = x[None]
        if x.ndim != 4:
            raise ValueError(f"request input must be (H,W,C) or (n,H,W,C), got {x.shape}")
        with self._cv:
            if len(self._pending) >= self.max_pending:
                raise QueueFull(
                    f"admission queue at max_pending={self.max_pending}"
                )
            self._seq += 1
            rid = rid or f"r{self._seq:06d}"
            handle = RequestHandle(rid, int(x.shape[0]))
            self._pending.append(
                Request(rid, x, Deadline.after(deadline_s), handle)
            )
            self._cv.notify_all()
            return handle

    def wait_nonempty(self, timeout_s: float) -> bool:
        """Block until a request is pending (or timeout) — the dispatch
        loop's idle parking spot, so an empty service burns no CPU."""
        with self._cv:
            return self._cv.wait_for(lambda: bool(self._pending), timeout_s)

    def pop_ready(self, max_images: int) -> Tuple[List[Request], List[Request]]:
        """Pop a FIFO prefix of live requests totaling <= ``max_images``
        images, shedding every expired request encountered on the way.

        Returns ``(taken, shed)``. Shed handles are completed with status
        ``SHED`` *here* (the caller stops waiting immediately) and the
        requests are returned so the server journals each one — counted,
        attributed, never silently dropped. FIFO order is preserved: the
        first live request that does not fit closes the batch (no
        out-of-order cherry-picking, so no starvation)."""
        taken: List[Request] = []
        shed: List[Request] = []
        images = 0
        with self._cv:
            while self._pending:
                req = self._pending[0]
                if req.deadline.expired:
                    self._pending.popleft()
                    req.handle._complete(
                        SHED, error="deadline expired before dispatch"
                    )
                    shed.append(req)
                    continue
                if images + req.n_images > max_images:
                    break
                self._pending.popleft()
                taken.append(req)
                images += req.n_images
        return taken, shed

"""Continuous-batching inference service (docs/SERVING.md).

The forward path grown into a serving loop: an admission queue with
per-request deadlines and class-aware SLO shedding (``queue``, ``slo``),
bucketed batch assembly over a fixed padded-shape set so the persistent
compile cache is hit, never missed (``batcher``), a dispatch loop
wrapping ``configs.build_forward`` — or the PR 5 elastic supervisor as
the in-service degradation ladder — that journals every batch
(``server``), a load generator with Poisson AND traffic-shaped arrivals
plus latency-percentile reporting and the saturation sweep (``loadgen``,
``traffic``), the HTTP network front end over the admission queue with
its threaded client fleet (``frontend``), the journaled closed-loop
Autopilot that walks a fixed degrade/restore ladder off live burn-rate
and queue-knee signals (``controller``), and the fleet tier above N of
those: a deterministic crc32 router with retry-with-redirect and
probe-driven backend hysteresis (``router``) over N real backend
processes spawned/killed/restarted across a process boundary
(``fleet`` — the ``host_loss`` chaos drill's stage).

Layering rule: ``queue``/``batcher``/``loadgen``/``traffic``/``slo`` are
stdlib+numpy only (no jax import — the same rule as
``resilience.policy``); only ``server`` pays the backend import, at
dispatch-build time, and ``frontend`` rides on ``server``.
``controller`` is import-light too — the ToleranceGate screen and the
shared error-budget constant are imported lazily at actuation time. ``router``
is stdlib-ONLY (transport and policy, never compute); ``fleet``'s
parent half is stdlib-only too — the jax import happens in the spawned
child processes.
"""

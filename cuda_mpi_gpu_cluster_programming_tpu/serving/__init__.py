"""Continuous-batching inference service (docs/SERVING.md).

The forward path grown into a serving loop: an admission queue with
per-request deadlines (``queue``), bucketed batch assembly over a fixed
padded-shape set so the persistent compile cache is hit, never missed
(``batcher``), a dispatch loop wrapping ``configs.build_forward`` — or the
PR 5 elastic supervisor as the in-service degradation ladder — that
journals every batch (``server``), and a Poisson load generator with
latency-percentile reporting (``loadgen``).

Layering rule: ``queue``/``batcher``/``loadgen`` are stdlib+numpy only (no
jax import — the same rule as ``resilience.policy``); only ``server`` pays
the backend import, at dispatch-build time.
"""

"""Continuous-batching inference service (docs/SERVING.md).

The forward path grown into a serving loop: an admission queue with
per-request deadlines and class-aware SLO shedding (``queue``, ``slo``),
bucketed batch assembly over a fixed padded-shape set so the persistent
compile cache is hit, never missed (``batcher``), a dispatch loop
wrapping ``configs.build_forward`` — or the PR 5 elastic supervisor as
the in-service degradation ladder — that journals every batch
(``server``), a load generator with Poisson AND traffic-shaped arrivals
plus latency-percentile reporting and the saturation sweep (``loadgen``,
``traffic``), and the HTTP network front end over the admission queue
with its threaded client fleet (``frontend``).

Layering rule: ``queue``/``batcher``/``loadgen``/``traffic``/``slo`` are
stdlib+numpy only (no jax import — the same rule as
``resilience.policy``); only ``server`` pays the backend import, at
dispatch-build time, and ``frontend`` rides on ``server``.
"""

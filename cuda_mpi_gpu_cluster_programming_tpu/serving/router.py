"""Fleet router: the tier above N serving front ends (docs/SERVING.md).

One :class:`ServingFrontend` serves one :class:`InferenceServer`; this
module is the layer ROADMAP item 3 names above it — a
:class:`FleetRouter` (stdlib ``ThreadingHTTPServer``, the same
no-new-deps rule as the front end) that speaks the front end's exact
wire contract northbound and proxies southbound to N backends, each a
separate OS process (``serving.fleet`` spawns them). Three disciplines,
all reused from earlier subsystems rather than invented here:

- **Routing is a hash, not a choice.** A request's home backend is
  ``crc32(rid) % N`` — the same deterministic crc32-of-rid discipline
  the replay scheduler uses (``observability/replay.py``), no RNG
  anywhere on the routing path. Spillover (when the home backend is not
  routable, or refuses) walks the remaining backends in a
  class+rid-salted crc32 order, so two runs over one journal route
  byte-identically. Classes listed in ``no_spill_classes`` (default:
  ``bulk``, whose requests are largest-bucket and deadline-less) never
  spill: with their home backend unroutable they are counted
  ``unroutable`` — a first-class verdict, never a silent drop.
- **Health is hysteretic, not a boolean.** A probe loop polls each
  backend's existing ``GET /healthz`` (+ a ``GET /metrics`` scrape, so
  the Prometheus surface stays exercised and journaled per probe) and
  drives a per-backend state machine with the ElasticPool's anti-flap
  rules (``parallel/elastic.py``): ``fail_k`` consecutive probe
  failures take a backend **up → down** (journaled, with the detect
  latency attributed); a down backend that answers again enters
  **probation** and re-admits only after ``readmit_m`` clean probes
  (mirroring ``mesh_probation``); ``quarantine_flaps`` heals inside
  ``flap_window_s`` quarantine it **sticky** (mirroring
  ``mesh_quarantine``) — a flapping host cannot oscillate the fleet.
  A request-path connection failure is fed to the same machine as a
  probe failure, so detection never waits out the probe interval.
- **A redirect is journaled, never silent.** On 429 (backpressure),
  504 (shed), or a connection failure the router retries the request on
  the next candidate under the PR 1 ``RetryPolicy`` backoff+jitter,
  with the request's own resolved deadline as the retry budget
  (``Deadline.remaining`` clamps every pause and every hop timeout).
  Every hop writes a ``router_redirect`` record (from/to/attempt/
  reason); the final verdict writes ``router_route``. Per-class
  accounting closes AT THE ROUTER: ``ok + shed + failed + rejected +
  unroutable == offered`` (:class:`RouterClassStats` — the PR 11
  identity grown one bucket).

When ``RouterConfig.fleet`` is set, the probe sweep also feeds a
:class:`~.fleet_controller.FleetController` (ISSUE 20): each probe
parses the backend's ``/healthz`` controller sub-object (ladder rung,
protected burn, intent, queue depth) into its ``BackendSlot`` and
journals a ``router_probe`` record; the controller arbitrates across
backends — staggered downshift tokens, drain-vs-shed, forecast
pre-actuation — actuating through :meth:`FleetRouter.set_drained`
(drained backends keep probing but receive no routed traffic; their
home load spills) and :meth:`FleetRouter.set_preshed` (listed classes
are pre-shed at the router with 429, counted ``rejected`` on both
ledgers so accounting stays closed).

Journals: the router writes its own (``router_config`` /
``router_route`` / ``router_redirect`` / ``router_backend_state`` /
``router_probe`` and, fleet-controlled, ``fleet_action`` /
``fleet_refusal``); each
backend keeps writing its own. ``observability.export.load_records`` on
the shared directory stitches all of them into one Perfetto timeline,
and ``observability.health`` folds backend-down windows into
:class:`~..observability.health.Incident` rows (phases detect → drain →
redirect → readmit, summing exactly to the incident wall).

Layering: stdlib-only (no jax, no numpy) — the router is transport and
policy, never compute; it must import nothing heavier than the front
end's client half does.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlparse

from ..observability.metrics import registry as metrics_registry
from ..observability.trace import off_timed_path
from ..resilience.journal import Journal
from ..resilience.policy import Deadline, RetryPolicy
from .fleet_controller import FleetController, FleetControllerConfig
from .traffic import ClassStats, _fmt_ms

# Backend states (the ElasticPool discipline, per process instead of per
# device): routable traffic goes to UP only — a probation backend earns
# readmission through clean PROBES, not through live requests.
UP = "up"
PROBATION = "probation"
DOWN = "down"
QUARANTINED = "quarantined"
ROUTABLE = (UP,)

# Wire verdicts the router retries elsewhere (ISSUE 16 contract): queue
# backpressure, shed, and transport failure. Everything else is a
# definitive per-request verdict and forwards to the client as-is.
_RETRY_CODES = (429, 504)
_CONN_FAIL = -1  # connection refused/reset/timeout pseudo-code


@dataclasses.dataclass(frozen=True)
class RouterConfig:
    """Fleet routing + hysteresis knobs. ``fail_k``/``readmit_m``/
    ``quarantine_flaps``/``flap_window_s`` mirror the ElasticPool's
    ``quarantine_flaps``/``probation_steps``/``flap_window`` semantics;
    ``retry`` is the PR 1 policy whose backoff paces redirects (its
    ``max_retries`` bounds attempts per request, the request deadline
    bounds them in time)."""

    probe_interval_s: float = 0.5
    probe_timeout_s: float = 2.0
    fail_k: int = 3
    readmit_m: int = 3
    quarantine_flaps: int = 3
    flap_window_s: float = 60.0
    retry: RetryPolicy = dataclasses.field(
        default_factory=lambda: RetryPolicy(
            max_retries=4, base_delay_s=0.05, max_delay_s=0.5, jitter=0.1
        )
    )
    default_deadline_s: Optional[float] = None
    no_spill_classes: Tuple[str, ...] = ("bulk",)
    max_wait_s: float = 120.0  # per-hop response-wait cap
    journal_path: Optional[str] = None
    # Fleet control plane (ISSUE 20): when set, the probe sweep feeds a
    # FleetController that arbitrates degradation across backends.
    fleet: Optional[FleetControllerConfig] = None


@dataclasses.dataclass
class BackendSlot:
    """One backend's routing identity + health-machine state."""

    index: int
    name: str
    url: str
    state: str = UP
    consec_fail: int = 0
    clean_probes: int = 0
    flaps: List[float] = dataclasses.field(default_factory=list)
    first_fail: Optional[float] = None  # clock of the streak's first miss
    down_since: Optional[float] = None
    probation_since: Optional[float] = None
    # Fleet-drain flag (ISSUE 20): a drained backend keeps its health
    # state and keeps probing, but _pick skips it — home traffic spills
    # exactly like probation.
    drained: bool = False
    # Scraped controller state from the last successful probe (None on
    # backends without an Autopilot — pre-20 /healthz payloads).
    ctl_level: Optional[int] = None
    ctl_mode: Optional[str] = None
    ctl_burn: Optional[float] = None
    ctl_overloaded: Optional[bool] = None
    queue_depth: Optional[int] = None

    @property
    def host_port(self) -> Tuple[str, int]:
        p = urlparse(self.url)
        return p.hostname or "127.0.0.1", int(p.port or 80)


class RouterClassStats(ClassStats):
    """Per-class accounting with the router's fifth bucket: a request
    whose candidate set is empty is ``unroutable`` — refused with HTTP
    503 and counted, so the closed identity survives fleet-wide outage
    instead of leaking requests."""

    def __init__(self) -> None:
        super().__init__()
        self.unroutable = 0

    @property
    def closed(self) -> bool:
        return (
            self.ok + self.shed + self.failed + self.rejected + self.unroutable
            == self.offered
        )

    def to_obj(self) -> dict:
        obj = super().to_obj()
        obj["unroutable"] = self.unroutable
        return obj


@dataclasses.dataclass
class RouterReport:
    """Router-side closed accounting snapshot (client-side reports from
    ``http_fleet_load`` see an ``unroutable`` 503 as ``failed`` — both
    ledgers close, the router's is the attributed one)."""

    per_class: Dict[str, RouterClassStats]
    redirects: int
    backends: Dict[str, str]  # name -> state
    duration_s: float = 0.0

    def _total(self, field: str) -> int:
        return sum(getattr(c, field) for c in self.per_class.values())

    @property
    def n_offered(self) -> int:
        return self._total("offered")

    @property
    def n_unroutable(self) -> int:
        return self._total("unroutable")

    @property
    def closed(self) -> bool:
        return all(c.closed for c in self.per_class.values())

    def all_latencies(self) -> List[float]:
        out: List[float] = []
        for c in self.per_class.values():
            out.extend(c.latencies_ms)
        return out

    def summary(self) -> str:
        """Machine-parseable 'Route:' payload (run CLI contract)."""
        from .loadgen import percentile

        lat = self.all_latencies()
        states = " ".join(
            f"{n}={s}" for n, s in sorted(self.backends.items())
        )
        return (
            f"reqs={self.n_offered} ok={self._total('ok')} "
            f"shed={self._total('shed')} failed={self._total('failed')} "
            f"rejected={self._total('rejected')} "
            f"unroutable={self.n_unroutable} redirects={self.redirects} "
            f"p50_ms={_fmt_ms(percentile(lat, 50))} "
            f"p99_ms={_fmt_ms(percentile(lat, 99))} "
            f"closed={self.closed} {states}"
        )

    def class_lines(self) -> List[str]:
        out = []
        for name in sorted(self.per_class):
            c = self.per_class[name]
            out.append(
                f"Route class: name={name or 'default'} offered={c.offered} "
                f"ok={c.ok} shed={c.shed} failed={c.failed} "
                f"rejected={c.rejected} unroutable={c.unroutable}"
            )
        return out

    def to_obj(self) -> dict:
        return {
            "classes": {
                (n or "default"): c.to_obj() for n, c in self.per_class.items()
            },
            "redirects": self.redirects,
            "backends": dict(self.backends),
            "accounting_closed": self.closed,
        }


@dataclasses.dataclass
class RouteResult:
    code: int
    body: bytes
    verdict: str  # ok|shed|failed|rejected|unroutable
    backend: str  # final backend name ("" when unroutable)
    attempts: int
    redirects: int


class _RouterHandler(BaseHTTPRequestHandler):
    """One northbound HTTP exchange. ``router`` is bound per-FleetRouter
    via a subclass (the front end's extension idiom)."""

    router: "FleetRouter"  # bound in FleetRouter.__init__
    server_version = "tpu-serve-router/1"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args) -> None:
        pass  # the journal is the access log

    def _send_json(self, code: int, payload: dict) -> None:
        self._send_raw(code, json.dumps(payload).encode())

    def _send_raw(self, code: int, body: bytes) -> None:
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if code == 429:
            self.send_header("Retry-After", "1")
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:
        ro = self.router
        if self.path == "/healthz":
            with ro._lock:
                states = {s.name: s.state for s in ro.slots}
            up = sum(1 for v in states.values() if v in ROUTABLE)
            self._send_json(
                200 if up else 503,
                {
                    "status": "ok" if up else "unroutable",
                    "routable": up,
                    "backends": states,
                },
            )
        elif self.path == "/stats":
            payload = ro.report().to_obj()
            if ro.fleet_controller is not None:
                payload["fleet"] = ro.fleet_controller.state_obj()
            self._send_json(200, payload)
        else:
            self._send_json(404, {"error": f"no route {self.path!r}"})

    def do_POST(self) -> None:
        if self.path != "/v1/infer":
            self._send_json(404, {"error": f"no route {self.path!r}"})
            return
        ro = self.router
        t0 = time.monotonic()
        try:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length)
            req = json.loads(raw or b"{}")
            if not isinstance(req, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, KeyError) as e:
            self._send_json(
                400, {"status": "REJECTED", "error": f"bad request: {e}"}
            )
            ro._finish("", "", t0, "rejected", 400, "", 0, 0, 0)
            return
        rid = str(req.get("rid") or "")
        if not rid:
            # Routing needs a rid (it IS the hash key); assign a
            # sequential one and re-encode so backend journals carry it.
            rid = ro._next_rid()
            req["rid"] = rid
            raw = json.dumps(req).encode()
        cls = str(req.get("class", ""))
        try:
            deadline_s = float(req["deadline_s"]) if req.get("deadline_s") else None
        except (TypeError, ValueError):
            deadline_s = None  # backend 400s the malformed body
        shape = req.get("shape")
        n_images = (
            int(shape[0]) if isinstance(shape, list) and len(shape) == 4 else 1
        )
        res = ro.route(rid, cls, deadline_s, raw)
        self._send_raw(res.code, res.body)
        ro._finish(
            rid, cls, t0, res.verdict, res.code, res.backend,
            res.attempts, res.redirects, n_images if res.verdict == "ok" else 0,
        )


class FleetRouter:
    """Deterministic consistent-hash router over N backend front ends.

    ``backends`` is the stable-order url list (position = routing
    index — restarts swap a slot's url via :meth:`replace_backend`,
    never its position, so the hash ring is stable across host loss).
    ``clock`` is injectable so the flap-window hysteresis is testable
    without real waiting; tests drive :meth:`probe_once` directly with
    ``probe_interval_s=0`` (no probe thread).
    """

    def __init__(
        self,
        backends: Sequence[str],
        cfg: RouterConfig = RouterConfig(),
        *,
        port: int = 0,
        host: str = "127.0.0.1",
        clock=time.monotonic,
    ):
        if not backends:
            raise ValueError("FleetRouter needs at least one backend url")
        self.cfg = cfg
        self._clock = clock
        self._t0 = clock()
        self.journal = (
            Journal(cfg.journal_path) if cfg.journal_path else None
        )
        self.slots = [
            BackendSlot(i, f"b{i}", url) for i, url in enumerate(backends)
        ]
        self._lock = threading.Lock()
        self.stats: Dict[str, RouterClassStats] = {}
        self.redirects = 0
        self.http_codes: Dict[int, int] = {}
        self._seq = 0
        self._started_at = clock()
        handler = type("BoundRouterHandler", (_RouterHandler,), {"router": self})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None
        self._probe_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        # Fleet control plane (ISSUE 20): evaluated from probe_once, owns
        # no thread. Classes in _preshed are refused 429 at the router
        # (tuple assignment is atomic — read lock-free on the hot path).
        self._preshed: Tuple[str, ...] = ()
        self.fleet_controller: Optional[FleetController] = None
        if cfg.fleet is not None:
            fc = (
                cfg.fleet
                if isinstance(cfg.fleet, FleetControllerConfig)
                else FleetControllerConfig.from_obj(dict(cfg.fleet))
            )
            self.fleet_controller = FleetController(self, fc)
        self._journal_append(
            "router_config",
            key="router",
            n_backends=len(self.slots),
            backends=[{"name": s.name, "url": s.url} for s in self.slots],
            fail_k=cfg.fail_k,
            readmit_m=cfg.readmit_m,
            quarantine_flaps=cfg.quarantine_flaps,
            flap_window_s=cfg.flap_window_s,
            probe_interval_s=cfg.probe_interval_s,
            retry=dataclasses.asdict(cfg.retry),
            no_spill_classes=list(cfg.no_spill_classes),
            t_ms=self._t_ms(),
            **(
                {"fleet": self.fleet_controller.cfg.to_obj()}
                if self.fleet_controller is not None
                else {}
            ),
        )

    # ------------------------------------------------------------ lifecycle

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "FleetRouter":
        if self._thread is not None:
            raise RuntimeError("router already started")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router", daemon=True
        )
        self._thread.start()
        if self.cfg.probe_interval_s > 0:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="router-probe", daemon=True
            )
            self._probe_thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop_evt.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(10.0)
        if self._probe_thread is not None:
            self._probe_thread.join(10.0)
            self._probe_thread = None
        self._thread = None

    def _t_ms(self) -> float:
        return round((self._clock() - self._t0) * 1e3, 3)

    def _next_rid(self) -> str:
        with self._lock:
            self._seq += 1
            return f"rt{self._seq:06d}"

    def _journal_append(self, kind: str, **payload) -> None:
        if self.journal is not None:
            self.journal.append(kind, **payload)

    # -------------------------------------------------------------- probing

    def _probe_loop(self) -> None:
        while not self._stop_evt.wait(self.cfg.probe_interval_s):
            self.probe_once()

    def probe_once(self) -> None:
        """One synchronous sweep over all backends (the probe thread's
        body; tests call it directly to step the machine without a
        clock)."""
        for slot in self.slots:
            if slot.state == QUARANTINED:
                continue  # sticky: a quarantined host needs an operator
            ok, ms, why, scrape = self._probe(slot)
            self._note_probe(slot, ok, ms, why)
            self._note_scrape(slot, ok, ms, scrape)
        if self.fleet_controller is not None:
            self.fleet_controller.evaluate(self._clock())

    def _probe(
        self, slot: BackendSlot
    ) -> Tuple[bool, float, str, Optional[dict]]:
        host, port = slot.host_port
        t0 = time.monotonic()
        try:
            conn = http.client.HTTPConnection(
                host, port, timeout=self.cfg.probe_timeout_s
            )
            try:
                conn.request("GET", "/healthz")
                # The probe MEASURES backend responsiveness around its own
                # socket wait — blocking here is the health signal.
                resp = conn.getresponse()  # noqa: blocking-socket-call-in-timed-region
                body = json.loads(resp.read() or b"{}")
                if resp.status != 200 or body.get("status") != "ok":
                    return False, (time.monotonic() - t0) * 1e3, (
                        f"healthz:{resp.status}"
                    ), None
                # The metrics scrape rides every probe: the Prometheus
                # surface stays exercised (and journaled backend-side as a
                # serve_transport record) and a wedged exporter is a
                # health failure, not a monitoring gap.
                conn.request("GET", "/metrics")
                m = conn.getresponse()  # noqa: blocking-socket-call-in-timed-region
                m.read()
                if m.status != 200:
                    return False, (time.monotonic() - t0) * 1e3, (
                        f"metrics:{m.status}"
                    ), None
            finally:
                conn.close()
        except (OSError, http.client.HTTPException, ValueError) as e:
            return False, (time.monotonic() - t0) * 1e3, (
                f"conn:{type(e).__name__}"
            ), None
        return True, (time.monotonic() - t0) * 1e3, "", body

    def _note_probe(
        self, slot: BackendSlot, ok: bool, ms: float, why: str
    ) -> None:
        """Advance one backend's state machine on a probe verdict (also
        fed by request-path connection failures — detection must not
        wait out the probe interval). Transitions journal
        ``router_backend_state``; the rules mirror ElasticPool:
        ``fail_k`` misses down a backend, a heal enters probation (and
        counts a flap — ``quarantine_flaps`` inside ``flap_window_s``
        quarantine it sticky), ``readmit_m`` clean probes re-admit."""
        now = self._clock()
        event = None  # (frm, to, reason, extra) journaled outside the lock
        with self._lock:
            if slot.state == QUARANTINED:
                return
            if ok:
                slot.consec_fail, slot.first_fail = 0, None
                if slot.state == DOWN:
                    slot.flaps = [
                        t
                        for t in slot.flaps
                        if now - t <= self.cfg.flap_window_s
                    ]
                    slot.flaps.append(now)
                    if len(slot.flaps) >= self.cfg.quarantine_flaps:
                        slot.state = QUARANTINED
                        event = (
                            DOWN, QUARANTINED, "flap",
                            {
                                "flaps": len(slot.flaps),
                                "window_s": self.cfg.flap_window_s,
                            },
                        )
                    else:
                        slot.state = PROBATION
                        slot.clean_probes = 0
                        slot.probation_since = now
                        event = (
                            DOWN, PROBATION, "heal",
                            {"probes_needed": self.cfg.readmit_m},
                        )
                elif slot.state == PROBATION:
                    slot.clean_probes += 1
                    if slot.clean_probes >= self.cfg.readmit_m:
                        slot.state = UP
                        prob_ms = (now - (slot.probation_since or now)) * 1e3
                        down_ms = (now - (slot.down_since or now)) * 1e3
                        slot.down_since = slot.probation_since = None
                        event = (
                            PROBATION, UP, "readmit",
                            {
                                "clean_probes": slot.clean_probes,
                                "probation_ms": round(prob_ms, 3),
                                "down_ms": round(down_ms, 3),
                            },
                        )
            else:
                slot.consec_fail += 1
                if slot.first_fail is None:
                    slot.first_fail = now
                if slot.state == PROBATION:
                    # A miss during probation resets the clean streak and
                    # sends the backend back down — the original
                    # down_since survives, so the incident wall covers
                    # the whole outage, not the last bounce.
                    slot.state = DOWN
                    slot.clean_probes = 0
                    event = (PROBATION, DOWN, why or "probe_failed", {})
                elif (
                    slot.state == UP
                    and slot.consec_fail >= self.cfg.fail_k
                ):
                    slot.state = DOWN
                    slot.down_since = slot.first_fail
                    detect_ms = (now - slot.first_fail) * 1e3
                    event = (
                        UP, DOWN, why or "probe_failed",
                        {
                            "consec_fail": slot.consec_fail,
                            "detect_ms": round(detect_ms, 3),
                        },
                    )
        if event is not None:
            frm, to, reason, extra = event
            self._journal_state(slot, frm, to, reason, probe_ms=round(ms, 3), **extra)

    @off_timed_path
    def _journal_state(
        self, slot: BackendSlot, frm: str, to: str, reason: str, **extra
    ) -> None:
        metrics_registry().counter(f"router.backend_{to}").inc()
        self._journal_append(
            "router_backend_state",
            key=f"{slot.name}:{to}",
            backend=slot.name,
            url=slot.url,
            frm=frm,
            to=to,
            reason=reason,
            t_ms=self._t_ms(),
            **extra,
        )

    def _note_scrape(
        self, slot: BackendSlot, ok: bool, ms: float, scrape: Optional[dict]
    ) -> None:
        """Fold one successful probe's scraped ``/healthz`` payload into
        the slot (ISSUE 20): the controller sub-object (ladder rung,
        protected burn, intent) and queue depth become the fleet
        controller's evidence, and every scrape journals a
        ``router_probe`` record. Backends without an Autopilot (pre-20
        payloads) scrape to None fields — the record still carries the
        queue depth, and old journals export unchanged."""
        if not ok:
            return  # the failed probe already journaled its transition
        scrape = scrape or {}
        q = scrape.get("queue")
        ctl = scrape.get("controller")
        with self._lock:
            depth = (q or {}).get("depth")
            slot.queue_depth = depth if isinstance(depth, int) else None
            if isinstance(ctl, dict):
                slot.ctl_level = int(ctl.get("level") or 0)
                slot.ctl_mode = str(ctl.get("mode") or "") or None
                intent = ctl.get("intent")
                if isinstance(intent, dict):
                    burn = intent.get("burn")
                    slot.ctl_burn = (
                        float(burn) if isinstance(burn, (int, float)) else None
                    )
                    slot.ctl_overloaded = bool(intent.get("overloaded"))
                else:
                    slot.ctl_burn = None
                    slot.ctl_overloaded = None
            else:
                slot.ctl_level = None
                slot.ctl_mode = None
                slot.ctl_burn = None
                slot.ctl_overloaded = None
        self._journal_probe(slot, ms)

    @off_timed_path
    def _journal_probe(self, slot: BackendSlot, ms: float) -> None:
        self._journal_append(
            "router_probe",
            key=f"probe:{slot.name}",
            backend=slot.name,
            state=slot.state,
            drained=slot.drained,
            level=slot.ctl_level,
            mode=slot.ctl_mode,
            burn=slot.ctl_burn,
            overloaded=slot.ctl_overloaded,
            depth=slot.queue_depth,
            probe_ms=round(ms, 3),
            t_ms=self._t_ms(),
        )

    def set_drained(self, index: int, drained: bool) -> None:
        """Fleet-drain hook (ISSUE 20): a drained backend keeps its
        health state and keeps probing but receives no routed traffic —
        home load spills exactly like probation. The FleetController's
        ``fleet_action`` record IS the journal entry; this just flips
        the flag."""
        with self._lock:
            self.slots[index].drained = bool(drained)
        metrics_registry().counter(
            "router.drain" if drained else "router.drain_release"
        ).inc()

    def set_preshed(self, classes: Sequence[str]) -> None:
        """Fleet pre-shed hook (ISSUE 20): listed classes are refused
        429 at the router before any forwarding — counted ``rejected``
        on both the router and client ledgers, so accounting stays
        closed while the fleet keeps its capacity for protected
        traffic."""
        self._preshed = tuple(classes)

    def replace_backend(self, index: int, url: str) -> None:
        """Point a slot at a restarted backend's new endpoint. The slot
        keeps its position (the hash ring is stable) and its state — a
        restarted host still re-admits through probation, never
        straight to UP."""
        with self._lock:
            slot = self.slots[index]
            old, slot.url = slot.url, url
        self._journal_append(
            "router_backend_state",
            key=f"{slot.name}:replace",
            backend=slot.name,
            url=url,
            frm=slot.state,
            to=slot.state,
            reason="endpoint_replaced",
            old_url=old,
            t_ms=self._t_ms(),
        )

    def backend_states(self) -> Dict[str, str]:
        with self._lock:
            return {s.name: s.state for s in self.slots}

    # -------------------------------------------------------------- routing

    def home(self, rid: str) -> int:
        return zlib.crc32(rid.encode()) % len(self.slots)

    def candidates(self, rid: str, cls: str) -> List[int]:
        """Deterministic candidate order: the crc32 home first, then —
        for classes allowed to spill — the rest in a class+rid-salted
        crc32 order. Pure function of (rid, cls, N): replayable."""
        home = self.home(rid)
        order = [home]
        if cls not in self.cfg.no_spill_classes:
            order.extend(
                sorted(
                    (i for i in range(len(self.slots)) if i != home),
                    key=lambda i: zlib.crc32(f"{cls}:{rid}:{i}".encode()),
                )
            )
        return order

    def _pick(self, order: List[int], avoid: Optional[int]) -> Optional[int]:
        with self._lock:
            for i in order:
                if (
                    i != avoid
                    and self.slots[i].state in ROUTABLE
                    and not self.slots[i].drained
                ):
                    return i
            # The backend that just refused may be the only routable one
            # left — backpressure clears, so retrying it beats giving up.
            if (
                avoid is not None
                and avoid in order
                and self.slots[avoid].state in ROUTABLE
                and not self.slots[avoid].drained
            ):
                return avoid
        return None

    def _forward(
        self, slot: BackendSlot, body: bytes, dl: Deadline
    ) -> Tuple[int, bytes, str]:
        host, port = slot.host_port
        timeout = max(0.05, dl.remaining(self.cfg.max_wait_s))
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request(
                "POST", "/v1/infer", body, {"Content-Type": "application/json"}
            )
            # The hop wait IS the redirect budget being spent — blocking
            # here is the mechanism, clamped by the request deadline.
            resp = conn.getresponse()  # noqa: blocking-socket-call-in-timed-region
            data = resp.read()
            return resp.status, data, f"http_{resp.status}"
        except (OSError, http.client.HTTPException) as e:
            return _CONN_FAIL, b"", f"conn:{type(e).__name__}"
        finally:
            conn.close()

    def route(
        self, rid: str, cls: str, deadline_s: Optional[float], body: bytes
    ) -> RouteResult:
        """Forward one request: home backend first, then redirect on
        429/504/connection-failure through the candidate walk under the
        RetryPolicy's backoff, the request's resolved deadline bounding
        both pauses and hop timeouts. Every hop is journaled."""
        if cls in self._preshed:
            # Fleet pre-shed (ISSUE 20): refused before any forwarding,
            # counted rejected on both ledgers (http_fleet_load maps 429
            # to rejected) — the closed identity survives pre-actuation.
            body_out = json.dumps(
                {
                    "rid": rid,
                    "status": "REJECTED",
                    "class": cls,
                    "reason": "fleet_preshed",
                    "error": "class pre-shed by fleet controller",
                }
            ).encode()
            return RouteResult(429, body_out, "rejected", "", 0, 0)
        dl = Deadline.after(
            deadline_s if deadline_s is not None else self.cfg.default_deadline_s
        )
        order = self.candidates(rid, cls)
        max_attempts = self.cfg.retry.max_retries + 1
        attempt = 0
        redirects = 0
        last_code: Optional[int] = None
        last_body = b""
        last_reason = ""
        last_idx: Optional[int] = None
        while attempt < max_attempts and not dl.expired:
            idx = self._pick(order, avoid=last_idx)
            if idx is None:
                break  # nothing routable right now
            slot = self.slots[idx]
            if last_idx is not None:
                redirects += 1
                self._journal_redirect(
                    rid, self.slots[last_idx].name, slot.name,
                    attempt, last_reason,
                )
                pause = min(
                    self.cfg.retry.delay_s(attempt),
                    dl.remaining(self.cfg.retry.max_delay_s),
                )
                if pause > 0:
                    time.sleep(pause)
            attempt += 1
            code, rbody, reason = self._forward(slot, body, dl)
            last_idx = idx
            if code == _CONN_FAIL:
                # Feed the request-path failure to the health machine —
                # a dead host is detected by the traffic it kills, not
                # just by the next probe tick.
                self._note_probe(slot, False, 0.0, reason)
            if code == 200:
                return RouteResult(200, rbody, "ok", slot.name, attempt, redirects)
            if code not in _RETRY_CODES and code != _CONN_FAIL:
                verdict = "rejected" if code in (400, 413) else "failed"
                return RouteResult(
                    code, rbody, verdict, slot.name, attempt, redirects
                )
            last_code, last_body, last_reason = code, rbody, reason
        if last_code is None:
            # Never forwarded anywhere: the candidate set held no
            # routable backend — the router's own attributable verdict.
            body_out = json.dumps(
                {
                    "rid": rid,
                    "status": "UNROUTABLE",
                    "class": cls,
                    "error": "no routable backend",
                }
            ).encode()
            return RouteResult(503, body_out, "unroutable", "", attempt, redirects)
        # Budget exhausted on a retryable verdict: the client sees the
        # last real backend answer (429/504), or 502 for a connection
        # failure — attributed, never silent.
        if last_code == _CONN_FAIL:
            body_out = json.dumps(
                {
                    "rid": rid,
                    "status": "FAILED",
                    "class": cls,
                    "reason": "backend_down",
                    "error": f"backend unreachable after {attempt} attempts",
                }
            ).encode()
            return RouteResult(
                502, body_out, "failed",
                self.slots[last_idx].name if last_idx is not None else "",
                attempt, redirects,
            )
        verdict = "rejected" if last_code == 429 else "shed"
        return RouteResult(
            last_code, last_body, verdict,
            self.slots[last_idx].name if last_idx is not None else "",
            attempt, redirects,
        )

    @off_timed_path
    def _journal_redirect(
        self, rid: str, frm: str, to: str, attempt: int, reason: str
    ) -> None:
        metrics_registry().counter("router.redirects").inc()
        self._journal_append(
            "router_redirect",
            key=f"redirect:{rid}",
            rid=rid,
            frm=frm,
            to=to,
            attempt=attempt,
            reason=reason,
            t_ms=self._t_ms(),
        )

    # ----------------------------------------------------------- accounting

    @off_timed_path
    def _finish(
        self,
        rid: str,
        cls: str,
        t0: float,
        verdict: str,
        code: int,
        backend: str,
        attempts: int,
        redirects: int,
        n_images: int,
    ) -> None:
        """Close one request's ledger AFTER the response hit the socket:
        per-class closed accounting, metrics, and the ``router_route``
        verdict record."""
        t1 = time.monotonic()
        ms = (t1 - t0) * 1e3
        with self._lock:
            self.http_codes[code] = self.http_codes.get(code, 0) + 1
            st = self.stats.setdefault(cls, RouterClassStats())
            st.offered += 1
            if verdict == "ok":
                st.ok += 1
                st.images_ok += n_images
                st.latencies_ms.append(ms)
            elif verdict == "shed":
                st.shed += 1
            elif verdict == "rejected":
                st.rejected += 1
            elif verdict == "unroutable":
                st.unroutable += 1
            else:
                st.failed += 1
            self.redirects += redirects
        reg = metrics_registry()
        reg.counter(f"router.http_{code}").inc()
        reg.histogram("router.transport_ms").observe(ms)
        if verdict == "unroutable":
            reg.counter("router.unroutable").inc()
        self._journal_append(
            "router_route",
            key=f"route:{rid or code}",
            rid=rid,
            cls=cls,
            verdict=verdict,
            backend=backend,
            attempts=attempts,
            redirects=redirects,
            http=code,
            ms=round(ms, 3),
            t_ms=self._t_ms(),
        )

    def report(self) -> RouterReport:
        with self._lock:
            per_class: Dict[str, RouterClassStats] = {}
            for name, st in self.stats.items():
                c = RouterClassStats()
                c.offered, c.ok, c.shed = st.offered, st.ok, st.shed
                c.failed, c.rejected = st.failed, st.rejected
                c.unroutable, c.images_ok = st.unroutable, st.images_ok
                c.latencies_ms = list(st.latencies_ms)
                per_class[name] = c
            return RouterReport(
                per_class=per_class,
                redirects=self.redirects,
                backends={s.name: s.state for s in self.slots},
                duration_s=self._clock() - self._started_at,
            )

"""Fleet control plane (ISSUE 20): cross-backend arbitration over the router.

PR 18's AutopilotController closes the loop *inside* one backend; PR 16's
FleetRouter probes every backend's ``/healthz``.  Nothing arbitrated *across*
backends, so under correlated pressure every Autopilot independently walks its
degrade ladder and the fleet all-degrades at once — exactly the failure a
fleet exists to prevent.  The paper's staged-parallelism thesis (V2.2→V4:
coordination beats replicate-all) applies one level up: N self-healers with no
coordination tier behave like V2.1 broadcast-all.

``FleetController`` is evaluated from the router's existing probe cadence —
it owns **no thread**.  Each ``probe_once()`` sweep scrapes every backend's
controller state (ladder rung, protected burn, queue depth, intent) into the
router's ``BackendSlot``s, then calls ``evaluate()``, which folds that fleet
view plus router-level per-class accounting into journaled actions:

  staggered downshift tokens
      At most ``max_concurrent_degraded`` backends may hold a non-top ladder
      rung at once.  A backend degrading past its token gets a journaled
      ``fleet_refusal`` and is drained instead — the router redirects its
      home traffic via the existing spillover path while probes continue.

  drain-vs-shed arbitration
      A backend whose protected burn stays >= ``drain_burn_high`` for
      ``drain_after_s`` is drained rather than left shedding.  Re-admission
      is strict LIFO (last drained, first back) once the backend's controller
      reports grow-back — empty queue and a not-overloaded intent.  Burn is
      deliberately NOT the readmit key: a drained backend gets no traffic, so
      its sliding burn window freezes at the pre-drain value.

  forecast pre-actuation
      The realized arrival rate (sampled from the router's offered counters)
      is least-squares fit against the ``traffic.shaped_arrivals`` diurnal
      basis.  When *forecast* burn (predicted rate / fleet capacity) crests
      ``forecast_burn_high``, the fleet pre-sheds deferrable classes at the
      router (429, counted ``rejected`` on both ledgers) and pre-releases
      drains before the ramp crest; every forecast-driven action journals
      its predicted-vs-realized evidence.

Every action/refusal is ONE ``fleet_action`` / ``fleet_refusal`` record with
full evidence, mirroring the ``controller_action`` contract (PR 18).  Records
are written through the router's journal; ``observability.export`` renders
them on the fleet lane and ``observability.health`` folds drain incidents
into detect→drain→readmit phases.

Stdlib only; no jax import (router hot path).
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

from ..observability.metrics import registry as _metrics
from ..observability.trace import off_timed_path

__all__ = [
    "FleetControllerConfig",
    "FleetController",
    "fit_diurnal",
    "predict_rate",
]


# ------------------------------------------------------------ forecast ---


def fit_diurnal(
    samples: Sequence[Tuple[float, float]], period_s: float
) -> Optional[Dict[str, float]]:
    """Least-squares fit of ``(t_s, rate_rps)`` samples against the diurnal
    basis used by ``traffic.shaped_arrivals``:

        r(t) = base + amp * sin(2*pi*t/period + phase)

    The phase is free because the fleet does not know when the load started
    (the shaped trace is phased to begin at the trough; the controller's
    clock is not).  Fit is the classical linearisation r = a + b*sin(wt) +
    c*cos(wt) with amp = hypot(b, c), solved by Gaussian elimination on the
    3x3 normal equations.  Returns ``{"base","amp","phase","period_s","n",
    "rmse"}`` or None when under-determined/degenerate.
    """
    pts = [(float(t), float(r)) for t, r in samples]
    if len(pts) < 3 or period_s <= 0.0:
        return None
    w = 2.0 * math.pi / period_s
    # Normal equations A x = y over basis [1, sin(wt), cos(wt)].
    a = [[0.0] * 3 for _ in range(3)]
    y = [0.0, 0.0, 0.0]
    for t, r in pts:
        row = (1.0, math.sin(w * t), math.cos(w * t))
        for i in range(3):
            y[i] += row[i] * r
            for j in range(3):
                a[i][j] += row[i] * row[j]
    # Gaussian elimination with partial pivoting.
    m = [a[i] + [y[i]] for i in range(3)]
    for col in range(3):
        piv = max(range(col, 3), key=lambda i: abs(m[i][col]))
        if abs(m[piv][col]) < 1e-9:
            return None
        m[col], m[piv] = m[piv], m[col]
        for i in range(3):
            if i == col:
                continue
            f = m[i][col] / m[col][col]
            for j in range(col, 4):
                m[i][j] -= f * m[col][j]
    base, b, c = (m[i][3] / m[i][i] for i in range(3))
    amp = math.hypot(b, c)
    phase = math.atan2(c, b)
    sq = 0.0
    for t, r in pts:
        sq += (base + b * math.sin(w * t) + c * math.cos(w * t) - r) ** 2
    return {
        "base": base,
        "amp": amp,
        "phase": phase,
        "period_s": float(period_s),
        "n": float(len(pts)),
        "rmse": math.sqrt(sq / len(pts)),
    }


def predict_rate(fit: Dict[str, float], t_s: float) -> float:
    """Evaluate a ``fit_diurnal`` fit at time ``t_s`` (same clock as the
    samples it was fit on)."""
    w = 2.0 * math.pi / fit["period_s"]
    return fit["base"] + fit["amp"] * math.sin(w * t_s + fit["phase"])


# -------------------------------------------------------------- config ---


@dataclasses.dataclass(frozen=True)
class FleetControllerConfig:
    """Knobs for the fleet control plane.  Mirrors ``ControllerConfig``'s
    to_obj/from_obj contract so it rides inside ``RouterConfig`` payloads."""

    # Evaluation cadence (seconds of router clock between folds; the router
    # calls evaluate() every probe sweep and this throttles it).
    eval_s: float = 0.25
    # (a) staggered downshift tokens.
    max_concurrent_degraded: int = 1
    token_cooldown_s: float = 1.0  # per-backend fleet_refusal re-journal gap
    # (b) drain-vs-shed arbitration.
    drain_burn_high: float = 1.0  # protected burn that arms the drain timer
    drain_after_s: float = 2.0  # sustained-burn dwell before draining
    drain_min_s: float = 1.0  # minimum drain dwell before readmit
    max_drained: int = 1  # at most this many drained at once
    min_active: int = 1  # never drain below this many routable backends
    # (c) forecast pre-actuation (off until period + capacity are known).
    forecast: bool = True
    forecast_period_s: Optional[float] = None  # diurnal period to fit
    forecast_horizon_s: float = 1.0  # how far ahead to act
    forecast_capacity_rps: Optional[float] = None  # fleet-wide sustainable rps
    forecast_min_samples: int = 6
    forecast_window: int = 240  # rate samples kept for the fit
    forecast_burn_high: float = 0.95  # predicted rate/capacity that presheds
    forecast_burn_low: float = 0.55  # predicted burn that relaxes preshed
    preshed_min_s: float = 1.0  # minimum preshed dwell before release
    preshed_classes: Tuple[str, ...] = ("bulk", "batch")
    protected_cls: str = "interactive"

    def to_obj(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["preshed_classes"] = list(self.preshed_classes)
        return d

    @classmethod
    def from_obj(cls, obj: Dict[str, Any]) -> "FleetControllerConfig":
        fields = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in obj.items() if k in fields}
        if "preshed_classes" in kw:
            kw["preshed_classes"] = tuple(kw["preshed_classes"])
        return cls(**kw)


# ---------------------------------------------------------- fleet view ---


@dataclasses.dataclass
class _BackendView:
    """One backend's scraped state, snapshotted under the router lock."""

    index: int
    name: str
    state: str
    drained: bool
    level: int  # ladder rung depth (0 = top / undegraded)
    mode: Optional[str]
    burn: Optional[float]  # protected-class burn scraped from intent
    overloaded: Optional[bool]
    depth: Optional[int]  # queue depth scraped from /healthz


class FleetController:
    """Cross-backend arbitration evaluated from the router's probe cadence.

    Owns no thread: the router calls :meth:`evaluate` at the tail of every
    ``probe_once()`` sweep (and tests call it directly with an injectable
    ``now=``).  All actuation goes through the router (``set_drained`` /
    ``set_preshed``); all evidence goes through the router's journal as
    ``fleet_action`` / ``fleet_refusal`` records keyed ``fleet:<seq>``.
    """

    def __init__(self, router, cfg: Optional[FleetControllerConfig] = None):
        self.router = router
        self.cfg = cfg or FleetControllerConfig()
        self._lock_free = True  # evaluate() runs on the probe thread only
        self._seq = 0
        self._last_eval: Optional[float] = None
        # (a) tokens: backend indices currently granted a degraded rung,
        # in grant order.
        self._tokens: List[int] = []
        self._refused_t: Dict[int, float] = {}
        # (b) drain stack (LIFO: last drained is first readmitted).
        self._drained: List[int] = []
        self._drain_t: Dict[int, float] = {}
        self._burn_high_since: Dict[int, float] = {}
        self._drain_refused_t: Dict[int, float] = {}
        # (c) forecast state.
        self._samples: Deque[Tuple[float, float]] = deque(
            maxlen=max(8, int(self.cfg.forecast_window))
        )
        self._last_offered: Optional[int] = None
        self._capacity_rps: Optional[float] = self.cfg.forecast_capacity_rps
        self._preshed_active = False
        self._preshed_entry: Dict[str, float] = {}
        self._preshed_peak_rps = 0.0
        self.action_counts: Dict[str, int] = {}

    # ------------------------------------------------------- operator ---

    def set_capacity_rps(self, rps: Optional[float]) -> None:
        """Operator input: fleet-wide sustainable request rate used as the
        forecast-burn denominator (e.g. from ``bench.saturating_rate``).
        Not journaled itself — it is recorded as evidence on every forecast
        action it feeds."""
        self._capacity_rps = None if rps is None else float(rps)

    # ------------------------------------------------------- evaluate ---

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Fold the scraped fleet view into actions.  Returns the records
        journaled this step (empty when throttled or nothing to do)."""
        if now is None:
            now = time.monotonic()
        if (
            self._last_eval is not None
            and now - self._last_eval < self.cfg.eval_s
        ):
            return []
        prev = self._last_eval
        self._last_eval = now
        views = self._snapshot()
        if prev is not None and now > prev:
            self._sample_rate(now, now - prev)
        recs: List[Dict[str, Any]] = []
        recs += self._forecast_step(now)
        recs += self._token_step(views, now)
        recs += self._drain_step(views, now)
        recs += self._readmit_step(views, now)
        return recs

    def _snapshot(self) -> List[_BackendView]:
        r = self.router
        with r._lock:
            return [
                _BackendView(
                    index=i,
                    name=s.name,
                    state=s.state,
                    drained=s.drained,
                    level=int(s.ctl_level or 0),
                    mode=s.ctl_mode,
                    burn=s.ctl_burn,
                    overloaded=s.ctl_overloaded,
                    depth=s.queue_depth,
                )
                for i, s in enumerate(r.slots)
            ]

    # ------------------------------------------------------ (a) tokens ---

    def _token_step(
        self, views: List[_BackendView], now: float
    ) -> List[Dict[str, Any]]:
        cfg, recs = self.cfg, []
        by_index = {v.index: v for v in views}
        # Release tokens whose holder climbed back to the top rung (or left
        # the routable pool — its degradation no longer gates the fleet).
        for i in list(self._tokens):
            v = by_index.get(i)
            if v is None or (v.level == 0 and not v.drained):
                self._tokens.remove(i)
                self._refused_t.pop(i, None)
                recs.append(
                    self._journal(
                        "fleet_action", "token_release",
                        v.name if v else str(i), now,
                        actuated=True, reversal=True, views=views,
                        evidence={"holders_after": self._token_names()},
                    )
                )
        # Grant tokens to degraded backends, oldest degradation first
        # (stable index order is fine: the probe sweep is index-ordered).
        for v in views:
            wants = v.level > 0 or bool(v.overloaded)
            if not wants or v.drained or v.index in self._tokens:
                continue
            if len(self._tokens) < cfg.max_concurrent_degraded:
                self._tokens.append(v.index)
                recs.append(
                    self._journal(
                        "fleet_action", "token_grant", v.name, now,
                        actuated=True, reversal=False, views=views,
                        evidence={
                            "level": v.level,
                            "burn": v.burn,
                            "holders_after": self._token_names(),
                        },
                    )
                )
                continue
            # Token budget exhausted: journaled refusal (throttled per
            # backend) and the router redirects load off it via drain.
            last = self._refused_t.get(v.index)
            if last is not None and now - last < cfg.token_cooldown_s:
                continue
            self._refused_t[v.index] = now
            recs.append(
                self._journal(
                    "fleet_refusal", "token_refused", v.name, now,
                    actuated=False, reversal=False, views=views,
                    cause="max_concurrent_degraded",
                    evidence={
                        "level": v.level,
                        "burn": v.burn,
                        "overloaded": v.overloaded,
                        "holders": self._token_names(),
                        "max_concurrent_degraded":
                            cfg.max_concurrent_degraded,
                    },
                )
            )
            recs += self._maybe_drain(
                v, views, now, cause="token_refused", detect_ms=0.0
            )
        return recs

    def _token_names(self) -> List[str]:
        slots = self.router.slots
        return [slots[i].name for i in self._tokens if i < len(slots)]

    # ------------------------------------------------------- (b) drain ---

    def _drain_step(
        self, views: List[_BackendView], now: float
    ) -> List[Dict[str, Any]]:
        cfg, recs = self.cfg, []
        for v in views:
            if v.drained or v.state != "up" or v.burn is None:
                self._burn_high_since.pop(v.index, None)
                continue
            if v.burn >= cfg.drain_burn_high:
                t0 = self._burn_high_since.setdefault(v.index, now)
                if now - t0 >= cfg.drain_after_s:
                    recs += self._maybe_drain(
                        v, views, now,
                        cause="sustained_burn",
                        detect_ms=(now - t0) * 1e3,
                    )
            else:
                self._burn_high_since.pop(v.index, None)
        return recs

    def _maybe_drain(
        self,
        v: _BackendView,
        views: List[_BackendView],
        now: float,
        *,
        cause: str,
        detect_ms: float,
    ) -> List[Dict[str, Any]]:
        cfg = self.cfg
        if v.drained or v.index in self._drained:
            return []
        # Drain-vs-shed arbitration, resolved: while the fleet is preshed
        # for a forecast crest, SHED has been chosen over DRAIN.  Pulling a
        # backend out now spills its protected-class share onto the
        # survivors mid-crest and cascades the whole fleet down its
        # ladders — the one correlated failure this tier exists to prevent.
        if self._preshed_active:
            return self._refuse_drain(
                v, views, now, "preshed_active",
                {"burn": v.burn, "level": v.level},
            )
        if len(self._drained) >= cfg.max_drained:
            return self._refuse_drain(
                v, views, now, "max_drained",
                {
                    "drained": self._drained_names(),
                    "max_drained": cfg.max_drained,
                },
            )
        active_after = sum(
            1
            for o in views
            if o.index != v.index
            and o.state == "up"
            and not o.drained
            and o.index not in self._drained
        )
        if active_after < cfg.min_active:
            return self._refuse_drain(
                v, views, now, "min_active",
                {
                    "active_after": active_after,
                    "min_active": cfg.min_active,
                },
            )
        t0 = time.perf_counter()
        self.router.set_drained(v.index, True)
        ms = (time.perf_counter() - t0) * 1e3
        v.drained = True
        self._drained.append(v.index)
        self._drain_t[v.index] = now
        self._burn_high_since.pop(v.index, None)
        return [
            self._journal(
                "fleet_action", "drain", v.name, now,
                actuated=True, reversal=False, views=views,
                cause=cause, ms=ms,
                evidence={
                    "detect_ms": round(detect_ms, 3),
                    "burn": v.burn,
                    "level": v.level,
                    "depth": v.depth,
                    "drained_after": self._drained_names(),
                },
            )
        ]

    def _refuse_drain(
        self,
        v: _BackendView,
        views: List[_BackendView],
        now: float,
        cause: str,
        evidence: Dict[str, Any],
    ) -> List[Dict[str, Any]]:
        """Journal ONE drain_refused per backend per cooldown window — a
        refused drain usually stays refused for many sweeps, and the
        journal needs the arbitration, not a record per probe."""
        last = self._drain_refused_t.get(v.index)
        if last is not None and now - last < self.cfg.token_cooldown_s:
            return []
        self._drain_refused_t[v.index] = now
        return [
            self._journal(
                "fleet_refusal", "drain_refused", v.name, now,
                actuated=False, reversal=False, views=views,
                cause=cause, evidence=evidence,
            )
        ]

    def _drained_names(self) -> List[str]:
        slots = self.router.slots
        return [slots[i].name for i in self._drained if i < len(slots)]

    # ----------------------------------------------------- (b) readmit ---

    def _readmit_step(
        self, views: List[_BackendView], now: float
    ) -> List[Dict[str, Any]]:
        """Strict LIFO: only the most recently drained backend may readmit;
        the stack below it waits its turn (mirrors the Autopilot's LIFO
        ladder discipline)."""
        recs: List[Dict[str, Any]] = []
        by_index = {v.index: v for v in views}
        while self._drained:
            idx = self._drained[-1]
            v = by_index.get(idx)
            if v is None:
                self._drained.pop()
                self._drain_t.pop(idx, None)
                continue
            if not self._grow_back(v, now):
                break
            recs.append(self._do_readmit(v, views, now, cause="grow_back"))
        return recs

    def _grow_back(self, v: _BackendView, now: float) -> bool:
        """Readmit key: drain dwell served, probes still passing, queue
        drained to empty, and the backend's own intent not overloaded.
        Burn is deliberately excluded — it is frozen while drained."""
        dwell = now - self._drain_t.get(v.index, now)
        if dwell < self.cfg.drain_min_s:
            return False
        if v.state != "up":
            return False
        if v.depth is not None and v.depth > 0:
            return False
        return not bool(v.overloaded)

    def _do_readmit(
        self,
        v: _BackendView,
        views: List[_BackendView],
        now: float,
        *,
        cause: str,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        t0 = time.perf_counter()
        self.router.set_drained(v.index, False)
        ms = (time.perf_counter() - t0) * 1e3
        v.drained = False
        if v.index in self._drained:
            self._drained.remove(v.index)
        dwell = now - self._drain_t.pop(v.index, now)
        self._refused_t.pop(v.index, None)
        ev = {
            "drain_ms": round(dwell * 1e3, 3),
            "level": v.level,
            "depth": v.depth,
            "overloaded": v.overloaded,
            "drained_after": self._drained_names(),
        }
        if extra:
            ev.update(extra)
        return self._journal(
            "fleet_action", "readmit", v.name, now,
            actuated=True, reversal=True, views=views,
            cause=cause, ms=ms, evidence=ev,
        )

    # ---------------------------------------------------- (c) forecast ---

    def _sample_rate(self, now: float, dt: float) -> None:
        """One realized-arrival-rate sample per evaluate, from the delta of
        the router's total offered counter (per-class accounting already
        maintained by the request path — no new bookkeeping)."""
        r = self.router
        with r._lock:
            offered = sum(st.offered for st in r.stats.values())
        if self._last_offered is None:
            self._last_offered = offered
            return
        rate = max(0.0, (offered - self._last_offered) / dt)
        self._last_offered = offered
        self._samples.append((now, rate))
        if self._preshed_active:
            self._preshed_peak_rps = max(self._preshed_peak_rps, rate)

    def _forecast_step(self, now: float) -> List[Dict[str, Any]]:
        cfg = self.cfg
        if (
            not cfg.forecast
            or cfg.forecast_period_s is None
            or self._capacity_rps is None
            or self._capacity_rps <= 0.0
            or len(self._samples) < cfg.forecast_min_samples
        ):
            return []
        fit = fit_diurnal(self._samples, cfg.forecast_period_s)
        realized = self._samples[-1][1]
        realized_burn = realized / self._capacity_rps
        predicted = (
            predict_rate(fit, now + cfg.forecast_horizon_s)
            if fit is not None
            else None
        )
        predicted_burn = (
            predicted / self._capacity_rps if predicted is not None else None
        )
        recs: List[Dict[str, Any]] = []
        if not self._preshed_active:
            by_forecast = (
                predicted_burn is not None
                and predicted_burn >= cfg.forecast_burn_high
            )
            # Reactive backstop: a realized crest the fit has not converged
            # on yet must still preshed.
            by_realized = realized_burn >= cfg.forecast_burn_high
            if by_forecast or by_realized:
                self._preshed_active = True
                self._preshed_peak_rps = realized
                self._preshed_entry = {
                    "predicted_rps": predicted,
                    "predicted_burn": predicted_burn,
                    "realized_rps": realized,
                    "t": now,
                }
                t0 = time.perf_counter()
                self.router.set_preshed(cfg.preshed_classes)
                ms = (time.perf_counter() - t0) * 1e3
                recs.append(
                    self._journal(
                        "fleet_action", "preshed", ",".join(
                            cfg.preshed_classes
                        ), now,
                        actuated=True, reversal=False, views=None,
                        cause="forecast" if by_forecast else "realized",
                        ms=ms,
                        evidence=self._forecast_evidence(
                            fit, predicted, predicted_burn,
                            realized, realized_burn,
                        ),
                    )
                )
                # Pre-release every drain before the crest: the fleet needs
                # all capacity for the protected class it still admits.
                views = self._snapshot()
                by_index = {v.index: v for v in views}
                for idx in list(reversed(self._drained)):
                    v = by_index.get(idx)
                    if v is None:
                        continue
                    recs.append(
                        self._do_readmit(
                            v, views, now,
                            cause="forecast_release",
                            extra={
                                "predicted_rps": predicted,
                                "predicted_burn": predicted_burn,
                            },
                        )
                    )
        else:
            worst = max(
                realized_burn,
                predicted_burn if predicted_burn is not None else 0.0,
            )
            # Release discipline mirrors drain grow-back: a minimum dwell,
            # and every ROUTABLE backend back at the top rung and not
            # overloaded.  The realized rate alone cannot be trusted here —
            # in a closed loop a collapsing fleet stops being OFFERED
            # traffic, which reads exactly like calm and would release the
            # shed into the crest (drained backends are excluded: their
            # scraped state is frozen at the pre-drain value by design).
            dwelled = (
                now - self._preshed_entry.get("t", now) >= cfg.preshed_min_s
            )
            grown_back = all(
                v.level == 0 and not bool(v.overloaded)
                for v in self._snapshot()
                if v.state == "up" and not v.drained
            )
            if worst <= cfg.forecast_burn_low and dwelled and grown_back:
                self._preshed_active = False
                entry = self._preshed_entry
                t0 = time.perf_counter()
                self.router.set_preshed(())
                ms = (time.perf_counter() - t0) * 1e3
                ev = self._forecast_evidence(
                    fit, predicted, predicted_burn, realized, realized_burn
                )
                ev.update(
                    {
                        "entry_predicted_rps": entry.get("predicted_rps"),
                        "entry_realized_rps": entry.get("realized_rps"),
                        "realized_peak_rps": round(
                            self._preshed_peak_rps, 3
                        ),
                        "preshed_s": round(now - entry.get("t", now), 3),
                    }
                )
                recs.append(
                    self._journal(
                        "fleet_action", "preshed_release", ",".join(
                            cfg.preshed_classes
                        ), now,
                        actuated=True, reversal=True, views=None,
                        cause="forecast", ms=ms, evidence=ev,
                    )
                )
        return recs

    def _forecast_evidence(
        self, fit, predicted, predicted_burn, realized, realized_burn
    ) -> Dict[str, Any]:
        cfg = self.cfg
        ev: Dict[str, Any] = {
            "predicted_rps":
                None if predicted is None else round(predicted, 3),
            "predicted_burn":
                None if predicted_burn is None else round(predicted_burn, 4),
            "realized_rps": round(realized, 3),
            "realized_burn": round(realized_burn, 4),
            "capacity_rps": round(self._capacity_rps, 3),
            "horizon_s": cfg.forecast_horizon_s,
            "burn_high": cfg.forecast_burn_high,
            "burn_low": cfg.forecast_burn_low,
            "n_samples": len(self._samples),
        }
        if fit is not None:
            ev["fit"] = {
                "base": round(fit["base"], 3),
                "amp": round(fit["amp"], 3),
                "rmse": round(fit["rmse"], 3),
                "period_s": fit["period_s"],
            }
        return ev

    # ------------------------------------------------------ journaling ---

    @off_timed_path
    def _journal(
        self,
        kind: str,
        action: str,
        target: str,
        now: float,
        *,
        actuated: bool,
        reversal: bool,
        views: Optional[List[_BackendView]],
        cause: Optional[str] = None,
        ms: float = 0.0,
        evidence: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """ONE record per action/refusal, mirroring ``controller_action``:
        what was done, to whom, whether it actuated, and the full fleet
        evidence it was decided on."""
        self._seq += 1
        ev: Dict[str, Any] = dict(evidence or {})
        if views is not None:
            ev["fleet"] = {
                v.name: {
                    "state": v.state,
                    "level": v.level,
                    "burn": v.burn,
                    "drained": v.drained,
                }
                for v in views
            }
        rec = {
            "action": action,
            "target": target,
            "actuated": bool(actuated),
            "reversal": bool(reversal),
            "tokens": self._token_names(),
            "drained": self._drained_names(),
            "preshed": self._preshed_active,
            "ms": round(ms, 3),
            "evidence": ev,
        }
        if cause is not None:
            rec["cause"] = cause
        self.action_counts[action] = self.action_counts.get(action, 0) + 1
        reg = _metrics()
        reg.counter("fleet.actions").inc()
        reg.counter(f"fleet.action.{action}").inc()
        if kind == "fleet_refusal":
            reg.counter("fleet.refusals").inc()
        r = self.router
        r._journal_append(
            kind, key=f"fleet:{self._seq}", t_ms=r._t_ms(), **rec
        )
        return dict(rec, kind=kind)

    # ------------------------------------------------------- reporting ---

    def state_obj(self) -> Dict[str, Any]:
        """JSON-safe state for the router's ``/stats`` endpoint."""
        return {
            "tokens": self._token_names(),
            "drained": self._drained_names(),
            "preshed": self._preshed_active,
            "preshed_classes": list(self.cfg.preshed_classes),
            "capacity_rps": self._capacity_rps,
            "n_samples": len(self._samples),
            "actions": dict(self.action_counts),
        }

    def summary(self) -> Dict[str, Any]:
        """Bench-row summary: action counts plus totals."""
        total = sum(self.action_counts.values())
        return {"actions": dict(self.action_counts), "total": total}

"""Traffic-shaped load: diurnal ramps, bursts, flash crowds, class mixes.

PR 6's load model is a single homogeneous Poisson stream of 1-image
requests — the right first tool and nothing like production traffic,
which breathes (diurnal ramps), spikes (bursts, flash crowds), and mixes
request classes whose sizes are heavy-tailed (many tiny interactive
calls, a fat tail of bulk batches). This module generates exactly those
shapes, seeded and deterministic (the PR 1 reproducibility rule: a drill
that cannot replay is not a drill):

- :func:`shaped_arrivals` turns a shape spec (``"steady"``,
  ``"diurnal"``, ``"burst"``, ``"flash"``, composable with ``+`` —
  ``"diurnal+burst"``) into sorted arrival offsets. Diurnal is an
  inhomogeneous Poisson via thinning (rate swings ±``amp`` around the
  base over ``period`` seconds, starting at the trough so a run ramps
  up); burst adds a ``mult``× arrival clump every ``every`` seconds;
  flash adds ONE ``mult``× crowd at ``at``·duration.
- :class:`RequestClass` couples a mix weight, a heavy-tailed size
  distribution over the bucket set, and the class's deadline + SLO
  target; :func:`default_class_mix` is the canonical
  interactive/batch/bulk triple; :func:`assign_classes` deals a seeded
  class per arrival.
- :class:`ShapedReport`/:class:`ClassStats` carry per-class accounting
  that must CLOSE per class — ``ok + shed + failed + rejected ==
  offered``, the queue's no-silent-loss contract extended to the load
  side — plus per-class nearest-rank p50/p99.

Stdlib + numpy only (no jax import), same layering rule as ``queue``/
``loadgen``.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, List, Optional, Sequence, Tuple

from .slo import SLOClass, SLOPolicy


# ------------------------------------------------------------- shapes ---


@dataclasses.dataclass(frozen=True)
class TrafficShape:
    """One parsed shape component (see :func:`parse_shape`)."""

    kind: str  # steady | diurnal | burst | flash
    params: Tuple[Tuple[str, float], ...] = ()

    def param(self, name: str, default: float) -> float:
        return dict(self.params).get(name, default)


_SHAPE_KINDS = ("steady", "diurnal", "burst", "flash")


def parse_shape(spec: str) -> List[TrafficShape]:
    """``"diurnal:amp=0.8,period=4+burst:every=2,mult=5"`` -> components.

    Unknown kinds/params raise — a typo'd drill spec must fail loudly,
    not silently run a steady load labeled diurnal (the chaos
    KNOWN_SITES rule applied to traffic)."""
    comps: List[TrafficShape] = []
    for part in (spec or "steady").split("+"):
        part = part.strip()
        if not part:
            continue
        kind, _, rest = part.partition(":")
        kind = kind.strip()
        if kind not in _SHAPE_KINDS:
            raise ValueError(
                f"unknown traffic shape {kind!r} (valid: {', '.join(_SHAPE_KINDS)})"
            )
        params = []
        for kv in rest.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            try:
                params.append((k.strip(), float(v)))
            except ValueError:
                raise ValueError(
                    f"traffic shape param {kv!r} is not key=number"
                ) from None
        comps.append(TrafficShape(kind, tuple(params)))
    return comps or [TrafficShape("steady")]


def _steady(rng: random.Random, rate: float, duration: float) -> List[float]:
    t, out = 0.0, []
    while True:
        t += rng.expovariate(rate)
        if t >= duration:
            return out
        out.append(t)


def shaped_arrivals(
    shape, rate_rps: float, duration_s: float, seed: int = 0
) -> List[float]:
    """Sorted arrival offsets for a shape spec (string or parsed list).

    The FIRST component carries the base load at ``rate_rps``; burst/
    flash components after it ADD their spikes on top (so
    ``"diurnal+burst"`` is a breathing base with clumps riding it). A
    burst/flash listed first still gets a steady base underneath — a
    flash crowd arrives *on top of* normal traffic, not instead of it.
    """
    comps = parse_shape(shape) if isinstance(shape, str) else list(shape)
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = random.Random(f"traffic:{seed}")
    out: List[float] = []
    base_done = False
    for comp in comps:
        if comp.kind == "steady":
            out.extend(_steady(rng, rate_rps, duration_s))
            base_done = True
        elif comp.kind == "diurnal":
            # Inhomogeneous Poisson by thinning: rate(t) swings ±amp
            # around base over one period, phased to START at the trough
            # so the window ramps up like a morning.
            amp = min(0.99, max(0.0, comp.param("amp", 0.6)))
            period = comp.param("period", duration_s)
            rmax = rate_rps * (1.0 + amp)
            t = 0.0
            while True:
                t += rng.expovariate(rmax)
                if t >= duration_s:
                    break
                r_t = rate_rps * (
                    1.0 + amp * math.sin(2 * math.pi * t / period - math.pi / 2)
                )
                if rng.random() < r_t / rmax:
                    out.append(t)
            base_done = True
        elif comp.kind == "burst":
            if not base_done:
                out.extend(_steady(rng, rate_rps, duration_s))
                base_done = True
            every = max(1e-3, comp.param("every", max(duration_s / 2, 1e-3)))
            width = comp.param("width", min(0.2, every / 4))
            mult = comp.param("mult", 4.0)
            t0 = every
            while t0 < duration_s:
                out.extend(
                    t0 + a for a in _steady(rng, rate_rps * mult, width)
                )
                t0 += every
        elif comp.kind == "flash":
            if not base_done:
                out.extend(_steady(rng, rate_rps, duration_s))
                base_done = True
            at = comp.param("at", 0.5) * duration_s
            width = comp.param("width", max(duration_s * 0.1, 1e-3))
            mult = comp.param("mult", 8.0)
            out.extend(
                min(at + a, duration_s - 1e-9)
                for a in _steady(rng, rate_rps * mult, width)
            )
    return sorted(out)


# -------------------------------------------------------- class mixes ---


@dataclasses.dataclass(frozen=True)
class RequestClass:
    """One traffic class: mix weight, size distribution, deadline, SLO."""

    name: str
    weight: float  # mix probability mass (normalized across the mix)
    sizes: Tuple[int, ...]  # n_images per request, drawn from these
    size_weights: Tuple[float, ...]  # heavy-tailed over ``sizes``
    deadline_s: Optional[float]  # hard deadline (shed reason="deadline")
    slo_ms: float  # latency target (shed reason="slo" once blown)

    def slo_class(self) -> SLOClass:
        return SLOClass(self.name, slo_ms=self.slo_ms, deadline_s=self.deadline_s)


def default_class_mix(
    buckets: Sequence[int],
    *,
    interactive_slo_ms: float = 1000.0,
    batch_slo_ms: float = 5000.0,
    bulk_slo_ms: float = 0.0,
) -> Tuple[RequestClass, ...]:
    """The canonical three-class mix over a bucket set: a heavy head of
    1-image interactive calls with a tight SLO, a middle of multi-image
    batch calls, and a thin tail of largest-bucket bulk requests with no
    SLO (shed last, by hard deadline only). Sizes within a class are
    weighted ~1/n — the heavy-tailed request-size reality that makes a
    fixed bucket set earn its keep."""
    bs = sorted(set(int(b) for b in buckets))
    mid = [b for b in bs if 1 < b < bs[-1]] or bs[:1]
    return (
        RequestClass(
            "interactive", 0.7, (1,), (1.0,),
            deadline_s=interactive_slo_ms * 4 / 1e3, slo_ms=interactive_slo_ms,
        ),
        RequestClass(
            "batch", 0.25, tuple(mid), tuple(1.0 / b for b in mid),
            deadline_s=batch_slo_ms * 4 / 1e3, slo_ms=batch_slo_ms,
        ),
        RequestClass(
            "bulk", 0.05, (bs[-1],), (1.0,),
            deadline_s=None, slo_ms=bulk_slo_ms,
        ),
    )


def slo_policy(classes: Sequence[RequestClass]) -> SLOPolicy:
    """The admission policy a class mix implies (docs/SERVING.md)."""
    return SLOPolicy([c.slo_class() for c in classes])


def assign_classes(
    classes: Sequence[RequestClass], n: int, seed: int = 0
) -> List[Tuple[RequestClass, int]]:
    """Seeded per-arrival (class, n_images) assignments — the same
    deterministic-schedule rule as the arrival offsets, so two runs at
    one seed offer byte-identical work."""
    rng = random.Random(f"classes:{seed}")
    weights = [c.weight for c in classes]
    out: List[Tuple[RequestClass, int]] = []
    for _ in range(n):
        c = rng.choices(list(classes), weights=weights)[0]
        size = rng.choices(list(c.sizes), weights=list(c.size_weights))[0]
        out.append((c, int(size)))
    return out


# ---------------------------------------------------------- accounting ---


def _fmt_ms(v: Optional[float]) -> str:
    return f"{v:.3f}" if v is not None else "nan"


@dataclasses.dataclass
class ClassStats:
    """One class's closed accounting + latency percentiles."""

    offered: int = 0
    ok: int = 0
    shed: int = 0
    failed: int = 0
    rejected: int = 0
    images_ok: int = 0
    latencies_ms: List[float] = dataclasses.field(default_factory=list)

    @property
    def closed(self) -> bool:
        return self.ok + self.shed + self.failed + self.rejected == self.offered

    def percentile(self, q: float) -> Optional[float]:
        from .loadgen import percentile  # local: avoid a module cycle

        return percentile(self.latencies_ms, q)

    def to_obj(self) -> dict:
        p50, p99 = self.percentile(50), self.percentile(99)
        return {
            "offered": self.offered, "ok": self.ok, "shed": self.shed,
            "failed": self.failed, "rejected": self.rejected,
            "p50_ms": round(p50, 3) if p50 is not None else None,
            "p99_ms": round(p99, 3) if p99 is not None else None,
        }


@dataclasses.dataclass
class ShapedReport:
    """One shaped load run's verdict, per class and total."""

    shape: str
    per_class: Dict[str, ClassStats]
    duration_s: float = 0.0
    sustained_img_s: float = 0.0

    def _total(self, field: str) -> int:
        return sum(getattr(c, field) for c in self.per_class.values())

    @property
    def n_requests(self) -> int:
        return self._total("offered")

    @property
    def n_ok(self) -> int:
        return self._total("ok")

    @property
    def n_shed(self) -> int:
        return self._total("shed")

    @property
    def n_failed(self) -> int:
        return self._total("failed")

    @property
    def n_rejected(self) -> int:
        return self._total("rejected")

    @property
    def closed(self) -> bool:
        """Accounting closes for EVERY class, not just in aggregate —
        a lost bulk request cannot hide behind a surplus interactive one."""
        return all(c.closed for c in self.per_class.values())

    def all_latencies(self) -> List[float]:
        out: List[float] = []
        for c in self.per_class.values():
            out.extend(c.latencies_ms)
        return out

    def summary(self) -> str:
        """Machine-parseable 'Serve load:' payload (run CLI contract)."""
        from .loadgen import percentile

        lat = self.all_latencies()
        p50, p99 = percentile(lat, 50), percentile(lat, 99)
        return (
            f"shape={self.shape} reqs={self.n_requests} ok={self.n_ok} "
            f"shed={self.n_shed} failed={self.n_failed} "
            f"rejected={self.n_rejected} "
            f"p50_ms={_fmt_ms(p50)} p99_ms={_fmt_ms(p99)} "
            f"img_s={self.sustained_img_s:.1f} wall_s={self.duration_s:.2f}"
        )

    def class_lines(self) -> List[str]:
        """One machine-parseable 'Serve class:' line per class."""
        out = []
        for name in sorted(self.per_class):
            c = self.per_class[name]
            out.append(
                f"Serve class: name={name or 'default'} offered={c.offered} "
                f"ok={c.ok} shed={c.shed} failed={c.failed} "
                f"rejected={c.rejected} p50_ms={_fmt_ms(c.percentile(50))} "
                f"p99_ms={_fmt_ms(c.percentile(99))}"
            )
        return out

    def to_obj(self) -> dict:
        return {
            "shape": self.shape,
            "classes": {
                (n or "default"): c.to_obj() for n, c in self.per_class.items()
            },
            "accounting_closed": self.closed,
        }

"""Backend fleet launcher: N serving processes behind one FleetRouter.

The CPU-testable stand-in ROADMAP item 3 names for one-frontend-per-host
over ``parallel/deploy.py``: each backend is a REAL OS process (module
entry ``python -m cuda_mpi_gpu_cluster_programming_tpu.serving.fleet
--child ...``) building its own :class:`~.server.InferenceServer` +
:class:`~.frontend.ServingFrontend` on an ephemeral port and announcing
readiness with one machine-parsed line::

    FLEET_READY name=b0 port=41231

so host loss is a process fault, not a thread fault — the drills that
matter (``host_loss`` chaos SIGKILLs a backend mid-load) exercise a
kill(2) across a process boundary, the thing every earlier drill
(device loss, SDC, flap) could not: those all die *inside* one process.

Each backend writes its own journal (``<journal_dir>/backend_<i>.jsonl``)
beside the router's (``<journal_dir>/router.jsonl``);
``observability.export.load_records`` on the directory stitches all of
them into one Perfetto timeline.

The parent-side :class:`BackendFleet` spawns/kills/restarts children:
``kill(i)`` is SIGKILL (host loss — no goodbye), ``restart(i)`` respawns
the slot on a NEW ephemeral port (a replacement host) and returns the
url for :meth:`FleetRouter.replace_backend` — the slot's hash-ring
position never moves, and the restarted backend still re-admits through
the router's probation.

:func:`maybe_host_loss` is the chaos consumer: the seeded ``host_loss``
site picks its victim as ``seed % n`` — deterministic per spec, like
every other chaos site.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from ..resilience import chaos

READY_PREFIX = "FLEET_READY"
_PKG_ROOT = Path(__file__).resolve().parents[2]  # repo root (package parent)


class FleetError(RuntimeError):
    pass


class BackendProc:
    """One spawned backend: process handle + announced endpoint."""

    def __init__(
        self, index: int, proc: subprocess.Popen, port: int, journal_path: str
    ):
        self.index = index
        self.name = f"b{index}"
        self.proc = proc
        self.port = port
        self.journal_path = journal_path

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def alive(self) -> bool:
        return self.proc.poll() is None


def _read_ready(proc: subprocess.Popen, timeout_s: float) -> int:
    """Scan child stdout for the READY line (bounded — a backend that
    never comes up is a spawn failure, not a hang). The scan runs in a
    helper thread so a wedged child can't block the launcher past its
    deadline."""
    found: List[int] = []
    err: List[str] = []

    def _scan() -> None:
        tail: List[str] = []
        for line in proc.stdout:  # type: ignore[union-attr]
            tail.append(line.rstrip()[-200:])
            if line.startswith(READY_PREFIX):
                for tok in line.split():
                    if tok.startswith("port="):
                        found.append(int(tok[5:]))
                        return
        err.append("; ".join(tail[-5:]))

    t = threading.Thread(target=_scan, daemon=True)
    t.start()
    t.join(timeout_s)
    if not found:
        proc.kill()
        tail = err[0] if err else "no output"
        raise FleetError(
            f"backend never announced {READY_PREFIX} within {timeout_s}s "
            f"(rc={proc.poll()}, tail: {tail})"
        )
    return found[0]


class BackendFleet:
    """Spawn and manage N backend serving processes.

    ``journal_dir`` receives one ``backend_<i>.jsonl`` per backend (and
    is where callers point the router's own journal, so one directory
    exports as one stitched timeline). Children inherit the environment
    plus ``JAX_PLATFORMS`` and a PYTHONPATH entry for the repo root, so
    the fleet spawns correctly from any cwd.
    """

    def __init__(
        self,
        n: int,
        journal_dir,
        *,
        height: int = 63,
        width: int = 63,
        max_batch: int = 4,
        config: str = "v1_jit",
        slo: bool = True,
        slo_scale: float = 1.0,
        spawn_timeout_s: float = 240.0,
        env: Optional[Dict[str, str]] = None,
        controller=None,
    ):
        if n < 1:
            raise ValueError("fleet needs n >= 1 backends")
        self.n = n
        self.journal_dir = Path(journal_dir)
        self.height, self.width = height, width
        self.max_batch, self.config, self.slo = max_batch, config, slo
        # Scales every class latency budget + deadline in the children
        # (SLOPolicy.scaled — the replay what-if dial, live): the fleet
        # pressure drill tightens SLOs so a CI-sized swell burns
        # measurably instead of hiding under second-scale budgets.
        self.slo_scale = slo_scale
        self.spawn_timeout_s = spawn_timeout_s
        self._extra_env = dict(env or {})
        # Optional ControllerConfig (or its to_obj dict): every child
        # runs an Autopilot — the fleet-control drills (ISSUE 20) need N
        # real controllers to arbitrate across.
        self.controller = controller
        self.backends: List[Optional[BackendProc]] = [None] * n

    def _spawn(self, index: int) -> BackendProc:
        jpath = str(self.journal_dir / f"backend_{index}.jsonl")
        cmd = [
            sys.executable, "-m",
            "cuda_mpi_gpu_cluster_programming_tpu.serving.fleet",
            "--child", "--name", f"b{index}",
            "--config", self.config,
            "--height", str(self.height), "--width", str(self.width),
            "--max-batch", str(self.max_batch),
            "--journal", jpath, "--port", "0",
        ]
        if self.slo:
            cmd.append("--slo")
            if self.slo_scale != 1.0:
                cmd.extend(["--slo-scale", repr(self.slo_scale)])
        if self.controller is not None:
            import json

            obj = (
                self.controller
                if isinstance(self.controller, dict)
                else self.controller.to_obj()
            )
            cmd.extend(["--controller", json.dumps(obj)])
        env = {**os.environ, **self._extra_env}
        env["PYTHONPATH"] = (
            str(_PKG_ROOT) + os.pathsep + env.get("PYTHONPATH", "")
        ).rstrip(os.pathsep)
        # Chaos must not recurse into children: the parent owns the
        # host_loss budget; a child re-drawing it would double-fire.
        env.pop(chaos.CHAOS_ENV, None)
        proc = subprocess.Popen(
            cmd,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        port = _read_ready(proc, self.spawn_timeout_s)
        return BackendProc(index, proc, port, jpath)

    def start(self) -> "BackendFleet":
        self.journal_dir.mkdir(parents=True, exist_ok=True)
        # Spawn all children first (they warm up concurrently), then
        # collect READY lines — fleet bring-up costs one warmup, not N.
        for i in range(self.n):
            self.backends[i] = self._spawn(i)
        return self

    def urls(self) -> List[str]:
        out = []
        for b in self.backends:
            if b is None:
                raise FleetError("fleet not started")
            out.append(b.url)
        return out

    def kill(self, index: int) -> None:
        """SIGKILL — host loss, no drain, no goodbye. The router finds
        out the way production does: requests die and probes miss."""
        b = self.backends[index]
        if b is not None:
            b.proc.kill()
            b.proc.wait(10.0)

    def restart(self, index: int) -> str:
        """Respawn a dead slot on a new ephemeral port (a replacement
        host keeps the slot's ring position, not its address). Returns
        the new url for ``FleetRouter.replace_backend``."""
        old = self.backends[index]
        if old is not None and old.alive:
            raise FleetError(f"backend {index} still alive; kill it first")
        self.backends[index] = self._spawn(index)
        return self.backends[index].url

    def stop(self) -> None:
        for b in self.backends:
            if b is None or not b.alive:
                continue
            b.proc.terminate()
        for b in self.backends:
            if b is None:
                continue
            try:
                b.proc.wait(10.0)
            except subprocess.TimeoutExpired:
                b.proc.kill()
                b.proc.wait(10.0)

    def __enter__(self) -> "BackendFleet":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_host_loss(fleet: BackendFleet) -> Optional[int]:
    """Fire the seeded ``host_loss`` chaos site if armed: SIGKILL one
    backend chosen as ``seed % n`` (deterministic per CHAOS_SPEC, the
    same discipline as every other site). Returns the killed index, or
    None when the site didn't fire."""
    ch = chaos.active()
    if ch is None or not ch.draw("host_loss"):
        return None
    idx = ch.spec.seed % fleet.n
    fleet.kill(idx)
    return idx


# ------------------------------------------------------------ child entry


def _child_main(argv: List[str]) -> int:
    """One backend process: InferenceServer + ServingFrontend on an
    ephemeral port, READY line on stdout, then park until killed."""
    import argparse
    import dataclasses

    ap = argparse.ArgumentParser(prog="serving.fleet --child")
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--name", default="b0")
    ap.add_argument("--config", default="v1_jit")
    ap.add_argument("--height", type=int, default=63)
    ap.add_argument("--width", type=int, default=63)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--journal", default="")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--slo", action="store_true")
    ap.add_argument("--slo-scale", type=float, default=1.0)
    ap.add_argument("--controller", default="")
    args = ap.parse_args(argv)

    from ..models.alexnet import BLOCKS12
    from .frontend import ServingFrontend
    from .server import InferenceServer, ServeConfig

    model_cfg = dataclasses.replace(
        BLOCKS12, in_height=args.height, in_width=args.width
    )
    slo = None
    if args.slo:
        from .batcher import power_of_two_buckets
        from .traffic import default_class_mix, slo_policy

        slo = slo_policy(
            default_class_mix(power_of_two_buckets(args.max_batch))
        )
        if args.slo_scale != 1.0:
            slo = slo.scaled(args.slo_scale)
    controller = None
    if args.controller:
        import json

        controller = json.loads(args.controller)
    srv = InferenceServer(
        ServeConfig(
            config=args.config,
            max_batch=args.max_batch,
            model_cfg=model_cfg,
            journal_path=args.journal or None,
            slo=slo,
            controller=controller,
        )
    )
    srv.start()
    fe = ServingFrontend(srv, port=args.port).start()
    print(f"{READY_PREFIX} name={args.name} port={fe.port}", flush=True)
    try:
        while True:  # host loss is SIGKILL; orderly stop is SIGTERM
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        fe.stop()
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(_child_main(sys.argv[1:]))

"""Poisson load generator + latency reporting for the serve bench.

Arrivals are an explicitly seeded Poisson process (``random.Random(seed)``
exponential inter-arrival gaps — deterministic schedule per seed, the same
reproducibility rule as PR 1's jittered backoff), submitted against a
running :class:`~.server.InferenceServer` on the caller's thread while the
server's dispatch thread drains them continuously.

The report separates the three ways a request can finish — OK, SHED
(deadline), FAILED — and computes p50/p99 over the OK latencies; sustained
img/s is completed images over the span from first submit to last
completion (arrival ramp included: the number a capacity planner can hold
against the offered rate). ``percentile`` is the nearest-rank estimator so
small smoke runs report an actually-observed latency, never an
interpolated one.

Stdlib + numpy only (no jax import).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, List, Optional

import numpy as np

from .queue import FAILED, OK, SHED, RequestHandle
from .server import InferenceServer
from .traffic import (
    ClassStats,
    RequestClass,
    ShapedReport,
    assign_classes,
    default_class_mix,
    shaped_arrivals,
)


def poisson_arrivals(
    rate_rps: float, duration_s: float, seed: int = 0
) -> List[float]:
    """Arrival offsets (seconds from start) of a seeded Poisson process."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = random.Random(f"loadgen:{seed}")
    t, out = 0.0, []
    while True:
        t += rng.expovariate(rate_rps)
        if t >= duration_s:
            return out
        out.append(t)


def percentile(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on empty input."""
    if not xs:
        return None
    s = sorted(xs)
    if q <= 0:
        return s[0]
    rank = int(np.ceil(q / 100.0 * len(s)))
    return s[min(max(rank, 1), len(s)) - 1]


@dataclasses.dataclass
class LoadReport:
    """One load run's verdict — everything the bench JSON row needs."""

    n_requests: int
    n_ok: int
    n_shed: int
    n_failed: int
    n_rejected: int  # admission-control refusals (QueueFull / too wide)
    p50_ms: Optional[float]
    p99_ms: Optional[float]
    sustained_img_s: float
    duration_s: float
    latencies_ms: List[float]

    def summary(self) -> str:
        """Machine-parseable 'Serve load:' payload for the run CLI."""
        p50 = f"{self.p50_ms:.3f}" if self.p50_ms is not None else "nan"
        p99 = f"{self.p99_ms:.3f}" if self.p99_ms is not None else "nan"
        return (
            f"reqs={self.n_requests} ok={self.n_ok} shed={self.n_shed} "
            f"failed={self.n_failed} rejected={self.n_rejected} "
            f"p50_ms={p50} p99_ms={p99} "
            f"img_s={self.sustained_img_s:.1f} wall_s={self.duration_s:.2f}"
        )


def run_load(
    server: InferenceServer,
    *,
    rate_rps: float,
    duration_s: float,
    seed: int = 0,
    make_input: Optional[Callable[[int], np.ndarray]] = None,
    deadline_s: Optional[float] = None,
    wait_timeout_s: float = 120.0,
) -> LoadReport:
    """Drive a started server with Poisson traffic and wait everything out.

    ``make_input(i)`` supplies the i-th request's (n, H, W, C) array;
    default is a single deterministic image matching the server's model
    geometry. Every submitted handle is awaited (bounded), so the report
    accounts for each request exactly once: ok + shed + failed +
    rejected == offered.
    """
    if make_input is None:
        m = server._model_cfg()
        img = np.ones((1, m.in_height, m.in_width, m.in_channels), np.float32)
        make_input = lambda i: img  # noqa: E731 — trivial default factory
    arrivals = poisson_arrivals(rate_rps, duration_s, seed)
    handles: List[RequestHandle] = []
    n_rejected = 0
    t0 = time.monotonic()
    for i, at in enumerate(arrivals):
        now = time.monotonic() - t0
        if at > now:
            time.sleep(at - now)
        try:
            handles.append(server.submit(make_input(i), deadline_s=deadline_s))
        except (ValueError, RuntimeError):
            n_rejected += 1  # QueueFull/too-wide: admission control, counted
    wait_deadline = time.monotonic() + wait_timeout_s
    for h in handles:
        h.wait(max(0.0, wait_deadline - time.monotonic()))
    ok = [h for h in handles if h.status == OK]
    lat = [h.latency_ms for h in ok if h.latency_ms is not None]
    completed_at = [h.completed_at for h in handles if h.completed_at is not None]
    wall = (max(completed_at) - t0) if completed_at else (time.monotonic() - t0)
    images_ok = sum(h.n_images for h in ok)
    return LoadReport(
        n_requests=len(handles) + n_rejected,
        n_ok=len(ok),
        n_shed=sum(1 for h in handles if h.status == SHED),
        n_failed=sum(1 for h in handles if h.status == FAILED),
        n_rejected=n_rejected,
        p50_ms=percentile(lat, 50),
        p99_ms=percentile(lat, 99),
        sustained_img_s=images_ok / wall if wall > 0 else 0.0,
        duration_s=wall,
        latencies_ms=lat,
    )


# ------------------------------------------------------- shaped traffic ---


def run_shaped_load(
    server: InferenceServer,
    *,
    shape: str = "steady",
    rate_rps: float,
    duration_s: float,
    classes: Optional[List[RequestClass]] = None,
    seed: int = 0,
    wait_timeout_s: float = 120.0,
) -> ShapedReport:
    """Drive a started server with traffic-shaped, class-mixed load.

    Arrivals come from :func:`~.traffic.shaped_arrivals` (diurnal ramps,
    bursts, flash crowds — seeded, deterministic); each arrival draws a
    seeded (class, n_images) assignment from the heavy-tailed mix
    (default: :func:`~.traffic.default_class_mix` over the server's
    bucket set) and submits with the class's own deadline. Every handle
    is awaited (bounded), so per-class accounting CLOSES: ok + shed +
    failed + rejected == offered for every class — the report's
    ``closed`` property is the drill's acceptance check.
    """
    if classes is None:
        classes = list(default_class_mix(server.buckets))
    m = server._model_cfg()
    imgs: dict = {}  # n_images -> cached input (allocation, not payload)

    def _input(n: int) -> np.ndarray:
        if n not in imgs:
            imgs[n] = np.ones(
                (n, m.in_height, m.in_width, m.in_channels), np.float32
            )
        return imgs[n]

    arrivals = shaped_arrivals(shape, rate_rps, duration_s, seed)
    plan = assign_classes(classes, len(arrivals), seed)
    stats: dict = {c.name: ClassStats() for c in classes}
    handles: List[tuple] = []  # (RequestClass, handle)
    t0 = time.monotonic()
    for (at, (c, n)) in zip(arrivals, plan):
        now = time.monotonic() - t0
        if at > now:
            time.sleep(at - now)
        st = stats[c.name]
        st.offered += 1
        try:
            handles.append(
                (c, server.submit(_input(n), deadline_s=c.deadline_s, cls=c.name))
            )
        except (ValueError, RuntimeError):
            st.rejected += 1  # QueueFull/too-wide: backpressure, counted
    wait_deadline = time.monotonic() + wait_timeout_s
    for _c, h in handles:
        h.wait(max(0.0, wait_deadline - time.monotonic()))
    images_ok = 0
    completed_at: List[float] = []
    for c, h in handles:
        st = stats[c.name]
        if h.completed_at is not None:
            completed_at.append(h.completed_at)
        if h.status == OK:
            st.ok += 1
            st.images_ok += h.n_images
            images_ok += h.n_images
            if h.latency_ms is not None:
                st.latencies_ms.append(h.latency_ms)
        elif h.status == SHED:
            st.shed += 1
        else:
            st.failed += 1
    wall = (max(completed_at) - t0) if completed_at else (time.monotonic() - t0)
    return ShapedReport(
        shape=shape,
        per_class=stats,
        duration_s=wall,
        sustained_img_s=images_ok / wall if wall > 0 else 0.0,
    )


# ------------------------------------------------------ saturation sweep ---


def locate_knee(rows: List[dict], factor: float = 3.0) -> Optional[float]:
    """The p99 knee of a saturation sweep: the first offered rate (img/s,
    ascending) whose journal p99 exceeds ``factor`` x the lowest measured
    rate's p99 — where the latency curve leaves its flat region and turns
    vertical. None when every swept rate stayed under the threshold (the
    sweep never crossed capacity — sweep higher)."""
    measured = [
        r for r in sorted(rows, key=lambda r: r["offered_img_s"])
        if isinstance(r.get("p99_ms"), (int, float))
    ]
    if not measured:
        return None
    base = measured[0]["p99_ms"]
    if base <= 0:
        return None
    for r in measured[1:]:
        if r["p99_ms"] > factor * base:
            return float(r["offered_img_s"])
    return None


def saturation_sweep(
    server: InferenceServer,
    rates_rps: List[float],
    *,
    duration_s: float,
    classes: Optional[List[RequestClass]] = None,
    shape: str = "steady",
    seed: int = 0,
    knee_factor: float = 3.0,
    journal_path: str = "",
) -> List[dict]:
    """Sweep offered load past capacity on ONE started server; one row
    dict per rate, each carrying the located ``knee_rate_img_s``.

    Per rate: the metrics registry is reset (so its ``serve.request_ms``
    percentiles cover exactly this rate's window), a shaped load runs,
    and percentiles are computed BOTH from the journal slice this rate
    appended and from the registry histogram — the same nearest-rank
    estimator over the same population, so the row can assert they agree
    (``percentiles_agree``). After the sweep the p99 knee is located
    (:func:`locate_knee`) and stamped on every row.
    """
    from ..observability.metrics import registry as metrics_registry
    from ..resilience.journal import Journal
    from .server import class_latencies_from_records, latencies_from_records

    if classes is None:
        classes = list(default_class_mix(server.buckets))
    rows: List[dict] = []
    for rate in sorted(rates_rps):
        n0 = len(Journal.load(journal_path)) if journal_path else 0
        misses0 = server.stats.cache_misses
        metrics_registry().reset()
        report = run_shaped_load(
            server, shape=shape, rate_rps=rate, duration_s=duration_s,
            classes=classes, seed=seed,
        )
        # Quiesce before reading: a handle wakes its waiter BEFORE the
        # dispatch thread's @off_timed_path completion helper finishes
        # journaling the batch, so the last batch's records can lag the
        # report by a scheduler slice. The rate's row must cover its whole
        # population (and the registry must be settled before the next
        # rate resets it) — poll, bounded.
        recs: List[dict] = []
        quiesce = time.monotonic() + 10.0
        while journal_path:
            recs = Journal.load(journal_path)[n0:]
            if (
                len(latencies_from_records(recs)) >= report.n_ok
                or time.monotonic() >= quiesce
            ):
                break
            time.sleep(0.01)
        jlat = latencies_from_records(recs)
        by_cls = class_latencies_from_records(recs)
        reg_p99 = metrics_registry().histogram("serve.request_ms").percentile(99)
        j_p99 = percentile(jlat, 99)
        rows.append(
            {
                "rate_rps": rate,
                "offered": report.n_requests,
                "offered_img_s": round(rate * _mean_images(classes), 3),
                "value": round(report.sustained_img_s, 1),
                "p50_ms": percentile(jlat, 50),
                "p99_ms": j_p99,
                "metrics_p99_ms": reg_p99,
                "percentiles_agree": (
                    j_p99 is not None and reg_p99 is not None
                    and abs(j_p99 - reg_p99) <= max(1e-6, 0.05 * j_p99)
                ),
                "classes": {
                    (n or "default"): {
                        **report.per_class[n].to_obj(),
                        "journal_p99_ms": percentile(by_cls.get(n, []), 99),
                    }
                    for n in report.per_class
                },
                "n_ok": report.n_ok,
                "n_shed": report.n_shed,
                "n_failed": report.n_failed,
                "n_rejected": report.n_rejected,
                "accounting_closed": report.closed,
                "cache_misses": server.stats.cache_misses - misses0,
                "duration_s": round(report.duration_s, 3),
                "shape": shape,
                "seed": seed,
            }
        )
    knee = locate_knee(rows, knee_factor)
    for r in rows:
        r["knee_rate_img_s"] = knee
        r["knee_factor"] = knee_factor
    return rows


def _mean_images(classes: List[RequestClass]) -> float:
    """Expected images per request under the mix — converts an arrival
    rate (req/s) into offered load (img/s), the knee's unit."""
    wsum = sum(c.weight for c in classes) or 1.0
    total = 0.0
    for c in classes:
        szw = sum(c.size_weights) or 1.0
        mean_sz = sum(s * w for s, w in zip(c.sizes, c.size_weights)) / szw
        total += (c.weight / wsum) * mean_sz
    return total


def saturating_rate(
    journal_path: str,
    classes: List[RequestClass],
    *,
    oversubscribe: float = 1.5,
    batch_efficiency: float = 1.0,
    fallback_img_s: float = 600.0,
    lo_rps: float = 150.0,
    hi_rps: float = 4000.0,
) -> float:
    """Pick a saturating request rate from a capacity probe's measured
    service throughput — the autopilot A/B's anti-flake (docs/SERVING.md
    "Autopilot").

    A FIXED saturating rate cannot survive hosts whose speed varies 3x:
    too low and the controller-off side never burns (the A/B goes
    vacuous), too high and BOTH sides peg at the burn cap. The peg is
    structural, not a tuning artifact: under shed-at-cut overload every
    SERVED request has queue wait near the shed cut, so its end-to-end
    latency violates the SLO too — violation share goes to ~1 as soon
    as the protected class alone saturates. The usable regime is a rate
    whose total offered load oversubscribes capacity while the
    protected class ALONE still fits — there, shedding the unprotected
    classes visibly rescues the protected one (the default mix's
    protected class is ~half the image load, so 1.5x total puts it at
    ~0.75x capacity).

    ``journal_path`` should come from a short SATURATED, SLO-free,
    controller-free probe: with no shed path, every batch runs at the
    service's real (max_batch) batching, so the journal's busy
    throughput (``n_images / batch_ms`` over ``serve_batch`` records)
    IS the capacity — ``batch_efficiency`` stays 1.0. For a CALM probe
    (small batches under-drive the batcher) pass ~1.5. The img/s
    estimate converts to req/s via the mix's expected images/request,
    times ``oversubscribe``, clamped to [lo_rps, hi_rps];
    ``fallback_img_s`` covers a journal with no batches.
    """
    from ..resilience.journal import Journal

    imgs = 0.0
    busy_ms = 0.0
    for r in Journal.load(journal_path):
        if r.get("kind") == "serve_batch" and r.get("batch_ms"):
            imgs += float(r.get("n_images", 0))
            busy_ms += float(r["batch_ms"])
    busy_img_s = imgs / (busy_ms / 1000.0) if busy_ms else fallback_img_s
    cap_img_s = batch_efficiency * busy_img_s
    rate = oversubscribe * cap_img_s / max(_mean_images(classes), 1e-9)
    return min(hi_rps, max(lo_rps, rate))


def correlated_pressure(
    duration_s: float, *, amp: float = 0.9, period_s: Optional[float] = None
) -> str:
    """The fleet-control drill's load shape (ISSUE 20): one diurnal
    swell whose crest hits EVERY backend at once — deterministic routing
    spreads rids uniformly, so a fleet-wide ramp is per-backend
    correlated pressure, the exact failure mode N uncoordinated
    Autopilots all-degrade under. With the default ``amp=0.9`` the
    crest carries 1.9x the base rate at ``period/2`` and the trough
    ~0.1x — callers size the base at ~0.8x fleet capacity so the crest
    oversubscribes while the protected class alone still fits. Returns
    a ``traffic.parse_shape`` spec string.
    """
    period = duration_s if period_s is None else period_s
    return f"diurnal:amp={amp},period={period}"


def maybe_fleet_pressure(
    rate_rps: float, duration_s: float, *, amp: float = 0.9
) -> Optional[str]:
    """Chaos consumer for the seeded ``fleet_pressure`` site: when the
    site fires, the drill's load becomes a correlated diurnal swell
    (:func:`correlated_pressure`) over the whole window. Returns the
    shape spec to feed ``run_shaped_load``/``http_fleet_load``, or None
    when the site didn't fire (callers keep their calm shape). The
    swell is deterministic per CHAOS_SPEC seed — same discipline as
    every other site."""
    from ..resilience import chaos

    ch = chaos.active()
    if ch is None or not ch.draw("fleet_pressure"):
        return None
    return correlated_pressure(duration_s, amp=amp)

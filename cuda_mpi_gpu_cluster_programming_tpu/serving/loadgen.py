"""Poisson load generator + latency reporting for the serve bench.

Arrivals are an explicitly seeded Poisson process (``random.Random(seed)``
exponential inter-arrival gaps — deterministic schedule per seed, the same
reproducibility rule as PR 1's jittered backoff), submitted against a
running :class:`~.server.InferenceServer` on the caller's thread while the
server's dispatch thread drains them continuously.

The report separates the three ways a request can finish — OK, SHED
(deadline), FAILED — and computes p50/p99 over the OK latencies; sustained
img/s is completed images over the span from first submit to last
completion (arrival ramp included: the number a capacity planner can hold
against the offered rate). ``percentile`` is the nearest-rank estimator so
small smoke runs report an actually-observed latency, never an
interpolated one.

Stdlib + numpy only (no jax import).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, List, Optional

import numpy as np

from .queue import FAILED, OK, SHED, RequestHandle
from .server import InferenceServer


def poisson_arrivals(
    rate_rps: float, duration_s: float, seed: int = 0
) -> List[float]:
    """Arrival offsets (seconds from start) of a seeded Poisson process."""
    if rate_rps <= 0 or duration_s <= 0:
        return []
    rng = random.Random(f"loadgen:{seed}")
    t, out = 0.0, []
    while True:
        t += rng.expovariate(rate_rps)
        if t >= duration_s:
            return out
        out.append(t)


def percentile(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]); None on empty input."""
    if not xs:
        return None
    s = sorted(xs)
    if q <= 0:
        return s[0]
    rank = int(np.ceil(q / 100.0 * len(s)))
    return s[min(max(rank, 1), len(s)) - 1]


@dataclasses.dataclass
class LoadReport:
    """One load run's verdict — everything the bench JSON row needs."""

    n_requests: int
    n_ok: int
    n_shed: int
    n_failed: int
    n_rejected: int  # admission-control refusals (QueueFull / too wide)
    p50_ms: Optional[float]
    p99_ms: Optional[float]
    sustained_img_s: float
    duration_s: float
    latencies_ms: List[float]

    def summary(self) -> str:
        """Machine-parseable 'Serve load:' payload for the run CLI."""
        p50 = f"{self.p50_ms:.3f}" if self.p50_ms is not None else "nan"
        p99 = f"{self.p99_ms:.3f}" if self.p99_ms is not None else "nan"
        return (
            f"reqs={self.n_requests} ok={self.n_ok} shed={self.n_shed} "
            f"failed={self.n_failed} rejected={self.n_rejected} "
            f"p50_ms={p50} p99_ms={p99} "
            f"img_s={self.sustained_img_s:.1f} wall_s={self.duration_s:.2f}"
        )


def run_load(
    server: InferenceServer,
    *,
    rate_rps: float,
    duration_s: float,
    seed: int = 0,
    make_input: Optional[Callable[[int], np.ndarray]] = None,
    deadline_s: Optional[float] = None,
    wait_timeout_s: float = 120.0,
) -> LoadReport:
    """Drive a started server with Poisson traffic and wait everything out.

    ``make_input(i)`` supplies the i-th request's (n, H, W, C) array;
    default is a single deterministic image matching the server's model
    geometry. Every submitted handle is awaited (bounded), so the report
    accounts for each request exactly once: ok + shed + failed +
    rejected == offered.
    """
    if make_input is None:
        m = server._model_cfg()
        img = np.ones((1, m.in_height, m.in_width, m.in_channels), np.float32)
        make_input = lambda i: img  # noqa: E731 — trivial default factory
    arrivals = poisson_arrivals(rate_rps, duration_s, seed)
    handles: List[RequestHandle] = []
    n_rejected = 0
    t0 = time.monotonic()
    for i, at in enumerate(arrivals):
        now = time.monotonic() - t0
        if at > now:
            time.sleep(at - now)
        try:
            handles.append(server.submit(make_input(i), deadline_s=deadline_s))
        except (ValueError, RuntimeError):
            n_rejected += 1  # QueueFull/too-wide: admission control, counted
    wait_deadline = time.monotonic() + wait_timeout_s
    for h in handles:
        h.wait(max(0.0, wait_deadline - time.monotonic()))
    ok = [h for h in handles if h.status == OK]
    lat = [h.latency_ms for h in ok if h.latency_ms is not None]
    completed_at = [h.completed_at for h in handles if h.completed_at is not None]
    wall = (max(completed_at) - t0) if completed_at else (time.monotonic() - t0)
    images_ok = sum(h.n_images for h in ok)
    return LoadReport(
        n_requests=len(handles) + n_rejected,
        n_ok=len(ok),
        n_shed=sum(1 for h in handles if h.status == SHED),
        n_failed=sum(1 for h in handles if h.status == FAILED),
        n_rejected=n_rejected,
        p50_ms=percentile(lat, 50),
        p99_ms=percentile(lat, 99),
        sustained_img_s=images_ok / wall if wall > 0 else 0.0,
        duration_s=wall,
        latencies_ms=lat,
    )

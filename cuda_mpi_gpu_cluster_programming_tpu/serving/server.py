"""The continuous-batching inference server: queue -> bucket -> dispatch
-> (degrade) lifecycle around ``configs.build_forward``.

Dispatch discipline (docs/SERVING.md):

- **Warmup compiles everything, dispatch compiles nothing.** At
  :meth:`InferenceServer.start` every bucket shape is compiled once (the
  PR 2 persistent compile cache makes that cheap across restarts); a
  dispatched batch whose bucket shape is not warmed on the current rung is
  counted as a ``cache_miss`` — the acceptance number that must be zero
  after warmup.
- **Every batch is journaled** (``serve_batch`` records with per-request
  latencies; ``serve_shed``/``serve_fail`` for the explicit loss paths) via
  PR 3's fsync'd ``Journal``, so the bench's p50/p99 come from the same
  crash-consistent trail every other artifact uses.
- **Degradation, not 500s.** With ``supervise=True`` the forward is the
  PR 5 elastic :class:`~..resilience.supervisor.Supervisor`: an SDC trip or
  device loss mid-batch re-plans down the ladder, re-warms every bucket on
  the new rung (``on_rebuild``), and REPLAYS the in-flight batch — callers
  get answers, late, instead of errors.
- **Deadline-aware shedding.** Expired requests complete with status
  ``SHED`` at assembly time and are journaled — never silently dropped.
- **Every run is replayable.** The journal carries the full arrival
  schedule, not just the outcomes: one ``serve_config`` record at build
  time (config / shards / buckets / SLO policy / model geometry) and one
  ``serve_submit`` record per admission attempt (arrival offset, request
  shape, class, resolved deadline, admitted-or-rejected). Together with
  the ``sup_*``/``mesh_*`` incident records they are exactly what
  ``observability.replay`` needs to re-drive the run — same arrivals,
  same chaos schedule — on a live server (docs/OBSERVABILITY.md
  "Replay & regression gating").

The dispatch loop keeps host syncs out of its body (staticcheck's
``host-sync-in-hot-loop`` rule now covers this file): the timed region
lives in ``_dispatch``, and result slicing/journal writes run in
``@off_timed_path`` completion helpers, the same contract the supervisor's
screening uses.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability.metrics import registry as metrics_registry
from ..observability.trace import current_ids, get_tracer, span
from ..resilience.journal import Journal
from ..resilience.sentinel import off_timed_path
from .batcher import AssembledBatch, Batcher, power_of_two_buckets
from .queue import FAILED, OK, AdmissionQueue, QueueFull, Request, RequestHandle


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """How to build and run the service (CLI/bench surface in one place)."""

    config: str = "v1_jit"  # configs.REGISTRY key (blocks12 family)
    n_shards: int = 1
    # The precision policy the service runs (and warms, and derives its
    # tuned bucket set at): a policy name — fp32 | bf16 | int8w
    # (docs/PRECISION.md). ``policy`` records HOW it was chosen
    # (compute|dtype|policy|tuned — the run CLI's Precision source token)
    # so journals/bench rows stay attributable.
    compute: str = "fp32"
    policy: str = ""
    max_batch: int = 8
    # None = powers of two up to max_batch, or the TunePlan-derived set
    # when plan_path names a plan covering this point (tuning.plan_batches).
    buckets: Optional[Tuple[int, ...]] = None
    plan_path: str = ""
    supervise: bool = False
    journal_path: str = ""
    max_pending: int = 1024
    poll_s: float = 0.02
    default_deadline_s: Optional[float] = None
    model_cfg: Any = None  # Blocks12Config override (tests use 63x63)
    # Optional serving.slo.SLOPolicy: per-class SLO targets with pop-time
    # shed-by-class (docs/SERVING.md "Network front end & SLOs"). None =
    # the PR 6 behavior (hard deadlines only).
    slo: Any = None
    # Live resource telemetry cadence (docs/OBSERVABILITY.md "Roofline
    # attribution"): every ``mem_snapshot_s`` seconds the dispatch loop
    # journals one ``serve_gauges`` (queue depth / pending images /
    # oldest wait) and one ``mem_snapshot`` (device memory_stats, RSS
    # fallback) record — strictly off the timed path, exported as
    # Perfetto counter tracks. 0 disables.
    mem_snapshot_s: float = 1.0
    # Optional serving.controller.ControllerConfig: the autopilot — a
    # journaled closed-loop controller evaluated from the observation
    # cadence that trades admission, bucket width, precision, and
    # capacity for the protected class's SLO under pressure
    # (docs/SERVING.md "Autopilot"). None = every knob stays fixed at
    # build time (the pre-PR 18 behavior).
    controller: Any = None


@dataclasses.dataclass
class ServeStats:
    """Steady-state counters the bench row and CLI line surface."""

    n_batches: int = 0
    n_images: int = 0
    n_ok: int = 0
    n_shed: int = 0
    n_failed: int = 0
    warmup_compiles: int = 0
    cache_misses: int = 0  # post-warmup dispatches at an un-warmed shape
    rewarm_ms: float = 0.0  # wall ms spent re-compiling buckets on degrades
    promotions: int = 0  # supervised grow-back climbs committed mid-serve
    batch_ms: List[float] = dataclasses.field(default_factory=list)

    def summary(self) -> str:
        return (
            f"batches={self.n_batches} images={self.n_images} ok={self.n_ok} "
            f"shed={self.n_shed} failed={self.n_failed} "
            f"cache_misses={self.cache_misses} warmups={self.warmup_compiles}"
        )


class InferenceServer:
    """Continuous-batching service over one execution config.

    Two run modes: :meth:`start`/:meth:`stop` spin the dispatch loop on a
    background thread (the load-generator path), while
    :meth:`run_until_drained` runs it inline until the queue empties — the
    deterministic path the chaos drills and tests use (batch assembly then
    depends only on submission order, never on thread timing).
    """

    def __init__(self, cfg: ServeConfig, params=None, plan=None, ladder=None):
        # ``ladder``: explicit supervisor LadderEntry list (supervise mode
        # only) — the chaos drills pin a clean comparison server to the
        # exact rung a faulted run degraded to.
        self.cfg = cfg
        self._ladder = ladder
        self.queue = AdmissionQueue(max_pending=cfg.max_pending, slo=cfg.slo)
        self.stats = ServeStats()
        self.journal = Journal(cfg.journal_path) if cfg.journal_path else None
        self._plan = plan
        self._params = params
        self._fwd = None
        self.sup = None  # the Supervisor in supervise mode (drill surface)
        self._warmed: set = set()  # bucket sizes compiled on the current rung
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._started = False
        # The journal epoch every serve_submit arrival offset is relative
        # to; replay only needs the offsets' relative spacing, so the
        # construction instant is as good an epoch as any.
        self._epoch = time.monotonic()
        self._seq_submit = 0
        self._seq_snapshot = 0
        self._last_snapshot = 0.0  # monotonic: first _step snapshots
        self._submit_lock = threading.Lock()  # submit() is thread-safe
        self._compute_override: Optional[str] = None  # live dtype downshift
        self.buckets = self._resolve_buckets()
        self._batcher = Batcher(self.queue, self.buckets)
        self.controller = None
        if cfg.controller is not None:
            from .controller import AutopilotController, ControllerConfig

            ctl_cfg = (
                cfg.controller
                if isinstance(cfg.controller, ControllerConfig)
                else ControllerConfig.from_obj(cfg.controller)
            )
            self.controller = AutopilotController(self, ctl_cfg)

    # ------------------------------------------------------------- building

    def _resolve_buckets(self) -> Tuple[int, ...]:
        cfg = self.cfg
        if cfg.buckets:
            return tuple(sorted(set(int(b) for b in cfg.buckets)))
        if cfg.plan_path:
            import jax

            from ..models.alexnet import BLOCKS12
            from ..tuning.plan import plan_batches

            tuned = plan_batches(
                cfg.plan_path,
                device_kind=jax.devices()[0].device_kind,
                model_cfg=cfg.model_cfg or BLOCKS12,
                dtype=cfg.compute,
            )
            tuned = [b for b in tuned if b <= cfg.max_batch]
            if tuned:
                return tuple(tuned)
        return power_of_two_buckets(cfg.max_batch)

    def _model_cfg(self):
        from ..models.alexnet import BLOCKS12

        return self.cfg.model_cfg if self.cfg.model_cfg is not None else BLOCKS12

    @property
    def current_compute(self) -> str:
        """The precision the service is running RIGHT NOW — the build
        compute unless the autopilot has a live dtype override installed
        (``apply_compute``)."""
        return self._compute_override or self.cfg.compute

    def _build(self) -> None:
        from ..configs import REGISTRY, build_forward
        from ..models.init import init_params_deterministic

        cfg = self.cfg
        exec_cfg = REGISTRY[cfg.config]
        if exec_cfg.model != "blocks12":
            raise ValueError(
                f"serving supports the Blocks 1-2 configs only, got {cfg.config!r}"
            )
        model_cfg = self._model_cfg()
        if self._params is None:
            self._params = init_params_deterministic(model_cfg)
        if cfg.supervise:
            from ..resilience.supervisor import Supervisor, default_ladder

            self.sup = Supervisor(
                model_cfg,
                self._ladder
                or default_ladder(exec_cfg.strategy, exec_cfg.tier, cfg.n_shards),
                plan=self._plan,
                journal=self.journal,
                on_rebuild=self._rewarm,
                site="serve",
            )
        else:
            self._fwd = build_forward(
                exec_cfg,
                model_cfg,
                n_shards=cfg.n_shards,
                compute=cfg.compute,
                plan=self._plan,
            )

    def _warm_input(self, bucket: int) -> np.ndarray:
        m = self._model_cfg()
        return np.zeros(
            (bucket, m.in_height, m.in_width, m.in_channels), np.float32
        )

    @off_timed_path
    def _note_compile(self, xb: np.ndarray, ms: float, *, hit: bool) -> None:
        """Journal one ``compile_event`` for an UNSUPERVISED warmup compile
        (the supervised path journals through the supervisor's ledger) —
        observability.health folds these into compile-cost attribution."""
        if self.journal is None:
            return
        from ..configs import REGISTRY
        from ..observability.health import compile_event, journal_compile_event

        strategy = REGISTRY[self.cfg.config].strategy
        journal_compile_event(
            self.journal,
            compile_event(
                site="serve",
                entry=self.cfg.config,
                shape=xb.shape,
                dtype=self.current_compute,
                ms=ms,
                cache_hit=hit,
                n_shards=(self.cfg.n_shards if strategy != "single" else 1),
                fn=None if hit else self._fwd,
                args=(self._params, xb),
            ),
        )

    @off_timed_path
    def warmup(self) -> None:
        """Compile every bucket shape now, before any request is waiting.
        After this, a dispatch that compiles is a counted cache miss.
        Off the timed path by contract: warmup fences are setup cost, not
        serving latency."""
        with span("serve.warmup", buckets=list(self.buckets)):
            for bucket in self.buckets:
                self._warm_bucket(bucket)

    @off_timed_path
    def _warm_bucket(self, bucket: int) -> float:
        """Compile ONE bucket shape on the current rung/precision and
        journal it — warmup's unit, shared with the autopilot's actuation
        paths (bucket widening and dtype shifts re-warm through here, so
        post-actuation dispatch stays a compile-cache hit)."""
        import jax

        xb = self._warm_input(bucket)
        if self.sup is not None:
            # compile_event journaling rides the supervisor's
            # per-(rung, shape) ledger inside warm().
            ms = self.sup.warm(self._params, xb)
        else:
            t0 = time.perf_counter()
            jax.block_until_ready(self._fwd(self._params, xb))
            ms = (time.perf_counter() - t0) * 1e3
            self._note_compile(xb, ms, hit=bucket in self._warmed)
        self.stats.warmup_compiles += 1
        self._warmed.add(bucket)
        self._journal(
            "serve_warm", key=f"warm:b{bucket}", bucket=bucket,
            ms=round(ms, 3), dtype=self.current_compute,
        )
        return ms

    def _rewarm(self, entry) -> None:
        """Supervisor on_rebuild hook: a degrade landed on a fresh rung, so
        every bucket must compile again BEFORE the failed batch replays —
        re-warming here keeps the replay itself a cache hit and the
        steady-state miss count at zero across degradations. The params are
        live-resharded onto the rung's surviving-device mesh FIRST, so the
        warm compiles land on exactly the placement the replay (which the
        supervisor reshards the same way) will dispatch with — after a
        mesh shrink nothing here touches a lost device."""
        with span("serve.rewarm", entry=entry.key):
            self._warmed.clear()
            self._params = self.sup.reshard(self._params)
            ms = 0.0
            for bucket in self.buckets:
                ms += self.sup.warm(self._params, self._warm_input(bucket))
                self.stats.warmup_compiles += 1
                self._warmed.add(bucket)
            self.stats.rewarm_ms += ms
            metrics_registry().counter("serve.rewarms").inc()
            self._journal(
                "serve_rewarm", key=f"rewarm:{entry.key}", entry=entry.key,
                buckets=list(self.buckets), ms=round(ms, 3),
                devices=self.sup.pool.n_alive,
            )

    # ------------------------------------------------------------ lifecycle

    def start(self) -> "InferenceServer":
        """Build, warm every bucket, then serve on a background thread."""
        if self._started:
            raise RuntimeError("server already started")
        self._ensure_built()
        self._started = True
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True
        )
        self._thread.start()
        return self

    def _ensure_built(self) -> None:
        if self._fwd is None and self.sup is None:
            self._build()
            self._journal_config()
            self.warmup()

    @off_timed_path
    def _journal_config(self) -> None:
        """One ``serve_config`` record per built server: the exact
        conditions this run serves under, so a replay
        (observability.replay) can rebuild an equivalent server from the
        journal ALONE — config, topology, bucket set, SLO policy, model
        geometry. Written before warmup so even a run killed mid-warm
        leaves a replayable header."""
        m = self._model_cfg()
        cfg = self.cfg
        self._journal(
            "serve_config",
            key="config",
            config=cfg.config,
            n_shards=cfg.n_shards,
            compute=cfg.compute,
            max_batch=cfg.max_batch,
            buckets=list(self.buckets),
            max_pending=cfg.max_pending,
            poll_s=cfg.poll_s,
            default_deadline_s=cfg.default_deadline_s,
            supervise=cfg.supervise,
            height=m.in_height,
            width=m.in_width,
            channels=m.in_channels,
            slo=cfg.slo.to_obj() if cfg.slo is not None else None,
            devices=self.sup.pool.n_alive if self.sup is not None else 1,
            # The autopilot's knobs (None = uncontrolled): a replay
            # rebuilds the exact controller from this, and the
            # --controller on|off A/B overrides it (observability.replay).
            controller=(
                self.controller.cfg.to_obj()
                if self.controller is not None
                else None
            ),
        )

    def stop(self, drain: bool = True, timeout_s: float = 60.0) -> None:
        """Stop the dispatch thread; with ``drain`` (default) the loop
        first finishes everything already admitted."""
        if self._thread is None:
            return
        if drain:
            deadline = time.monotonic() + timeout_s
            while len(self.queue) and time.monotonic() < deadline:
                time.sleep(0.005)
        self._stop.set()
        self._thread.join(timeout_s)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._step()

    def run_until_drained(self) -> None:
        """Inline dispatch until the queue is empty — the deterministic
        mode: with all requests pre-submitted, batch assembly depends only
        on FIFO order and the bucket set (chaos drills compare two such
        runs bit-for-bit)."""
        self._ensure_built()
        while len(self.queue):
            self._step()

    # ------------------------------------------------------------- dispatch

    def _step(self) -> None:
        # Grow-back check FIRST, strictly between batches (off the dispatch
        # timed region): a healed+graduated pool promotes the supervisor —
        # and re-warms every bucket at the higher rung — before the next
        # batch is even assembled, so in-flight requests are never dropped
        # and no post-promotion dispatch can miss the compile cache.
        self._maybe_promote()
        self._observe_queue()
        self._observe_resources()
        self._observe_controller()
        batch, shed = self._batcher.next_batch(self.cfg.poll_s)
        if shed:
            self._record_shed(shed)
        if batch is not None:
            self._dispatch(batch)

    @off_timed_path
    def _observe_queue(self) -> None:
        """Mirror the queue's saturation gauges into the metrics registry
        between batches — ``serve.queue_oldest_wait_ms`` climbs toward the
        tightest class SLO while every request is still servable, so
        saturation is observable BEFORE the first shed (docs/SERVING.md).
        O(1) per step; strictly off the dispatch timed region."""
        qs = self.queue.stats()
        reg = metrics_registry()
        reg.gauge("serve.queue_depth").set(qs.depth)
        reg.gauge("serve.queue_pending_images").set(qs.pending_images)
        reg.gauge("serve.queue_oldest_wait_ms").set(qs.oldest_wait_ms)

    @off_timed_path
    def _observe_resources(self) -> None:
        """Live resource telemetry, throttled to ``cfg.mem_snapshot_s``
        (docs/OBSERVABILITY.md "Roofline attribution"): one
        ``serve_gauges`` journal record (the queue saturation trio) and
        one ``mem_snapshot`` record (device ``memory_stats()`` summed
        over local devices, process-RSS fallback with ``source`` named)
        per interval, plus the ``mem.*`` registry gauges. Strictly off
        the dispatch timed region; journal-less servers keep the gauges
        and skip the records."""
        if self.cfg.mem_snapshot_s <= 0:
            return
        now = time.monotonic()
        if now - self._last_snapshot < self.cfg.mem_snapshot_s:
            return
        self._last_snapshot = now
        from ..observability.specs import device_memory_stats

        snap = device_memory_stats()
        reg = metrics_registry()
        for field in ("bytes_in_use", "peak_bytes_in_use"):
            if isinstance(snap.get(field), (int, float)):
                reg.gauge(f"mem.{field}").set(snap[field])
        if self.journal is None:
            return
        qs = self.queue.stats()
        self._seq_snapshot += 1
        t_ms = round((now - self._epoch) * 1e3, 3)
        self._journal(
            "serve_gauges",
            key=f"gauges:{self._seq_snapshot}",
            t_ms=t_ms,
            depth=qs.depth,
            pending_images=qs.pending_images,
            oldest_wait_ms=qs.oldest_wait_ms,
            # Controller ladder depth rides the gauge record (ISSUE 20)
            # so the degrade trajectory is a counter series beside the
            # queue trio; absent without an Autopilot — pre-20 journals
            # export unchanged.
            **(
                {"ctl_level": self.controller.level}
                if self.controller is not None
                else {}
            ),
        )
        self._journal(
            "mem_snapshot", key=f"mem:{self._seq_snapshot}", t_ms=t_ms, **snap
        )

    @off_timed_path
    def _maybe_promote(self) -> None:
        """Between-batches grow-back (docs/RESILIENCE.md "Grow-back &
        hysteresis"): retry pending heals against a fresh device re-query
        and, when the eligible pool satisfies a higher rung, run the
        supervised promotion. The supervisor's ``on_rebuild`` hook fires
        ``_rewarm`` inside the promotion, so every bucket is compiled at
        the higher rung before this returns — the cutover costs zero
        cache misses on the post-promotion dispatch path."""
        if self.sup is None:
            return
        state = self.sup.maybe_promote(self._params)
        if state is not None:
            self._params = state
            self.stats.promotions += 1
            metrics_registry().counter("serve.promotions").inc()

    @off_timed_path
    def _observe_controller(self) -> None:
        """Autopilot evaluation (docs/SERVING.md "Autopilot"), on the
        same between-batches observation cadence as the queue/resource
        gauges — the controller folds signals and (rarely) actuates, all
        strictly off the dispatch timed region."""
        if self.controller is not None:
            self.controller.evaluate(time.monotonic())

    # --------------------------------------------------- controller hooks
    #
    # The autopilot's actuation surface: each method swaps ONE live knob
    # in place, reversibly, between batches. The controller journals the
    # decision (``controller_action`` with evidence); these journal only
    # what the equivalent build-time path already journals (warm/rewarm
    # records), so the trail stays one vocabulary.

    @off_timed_path
    def apply_slo_policy(self, policy) -> None:
        """Swap the queue's pop-time admission policy. The queue reads
        ``self.slo`` per pop under its own lock, so an atomic attribute
        swap is the whole cutover — in-flight requests see the new cuts
        on their next pop, admitted work is never dropped retroactively."""
        self.queue.slo = policy

    @off_timed_path
    def apply_buckets(self, buckets) -> float:
        """Swap the active bucket set (narrow under pressure, widen on
        recovery). Any bucket not compiled on the current rung is warmed
        FIRST (a widen after a mid-narrow rewarm would otherwise compile
        on the request path), then the batcher is rebuilt over the new
        set — its dispatch seq carries over so journal keys stay unique.
        Returns the wall ms spent warming (0 = pure resize)."""
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not buckets:
            raise ValueError("bucket set cannot be empty")
        ms = 0.0
        for bucket in buckets:
            if bucket not in self._warmed:
                ms += self._warm_bucket(bucket)
        seq = self._batcher._seq
        self.buckets = buckets
        self._batcher = Batcher(self.queue, buckets)
        self._batcher._seq = seq
        return ms

    @off_timed_path
    def apply_compute(self, compute: str) -> float:
        """Rebuild the UNSUPERVISED forward at a new precision policy and
        re-warm every bucket before the next dispatch — the autopilot's
        dtype downshift/upshift, ToleranceGate-screened by the caller
        (no silent adoption; the supervisor's rungs carry no dtype axis,
        so supervised servers degrade capacity instead). Journals one
        ``serve_rewarm`` (the same record a ladder rebuild writes) and
        returns its wall ms."""
        if self.sup is not None:
            raise RuntimeError(
                "dtype actuation is unsupervised-only — supervised "
                "servers degrade through the ladder"
            )
        from ..configs import REGISTRY, build_forward

        self._fwd = build_forward(
            REGISTRY[self.cfg.config],
            self._model_cfg(),
            n_shards=self.cfg.n_shards,
            compute=compute,
            plan=self._plan,
        )
        self._compute_override = (
            compute if compute != self.cfg.compute else None
        )
        self._warmed.clear()
        ms = 0.0
        for bucket in self.buckets:
            ms += self._warm_bucket(bucket)
        self.stats.rewarm_ms += ms
        metrics_registry().counter("serve.rewarms").inc()
        self._journal(
            "serve_rewarm", key=f"rewarm:dtype:{compute}",
            entry=self.cfg.config, buckets=list(self.buckets),
            ms=round(ms, 3), dtype=compute, devices=1,
        )
        return ms

    @off_timed_path
    def request_degrade(self, cause: str) -> bool:
        """Ask the supervisor DOWN one rung as a capacity decision (the
        autopilot's load-pressure rung) — same degrade walk, re-warm, and
        journal trail as a fault trip, but with a ``requested:`` cause.
        False when unsupervised or already at the floor."""
        if self.sup is None:
            return False
        return self.sup.request_degrade(cause)

    @off_timed_path
    def request_promote(self) -> bool:
        """The explicit grow-back half: climb one rung, sentinel-verified
        like any promotion (a refusal journals ``sup_promote_refused``
        and leaves the rung as-is). False when nothing was adopted."""
        if self.sup is None:
            return False
        state = self.sup.request_promote(self._params)
        if state is None:
            return False
        self._params = state
        self.stats.promotions += 1
        metrics_registry().counter("serve.promotions").inc()
        return True

    def _dispatch(self, batch: AssembledBatch) -> None:
        """One timed region: pad -> run -> fence. Completion (slicing,
        handle wakeups, journal append) happens off the timed path."""
        import jax

        if batch.bucket not in self._warmed:
            # Post-warmup compile on the request path — the exact failure
            # the bucket discipline exists to prevent. Counted AND
            # journaled, then warmed so it can only fire once per shape.
            self.stats.cache_misses += 1
            metrics_registry().counter("serve.cache_misses").inc()
            self._journal(
                "serve_miss", key=f"miss:b{batch.bucket}", bucket=batch.bucket
            )
        xb = batch.padded_input()
        t0 = time.perf_counter()
        try:
            if self.sup is not None:
                out = self.sup.execute(self._params, xb)
            else:
                out = self._fwd(self._params, xb)
                jax.block_until_ready(out)
        except Exception as e:  # noqa — terminal failure: ladder exhausted
            # (supervise) or the bare forward raised. Every in-flight
            # request completes FAILED with the cause — no hung handles.
            self._record_failed(batch, e)
            return
        batch_ms = (time.perf_counter() - t0) * 1e3
        self._warmed.add(batch.bucket)
        self._complete(batch, out, batch_ms)

    @off_timed_path
    def _complete(self, batch: AssembledBatch, out, batch_ms: float) -> None:
        """Slice the padded output back per request and wake the handles —
        one host transfer per batch, contractually between timed regions.
        Tracing happens HERE, after the timed region: the dispatch span is
        emitted from its measured bounds and each request gets a
        queue-wait span (submit -> dispatch start), so the trace carries
        the queue-wait vs dispatch attribution without a single host sync
        on the dispatch path."""
        arr = np.asarray(out)
        lat_ms: Dict[str, float] = {}
        req_cls: Dict[str, str] = {}
        reg = metrics_registry()
        for req, off in batch.offsets():
            req.handle._complete(OK, arr[off : off + req.n_images])
            lat_ms[req.rid] = round(req.handle.latency_ms, 3)
            req_cls[req.rid] = req.cls
            # Per-request latency histogram: the SAME nearest-rank
            # estimator and population as the journal-derived serve
            # percentiles, so bench (registry) and journal p99s agree.
            reg.histogram("serve.request_ms").observe(req.handle.latency_ms)
        if self.controller is not None:
            # Feed the autopilot's sliding burn windows from the same
            # per-request outcomes the journal records — the live half
            # of the PR 15 attainment fold.
            for req in batch.requests:
                self.controller.note_ok(req.cls, lat_ms[req.rid])
        self.stats.n_batches += 1
        self.stats.n_images += batch.n_images
        self.stats.n_ok += len(batch.requests)
        self.stats.batch_ms.append(batch_ms)
        reg.counter("serve.ok").inc(len(batch.requests))
        reg.counter("serve.images").inc(batch.n_images)
        reg.histogram("serve.batch_ms").observe(batch_ms)
        trace_fields: Dict[str, str] = {}
        tr = get_tracer()
        if tr is not None:
            # Monotonic bounds reconstructed from the measured region so
            # the span write costs the timed path nothing.
            t1 = tr.clock()
            t0 = t1 - batch_ms / 1e3
            dsid = tr.emit(
                "serve.dispatch", t0, t1, track="dispatch",
                bucket=batch.bucket, seq=batch.seq,
                n_requests=len(batch.requests),
                entry=(
                    self.sup.entry.key if self.sup is not None
                    else self.cfg.config
                ),
            )
            trace_fields = {"trace_id": tr.trace_id, "span_id": dsid}
            for req in batch.requests:
                wait_ms = (t0 - req.handle.submitted_at) * 1e3
                reg.histogram("serve.queue_wait_ms").observe(max(0.0, wait_ms))
                tr.emit(
                    "serve.queue_wait", req.handle.submitted_at, t0,
                    parent_id="", track="queue", rid=req.rid,
                )
        else:
            for req in batch.requests:
                reg.histogram("serve.queue_wait_ms").observe(
                    max(0.0, req.handle.latency_ms - batch_ms)
                )
        self._journal(
            "serve_batch",
            key=f"batch:{batch.seq}",
            bucket=batch.bucket,
            n_requests=len(batch.requests),
            n_images=batch.n_images,
            pad=batch.pad,
            batch_ms=round(batch_ms, 3),
            req_lat_ms=lat_ms,
            req_cls=req_cls,
            entry=self.sup.entry.key if self.sup is not None else self.cfg.config,
            **trace_fields,
        )

    @off_timed_path
    def _record_shed(self, shed: List[Request]) -> None:
        self.stats.n_shed += len(shed)
        reg = metrics_registry()
        reg.counter("serve.shed").inc(len(shed))
        if self.controller is not None:
            for req in shed:
                self.controller.note_shed(req.cls)
        for req in shed:
            reason = req.shed_reason or "deadline"
            if reason == "slo":
                # SLO sheds counted separately: "capacity protected the
                # SLO" vs "a caller's own deadline lapsed" are different
                # operational stories (docs/SERVING.md).
                reg.counter("serve.shed_slo").inc()
            self._journal(
                "serve_shed", key=f"shed:{req.rid}", rid=req.rid,
                n_images=req.n_images, cls=req.cls, reason=reason,
                waited_ms=round(req.handle.latency_ms or 0.0, 3),
            )

    @off_timed_path
    def _record_failed(self, batch: AssembledBatch, e: BaseException) -> None:
        cause = f"{type(e).__name__}: {e}"[:200]
        for req in batch.requests:
            req.handle._complete(FAILED, error=cause)
        if self.controller is not None:
            for req in batch.requests:
                self.controller.note_fail(req.cls)
        self.stats.n_failed += len(batch.requests)
        metrics_registry().counter("serve.failed").inc(len(batch.requests))
        self._journal(
            "serve_fail",
            key=f"fail:{batch.seq}",
            bucket=batch.bucket,
            n_requests=len(batch.requests),
            # Per-request class attribution, same shape as serve_batch's
            # req_cls: a failed bulk batch and a failed interactive batch
            # are different stories, and replay accounting closes per class.
            req_cls={req.rid: req.cls for req in batch.requests},
            cause=cause,
        )

    # ------------------------------------------------------------- frontend

    def submit(
        self,
        x,
        *,
        deadline_s: Optional[float] = None,
        rid: Optional[str] = None,
        cls: str = "",
    ) -> RequestHandle:
        """Admit one request (thread-safe). Requests wider than the largest
        bucket are rejected at the door — they could never dispatch.
        Deadline resolution: explicit ``deadline_s`` > the class's default
        (SLO policy) > the server default."""
        x = np.asarray(x)
        n = 1 if x.ndim == 3 else int(x.shape[0])
        if n > self.buckets[-1]:
            self._journal_submit(rid or "", n, cls, None, "too_wide")
            raise ValueError(
                f"request of {n} images exceeds the largest bucket "
                f"{self.buckets[-1]} — split it client-side"
            )
        if deadline_s is None and self.cfg.slo is not None:
            deadline_s = self.cfg.slo.deadline_for(cls)
        if deadline_s is None:
            deadline_s = self.cfg.default_deadline_s
        try:
            handle = self.queue.submit(x, deadline_s=deadline_s, rid=rid, cls=cls)
        except QueueFull:
            self._journal_submit(rid or "", n, cls, deadline_s, "queue_full")
            raise
        self._journal_submit(
            handle.rid, n, cls, deadline_s, "", t=handle.submitted_at
        )
        return handle

    def _journal_submit(
        self,
        rid: str,
        n: int,
        cls: str,
        deadline_s: Optional[float],
        reason: str,
        t: Optional[float] = None,
    ) -> None:
        """One ``serve_submit`` record per admission attempt — the arrival
        schedule half of the replay contract (``serve_config`` is the
        conditions half). ``t_ms`` is the arrival offset from the server
        epoch; rejected attempts (``admitted=False`` with their reason)
        are recorded too, because a replayed load must OFFER them again
        for per-class accounting to close identically. Runs on the
        submitting thread, never the dispatch loop."""
        if self.journal is None:
            return
        with self._submit_lock:  # HTTP handler threads submit concurrently
            self._seq_submit += 1
            self._journal(
                "serve_submit",
                key=f"sub:{self._seq_submit}",
                rid=rid,
                t_ms=round(
                    ((t if t is not None else time.monotonic()) - self._epoch)
                    * 1e3,
                    3,
                ),
                n=n,
                cls=cls,
                deadline_s=deadline_s,
                admitted=not reason,
                reason=reason,
            )

    def _journal(self, kind: str, key: str, **payload) -> None:
        if self.journal is not None:
            # Correlation fields ride along when a tracer is active (and a
            # call site's explicit span_id wins over the ambient one);
            # schemas keep their shape for pre-observability tooling.
            self.journal.append(kind, key=key, **{**current_ids(), **payload})

    def summary(self) -> str:
        """One machine-parseable line ('Serve: ...' — run CLI contract)."""
        s = self.stats.summary()
        buckets = ",".join(str(b) for b in self.buckets)
        tail = (
            f" entry={self.sup.entry.key} trips={len(self.sup.trips)}"
            f" promotions={self.sup.promotions}"
            if self.sup
            else ""
        )
        return f"{s} buckets={buckets}{tail}"


def request_latencies_from_journal(path) -> List[float]:
    """All per-request latencies (ms) journaled by ``serve_batch`` records —
    the crash-consistent source the serve bench computes p50/p99 from (a
    killed run's percentiles cover exactly the requests that completed)."""
    return latencies_from_records(Journal.load(path))


def latencies_from_records(records: List[dict]) -> List[float]:
    """Per-request latencies out of an already-loaded record list (the
    saturation sweep slices ONE journal into per-rate windows)."""
    lats: List[float] = []
    for rec in records:
        if rec.get("kind") == "serve_batch":
            req_lat = rec.get("req_lat_ms")
            if isinstance(req_lat, dict):
                lats.extend(
                    float(v) for v in req_lat.values()
                    if isinstance(v, (int, float))
                )
    return lats


def class_latencies_from_records(records: List[dict]) -> Dict[str, List[float]]:
    """{class name: [latency ms, ...]} from ``serve_batch`` records — the
    per-class p99 source (``req_cls`` maps each rid to its class; rids
    journaled before the class field existed land under ``""``)."""
    out: Dict[str, List[float]] = {}
    for rec in records:
        if rec.get("kind") != "serve_batch":
            continue
        req_lat = rec.get("req_lat_ms")
        req_cls = rec.get("req_cls") or {}
        if not isinstance(req_lat, dict):
            continue
        for rid, v in req_lat.items():
            if isinstance(v, (int, float)):
                out.setdefault(str(req_cls.get(rid, "")), []).append(float(v))
    return out


def class_latencies_from_journal(path) -> Dict[str, List[float]]:
    """Journal-file form of :func:`class_latencies_from_records`."""
    return class_latencies_from_records(Journal.load(path))

"""Bucketed batch assembly: pack pending requests into a FIXED shape set.

Every dispatched batch is padded up to one of a small configured set of
bucket sizes (powers of two up to ``max_batch`` by default, or the batch
sizes the active ``TunePlan`` holds tuned winners for —
``tuning.plan.plan_batches``). The compile-cache discipline of
SNIPPETS.md [1] depends on this: the PR 2 persistent XLA cache is keyed by
shape, so a service that dispatches arbitrary batch sizes compiles on the
request path; one that dispatches only bucket shapes compiles exactly
``len(buckets)`` times at warmup and never again.

Invariants (tests/test_serving.py):
  - every assembled batch's padded size is a member of the bucket set;
  - requests are never split across batches and never reordered (FIFO);
  - every popped request lands in exactly one batch; expired ones are shed
    through the queue's explicit-shed path, never silently dropped.

Stdlib + numpy only (no jax import).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .queue import AdmissionQueue, Request


def power_of_two_buckets(max_batch: int) -> Tuple[int, ...]:
    """1, 2, 4, ... up to and including ``max_batch`` (itself included even
    when not a power of two — the configured ceiling is always a legal
    dispatch shape)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    buckets = []
    b = 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return tuple(buckets)


def bucket_for(n_images: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= ``n_images``. Raises when nothing fits — the
    admission layer must reject requests larger than max(buckets), so
    hitting this from the dispatch loop is a logic error, not load."""
    for b in sorted(buckets):
        if n_images <= b:
            return int(b)
    raise ValueError(
        f"{n_images} images fit no bucket (buckets={sorted(buckets)})"
    )


@dataclasses.dataclass
class AssembledBatch:
    """One dispatch unit: FIFO requests padded to a bucket shape."""

    seq: int
    requests: List[Request]
    bucket: int  # padded batch size — ALWAYS a member of the bucket set

    @property
    def n_images(self) -> int:
        return sum(r.n_images for r in self.requests)

    @property
    def pad(self) -> int:
        return self.bucket - self.n_images

    def offsets(self) -> List[Tuple[Request, int]]:
        """(request, row offset) pairs — how to slice the padded output."""
        out, off = [], 0
        for r in self.requests:
            out.append((r, off))
            off += r.n_images
        return out

    def padded_input(self) -> np.ndarray:
        """(bucket, H, W, C) array: requests concatenated, zero rows after.
        Zero padding is numerically safe here — the forward is pointwise
        per image (conv/pool/LRN never mix batch rows), so pad rows cannot
        contaminate real outputs; they are sliced off before completion."""
        xs = [r.x for r in self.requests]
        n = self.n_images
        if self.pad:
            xs.append(np.zeros((self.pad,) + xs[0].shape[1:], xs[0].dtype))
        out = np.concatenate(xs, axis=0)
        assert out.shape[0] == self.bucket and n <= self.bucket
        return out


class Batcher:
    """Pull-side batch assembler over an :class:`AdmissionQueue`."""

    def __init__(self, queue: AdmissionQueue, buckets: Sequence[int]):
        if not buckets:
            raise ValueError("Batcher needs a non-empty bucket set")
        self.queue = queue
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.max_batch = self.buckets[-1]
        self._seq = 0

    def next_batch(
        self, wait_s: float = 0.05
    ) -> Tuple[Optional[AssembledBatch], List[Request]]:
        """Assemble the next batch, or (None, shed) when nothing is ready.

        Waits up to ``wait_s`` for work, pops a FIFO prefix capped at the
        largest bucket, and pads to the smallest bucket that fits — the
        latency/throughput trade is made by the bucket set, not a timer:
        a lone request dispatches immediately at bucket 1 instead of
        waiting for co-riders that may never come (deadline-aware: holding
        it could expire it). ``shed`` carries every request the queue
        dropped on the way — hard-deadline expiries AND class-SLO
        blow-outs when an :class:`~.slo.SLOPolicy` is installed
        (``Request.shed_reason`` says which); the server journals each
        one attributably."""
        if not len(self.queue):
            self.queue.wait_nonempty(wait_s)
        taken, shed = self.queue.pop_ready(self.max_batch)
        if not taken:
            return None, shed
        self._seq += 1
        batch = AssembledBatch(
            self._seq, taken, bucket_for(sum(r.n_images for r in taken), self.buckets)
        )
        return batch, shed

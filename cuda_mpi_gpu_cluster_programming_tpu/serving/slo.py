"""SLO-aware admission: per-class latency targets and shed-by-class.

PR 6's queue sheds by *age* alone: a request is dropped exactly when its
hard deadline expires. Under saturation that policy is blind to what the
request is — a bulk re-index job and an interactive query shed at the
same age even though one has seconds of budget and the other milliseconds.
This module adds the class dimension:

- :class:`SLOClass` names a request class and its latency SLO
  (``slo_ms`` — the p99 target the class is operated against) plus the
  class's default hard deadline. ``shed_wait_ms`` (default: the SLO
  itself) is the queue-wait point past which dispatching the request is
  *wasted capacity*: it can no longer meet its SLO, and the batch slot it
  would occupy pushes the next request over too.
- :class:`SLOPolicy` is the queue's pop-time hook
  (:meth:`AdmissionQueue.pop_ready`): ``should_shed(cls, waited_ms)``
  returns ``"slo"`` when a request's wait has blown its class budget.
  Because each class carries its own threshold, saturation sheds the
  tight-SLO classes first while loose classes still complete — shed by
  class, not by a single global age. Idle queues never trigger it (waits
  stay near zero), so the policy costs nothing until the queue actually
  saturates.

Every policy shed completes the handle with status ``SHED`` and is
journaled (``serve_shed`` with ``cls``/``reason="slo"``/``waited_ms``) —
the same no-silent-loss contract as deadline shedding (``reason=
"deadline"``).

Stdlib only (no jax/numpy import) — the queue-layer rule.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

# The class name requests fall into when the submitter names none; its SLO
# is unbounded so an un-classed service behaves exactly like PR 6.
DEFAULT_CLASS = ""


@dataclasses.dataclass(frozen=True)
class SLOClass:
    """One request class's operating targets."""

    name: str
    slo_ms: float  # p99 latency target (0 = unbounded: never SLO-shed)
    deadline_s: Optional[float] = None  # class default hard deadline
    # Queue-wait past which the request is shed as unservable within its
    # SLO; defaults to slo_ms (a request that already waited its whole
    # latency budget cannot meet it, dispatch time still to come).
    shed_wait_ms: Optional[float] = None

    @property
    def shed_cut_ms(self) -> float:
        cut = self.shed_wait_ms if self.shed_wait_ms is not None else self.slo_ms
        return float(cut or 0.0)

    def to_obj(self) -> dict:
        return {
            "name": self.name,
            "slo_ms": self.slo_ms,
            "deadline_s": self.deadline_s,
            "shed_wait_ms": self.shed_cut_ms or None,
        }

    @staticmethod
    def from_obj(obj: dict) -> "SLOClass":
        """Inverse of :meth:`to_obj` — the ``serve_config`` journal record
        round-trip ``observability.replay`` rebuilds a recorded run's
        admission policy from."""
        return SLOClass(
            name=str(obj.get("name", "")),
            slo_ms=float(obj.get("slo_ms") or 0.0),
            deadline_s=(
                float(obj["deadline_s"])
                if obj.get("deadline_s") is not None
                else None
            ),
            shed_wait_ms=(
                float(obj["shed_wait_ms"])
                if obj.get("shed_wait_ms") is not None
                else None
            ),
        )

    def scaled(self, factor: float) -> "SLOClass":
        """This class with every latency budget scaled by ``factor`` — the
        replay harness's ``--slo-scale`` what-if knob (0.5 = 'would the
        run hold with SLOs twice as tight?'). Unbounded budgets (0 /
        None) stay unbounded: scaling cannot invent a ceiling."""
        return SLOClass(
            name=self.name,
            slo_ms=self.slo_ms * factor if self.slo_ms else self.slo_ms,
            deadline_s=(
                self.deadline_s * factor
                if self.deadline_s is not None
                else None
            ),
            shed_wait_ms=(
                self.shed_wait_ms * factor
                if self.shed_wait_ms
                else self.shed_wait_ms
            ),
        )


class SLOPolicy:
    """Per-class shed policy the queue consults at pop time.

    Unknown class names resolve to ``default`` (unbounded unless given) —
    a request the submitter never classified is served exactly like a
    PR 6 request, never SLO-shed.
    """

    def __init__(
        self,
        classes: Sequence[SLOClass],
        default: Optional[SLOClass] = None,
    ):
        self.classes: Dict[str, SLOClass] = {c.name: c for c in classes}
        self.default = default or SLOClass(DEFAULT_CLASS, slo_ms=0.0)

    def class_for(self, name: str) -> SLOClass:
        return self.classes.get(name, self.default)

    def deadline_for(self, name: str) -> Optional[float]:
        """The class's default hard deadline (an explicit per-request
        deadline always wins — resolution happens at submit)."""
        return self.class_for(name).deadline_s

    def should_shed(self, cls: str, waited_ms: float) -> Optional[str]:
        """``"slo"`` when the request's queue wait has blown its class
        budget (completing it would only burn a batch slot that pushes
        the *next* request over), else None. Hard-deadline expiry is the
        queue's own check, journaled ``reason="deadline"``."""
        cut = self.class_for(cls).shed_cut_ms
        if cut and waited_ms > cut:
            return "slo"
        return None

    def to_obj(self) -> dict:
        return {
            "classes": [c.to_obj() for c in self.classes.values()],
            "default": self.default.to_obj(),
        }

    @staticmethod
    def from_obj(obj: dict) -> "SLOPolicy":
        """Inverse of :meth:`to_obj` (the ``serve_config`` round-trip)."""
        return SLOPolicy(
            [SLOClass.from_obj(c) for c in obj.get("classes") or []],
            default=(
                SLOClass.from_obj(obj["default"])
                if obj.get("default")
                else None
            ),
        )

    def scaled(self, factor: float) -> "SLOPolicy":
        """Every class budget scaled by ``factor`` (replay ``--slo-scale``)."""
        return SLOPolicy(
            [c.scaled(factor) for c in self.classes.values()],
            default=self.default.scaled(factor),
        )

    def tightened(self, name: str, shed_wait_ms: float) -> "SLOPolicy":
        """This policy with ``name``'s pop-time shed cut replaced — the
        autopilot's admission-tightening actuation (serving.controller).
        Only ``shed_wait_ms`` moves: the class's SLO target and deadline
        are product contracts the controller must never rewrite, and the
        burn it steers by stays priced against them. A class the policy
        does not know is added (an unbounded class gains its first
        finite cut this way — bulk under pressure)."""
        cur = self.class_for(name)
        new = dataclasses.replace(
            cur, name=name, shed_wait_ms=float(shed_wait_ms)
        )
        classes = [
            new if c.name == name else c for c in self.classes.values()
        ]
        if name not in self.classes:
            classes.append(new)
        return SLOPolicy(classes, default=self.default)

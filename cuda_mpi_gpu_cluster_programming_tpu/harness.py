"""Bench/test harness: sweep, triage, CSV, ASCII summary table.

Python replacement for the reference's bash harness layer (L5):

- ``scripts/common_test_utils.sh`` — run+classify (exit 0/2/3/4 =
  ok / env-warn / mpi-warn / critical via log grep, :84-117), CSV row writer
  (:71-81), box-drawing ASCII summary table (:119-178), per-case pipeline
  (:187-346).
- ``scripts/0_run_final_project.sh`` / ``1_final_unique_machine.sh`` — the
  variant x np sweep matrix (:44-70) and the 20-column CSV schema (:41).
- ``final_project/v4_mpi_cuda/test_v4.sh`` — per-case log capture + colored
  PASS/FAIL/WARN summary.
- ``scripts/test_hw.sh`` — per-run timeout (:124) and sweep skip rules.

Each case runs ``python -m cuda_mpi_gpu_cluster_programming_tpu.run`` in a
subprocess (the ``mpirun -np N ./template`` analogue); ``--fake-devices``
maps to ``--oversubscribe`` (N virtual XLA host devices stand in for N TPU
cores). The stdout contract parsed here is the same one the reference greps
(``Final Output Shape:`` / first-10 / ``completed in X ms``).
"""

from __future__ import annotations

import argparse
import csv
import dataclasses
import datetime
import json
import os
import platform
import re
import subprocess
import sys
import time
from pathlib import Path
from typing import List, Optional, Sequence

from .resilience import chaos
from .resilience.journal import JOURNAL_NAME, Journal, atomic_write_text
from .resilience.policy import DEGRADED, Deadline, FaultLog, RetryPolicy
from .utils.env_info import cpu_subprocess_env

# 20-column CSV schema (analogue of 0_run_final_project.sh:41) + the two
# resilience attempt-metadata columns (appended, so historical column
# indexes are untouched).
CSV_COLUMNS = [
    "SessionID",
    "MachineID",
    "GitCommit",
    "Timestamp",
    "Variant",
    "ConfigKey",
    "NP",
    "Batch",
    "BuildStatus",
    "BuildMsg",
    "RunStatus",
    "RunMsg",
    "ParseStatus",
    "ParseMsg",
    "Status",
    "ExecutionTime_ms",
    "Compile_ms",
    "OutputShape",
    "First5Values",
    "LogFile",
    "Attempts",
    "ResilienceMsg",
    "PlanHash",
    "SupervisorMsg",
    "Dtype",
]

# Exit-code triage classes (common_test_utils.sh:96-116); DEGRADED comes
# from resilience.policy — a run that succeeded only on a fallback tier.
OK, ENV_WARN, MESH_WARN, CRITICAL, FAIL, TIMEOUT, PARSE_ERR = (
    "OK",
    "ENV_WARN",
    "MESH_WARN",
    "CRITICAL",
    "FAIL",
    "TIMEOUT",
    "PARSE_ERR",
)
STATUS_SYMBOL = {
    OK: "✓",  # ✓
    ENV_WARN: "⚠",  # ⚠
    MESH_WARN: "⚠",
    PARSE_ERR: "⚠",
    DEGRADED: "↓",  # succeeded on a fallback tier — warn, don't fail
    CRITICAL: "✗",  # ✗
    FAIL: "✗",
    TIMEOUT: "⏱",  # ⏱
}

_ENV_PATTERNS = [
    r"RuntimeError: Unable to initialize backend",
    r"No TPU devices",
    r"libtpu",
    # Observed round-1 on the tunneled axon TPU (BENCH_r01.json tail): the
    # backend registers but init fails server-side.
    r"TPU backend setup/compile error",
    # Observed round-3 (perf/sweep_20260729_204754.json): the tunnel's
    # remote-compile relay returns HTTP 5xx / kills its helper subprocess
    # transiently — an environment fault, not a framework failure (the
    # same configs compiled clean minutes later).
    # Keep this ANCHORED to the relay's HTTP error: a bare
    # "tpu_compile_helper ..." match would also excuse deterministic
    # compile failures of a genuinely-broken program as environment noise.
    r"remote_compile: HTTP 5\d\d",
]
# Explicit wedged-TPU-tunnel diagnosis (printed by the bounded probe in
# utils.probe / bench.py). Note the bare platform banner is NOT in this
# list: every run prints it, so a genuine framework deadlock that hangs
# before first compile would be masked as an environment problem. Instead,
# a timed-out run with no progress marker triggers an ACTIVE device probe
# (classify_timeout's device_responsive hook) — dead device => ENV_WARN,
# healthy device => the hang was ours => TIMEOUT.
_WEDGE_PATTERNS = [
    r"wedged tunnel",
]
_MESH_PATTERNS = [
    r"needs \d+ devices, have \d+",
]
_CRITICAL_PATTERNS = [
    r"Segmentation fault",
    r"core dumped",
    r"Illegal instruction",
    r"Fatal Python error",
    r"MemoryError",
]


def classify(returncode: int, log_text: str) -> str:
    """Classify a finished run (common_test_utils.sh:96-116 analogue).

    Warnings (ENV_WARN / MESH_WARN) don't fail the suite — this is how
    machines without a TPU / without enough devices still exercise the
    other paths, exactly like the reference's GPU-less machines. To avoid
    masking real failures, ENV/MESH patterns are matched only against the
    tail of the log (the actual raised error), not JAX's startup chatter —
    an unrelated ValueError after a benign "Unable to initialize backend"
    INFO line still classifies as FAIL.
    """
    if returncode == 0:
        return OK
    if returncode == 124:  # killed by a `timeout` wrapper (test_hw.sh:124)
        return classify_timeout(log_text)
    lines = [ln for ln in log_text.strip().splitlines() if ln.strip()]
    tail = "\n".join(lines[-8:])
    for pat in _CRITICAL_PATTERNS:
        if re.search(pat, log_text):
            return CRITICAL
    for pat in _MESH_PATTERNS:
        if re.search(pat, tail):
            return MESH_WARN
    for pat in _ENV_PATTERNS:
        if re.search(pat, tail):
            return ENV_WARN
    return FAIL


def classify_timeout(log_text: str, device_responsive=None) -> str:
    """Triage a timed-out/killed run: confirmed wedged-tunnel hangs are
    ENV_WARN, everything else is a genuine TIMEOUT.

    A run that never produced a progress marker (compile/complete lines)
    died before or inside device execution. That is only an environment
    problem when the device is actually unresponsive — either the log
    carries the probe's explicit "wedged tunnel" diagnosis, or the
    ``device_responsive`` callback (an active bounded probe, run only when
    needed) reports the device dead. A hang on a HEALTHY device is a
    framework deadlock and stays TIMEOUT — the reference's GPU-less-machine
    tolerance (common_test_utils.sh:103-115) must not excuse real
    regressions. A run that DID make progress is always a real TIMEOUT.
    """
    progressed = _RE_COMPILE.search(log_text) or _RE_TIME.search(log_text)
    if progressed:
        return TIMEOUT
    if any(re.search(p, log_text) for p in _WEDGE_PATTERNS):
        return ENV_WARN
    if device_responsive is not None and not device_responsive():
        return ENV_WARN
    return TIMEOUT


# Probe-verdict cache: a sweep on a wedged device would otherwise pay one
# full bounded probe (~45 s) per timed-out case. The verdict is reused for
# _PROBE_TTL_S; a healed (or newly wedged) tunnel is re-detected afterwards.
_PROBE_TTL_S = 300.0
# [-inf, _] forces the first call to actually probe — time.monotonic() is
# seconds-since-boot, so a 0.0 seed would fake a fresh verdict on young VMs.
_probe_verdict: List = [float("-inf"), True]


def _cached_device_responsive() -> bool:
    now = time.monotonic()
    if now - _probe_verdict[0] > _PROBE_TTL_S:
        from .utils.probe import device_responsive

        _probe_verdict[:] = [now, device_responsive()]
    return _probe_verdict[1]


# Stdout-contract regexes (common_test_utils.sh:296-317 analogue).
_RE_TIME = re.compile(r"completed in ([0-9.]+) ms")
_RE_COMPILE = re.compile(r"Compile time: ([0-9.]+) ms")
_RE_SHAPE = re.compile(r"Final Output Shape: ([0-9x]+)")
_RE_FIRST = re.compile(r"Final Output \(first 10 values\): (.+)")
# Structured fallback event printed by the run CLI's Degrader
# (resilience.policy.DegradedEvent.__str__).
_RE_DEGRADED = re.compile(r"^DEGRADED\(.+?\): .*$", re.MULTILINE)
# Tuning-plan identity printed by the run CLI (run.py "Tune plan:" line):
# rows measured under a tuned per-layer variant plan carry its hash, so a
# tuned number can never masquerade as a default-lowering one in the CSV.
_RE_PLAN = re.compile(r"^Tune plan: (?:cache|swept|loaded) hash=([0-9a-f]+)", re.MULTILINE)
# Elastic-supervisor incident line printed by the run CLI under --supervise
# (resilience.supervisor.Supervisor.summary): attempts/trips/degradations
# plus the ladder rung that finally served the batch.
_RE_SUPERVISOR = re.compile(r"^Supervisor: (.+)$", re.MULTILINE)
# Precision-policy line printed by the run CLI (docs/PRECISION.md): the
# dtype the run ACTUALLY measured under (an int8w/bf16 row must never be
# read as fp32, and a tuned-winner adoption is visible per row).
_RE_PRECISION = re.compile(r"^Precision: dtype=(\S+)", re.MULTILINE)


def is_wedged(r: CaseResult, log_text: str) -> bool:
    """A 'successful' capture that measured nothing: the wedged-tunnel
    signature (four consecutive rounds of value=0.0 bench rows — VERDICT).
    Such a row must trigger probe -> backoff -> re-capture, and must NEVER
    be committed as data."""
    if r.run_status != OK:
        return False
    if r.time_ms is not None and r.time_ms <= 0.0:
        return True
    return any(re.search(p, log_text) for p in _WEDGE_PATTERNS)


@dataclasses.dataclass
class CaseResult:
    variant: str
    config_key: str
    np: int
    batch: int
    build_status: str = "OK"
    build_msg: str = ""
    run_status: str = FAIL
    run_msg: str = ""
    parse_status: str = "OK"
    parse_msg: str = ""
    time_ms: Optional[float] = None
    compile_ms: Optional[float] = None
    shape: str = ""
    first5: str = ""
    log_file: str = ""
    attempts: int = 1
    resilience_msg: str = ""  # retry/suppression trail (FaultLog.summary)
    degraded_msg: str = ""  # the run CLI's DEGRADED(from -> to) event line
    plan_hash: str = ""  # TunePlan identity the run measured under ("" = untuned)
    supervisor_msg: str = ""  # the run CLI's 'Supervisor: ...' incident line
    dtype: str = ""  # precision policy the run measured under ("" = pre-policy log)

    @property
    def status(self) -> str:
        if self.run_status != OK:
            return self.run_status
        if self.degraded_msg:
            # Degradation outranks parse nits: the row's numbers belong to a
            # FALLBACK tier and must never be read as the requested one.
            return DEGRADED
        if self.parse_status != "OK":
            return PARSE_ERR
        return OK


def parse_run_log(text: str, result: CaseResult) -> None:
    """Extract time/shape/first-values; missing fields degrade to parse
    errors, not failures (common_test_utils.sh:319-324)."""
    missing = []
    m = _RE_TIME.search(text)
    if m:
        result.time_ms = float(m.group(1))
    else:
        missing.append("time")
    m = _RE_COMPILE.search(text)
    if m:
        result.compile_ms = float(m.group(1))
        result.build_msg = f"jit compile {result.compile_ms:.0f} ms"
    m = _RE_SHAPE.search(text)
    if m:
        result.shape = m.group(1)
    else:
        missing.append("shape")
    m = _RE_FIRST.search(text)
    if m:
        result.first5 = " ".join(m.group(1).split()[:5])
    else:
        missing.append("values")
    if missing:
        result.parse_status = PARSE_ERR
        result.parse_msg = "missing: " + ",".join(missing)


def git_commit() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            cwd=Path(__file__).resolve().parent.parent,
            timeout=10,
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _csv_line(values: List) -> str:
    """One CSV-encoded line (with terminator) — csv handles the quoting."""
    import io

    buf = io.StringIO()
    csv.writer(buf).writerow(values)
    return buf.getvalue()


@dataclasses.dataclass
class Session:
    """A harness session: one log dir, one CSV (0_run_final_project.sh:15-23),
    one crash-consistent journal.

    Every committed case is journaled (kind ``case``, the full row keyed by
    its sweep coordinates) AFTER its CSV append, making the journal the
    source of truth: ``resume=True`` reopens an interrupted session, REBUILDS
    the CSV atomically from the journaled rows (dropping any torn row a kill
    mid-append left behind), and exposes ``completed`` so the sweep skips
    journaled-complete cases and re-runs interrupted ones.
    """

    log_root: Path
    session_id: str = ""
    machine_id: str = ""
    commit: str = ""
    resume: bool = False

    def __post_init__(self) -> None:
        ts = datetime.datetime.now().strftime("%Y%m%d_%H%M%S")
        self.machine_id = self.machine_id or platform.node() or "unknown"
        self.session_id = self.session_id or f"bench_{ts}_{self.machine_id}"
        self.commit = self.commit or git_commit()
        self.dir = self.log_root / self.session_id
        self.dir.mkdir(parents=True, exist_ok=True)
        self.csv_path = self.dir / "summary.csv"
        journal_path = self.dir / JOURNAL_NAME
        self.completed: dict = {}
        if self.resume:
            self.completed = Journal.completed(Journal.load(journal_path), "case")
            text = _csv_line(CSV_COLUMNS)
            for rec in self.completed.values():
                row = rec.get("row", {})
                text += _csv_line([row.get(c, "") for c in CSV_COLUMNS])
            atomic_write_text(self.csv_path, text)
        else:
            atomic_write_text(self.csv_path, _csv_line(CSV_COLUMNS))
        self.journal = Journal(journal_path)
        # Environment dump next to the CSV (the pc_v4_environment_info.txt
        # analogue) so analysis can attribute numbers to toolchains. No
        # device probe here — the harness process must not initialize a
        # backend the run subprocesses will claim.
        if not (self.resume and (self.dir / "env.json").exists()):
            from .utils.env_info import collect

            atomic_write_text(
                self.dir / "env.json",
                json.dumps(collect(probe_devices=False), indent=2) + "\n",
            )

    def log_row(self, r: CaseResult, journal_key: str = "") -> None:
        values = [
            self.session_id,
            self.machine_id,
            self.commit,
            datetime.datetime.now().isoformat(timespec="seconds"),
            r.variant,
            r.config_key,
            r.np,
            r.batch,
            r.build_status,
            r.build_msg,
            r.run_status,
            r.run_msg,
            r.parse_status,
            r.parse_msg,
            r.status,
            f"{r.time_ms:.3f}" if r.time_ms is not None else "",
            f"{r.compile_ms:.1f}" if r.compile_ms is not None else "",
            r.shape,
            r.first5,
            r.log_file,
            r.attempts,
            r.resilience_msg or r.degraded_msg,
            r.plan_hash,
            r.supervisor_msg,
            r.dtype,
        ]
        with open(self.csv_path, "a", newline="") as f:
            csv.writer(f).writerow(values)
        # Journal AFTER the CSV append: a kill between the two re-runs the
        # case on --resume and the rebuilt CSV drops the orphan row, so a
        # case is never double-counted.
        self.journal.append(
            "case",
            key=journal_key or f"{r.config_key}|np={r.np}|b={r.batch}",
            row=dict(zip(CSV_COLUMNS, values)),
        )


def case_result_from_row(row: dict) -> CaseResult:
    """Rebuild a CaseResult from a journaled CSV-row dict (the --resume
    replay path: journaled-complete cases re-enter the summary table and
    exit-code triage without re-running)."""
    r = CaseResult(
        variant=str(row.get("Variant", "")),
        config_key=str(row.get("ConfigKey", "")),
        np=int(row.get("NP", 0) or 0),
        batch=int(row.get("Batch", 0) or 0),
        build_status=str(row.get("BuildStatus", "OK")),
        build_msg=str(row.get("BuildMsg", "")),
        run_status=str(row.get("RunStatus", FAIL)),
        run_msg=str(row.get("RunMsg", "")),
        parse_status=str(row.get("ParseStatus", "OK")),
        parse_msg=str(row.get("ParseMsg", "")),
        shape=str(row.get("OutputShape", "")),
        first5=str(row.get("First5Values", "")),
        log_file=str(row.get("LogFile", "")),
        attempts=int(row.get("Attempts", 1) or 1),
        resilience_msg=str(row.get("ResilienceMsg", "")),
        plan_hash=str(row.get("PlanHash", "")),
        supervisor_msg=str(row.get("SupervisorMsg", "")),
        dtype=str(row.get("Dtype", "")),
    )
    if row.get("ExecutionTime_ms"):
        r.time_ms = float(row["ExecutionTime_ms"])
    if row.get("Compile_ms"):
        r.compile_ms = float(row["Compile_ms"])
    if row.get("Status") == DEGRADED:
        r.degraded_msg = r.resilience_msg or "DEGRADED (journaled)"
    return r


# Synthetic stdout of a chaos-injected subprocess wedge: the run "succeeds"
# (rc 0) but measured nothing — the value=0.0 signature plus the probe's
# wedged-tunnel diagnosis, exactly the round-1..5 failure mode.
_CHAOS_WEDGE_TEXT = (
    "probe timed out after 45s (wedged tunnel?)\n"
    "Compile time: 0.0 ms\n"
    "Final Output Shape: 0x0x0\n"
    "Final Output (first 10 values): 0.0\n"
    "AlexNet TPU Forward Pass completed in 0.000 ms "
    "(amortized over 0 fenced passes; 0.0 img/s)\n"
)


def _run_once(
    r: CaseResult,
    cmd: List[str],
    env: dict,
    log_path: Path,
    timeout_s: float,
    fake_devices: int,
) -> str:
    """One attempt of the build→run→classify pipeline; returns the log text.

    There is no ``make`` step on TPU; the "build" is XLA jit compilation,
    reported by the runner as ``Compile time:`` and recorded in BuildMsg.
    """
    t0 = time.perf_counter()
    ch = chaos.active()
    if ch and ch.draw("subprocess_wedge"):
        # Drill: don't launch anything — synthesize the wedged capture the
        # tunnel produces, so the re-capture path is exercised end to end.
        text = _CHAOS_WEDGE_TEXT
        r.run_status = OK
    else:
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=timeout_s,
                env=env,
                cwd=Path(__file__).resolve().parent.parent,
            )
            text = proc.stdout + "\n--- stderr ---\n" + proc.stderr
            r.run_status = classify(proc.returncode, text)
            if r.run_status != OK:
                last = [ln for ln in proc.stderr.strip().splitlines() if ln.strip()]
                r.run_msg = (last[-1][:160] if last else f"exit {proc.returncode}")
        except subprocess.TimeoutExpired as e:
            def _s(x):
                return x.decode(errors="replace") if isinstance(x, bytes) else (x or "")

            text = _s(e.stdout) + "\n--- stderr ---\n" + _s(e.stderr)
            if fake_devices:
                # CPU-mesh children can't be wedged by the tunnel; their hangs
                # are always the framework's fault.
                device_check = None
            else:
                device_check = _cached_device_responsive
            r.run_status = classify_timeout(text, device_check)
            r.run_msg = f"timeout after {timeout_s:.0f}s" + (
                " (wedged TPU tunnel confirmed by probe)" if r.run_status == ENV_WARN else ""
            )
    wall = time.perf_counter() - t0
    log_path.write_text(f"$ {' '.join(cmd)}\n# wall {wall:.2f}s\n{text}")

    if r.run_status == OK:
        parse_run_log(text, r)
        m = _RE_DEGRADED.search(text)
        if m:
            r.degraded_msg = m.group(0)[:200]
        m = _RE_PLAN.search(text)
        if m:
            r.plan_hash = m.group(1)
        m = _RE_SUPERVISOR.search(text)
        if m:
            r.supervisor_msg = m.group(1)[:200]
        m = _RE_PRECISION.search(text)
        if m:
            r.dtype = m.group(1)
    return text


def run_case(
    session: Session,
    config_key: str,
    variant: str,
    np_: int,
    batch: int,
    timeout_s: float = 300.0,
    fake_devices: int = 0,
    extra_args: Sequence[str] = (),
    log_tag: str = "",
    retry_policy: Optional[RetryPolicy] = None,
    deadline: Optional[Deadline] = None,
    sleep=time.sleep,
    journal_key: str = "",
) -> CaseResult:
    """Run one case with bounded retry + wedge-aware re-capture, then commit
    exactly ONE row (common_test_utils.sh:223-346, hardened).

    Retryable outcomes: ENV_WARN (transient environment fault), TIMEOUT,
    and a wedged capture (``is_wedged`` — rc 0 but value=0.0 / wedge
    signature in the log). Each retry backs off per ``retry_policy`` and
    respects ``deadline``; a wedge additionally probes the device first so
    the fault log states WHY the re-capture was attempted. A terminally
    wedged case is committed as ENV_WARN with its numbers cleared — never
    as a value=0.0 data row.
    """
    policy = retry_policy or RetryPolicy(max_retries=0)
    deadline = deadline or Deadline.after(None)
    flog = FaultLog(site=f"case:{config_key}/np{np_}/b{batch}")
    # Journal the attempt BEFORE launching: a case with a start record but
    # no committed row is exactly the "interrupted" state --resume re-runs.
    session.journal.append(
        "case_start", key=journal_key or f"{config_key}|np={np_}|b={batch}"
    )
    safe_key = config_key.replace(".", "_")
    tag = f"_{log_tag}" if log_tag else ""

    cmd = [
        sys.executable,
        "-m",
        "cuda_mpi_gpu_cluster_programming_tpu.run",
        "--config",
        config_key,
        "--shards",
        str(np_),
        "--batch",
        str(batch),
        *extra_args,
    ]
    if fake_devices:
        # The --oversubscribe analogue: N virtual host devices on CPU.
        env = cpu_subprocess_env(fake_devices)
    else:
        # Inherit the environment untouched: the ambient PYTHONPATH points at
        # the sitecustomize that registers the TPU plugin (see verify skill).
        env = dict(os.environ)

    wedged = False
    for attempt in range(max(0, policy.max_retries) + 1):
        r = CaseResult(variant=variant, config_key=config_key, np=np_, batch=batch)
        r.attempts = attempt + 1
        # Retries keep every attempt's log on disk (the first attempt keeps
        # the historical un-suffixed name).
        try_tag = f"_try{attempt}" if attempt else ""
        log_path = session.dir / f"run_{safe_key}_np{np_}_b{batch}{tag}{try_tag}.log"
        r.log_file = log_path.name
        t0 = time.monotonic()
        text = _run_once(
            r, cmd, env, log_path, deadline.remaining(cap=timeout_s), fake_devices
        )
        wedged = is_wedged(r, text)
        retryable = wedged or r.run_status in (ENV_WARN, TIMEOUT)
        if not retryable:
            flog.record("ok", duration_s=time.monotonic() - t0)
            break
        cause = "wedged capture (value=0.0)" if wedged else r.run_status
        if wedged and not fake_devices:
            # Probe before re-spending a full case timeout on a dead tunnel;
            # the verdict is advisory (bounded retries continue either way)
            # but makes the fault log diagnostic.
            cause += (
                "; probe: device responsive"
                if _cached_device_responsive()
                else "; probe: device unresponsive"
            )
        if attempt >= policy.max_retries or deadline.expired:
            flog.record("fail", cause, time.monotonic() - t0)
            break
        pause = min(policy.delay_s(attempt + 1), deadline.remaining())
        flog.record("retry", cause, time.monotonic() - t0, backoff_s=pause)
        if pause > 0:
            sleep(pause)

    if wedged:
        # Terminal wedge: suppress the garbage numbers — the row records the
        # environment fault, not a fake 0.0 measurement.
        r.run_status = ENV_WARN
        r.run_msg = f"wedged capture suppressed after {r.attempts} attempt(s)"
        r.time_ms = r.compile_ms = None
        r.shape = r.first5 = ""
        r.parse_status, r.parse_msg = "OK", ""
    r.resilience_msg = flog.summary()
    session.log_row(r, journal_key=journal_key)
    return r


def summary_table(results: List[CaseResult]) -> str:
    """Unicode box-drawing summary (common_test_utils.sh:133-178 analogue)."""
    headers = ["Variant", "Config", "NP", "Batch", "St", "Time(ms)", "Shape", "First values"]
    rows = []
    for r in results:
        rows.append(
            [
                r.variant,
                r.config_key,
                str(r.np),
                str(r.batch),
                STATUS_SYMBOL.get(r.status, "?"),
                f"{r.time_ms:.3f}" if r.time_ms is not None else "-",
                r.shape or "-",
                (r.first5[:28] or r.run_msg[:28]) or "-",
            ]
        )
    widths = [max(len(h), *(len(row[i]) for row in rows)) if rows else len(h) for i, h in enumerate(headers)]

    def line(l: str, m: str, r_: str) -> str:
        return l + m.join("─" * (w + 2) for w in widths) + r_

    def fmt(cells: List[str]) -> str:
        return "│" + "│".join(f" {c:<{w}} " for c, w in zip(cells, widths)) + "│"

    out = [line("┌", "┬", "┐"), fmt(headers), line("├", "┼", "┤")]
    out += [fmt(row) for row in rows]
    out.append(line("└", "┴", "┘"))
    return "\n".join(out)


def make_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="cuda_mpi_gpu_cluster_programming_tpu.harness")
    p.add_argument(
        "--configs",
        default=(
            "v1_jit,v2.1_replicated,v2.2_sharded,v3_pallas,v4_hybrid,v5_collective,"
            "v6_full_jit,v6_full_pallas,v6_full_sharded,v7_tp"
        ),
        help="comma-separated config keys (default: full V1-V7 matrix incl. V6 full-AlexNet)",
    )
    p.add_argument("--shards", default="1,2,4", help="comma-separated shard counts (np sweep)")
    p.add_argument("--batches", default="1", help="comma-separated batch sizes")
    p.add_argument(
        "--computes",
        default="fp32",
        help="comma-separated precision policies to sweep "
        "(fp32,bf16,int8w — docs/PRECISION.md)",
    )
    p.add_argument("--timeout", type=float, default=300.0, help="per-case timeout seconds")
    p.add_argument(
        "--fake-devices",
        type=int,
        default=0,
        help="run cases on N virtual CPU devices (mpirun --oversubscribe analogue); "
        "0 = use the real backend",
    )
    p.add_argument("--log-root", default="logs", help="session log directory root")
    p.add_argument("--height", type=int, default=227)
    p.add_argument("--width", type=int, default=227)
    p.add_argument("--repeats", type=int, default=10)
    p.add_argument(
        "--max-retries",
        type=int,
        default=1,
        help="bounded per-case retries on ENV_WARN/TIMEOUT/wedged captures "
        "(0 = the historical one-shot behavior)",
    )
    p.add_argument(
        "--retry-backoff",
        type=float,
        default=2.0,
        help="base backoff seconds before the first retry (doubles per retry, jittered)",
    )
    p.add_argument(
        "--deadline-s",
        type=float,
        default=0.0,
        help="whole-sweep wall-clock budget; retries and per-case timeouts "
        "never outlive it (0 = unbounded)",
    )
    p.add_argument(
        "--fallback-chain",
        default="",
        help="forwarded to the run CLI: comma-separated fallback config keys, "
        "or 'auto' for the canonical tier ladder; failed cases re-run on the "
        "next tier and triage as DEGRADED instead of failing",
    )
    p.add_argument(
        "--plan",
        default="",
        help="TunePlan JSON path forwarded to every case's run CLI; each "
        "row's PlanHash column records the plan it actually measured under "
        "(docs/TUNING.md)",
    )
    p.add_argument(
        "--supervise",
        action="store_true",
        help="forwarded to every case's run CLI: run under the elastic "
        "supervisor (in-graph digest screening + shard-ladder re-planning); "
        "each row's SupervisorMsg column records the incident trail, and a "
        "case that finished on a lower rung triages as DEGRADED "
        "(docs/RESILIENCE.md). Blocks 1-2 configs only",
    )
    p.add_argument(
        "--resume",
        default="",
        metavar="SESSION_DIR",
        help="resume an interrupted sweep: path to its logs/<session> "
        "directory. Journaled-complete cases are replayed from the journal "
        "without re-running; interrupted/missing ones run normally and "
        "append to the same CSV (docs/RESILIENCE.md)",
    )
    return p


def main(argv=None) -> int:
    args = make_parser().parse_args(argv)
    from .configs import REGISTRY

    configs = [c.strip() for c in args.configs.split(",") if c.strip()]
    shard_counts = [int(s) for s in args.shards.split(",")]
    batches = [int(b) for b in args.batches.split(",")]
    computes = [c.strip() for c in args.computes.split(",") if c.strip()]
    bad = [c for c in computes if c not in ("fp32", "bf16", "int8w")]
    if bad:
        print(f"unknown compute modes: {bad}", file=sys.stderr)
        return 2
    unknown = [c for c in configs if c not in REGISTRY]
    if unknown:
        print(f"unknown configs: {unknown}", file=sys.stderr)
        return 2

    if args.resume:
        sdir = Path(args.resume)
        if not sdir.is_dir():
            print(f"--resume: no such session directory {sdir}", file=sys.stderr)
            return 2
        session = Session(
            log_root=sdir.parent, session_id=sdir.name, resume=True
        )
        print(
            f"Resuming session {session.session_id}: "
            f"{len(session.completed)} journaled-complete case(s) will be skipped"
        )
    else:
        session = Session(log_root=Path(args.log_root))
    print(f"Session: {session.session_id} (commit {session.commit})")
    print(f"Logs:    {session.dir}")

    extra = ["--height", str(args.height), "--width", str(args.width), "--repeats", str(args.repeats)]
    if args.fallback_chain:
        extra += ["--fallback-chain", args.fallback_chain]
    if args.plan:
        extra += ["--plan", args.plan]
    if args.supervise:
        extra += ["--supervise"]
    policy = RetryPolicy(max_retries=max(0, args.max_retries), base_delay_s=args.retry_backoff)
    deadline = Deadline.after(args.deadline_s or None)
    results: List[CaseResult] = []
    for key in configs:
        variant = REGISTRY[key].version_name
        single = REGISTRY[key].strategy == "single"
        for np_ in [1] if single else shard_counts:
            for batch in batches:
                for compute in computes:
                    # --oversubscribe semantics: with --fake-devices, grow the
                    # virtual mesh to fit np_ so every sweep point actually runs.
                    fake = max(args.fake_devices, np_) if args.fake_devices else 0
                    # Non-fp32 rows get a distinct variant name so the
                    # analysis warehouse keeps the modes separate
                    # (analysis.md:69-92 canonical-name discipline).
                    vname = variant if compute == "fp32" else f"{variant} {compute}"
                    # Full-AlexNet rows use seeded-random init: constant init
                    # is degenerate there (identical weights per channel ->
                    # all 1000 logits equal), so its printed first-5 verifies
                    # nothing. Seed 0's golden is committed in tests/oracle.py
                    # (V6_RANDOM_SEED0_BATCH1_FIRST10).
                    init_args = (
                        ["--init", "random", "--seed", "0"]
                        if REGISTRY[key].model == "alexnet_full"
                        else []
                    )
                    case_key = f"{key}|np={np_}|b={batch}|{compute}"
                    if case_key in session.completed:
                        r = case_result_from_row(
                            session.completed[case_key].get("row", {})
                        )
                        results.append(r)
                        print(
                            f"[{key} np={np_} b={batch} {compute}] "
                            f"{STATUS_SYMBOL.get(r.status, '?')} {r.status} "
                            "(journaled, skipped)"
                        )
                        continue
                    print(f"[{key} np={np_} b={batch} {compute}] ...", end="", flush=True)
                    r = run_case(
                        session,
                        key,
                        vname,
                        np_,
                        batch,
                        timeout_s=args.timeout,
                        fake_devices=fake,
                        # int8w rides the policy flag (the legacy --compute
                        # spelling stays fp32|bf16-only for old scripts).
                        extra_args=extra
                        + (
                            ["--dtype", compute]
                            if compute == "int8w"
                            else ["--compute", compute]
                        )
                        + init_args,
                        # Distinct log file per compute mode — both sweeps of
                        # one (config, np, batch) point must keep their logs.
                        log_tag=compute if len(computes) > 1 else "",
                        retry_policy=policy,
                        deadline=deadline,
                        journal_key=case_key,
                    )
                    results.append(r)
                    tail = f"{r.time_ms:.1f} ms" if r.time_ms is not None else r.run_msg
                    print(f" {STATUS_SYMBOL.get(r.status, '?')} {r.status} {tail}")

    print()
    print(summary_table(results))
    print(f"\nCSV: {session.csv_path}")
    # Warnings don't fail the suite (common_test_utils.sh exit semantics).
    worst = {CRITICAL: 4, FAIL: 1, TIMEOUT: 2}
    return max((worst.get(r.status, 0) for r in results), default=0)


if __name__ == "__main__":
    raise SystemExit(main())

"""Device-mesh construction.

The TPU analogue of the reference's process topology: ``mpirun -np N``
(scripts/common_test_utils.sh:274-276) becomes a 1-D ``jax.sharding.Mesh``
over N devices whose axis carries the row decomposition ("sp", the
sequence/context-parallel axis over image height), optionally crossed with a
data-parallel batch axis ("dp"). Multi-host pods extend the same mesh with a
DCN axis (see parallel.distributed).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def device_count() -> int:
    return jax.device_count()


def make_mesh(
    n_shards: int,
    axis_name: str = "sp",
    dp: int = 1,
    dp_axis_name: str = "dp",
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(dp, n_shards)`` mesh (1-D when ``dp == 1``).

    Shard axis is innermost so neighbor ``ppermute`` halo shifts ride
    adjacent-device ICI links.
    """
    devs = list(devices) if devices is not None else jax.devices()
    need = dp * n_shards
    if len(devs) < need:
        raise ValueError(
            f"mesh needs {need} devices (dp={dp} x shards={n_shards}), have {len(devs)}"
        )
    grid = np.array(devs[:need]).reshape(dp, n_shards)
    if dp == 1:
        return Mesh(grid.reshape(n_shards), (axis_name,))
    return Mesh(grid, (dp_axis_name, axis_name))

"""Neighbor halo exchange over the shard axis via ``ppermute``.

The TPU-native replacement for the reference's nonblocking MPI halo
exchange (``MPI_Irecv/Isend/Wait`` with tags 0/1 and 2/3,
2.2_scatter_halo/src/main.cpp:118-135,178-187; V4 host-staged variant
v4_mpi_cuda/src/main_mpi_cuda.cpp:64-79). Two ``ppermute`` shifts move
boundary rows directly device-to-device over ICI — the reference's
planned-but-unbuilt V5 ("CUDA-aware MPI", README.md:158-166) is the
*default* transport here.

Edge behavior: ``lax.ppermute`` delivers zeros to devices with no source in
the permutation, which is exactly the zero-fill the reference applies at
boundary ranks (2.2:124-135) and doubles as the conv's global zero padding.

``halo_exchange_gathered`` is the deliberately-inefficient V4 analogue: it
all-gathers every shard's block and slices halos locally — the moral
equivalent of V4 staging halos through host memory — kept as a measured
config so the V4-vs-V5 comparison story is reproducible on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def halo_exchange(x: jax.Array, h_top: int, h_bot: int, axis_name: str, n_shards: int) -> jax.Array:
    """Pad a per-shard row block with neighbor halos along axis 1.

    ``x``: (N, B, W, C) block inside shard_map. Returns
    (N, h_top + B + h_bot, W, C). Shard 0's top and shard n-1's bottom
    arrive as zeros.

    Halos wider than one block are fetched **multi-hop**: hop ``k`` pulls
    from the shard ``k`` away (farthest hop sends only the rows still
    missing). This is what lets shard counts exceed the row count of small
    late layers — the failure mode the reference could not express at all
    (its ranks exchange with immediate neighbors only).
    """
    b = x.shape[1]
    parts = []
    if h_top > 0:
        k_top = -(-h_top // b)  # ceil
        for k in range(k_top, 0, -1):  # farthest neighbor first (topmost rows)
            down = [(j, j + k) for j in range(n_shards - k)]
            rows = h_top - (k - 1) * b if k == k_top else b
            parts.append(lax.ppermute(x[:, -rows:] if rows < b else x, axis_name, down))
    parts.append(x)
    if h_bot > 0:
        k_bot = -(-h_bot // b)
        for k in range(1, k_bot + 1):
            up = [(j + k, j) for j in range(n_shards - k)]
            rows = h_bot - (k - 1) * b if k == k_bot else b
            parts.append(lax.ppermute(x[:, :rows] if rows < b else x, axis_name, up))
    if len(parts) == 1:
        return x
    return jnp.concatenate(parts, axis=1)


def halo_exchange_gathered(
    x: jax.Array, h_top: int, h_bot: int, axis_name: str, n_shards: int
) -> jax.Array:
    """V4-style staged halo: all_gather all blocks, slice what's needed.

    Moves n_shards*B rows per device instead of h_top+h_bot — the measured
    cost of the reference's "stage everything through a central hop" design.
    """
    if h_top == 0 and h_bot == 0:
        return x
    b = x.shape[1]
    i = lax.axis_index(axis_name)
    full = lax.all_gather(x, axis_name, axis=1, tiled=True)  # (N, n*B, W, C)
    total = n_shards * b
    # zero-pad both ends so edge shards read zeros, then dynamic-slice
    padded = jnp.pad(full, ((0, 0), (h_top, h_bot), (0, 0), (0, 0)))
    start = i * b  # position of this shard's block start inside `padded`
    return lax.dynamic_slice_in_dim(padded, start, h_top + b + h_bot, axis=1)


def exchange(staged: bool):
    return halo_exchange_gathered if staged else halo_exchange

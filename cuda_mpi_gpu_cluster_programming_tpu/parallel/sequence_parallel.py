"""Long-context sequence parallelism: ring attention + Ulysses all-to-all.

The reference's only long-axis decomposition is its 1-D image-row scatter
with neighbor halo exchange (SURVEY §5.7 — "mechanically identical to
context-parallel stencil pipelines"). This module is the genuine long-context
tier built on the same mesh machinery:

- :func:`ring_attention` — blockwise attention with online-softmax
  accumulation; K/V blocks circulate the ring via ``lax.ppermute`` over ICI
  while every shard keeps only ``L/n`` of the sequence resident. Memory per
  chip is O(L/n), so context length scales linearly with the ring size.
- :func:`ulysses_attention` — all-to-all sequence parallelism: reshard from
  sequence-sharded to head-sharded with ``lax.all_to_all``, run exact local
  attention over the full sequence for the local heads, reshard back.
  Communication is two all-to-alls instead of n ppermute hops; needs
  ``n_heads % n_shards == 0``.

Both are validated shard-vs-single against ``ops.attention.attention`` on
the virtual 8-device mesh (tests/test_sequence_parallel.py), the same
equivalence discipline as the conv pipeline.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import NEG_INF
from ..ops.vma import kernel_check_vma
from .compat import shard_map, to_varying
from .mesh import make_mesh


def _block_scores(q, k, scale):
    """(B, Lq, H, D) x (B, Lk, H, D) -> fp32 scores (B, H, Lq, Lk)."""
    return jnp.einsum(
        "blhd,bmhd->bhlm", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale


def _ring_attention_local(q, k, v, *, axis_name: str, n_shards: int, causal: bool, vary_axes=None):
    """Per-shard body: online-softmax over ring-circulating K/V blocks.

    q/k/v: this shard's (B, Lb, H, D) block. At step t the resident K/V
    block is the one originally owned by shard ``(me - t) mod n`` (each step
    ppermutes blocks one hop forward around the ring).
    """
    b, lb, h, d = q.shape
    me = lax.axis_index(axis_name)
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))
    q_pos = me * lb + jnp.arange(lb)  # global positions of my queries

    def step(t, carry):
        k_blk, v_blk, m, num, den = carry
        src = (me - t) % n_shards  # original owner of the resident block
        s = _block_scores(q, k_blk, scale)  # (B, H, Lb, Lb)
        if causal:
            k_pos = src * lb + jnp.arange(lb)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, NEG_INF)
        blk_max = jnp.max(s, axis=-1)  # (B, H, Lb)
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])  # (B, H, Lb, Lb)
        num = num * corr[..., None] + jnp.einsum(
            "bhlm,bmhd->bhld", p, v_blk.astype(jnp.float32)
        )
        den = den * corr + jnp.sum(p, axis=-1)
        # Circulate K/V one hop: shard i -> shard (i+1) mod n.
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, m_new, num, den

    # The zero/neg-inf initials are shard-invariant, but the loop carries
    # shard-varying updates — fori_loop needs both sides typed alike.
    _to_varying = _to_varying_fn(vary_axes or (axis_name,))
    m0 = _to_varying(jnp.full((b, h, lb), NEG_INF, jnp.float32))
    num0 = _to_varying(jnp.zeros((b, h, lb, d), jnp.float32))
    den0 = _to_varying(jnp.zeros((b, h, lb), jnp.float32))
    # lax.fori_loop keeps the compiled program size O(1) in ring size (a
    # Python loop would unroll n_shards copies of the body — fine at 8,
    # wasteful at pod scale). The causal mask already indexes by the traced
    # step (`src = (me - t) % n`), and the ppermute count is exactly
    # n_shards, so the last rotation restores K/V ownership.
    _, _, _, num, den = lax.fori_loop(0, n_shards, step, (k, v, m0, num0, den0))
    out = num / jnp.maximum(den, 1e-30)[..., None]  # (B, H, Lb, D)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def _to_varying_fn(axes):
    # lax.pcast(..., to='varying') is the current spelling; pvary the
    # deprecated alias; identity on releases without either (their rep
    # system does not type fori_loop carries as varying). ``axes``: every
    # mesh axis the loop carry varies over — with a head_axis (sp x tp
    # composition) the K/V inputs vary over BOTH, and fori_loop demands
    # carry-in/carry-out type equality. One implementation: parallel.compat.
    return to_varying(axes)


def _ring_attention_local_flash(q, k, v, *, axis_name: str, n_shards: int, causal: bool, vary_axes=None):
    """Flash-engine ring body: each hop runs the Pallas flash kernel on the
    resident K/V block and merges the normalized partial via its per-row
    LSE — exact, because partials over disjoint key sets satisfy

        lse  = logaddexp(lse1, lse2)
        out  = exp(lse1 - lse)*out1 + exp(lse2 - lse)*out2.

    Removes the einsum engine's (B, H, Lb, Lb) score residency: memory is
    O(Lb·D) per chip on top of the ring's O(L/n) — the two-level long-
    context composition (ring across chips × flash within chip). Causal
    hops split three ways on the block's global position: src < me = full
    attention, src == me = in-block causal, src > me = skipped (the flash
    kernel's causal mask is block-local, so the split is done here).

    Differentiable end to end: each hop's kernel call carries the joint
    (out, lse) VJP, the LSE-merge arithmetic is plain XLA, and the
    fori_loop/ppermute/switch all have transpose rules — so this body
    needs no custom backward of its own.
    """
    from ..ops.flash_attention import flash_attention_with_lse

    b, lb, h, d = q.shape
    me = lax.axis_index(axis_name)
    vary = tuple(vary_axes or (axis_name,))
    tv = _to_varying_fn(vary)

    # The kernel calls carry vma on their out_shapes and skip_fn pcasts its
    # constants, so all three lax.switch branches type-check as varying and
    # the shard_map keeps check_vma=True (scoped fix: the checker still
    # guards the ppermutes and the LSE merge).
    def full_fn(q, kb, vb):
        o, s = flash_attention_with_lse(q, kb, vb, causal=False, vma=vary)
        return o.astype(jnp.float32), s

    def causal_fn(q, kb, vb):
        o, s = flash_attention_with_lse(q, kb, vb, causal=True, vma=vary)
        return o.astype(jnp.float32), s

    def skip_fn(q, kb, vb):
        return (
            tv(jnp.zeros((b, lb, h, d), jnp.float32)),
            tv(jnp.full((b, h, lb), NEG_INF, jnp.float32)),
        )

    def step(t, carry):
        k_blk, v_blk, out, lse = carry
        src = (me - t) % n_shards
        if causal:
            idx = jnp.where(src < me, 0, jnp.where(src == me, 1, 2))
            o_t, lse_t = lax.switch(idx, [full_fn, causal_fn, skip_fn], q, k_blk, v_blk)
        else:
            o_t, lse_t = full_fn(q, k_blk, v_blk)
        lse_new = jnp.logaddexp(lse, lse_t)  # (B, H, Lb)
        w_old = jnp.exp(lse - lse_new)
        w_t = jnp.exp(lse_t - lse_new)
        out = (
            out * jnp.transpose(w_old, (0, 2, 1))[..., None]
            + o_t * jnp.transpose(w_t, (0, 2, 1))[..., None]
        )
        perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        return k_blk, v_blk, out, lse_new

    out0 = tv(jnp.zeros((b, lb, h, d), jnp.float32))
    lse0 = tv(jnp.full((b, h, lb), NEG_INF, jnp.float32))
    _, _, out, _ = lax.fori_loop(0, n_shards, step, (k, v, out0, lse0))
    return out.astype(q.dtype)


def _validate_engine(engine: str) -> None:
    if engine not in ("einsum", "flash"):
        raise ValueError(f"engine must be einsum|flash, got {engine!r}")


def _validate_mesh_axis_size(mesh, axis_name: str, n_shards: int) -> None:
    """n_shards must equal the mesh's axis size. Ring: the fori_loop runs
    n_shards hops and the ppermute permutation has n_shards entries, so a
    mismatch silently computes attention over a subset of the K/V blocks
    (verified: max abs error ~0.8 vs the oracle). Ulysses: the L/H split
    arithmetic assumes the all_to_all group size equals n_shards."""
    if mesh is not None and dict(mesh.shape).get(axis_name) != n_shards:
        raise ValueError(
            f"n_shards ({n_shards}) != mesh axis {axis_name!r} size "
            f"({dict(mesh.shape).get(axis_name)}); the ring/reshard hop "
            "count is n_shards"
        )


def _validate_head_axis_mesh(mesh, head_axis: str) -> int:
    """Shared sp x tp pre-validation: explicit mesh, axis present. Returns
    the head-axis size so each caller applies its own divisibility rule
    (ring: tp; ulysses: sp*tp) — all with global numbers, so failures
    never surface as raw shard_map errors quoting shard-local shapes."""
    if mesh is None:
        raise ValueError("head_axis needs an explicit mesh containing both axes")
    if head_axis not in mesh.shape:
        raise ValueError(
            f"head_axis {head_axis!r} not in mesh axes {tuple(mesh.shape)}"
        )
    return dict(mesh.shape)[head_axis]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    n_shards: int,
    causal: bool = False,
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
    engine: str = "einsum",
    head_axis: Optional[str] = None,
    with_digests: bool = False,
) -> jax.Array:
    """Sequence-sharded blockwise ring attention. q,k,v: (B, L, H, D).

    ``with_digests``: return ``(out, {"qkv": (n,), "out": (n,)})`` — one
    in-graph activation digest per shard of the inputs and of the attention
    output, computed inside the shard_map body (the SDC sentinel taps; see
    ``parallel.sharded``). Screening is host-side and off the timed path.

    The sequence axis is sharded ``n_shards`` ways; K/V blocks ride the ring
    via ``ppermute`` (ICI neighbor traffic, the same collective as the conv
    halo exchange). Requires ``L % n_shards == 0``.

    ``head_axis``: optional second mesh axis sharding H — the sp×tp
    composition (Megatron attention heads over ``tp``, sequence over
    ``sp``). Heads are embarrassingly parallel in attention, so the ring
    body is unchanged; only the shard_map spec names the extra axis. The
    caller's ``mesh`` must contain both axes.

    ``engine``: ``"einsum"`` (default) materializes each hop's (Lb, Lb)
    score block with XLA ops — differentiable. ``"flash"`` runs the Pallas
    flash kernel per hop and merges partials by LSE — O(Lb·D) within-chip
    memory for long per-chip blocks, and ALSO differentiable: the kernel's
    joint (out, lse) VJP (ops.flash_attention) lets gradients flow through
    the per-hop merge, so autodiff reverses the whole ring (ppermutes
    transpose to reversed permutations, the merge arithmetic is plain XLA).
    Gradient-vs-oracle equivalence is tested at n∈{2,4}, causal and not
    (tests/test_flash_attention.py).
    """
    b, l, h, d = q.shape
    if l % n_shards != 0:
        raise ValueError(f"sequence length {l} not divisible by {n_shards} shards")
    _validate_engine(engine)
    if engine == "flash":
        # The flash kernel tiles each shard's block at (up to) 128 rows, so
        # the PER-SHARD length must divide by its clamped block size —
        # validate here with global numbers, or the error would surface
        # from inside the shard_map trace quoting the shard-local length.
        from ..ops.flash_attention import flash_block

        lb = l // n_shards
        blk = flash_block(lb)
        if lb % blk:
            raise ValueError(
                f"engine='flash' needs the per-shard block (L/n = {lb}) to be "
                f"a multiple of the flash block size ({blk}); L={l}, "
                f"n_shards={n_shards}. Use the einsum engine or pad L."
            )
    _validate_mesh_axis_size(mesh, axis_name, n_shards)
    if head_axis is not None:
        tp = _validate_head_axis_mesh(mesh, head_axis)
        if h % tp:
            raise ValueError(f"head count {h} not divisible by {head_axis}={tp} shards")
    if mesh is None:
        mesh = make_mesh(n_shards, axis_name=axis_name)
    local = _ring_attention_local_flash if engine == "flash" else _ring_attention_local
    vary = (axis_name,) + ((head_axis,) if head_axis else ())
    body = functools.partial(
        local, axis_name=axis_name, n_shards=n_shards, causal=causal,
        vary_axes=vary,
    )
    spec = P(None, axis_name, head_axis, None)
    fn = shard_map(
        _with_stage_digests(body) if with_digests else body,
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(
            (spec, {"qkv": P(axis_name), "out": P(axis_name)})
            if with_digests
            else spec
        ),
        # Flash engine: checker ON wherever the kernels can tag vma (real
        # TPU) — ops.vma.kernel_check_vma; the blanket disable now only
        # survives in interpret mode, where jax's own interpreter can't
        # propagate vma. Einsum engine: always on.
        check_vma=(engine != "flash" or kernel_check_vma()),
    )
    return fn(q, k, v)


def _with_stage_digests(body):
    """Wrap a per-shard attention body with in-graph sentinel taps: digest
    the (q, k, v) inputs and the output on each shard (one float32 scalar
    apiece, concatenated across shards by the caller's out_specs)."""
    from ..resilience.sentinel import tree_digest

    def tapped(q, k, v):
        out = body(q, k, v)
        digs = {"qkv": tree_digest((q, k, v))[None], "out": tree_digest(out)[None]}
        return out, digs

    return tapped


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool, engine: str, vary_axes=None):  # noqa: D401
    """Per-shard body: all_to_all L-shard -> H-shard, exact attention, back.

    After the reshard each shard holds the FULL sequence for its local
    heads, so ``engine='flash'`` is just :func:`ops.flash_attention` on
    that call — the whole-sequence signature with the standard flash VJP
    (the ring engine instead differentiates through its per-hop joint
    (out, lse) VJP) — dropping the (L, L) score residency of the einsum
    path.
    """
    if engine == "flash":
        from ..ops.flash_attention import flash_attention

        # vma-tagged kernel out_shapes keep the caller's check_vma=True
        # guarding the two all_to_alls (scoped round-3-advisor fix).
        attention = functools.partial(
            flash_attention, vma=tuple(vary_axes or (axis_name,))
        )
    else:
        from ..ops.attention import attention

    # (B, Lb, H, D) -> (B, L, Hb, D): concat sequence, split heads.
    def to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    out = attention(to_heads(q), to_heads(k), to_heads(v), causal=causal)
    return to_seq(out)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    n_shards: int,
    causal: bool = False,
    mesh: Optional[Mesh] = None,
    axis_name: str = "sp",
    engine: str = "einsum",
    head_axis: Optional[str] = None,
    with_digests: bool = False,
) -> jax.Array:
    """All-to-all (Ulysses-style) sequence parallelism. q,k,v: (B, L, H, D).

    ``with_digests``: as in :func:`ring_attention` — per-shard in-graph
    digests of the inputs and output ride alongside the result.

    Resharding sequence->heads makes each shard run *exact* attention over
    the full sequence for ``H/n`` heads; two tiled ``all_to_all`` collectives
    replace the ring's n ppermute hops. Requires ``L % n == 0`` and
    ``H % n == 0``.

    ``engine='flash'`` swaps the local exact attention for the Pallas flash
    kernel — O(L) instead of O(L^2) memory per shard, and still
    differentiable (the local call is the whole-sequence signature the
    flash custom VJP covers). Requires ``L`` to divide by the flash block
    (128 when ``L >= 128``).
    """
    b, l, h, d = q.shape
    if l % n_shards != 0:
        raise ValueError(f"sequence length {l} not divisible by {n_shards} shards")
    if h % n_shards != 0:
        raise ValueError(f"head count {h} not divisible by {n_shards} shards")
    _validate_mesh_axis_size(mesh, axis_name, n_shards)
    if head_axis is not None:
        # sp x tp: heads are pre-sharded over tp; the all_to_all then splits
        # each tp shard's local heads over sp, so H must divide by BOTH.
        tp = _validate_head_axis_mesh(mesh, head_axis)
        if h % (n_shards * tp):
            raise ValueError(
                f"head count {h} not divisible by sp x {head_axis} = "
                f"{n_shards} x {tp} shards"
            )
    _validate_engine(engine)
    if engine == "flash":
        from ..ops.flash_attention import flash_block

        blk = flash_block(l)
        if l % blk:
            raise ValueError(
                f"engine='flash' needs L ({l}) to be a multiple of the flash "
                f"block size ({blk}). Use the einsum engine or pad L."
            )
    if mesh is None:
        mesh = make_mesh(n_shards, axis_name=axis_name)
    body = functools.partial(
        _ulysses_local, axis_name=axis_name, causal=causal, engine=engine,
        vary_axes=(axis_name,) + ((head_axis,) if head_axis else ()),
    )
    spec = P(None, axis_name, head_axis, None)
    fn = shard_map(
        _with_stage_digests(body) if with_digests else body,
        mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=(
            (spec, {"qkv": P(axis_name), "out": P(axis_name)})
            if with_digests
            else spec
        ),
        # Same policy as ring: flash keeps the checker wherever the kernel
        # can tag vma (real TPU); einsum always.
        check_vma=(engine != "flash" or kernel_check_vma()),
    )
    return fn(q, k, v)

"""Pipeline parallelism: GPipe-style microbatch rotation over a ``pp`` axis.

The reference has no pipeline tier (SURVEY §2.2 marks PP "no"); this module
completes the framework's parallelism zoo (dp / sp-cp / tp / pp / ep) the
TPU-native way: the layer stack is split into S stages, stage s's params
live on mesh slot s (``shard_map`` over the "pp" axis), and microbatches
rotate stage-to-stage via ``lax.ppermute`` — ICI neighbor traffic, the same
collective that carries the conv halo and the ring-attention K/V blocks.

Schedule: classic GPipe fill-and-drain. With M microbatches and S stages
the loop runs M + S - 1 steps; at step t, stage 0 ingests microbatch t
(while t < M) and stage S-1 emits microbatch t - (S-1) (once t >= S-1).
The whole schedule is a single ``lax.scan`` — compiled program size is
O(1) in both M and S — and is differentiable end to end, so the same code
path serves training (activations are rematerialized by scan's transpose,
GPipe's per-microbatch checkpointing for free).

No deviation from the math: pipelining reorders *scheduling*, not
arithmetic — per-microbatch outputs are bit-identical to the sequential
forward (enforced in tests/test_pipeline.py).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .compat import shard_map
from .mesh import make_mesh

Params = Any


def stack_layers(layers: List[Params]) -> Params:
    """List of per-layer pytrees -> one pytree with a stacked leading axis.

    The stacked axis is what ``pipeline_apply`` shards over "pp" (and what
    the stage body scans over), so S stages of L/S layers each see leaves
    of shape (S, L/S, ...)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *layers)


def _reshape_stages(stacked: Params, n_stages: int) -> Params:
    def r(x):
        n_layers = x.shape[0]
        if n_layers % n_stages:
            raise ValueError(f"{n_layers} layers not divisible by {n_stages} stages")
        return x.reshape(n_stages, n_layers // n_stages, *x.shape[1:])

    return jax.tree_util.tree_map(r, stacked)


def pipeline_apply(
    layer_fn: Callable[[Params, jax.Array], jax.Array],
    stacked_layers: Params,
    x: jax.Array,
    *,
    n_stages: int,
    n_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = "pp",
    dp_axis: Optional[str] = None,
) -> jax.Array:
    """Run ``layer_fn`` over every layer of ``stacked_layers`` on ``x``,
    layers split into ``n_stages`` pipeline stages over the mesh.

    ``layer_fn(layer_params, x) -> x`` applies ONE layer; stages scan it
    over their layers-per-stage block. ``x`` is (B, ...) with B divisible
    by ``n_microbatches``. Returns the same (B, ...) as the sequential
    ``for layer: x = layer_fn(layer, x)`` composition.

    ``dp_axis`` composes the pipeline with data parallelism on a 2-D mesh
    (e.g. ``Mesh(..., ("dp", "pp"))``): each dp row runs the full pipeline
    on its microbatch slice — stage params replicated over dp, microbatch
    dim sharded over dp, ppermute/psum confined to the pp axis. The caller
    shards B over dp outside (or relies on shard_map's split here).
    """
    b = x.shape[0]
    m = n_microbatches
    if b % m:
        raise ValueError(f"batch {b} not divisible by {m} microbatches")
    if mesh is None:
        if dp_axis is not None:
            raise ValueError(
                "dp_axis requires an explicit 2-D mesh containing that axis "
                "(the auto-built default mesh is pp-only)"
            )
        mesh = make_mesh(n_stages, axis_name=axis_name)
    if dp_axis is not None:
        if dp_axis not in mesh.axis_names:
            raise ValueError(
                f"dp_axis {dp_axis!r} not in mesh axes {mesh.axis_names}"
            )
        dp = mesh.shape[dp_axis]
        if (b // m) % dp:
            raise ValueError(
                f"microbatch size {b // m} not divisible by dp axis {dp}"
            )
    staged = _reshape_stages(stacked_layers, n_stages)
    x_mb = x.reshape(m, b // m, *x.shape[1:])

    def stage_body(stage_params, x_all):
        """One device's life: S + M - 1 scan steps of its own stage."""
        me = lax.axis_index(axis_name)
        s = n_stages

        def apply_stage(inp):
            # stage_params leaves are (1, L/S, ...) after shard_map split.
            def one_layer(h, lp):
                return layer_fn(lp, h), None

            squeezed = jax.tree_util.tree_map(lambda p: p[0], stage_params)
            out, _ = lax.scan(one_layer, inp, squeezed)
            return out

        def step(carry, t):
            state = carry
            # Stage 0 ingests microbatch t (clamped; steps past M re-feed
            # the last microbatch, but their outputs are never collected).
            feed = lax.dynamic_index_in_dim(
                x_all, jnp.clip(t, 0, m - 1), axis=0, keepdims=False
            )
            inp = jnp.where(me == 0, feed, state)
            out = apply_stage(inp)
            # Last stage's output at step t is microbatch t-(S-1): collect
            # it there, zeros elsewhere; psum after the scan replicates.
            emit = jnp.where((me == s - 1) & (t >= s - 1), out, jnp.zeros_like(out))
            # Rotate every stage's output one hop down the pipeline.
            nxt = lax.ppermute(
                out, axis_name, [(i, (i + 1) % s) for i in range(s)]
            )
            return nxt, emit

        state0 = jnp.zeros_like(x_all[0])
        _, emitted = lax.scan(step, state0, jnp.arange(m + s - 1))
        # emitted: (M+S-1, mb, ...); microbatch j lives at step S-1+j on the
        # last stage and is zero everywhere else -> psum replicates it.
        y = lax.psum(emitted[s - 1 :], axis_name)
        return y

    if dp_axis is None:
        in_specs = (P(axis_name), P())  # stage axis sharded; input replicated
        out_specs = P()
    else:
        # dp x pp: stage params replicated over dp; the microbatch dim (dim 1
        # of x_mb) and of the output sharded over dp.
        in_specs = (P(axis_name), P(None, dp_axis))
        out_specs = P(None, dp_axis)
    fn = shard_map(
        stage_body,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        # noqa: check-vma-disabled — the psum-of-zeros collection trick
        # produces a genuinely replicated result the checker can't prove.
        check_vma=False,
    )
    y = fn(staged, x_mb)
    return y.reshape(b, *x.shape[1:])


def pipeline_lm_forward(
    params: Params,
    tokens: jax.Array,
    cfg,
    *,
    n_stages: int,
    n_microbatches: int,
    mesh: Optional[Mesh] = None,
    dp_axis: Optional[str] = None,
) -> jax.Array:
    """Transformer-LM forward with the decoder stack pipelined over "pp".

    Embedding and the weight-tied head run replicated outside the pipeline
    (they are a tiny fraction of the FLOPs); the n_layers decoder blocks
    are staged. Numerically identical to ``forward_lm`` — enforced in
    tests/test_pipeline.py.
    """
    from ..models.transformer import decoder_block, rmsnorm

    b, l = tokens.shape
    if l > cfg.max_len:
        raise ValueError(f"sequence length {l} exceeds max_len {cfg.max_len}")
    x = params["embed"][tokens] + params["pos"][:l][None]
    stacked = stack_layers(params["layers"])
    x = pipeline_apply(
        functools.partial(decoder_block, cfg=cfg),
        stacked,
        x,
        n_stages=n_stages,
        n_microbatches=n_microbatches,
        mesh=mesh,
        dp_axis=dp_axis,
    )
    x = rmsnorm(x, params["final_norm"]["g"])
    return x @ params["embed"].T


def pipeline_lm_loss(
    params: Params,
    tokens: jax.Array,
    cfg,
    *,
    n_stages: int,
    n_microbatches: int,
    mesh: Optional[Mesh] = None,
    dp_axis: Optional[str] = None,
) -> jax.Array:
    """Next-token cross-entropy through the pipelined forward."""
    logits = pipeline_lm_forward(
        params,
        tokens[:, :-1],
        cfg,
        n_stages=n_stages,
        n_microbatches=n_microbatches,
        mesh=mesh,
        dp_axis=dp_axis,
    ).astype(jnp.float32)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)

"""Multi-host runner: host inventory, pod init, DCN x ICI meshes, launcher.

The reference's multi-machine layer is ``scripts/2_final_multi_machine.sh``:
a ``HOSTS_INFO`` inventory of ``user@host arch`` entries (:26-29),
passwordless-SSH setup (:219-241), rsync code sync (:258-287), MPI hostfile
generation (:289-303), and ``mpirun --hostfile ... --mca btl_tcp_if_exclude
...`` launches (:393-410). On TPU the same capability is:

- **Inventory** — ``HostSpec``/``ClusterConfig``: the HOSTS_INFO analogue.
  ``arch`` becomes the accelerator kind per host; host 0 is the coordinator
  (the reference's master, :224).
- **Runtime init** — ``initialize()``: ``jax.distributed.initialize`` with
  coordinator address / process count / process id — the MPI_Init of the
  JAX world. On real TPU pods all three are auto-detected from the metadata
  server; the explicit form is for CPU simulation and bring-your-own
  clusters.
- **Mesh** — ``make_multihost_mesh()``: a (dcn, ici) mesh where the slow
  inter-host axis (DCN — the analogue of the reference's TCP-between-
  machines) carries data parallelism and the fast intra-slice ICI axis
  carries the row/halo decomposition, so halos never cross DCN.
- **Launcher** — ``launch_plan()`` renders the per-host commands (the
  hostfile + mpirun analogue, printable/dry-runnable for SSH deployment);
  ``launch_local()`` actually runs an N-process cluster on localhost
  (each process a separate Python interpreter with its own XLA CPU
  backend, connected through the same gRPC coordinator a pod uses) — the
  ``mpirun --oversubscribe`` localhost test the reference relies on, but
  exercising the *real* multi-process runtime rather than a fake.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import socket
import subprocess
import sys
from typing import List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..utils.env_info import cpu_subprocess_env

DEFAULT_COORDINATOR_PORT = 9911


@dataclasses.dataclass(frozen=True)
class HostSpec:
    """One inventory entry (`user@host arch`, 2_final_multi_machine.sh:26-29)."""

    host: str
    user: Optional[str] = None
    arch: str = "tpu"  # accelerator kind; the GPU compute-capability analogue

    @classmethod
    def parse(cls, entry: str) -> "HostSpec":
        parts = entry.split()
        if not parts or len(parts) > 2:
            raise ValueError(
                f"malformed host entry {entry!r}: expected 'user@host arch' or 'host arch'"
            )
        addr = parts[0]
        arch = parts[1] if len(parts) == 2 else "tpu"
        user, _, host = addr.rpartition("@")
        return cls(host=host, user=user or None, arch=arch)

    @property
    def ssh_target(self) -> str:
        return f"{self.user}@{self.host}" if self.user else self.host


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """The HOSTS_INFO analogue: process 0's host coordinates the job."""

    hosts: Tuple[HostSpec, ...]
    coordinator_port: int = DEFAULT_COORDINATOR_PORT
    local_devices_per_host: Optional[int] = None  # None = autodetect

    @classmethod
    def parse(cls, entries: Sequence[str], port: int = DEFAULT_COORDINATOR_PORT) -> "ClusterConfig":
        return cls(hosts=tuple(HostSpec.parse(e) for e in entries), coordinator_port=port)

    @property
    def coordinator_address(self) -> str:
        return f"{self.hosts[0].host}:{self.coordinator_port}"

    @property
    def num_processes(self) -> int:
        return len(self.hosts)


def initialize(
    cluster: Optional[ClusterConfig] = None, process_id: Optional[int] = None
) -> None:
    """MPI_Init analogue. With no arguments (real pod), everything is
    auto-detected; with a ClusterConfig, pass explicit coordinates."""
    if cluster is None:
        jax.distributed.initialize()
        return
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(
        coordinator_address=cluster.coordinator_address,
        num_processes=cluster.num_processes,
        process_id=process_id,
    )


def maybe_initialize_from_env() -> bool:
    """Join the cluster described by JAX_COORDINATOR_ADDRESS /
    JAX_NUM_PROCESSES / JAX_PROCESS_ID if they are set (the variables
    ``launch_plan``/``launch_local`` export — jax itself only auto-reads the
    coordinator address, not the process coordinates). Call this at entry-
    point start; a no-op when the variables are absent or the runtime is
    already initialized. Returns True if it joined a cluster."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    n = os.environ.get("JAX_NUM_PROCESSES")
    if not addr or not n:
        return False
    if jax.distributed.is_initialized():
        return True
    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(n),
        process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
    )
    return True


def make_multihost_mesh(
    ici_shards: Optional[int] = None,
    dcn_axis_name: str = "dp",
    ici_axis_name: str = "sp",
) -> Mesh:
    """(num_hosts, ici_shards) mesh: DCN outer (data parallel), ICI inner
    (row/halo decomposition). Defaults to all local devices per host on the
    ICI axis. Works identically for a real pod (devices grouped by process)
    and the localhost simulation."""
    n_proc = jax.process_count()
    n_local = jax.local_device_count()
    ici_shards = ici_shards or n_local
    if n_proc * ici_shards > jax.device_count():
        raise ValueError(
            f"mesh needs {n_proc}x{ici_shards} devices, have {jax.device_count()}"
        )
    # Group devices by owning process so the inner axis stays intra-host
    # (ICI) and only the outer axis crosses hosts (DCN).
    by_proc: dict = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    grid = np.array(
        [sorted(by_proc[p], key=lambda d: d.id)[:ici_shards] for p in sorted(by_proc)]
    )
    return Mesh(grid, (dcn_axis_name, ici_axis_name))


def launch_plan(
    cluster: ClusterConfig,
    script: str,
    script_args: Sequence[str] = (),
    workdir: str = "/root/repo",
    extra_env: Optional[dict] = None,
) -> List[str]:
    """Render per-host launch commands (hostfile + mpirun analogue,
    2_final_multi_machine.sh:289-303,393-410). Host 0's command runs
    locally; the rest are ssh invocations — printable for dry runs,
    executable by the deployment wrapper (``parallel.deploy``).

    ``extra_env`` adds environment assignments to every host's command (the
    ``--mca``/env-tuning analogue; e.g. the virtual-CPU variables for a
    localhost simulation)."""
    extras = "".join(
        f"{k}={shlex.quote(str(v))} " for k, v in (extra_env or {}).items()
    )
    cmds = []
    for pid, host in enumerate(cluster.hosts):
        inner = (
            f"cd {shlex.quote(workdir)} && "
            f"{extras}"
            f"JAX_COORDINATOR_ADDRESS={cluster.coordinator_address} "
            f"JAX_NUM_PROCESSES={cluster.num_processes} "
            f"JAX_PROCESS_ID={pid} "
            f"{sys.executable} -m {script} {' '.join(map(shlex.quote, script_args))}"
        ).rstrip()
        if pid == 0:
            cmds.append(inner)
        else:
            cmds.append(f"ssh {host.ssh_target} {shlex.quote(inner)}")
    return cmds


def launch_local(
    n_processes: int,
    devices_per_process: int = 1,
    module: str = "cuda_mpi_gpu_cluster_programming_tpu.parallel.distributed",
    module_args: Sequence[str] = (),
    timeout_s: float = 300.0,
    port: Optional[int] = None,
) -> List[subprocess.CompletedProcess]:
    """Run an N-process cluster on localhost (CPU backend, real gRPC
    coordinator). Each process sees only its own ``devices_per_process``
    local devices; jax.distributed stitches them into one global runtime —
    the honest analogue of `mpirun --oversubscribe -np N` on one machine."""
    if port is None:
        # Concurrent clusters on one machine must not collide on the
        # coordinator port: grab a free ephemeral one.
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
    procs = []
    for pid in range(n_processes):
        env = cpu_subprocess_env(devices_per_process)
        env["JAX_COORDINATOR_ADDRESS"] = f"localhost:{port}"
        env["JAX_NUM_PROCESSES"] = str(n_processes)
        env["JAX_PROCESS_ID"] = str(pid)
        procs.append(
            # The local cluster launch itself (waited on with a bounded
            # timeout by the caller), not a retryable transport.
            subprocess.Popen(  # noqa: raw-subprocess
                [sys.executable, "-m", module, *module_args],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
            )
        )
    done = []
    for pid, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        done.append(
            subprocess.CompletedProcess(p.args, p.returncode, stdout=out, stderr="")
        )
    return done


def _selftest_main() -> int:
    """Per-process body for the localhost cluster self-test: initialize the
    distributed runtime, build the DCN x ICI mesh, psum a rank-dependent
    value across every device, and verify the closed form on process 0 —
    the reference's parallel-vs-serial check (hw1) applied to the runtime
    itself."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    pid = int(os.environ.get("JAX_PROCESS_ID", "0"))
    if not maybe_initialize_from_env():
        raise SystemExit("selftest requires JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES")
    mesh = make_multihost_mesh()
    n_dev = jax.device_count()
    # Grouping invariant: the inner (ICI) axis must stay intra-process;
    # only the outer (DCN) axis crosses hosts.
    for row in mesh.devices:
        owners = {d.process_index for d in row}
        if len(owners) != 1:
            raise SystemExit(f"ICI axis crosses processes: {owners}")

    @jax.jit
    def global_sum(x):
        return jnp.sum(x)

    # One row per global device, value = device id + 1; the global sum over
    # the row-sharded array must equal n(n+1)/2 regardless of process count.
    rows = jax.device_put(
        np.arange(1, n_dev + 1, dtype=np.float32).reshape(n_dev, 1),
        NamedSharding(mesh, P(("dp", "sp"), None)),
    )
    total = float(global_sum(rows))
    expect = n_dev * (n_dev + 1) / 2
    ok = abs(total - expect) < 1e-6
    print(
        f"pid={pid}: processes={jax.process_count()} global_devices={n_dev} "
        f"mesh={dict(zip(mesh.axis_names, mesh.devices.shape))} "
        f"psum={total:.1f} expect={expect:.1f} -> {'PASSED' if ok else 'FAILED'}"
    )
    return 0 if ok else 1


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="cuda_mpi_gpu_cluster_programming_tpu.parallel.distributed"
    )
    p.add_argument(
        "--plan",
        nargs="+",
        metavar="HOST",
        help="print the per-host launch plan for this inventory and exit",
    )
    p.add_argument("--script", default="cuda_mpi_gpu_cluster_programming_tpu.run")
    p.add_argument(
        "--local-cluster",
        type=int,
        metavar="N",
        help="launch an N-process localhost cluster running the self-test",
    )
    p.add_argument("--devices-per-process", type=int, default=2)
    args = p.parse_args(argv)

    if args.plan:
        cluster = ClusterConfig.parse(args.plan)
        for cmd in launch_plan(cluster, args.script):
            print(cmd)
        return 0
    if args.local_cluster:
        results = launch_local(
            args.local_cluster, devices_per_process=args.devices_per_process
        )
        for r in results:
            sys.stdout.write(r.stdout)
        return max(r.returncode for r in results)
    # No orchestration flag: act as one process of a cluster (the mode
    # launch_local/launch_plan spawn).
    return _selftest_main()


if __name__ == "__main__":
    raise SystemExit(main())

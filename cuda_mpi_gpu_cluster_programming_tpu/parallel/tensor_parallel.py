"""Tensor parallelism: conv output-channel (K-axis) filter decomposition.

The parallelism family the reference names but never builds — "filter
decomposition" is listed as the alternative to its row decomposition
(reference README.md:638; SURVEY §2.2 marks TP "no — optional extension:
shard K axis of conv"). Where ``parallel.sharded`` splits the *spatial* H
axis (halos in image rows), this splits the *filter bank*: each shard owns
K/n output channels of every conv layer, so weights — not activations — are
what's partitioned. The two strategies are duals:

- row-sharding: activations sharded, weights replicated, halos in H;
- TP: weights sharded, activations replicated at layer boundaries, the
  "halo" rotated onto the channel axis (the LRN's cross-channel window
  needs ``size//2`` neighbor channels — exchanged with the same paired
  ``ppermute`` shifts the row pipeline uses for image rows).

Boundary collectives: one ``all_gather`` over channels after block 1
(conv2 consumes *all* of conv1's channels), one channel-halo ``ppermute``
pair before the LRN, and the shard_map output sharding assembles the final
channel-sharded result. Everything rides ICI.

Numerics: each output channel's dot products are computed by exactly one
shard with the same reduction order as the single-device pass, so the TP
forward is bit-exact vs ``forward_blocks12`` (tested at n ∈ {1,2,4,8} —
the same shard-vs-single discipline as the row pipeline).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..models.alexnet import BLOCKS12, Blocks12Config
from ..ops.reference import conv2d, lrn, maxpool, relu
from .compat import shard_map
from .mesh import make_mesh


def _channel_halo(z: jax.Array, half: int, axis_name: str, n_shards: int) -> jax.Array:
    """Attach ``half`` neighbor channels on each side of the local slice.

    Ring-edge shards receive ppermute's zero fill — equivalent to the LRN's
    clipped-window edge semantics, since the window sums squares and the
    zero channels contribute nothing (ops.reference.lrn edge behavior).
    """
    fwd = [(i, i + 1) for i in range(n_shards - 1)]  # shard i -> i+1
    bwd = [(i + 1, i) for i in range(n_shards - 1)]
    left = lax.ppermute(z[..., -half:], axis_name, fwd)  # prev shard's last channels
    right = lax.ppermute(z[..., :half], axis_name, bwd)  # next shard's first channels
    return jnp.concatenate([left, z, right], axis=-1)


def build_tp_forward(
    model_cfg: Blocks12Config = BLOCKS12,
    n_shards: int = 1,
    mesh: Optional[Mesh] = None,
    axis_name: str = "tp",
    with_digests: bool = False,
) -> Callable:
    """Jitted ``(params, x) -> out`` with conv filters K-sharded n ways.

    ``with_digests``: return ``(out, {layer: (n_shards,) float32})`` with
    one in-graph activation digest per Conv1/Pool1/Conv2/Pool2/LRN2
    boundary, taken on each shard's LOCAL channel slice inside the
    shard_map body (the SDC sentinel taps — see ``parallel.sharded``).
    """
    cfg = model_cfg
    for name, spec in (("conv1", cfg.conv1), ("conv2", cfg.conv2)):
        if spec.out_channels % n_shards:
            raise ValueError(
                f"{name} K={spec.out_channels} not divisible by {n_shards} TP shards"
            )
    half = cfg.lrn2.size // 2
    local2 = cfg.conv2.out_channels // n_shards
    if n_shards > 1 and local2 < half:
        raise ValueError(
            f"LRN window half-width {half} exceeds the {local2} local channels "
            f"at {n_shards} shards — channel halo would need multi-hop"
        )
    if mesh is None:
        mesh = make_mesh(n_shards, axis_name=axis_name)
    else:
        axis_name = mesh.axis_names[-1]
        axis_size = mesh.devices.shape[-1]
        if axis_size != n_shards:
            raise ValueError(
                f"mesh axis {axis_name!r} has {axis_size} devices but "
                f"tp n_shards={n_shards}; the filter slices would not line up"
            )

    if with_digests:
        from ..resilience.sentinel import tree_digest

    def local(params, x):
        p1, p2 = params["conv1"], params["conv2"]
        digs = {}

        def tap(name, v):
            # In-graph sentinel tap on the shard-LOCAL channel slice; one
            # float32 scalar per shard, concatenated to (n,) by out_specs.
            if with_digests:
                digs[name] = tree_digest(v)[None]
            return v

        # Block 1 on this shard's filter slice: (B, h, w, K1/n).
        y = tap("conv1", relu(conv2d(x, p1["w"], p1["b"], stride=cfg.conv1.stride, padding=cfg.conv1.padding)))
        y = tap("pool1", maxpool(y, window=cfg.pool1.window, stride=cfg.pool1.stride))
        # conv2 needs every conv1 channel: gather the channel axis (the TP
        # boundary collective — activations are small here, 27x27x96).
        y = lax.all_gather(y, axis_name, axis=3, tiled=True)
        z = tap("conv2", relu(conv2d(y, p2["w"], p2["b"], stride=cfg.conv2.stride, padding=cfg.conv2.padding)))
        z = tap("pool2", maxpool(z, window=cfg.pool2.window, stride=cfg.pool2.stride))
        # LRN crosses channels: exchange `half` neighbor channels, normalize,
        # keep the owned slice.
        if n_shards > 1:
            zp = _channel_halo(z, half, axis_name, n_shards)
        else:
            zp = z
        zl = lrn(
            zp,
            size=cfg.lrn2.size,
            alpha=cfg.lrn2.alpha,
            beta=cfg.lrn2.beta,
            k=cfg.lrn2.k,
            alpha_over_size=cfg.lrn2.alpha_over_size,
        )
        out = zl[..., half:-half] if n_shards > 1 else zl
        tap("lrn2", out)
        return (out, digs) if with_digests else out

    wspec = P(None, None, None, axis_name)  # HWIO: shard the O axis
    pspec = {
        "conv1": {"w": wspec, "b": P(axis_name)},
        "conv2": {"w": wspec, "b": P(axis_name)},
    }
    out_spec = P(None, None, None, axis_name)
    stages = ("conv1", "pool1", "conv2", "pool2", "lrn2")
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(pspec, P()),
        out_specs=(
            (out_spec, {s: P(axis_name) for s in stages})
            if with_digests
            else out_spec
        ),
    )
    return jax.jit(fn)


# --- Megatron-style tensor parallelism for the transformer LM family ----
#
# The conv TP above hand-writes its collectives (channel-halo LRN needs
# them); the LM's matmuls need none by hand — the classic Megatron layout
# (column-parallel wqkv/w_up, row-parallel wo/w_down, heads implicitly
# split with wqkv) is expressed as GSPMD shardings and XLA inserts the two
# all-reduces per block on its own. Scaling-book recipe: pick the mesh,
# annotate, let the compiler place collectives.

_LM_TP_SPECS = {
    "wqkv": ("tp_col",),    # (D, 3, D) -> shard the last (per-projection) dim
    "w_up": ("tp_col",),    # (D, F)    -> shard output dim
    "wo": ("tp_row",),      # (D, D)    -> shard input dim
    "w_down": ("tp_row",),  # (F, D)    -> shard input dim
}


def shard_lm_params_tp(params, mesh=None, *, n_shards: int = 0, axis_name: str = "tp"):
    """device_put transformer-LM params in the Megatron TP layout.

    Column-parallel matrices shard their LAST dim, row-parallel their
    FIRST; embeddings, position table, norms (and MoE expert stacks, whose
    parallel axis is "ep", not "tp") stay replicated. Works on dense-FFN
    configs; per-matrix divisibility is validated eagerly.
    """
    from jax.sharding import NamedSharding

    from .mesh import make_mesh as _make_mesh

    if mesh is None:
        mesh = _make_mesh(n_shards, axis_name=axis_name)
    tp = mesh.shape[axis_name]

    def put(path, leaf):
        key = getattr(path[-1], "key", None) if path else None
        kind = _LM_TP_SPECS.get(key)
        # ndim rules keep this strictly the DENSE Megatron layout: wqkv is
        # the (D, 3, D) rank-3 exception (column shard on the last,
        # per-projection dim so q/k/v boundaries stay aligned). MoE expert
        # stacks share the w_up/w_down key names at rank 3 but belong to
        # the "ep" axis (parallel/expert.py), so they fall through to
        # replication here, as documented.
        if key == "wqkv" and leaf.ndim == 3:
            dim = 2
        elif kind is not None and leaf.ndim == 2:
            dim = 1 if kind[0] == "tp_col" else 0
        else:
            return jax.device_put(leaf, NamedSharding(mesh, P()))
        if leaf.shape[dim] % tp:
            raise ValueError(
                f"{key} dim {dim} size {leaf.shape[dim]} not divisible by "
                f"{tp} '{axis_name}' shards"
            )
        spec_axes = [None] * leaf.ndim
        spec_axes[dim] = axis_name
        return jax.device_put(leaf, NamedSharding(mesh, P(*spec_axes)))

    return jax.tree_util.tree_map_with_path(put, params)

"""JAX version compatibility for the shard_map API family.

The framework targets the current TPU toolchain (top-level
``jax.shard_map`` with the ``check_vma`` varying-manual-axes checker and
``lax.pcast``/pvary annotations), but the CPU CI containers can lag
releases behind — where shard_map still lives in ``jax.experimental`` and
the checker is spelled ``check_rep``. Every in-repo shard_map construction
routes through this module so one shim absorbs the API drift instead of
each builder growing its own try/except (the collection errors this file
heals were exactly that: ``from jax import shard_map`` dying at import time
on older containers, taking the whole sharded test family with it).

``to_varying(axes)`` is the matching shim for the loop-carry annotations:
identity on jax builds without pcast/pvary (their rep system does not
distinguish varying from replicated in fori_loop carries).
"""

from __future__ import annotations

from typing import Callable

try:  # current API: top-level export, check_vma kwarg
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # pre-export releases: experimental home, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` with the checker kwarg spelled per the installed
    release (``check_vma`` today, ``check_rep`` on older containers)."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )


def to_varying(axes) -> Callable:
    """Annotate an array as varying over ``axes`` for fori_loop carry typing
    — lax.pcast (current), pvary (the deprecated alias), or identity on
    releases whose rep system has no varying annotation at all."""
    from jax import lax

    axes = tuple(axes)
    if hasattr(lax, "pcast"):
        return lambda a: lax.pcast(a, axes, to="varying")
    if hasattr(lax, "pvary"):
        return lambda a: lax.pvary(a, axes)  # noqa — pre-pcast fallback
    return lambda a: a

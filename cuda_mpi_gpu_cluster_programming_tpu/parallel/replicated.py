"""Replicate-all execution: the reference's V2.1 anti-baseline.

V2.1 broadcasts the full input and all parameters to every rank and has
every rank redundantly compute the complete forward pass
(2.1_broadcast_all/src/main.cpp:49-87); it exists to demonstrate *negative*
scaling (BASELINE.md: 0.702→0.793 s as np goes 1→4). The TPU analogue:
fully-replicated ``NamedSharding`` on an N-device mesh — under SPMD every
device executes the whole computation on its own replica. ``device_put`` of
the replicated operands is the Bcast analogue.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.alexnet import BLOCKS12, Blocks12Config, forward_blocks12
from .mesh import make_mesh


def build_replicated_forward(
    model_cfg: Blocks12Config = BLOCKS12,
    n_shards: int = 1,
    mesh: Optional[Mesh] = None,
    quantized: bool = False,
) -> Callable:
    """``quantized``: run the int8w policy on every replica — the quantized
    forward (in-graph calibration from the fp32 tree,
    precision.quantize.forward_blocks12_int8w) replaces the fp32 pass under
    the same replicate-everything shardings, so each replica quantizes the
    identical param tree to identical int8 values/scales."""
    mesh = mesh or make_mesh(n_shards)
    repl = NamedSharding(mesh, P())
    if quantized:
        from ..precision.quantize import forward_blocks12_int8w

        model_fwd = lambda p, x: forward_blocks12_int8w(  # noqa: E731
            p, x, model_cfg, tier="reference"
        )
    else:
        model_fwd = lambda p, x: forward_blocks12(p, x, model_cfg)  # noqa: E731

    @jax.jit
    def fwd(params, x):
        params = jax.lax.with_sharding_constraint(params, repl)
        x = jax.lax.with_sharding_constraint(x, repl)
        out = model_fwd(params, x)
        return jax.lax.with_sharding_constraint(out, repl)

    return fwd

"""FSDP / ZeRO-style parameter sharding over a ``dp`` mesh axis.

Plain data parallelism replicates every parameter (and its optimizer
state) on every device — the memory wall ZeRO/FSDP exists to break. The
GSPMD formulation (the scaling-book recipe, same pattern as
``parallel/expert.py``): annotate each parameter leaf as sharded along
one of its own axes over the SAME mesh axis the batch is sharded over,
and let XLA insert the collectives — parameters are all-gathered just
before the layers that use them (forward and again in the recompute-free
backward), gradients reduce-scatter back to their owning shard, and the
optimizer update runs on 1/n of every tensor per device. Parameter,
gradient, and optimizer-state memory all scale as 1/n_dp while the math
stays exactly data parallelism.

The reference has no analogue (its model is replicated on every rank —
V2.1's broadcast-all is the ANTI-pattern this module removes); this is
the TPU-native completion of the dp column of the parallelism zoo:
dp(replicated) / fsdp(dp-sharded) / sp / tp / pp / ep.

No new train-step code is needed: ``models.transformer.make_lm_train_step``
jits the same loss, and GSPMD propagates the param shardings through
grads and optimizer state (the optax state pytree mirrors the param
tree, so its leaves inherit the same placement) — placement IS the
implementation, exactly as in expert parallelism.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import make_mesh

Params = Any


def fsdp_spec(shape, dp: int, axis_name: str = "dp") -> P:
    """PartitionSpec sharding the LARGEST dp-divisible dim of ``shape``.

    Largest-dim choice minimizes per-shard padding waste and matches how
    FSDP implementations flatten-and-split; leaves with no divisible dim
    (tiny biases, scalars) stay replicated — their memory is negligible,
    which is why real FSDP wraps them with the nearest block.
    """
    if not shape:
        return P()
    order = sorted(range(len(shape)), key=lambda i: shape[i], reverse=True)
    for i in order:
        if shape[i] % dp == 0 and shape[i] >= dp:
            return P(*[axis_name if j == i else None for j in range(len(shape))])
    return P()


def shard_params_fsdp(
    params: Params,
    mesh: Optional[Mesh] = None,
    *,
    n_shards: int = 0,
    axis_name: str = "dp",
) -> Params:
    """device_put every parameter leaf sharded per :func:`fsdp_spec`."""
    if mesh is None:
        mesh = make_mesh(n_shards, axis_name=axis_name)
    dp = mesh.shape[axis_name]

    def put(leaf):
        return jax.device_put(
            leaf, NamedSharding(mesh, fsdp_spec(leaf.shape, dp, axis_name))
        )

    return jax.tree.map(put, params)


def sharded_fraction(params: Params, axis_name: str = "dp") -> float:
    """Fraction of parameter BYTES whose leaf is actually sharded over
    ``axis_name`` — the honest memory-scaling number (replicated stragglers
    counted against it). Used by tests to assert FSDP placement engaged."""
    total = 0
    sharded = 0
    for leaf in jax.tree.leaves(params):
        n = leaf.size * leaf.dtype.itemsize
        total += n
        spec = getattr(leaf.sharding, "spec", None)
        if spec is not None and any(
            (s == axis_name or (isinstance(s, tuple) and axis_name in s))
            for s in spec
            if s is not None
        ):
            sharded += n
    return sharded / total if total else 0.0
